"""Serving front plane (dragonboat_tpu.gateway, docs/GATEWAY.md).

Covers, per the gateway tentpole:

* RoutingCache units: copy-on-write snapshot reads, event-tap
  learn/invalidate from ``leader_updated``/``balance_move_*``, bulk
  refresh from a balance ClusterView, discovery fallback;
* AdmissionController units: bounded per-shard queue, deadline-aware
  shed via ``LatencyBudget.can_meet``, depth accounting, the
  sustained-shed dump trigger;
* Gateway end-to-end on a 3-host in-proc cluster: session handles with
  per-session ordering, batched submission, exactly-once results;
* leader-lease reads: the fast path under CheckQuorum, fallback when
  ``check_quorum`` is off, and the SAFETY cases — leader transfer and
  leader kill mid-lease force fallback to ReadIndex (no stale read
  past lease expiry), with an ``audit/`` stale-read containment pass
  over a gateway read/write history under leader-kill churn;
* overload: a flooded tiny-queue gateway sheds (``gateway_shed_total``
  > 0), completes everything it admits, and auto-dumps the flight
  recorder on sustained shedding.
"""
import json
import shutil
import threading
import time

import pytest

from dragonboat_tpu import (
    Config,
    EngineConfig,
    ExpertConfig,
    Gateway,
    GatewayBusy,
    GatewayClosed,
    GatewayConfig,
    LatencyBudget,
    NodeHost,
    NodeHostConfig,
)
from dragonboat_tpu.audit.checker import check_stale_reads
from dragonboat_tpu.audit.history import HistoryRecorder
from dragonboat_tpu.audit.model import AuditKV, audit_set_cmd
from dragonboat_tpu.balance.view import ClusterView, ReplicaView, ShardView
from dragonboat_tpu.events import EventFanout
from dragonboat_tpu.gateway import AdmissionController, RoutingCache
from dragonboat_tpu.metrics import MetricsRegistry
from dragonboat_tpu.raftio import LeaderInfo
from dragonboat_tpu.transport.inproc import reset_inproc_network

from test_nodehost import KVStore, set_cmd


# ---------------------------------------------------------------------------
# routing cache units
# ---------------------------------------------------------------------------
class TestRoutingCache:
    def test_learn_lookup_invalidate_snapshot_discipline(self):
        rc = RoutingCache(lambda: {})
        assert rc.lookup(1) is None
        rc.learn(1, "h-a")
        t0 = rc._table
        assert rc.lookup(1) == "h-a"
        rc.learn(2, "h-b")
        # copy-on-write: the old snapshot object is untouched
        assert t0 == {1: "h-a"} and rc.lookup(2) == "h-b"
        rc.invalidate(1)
        assert rc.lookup(1) is None and rc.lookup(2) == "h-b"
        rc.invalidate(99)  # absent: no-op, no error
        rc.invalidate_all()
        assert rc.table() == {}

    def test_leader_updated_tap_learns_and_invalidates(self):
        rc = RoutingCache(lambda: {})
        tap_a = rc.host_tap("h-a")
        # the leader's own observation learns the route
        tap_a("leader_updated", (LeaderInfo(1, replica_id=3, term=2,
                                            leader_id=3),))
        assert rc.lookup(1) == "h-a"
        # a follower learning some other leader cannot map it: ignored
        tap_b = rc.host_tap("h-b")
        tap_b("leader_updated", (LeaderInfo(1, replica_id=2, term=2,
                                            leader_id=3),))
        assert rc.lookup(1) == "h-a"
        # leaderless observation invalidates
        tap_b("leader_updated", (LeaderInfo(1, replica_id=2, term=3,
                                            leader_id=0),))
        assert rc.lookup(1) is None

    def test_balance_move_events_invalidate(self):
        rc = RoutingCache(lambda: {})
        rc.learn(7, "h-a")
        tap = rc.host_tap("h-a")

        class Info:
            shard_id = 7

        tap("balance_move_started", (Info(),))
        assert rc.lookup(7) is None

    def test_refresh_from_view_bulk_updates(self):
        rc = RoutingCache(lambda: {})
        rc.learn(1, "stale-host")
        view = ClusterView(
            hosts=("h-a", "h-b"),
            draining=(),
            shards=(
                ShardView(
                    shard_id=1,
                    members=((1, "h-a"), (2, "h-b")),
                    replicas=(ReplicaView(1, "h-a", 5, True),),
                    leader_replica_id=1,
                    leader_host="h-a",
                ),
                ShardView(
                    shard_id=2,
                    members=((1, "h-b"),),
                    replicas=(),
                    leader_replica_id=0,
                    leader_host="",  # unknown leader: not in leader_map
                ),
            ),
        )
        assert view.leader_map() == {1: "h-a"}
        rc.refresh_from_view(view)
        assert rc.lookup(1) == "h-a" and rc.lookup(2) is None

    def test_event_fanout_add_tap_sees_leader_updated(self):
        seen = []
        fan = EventFanout()
        try:
            fan.add_tap(lambda name, args: seen.append((name, args)))
            info = LeaderInfo(4, replica_id=1, term=1, leader_id=1)
            fan.leader_updated(info)
            assert seen == [("leader_updated", (info,))]
        finally:
            fan.close()


# ---------------------------------------------------------------------------
# raft-level lease semantics (quorum-responded renewal, decay, loss)
# ---------------------------------------------------------------------------
class TestRaftLease:
    def _leader(self, check_quorum=True):
        from dragonboat_tpu.pb import Message, MessageType
        from raft_harness import Network

        net = Network.of(3, check_quorum=check_quorum)
        net.elect(1)
        return net, net.peers[1], Message, MessageType

    def test_lease_seeded_at_election_and_renewed_by_responses(self):
        net, l, Message, MessageType = self._leader()
        assert l.lease_remaining_ticks() > 0  # vote grants seed it
        # drive ticks WITH heartbeat exchange: lease never decays below
        # a full window minus the heartbeat cadence
        for _ in range(3 * l.election_timeout):
            net.submit(1, Message(type=MessageType.LOCAL_TICK))
        assert l.lease_remaining_ticks() >= l.election_timeout - 2

    def test_lease_decays_without_quorum_responses(self):
        net, l, Message, MessageType = self._leader()
        net.isolate(2)
        net.isolate(3)
        # responses stop arriving; the lease decays tick by tick (the
        # CHECK_QUORUM sweep will also depose the leader at the window
        # boundary, which forces remaining to 0 via the role gate)
        start = l.lease_remaining_ticks()
        for _ in range(l.election_timeout + 1):
            l.handle(Message(type=MessageType.LOCAL_TICK))
        assert l.lease_remaining_ticks() < max(start, 1), (
            start, l.lease_remaining_ticks(), l.role
        )
        assert l.lease_remaining_ticks() == 0

    def test_no_lease_without_check_quorum(self):
        net, l, Message, MessageType = self._leader(check_quorum=False)
        assert l.lease_remaining_ticks() == 0

    def test_follower_has_no_lease(self):
        net, l, Message, MessageType = self._leader()
        assert net.peers[2].lease_remaining_ticks() == 0

    def test_transfer_in_flight_zeroes_lease(self):
        # transfer votes bypass the vote-refusal lease (hint != 0), so
        # the target can be elected well inside the old window — the
        # lease must go to zero the moment a transfer is requested
        net, l, Message, MessageType = self._leader()
        assert l.lease_remaining_ticks() > 0
        l.handle(Message(type=MessageType.LEADER_TRANSFER, hint=2))
        assert l.leader_transfer_target == 2
        assert l.lease_remaining_ticks() == 0

    def test_boot_grace_refuses_votes_after_restart(self):
        from raft_harness import new_raft
        from dragonboat_tpu.pb import Message, MessageType, State

        # a voter restored from persisted state can't know how recently
        # it heard from a leader: it must refuse non-transfer votes for
        # one election window (leader_id is volatile — restart hole)
        r = new_raft(1, [1, 2, 3], check_quorum=True,
                     state=State(term=3, vote=2, commit=0))
        r.handle(Message(type=MessageType.REQUEST_VOTE, from_=2,
                         term=4, log_index=0, log_term=0))
        assert r.term == 3 and not r.msgs  # ignored inside boot grace
        for _ in range(r.election_timeout):
            r.tick_count += 1
        r.handle(Message(type=MessageType.REQUEST_VOTE, from_=2,
                         term=4, log_index=0, log_term=0))
        assert r.term == 4  # grace over: the vote request is processed
        # a fresh node (no persisted state) has no grace
        r2 = new_raft(1, [1, 2, 3], check_quorum=True)
        r2.handle(Message(type=MessageType.REQUEST_VOTE, from_=2,
                          term=4, log_index=0, log_term=0))
        assert r2.term == 4

    def test_single_voter_lease_always_held(self):
        from raft_harness import new_raft
        from dragonboat_tpu.pb import Message, MessageType

        r = new_raft(1, [1], check_quorum=True)
        r.handle(Message(type=MessageType.ELECTION))
        for _ in range(25):
            r.handle(Message(type=MessageType.LOCAL_TICK))
        assert r.lease_remaining_ticks() == r.election_timeout


# ---------------------------------------------------------------------------
# admission units
# ---------------------------------------------------------------------------
class TestAdmission:
    def _budget(self, p99=0.05):
        b = LatencyBudget(bootstrap=p99, floor=0.001)
        for _ in range(16):
            b.observe(p99)
        return b

    def test_queue_full_sheds_and_depth_accounting(self):
        m = MetricsRegistry()
        ac = AdmissionController(
            self._budget(), max_queue_per_shard=2, metrics=m
        )
        dl = time.monotonic() + 10.0
        assert ac.admit(1, dl) is None
        assert ac.admit(1, dl) is None
        assert ac.depth(1) == 2
        assert ac.admit(1, dl) == "queue_full"
        # another shard is unaffected (per-shard bound)
        assert ac.admit(2, dl) is None
        ac.complete(1)
        assert ac.admit(1, dl) is None
        assert ac.depth(1) == 2 and ac.depth(2) == 1
        assert ac.shed_total == 1
        assert m.counter("gateway_shed_total",
                         {"reason": "queue_full"}).value == 1

    def test_deadline_shed_when_p99_says_unreachable(self):
        ac = AdmissionController(self._budget(p99=0.5),
                                 max_queue_per_shard=8)
        # 50ms of headroom against a 500ms p99: cannot meet
        assert ac.admit(1, time.monotonic() + 0.05) == "deadline"
        # past deadline: shed without charging depth
        assert ac.admit(1, time.monotonic() - 1.0) == "deadline"
        assert ac.depth(1) == 0
        # ample headroom admits
        assert ac.admit(1, time.monotonic() + 5.0) is None

    def test_sustained_shed_fires_dump_once_per_cooldown(self):
        dumps = []
        ac = AdmissionController(
            self._budget(), max_queue_per_shard=1,
            dump_threshold=5, dump_window=5.0, dump_cooldown=60.0,
            dump_cb=dumps.append,
        )
        dl = time.monotonic() + 10.0
        assert ac.admit(1, dl) is None
        for _ in range(12):
            assert ac.admit(1, dl) == "queue_full"
        assert ac.dumps == 1 and len(dumps) == 1
        assert "sustained shedding" in dumps[0]


# ---------------------------------------------------------------------------
# cluster harness
# ---------------------------------------------------------------------------
GW_ADDRS = {1: "gwt-1", 2: "gwt-2", 3: "gwt-3"}


def make_gw_cluster(sm_factory=KVStore, *, check_quorum=True, shards=(1,),
                    rtt_ms=2, recorder=False, tag="gwt"):
    reset_inproc_network()
    addrs = {r: f"{tag}-{r}" for r in (1, 2, 3)}
    nhs = {}
    for r, a in addrs.items():
        d = f"/tmp/nh-{tag}-{r}"
        shutil.rmtree(d, ignore_errors=True)
        nhs[a] = NodeHost(NodeHostConfig(
            nodehost_dir=d,
            rtt_millisecond=rtt_ms,
            raft_address=a,
            enable_flight_recorder=recorder,
            expert=ExpertConfig(
                engine=EngineConfig(exec_shards=2, apply_shards=2)
            ),
        ))
    for sid in shards:
        for r, a in addrs.items():
            nhs[a].start_replica(
                addrs, False, sm_factory,
                Config(replica_id=r, shard_id=sid, election_rtt=10,
                       heartbeat_rtt=1, check_quorum=check_quorum),
            )
    return addrs, nhs


def wait_leader(nhs, shard_id=1, timeout=20.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        for a, nh in nhs.items():
            try:
                if nh.is_leader_of(shard_id):
                    return a
            except Exception:
                pass
        time.sleep(0.02)
    raise AssertionError(f"no leader for shard {shard_id} within {timeout}s")


def close_all(nhs, gw=None):
    if gw is not None:
        gw.close()
    for nh in nhs.values():
        try:
            nh.close()
        except Exception:
            pass


# ---------------------------------------------------------------------------
# gateway end-to-end
# ---------------------------------------------------------------------------
class TestGatewayEndToEnd:
    def test_propose_read_and_routing_via_events(self):
        addrs, nhs = make_gw_cluster(tag="gwt-e2e")
        gw = Gateway(nhs, GatewayConfig(workers=2))
        try:
            leader = wait_leader(nhs)
            h = gw.connect(1)
            for i in range(10):
                r = h.sync_propose(set_cmd(f"k{i}", i))
            assert r.value == 10
            # reads see the writes; the route learned from events or
            # discovery points at the leader host
            assert gw.read(1, "k9") == 9
            assert gw.routes.lookup(1) == leader
            st = gw.stats()
            assert st["committed"] == 10 and st["failed"] == 0
            assert st["lease_reads"] + st["read_fallbacks"] >= 1
            h.close()
        finally:
            close_all(nhs, gw)

    def test_per_session_ordering_under_async_submission(self):
        addrs, nhs = make_gw_cluster(AuditKV, tag="gwt-ord")
        gw = Gateway(nhs, GatewayConfig(workers=2))
        try:
            wait_leader(nhs)
            h = gw.connect(1)
            futs = [
                h.propose(audit_set_cmd("seq", f"v{i}")) for i in range(24)
            ]
            for f in futs:
                f.result(20.0)
            # every replica applied the handle's writes in submission
            # order (the per-session in-flight gate + series discipline)
            deadline = time.time() + 10.0
            while time.time() < deadline:
                vals = [
                    [v for _, k, v in nh._get_node(1).sm.managed.sm.journal
                     if k == "seq"]
                    for nh in nhs.values()
                ]
                if all(len(v) == 24 for v in vals):
                    break
                time.sleep(0.05)
            for v in vals:
                assert v == [f"v{i}" for i in range(24)], v
            h.close()
        finally:
            close_all(nhs, gw)

    def test_noop_handle_and_closed_gateway_rejects(self):
        addrs, nhs = make_gw_cluster(tag="gwt-noop")
        gw = Gateway(nhs)
        try:
            wait_leader(nhs)
            h = gw.noop_handle(1)
            h.sync_propose(set_cmd("x", 1))
            assert gw.read(1, "x") == 1
            gw.close()
            with pytest.raises(GatewayClosed):
                h.propose(set_cmd("y", 2))
            with pytest.raises(GatewayClosed):
                gw.read(1, "x")
        finally:
            close_all(nhs, gw)


# ---------------------------------------------------------------------------
# lease reads
# ---------------------------------------------------------------------------
class TestLeaseReads:
    def test_lease_fast_path_skips_read_index(self):
        addrs, nhs = make_gw_cluster(tag="gwt-lease")
        gw = Gateway(nhs)
        try:
            leader = wait_leader(nhs)
            h = gw.connect(1)
            h.sync_propose(set_cmd("a", 1))
            # the leader host holds a CheckQuorum lease
            st = nhs[leader].lease_status(1)
            assert st["is_leader"] and st["check_quorum"]
            assert st["remaining_ticks"] > 0
            before = gw.stats()["lease_reads"]
            for _ in range(5):
                assert gw.read(1, "a") == 1
            assert gw.stats()["lease_reads"] >= before + 4
            # and the raw probe agrees
            ok, v = nhs[leader].try_lease_read(1, "a")
            assert ok and v == 1
            h.close()
        finally:
            close_all(nhs, gw)

    def test_no_lease_without_check_quorum_falls_back(self):
        addrs, nhs = make_gw_cluster(check_quorum=False, tag="gwt-nolease")
        gw = Gateway(nhs)
        try:
            leader = wait_leader(nhs)
            h = gw.noop_handle(1)
            h.sync_propose(set_cmd("a", 1))
            ok, _ = nhs[leader].try_lease_read(1, "a")
            assert not ok
            assert gw.read(1, "a") == 1  # ReadIndex fallback still serves
            assert gw.stats()["read_fallbacks"] >= 1
            assert gw.stats()["lease_reads"] == 0
        finally:
            close_all(nhs, gw)

    def test_leader_transfer_mid_lease_forces_fallback(self):
        addrs, nhs = make_gw_cluster(tag="gwt-xfer")
        gw = Gateway(nhs)
        try:
            leader = wait_leader(nhs)
            h = gw.noop_handle(1)
            h.sync_propose(set_cmd("a", 1))
            assert gw.read(1, "a") == 1
            old = nhs[leader]
            old_node = old._get_node(1)
            target = next(
                r for r, a in addrs.items() if a != leader
            )
            old.request_leader_transfer(1, target)
            # the OLD leader must lose the lease the moment it steps
            # down — no stale read past lease expiry
            deadline = time.time() + 10.0
            while time.time() < deadline:
                if not old.is_leader_of(1):
                    break
                time.sleep(0.01)
            assert not old.is_leader_of(1), "transfer did not complete"
            assert old_node.lease_remaining_ticks() == 0
            assert old.try_lease_read(1, "a") == (False, None)
            # gateway reads keep serving (rerouted / fallback)
            assert gw.read(1, "a") == 1
            new_leader = wait_leader(nhs)
            assert new_leader != leader
            # route converges to the new leader via leader_updated taps
            deadline = time.time() + 5.0
            while time.time() < deadline:
                if gw.routes.lookup(1) == new_leader:
                    break
                time.sleep(0.02)
            assert gw.routes.lookup(1) == new_leader
        finally:
            close_all(nhs, gw)

    def test_leader_kill_mid_lease_forces_fallback(self):
        addrs, nhs = make_gw_cluster(tag="gwt-kill")
        gw = Gateway(nhs)
        try:
            leader = wait_leader(nhs)
            h = gw.noop_handle(1)
            h.sync_propose(set_cmd("a", 1))
            assert gw.read(1, "a") == 1
            victim = nhs[leader]
            victim_node = victim._get_node(1)
            assert victim_node.lease_held(0)
            # kill the leader host mid-lease: its replica stops, the
            # lease probe must refuse instantly (stopped gate), and the
            # survivors elect a new leader the gateway reroutes to
            gw.remove_host(leader)
            victim.close()
            assert victim_node.lease_remaining_ticks() == 0
            survivors = {a: nh for a, nh in nhs.items() if a != leader}
            new_leader = wait_leader(survivors, timeout=30.0)
            assert gw.read(1, "a", timeout=10.0) == 1
            assert new_leader in survivors
        finally:
            close_all(nhs, gw)

    def test_stale_read_containment_under_leader_kill_churn(self):
        """The audit/ containment pass over a gateway read/write
        history: writes via exactly-once handles, reads via the lease
        fast path (recorded as 'stale'-kind ops, so the checker holds
        them to the containment contract: never a never-written,
        aborted, or future value), leader killed mid-run."""
        addrs, nhs = make_gw_cluster(AuditKV, tag="gwt-audit")
        gw = Gateway(nhs, GatewayConfig(default_timeout=8.0))
        rec = HistoryRecorder()
        try:
            leader = wait_leader(nhs)
            wc = rec.new_client()
            rc_ = rec.new_client()
            stop = threading.Event()
            seq = [0]

            def writer():
                h = gw.connect(1, timeout=10.0)
                while not stop.is_set():
                    seq[0] += 1
                    val = f"w-{seq[0]}"
                    op = rec.invoke(wc, "w", "k", val)
                    try:
                        h.sync_propose(audit_set_cmd("k", val))
                        rec.ok(op)
                    except Exception:
                        rec.ambiguous(op)  # may have committed
                    time.sleep(0.005)

            def reader():
                while not stop.is_set():
                    op = rec.invoke(rc_, "stale", "k")
                    try:
                        rec.ok(op, gw.read(1, "k", timeout=5.0))
                    except Exception:
                        rec.fail(op)
                    time.sleep(0.003)

            tw = threading.Thread(target=writer, daemon=True, name="gw-aud-w")
            tr = threading.Thread(target=reader, daemon=True, name="gw-aud-r")
            tw.start()
            tr.start()
            time.sleep(1.5)
            # leader kill mid-lease, mid-traffic
            gw.remove_host(leader)
            nhs[leader].close()
            survivors = {a: nh for a, nh in nhs.items() if a != leader}
            wait_leader(survivors, timeout=30.0)
            time.sleep(2.0)
            stop.set()
            tw.join(timeout=15)
            tr.join(timeout=15)
            ops = rec.ops()
            reads_ok = [o for o in ops if o.kind == "stale"
                        and o.status == "ok"]
            assert len(reads_ok) > 20, rec.counts()
            violations = check_stale_reads(ops)
            assert violations == [], "\n".join(
                v.describe() for v in violations
            )
            # the lease fast path actually carried reads in this run
            assert gw.stats()["lease_reads"] > 0
        finally:
            close_all(nhs, gw)


# ---------------------------------------------------------------------------
# overload shedding
# ---------------------------------------------------------------------------
class TestOverload:
    def test_flood_sheds_bounded_queue_and_dumps_recorder(self):
        addrs, nhs = make_gw_cluster(recorder=True, tag="gwt-shed")
        gw = Gateway(nhs, GatewayConfig(
            workers=1,
            max_queue_per_shard=8,
            shed_dump_threshold=10,
            shed_dump_window=5.0,
            shed_dump_cooldown=0.0,
            default_timeout=10.0,
        ))
        try:
            wait_leader(nhs)
            handles = [gw.noop_handle(1) for _ in range(16)]
            futs, sheds = [], 0
            for round_ in range(8):
                for i, h in enumerate(handles):
                    try:
                        futs.append(
                            h.propose(set_cmd(f"f{round_}-{i}", i))
                        )
                    except GatewayBusy:
                        sheds += 1
            # everything ADMITTED completes; everything else shed
            done = 0
            for f in futs:
                f.result(20.0)
                done += 1
            st = gw.stats()
            assert sheds > 0 and st["shed"] == sheds
            assert done == len(futs) and st["committed"] >= done
            # sustained shedding auto-dumped the flight recorder
            assert st["shed_dumps"] >= 1
            assert "sustained shedding" in gw.last_shed_dump
            # the shed landed in the flight recorder lane too
            ev = []
            for nh in nhs.values():
                if nh.recorder is not None:
                    ev.extend(nh.recorder.events(1))
            assert any(k == "gateway_shed" for _, _, _, k, _ in ev)
        finally:
            close_all(nhs, gw)

    def test_deadline_shed_rejects_before_queueing(self):
        addrs, nhs = make_gw_cluster(tag="gwt-dl")
        budget = LatencyBudget(bootstrap=2.0, floor=0.001)
        for _ in range(16):
            budget.observe(2.0)  # observed p99: 2s commits
        gw = Gateway(nhs, GatewayConfig(budget=budget))
        try:
            wait_leader(nhs)
            h = gw.noop_handle(1)
            with pytest.raises(GatewayBusy, match="deadline"):
                h.propose(set_cmd("x", 1), timeout=0.05)
            assert gw.stats()["shed"] == 1
            assert gw.admission.depth(1) == 0  # nothing charged
        finally:
            close_all(nhs, gw)


# ---------------------------------------------------------------------------
# snapshot-cap feedback auto-wiring (ROADMAP 5a)
# ---------------------------------------------------------------------------
class _CapFakeHost:
    """A NodeHost stand-in with only what the cap wiring touches: a
    transport carrying a shared snapshot pacer behind the
    ``set_snapshot_send_rate`` runtime knob.  No event fanout — the
    gateway tolerates tap failures (routes via discovery)."""

    class _T:
        def __init__(self, rate):
            from dragonboat_tpu.bigstate.pacing import TokenBucket

            self.max_snapshot_send_rate = rate or 0
            self.snapshot_pacer = TokenBucket(rate) if rate else None

        def set_snapshot_send_rate(self, rate):
            from dragonboat_tpu.bigstate.pacing import TokenBucket

            self.max_snapshot_send_rate = rate
            if rate > 0:
                if self.snapshot_pacer is None:
                    self.snapshot_pacer = TokenBucket(rate)
                else:
                    self.snapshot_pacer.set_rate(rate)
            else:
                self.snapshot_pacer = None

    def __init__(self, rate):
        self.transport = self._T(rate)

    def set_snapshot_send_rate(self, rate):
        self.transport.set_snapshot_send_rate(rate)


def _wait_for(cond, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(0.02)
    return cond()


class TestCapFeedbackWiring:
    def test_degraded_commit_latency_shrinks_the_cap(self):
        """A host with a configured stream cap, fronted by a gateway
        whose LatencyBudget observes degraded commits, gets its cap
        shrunk automatically; healthy latency recovers it (AIMD)."""
        budget = LatencyBudget(bootstrap=0.01, floor=0.001)
        host = _CapFakeHost(rate=1_000_000.0)
        gw = Gateway(
            {"h1": host},
            GatewayConfig(
                budget=budget,
                cap_feedback_target_p99=0.05,
                cap_feedback_interval=0.02,
            ),
        )
        try:
            pacer = host.transport.snapshot_pacer
            # the loop binds lazily from the feedback thread (the
            # runtime knob may configure caps long after attach)
            assert _wait_for(lambda: "h1" in gw.cap_feedback_stats())
            for _ in range(32):
                budget.observe(0.5)  # p99 way over the 50ms target
            assert _wait_for(lambda: pacer.rate < 1_000_000.0), (
                "cap never shrank"
            )
            st = gw.cap_feedback_stats()["h1"]
            assert st["adjustments"] >= 1 and st["base_rate"] == 1_000_000.0
            # healthy again: flush the degraded samples out of the
            # budget's sliding window so p99 actually drops, then the
            # loop recovers toward (and caps at) base
            for _ in range(600):
                budget.observe(0.001)
            low = pacer.rate
            assert _wait_for(lambda: pacer.rate > low), "cap never recovered"
        finally:
            gw.close()

    def test_close_restores_the_configured_cap(self):
        """A cap shrunk by the AIMD loop must not outlive the gateway
        at the floor: close() hands the host its configured base back
        (the host outlives the gateway; nothing else would grow it)."""
        budget = LatencyBudget(bootstrap=0.01, floor=0.001)
        host = _CapFakeHost(rate=1_000_000.0)
        gw = Gateway(
            {"h1": host},
            GatewayConfig(
                budget=budget, cap_feedback_target_p99=0.05,
                cap_feedback_interval=0.02,
            ),
        )
        try:
            pacer = host.transport.snapshot_pacer
            for _ in range(32):
                budget.observe(0.5)
            assert _wait_for(lambda: pacer.rate < 1_000_000.0)
        finally:
            gw.close()
        assert host.transport.snapshot_pacer.rate == 1_000_000.0

    def test_late_configured_cap_and_runtime_retune(self):
        """The runtime knob works END TO END: a cap configured AFTER
        attach gains a loop automatically, and raising the configured
        base moves the AIMD ceiling instead of being clamped back to
        the stale attach-time base (review findings)."""
        budget = LatencyBudget(bootstrap=0.01, floor=0.001)
        host = _CapFakeHost(rate=None)  # no cap at attach time
        gw = Gateway(
            {"h1": host},
            GatewayConfig(
                budget=budget, cap_feedback_target_p99=0.05,
                cap_feedback_interval=0.02,
            ),
        )
        try:
            assert gw.cap_feedback_stats() == {}
            host.set_snapshot_send_rate(1_000_000.0)  # operator knob
            assert _wait_for(lambda: "h1" in gw.cap_feedback_stats())
            # raise the configured base: the loop must track it, and
            # with healthy p99 the rate may grow PAST the old base
            host.set_snapshot_send_rate(2_000_000.0)
            assert _wait_for(
                lambda: gw.cap_feedback_stats().get("h1", {}).get(
                    "base_rate"
                ) == 2_000_000.0
            )
            # remove the cap: the loop retires instead of ticking an
            # orphaned bucket
            host.set_snapshot_send_rate(0)
            assert _wait_for(lambda: gw.cap_feedback_stats() == {})
        finally:
            gw.close()

    def test_opt_out_and_capless_hosts(self):
        """cap_feedback=False attaches no loop; a host without a
        configured cap (pacer None) never gets one invented for it."""
        host = _CapFakeHost(rate=8_000_000.0)
        gw = Gateway({"h1": host}, GatewayConfig(cap_feedback=False))
        try:
            assert gw.cap_feedback_stats() == {}
            assert gw._cap_thread is None
        finally:
            gw.close()
        capless = _CapFakeHost(rate=None)
        gw2 = Gateway({"h1": capless}, GatewayConfig())
        try:
            assert gw2.cap_feedback_stats() == {}
            assert capless.transport.snapshot_pacer is None
        finally:
            gw2.close()

    def test_remove_host_drops_its_loop(self):
        host = _CapFakeHost(rate=1_000_000.0)
        gw = Gateway({"h1": host}, GatewayConfig(cap_feedback_interval=0.05))
        try:
            assert _wait_for(lambda: "h1" in gw.cap_feedback_stats())
            gw.remove_host("h1")
            assert gw.cap_feedback_stats() == {}
        finally:
            gw.close()
