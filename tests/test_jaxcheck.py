"""Device-plane program auditor tests (analysis/jaxcheck + jitcheck,
docs/ANALYSIS.md "Device-plane audit").

True-positive fixtures per rule — an auditor that cannot catch a seeded
violation guards nothing: an injected float32 promotion, a host
callback inside a jitted fn, a donation broken by aliased operands, a
G-first layout in an internal-layout program, a forced post-warmup
retrace — plus the registry-completeness rule and the zero-unbaselined
tree gate (the real ops/ registry audits clean against
analysis/jax_baseline.txt).

The 3-replica colocated cluster pass under the recompile sentry is
env-gated behind DRAGONBOAT_TPU_JITCHECK (heavy; existing env-gate
practice)."""
import functools
import os
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from dragonboat_tpu.analysis import jaxcheck, jitcheck
from dragonboat_tpu.analysis.raftlint import gate, load_baseline
from dragonboat_tpu.ops import registry
from dragonboat_tpu.ops.registry import CANON, EntryPoint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
JAX_BASELINE = os.path.join(
    REPO, "dragonboat_tpu", "analysis", "jax_baseline.txt"
)

G = CANON["G"]


def _ep(name, fn, build, **kw):
    return EntryPoint(name, fn, build, **kw)


def rules_of(findings):
    return {f.rule for f in findings}


# ---------------------------------------------------------------------------
# dtype policy
# ---------------------------------------------------------------------------
class TestDtypeRule:
    def test_injected_float_promotion_caught(self):
        @jax.jit
        def bad(x):
            return (x * 0.5).sum()  # silent int32 -> float32 promotion

        ep = _ep("fix.float", bad, lambda: ((jnp.zeros((G,), jnp.int32),), {}))
        fs = jaxcheck.audit([ep])
        assert "dtype" in rules_of(fs)
        assert any("float32" in f.message for f in fs)

    def test_weak_typed_output_caught(self):
        @jax.jit
        def weak_out(x):
            # both where() arms are python literals -> weak int32 output
            return jnp.where(x > 0, 1, 0)

        ep = _ep(
            "fix.weak", weak_out, lambda: ((jnp.zeros((G,), jnp.int32),), {})
        )
        fs = jaxcheck.audit([ep])
        assert any("weak" in f.message for f in fs if f.rule == "dtype")

    def test_sanctioned_program_clean(self):
        @jax.jit
        def good(x, m):
            return jnp.where(m, x + jnp.int32(1), x)

        ep = _ep(
            "fix.clean",
            good,
            lambda: (
                (jnp.zeros((G,), jnp.int32), jnp.zeros((G,), bool)),
                {},
            ),
        )
        assert jaxcheck.audit([ep]) == []

    def test_whitelist_exception(self):
        @jax.jit
        def uses_f32(x):
            return x.astype(jnp.float32)

        ep = _ep(
            "fix.wl", uses_f32, lambda: ((jnp.zeros((G,), jnp.int32),), {})
        )
        assert rules_of(jaxcheck.audit([ep])) == {"dtype"}
        # an explicitly whitelisted dtype is not a finding
        assert jaxcheck.audit([ep], extra_ok=("float32",)) == []


# ---------------------------------------------------------------------------
# transfer audit
# ---------------------------------------------------------------------------
class TestTransferRule:
    def test_pure_callback_in_jitted_fn_caught(self):
        import numpy as np

        @jax.jit
        def bad(x):
            y = jax.pure_callback(
                lambda a: np.asarray(a, np.int32),
                jax.ShapeDtypeStruct(x.shape, x.dtype),
                x,
            )
            return y + 1

        ep = _ep("fix.cb", bad, lambda: ((jnp.zeros((G,), jnp.int32),), {}))
        fs = [f for f in jaxcheck.audit([ep]) if f.rule == "transfer"]
        assert fs and any("callback" in f.message for f in fs)

    def test_debug_callback_caught(self):
        @jax.jit
        def bad(x):
            jax.debug.callback(lambda a: None, x)
            return x + 1

        ep = _ep("fix.dbg", bad, lambda: ((jnp.zeros((G,), jnp.int32),), {}))
        assert "transfer" in rules_of(jaxcheck.audit([ep]))


# ---------------------------------------------------------------------------
# donation audit
# ---------------------------------------------------------------------------
class TestDonationRule:
    def test_donation_broken_by_aliased_operands_caught(self):
        # the output IS operand 0 (pass-through), so the donated operand
        # 1 — same shape, could alias — cannot: jax drops the donation
        # and the call degrades to copy+free (the ops/route.py
        # "aliased zeros break donate_argnums" class)
        f = functools.partial(jax.jit, donate_argnums=(1,))(lambda x, y: x)
        ep = _ep(
            "fix.donate",
            f,
            lambda: (
                (
                    jnp.zeros((8, 4), jnp.int32),
                    jnp.ones((8, 4), jnp.int32),
                ),
                {},
            ),
            donate=(1,),
        )
        fs = jaxcheck.audit([ep])
        assert rules_of(fs) == {"donation"}
        assert "0/1" in fs[0].message

    def test_working_donation_clean(self):
        f = functools.partial(jax.jit, donate_argnums=(0,))(
            lambda x, y: x + y
        )
        ep = _ep(
            "fix.donate_ok",
            f,
            lambda: (
                (
                    jnp.zeros((8, 4), jnp.int32),
                    jnp.ones((8, 4), jnp.int32),
                ),
                {},
            ),
            donate=(0,),
        )
        assert jaxcheck.audit([ep]) == []

    def test_early_free_donation_not_flagged(self):
        # donated buffer with NO shape-matched output: legitimate
        # early-free donation (the _assemble_and_step inbox pattern)
        f = functools.partial(jax.jit, donate_argnums=(0,))(
            lambda x: x.sum()
        )
        ep = _ep(
            "fix.donate_free",
            f,
            lambda: ((jnp.zeros((8, 4), jnp.int32),), {}),
            donate=(0,),
        )
        assert jaxcheck.audit([ep]) == []


# ---------------------------------------------------------------------------
# G-last layout
# ---------------------------------------------------------------------------
class TestGLastRule:
    def test_g_first_compute_caught(self):
        @jax.jit
        def bad(x):  # [G, P] math: G on the major axis pads the lanes
            return x + jnp.int32(1)

        ep = _ep(
            "fix.gfirst",
            bad,
            lambda: ((jnp.zeros((G, CANON["P"]), jnp.int32),), {}),
            g_last=True,
        )
        fs = jaxcheck.audit([ep])
        assert rules_of(fs) == {"g-last"}

    def test_g_last_compute_clean(self):
        @jax.jit
        def good(x):
            return x + jnp.int32(1)

        ep = _ep(
            "fix.glast",
            good,
            lambda: ((jnp.zeros((CANON["P"], G), jnp.int32),), {}),
            g_last=True,
        )
        assert jaxcheck.audit([ep]) == []

    def test_constant_fills_exempt(self):
        @jax.jit
        def ctor(x):
            # the make_out pattern: G-major constant that folds under
            # jit, transposed at the boundary — not lane traffic
            return x + jnp.zeros((G, CANON["P"]), jnp.int32).T

        ep = _ep(
            "fix.ctor",
            ctor,
            lambda: ((jnp.zeros((CANON["P"], G), jnp.int32),), {}),
            g_last=True,
        )
        assert jaxcheck.audit([ep]) == []


# ---------------------------------------------------------------------------
# registry completeness
# ---------------------------------------------------------------------------
class TestRegistryCompleteness:
    def test_jit_defs_sees_decorator_and_assignment_shapes(self, tmp_path):
        (tmp_path / "fake.py").write_text(
            "import jax, functools\n"
            "@jax.jit\n"
            "def plain(x):\n    return x\n"
            "@functools.partial(jax.jit, static_argnames=('n',))\n"
            "def partialed(x, n):\n    return x\n"
            "def unjitted(x):\n    return x\n"
            # assignment forms escape decorator-only scans (review
            # finding): both spellings must register
            "fast = jax.jit(unjitted)\n"
            "faster = functools.partial(jax.jit, donate_argnums=(0,))"
            "(unjitted)\n"
            "not_a_jit = functools.partial(max, 0)\n"
        )
        defs = {(m, f) for m, f, _ in jaxcheck._jit_defs(str(tmp_path))}
        assert defs == {
            ("fake", "plain"),
            ("fake", "partialed"),
            ("fake", "fast"),
            ("fake", "faster"),
        }

    def test_every_ops_jit_is_registered(self):
        # the live-tree completeness gate, independent of the baseline
        assert jaxcheck._check_registry_complete(registry.ENTRY_POINTS) == []

    def test_registry_covers_documented_surface(self):
        names = {ep.name for ep in registry.ENTRY_POINTS}
        for must in (
            "kernel.step",
            "kernel.step_internal",
            "engine._gather_detail_vals",
            "colocated._assemble_and_step",
            "colocated._select_and_blob",
            "route.routed_round",
        ):
            assert must in names


# ---------------------------------------------------------------------------
# the zero-unbaselined-tree gate (the PR 5 pattern: analysis gates itself)
# ---------------------------------------------------------------------------
class TestTreeGate:
    def test_tree_audits_clean_against_baseline(self):
        findings = jaxcheck.audit()
        new, _stale = gate(findings, load_baseline(JAX_BASELINE))
        assert new == [], "unbaselined device-plane findings:\n" + "\n".join(
            f.render() for f in new
        )


# ---------------------------------------------------------------------------
# recompile sentry (analysis/jitcheck)
# ---------------------------------------------------------------------------
class TestJitcheckSentry:
    def test_forced_post_warmup_retrace_caught(self):
        @jax.jit
        def f(x):
            return x + 1

        s = jitcheck.Sentry([("fix.retrace", f)])
        f(jnp.zeros((4,), jnp.int32))  # warmup shape
        s.mark()
        assert s.retraces() == []
        f(jnp.zeros((4,), jnp.int32))  # same shape: cache hit, no growth
        assert s.retraces() == []
        f(jnp.zeros((5,), jnp.int32))  # drifted shape: retrace
        rows = s.retraces()
        assert rows and rows[0][0] == "fix.retrace"
        assert rows[0][2] > rows[0][1]
        assert "post-warmup retrace" in jitcheck.format_retraces(rows)

    def test_unmarked_sentry_reports_nothing(self):
        s = jitcheck.Sentry([])
        assert s.retraces() == []

    def test_runtime_registry_excludes_audit_wrappers(self):
        names = {n for n, _ in registry.runtime_entry_points()}
        assert "route.routed_round" not in names
        assert "kernel.step" in names


# ---------------------------------------------------------------------------
# the 3-replica cluster pass: zero post-warmup retraces end to end
# (env-gated: heavy sentry runs sit behind DRAGONBOAT_TPU_JITCHECK)
# ---------------------------------------------------------------------------
@pytest.mark.skipif(
    os.environ.get("DRAGONBOAT_TPU_JITCHECK", "0") in ("", "0"),
    reason="recompile-sentry cluster pass runs under DRAGONBOAT_TPU_JITCHECK=1",
)
class TestClusterSentryPass:
    def test_colocated_3replica_zero_postwarm_retraces(self):
        from test_colocated import colo_shard_config, make_colocated_cluster
        from test_nodehost import (
            ADDRS,
            KVStore,
            propose_r,
            set_cmd,
            wait_for_leader,
        )

        jitcheck.enable(True)
        group, nhs = make_colocated_cluster()
        try:
            for rid, nh in nhs.items():
                nh.start_replica(ADDRS, False, KVStore, colo_shard_config(rid))
            wait_for_leader(nhs)
            lid, ok = nhs[1].get_leader_id(1)
            assert ok
            s = nhs[lid].get_noop_session(1)
            for i in range(10):  # warmup traffic: all launch shapes hit
                propose_r(nhs[lid], s, set_cmd(f"warm{i}", b"v"))
            jitcheck.mark_warm()
            for i in range(30):
                propose_r(nhs[lid], s, set_cmd(f"load{i}", b"v"))
            nhs[lid].request_leader_transfer(1, (lid % 3) + 1)
            for i in range(10):
                lid2, ok = nhs[1].get_leader_id(1)
                if ok:
                    s2 = nhs[lid2].get_noop_session(1)
                    propose_r(nhs[lid2], s2, set_cmd(f"post{i}", b"v"))
            rows = jitcheck.retraces()
            assert rows == [], (
                "post-warmup retraces in the cluster pass:\n"
                + jitcheck.format_retraces(rows)
            )
        finally:
            for nh in nhs.values():
                nh.close()
