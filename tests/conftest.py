"""Test configuration.

JAX tests run on a virtual 8-device CPU mesh so multi-chip sharding is
exercised without TPU hardware (SURVEY.md environment notes); the real-TPU
bench path is bench.py.
"""
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
