"""Test configuration.

JAX tests run on the CPU backend with XLA forced to expose 8 host
devices, so mesh-capable code paths CAN build a multi-device mesh —
but the suite itself exercises device 0 only (no test constructs a
Mesh or shards across devices; VERDICT r5 weak #4).  Multi-chip mesh
placement is covered by the driver's `__graft_entry__.py` dryrun tiers
and the real-TPU bench path in bench.py, not by pytest.
"""
import os
import sys

# run the whole suite with internal invariant assertions ON (reference:
# build-tag-gated internal/invariants checks enabled in CI builds [U])
os.environ.setdefault("DRAGONBOAT_TPU_INVARIANTS", "1")

# run the chaos/fault test modules under the lock-order witness
# (analysis/lockcheck, docs/ANALYSIS.md): any lock-order cycle a chaotic
# schedule merely GRAZES — even if this run got lucky with timing —
# fails the test with both witness stacks.  Same env-gate pattern as
# invariants; set =0 to opt out.  Scoped to the modules that churn
# clusters hardest rather than suite-wide to bound the tier-1 budget
# (overhead tracked by bench.phase_lockcheck).
os.environ.setdefault("DRAGONBOAT_TPU_LOCKCHECK", "1")

# NOTE: this image's sitecustomize imports jax at interpreter start to
# register the TPU tunnel plugin, so mutating JAX_PLATFORMS here is too
# late — pin the backend via jax.config before first backend init instead.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# DRAGONBOAT_TEST_TPU=1 lets a test run target the real chip (used for
# the recorded scale artifacts: the CPU backend can't launch a 65k-row
# program at election cadence; the product backend can) — everything
# else stays on the virtual 8-device CPU mesh.
if os.environ.get("DRAGONBOAT_TEST_TPU", "0").lower() not in ("1", "true"):
    jax.config.update("jax_platforms", "cpu")
# cache compiled kernels across test processes (the step kernel is large)
jax.config.update("jax_compilation_cache_dir", "/root/.cache/jax")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running soak/chaos schedules (tier-1 runs -m 'not slow')",
    )
    config.addinivalue_line(
        "markers",
        "flaky_isolated: load-scheduling-sensitive tests that pass in "
        "isolation (ROADMAP's rotating tier-1 flakes).  A failed run is "
        "retried ONCE after the process quiesces (gc + settle sleep) so "
        "residual load from earlier modules can't rotate tier-1 red; a "
        "real regression still fails both runs.",
    )


def pytest_runtest_protocol(item, nextitem):
    """Serial re-run isolation for @pytest.mark.flaky_isolated (see the
    marker registration above).  The two known carriers — the colocated
    forced-escalation chaos schedule and the colocated quiesce
    fast-lane — each pass in isolation and fail only under CPU
    contention from the surrounding suite (both fail identically on
    the pristine seed tree; ROADMAP 'rotating load flakes').  The
    retry runs after a gc + 1.5s settle window, which is the
    'isolation' those tests actually need: background apply/step
    threads from earlier clusters have drained by then."""
    if item.get_closest_marker("flaky_isolated") is None:
        return None
    import gc
    import time as _time

    from _pytest.runner import runtestprotocol

    item.ihook.pytest_runtest_logstart(
        nodeid=item.nodeid, location=item.location
    )
    reports = runtestprotocol(item, nextitem=nextitem, log=False)
    if any(r.failed for r in reports):
        gc.collect()
        _time.sleep(1.5)
        reports = runtestprotocol(item, nextitem=nextitem, log=False)
    for r in reports:
        item.ihook.pytest_runtest_logreport(report=r)
    item.ihook.pytest_runtest_logfinish(
        nodeid=item.nodeid, location=item.location
    )
    return True


# -- lock-order witness for the chaos/fault modules -----------------------
_LOCKCHECK_MODULES = frozenset(
    ("test_chaos", "test_chaos_extended", "test_chaos_colocated", "test_faults")
)

# -- recompile sentry (analysis/jitcheck) for the engine-driven modules ---
# env-gated via DRAGONBOAT_TPU_JITCHECK: each test starts from a fresh
# trace-cache snapshot (engine _warm() re-marks at construction) and
# fails if any ops/ entry point retraced after warmup — the mid-run
# compile that stalls a remote-device launch pipeline for tens of
# seconds (docs/ANALYSIS.md "Device-plane audit")
_JITCHECK_MODULES = frozenset(("test_vector_engine", "test_colocated"))


def _lockcheck_wanted(item) -> bool:
    from dragonboat_tpu.analysis import lockcheck

    mod = getattr(item, "module", None)
    return lockcheck.ENABLED and getattr(mod, "__name__", "") in _LOCKCHECK_MODULES


def _jitcheck_wanted(item) -> bool:
    from dragonboat_tpu.analysis import jitcheck

    mod = getattr(item, "module", None)
    return jitcheck.ENABLED and getattr(mod, "__name__", "") in _JITCHECK_MODULES


def pytest_runtest_setup(item):
    if _lockcheck_wanted(item):
        from dragonboat_tpu.analysis import lockcheck

        item._lockcheck_witness = lockcheck.install()
    if _jitcheck_wanted(item):
        from dragonboat_tpu.analysis import jitcheck

        jitcheck.mark_warm()
        item._jitcheck_armed = True


def pytest_runtest_teardown(item, nextitem):
    import pytest as _pytest

    # lockcheck cleanup FIRST: a jitcheck failure below must not skip
    # uninstall() and leak the patched lock constructors into every
    # later test (latent today — the module sets are disjoint — but a
    # shared module would make the ordering load-bearing)
    w = getattr(item, "_lockcheck_witness", None)
    if w is not None:
        del item._lockcheck_witness
        from dragonboat_tpu.analysis import lockcheck

        lockcheck.uninstall()
        if w.cycles:
            _pytest.fail(
                "lock-order witness: cycle(s) recorded during this test\n"
                + w.format_cycles(),
                pytrace=False,
            )
    if getattr(item, "_jitcheck_armed", False):
        del item._jitcheck_armed
        from dragonboat_tpu.analysis import jitcheck

        rows = jitcheck.retraces()
        if rows:
            _pytest.fail(
                "jitcheck: post-warmup retrace(s) during this test\n"
                + jitcheck.format_retraces(rows),
                pytrace=False,
            )
