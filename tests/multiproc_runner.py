"""Child process for the multi-process cluster test.

One OS process == one NodeHost over real TCP + gossip on loopback —
the reference's normal deployment shape (drummer ran real multi-process
clusters [U]); every in-repo integration test before this ran all
NodeHosts in one process.  Driven by the parent via a file protocol
(commands in, results out) so kill -9 looks exactly like a machine
crash: no atexit, no graceful close.

Usage: python multiproc_runner.py <rid> <workdir> <base_port>
"""
import json
import os
import sys
import time


def _write_atomic(path: str, obj) -> None:
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(obj, f)
    os.replace(tmp, path)


def main() -> None:
    rid = int(sys.argv[1])
    workdir = sys.argv[2]
    base_port = int(sys.argv[3])
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(
        0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    )
    # this image's sitecustomize imports jax at interpreter start; pin
    # the cpu backend so a child never probes the TPU tunnel (the host
    # engine path used here needs no device at all)
    os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001 — no jax needed on this path
        pass

    from dragonboat_tpu import (
        GossipConfig,
        EngineConfig,
        ExpertConfig,
        NodeHost,
        NodeHostConfig,
    )
    from dragonboat_tpu.transport.tcp import tcp_transport_factory
    from test_nodehost import KVStore, shard_config

    nh = NodeHost(
        NodeHostConfig(
            nodehost_dir=f"{workdir}/nh-{rid}",
            rtt_millisecond=20,
            raft_address=f"127.0.0.1:{base_port + rid}",
            address_by_nodehost_id=True,
            gossip=GossipConfig(
                bind_address=f"127.0.0.1:{base_port + 100 + rid}",
                seed=[f"127.0.0.1:{base_port + 100 + 1}"],
            ),
            expert=ExpertConfig(
                engine=EngineConfig(exec_shards=1, apply_shards=1),
                transport_factory=tcp_transport_factory,
            ),
        )
    )
    # publish our nodehost id, then wait for the full member map: gossip
    # addressing resolves replica -> nodehost-id -> address dynamically,
    # so peers can restart on new ports and still be found
    _write_atomic(f"{workdir}/nhid-{rid}.json", {"nhid": nh.nodehost_id})
    members = {}
    deadline = time.time() + 60
    while len(members) < 3:
        for r in (1, 2, 3):
            p = f"{workdir}/nhid-{r}.json"
            if r not in members and os.path.exists(p):
                try:
                    with open(p) as f:
                        members[r] = json.load(f)["nhid"]
                except (json.JSONDecodeError, KeyError):
                    pass
        if time.time() > deadline:
            raise TimeoutError(f"runner {rid}: member map incomplete")
        time.sleep(0.1)
    nh.start_replica(
        members, False, KVStore,
        shard_config(rid, election_rtt=20, heartbeat_rtt=2,
                     pre_vote=True, check_quorum=True),
    )

    # command loop: cmd-<rid>-<n>.json in, res-<rid>-<n>.json out
    n = 0
    session = nh.get_noop_session(1)
    while True:
        lid, ok = nh.get_leader_id(1)
        _write_atomic(
            f"{workdir}/status-{rid}.json",
            {"leader": lid if ok else 0, "pid": os.getpid(),
             "t": time.time()},
        )
        cmd_path = f"{workdir}/cmd-{rid}-{n}.json"
        if not os.path.exists(cmd_path):
            time.sleep(0.05)
            continue
        with open(cmd_path) as f:
            cmd = json.load(f)
        res = {"ok": False}
        try:
            if cmd["op"] == "propose":
                import pickle

                payload = pickle.dumps(("set", cmd["key"], cmd["val"].encode()))
                end = time.time() + cmd.get("deadline", 30.0)
                while True:
                    try:
                        nh.sync_propose(session, payload, timeout=3.0)
                        res = {"ok": True}
                        break
                    except Exception as e:  # noqa: BLE001 — retry
                        if time.time() > end:
                            res = {"ok": False, "err": type(e).__name__}
                            break
                        time.sleep(0.05)
            elif cmd["op"] == "read":
                end = time.time() + cmd.get("deadline", 30.0)
                while True:
                    try:
                        v = nh.stale_read(1, cmd["key"])
                        if v is not None or time.time() > end:
                            res = {
                                "ok": v is not None,
                                "val": v.decode() if v is not None else None,
                            }
                            break
                    except Exception as e:  # noqa: BLE001 — retry
                        if time.time() > end:
                            res = {"ok": False, "err": type(e).__name__}
                            break
                    time.sleep(0.05)
            elif cmd["op"] == "exit":
                _write_atomic(f"{workdir}/res-{rid}-{n}.json", {"ok": True})
                nh.close()
                return
        except Exception as e:  # noqa: BLE001 — report, keep serving
            res = {"ok": False, "err": repr(e)}
        _write_atomic(f"{workdir}/res-{rid}-{n}.json", res)
        n += 1


if __name__ == "__main__":
    main()
