"""Pure-protocol unit tests — the etcd-style golden suite.

Modelled on the reference's internal/raft/raft_test.go + raft_etcd_test.go
[U].  These tests define the semantics the vectorized TPU kernel must
reproduce; test_step_kernel_parity.py fuzzes the kernel against this core.
"""
import pytest

from dragonboat_tpu.pb import (
    ConfigChange,
    ConfigChangeType,
    Entry,
    EntryType,
    Message,
    MessageType,
    NO_LEADER,
    SystemCtx,
)
from dragonboat_tpu.raft.raft import RaftRole, election_jitter
from dragonboat_tpu.raft.remote import RemoteState

from raft_harness import Network, new_raft


# ---------------------------------------------------------------------------
# elections
# ---------------------------------------------------------------------------
class TestElection:
    def test_initial_state_is_follower(self):
        r = new_raft(1, [1, 2, 3])
        assert r.role == RaftRole.FOLLOWER
        assert r.term == 0
        assert r.leader_id == NO_LEADER

    def test_single_replica_becomes_leader_immediately(self):
        r = new_raft(1, [1])
        r.handle(Message(type=MessageType.ELECTION))
        assert r.role == RaftRole.LEADER
        assert r.term == 1
        # noop entry appended and committed
        assert r.log.last_index() == 1
        assert r.log.committed == 1

    def test_three_replica_election(self):
        net = Network.of(3)
        net.elect(1)
        l = net.peers[1]
        assert l.term == 1
        assert net.peers[2].role == RaftRole.FOLLOWER
        assert net.peers[2].leader_id == 1
        assert net.peers[3].leader_id == 1

    def test_election_timeout_randomized_and_deterministic(self):
        r1 = new_raft(1, [1, 2, 3])
        r2 = new_raft(1, [1, 2, 3])
        # same identity + seq -> identical jitter (replay determinism)
        assert r1.randomized_election_timeout == r2.randomized_election_timeout
        assert (
            r1.election_timeout
            <= r1.randomized_election_timeout
            < 2 * r1.election_timeout
        )
        vals = {election_jitter(1, 1, s, 10) for s in range(50)}
        assert len(vals) > 1  # actually varies

    def test_tick_triggers_election(self):
        net = Network.of(3)
        r = net.peers[1]
        for _ in range(r.randomized_election_timeout):
            r.handle(Message(type=MessageType.LOCAL_TICK))
        net.send(net.drain(r))
        assert r.role == RaftRole.LEADER

    def test_vote_rejected_when_log_behind(self):
        net = Network.of(3)
        net.elect(1)
        net.propose(1)
        # isolate 3 so it misses an entry
        net.isolate(3)
        net.propose(1)
        net.recover()
        # replica 3 campaigns with a stale log: must lose
        net.submit(3, Message(type=MessageType.ELECTION))
        assert net.peers[3].role != RaftRole.LEADER

    def test_vote_granted_once_per_term(self):
        r = new_raft(1, [1, 2, 3])
        r.handle(
            Message(type=MessageType.REQUEST_VOTE, from_=2, to=1, term=1)
        )
        msgs = r.drain_messages()
        assert msgs[0].type == MessageType.REQUEST_VOTE_RESP
        assert not msgs[0].reject
        assert r.vote == 2
        # second candidate same term -> reject
        r.handle(
            Message(type=MessageType.REQUEST_VOTE, from_=3, to=1, term=1)
        )
        msgs = r.drain_messages()
        assert msgs[0].reject

    def test_duelling_candidates(self):
        net = Network.of(3)
        net.cut(1, 3)
        # both 1 and 3 campaign; 2 votes for whoever asks first
        net.submit(1, Message(type=MessageType.ELECTION))
        assert net.peers[1].role == RaftRole.LEADER
        net.submit(3, Message(type=MessageType.ELECTION))
        # 3 cannot win (2 already voted for 1 in term 1... but 3 campaigns at
        # term 2 and 2 grants): either way exactly one leader at the end
        net.recover()
        net.tick_all(25)
        leaders = [r for r in net.peers.values() if r.role == RaftRole.LEADER]
        assert len(leaders) == 1

    def test_leader_steps_down_on_higher_term(self):
        net = Network.of(3)
        net.elect(1)
        assert net.peers[1].role == RaftRole.LEADER
        net.peers[1].handle(
            Message(type=MessageType.REQUEST_VOTE, from_=3, to=1, term=99)
        )
        assert net.peers[1].role == RaftRole.FOLLOWER
        assert net.peers[1].term == 99

    def test_candidate_falls_back_on_replicate(self):
        r = new_raft(1, [1, 2, 3])
        r.handle(Message(type=MessageType.ELECTION))
        assert r.role == RaftRole.CANDIDATE
        r.drain_messages()
        r.handle(Message(type=MessageType.REPLICATE, from_=2, to=1, term=r.term))
        assert r.role == RaftRole.FOLLOWER
        assert r.leader_id == 2


# ---------------------------------------------------------------------------
# prevote
# ---------------------------------------------------------------------------
class TestPreVote:
    def test_prevote_does_not_bump_term(self):
        net = Network.of(3, pre_vote=True)
        r3 = net.peers[3]
        # isolate 3; its campaigns must not disturb term
        net.isolate(3)
        for _ in range(50):
            r3.handle(Message(type=MessageType.LOCAL_TICK))
            net.send(net.drain(r3))
        assert r3.term == 0
        assert r3.role == RaftRole.PRE_CANDIDATE
        # now the cluster elects a leader at term 1 — rejoining 3 does not
        # force an election (the classic partition-rejoin disruption)
        net.recover()
        net.elect(1)
        assert net.peers[1].term == 1

    def test_prevote_then_real_election(self):
        net = Network.of(3, pre_vote=True)
        net.elect(1)
        assert net.peers[1].role == RaftRole.LEADER
        assert net.peers[1].term == 1

    def test_prevote_rejected_by_leader_lease(self):
        net = Network.of(3, pre_vote=True, check_quorum=True)
        net.elect(1)
        net.propose(1)
        # 3 tries to campaign while leader is live: followers in lease drop it
        net.submit(3, Message(type=MessageType.ELECTION))
        assert net.peers[1].role == RaftRole.LEADER
        assert net.peers[1].term == 1


# ---------------------------------------------------------------------------
# replication
# ---------------------------------------------------------------------------
class TestReplication:
    def test_basic_commit(self):
        net = Network.of(3)
        net.elect(1)
        net.propose(1, b"hello")
        l = net.peers[1]
        assert l.log.committed == 2  # noop + proposal
        for pid in (2, 3):
            assert net.peers[pid].log.committed == 2

    def test_commit_requires_quorum(self):
        net = Network.of(3)
        net.elect(1)
        net.isolate(2)
        net.isolate(3)
        net.propose(1, b"nope")
        assert net.peers[1].log.committed == 1  # only the noop
        assert net.peers[1].log.last_index() == 2

    def test_commit_current_term_only(self):
        """An old-term entry is only committed via a new-term commit
        (raft paper §5.4.2; reference: raft.tryCommit [U])."""
        net = Network.of(3)
        net.elect(1)
        net.isolate(2)
        net.isolate(3)
        net.propose(1, b"old-term")  # index 2, replicated nowhere
        net.recover()
        net.isolate(1)
        net.elect(2)  # term 2
        l2 = net.peers[2]
        # entry at index 2 from term 2 (its noop barrier)
        assert l2.log.committed == 2
        assert l2.log.term(2) == l2.term

    def test_follower_log_divergence_truncated(self):
        net = Network.of(3)
        net.elect(1)
        net.propose(1, b"a")
        net.isolate(1)
        # 1 appends entries that never replicate
        net.propose(1, b"lost1")
        net.propose(1, b"lost2")
        assert net.peers[1].log.last_index() == 4
        net.recover()
        net.isolate(1)
        net.elect(2)
        net.propose(2, b"b")
        net.recover()
        # heartbeats bring 1 back in line
        net.tick_all(3)
        r1 = net.peers[1]
        assert r1.role == RaftRole.FOLLOWER
        l2 = net.peers[2]
        assert r1.log.last_index() == l2.log.last_index()
        assert r1.log.committed == l2.log.committed
        for i in range(1, r1.log.last_index() + 1):
            assert r1.log.term(i) == l2.log.term(i)

    def test_replicate_resp_advances_match(self):
        net = Network.of(3)
        net.elect(1)
        l = net.peers[1]
        for pid in (2, 3):
            assert l.remotes[pid].match == 1
            assert l.remotes[pid].next == 2
            assert l.remotes[pid].state == RemoteState.REPLICATE

    def test_stale_replicate_acked_with_committed(self):
        net = Network.of(3)
        net.elect(1)
        net.propose(1, b"x")
        r2 = net.peers[2]
        r2.handle(
            Message(
                type=MessageType.REPLICATE,
                from_=1,
                to=2,
                term=net.peers[1].term,
                log_index=0,
                log_term=0,
                entries=(),
                commit=0,
            )
        )
        msgs = r2.drain_messages()
        assert msgs[0].type == MessageType.REPLICATE_RESP
        assert msgs[0].log_index == r2.log.committed

    def test_proposal_forwarded_by_follower(self):
        net = Network.of(3)
        net.elect(1)
        net.propose(2, b"via-follower")
        assert net.peers[1].log.committed == 2

    def test_proposal_dropped_without_leader(self):
        r = new_raft(1, [1, 2, 3])
        r.handle(
            Message(type=MessageType.PROPOSE, entries=(Entry(cmd=b"x"),))
        )
        de, _ = r.drain_dropped()
        assert len(de) == 1

    def test_old_term_messages_ignored(self):
        net = Network.of(3)
        net.elect(1)
        l = net.peers[1]
        before = l.log.last_index()
        l.handle(
            Message(
                type=MessageType.REPLICATE,
                from_=2,
                to=1,
                term=0,
                entries=(Entry(term=0, index=before + 1),),
            )
        )
        assert l.log.last_index() == before


# ---------------------------------------------------------------------------
# check quorum / leader lease
# ---------------------------------------------------------------------------
class TestCheckQuorum:
    def test_leader_steps_down_without_quorum(self):
        net = Network.of(3, check_quorum=True)
        net.elect(1)
        net.isolate(2)
        net.isolate(3)
        l = net.peers[1]
        for _ in range(2 * l.election_timeout + 1):
            l.handle(Message(type=MessageType.LOCAL_TICK))
            net.send(net.drain(l))
        assert l.role == RaftRole.FOLLOWER

    def test_leader_stays_with_quorum(self):
        net = Network.of(3, check_quorum=True)
        net.elect(1)
        net.isolate(3)
        net.tick_all(25)
        assert net.peers[1].role == RaftRole.LEADER

    def test_lease_blocks_disruptive_vote(self):
        net = Network.of(3, check_quorum=True)
        net.elect(1)
        net.tick_all(1)  # heartbeats establish recent contact
        r2 = net.peers[2]
        r2.handle(
            Message(type=MessageType.REQUEST_VOTE, from_=3, to=2, term=5)
        )
        # in lease: ignored, term unchanged
        assert r2.term == net.peers[1].term
        assert not r2.drain_messages()


# ---------------------------------------------------------------------------
# leader transfer
# ---------------------------------------------------------------------------
class TestLeaderTransfer:
    def test_transfer_to_up_to_date_follower(self):
        net = Network.of(3)
        net.elect(1)
        net.propose(1, b"x")
        net.submit(1, Message(type=MessageType.LEADER_TRANSFER, hint=2))
        assert net.peers[2].role == RaftRole.LEADER
        assert net.peers[1].role == RaftRole.FOLLOWER
        assert net.peers[2].term == net.peers[1].term

    def test_transfer_ignored_for_unknown_target(self):
        net = Network.of(3)
        net.elect(1)
        net.submit(1, Message(type=MessageType.LEADER_TRANSFER, hint=99))
        assert net.peers[1].role == RaftRole.LEADER

    def test_proposals_dropped_during_transfer(self):
        net = Network.of(3)
        net.elect(1)
        l = net.peers[1]
        net.isolate(2)
        net.submit(1, Message(type=MessageType.LEADER_TRANSFER, hint=2))
        assert l.leader_transfer_target == 2
        l.handle(Message(type=MessageType.PROPOSE, entries=(Entry(cmd=b"x"),)))
        de, _ = l.drain_dropped()
        assert len(de) == 1

    def test_transfer_aborts_after_election_timeout(self):
        net = Network.of(3)
        net.elect(1)
        l = net.peers[1]
        net.isolate(2)
        net.submit(1, Message(type=MessageType.LEADER_TRANSFER, hint=2))
        for _ in range(l.election_timeout + 1):
            l.handle(Message(type=MessageType.LOCAL_TICK))
        assert l.leader_transfer_target == 0
        assert l.role == RaftRole.LEADER  # still leader, transfer aborted

    def test_transfer_via_follower_forwarded(self):
        net = Network.of(3)
        net.elect(1)
        net.propose(1, b"x")
        net.submit(3, Message(type=MessageType.LEADER_TRANSFER, hint=2))
        assert net.peers[2].role == RaftRole.LEADER


# ---------------------------------------------------------------------------
# ReadIndex
# ---------------------------------------------------------------------------
class TestReadIndex:
    def test_leader_read_index_quorum(self):
        net = Network.of(3)
        net.elect(1)
        net.propose(1, b"x")
        l = net.peers[1]
        ctx = SystemCtx(low=7, high=9)
        net.submit(
            1, Message(type=MessageType.READ_INDEX, hint=7, hint_high=9)
        )
        rtr = l.drain_ready_to_reads()
        assert len(rtr) == 1
        assert rtr[0].system_ctx == ctx
        assert rtr[0].index == l.log.committed

    def test_single_node_read_index_immediate(self):
        r = new_raft(1, [1])
        r.handle(Message(type=MessageType.ELECTION))
        r.drain_messages()
        r.handle(Message(type=MessageType.READ_INDEX, hint=1, hint_high=2))
        rtr = r.drain_ready_to_reads()
        assert len(rtr) == 1
        assert rtr[0].index == r.log.committed

    def test_follower_read_index_forwarded(self):
        net = Network.of(3)
        net.elect(1)
        net.propose(1, b"x")
        net.submit(
            2, Message(type=MessageType.READ_INDEX, hint=3, hint_high=4)
        )
        rtr = net.peers[2].drain_ready_to_reads()
        assert len(rtr) == 1
        assert rtr[0].index == net.peers[1].log.committed

    def test_read_index_dropped_before_first_commit(self):
        r = new_raft(1, [1, 2, 3])
        r.handle(Message(type=MessageType.ELECTION))
        r.drain_messages()
        r.votes = {1: True, 2: True}
        r.handle(
            Message(type=MessageType.REQUEST_VOTE_RESP, from_=2, to=1, term=r.term)
        )
        assert r.role == RaftRole.LEADER
        # noop not yet committed (no acks): read index must be dropped
        r.drain_messages()
        r.handle(Message(type=MessageType.READ_INDEX, hint=5, hint_high=6))
        _, dropped = r.drain_dropped()
        assert len(dropped) == 1


# ---------------------------------------------------------------------------
# membership
# ---------------------------------------------------------------------------
class TestMembership:
    def test_add_replica(self):
        net = Network.of(3)
        net.elect(1)
        l = net.peers[1]
        l.apply_config_change(
            ConfigChange(
                type=ConfigChangeType.ADD_REPLICA, replica_id=4, address="a4"
            )
        )
        assert 4 in l.remotes
        assert l.quorum() == 3

    def test_remove_replica_advances_commit(self):
        net = Network.of(3)
        net.elect(1)
        l = net.peers[1]
        net.isolate(3)
        net.propose(1, b"x")  # only 1+2 have it: committed (quorum 2)
        net.propose(1, b"y")
        assert l.log.committed == 3
        l.apply_config_change(
            ConfigChange(type=ConfigChangeType.REMOVE_REPLICA, replica_id=3)
        )
        assert 3 not in l.remotes
        assert l.quorum() == 2

    def test_pending_config_change_blocks_second(self):
        net = Network.of(3)
        net.elect(1)
        l = net.peers[1]
        e = Entry(type=EntryType.CONFIG_CHANGE, cmd=b"cc1")
        l.handle(Message(type=MessageType.PROPOSE, entries=(e,)))
        assert l.pending_config_change
        e2 = Entry(type=EntryType.CONFIG_CHANGE, cmd=b"cc2")
        l.handle(Message(type=MessageType.PROPOSE, entries=(e2,)))
        de, _ = l.drain_dropped()
        assert len(de) == 1

    def test_promote_non_voting(self):
        net = Network.of(2)
        rafts = dict(net.peers)
        r3 = new_raft(3, [1, 2], non_votings=[3])
        net.peers[3] = r3
        for r in rafts.values():
            r._add_non_voting(3, "a3")
        net.elect(1)
        l = net.peers[1]
        assert l.quorum() == 2
        net.propose(1, b"x")
        # non-voting receives entries
        assert r3.log.last_index() == l.log.last_index()
        assert r3.role == RaftRole.NON_VOTING
        # promote
        for r in net.peers.values():
            r.apply_config_change(
                ConfigChange(
                    type=ConfigChangeType.ADD_REPLICA, replica_id=3, address="a3"
                )
            )
        assert r3.role == RaftRole.FOLLOWER
        assert net.peers[1].quorum() == 2
        net.propose(1, b"y")
        assert r3.log.committed == l.log.committed


# ---------------------------------------------------------------------------
# witness
# ---------------------------------------------------------------------------
class TestWitness:
    def _witness_net(self):
        rafts = {
            1: new_raft(1, [1, 2], witnesses=[3]),
            2: new_raft(2, [1, 2], witnesses=[3]),
            3: new_raft(3, [1, 2], witnesses=[3]),
        }
        return Network(rafts)

    def test_witness_counts_for_quorum(self):
        net = self._witness_net()
        net.elect(1)
        l = net.peers[1]
        assert l.quorum() == 2
        net.isolate(2)
        net.propose(1, b"x")  # 1 + witness 3 = quorum
        assert l.log.committed == 2

    def test_witness_gets_metadata_entries(self):
        net = self._witness_net()
        net.elect(1)
        net.propose(1, b"secret-payload")
        w = net.peers[3]
        assert w.log.last_index() == 2
        e = w.log._get_entries(2, 3, 2**62)[0]
        assert e.type == EntryType.METADATA
        assert e.cmd == b""

    def test_witness_never_campaigns(self):
        net = self._witness_net()
        w = net.peers[3]
        for _ in range(50):
            w.handle(Message(type=MessageType.LOCAL_TICK))
        assert w.role == RaftRole.WITNESS
        assert not [m for m in w.drain_messages() if not m.is_local()]

    def test_witness_votes(self):
        net = self._witness_net()
        net.isolate(2)
        net.elect(1)  # needs witness vote
        assert net.peers[1].role == RaftRole.LEADER


# ---------------------------------------------------------------------------
# snapshot / compaction interaction with replication
# ---------------------------------------------------------------------------
class TestSnapshotReplication:
    def test_leader_sends_snapshot_for_compacted_follower(self):
        from dragonboat_tpu.pb import Membership, Snapshot

        net = Network.of(3)
        net.elect(1)
        for i in range(5):
            net.propose(1, b"e%d" % i)
        l = net.peers[1]
        # simulate compaction: logdb keeps a snapshot at index 4
        ss = Snapshot(
            index=4,
            term=l.log.term(4),
            membership=Membership(addresses={1: "a1", 2: "a2", 3: "a3"}),
        )
        l.log.logdb.apply_snapshot(ss)
        l.log.logdb.compact(4)
        l.log.inmem.applied_log_to(l.log.last_index())
        # force 3 far behind
        rm = l.remotes[3]
        rm.become_retry()
        rm.next = 2
        rm.match = 1
        l.send_replicate(3)
        msgs = l.drain_messages()
        assert msgs[0].type == MessageType.INSTALL_SNAPSHOT
        assert msgs[0].snapshot.index == 4
        assert rm.state == RemoteState.SNAPSHOT

    def test_follower_restores_from_snapshot(self):
        from dragonboat_tpu.pb import Membership, Snapshot

        r = new_raft(2, [1, 2, 3])
        ss = Snapshot(
            index=10,
            term=3,
            membership=Membership(addresses={1: "a1", 2: "a2", 3: "a3"}),
        )
        r.handle(
            Message(
                type=MessageType.INSTALL_SNAPSHOT, from_=1, to=2, term=3, snapshot=ss
            )
        )
        assert r.log.committed == 10
        assert r.log.inmem.snapshot.index == 10
        msgs = r.drain_messages()
        assert msgs[0].type == MessageType.REPLICATE_RESP
        assert msgs[0].log_index == 10

    def test_stale_snapshot_rejected(self):
        net = Network.of(3)
        net.elect(1)
        net.propose(1, b"x")
        from dragonboat_tpu.pb import Snapshot

        r2 = net.peers[2]
        committed = r2.log.committed
        ss = Snapshot(index=1, term=1)
        r2.handle(
            Message(
                type=MessageType.INSTALL_SNAPSHOT,
                from_=1,
                to=2,
                term=net.peers[1].term,
                snapshot=ss,
            )
        )
        msgs = r2.drain_messages()
        assert msgs[0].log_index == committed


# ---------------------------------------------------------------------------
# flow control / remote states
# ---------------------------------------------------------------------------
class TestRemoteFlow:
    def test_unreachable_backs_off(self):
        net = Network.of(3)
        net.elect(1)
        l = net.peers[1]
        assert l.remotes[2].state == RemoteState.REPLICATE
        l.handle(Message(type=MessageType.UNREACHABLE, from_=2))
        assert l.remotes[2].state == RemoteState.RETRY

    def test_heartbeat_resp_resumes_wait(self):
        net = Network.of(3)
        net.elect(1)
        l = net.peers[1]
        rm = l.remotes[2]
        rm.become_wait()
        l.handle(
            Message(type=MessageType.HEARTBEAT_RESP, from_=2, to=1, term=l.term)
        )
        assert rm.state != RemoteState.WAIT

    def test_reject_decrements_next(self):
        r = new_raft(1, [1, 2])
        r.handle(Message(type=MessageType.ELECTION))
        r.drain_messages()
        r.handle(
            Message(type=MessageType.REQUEST_VOTE_RESP, from_=2, to=1, term=r.term)
        )
        assert r.is_leader()
        for i in range(4):  # log: noop@1 + entries 2..5
            r.handle(
                Message(type=MessageType.PROPOSE, entries=(Entry(cmd=b"x"),))
            )
        rm = r.remotes[2]
        rm.become_retry()
        rm.next = 5
        rm.state = RemoteState.WAIT
        r.drain_messages()
        r.handle(
            Message(
                type=MessageType.REPLICATE_RESP,
                from_=2,
                to=1,
                term=r.term,
                reject=True,
                log_index=4,
                hint=2,
            )
        )
        assert rm.next == 3  # min(rejected=4, hint+1=3)


# ---------------------------------------------------------------------------
# quiesce
# ---------------------------------------------------------------------------
class TestQuiesce:
    def test_enter_and_exit(self):
        from dragonboat_tpu.raft.quiesce import QuiesceManager

        q = QuiesceManager(enabled=True, election_timeout=10)
        for _ in range(q.threshold):
            q.tick()
        assert q.is_quiesced()
        assert q.record_activity(MessageType.PROPOSE)  # exits
        assert not q.is_quiesced()
        # grace period prevents immediate re-entry
        q.tick()
        assert not q.is_quiesced()

    def test_heartbeat_does_not_reset_idle(self):
        from dragonboat_tpu.raft.quiesce import QuiesceManager

        q = QuiesceManager(enabled=True, election_timeout=10)
        for _ in range(q.threshold - 1):
            q.tick()
            q.record_activity(MessageType.HEARTBEAT)
        q.tick()
        assert q.is_quiesced()
