#!/usr/bin/env bash
# Production-day scenario smoke (docs/SCENARIO.md): a tiny seeded
# mini-day (scale 0.4) over the mixed on-disk/in-memory/witness fleet
# under live gateway traffic.  Asserts
#   1. every disturbance class fired at least once (rolling restart,
#      leader churn, snapshot-stream kill/stall, region drain, DR
#      export->import, elastic load-feedback),
#   2. zero Wing-Gong audit violations across the DR boundary,
#   3. zero recovery-SLA misses (every recovery ran under
#      assert_recovery_sla with its fault class),
#   4. the DayReport ledger carries a throughput-dip entry per class.
# ~10-15s — wired into tier1.sh as a post-step.
cd "$(dirname "$0")/.." || exit 1
exec env JAX_PLATFORMS=cpu python - <<'EOF'
import logging

logging.basicConfig(level=logging.ERROR)

from dragonboat_tpu.scenario import DISTURBANCE_CLASSES, DayPlan, ScenarioRunner

plan = DayPlan.mini(7, scale=0.4)
r = ScenarioRunner(plan, tag="smoke-day").run()
assert r.ok, (r.aborted, r.violations, r.audit)
assert set(r.disturbances_fired) == set(DISTURBANCE_CLASSES), (
    r.disturbances_fired
)
assert all(n >= 1 for n in r.disturbances_fired.values()), (
    r.disturbances_fired
)
assert r.audit["ok"] and not r.violations
assert all(c["violations"] == 0 for c in r.recovery.values()), r.recovery
assert set(r.fault_dips) == set(DISTURBANCE_CLASSES), r.fault_dips
# the elastic loop's ledger: >=1 load-driven move fired under the storm,
# ZERO fired in the quiet pre-check, and the move shed the hot shard's
# p99 below the storm peak (ISSUE 18 acceptance)
el = next(p for p in r.phases if p["name"] == "elastic")
assert el["events"] >= 1 and el["quiet_moves"] == 0, el
assert el["p99_after_s"] < el["p99_storm_s"], el
print(
    "SCENARIO_SMOKE_OK "
    f"wall={r.wall_s:.1f}s baseline={r.baseline_committed_per_s:.0f}/s "
    f"classes={len(r.disturbances_fired)} "
    f"elastic_moves={el['events']} "
    f"p99_storm={el['p99_storm_s']*1000:.0f}ms "
    f"p99_after={el['p99_after_s']*1000:.0f}ms "
    f"ops_ok={r.audit['ops'].get('ok', 0)} audit=green sla_misses=0"
)
EOF
