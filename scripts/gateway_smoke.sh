#!/usr/bin/env bash
# Serving-front-plane smoke (gateway tentpole, docs/GATEWAY.md): boot a
# 3-host in-proc cluster with check_quorum on, front it with a Gateway,
# then assert
#   1. exactly-once handles commit a small write workload through the
#      batched per-shard submission path,
#   2. reads are served off the CheckQuorum leader LEASE (lease_reads
#      > 0 — the per-read ReadIndex quorum round trip was skipped),
#   3. the routing cache converged to the leader host via the
#      leader_updated event tap,
#   4. a flooded tiny-queue gateway SHEDS (gateway_shed_total > 0)
#      while everything it admitted still completes.
# Cheap (~10s, host path only, no device) — wired into tier1.sh as a
# post-step.
cd "$(dirname "$0")/.." || exit 1
exec env JAX_PLATFORMS=cpu python - <<'EOF'
import shutil
import sys
import time

sys.path.insert(0, "tests")

from dragonboat_tpu import (
    Config,
    EngineConfig,
    ExpertConfig,
    Gateway,
    GatewayBusy,
    GatewayConfig,
    NodeHost,
    NodeHostConfig,
)
from dragonboat_tpu.transport.inproc import reset_inproc_network
from test_nodehost import KVStore, set_cmd

ADDRS = {1: "gw-smoke-1", 2: "gw-smoke-2", 3: "gw-smoke-3"}
reset_inproc_network()
nhs = {}
for rid, addr in ADDRS.items():
    d = f"/tmp/nh-gw-smoke-{rid}"
    shutil.rmtree(d, ignore_errors=True)
    nhs[addr] = NodeHost(NodeHostConfig(
        nodehost_dir=d,
        rtt_millisecond=2,
        raft_address=addr,
        expert=ExpertConfig(engine=EngineConfig(exec_shards=2, apply_shards=2)),
    ))
gw = None
try:
    for rid, addr in ADDRS.items():
        nhs[addr].start_replica(
            ADDRS, False, KVStore,
            Config(replica_id=rid, shard_id=1, election_rtt=10,
                   heartbeat_rtt=1, check_quorum=True),
        )
    deadline = time.time() + 20.0
    leader = None
    while time.time() < deadline and leader is None:
        leader = next((a for a, nh in nhs.items() if nh.is_leader_of(1)), None)
        time.sleep(0.02)
    assert leader, "no leader within 20s"

    gw = Gateway(nhs, GatewayConfig(workers=2))
    h = gw.connect(1, timeout=10.0)
    for i in range(30):
        h.sync_propose(set_cmd(f"k{i}", i), timeout=10.0)  # (1)
    for i in (0, 29):
        assert gw.read(1, f"k{i}", timeout=10.0) == i
    st = gw.stats()
    assert st["committed"] == 30, st
    assert st["lease_reads"] >= 1, st                       # (2)
    assert st["route_table"].get(1) == leader, (st, leader)  # (3)
    h.close()
    gw.close()

    # (4) overload: tiny queue, flood of async proposals -> sheds, and
    # every admitted future completes
    gw = Gateway(nhs, GatewayConfig(workers=1, max_queue_per_shard=4,
                                    default_timeout=10.0))
    handles = [gw.noop_handle(1) for _ in range(8)]
    futs, sheds = [], 0
    for r in range(12):
        for i, hh in enumerate(handles):
            try:
                futs.append(hh.propose(set_cmd(f"o{r}-{i}", i)))
            except GatewayBusy:
                sheds += 1
    for f in futs:
        f.result(20.0)
    st = gw.stats()
    assert sheds > 0 and st["shed"] == sheds, st
    print(
        f"GATEWAY_SMOKE_OK committed={30 + len(futs)} shed={sheds} "
        f"lease_reads>=1 route={leader}"
    )
finally:
    if gw is not None:
        try:
            gw.close()
        except Exception:
            pass
    for nh in nhs.values():
        try:
            nh.close()
        except Exception:
            pass
EOF
