#!/usr/bin/env bash
# Fused-commit-round smoke (ISSUE 15, docs/BENCH_NOTES_r10.md): boot a
# 3-replica colocated cluster with the launch pipeline at depth 2, a
# 10 ms simulated sync floor and fused waves at the product default
# (K=3), drive a small proposal workload with the hostplane parity
# oracle armed, then assert
#   1. fused waves actually fired (fused_waves > 0) and stepped K
#      rounds each (fused_rounds_stepped >= 3 * fused_waves),
#   2. the one-readback budget held: readback_windows == launches +
#      sel_fallbacks (ONE collect window per generation regardless of
#      its round count — a wave never pays K floors),
#   3. every future completes and the parity oracle stayed green on
#      every live generation (fused or single-round),
#   4. the pipeline drains clean at close (no in-flight generations or
#      deferred actions leak).
# Cheap (~5s) — wired into tier1.sh as a post-step.
cd "$(dirname "$0")/.." || exit 1
exec env JAX_PLATFORMS=cpu DRAGONBOAT_TPU_HOSTPLANE_PARITY=1 python - <<'EOF'
import shutil
import sys
import time

sys.path.insert(0, "tests")

from dragonboat_tpu import (
    Config,
    EngineConfig,
    ExpertConfig,
    NodeHost,
    NodeHostConfig,
)
from dragonboat_tpu.metrics import global_registry
from dragonboat_tpu.ops import hostplane
from dragonboat_tpu.ops.colocated import ColocatedEngineGroup
from dragonboat_tpu.transport.inproc import reset_inproc_network
from test_nodehost import KVStore, set_cmd

ADDRS = {1: "fused-smoke-1", 2: "fused-smoke-2", 3: "fused-smoke-3"}
reset_inproc_network()
group = ColocatedEngineGroup(
    capacity=16, P=5, W=32, M=8, E=4, O=32, budget=4,
    pipeline_depth=2, sync_floor_ms=10.0, fused_rounds=3,
)
nhs = {}
for rid, addr in ADDRS.items():
    d = f"/tmp/nh-fused-smoke-{rid}"
    shutil.rmtree(d, ignore_errors=True)
    nhs[rid] = NodeHost(NodeHostConfig(
        nodehost_dir=d,
        rtt_millisecond=5,
        raft_address=addr,
        expert=ExpertConfig(
            engine=EngineConfig(exec_shards=1, apply_shards=2),
            step_engine_factory=group.factory,
        ),
    ))
try:
    for rid, nh in nhs.items():
        nh.start_replica(
            ADDRS, False, KVStore,
            Config(replica_id=rid, shard_id=1, election_rtt=20,
                   heartbeat_rtt=2, pre_vote=True, check_quorum=True),
        )
    deadline = time.time() + 30.0
    leader = None
    while time.time() < deadline and leader is None:
        leader = next((r for r, nh in nhs.items() if nh.is_leader_of(1)),
                      None)
        time.sleep(0.02)
    assert leader, "no leader within 30s"

    nh = nhs[leader]
    sess = nh.get_noop_session(1)
    pending = []
    for i in range(40):
        pending.append(nh.propose(sess, set_cmd(f"k{i}", str(i)), 20.0))
        if len(pending) >= 8:
            rs = pending.pop(0)
            rs._event.wait(20.0)
            assert rs.code == 1, f"proposal failed: code={rs.code}"
    for rs in pending:
        rs._event.wait(20.0)
        assert rs.code == 1, f"tail proposal failed: code={rs.code}"  # (3)

    core = group.core
    # one-readback budget, snapshotted UNDER the core lock so a tick
    # generation dispatching mid-read can't skew it: every launched
    # generation is either completed (one window counted, plus one per
    # exact-gather fallback round) or still in flight — exact, not <=
    with core._lock:
        st = dict(core.stats)
        inflight = len(core._inflight)
    assert st["fused_waves"] > 0, st                       # (1)
    assert st["fused_rounds_stepped"] >= 3 * st["fused_waves"], st
    assert global_registry.counter("fused_waves_total").value > 0
    assert st["readback_windows"] + inflight == (          # (2)
        st["launches"] + st.get("sel_fallbacks", 0)
    ), (st, inflight)
    assert hostplane.PARITY_FAILURE_COUNT == 0, hostplane.PARITY_FAILURES
finally:
    for nh in nhs.values():
        try:
            nh.close()
        except Exception:
            pass

core = group.core
assert not core._inflight and not core._deferred, (        # (4)
    f"pipeline leaked: inflight={len(core._inflight)} "
    f"deferred={len(core._deferred)}"
)
print(
    f"FUSEDROUND_SMOKE_OK waves={st['fused_waves']} "
    f"rounds={st['fused_rounds_stepped']} "
    f"launches={st['launches']} "
    f"readback_windows={st['readback_windows']} "
    f"fences={st['fused_fences']} parity_green=1"
)
EOF
