#!/usr/bin/env bash
# Observability smoke (obs tentpole, docs/OBSERVABILITY.md): boot a
# 3-host in-proc cluster with tracing + flight recorder ON, push a
# small proposal workload, then assert
#   1. the exported Perfetto trace_event JSON parses,
#   2. it contains >= 1 CROSS-HOST stitched proposal (a follower:append
#      span parented, via the wire-carried trace context, to a propose
#      span recorded on a DIFFERENT host),
#   3. the merged flight-recorder timeline is non-empty.
# Cheap (~5s, host path only, no device) — wired into tier1.sh as a
# post-step.  OBS_SMOKE_JSON=<path> keeps the exported trace file.
cd "$(dirname "$0")/.." || exit 1
exec env JAX_PLATFORMS=cpu python - <<'EOF'
import json
import os
import shutil
import sys
import time

sys.path.insert(0, "tests")

from dragonboat_tpu import EngineConfig, ExpertConfig, NodeHost, NodeHostConfig
from dragonboat_tpu.obs import export_merged_json, hosts_timeline, stitched_traces
from dragonboat_tpu.transport.inproc import reset_inproc_network
from test_nodehost import KVStore, propose_r, set_cmd, shard_config, wait_for_leader

ADDRS = {1: "obs-smoke-1", 2: "obs-smoke-2", 3: "obs-smoke-3"}
reset_inproc_network()
nhs = {}
for rid, addr in ADDRS.items():
    d = f"/tmp/nh-obs-smoke-{rid}"
    shutil.rmtree(d, ignore_errors=True)
    nhs[rid] = NodeHost(NodeHostConfig(
        nodehost_dir=d,
        rtt_millisecond=5,
        raft_address=addr,
        enable_tracing=True,
        enable_flight_recorder=True,
        expert=ExpertConfig(engine=EngineConfig(exec_shards=2, apply_shards=2)),
    ))
try:
    for rid, nh in nhs.items():
        nh.start_replica(ADDRS, False, KVStore, shard_config(rid))
    wait_for_leader(nhs)
    lid, ok = nhs[1].get_leader_id(1)
    assert ok, "no leader"
    leader = nhs[lid]
    s = leader.get_noop_session(1)
    for i in range(10):
        propose_r(leader, s, set_cmd(f"smoke-{i}", b"v"))
    time.sleep(0.2)  # follower spans land asynchronously

    tracers = [nh.tracer for nh in nhs.values()]
    raw = export_merged_json(tracers)
    data = json.loads(raw)  # (1) the export parses
    assert data["traceEvents"], "empty traceEvents"

    stitched = 0  # (2) cross-host stitched proposals
    for tid, spans in stitched_traces(tracers).items():
        roots = [x for x in spans if x.name == "propose"]
        followers = [x for x in spans if x.name == "follower:append"]
        if any(
            r.span_id == f.parent_id and r.host != f.host
            for r in roots
            for f in followers
        ):
            stitched += 1
    assert stitched >= 1, "no cross-host stitched proposal trace"

    timeline = hosts_timeline(nhs.values())  # (3) the merged timeline
    assert "leader_change" in timeline, "flight recorder saw no election"

    out = os.environ.get("OBS_SMOKE_JSON")
    if out:
        with open(out, "w") as f:
            f.write(raw)
    print(
        f"OBS_SMOKE_OK events={len(data['traceEvents'])} "
        f"stitched_traces={stitched} timeline_lines={len(timeline.splitlines())}"
    )
finally:
    for nh in nhs.values():
        try:
            nh.close()
        except Exception:
            pass
EOF
