#!/usr/bin/env bash
# Multi-chip device-plane smoke (docs/MULTICHIP.md, ISSUE 12): run the
# sharded-vs-single-device parity tests under 8 forced host devices —
#   1. kernel step parity (shard_map G-slices bit-exact with the
#      single-device step),
#   2. the full sharded consensus round at 2/4/8 devices in a
#      replica-major layout (every group straddles device blocks, so
#      cross-device raft traffic genuinely rides the ppermute
#      collective exchange lane; zero lane drops at the xbudget_for
#      sizing),
#   3. a membership-change fence mid-run,
#   4. the jaxcheck transfer audit over the sharded entry points
#      (registry.mesh_entry_points): zero host transfers in the steady
#      sharded loop.
# The test module's conftest forces
# --xla_force_host_platform_device_count=8 (the MULTICHIP harness
# mechanism), so this runs anywhere the tier-1 suite runs.
cd "$(dirname "$0")/.." || exit 1
exec env JAX_PLATFORMS=cpu python -m pytest tests/test_multichip.py \
    -q -p no:cacheprovider \
    -k "parity or fence or transfer_free" \
    --no-header
