#!/usr/bin/env bash
# Churn-nemesis + linearizability-audit soak: N seeded acceptance
# rounds of tests/test_audit.py::test_audit_acceptance_256_shards
# (256-shard cluster under leader kills + transfers + membership
# churn + one Balancer move, checked per sampled shard).
#
#   scripts/audit_soak.sh [N] [BASE_SEED]
#
# N defaults to 5 (the acceptance bar), BASE_SEED to 1; round i runs
# seed BASE_SEED+i-1.  Every round prints its seed first, so any
# failure replays with:
#
#   DRAGONBOAT_TPU_AUDIT=1 DRAGONBOAT_TPU_SEED=<seed> \
#     python -m pytest tests/test_audit.py -k acceptance -s
#
# Wired like the DRAGONBOAT_TPU_SOAK gate: the test is `slow`-marked
# and skipped unless DRAGONBOAT_TPU_AUDIT=1, so tier-1 never pays for
# it.  Shard count can be overridden via DRAGONBOAT_TPU_AUDIT_SHARDS.
set -o pipefail
cd "$(dirname "$0")/.." || exit 1
N=${1:-5}
BASE=${2:-1}
for i in $(seq 1 "$N"); do
  seed=$((BASE + i - 1))
  echo "=== audit round $i/$N seed=$seed ==="
  if ! timeout -k 10 900 env JAX_PLATFORMS=cpu \
      DRAGONBOAT_TPU_AUDIT=1 DRAGONBOAT_TPU_SEED=$seed \
      python -m pytest tests/test_audit.py -q -s -k acceptance \
      -p no:cacheprovider; then
    echo "AUDIT SOAK FAILED at seed=$seed (replay: DRAGONBOAT_TPU_AUDIT=1 DRAGONBOAT_TPU_SEED=$seed)"
    exit 1
  fi
done
echo "AUDIT SOAK OK: $N rounds, seeds $BASE..$((BASE + N - 1))"
