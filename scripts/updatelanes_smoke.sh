#!/usr/bin/env bash
# Update-lane smoke (array-side pb.Update lanes, ISSUE 13 /
# docs/PARITY.md "Update-lane contract"): boot a 3-replica colocated
# cluster with the per-generation hostplane parity oracle armed, drive
# a small proposal workload through the device path, then assert
#   1. every future completes (the lane merge tail must not strand or
#      duplicate any completion),
#   2. the lane path actually carried rows: lane_rows > 0 (batched
#      save_state_lanes persists replaced per-row get_update walks —
#      the "Raft-less host rows" mechanism, visible without hardware),
#   3. zero divergence halts and the parity oracle stayed green across
#      every generation (lane words == the scalar twin's, bit for bit).
# Cheap (~5s) — wired into tier1.sh as a post-step.
cd "$(dirname "$0")/.." || exit 1
exec env JAX_PLATFORMS=cpu DRAGONBOAT_TPU_HOSTPLANE_PARITY=1 python - <<'EOF'
import shutil
import sys
import time

sys.path.insert(0, "tests")

from dragonboat_tpu import (
    Config,
    EngineConfig,
    ExpertConfig,
    NodeHost,
    NodeHostConfig,
)
from dragonboat_tpu.ops import hostplane
from dragonboat_tpu.ops.colocated import ColocatedEngineGroup
from dragonboat_tpu.transport.inproc import reset_inproc_network
from test_nodehost import KVStore, set_cmd

ADDRS = {1: "ul-smoke-1", 2: "ul-smoke-2", 3: "ul-smoke-3"}
reset_inproc_network()
group = ColocatedEngineGroup(
    capacity=16, P=5, W=32, M=8, E=4, O=32, budget=4,
)
nhs = {}
for rid, addr in ADDRS.items():
    d = f"/tmp/nh-ul-smoke-{rid}"
    shutil.rmtree(d, ignore_errors=True)
    nhs[rid] = NodeHost(NodeHostConfig(
        nodehost_dir=d,
        rtt_millisecond=5,
        raft_address=addr,
        expert=ExpertConfig(
            engine=EngineConfig(exec_shards=1, apply_shards=2),
            step_engine_factory=group.factory,
        ),
    ))
try:
    for rid, nh in nhs.items():
        nh.start_replica(
            ADDRS, False, KVStore,
            Config(replica_id=rid, shard_id=1, election_rtt=20,
                   heartbeat_rtt=2, pre_vote=True, check_quorum=True),
        )
    deadline = time.time() + 30.0
    leader = None
    while time.time() < deadline and leader is None:
        leader = next((r for r, nh in nhs.items() if nh.is_leader_of(1)),
                      None)
        time.sleep(0.02)
    assert leader, "no leader within 30s"

    nh = nhs[leader]
    sess = nh.get_noop_session(1)
    pending = []
    for i in range(30):
        pending.append(nh.propose(sess, set_cmd(f"k{i}", str(i)), 20.0))
        if len(pending) >= 6:
            rs = pending.pop(0)
            rs._event.wait(20.0)
            assert rs.code == 1, f"proposal failed: code={rs.code}"
    for rs in pending:
        rs._event.wait(20.0)
        assert rs.code == 1, f"tail proposal failed: code={rs.code}"  # (1)

    core = group.core
    st = core.stats
    assert st["launches"] > 5, st
    assert st.get("lane_rows", 0) > 0, (                   # (2)
        f"lane path never carried a row: {st}"
    )
    assert st.get("divergence_halts", 0) == 0, st          # (3)
    assert hostplane.PARITY_FAILURE_COUNT == 0, hostplane.PARITY_FAILURES
finally:
    for nh in nhs.values():
        try:
            nh.close()
        except Exception:
            pass

core = group.core
print(
    f"UPDATELANES_SMOKE_OK launches={core.stats['launches']} "
    f"lane_rows={core.stats.get('lane_rows', 0)} "
    f"lane_commit_rows={core.stats.get('lane_commit_rows', 0)} "
    f"early={core.stats.get('early_completions', 0)} parity_green=1"
)
EOF
