#!/usr/bin/env bash
# Tier-1 verify: the ROADMAP.md command (plus --durations=15 so the
# budget hogs are named in every run), runnable from any cwd, with four
# cheap post-steps: the observability smoke (scripts/obs_smoke.sh, ~5s),
# the serving-front-plane smoke (scripts/gateway_smoke.sh, ~10s: batched
# session proposals, lease reads, routing convergence, overload
# shedding), the big-state smoke (scripts/bigstate_smoke.sh, ~5s:
# capped resumable snapshot stream, cap respected, commit p50 held,
# mid-transfer kill resumes), the launch-pipeline smoke
# (scripts/pipeline_smoke.sh, ~5s: depth-2 double buffering at a 10ms
# simulated sync floor, overlap counter > 0, all futures complete,
# parity green), the fused-round smoke (scripts/fusedround_smoke.sh,
# ~5s: K=3 fused commit waves fire, one readback window per
# generation, parity green, clean drain), the update-lane smoke
# (scripts/updatelanes_smoke.sh,
# ~5s: live cluster generations with the array-side pb.Update lanes
# carrying rows, parity green, zero divergence halts), the multi-chip
# smoke (scripts/multichip_smoke.sh,
# ~60s warm: sharded kernel/round parity at 2/4/8 forced host
# devices + the transfer-free jaxcheck gate over the sharded entry
# points), the production-day scenario smoke (scripts/scenario_smoke.sh,
# ~10-15s: tiny seeded mini-day over the mixed on-disk/in-memory/witness
# fleet — every disturbance class fired, audit green, zero SLA misses),
# the cross-process RPC smoke (scripts/rpc_smoke.sh, ~5-8s: a real
# two-OS-process fleet over RPC/TCP + gossip, leader SIGKILLed and
# recovered under SLA, routing reconverged with zero shared memory)
# the read-plane smoke (scripts/readplane_smoke.sh, ~3s: 3-replica
# shard behind the gateway, one read per consistency level with the
# follower path actually taken, full audit incl. the bounded-read
# containment pass green),
# the fleet-scope telemetry smoke (scripts/fleetobs_smoke.sh, ~5s:
# 2-process fleet under traced gateway proposals, >=1 trace stitched
# across the RPC boundary, bounded obs tails polled from every
# process, JSON SLO burn-rate ledger with the full objective catalog),
# the wire-compat smoke (scripts/wirecheck_smoke.sh, ~3s: the full
# wirecheck gate — goldens/skew/fuzz/rot-guards — plus a live
# mutated-golden true positive)
# and the static-analysis gates + analyzer
# self-tests (scripts/lint.sh: raftlint + jaxcheck + wirecheck +
# fixtures, <3m).
# Prints
# DOTS_PASSED=<n> and a TIER1_BUDGET runtime line against the 870s
# ROADMAP budget, and exits non-zero if any step fails.
cd "$(dirname "$0")/.." || exit 1
t0=$(date +%s)
set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --durations=15 --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
t1=$(date +%s)
total=$((t1 - t0))
headroom=$((870 - total))
warn=""
if [ "$headroom" -lt 60 ]; then
    warn=" — UNDER 60s HEADROOM: gate new suite time behind env vars (ROADMAP budget note)"
fi
echo "TIER1_BUDGET: pytest ${total}s of 870s (headroom ${headroom}s)${warn}"
timeout -k 10 120 bash scripts/obs_smoke.sh || rc=$((rc == 0 ? 1 : rc))
timeout -k 10 120 bash scripts/gateway_smoke.sh || rc=$((rc == 0 ? 1 : rc))
timeout -k 10 120 bash scripts/bigstate_smoke.sh || rc=$((rc == 0 ? 1 : rc))
timeout -k 10 120 bash scripts/pipeline_smoke.sh || rc=$((rc == 0 ? 1 : rc))
timeout -k 10 120 bash scripts/fusedround_smoke.sh || rc=$((rc == 0 ? 1 : rc))
timeout -k 10 120 bash scripts/updatelanes_smoke.sh || rc=$((rc == 0 ? 1 : rc))
timeout -k 10 240 bash scripts/multichip_smoke.sh || rc=$((rc == 0 ? 1 : rc))
timeout -k 10 120 bash scripts/scenario_smoke.sh || rc=$((rc == 0 ? 1 : rc))
timeout -k 10 120 bash scripts/rpc_smoke.sh || rc=$((rc == 0 ? 1 : rc))
timeout -k 10 120 bash scripts/readplane_smoke.sh || rc=$((rc == 0 ? 1 : rc))
timeout -k 10 120 bash scripts/fleetobs_smoke.sh || rc=$((rc == 0 ? 1 : rc))
timeout -k 10 120 bash scripts/wirecheck_smoke.sh || rc=$((rc == 0 ? 1 : rc))
timeout -k 10 300 bash scripts/lint.sh || rc=$((rc == 0 ? 1 : rc))
exit $rc
