#!/usr/bin/env bash
# Tier-1 verify: the ROADMAP.md command, verbatim, runnable from any cwd,
# plus two cheap post-steps: the observability smoke (scripts/obs_smoke.sh,
# ~5s) and the raftlint gate + analyzer self-tests (scripts/lint.sh, <60s).
# Prints DOTS_PASSED=<n> and exits non-zero if any step fails.
cd "$(dirname "$0")/.." || exit 1
set -o pipefail; rm -f /tmp/_t1.log; timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly 2>&1 | tee /tmp/_t1.log; rc=${PIPESTATUS[0]}; echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)
timeout -k 10 120 bash scripts/obs_smoke.sh || rc=$((rc == 0 ? 1 : rc))
timeout -k 10 200 bash scripts/lint.sh || rc=$((rc == 0 ? 1 : rc))
exit $rc
