#!/usr/bin/env bash
# Launch-pipeline smoke (double-buffered generations, docs/
# BENCH_NOTES_r07.md): boot a 3-replica colocated cluster with the
# pipeline at depth 2 and a 10 ms simulated sync floor
# (DRAGONBOAT_TPU_SYNC_FLOOR_MS semantics via the engine kwarg), drive
# a small proposal workload, then assert
#   1. every future completes (zero lost/duplicated completions — the
#      merge tail running one generation behind must not strand any),
#   2. overlap actually occurred: pipeline_overlap_seconds_total > 0
#      (host work ran concurrently with an in-flight readback — the
#      double-buffering win, visible without hardware),
#   3. the pipeline drains clean at close (no in-flight generations or
#      deferred actions leak) and the hostplane parity oracle stayed
#      green across every pipelined generation.
# Cheap (~5s) — wired into tier1.sh as a post-step.
cd "$(dirname "$0")/.." || exit 1
exec env JAX_PLATFORMS=cpu DRAGONBOAT_TPU_HOSTPLANE_PARITY=1 python - <<'EOF'
import shutil
import sys
import time

sys.path.insert(0, "tests")

from dragonboat_tpu import (
    Config,
    EngineConfig,
    ExpertConfig,
    NodeHost,
    NodeHostConfig,
)
from dragonboat_tpu.metrics import global_registry
from dragonboat_tpu.ops import hostplane
from dragonboat_tpu.ops.colocated import ColocatedEngineGroup
from dragonboat_tpu.transport.inproc import reset_inproc_network
from test_nodehost import KVStore, set_cmd

ADDRS = {1: "pipe-smoke-1", 2: "pipe-smoke-2", 3: "pipe-smoke-3"}
reset_inproc_network()
group = ColocatedEngineGroup(
    capacity=16, P=5, W=32, M=8, E=4, O=32, budget=4,
    pipeline_depth=2, sync_floor_ms=10.0,
)
nhs = {}
for rid, addr in ADDRS.items():
    d = f"/tmp/nh-pipe-smoke-{rid}"
    shutil.rmtree(d, ignore_errors=True)
    nhs[rid] = NodeHost(NodeHostConfig(
        nodehost_dir=d,
        rtt_millisecond=5,
        raft_address=addr,
        expert=ExpertConfig(
            engine=EngineConfig(exec_shards=1, apply_shards=2),
            step_engine_factory=group.factory,
        ),
    ))
try:
    for rid, nh in nhs.items():
        nh.start_replica(
            ADDRS, False, KVStore,
            Config(replica_id=rid, shard_id=1, election_rtt=20,
                   heartbeat_rtt=2, pre_vote=True, check_quorum=True),
        )
    deadline = time.time() + 30.0
    leader = None
    while time.time() < deadline and leader is None:
        leader = next((r for r, nh in nhs.items() if nh.is_leader_of(1)),
                      None)
        time.sleep(0.02)
    assert leader, "no leader within 30s"

    nh = nhs[leader]
    sess = nh.get_noop_session(1)
    # async proposals keep generations flowing so readbacks overlap
    # the next launch's upload/dispatch
    pending = []
    for i in range(40):
        pending.append(nh.propose(sess, set_cmd(f"k{i}", str(i)), 20.0))
        if len(pending) >= 8:
            rs = pending.pop(0)
            rs._event.wait(20.0)
            assert rs.code == 1, f"proposal failed: code={rs.code}"
    done = 0
    for rs in pending:
        rs._event.wait(20.0)
        assert rs.code == 1, f"tail proposal failed: code={rs.code}"  # (1)
        done += 1

    core = group.core
    st = core.stats
    overlap = st.get("pipeline_overlap_s", 0.0)
    ctr = global_registry.counter("pipeline_overlap_seconds_total").value
    assert overlap > 0 and ctr > 0, (                      # (2)
        f"no pipeline overlap recorded: stats={overlap} counter={ctr}"
    )
    assert st["launches"] > 5, st
    assert hostplane.PARITY_FAILURE_COUNT == 0, hostplane.PARITY_FAILURES
finally:
    for nh in nhs.values():
        try:
            nh.close()
        except Exception:
            pass

core = group.core
assert not core._inflight and not core._deferred, (        # (3)
    f"pipeline leaked: inflight={len(core._inflight)} "
    f"deferred={len(core._deferred)}"
)
print(
    f"PIPELINE_SMOKE_OK launches={core.stats['launches']} "
    f"overlap_s={core.stats['pipeline_overlap_s']:.3f} "
    f"early={core.stats.get('early_completions', 0)} "
    f"fences={core.stats.get('pipeline_fences', 0)} parity_green=1"
)
EOF
