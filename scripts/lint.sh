#!/usr/bin/env bash
# Static-analysis gates + analyzer self-tests (docs/ANALYSIS.md), wired
# into tier-1 as a cheap post-step: raftlint (AST rules, <60s),
# jaxcheck (the device-plane program auditor: dtype/transfer/donation/
# G-last over every ops/ jit entry point, <60s on CPU) and wirecheck
# (the wire-compat auditor: golden corpus, skew matrix, 500-mutation
# decoder fuzz, registry rot guards, <30s) each fail on any finding
# not covered by their checked-in baselines, then the analyzer
# self-tests prove all three still catch seeded violations
# (true-positive fixtures) and that the lock-order witness detects an
# inverted acquisition.
cd "$(dirname "$0")/.." || exit 1
set -o pipefail
rc=0
timeout -k 5 60 env JAX_PLATFORMS=cpu python -m dragonboat_tpu.analysis \
    --baseline dragonboat_tpu/analysis/baseline.txt dragonboat_tpu bench.py \
    || rc=1
timeout -k 5 60 env JAX_PLATFORMS=cpu python -m dragonboat_tpu.analysis \
    --jax --baseline dragonboat_tpu/analysis/jax_baseline.txt \
    || rc=1
timeout -k 5 60 env JAX_PLATFORMS=cpu python -m dragonboat_tpu.analysis \
    --wire --baseline dragonboat_tpu/analysis/wire_baseline.txt \
    || rc=1
timeout -k 5 150 env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_analysis.py tests/test_invariants.py tests/test_jaxcheck.py \
    -q -p no:cacheprovider -p no:xdist -p no:randomly || rc=1
exit $rc
