#!/usr/bin/env bash
# raftlint gate + analyzer self-tests (docs/ANALYSIS.md), wired into
# tier-1 as a cheap post-step (<60s): fails on any finding not covered
# by dragonboat_tpu/analysis/baseline.txt, then proves the analyzer
# itself still catches seeded violations (true-positive fixtures) and
# that the lock-order witness detects an inverted acquisition.
cd "$(dirname "$0")/.." || exit 1
set -o pipefail
rc=0
timeout -k 5 60 env JAX_PLATFORMS=cpu python -m dragonboat_tpu.analysis \
    --baseline dragonboat_tpu/analysis/baseline.txt dragonboat_tpu bench.py \
    || rc=1
timeout -k 5 120 env JAX_PLATFORMS=cpu python -m pytest \
    tests/test_analysis.py tests/test_invariants.py -q \
    -p no:cacheprovider -p no:xdist -p no:randomly || rc=1
exit $rc
