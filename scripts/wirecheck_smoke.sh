#!/usr/bin/env bash
# Wire-compat smoke (docs/ANALYSIS.md "Wire-plane audit"): the full
# wirecheck gate exactly as lint.sh runs it (goldens + skew matrix +
# 500-mutation deterministic fuzz + rot guards, EMPTY baseline), then
# a live true-positive: a bit-flipped golden copy in a scratch dir
# must produce a golden-drift finding NAMING the mutated frame — the
# auditor is proven non-vacuous on every tier-1 run.  ~3s.
cd "$(dirname "$0")/.." || exit 1
set -o pipefail
timeout -k 5 60 env JAX_PLATFORMS=cpu python -m dragonboat_tpu.analysis \
    --wire --baseline dragonboat_tpu/analysis/wire_baseline.txt || exit 1
exec env JAX_PLATFORMS=cpu timeout -k 5 60 python - <<'EOF'
import shutil, sys, tempfile

from dragonboat_tpu.analysis import wire_registry
from dragonboat_tpu.analysis.wirecheck import (
    GOLDENS_DIR, check_goldens, golden_name,
)

e = wire_registry.entry("batch")
with tempfile.TemporaryDirectory() as tmp:
    shutil.copytree(GOLDENS_DIR, tmp, dirs_exist_ok=True)
    path = f"{tmp}/{golden_name('batch', 'v1')}"
    blob = bytearray(open(path, "rb").read())
    blob[len(blob) // 2] ^= 0x40
    open(path, "wb").write(bytes(blob))
    findings = check_goldens([e], tmp)
rules = {f.rule for f in findings}
named = any("batch" in f.message for f in findings)
if rules != {"golden-drift"} or not named:
    print(f"WIRECHECK_SMOKE: FAIL {findings}")
    sys.exit(1)
print("WIRECHECK_SMOKE: ok (gate green, mutated golden named)")
EOF
