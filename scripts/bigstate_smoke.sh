#!/usr/bin/env bash
# Big-state-plane smoke (bigstate tentpole, docs/BIGSTATE.md): boot a
# 3-host in-proc cluster on the OnDiskKV reference SM, fall a follower
# behind a compacted 8MB state, then assert
#   1. the laggard catches up via a STREAMED snapshot under a 4MB/s
#      bandwidth cap (snapshot_stream_bytes_total covers the state,
#      the token bucket actually throttled),
#   2. the cap is RESPECTED: effective stream rate <= ~1.35x cap
#      (burst headroom + the final sub-second partial interval),
#   3. the commit path is unaffected while the stream runs: p50
#      proposal latency during catch-up within 3x the healthy p50,
#   4. the receive cursor machinery is wired end to end (a forced
#      mid-stream kill resumes instead of restarting from zero).
# Cheap (~10s, host+disk path only, no device) — wired into tier1.sh
# as a post-step.
cd "$(dirname "$0")/.." || exit 1
exec env JAX_PLATFORMS=cpu python - <<'EOF'
import os
import shutil
import sys
import threading
import time

sys.path.insert(0, "tests")

from dragonboat_tpu import (
    Config,
    EngineConfig,
    ExpertConfig,
    Fault,
    FaultController,
    FaultPlan,
    NodeHost,
    NodeHostConfig,
    settings,
)
from dragonboat_tpu.bigstate.ondisk import ondisk_kv_factory, put_cmd
from dragonboat_tpu.storage.logdb import in_mem_logdb_factory
from dragonboat_tpu.transport.inproc import reset_inproc_network
from test_nodehost import propose_r, wait_for_leader

settings.Soft.snapshot_chunk_size = 256 * 1024
settings.Soft.snapshot_stream_max_tries = 8

ADDRS = {1: "bs-smoke-1", 2: "bs-smoke-2", 3: "bs-smoke-3"}
STATE_MB = 8
CAP = 4 * 1024 * 1024
reset_inproc_network()
for rid in ADDRS:
    shutil.rmtree(f"/tmp/nh-bs-smoke-{rid}", ignore_errors=True)
shutil.rmtree("/tmp/bs-smoke-sm", ignore_errors=True)


def mk(rid):
    return NodeHost(NodeHostConfig(
        nodehost_dir=f"/tmp/nh-bs-smoke-{rid}",
        rtt_millisecond=2,
        raft_address=ADDRS[rid],
        expert=ExpertConfig(
            engine=EngineConfig(exec_shards=2, apply_shards=2),
            logdb_factory=in_mem_logdb_factory,
        ),
    ))


fac = {rid: ondisk_kv_factory(f"/tmp/bs-smoke-sm/h{rid}") for rid in ADDRS}
nhs = {rid: mk(rid) for rid in ADDRS}
ctl = FaultController(seed=3, plan=FaultPlan())
try:
    for rid, nh in nhs.items():
        nh.start_replica(
            ADDRS, False, fac[rid],
            Config(replica_id=rid, shard_id=1, election_rtt=20,
                   heartbeat_rtt=2),
        )
    lid = wait_for_leader(nhs)
    nh = nhs[lid]
    s = nh.get_noop_session(1)

    def p50(samples=60):
        lat = []
        for _ in range(samples):
            t0 = time.monotonic()
            propose_r(nh, s, put_cmd(b"p", b"x"))
            lat.append(time.monotonic() - t0)
        lat.sort()
        return lat[len(lat) // 2]

    p50_healthy = p50()

    fid = next(r for r in ADDRS if r != lid)
    nhs[fid].close()
    live = {r: h for r, h in nhs.items() if r != fid}
    lid = wait_for_leader(live)
    nh = nhs[lid]
    s = nh.get_noop_session(1)
    val = os.urandom(1024 * 1024)
    for i in range(STATE_MB):
        propose_r(nh, s, put_cmd(b"big-%d" % i, val))
    for h in live.values():
        h.sync_request_snapshot(1, compaction_overhead=1)
        h.set_snapshot_send_rate(CAP)
        h.transport.set_fault_injector(ctl)
    kill = Fault("snapshot_stream_kill", p=1.0)
    ctl.activate(kill)

    nhf = mk(fid)
    nhs[fid] = nhf
    nhf.start_replica(
        ADDRS, False, fac[fid],
        Config(replica_id=fid, shard_id=1, election_rtt=20,
               heartbeat_rtt=2),
    )
    t0 = time.monotonic()

    def heal():
        while ctl.stats.get("stream_kills", 0) < 1:
            if time.monotonic() - t0 > 20:
                return
            time.sleep(0.001)
        ctl.deactivate(kill)

    threading.Thread(target=heal, daemon=True, name="bs-smoke-heal").start()

    p50_during = p50()  # (3) measured while the capped stream runs

    last = b"big-%d" % (STATE_MB - 1)
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline and nhf.stale_read(1, last) != val:
        time.sleep(0.05)
    assert nhf.stale_read(1, last) == val, "laggard never caught up"
    caught_s = time.monotonic() - t0

    sbytes = sum(h.transport.metrics["stream_bytes"] for h in live.values())
    resumes = sum(h.transport.metrics["stream_resumes"] for h in live.values())
    throttled = sum(
        h.transport.snapshot_pacer.throttled_seconds
        for h in live.values() if h.transport.snapshot_pacer is not None
    )
    # (1) — the full state rode the capped stream.  A killed-then-
    # resumed transfer legitimately undercounts: the resume cursor
    # SEEKS past chunks the receiver already persisted, and the killed
    # attempt's tail may die before its counter fold, so tolerate up
    # to 2MB of resume-skipped prefix (observed ~1MB deficits under
    # load; completeness itself is pinned by the stale_read catch-up
    # assert above — this bound only proves the data moved through
    # THIS stream, not some other path)
    floor_b = (STATE_MB << 20) - (2 << 20 if resumes else 0)
    assert sbytes >= floor_b, (sbytes, floor_b)
    assert throttled > 0, "token bucket never engaged"              # (1)
    eff = sbytes / caught_s
    assert eff <= 1.35 * CAP, f"cap violated: {eff/1e6:.1f}MB/s"    # (2)
    assert p50_during <= max(3 * p50_healthy, p50_healthy + 0.004), (
        f"commit p50 degraded: {p50_healthy*1e3:.2f} -> "
        f"{p50_during*1e3:.2f} ms"
    )                                                               # (3)
    assert ctl.stats.get("stream_kills", 0) >= 1                    # (4)
    assert resumes >= 1, "killed streamer restarted from zero"      # (4)
    print(
        f"BIGSTATE_SMOKE_OK streamed={sbytes >> 20}MB in {caught_s:.1f}s "
        f"(cap {CAP >> 20}MB/s, eff {eff/1e6:.1f}MB/s) "
        f"p50 {p50_healthy*1e3:.2f}->{p50_during*1e3:.2f}ms "
        f"kills={ctl.stats.get('stream_kills')} resumes={resumes}"
    )
finally:
    ctl.stop()
    for h in nhs.values():
        try:
            h.close()
        except Exception:
            pass
EOF
