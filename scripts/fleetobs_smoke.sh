#!/usr/bin/env bash
# Fleet-scope telemetry smoke (docs/OBSERVABILITY.md "Fleet scope"): a
# REAL two-OS-process fleet (scenario/procworker children, RPC/TCP +
# gossip) under gateway proposals CARRYING TRACE CONTEXT, with the
# parent's FleetScope polling every process over RPC_OP_OBS.  Asserts
#   1. at least one proposal's trace stitched ACROSS the RPC boundary
#      (client rpc:propose span + server-side spans, same trace_id,
#      distinct hosts),
#   2. the scope's poll loop collected metrics/recorder/span tails from
#      every process (bounded ring slices — the obs-bound lint rule),
#   3. the SLO burn-rate ledger evaluates as plain JSON and carries the
#      full default objective catalog (commit_p99, shed_ratio, ...).
# ~5s — wired into tier1.sh as a post-step.  The SIGKILL-gap acceptance
# run (leader killed mid-day, obs_gap on the merged timeline) is the
# DRAGONBOAT_MULTIPROC=1 gear of tests/test_fleetobs.py, not run here.
cd "$(dirname "$0")/.." || exit 1
exec env JAX_PLATFORMS=cpu python - <<'EOF'
import json
import logging

logging.basicConfig(level=logging.ERROR)

from dragonboat_tpu.scenario import run_fleetobs_smoke

out = run_fleetobs_smoke(n=2, workdir="/tmp/fleetobs-smoke-ci",
                         base_port=29850)
assert out["stitches"] >= 1, out
assert out["polls"] >= 2 and out["reply_bytes"] > 0, out
print(
    "FLEETOBS_SMOKE_OK "
    f"procs=2 stitches={out['stitches']} polls={out['polls']} "
    f"reply_bytes={out['reply_bytes']} "
    f"slo_objectives={out['slo_objectives']} "
    f"burning={json.dumps(out['burning'])}"
)
EOF
