#!/usr/bin/env bash
# Read-plane smoke (docs/READPLANE.md): a 3-replica in-proc shard
# behind the gateway serving one read per consistency level —
#   1. LINEARIZABLE through the routed leader (lease or ReadIndex),
#   2. FOLLOWER_LINEARIZABLE with the follower path ACTUALLY taken
#      (served by a non-leader host, applied-index stamp present),
#   3. BOUNDED_STALENESS with the staleness stamp within the bound,
# then a short recorded read/write mix over all three levels with the
# full offline audit (Wing-Gong linearizability over leader AND
# follower reads + the bounded-read containment pass) green.
# ~3s — wired into tier1.sh as a post-step.
cd "$(dirname "$0")/.." || exit 1
exec env JAX_PLATFORMS=cpu python - <<'EOF'
import logging, shutil, threading, time

logging.basicConfig(level=logging.ERROR)

from dragonboat_tpu import (
    Config, EngineConfig, ExpertConfig, Gateway, GatewayConfig,
    NodeHost, NodeHostConfig,
)
from dragonboat_tpu.audit import run_audit
from dragonboat_tpu.audit.history import AuditClient, HistoryRecorder, run_workload
from dragonboat_tpu.audit.model import AuditKV
from dragonboat_tpu.readplane import Consistency
from dragonboat_tpu.transport.inproc import reset_inproc_network

reset_inproc_network()
addrs = {r: f"rps-{r}" for r in (1, 2, 3)}
nhs = {}
for r, a in addrs.items():
    d = f"/tmp/nh-rps-{r}"
    shutil.rmtree(d, ignore_errors=True)
    nhs[a] = NodeHost(NodeHostConfig(
        nodehost_dir=d, rtt_millisecond=2, raft_address=a,
        expert=ExpertConfig(engine=EngineConfig(exec_shards=2, apply_shards=2)),
    ))
for r, a in addrs.items():
    nhs[a].start_replica(
        addrs, False, AuditKV,
        Config(replica_id=r, shard_id=1, election_rtt=10, heartbeat_rtt=1,
               check_quorum=True),
    )
gw = Gateway(nhs, GatewayConfig(workers=2))
try:
    deadline = time.time() + 20
    leader = None
    while leader is None and time.time() < deadline:
        leader = next((a for a, nh in nhs.items() if nh.is_leader_of(1)), None)
        time.sleep(0.02)
    assert leader, "no leader"

    rec = HistoryRecorder()
    c = AuditClient(nhs, 1, rec, seed=1)
    written = c.write("k")

    # one read per consistency level through the gateway
    lin = gw.read_at(1, ("get", "k"))
    assert lin.path in ("lease", "read_index"), lin
    deadline = time.time() + 20
    fol = gw.read_at(1, ("get", "k"),
                     consistency=Consistency.FOLLOWER_LINEARIZABLE)
    while fol.host == leader:  # p2c: insist on an actual follower once
        assert time.time() < deadline, "follower path never taken"
        fol = gw.read_at(1, ("get", "k"),
                         consistency=Consistency.FOLLOWER_LINEARIZABLE)
    assert fol.path == "follower" and fol.applied_index >= 1, fol
    while True:
        from dragonboat_tpu.readplane import StaleBoundExceeded
        try:
            bnd = gw.read_at(1, ("get", "k"),
                             consistency=Consistency.BOUNDED_STALENESS,
                             bound_ticks=200)
            break
        except StaleBoundExceeded:
            assert time.time() < deadline, "bounded path never served"
            time.sleep(0.05)
    assert bnd.path == "bounded" and bnd.staleness_ticks <= 200, bnd
    assert lin.value == fol.value == bnd.value == written, (
        lin.value, fol.value, bnd.value, written)

    # short recorded mix over every level, full audit green
    stop = threading.Event()
    clients = [AuditClient(nhs, 1, rec, seed=i) for i in (2, 3)]
    threads = run_workload(clients, ["k", "k2"], stop, read_ratio=0.25,
                           stale_ratio=0.05, follower_ratio=0.2,
                           bounded_ratio=0.2, pace=0.001)
    time.sleep(1.2)
    stop.set()
    for t in threads:
        t.join(10.0)
    rep = run_audit(rec.ops())
    assert rep.ok, rep.describe()

    rp = gw.stats()["read_paths"]
    assert rp["follower"] >= 1, rp
    print(
        "READPLANE_SMOKE_OK "
        f"paths=lease:{rp['lease']},read_index:{rp['read_index']},"
        f"follower:{rp['follower']},bounded:{rp['bounded']} "
        f"audit_ops={len(rec.ops())} audit=green"
    )
finally:
    gw.close()
    for nh in nhs.values():
        try:
            nh.close()
        except Exception:
            pass
EOF
