#!/usr/bin/env bash
# Cross-process RPC smoke (docs/GATEWAY.md "Networked ingress" /
# docs/SCENARIO.md multi-process gear): a REAL two-OS-process fleet —
# each host its own `python -m dragonboat_tpu.scenario.procworker`
# child with TCP raft transport, gossip liveness and an RpcServer
# ingress; the parent drives it purely over RPC/TCP through a
# gossip-routed Gateway (zero shared memory).  Asserts
#   1. open-loop commits land through RemoteHostHandle sessions,
#   2. a lease/ReadIndex read observes the last committed value,
#   3. the LEADER process dies by real SIGKILL and, after restart,
#      the fleet recovers under assert_recovery_sla (proc_kill9),
#   4. the RouteFeeder re-learns the post-kill leader from the
#      gossip-backed collector (rerouted=True) and post-kill commits
#      land.
# ~5-8s — wired into tier1.sh as a post-step.  The 3-process mini
# production day (asym partitions, linearizability audit) is the
# DRAGONBOAT_MULTIPROC=1 gear of tests/test_rpc.py, not run here.
cd "$(dirname "$0")/.." || exit 1
exec env JAX_PLATFORMS=cpu python - <<'EOF'
import logging

logging.basicConfig(level=logging.ERROR)

from dragonboat_tpu.scenario import run_rpc_smoke

out = run_rpc_smoke(n=2, workdir="/tmp/rpc-smoke-ci", base_port=29750)
assert out["committed"] == 8, out
assert out["rerouted"], out
print(
    "RPC_SMOKE_OK "
    f"procs=2 committed={out['committed']} rerouted={out['rerouted']}"
)
EOF
