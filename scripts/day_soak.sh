#!/usr/bin/env bash
# The production-day soak, both gears (docs/SCENARIO.md):
#
#   scripts/day_soak.sh                       # mini gear: ~30-60s day,
#                                             # every disturbance class
#   DRAGONBOAT_SOAK_DAY=1 scripts/day_soak.sh # full gear: hours-long
#                                             # (DRAGONBOAT_SOAK_HOURS,
#                                             #  default 1.0)
#   DRAGONBOAT_SOAK_DAY=1 DRAGONBOAT_BIGSTATE_GB=1 scripts/day_soak.sh
#                                             # full gear, GB tier: the
#                                             # first stream-chaos phase
#                                             # carries ~1GiB of on-disk
#                                             # state behind an 8MB/s cap
#
# Knobs: DRAGONBOAT_SOAK_SEED (default 0 mini / env for full) replays a
# byte-identical schedule; the report JSON lands in /tmp/day_report.json
# and the ledger table prints either way.  Exits non-zero unless the day
# is green (all classes fired, audit green, zero SLA misses).
cd "$(dirname "$0")/.." || exit 1
exec env JAX_PLATFORMS=cpu python - <<'EOF'
import logging
import os
import sys

logging.basicConfig(level=logging.WARNING)

from dragonboat_tpu.scenario import DayPlan, ScenarioRunner

seed = int(os.environ.get("DRAGONBOAT_SOAK_SEED", "0"))
full = os.environ.get("DRAGONBOAT_SOAK_DAY", "0") not in ("", "0")
if full:
    hours = float(os.environ.get("DRAGONBOAT_SOAK_HOURS", "1.0"))
    plan = DayPlan.full(seed, hours=hours)
    print(f"day gear=full seed={seed} hours={hours} "
          f"phases={len(plan.phases)}")
else:
    plan = DayPlan.mini(seed)
    print(f"day gear=mini seed={seed} phases={len(plan.phases)}")

r = ScenarioRunner(plan, tag="soak-day").run()
print(r.format_table())
r.to_json("/tmp/day_report.json")
print("report: /tmp/day_report.json")
if not r.ok:
    print(f"DAY RED: aborted={r.aborted!r} violations={r.violations[:5]}")
    if r.timeline:
        print("--- flight-recorder timeline (tail) ---")
        print("\n".join(r.timeline.splitlines()[-60:]))
    sys.exit(1)
print(f"DAY_SOAK_OK seed={seed} wall={r.wall_s:.1f}s")
EOF
