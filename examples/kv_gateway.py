"""A KV service front-end on the Gateway: the production client path.

Replaces the raw-NodeHost pattern of examples/multigroup.py for client
traffic: instead of each client resolving the leader and driving
``sync_propose``/``sync_read`` itself, clients hold cheap
:class:`~dragonboat_tpu.gateway.ClientHandle` sessions (exactly-once
via the replicated session registry) and the :class:`Gateway` does the
rest — leader routing off ``leader_updated`` events, per-shard batch
submission, admission control, and CheckQuorum lease reads that skip
the per-read ReadIndex quorum round trip (docs/GATEWAY.md).  Run:

    python examples/kv_gateway.py

When the backing NodeHosts run the colocated device engine, client
latency also rides the launch pipeline: generations double-buffer by
default (``DRAGONBOAT_TPU_PIPELINE_DEPTH``, default 2) and the
TPU-tunnel sync-latency model is reproducible on CPU via
``DRAGONBOAT_TPU_SYNC_FLOOR_MS`` (e.g. ``=100`` for the measured
~100 ms floor) — see docs/BENCH_NOTES_r07.md for the serial-vs-
pipelined ledger.  Fused commit waves (``DRAGONBOAT_TPU_FUSED_ROUNDS``,
default 3) then collapse a proposal's propose→commit rounds into one
launch + one readback window — docs/BENCH_NOTES_r10.md.
"""
from __future__ import annotations

import json
import os
import shutil
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dragonboat_tpu import (  # noqa: E402
    Config,
    EngineConfig,
    ExpertConfig,
    Gateway,
    GatewayConfig,
    IStateMachine,
    NodeHost,
    NodeHostConfig,
    Result,
)

ADDRS = {1: "kvgw-1", 2: "kvgw-2", 3: "kvgw-3"}
SHARDS = (1, 2)


class KV(IStateMachine):
    """cmd: b"key=value"; lookup: key -> value."""

    def __init__(self, shard_id, replica_id):
        self.d = {}

    def update(self, entry):
        k, v = entry.cmd.decode().split("=", 1)
        self.d[k] = v
        return Result(value=len(self.d))

    def lookup(self, q):
        return self.d.get(q)

    def save_snapshot(self, w, files, done):
        w.write(json.dumps(self.d).encode())

    def recover_from_snapshot(self, r, files, done):
        self.d = json.loads(r.read(-1).decode())


def main() -> None:
    for rid in ADDRS:
        shutil.rmtree(f"/tmp/nh-kvgw-{rid}", ignore_errors=True)
    nhs = {
        addr: NodeHost(
            NodeHostConfig(
                nodehost_dir=f"/tmp/nh-kvgw-{rid}",
                rtt_millisecond=5,
                raft_address=addr,
                expert=ExpertConfig(
                    engine=EngineConfig(exec_shards=2, apply_shards=2)
                ),
            )
        )
        for rid, addr in ADDRS.items()
    }
    gw = None
    try:
        for sid in SHARDS:
            for rid, addr in ADDRS.items():
                # check_quorum=True is what backs the leader lease: a
                # follower that heard from a live leader refuses votes
                # for an election window, so the leader can serve local
                # reads while its lease holds
                nhs[addr].start_replica(
                    ADDRS, False, KV,
                    Config(replica_id=rid, shard_id=sid, election_rtt=10,
                           heartbeat_rtt=1, check_quorum=True),
                )
        gw = Gateway(nhs, GatewayConfig(workers=2))

        # register session → put: one handle per client, exactly-once
        handles = {sid: gw.connect(sid, timeout=10.0) for sid in SHARDS}
        for sid, h in handles.items():
            for i in range(20):
                h.sync_propose(f"k{i}=s{sid}v{i}".encode(), timeout=10.0)

        # get with lease reads: served on the leader host WITHOUT a
        # ReadIndex quorum round trip while the CheckQuorum lease holds
        for sid in SHARDS:
            assert gw.read(sid, "k0", timeout=10.0) == f"s{sid}v0"
            assert gw.read(sid, "k19", timeout=10.0) == f"s{sid}v19"
        st = gw.stats()
        print("route table:", st["route_table"])
        print(
            f"committed={st['committed']} lease_reads={st['lease_reads']} "
            f"fallbacks={st['read_fallbacks']} shed={st['shed']}"
        )

        # measure the lease win: p50 of lease reads vs ReadIndex reads
        def p50(fn, n=60):
            lat = []
            for _ in range(n):
                t0 = time.perf_counter()
                fn()
                lat.append(time.perf_counter() - t0)
            lat.sort()
            return lat[n // 2] * 1000.0

        lease_p50 = p50(lambda: gw.read(1, "k0", timeout=10.0))
        leader = next(a for a in ADDRS.values() if nhs[a].is_leader_of(1))
        ri_p50 = p50(lambda: nhs[leader].sync_read(1, "k0", timeout=10.0))
        print(
            f"read p50: lease {lease_p50:.3f} ms vs read_index "
            f"{ri_p50:.3f} ms"
        )
        for h in handles.values():
            h.close()
        print("ok")
    finally:
        if gw is not None:
            gw.close()
        for nh in nhs.values():
            nh.close()


if __name__ == "__main__":
    main()
