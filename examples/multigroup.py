"""Multi-group + on-disk state machines on one NodeHost trio.

reference: the lni/dragonboat-example multigroup + ondisk examples [U].
Three NodeHosts in one process host TWO raft shards each: shard 1 is an
in-memory KV, shard 2 an on-disk KV that persists itself and reports its
applied index at open (only the log tail replays).  Run:

    python examples/multigroup.py

NOTE on the client pattern: this example drives NodeHost RAW (resolve
the leader by hand, ``sync_propose``/``sync_read`` per call) to keep
the SM-tier mechanics in focus.  For the production client path —
session handles, leader routing, admission control, lease reads — see
examples/kv_gateway.py and docs/GATEWAY.md.

NOTE on the device launch pipeline: when these NodeHosts share a
``ColocatedEngineGroup`` (the product device path), generations are
double-buffered by default — the merge tail runs one generation behind
the device so a remote link's per-sync latency overlaps the next
launch.  Two knobs make it reproducible without hardware:
``DRAGONBOAT_TPU_PIPELINE_DEPTH`` (2 = double-buffered, 1 = the old
serial loop) and ``DRAGONBOAT_TPU_SYNC_FLOOR_MS`` (simulated-tunnel
readback latency, e.g. 100 for the measured TPU-tunnel floor) — see
docs/BENCH_NOTES_r07.md and ``bench.py phase_pipeline``.  Routable
generations additionally fuse ``DRAGONBOAT_TPU_FUSED_ROUNDS``
consecutive consensus rounds device-side (default 3: a quiet-path
proposal commits in ONE launch + ONE readback window; 1 restores the
single-round loop) — docs/BENCH_NOTES_r10.md.
"""
from __future__ import annotations

import json
import os
import shutil
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dragonboat_tpu import (  # noqa: E402
    Config,
    EngineConfig,
    ExpertConfig,
    IOnDiskStateMachine,
    IStateMachine,
    NodeHost,
    NodeHostConfig,
    Result,
)

ADDRS = {1: "mg-1", 2: "mg-2", 3: "mg-3"}


class MemKV(IStateMachine):
    def __init__(self, shard_id, replica_id):
        self.d = {}

    def update(self, entry):
        k, v = entry.cmd.decode().split("=", 1)
        self.d[k] = v
        return Result(value=len(self.d))

    def lookup(self, q):
        return self.d.get(q)

    def save_snapshot(self, w, files, done):
        w.write(json.dumps(self.d).encode())

    def recover_from_snapshot(self, r, files, done):
        self.d = json.loads(r.read(-1).decode())

    def close(self):
        pass


class DiskKV(IOnDiskStateMachine):
    """Owns its own durability: a json file + applied-index marker."""

    def __init__(self, shard_id, replica_id):
        self.path = f"/tmp/mg-diskkv-{shard_id}-{replica_id}.json"
        self.d = {}
        self.applied = 0

    def open(self, stop_event) -> int:
        if os.path.exists(self.path):
            with open(self.path) as f:
                blob = json.load(f)
            self.d, self.applied = blob["d"], blob["applied"]
        return self.applied

    def update(self, entries):
        results = []
        for e in entries:
            k, v = e.cmd.decode().split("=", 1)
            self.d[k] = v
            self.applied = e.index
            results.append(Result(value=len(self.d)))
        return results

    def lookup(self, q):
        return self.d.get(q)

    def sync(self):
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"d": self.d, "applied": self.applied}, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self.path)

    def prepare_snapshot(self):
        return dict(self.d)

    def save_snapshot(self, ctx, w, files, done):
        w.write(json.dumps(ctx).encode())

    def recover_from_snapshot(self, r, files, done):
        self.d = json.loads(r.read(-1).decode())

    def close(self):
        pass


def main() -> None:
    for rid in ADDRS:
        shutil.rmtree(f"/tmp/nh-mg-{rid}", ignore_errors=True)
    for p in os.listdir("/tmp"):
        if p.startswith("mg-diskkv-"):
            os.unlink(f"/tmp/{p}")

    nhs = {
        rid: NodeHost(
            NodeHostConfig(
                nodehost_dir=f"/tmp/nh-mg-{rid}",
                rtt_millisecond=10,
                raft_address=ADDRS[rid],
                expert=ExpertConfig(
                    engine=EngineConfig(exec_shards=2, apply_shards=2)
                ),
            )
        )
        for rid in ADDRS
    }
    try:
        for rid, nh in nhs.items():
            nh.start_replica(
                ADDRS, False, MemKV,
                Config(shard_id=1, replica_id=rid, election_rtt=10),
            )
            nh.start_replica(
                ADDRS, False, DiskKV,
                Config(shard_id=2, replica_id=rid, election_rtt=10,
                       snapshot_entries=50),
            )

        def leader(shard):
            while True:
                for nh in nhs.values():
                    lid, ok = nh.get_leader_id(shard)
                    if ok and lid:
                        return nhs[lid]
                time.sleep(0.05)

        for shard in (1, 2):
            nh = leader(shard)
            s = nh.get_noop_session(shard)
            for i in range(5):
                while True:
                    try:
                        nh.sync_propose(
                            s, f"k{i}=s{shard}v{i}".encode(), timeout=2.0
                        )
                        break
                    except Exception:
                        time.sleep(0.05)
            print(f"shard {shard}: k0 =", nh.sync_read(shard, "k0"))

        # restart host 1: the on-disk SM reopens at its applied index and
        # only the log tail replays
        nhs[1].close()
        nhs[1] = NodeHost(
            NodeHostConfig(
                nodehost_dir="/tmp/nh-mg-1",
                rtt_millisecond=10,
                raft_address=ADDRS[1],
            )
        )
        nhs[1].start_replica(
            ADDRS, False, MemKV, Config(shard_id=1, replica_id=1, election_rtt=10)
        )
        nhs[1].start_replica(
            ADDRS, False, DiskKV,
            Config(shard_id=2, replica_id=1, election_rtt=10),
        )
        deadline = time.time() + 10
        while time.time() < deadline:
            try:
                if nhs[1].stale_read(2, "k4") == "s2v4":
                    break
            except Exception:
                pass
            time.sleep(0.05)
        print("restarted host 1, on-disk shard k4 =", nhs[1].stale_read(2, "k4"))
        print("ok")
    finally:
        for nh in nhs.values():
            nh.close()


if __name__ == "__main__":
    main()
