"""helloworld: a 3-replica in-memory KV on one machine.

reference: lni/dragonboat-example example/helloworld [U] — the minimum
end-to-end slice (BASELINE config 1): three NodeHosts in one process on
the in-proc transport, one raft shard, linearizable writes and reads.

Run:  python examples/helloworld.py
"""
import pickle
import sys
import time

sys.path.insert(0, ".")

from dragonboat_tpu import (
    Config,
    IStateMachine,
    NodeHost,
    NodeHostConfig,
    Result,
)


class KVStore(IStateMachine):
    """Commands are pickled (op, key, value); lookup returns the value."""

    def __init__(self, shard_id, replica_id):
        self.data = {}

    def update(self, entry):
        op, key, value = pickle.loads(entry.cmd)
        if op == "set":
            self.data[key] = value
        elif op == "del":
            self.data.pop(key, None)
        return Result(value=len(self.data))

    def lookup(self, query):
        return self.data.get(query)

    def save_snapshot(self, w, files, done):
        w.write(pickle.dumps(self.data))

    def recover_from_snapshot(self, r, files, done):
        self.data = pickle.loads(r.read())


def main():
    members = {1: "hw-1", 2: "hw-2", 3: "hw-3"}
    hosts = {}
    for replica_id, addr in members.items():
        cfg = NodeHostConfig(
            nodehost_dir=f"/tmp/helloworld-{replica_id}",
            rtt_millisecond=5,
            raft_address=addr,
        )
        hosts[replica_id] = NodeHost(cfg)
    for replica_id, nh in hosts.items():
        nh.start_replica(
            members,
            False,
            KVStore,
            Config(shard_id=128, replica_id=replica_id, election_rtt=10),
        )

    # wait for a leader
    while True:
        leader, ok = hosts[1].get_leader_id(128)
        if ok:
            print(f"leader elected: replica {leader}")
            break
        time.sleep(0.05)

    nh = hosts[2]  # any replica can take proposals (forwarded to the leader)
    session = nh.get_noop_session(128)
    for i in range(10):
        nh.sync_propose(session, pickle.dumps(("set", f"key-{i}", f"v{i}")))
    print("proposed 10 keys")

    # linearizable read from a different replica
    value = hosts[3].sync_read(128, "key-9")
    print(f"sync_read(key-9) from replica 3 -> {value!r}")

    for nh in hosts.values():
        nh.close()
    print("done")


if __name__ == "__main__":
    import shutil

    for rid in (1, 2, 3):
        shutil.rmtree(f"/tmp/helloworld-{rid}", ignore_errors=True)
    main()
