"""Deterministic placement planner: ClusterView -> ordered MovePlan.

Pure policy, no I/O.  Invariants, in priority order:

1. **drain** — zero member replicas on a draining host: every such
   replica gets a ``replace`` to the least-loaded target host not
   already holding the shard.
2. **repair** — replication factor restored after host loss: members on
   dead hosts are replaced; under-replicated shards (member count below
   the factor with nothing to replace) get an ``add``; surplus members
   (ghosts left by a killed move's failed rollback) get a ``remove``.
3. **spread** — member-replica counts across target hosts within ±1
   (what makes ``join(host)`` pull load onto a new host).
4. **leaders** — leader counts across target hosts within ±1, via pure
   leadership transfers (cheapest move, so it runs last, after the
   replica topology has settled).

Determinism contract (mirrors ``faults.FaultController``): the planner
is seeded, every iteration runs in sorted order, candidate selection
breaks ties by ``(count, host_key)``, and the seeded RNG is re-created
per ``plan()`` call — so the same seed and the same view (by
``describe()``) produce a byte-identical plan, across processes and
hash randomization.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from random import Random
from typing import Dict, List, Optional, Tuple

from .view import ClusterView, ShardView

MOVE_KINDS = ("replace", "add", "remove", "transfer")


@dataclass(frozen=True)
class Move:
    """One planned move.

    * ``replace``: add ``new_replica_id`` on ``dst_host``, wait for
      catch-up, transfer leadership off ``src_replica_id`` if it leads,
      remove ``src_replica_id`` (on ``src_host``; the host may be dead,
      the removal still goes through the survivors' quorum).
    * ``add``: the first half only (restore replication factor).
    * ``remove``: trim ``src_replica_id`` only — a surplus member (a
      ghost left by a killed move's failed rollback, or an
      over-replicated shard); nothing to roll back.
    * ``transfer``: leadership transfer to ``new_replica_id`` (an
      existing member), no membership change.
    """

    kind: str
    shard_id: int
    src_host: str = ""
    src_replica_id: int = 0
    dst_host: str = ""
    new_replica_id: int = 0

    def __post_init__(self):
        if self.kind not in MOVE_KINDS:
            raise ValueError(f"unknown move kind: {self.kind!r}")

    def describe(self) -> str:
        return (
            f"{self.kind}(shard={self.shard_id},"
            f"src={self.src_replica_id}@{self.src_host},"
            f"dst={self.new_replica_id}@{self.dst_host})"
        )


@dataclass
class MovePlan:
    """An ordered move schedule; ``describe()`` is the canonical
    byte-form used by the determinism tests."""

    moves: List[Move] = field(default_factory=list)

    def __len__(self) -> int:
        return len(self.moves)

    def __iter__(self):
        return iter(self.moves)

    def describe(self) -> str:
        return "\n".join(m.describe() for m in self.moves)


class Planner:
    def __init__(self, seed: int = 0, replication_factor: int = 3,
                 balance_replicas: bool = True):
        self.seed = seed
        self.replication_factor = replication_factor
        self.balance_replicas = balance_replicas

    # -- helpers ---------------------------------------------------------
    @staticmethod
    def _pick_least_loaded(
        counts: Dict[str, int], exclude, rng: Random,
        chips: Optional[Dict[str, int]] = None,
    ) -> Optional[str]:
        """Least-loaded candidate host; ties broken by sorted key, the
        rng only shuffles among EXACT ties to avoid always hammering
        the lexically-first host (deterministic: same seed, same draw
        sequence).

        ``chips`` weights load by per-host chip capacity (the
        multi-chip placement dimension, ROADMAP 3): an 8-chip host
        should carry ~8x a 1-chip host's replicas, so candidates rank
        by count/chips — compared exactly via cross-multiplication
        against the chip LCM-free integer key count*K/chips where K is
        the product-free common scale (count * prod(other chips) is
        overkill; count * SCALE // chips with SCALE = lcm-ish 10^6 is
        ample for integral determinism at any real fleet size)."""
        if chips:
            def key(h, c):
                return (c * 1_000_000) // max(1, chips.get(h, 1))
        else:
            def key(h, c):
                return c
        cands = sorted(
            (key(h, c), h) for h, c in counts.items() if h not in exclude
        )
        if not cands:
            return None
        best = [h for c, h in cands if c == cands[0][0]]
        return best[rng.randrange(len(best))] if len(best) > 1 else best[0]

    def plan(self, view: ClusterView, trim_live=frozenset()) -> MovePlan:
        """``trim_live``: shard ids whose surplus has PERSISTED across
        enough passes that trimming a live member is safe (the
        Balancer's streak counter supplies it).  A single view showing
        a live surplus may be transiently stale — a remove committed
        but not yet applied at the reporting replica — so live members
        are only trimmed on this explicit, stability-backed signal."""
        rng = Random(self.seed)
        trim_live = set(trim_live)
        targets = view.target_hosts()
        moves: List[Move] = []
        if not targets:
            return MovePlan(moves)
        # per-host chip capacities (all 1 on single-chip fleets, where
        # every decision below is byte-identical to the unweighted
        # planner); None disables the weighting entirely
        chips = {h: view.chips_of(h) for h in targets}
        if all(n <= 1 for n in chips.values()):
            chips = None
        draining = set(view.draining)
        alive = set(view.hosts)
        counts = {h: 0 for h in targets}
        leaders = {h: 0 for h in targets}
        # projected post-plan placement: shard -> {host: replica_id}
        placement: Dict[int, Dict[str, int]] = {}
        # projected leader host per shard (a replaced leader hands off
        # to its replacement; the executor realizes exactly that)
        leader_at: Dict[int, str] = {}
        next_id: Dict[int, int] = {}
        for s in view.shards:
            placement[s.shard_id] = {h: rid for rid, h in s.members}
            next_id[s.shard_id] = s.next_replica_id
            leader_at[s.shard_id] = s.leader_host
            for _, h in s.members:
                if h in counts:
                    counts[h] += 1
            if s.leader_host in leaders:
                leaders[s.leader_host] += 1

        def do_replace(shard_id: int, src_host: str, src_rid: int,
                       dst: str) -> None:
            new_rid = next_id[shard_id]
            next_id[shard_id] = new_rid + 1
            moves.append(Move(
                kind="replace", shard_id=shard_id,
                src_host=src_host, src_replica_id=src_rid,
                dst_host=dst, new_replica_id=new_rid,
            ))
            pl = placement[shard_id]
            pl.pop(src_host, None)
            pl[dst] = new_rid
            if src_host in counts:
                counts[src_host] -= 1
            counts[dst] += 1
            if leader_at[shard_id] == src_host:
                leader_at[shard_id] = dst
                if src_host in leaders:
                    leaders[src_host] -= 1
                leaders[dst] += 1

        # -- 1. drain + 2. repair (one sorted pass over shards) ----------
        for s in view.shards:
            pl = placement[s.shard_id]
            evict = sorted(
                (h, rid) for h, rid in pl.items()
                if h in draining or h not in alive
            )
            for src_host, src_rid in evict:
                pl = placement[s.shard_id]
                if len(pl) > self.replication_factor:
                    # surplus member on a draining/dead host (a replace
                    # whose final remove failed): a cheap remove-only
                    # finishes the job — no new replica needed
                    moves.append(Move(
                        kind="remove", shard_id=s.shard_id,
                        src_host=src_host, src_replica_id=src_rid,
                    ))
                    pl.pop(src_host, None)
                    if src_host in counts:
                        counts[src_host] -= 1
                    if leader_at[s.shard_id] == src_host:
                        leader_at[s.shard_id] = ""  # raft re-elects
                    continue
                dst = self._pick_least_loaded(counts, set(pl), rng, chips)
                if dst is None:
                    # every target already holds the shard (fewer
                    # survivors than the factor): the drain invariant
                    # outranks the factor — shrink by removing the
                    # draining/dead member, mirroring the repair path's
                    # min(factor, len(targets)) cap.  Without this a
                    # 3-host/rf-3 drain can never converge.
                    moves.append(Move(
                        kind="remove", shard_id=s.shard_id,
                        src_host=src_host, src_replica_id=src_rid,
                    ))
                    pl.pop(src_host, None)
                    if src_host in counts:
                        counts[src_host] -= 1
                    if leader_at[s.shard_id] == src_host:
                        leader_at[s.shard_id] = ""  # raft re-elects
                    continue
                do_replace(s.shard_id, src_host, src_rid, dst)
            # under-replicated with nothing left to evict: pure adds
            while len(placement[s.shard_id]) < min(
                self.replication_factor, len(targets)
            ):
                pl = placement[s.shard_id]
                dst = self._pick_least_loaded(counts, set(pl), rng, chips)
                if dst is None:
                    break
                new_rid = next_id[s.shard_id]
                next_id[s.shard_id] = new_rid + 1
                moves.append(Move(
                    kind="add", shard_id=s.shard_id,
                    dst_host=dst, new_replica_id=new_rid,
                ))
                pl[dst] = new_rid
                counts[dst] += 1
            # surplus members (ghosts left by a killed move's failed
            # rollback): trim back to the factor — GHOSTS ONLY (members
            # with no live replica).  A healthy member must never be
            # auto-trimmed: the collector's membership can transiently
            # show a surplus (a remove committed but not yet applied at
            # the most-applied replica), and trimming a live member on
            # that stale view would shrink a healthy shard.  A ghost
            # remove is idempotently safe — if the membership already
            # dropped it, the executor's goal poll succeeds instantly.
            pl = placement[s.shard_id]
            surplus = len(pl) - self.replication_factor
            if surplus > 0:
                live_hosts = {r.host for r in s.replicas}
                ghosts = sorted(
                    (h, rid) for h, rid in pl.items() if h not in live_hosts
                )
                cands = ghosts
                if s.shard_id in trim_live and len(ghosts) < surplus:
                    # stability-backed: an interrupted spread/leader
                    # replace rolled forward, leaving a live extra voter
                    # on a healthy host that no other invariant touches;
                    # trim non-leaders first, newest replica id first
                    cands = ghosts + sorted(
                        ((h, rid) for h, rid in pl.items()
                         if h in live_hosts),
                        key=lambda hv: (hv[0] == leader_at[s.shard_id],
                                        -hv[1], hv[0]),
                    )
                for host, rid in cands[:surplus]:
                    moves.append(Move(
                        kind="remove", shard_id=s.shard_id,
                        src_host=host, src_replica_id=rid,
                    ))
                    pl.pop(host, None)
                    if host in counts:
                        counts[host] -= 1

        # -- 3. spread: member counts within ±1 across targets ----------
        if self.balance_replicas and len(counts) > 1:
            # per-chip load when chip capacities differ: hi/lo rank by
            # count/chips (exact integer key), and a move happens only
            # while it cannot overshoot — the donor's per-chip load
            # AFTER the move stays >= the recipient's (exact
            # cross-multiplication).  With all chips equal (any value,
            # not just 1) this is bit-for-bit the old count diff <= 1
            ch = chips or {}

            def _load(h):
                return (counts[h] * 1_000_000) // max(1, ch.get(h, 1))

            for _ in range(len(view.shards) * len(targets)):
                hi = max(sorted(counts), key=_load)
                lo = min(sorted(counts), key=_load)
                c_hi, c_lo = max(1, ch.get(hi, 1)), max(1, ch.get(lo, 1))
                if (counts[hi] - 1) * c_lo < (counts[lo] + 1) * c_hi:
                    break
                # move a shard from hi to lo; prefer non-leader replicas
                # (cheaper move: no transfer leg)
                cand = None
                for s in view.shards:
                    pl = placement[s.shard_id]
                    if hi not in pl or lo in pl:
                        continue
                    if leader_at[s.shard_id] != hi:
                        cand = s
                        break
                    cand = cand or s
                if cand is None:
                    break
                do_replace(cand.shard_id, hi, placement[cand.shard_id][hi], lo)

        # -- 4. leaders: counts within ±1 via pure transfers -------------
        if len(leaders) > 1:
            for _ in range(len(view.shards)):
                hi = max(sorted(leaders), key=lambda h: leaders[h])
                lo = min(sorted(leaders), key=lambda h: leaders[h])
                if leaders[hi] - leaders[lo] <= 1:
                    break
                moved = False
                for s in view.shards:
                    pl = placement[s.shard_id]
                    if leader_at[s.shard_id] != hi or lo not in pl:
                        continue
                    # skip shards already touched by a membership move:
                    # their leadership settles as part of that move
                    if any(m.shard_id == s.shard_id and m.kind != "transfer"
                           for m in moves):
                        continue
                    moves.append(Move(
                        kind="transfer", shard_id=s.shard_id,
                        src_host=hi, src_replica_id=pl.get(hi, 0),
                        dst_host=lo, new_replica_id=pl[lo],
                    ))
                    leader_at[s.shard_id] = lo
                    leaders[hi] -= 1
                    leaders[lo] += 1
                    moved = True
                    break
                if not moved:
                    break
        return MovePlan(moves)

    # -- load-reactive pass (docs/BALANCE.md "Load-reactive rebalancing")
    def plan_spread_hot(
        self, view: ClusterView, hot_shards, *, max_moves: int = 1
    ) -> MovePlan:
        """Seeded, pure spread-hot pass: for each HOT shard (the
        Balancer's hysteresis-vetted set), move its leader off the
        current leader host onto the coldest target host — by pure
        ``transfer`` when the shard already has a member there (the
        cheap move), else by ``replace`` of the leader replica (the
        executor's replace realizes the leadership handoff).  Ranking
        is combined leader+member pressure (leaders weigh 1000x: the
        serving plane's commit path runs through leaders), ties break
        through ``_pick_least_loaded``'s seeded shuffle, and projected
        counts advance per move so multiple hot shards cannot dogpile
        one cold host.  ``max_moves`` clamps the whole pass — the
        thrash guard's last line."""
        rng = Random(self.seed)
        targets = view.target_hosts()
        moves: List[Move] = []
        if not targets or max_moves < 1:
            return MovePlan(moves)
        chips = {h: view.chips_of(h) for h in targets}
        if all(n <= 1 for n in chips.values()):
            chips = None
        counts = {h: 0 for h in targets}
        leaders = {h: 0 for h in targets}
        next_id: Dict[int, int] = {}
        placement: Dict[int, Dict[str, int]] = {}
        for s in view.shards:
            placement[s.shard_id] = {h: rid for rid, h in s.members}
            next_id[s.shard_id] = s.next_replica_id
            for _, h in s.members:
                if h in counts:
                    counts[h] += 1
            if s.leader_host in leaders:
                leaders[s.leader_host] += 1

        def pressure():
            return {h: leaders[h] * 1_000 + counts[h] for h in targets}

        for shard_id in sorted(set(hot_shards)):
            if len(moves) >= max_moves:
                break
            s = view.shard(shard_id)
            if s is None or not s.leader_host or s.leader_host not in counts:
                continue
            pl = placement[shard_id]
            cold = self._pick_least_loaded(
                pressure(), {s.leader_host}, rng, chips
            )
            if cold is None:
                continue
            # already the coldest placement: moving gains nothing (and
            # a transfer to an equally-hot host would just thrash)
            if (leaders[cold] * 1_000 + counts[cold]
                    >= leaders[s.leader_host] * 1_000 + counts[s.leader_host]):
                continue
            if cold in pl:
                moves.append(Move(
                    kind="transfer", shard_id=shard_id,
                    src_host=s.leader_host,
                    src_replica_id=pl.get(s.leader_host, 0),
                    dst_host=cold, new_replica_id=pl[cold],
                ))
            else:
                new_rid = next_id[shard_id]
                next_id[shard_id] = new_rid + 1
                moves.append(Move(
                    kind="replace", shard_id=shard_id,
                    src_host=s.leader_host,
                    src_replica_id=pl.get(s.leader_host, 0),
                    dst_host=cold, new_replica_id=new_rid,
                ))
                pl.pop(s.leader_host, None)
                pl[cold] = new_rid
                if s.leader_host in counts:
                    counts[s.leader_host] -= 1
                counts[cold] += 1
            leaders[s.leader_host] -= 1
            leaders[cold] += 1
        return MovePlan(moves)
