"""Cluster view: the balance control plane's input snapshot.

The collector aggregates per-shard stats from every registered NodeHost
(``NodeHost.balance_shard_stats``: leader identity, applied index,
cumulative proposal count, membership) plus host liveness (host handle
present and not closed; cross-process deployments layer the gossip
registry's direct-contact signal, ``GossipManager.alive_peers``, on
top) into one immutable :class:`ClusterView`.  The planner is a pure
function of a view, so ``describe()`` gives the canonical byte-form
used by the determinism tests — two views are the same input iff their
describe() strings are equal (the same contract as
``faults.FaultPlan.describe``).

No reference equivalent: dragonboat deliberately stops at mechanism
(``RequestAddReplica``, leadership transfer) and leaves placement
policy to the user [U]; this subsystem is the missing policy layer.
"""
from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import Callable, Dict, Optional, Tuple

from ..logger import get_logger

_log = get_logger("balance")


@dataclass(frozen=True)
class ReplicaView:
    """One live replica as observed on its host."""

    replica_id: int
    host: str          # host key (raft address)
    applied: int = 0
    is_leader: bool = False
    # chip coordinate of the replica's engine row on its host (-1:
    # host path / single device) — docs/MULTICHIP.md "Placement"
    device: int = -1


@dataclass(frozen=True)
class ShardView:
    """One shard's aggregated state.

    ``members`` is the authoritative replica_id -> host map from the
    most-applied live replica's membership; ``replicas`` are the live
    observations (a member on a dead host has no ReplicaView).
    ``next_replica_id`` is safe to assign to a NEW replica: above every
    current member AND every removed id (removed ids can never be
    re-added — rsm/membership rejects them).
    """

    shard_id: int
    members: Tuple[Tuple[int, str], ...]      # sorted (replica_id, host)
    replicas: Tuple[ReplicaView, ...]          # sorted by replica_id
    leader_replica_id: int = 0
    leader_host: str = ""
    next_replica_id: int = 1
    proposal_rate: int = 0    # proposals since the previous collect

    def member_hosts(self) -> Tuple[str, ...]:
        return tuple(h for _, h in self.members)

    def host_of(self, replica_id: int) -> Optional[str]:
        for rid, h in self.members:
            if rid == replica_id:
                return h
        return None

    def replica_on(self, host: str) -> Optional[int]:
        for rid, h in self.members:
            if h == host:
                return rid
        return None

    def describe(self) -> str:
        reps = ",".join(
            f"{r.replica_id}@{r.host}:{r.applied}{'*' if r.is_leader else ''}"
            for r in self.replicas
        )
        mem = ",".join(f"{rid}@{h}" for rid, h in self.members)
        return (
            f"shard({self.shard_id},members=[{mem}],live=[{reps}],"
            f"leader={self.leader_replica_id}@{self.leader_host},"
            f"next={self.next_replica_id},rate={self.proposal_rate})"
        )


@dataclass(frozen=True)
class ShardLoad:
    """One shard's serving-plane load evidence over the last collect
    window (docs/BALANCE.md "Load-reactive rebalancing").  ``p99_ms``
    is the gateway's observed commit p99 rounded to whole milliseconds
    (integers keep describe() byte-stable); ``submitted``/``shed`` are
    WINDOW DELTAS — the Collector differences the gateway's cumulative
    counters with the same first-sight baseline it uses for
    proposal_rate."""

    shard_id: int
    p99_ms: int = 0
    samples: int = 0
    submitted: int = 0
    shed: int = 0

    def describe(self) -> str:
        return (
            f"load({self.shard_id},p99={self.p99_ms}ms,"
            f"n={self.samples},sub={self.submitted},shed={self.shed})"
        )


@dataclass(frozen=True)
class ClusterView:
    """One collector pass over the whole cluster."""

    hosts: Tuple[str, ...]             # alive hosts, sorted
    draining: Tuple[str, ...]          # sorted subset being drained
    shards: Tuple[ShardView, ...]      # sorted by shard_id
    # per-host chip count (sorted (host, chips) pairs; hosts absent
    # here count as 1 chip) — the planner's capacity weights for the
    # multi-chip placement dimension (docs/MULTICHIP.md "Placement").
    # Default empty keeps single-chip fleets byte-identical.
    chips: Tuple[Tuple[str, int], ...] = ()
    # per-shard serving-plane load evidence (sorted by shard_id; empty
    # when no load source is attached — the default keeps existing
    # describe() baselines byte-identical, same opt-in as chips)
    load: Tuple[ShardLoad, ...] = ()

    def load_of(self, shard_id: int) -> Optional[ShardLoad]:
        for l in self.load:
            if l.shard_id == shard_id:
                return l
        return None

    def chips_of(self, host: str) -> int:
        for h, n in self.chips:
            if h == host:
                return max(1, n)
        return 1

    def target_hosts(self) -> Tuple[str, ...]:
        """Hosts moves may land on: alive and not draining."""
        d = set(self.draining)
        return tuple(h for h in self.hosts if h not in d)

    def shard(self, shard_id: int) -> Optional[ShardView]:
        for s in self.shards:
            if s.shard_id == shard_id:
                return s
        return None

    def replica_counts(self) -> Dict[str, int]:
        """Member-replica count per alive host (dead hosts excluded)."""
        counts = {h: 0 for h in self.hosts}
        for s in self.shards:
            for _, h in s.members:
                if h in counts:
                    counts[h] += 1
        return counts

    def leader_counts(self) -> Dict[str, int]:
        counts = {h: 0 for h in self.hosts}
        for s in self.shards:
            if s.leader_host in counts:
                counts[s.leader_host] += 1
        return counts

    def replicas_on(self, host: str) -> int:
        return sum(1 for s in self.shards for _, h in s.members if h == host)

    def leader_map(self) -> Dict[int, str]:
        """shard_id -> leader host, for shards with a known leader — the
        gateway routing cache's bulk-refresh input
        (gateway.RoutingCache.refresh_from_view)."""
        return {
            s.shard_id: s.leader_host
            for s in self.shards
            if s.leader_host
        }

    def describe(self) -> str:
        # chips appear in the canonical byte-form only when some host
        # is genuinely multi-chip: single-chip fleets keep the exact
        # pre-mesh describe() bytes (determinism baselines)
        chips = ""
        if any(n > 1 for _, n in self.chips):
            chips = f" chips={sorted(self.chips)!r}"
        body = (
            f"hosts={list(self.hosts)!r} draining={list(self.draining)!r}"
            f"{chips}\n"
            + "\n".join(s.describe() for s in self.shards)
        )
        # load rows follow the chips opt-in: only emitted when a load
        # source is attached, so pre-elastic baselines stay byte-exact
        if self.load:
            body += "\n" + ",".join(l.describe() for l in self.load)
        return body


class Collector:
    """Aggregates NodeHost stats into a ClusterView.

    Stateful only for proposal-rate derivation (previous cumulative
    counts); everything else is a pure snapshot.  ``alive`` overrides
    the liveness predicate — the default treats a registered,
    non-closed host as alive, which is exact for in-process fleets;
    cross-process deployments pass a gossip-backed predicate
    (``lambda key: nhid(key) in gm.alive_peers()``).
    """

    def __init__(
        self,
        alive: Optional[Callable[[str, object], bool]] = None,
        load_source: Optional[Callable[[], Dict[int, dict]]] = None,
    ):
        self._alive = alive
        # serving-plane evidence hook (``Gateway.shard_load``): absent
        # by default so membership-only deployments build byte-identical
        # views; failures degrade to "no load rows" (placement must
        # never depend on the gateway being up)
        self.load_source = load_source
        self._prev_load: Dict[int, Tuple[int, int]] = {}
        self._prev_proposals: Dict[int, int] = {}
        # hosts that reported last round: a host dropping out (liveness
        # flap, mid-collect failure) makes the round incomplete for the
        # rate baseline (see below)
        self._prev_reporters: set = set()
        # collect() advances the rate baseline, so concurrent callers
        # (the run loop's per-move collects + a monitoring thread's
        # view()) must serialize or proposal_rate becomes 'proposals
        # since whichever caller collected last'
        self._collect_lock = threading.Lock()

    def host_alive(self, key: str, nh) -> bool:
        if self._alive is not None:
            return self._alive(key, nh)
        return nh is not None and not getattr(nh, "_closed", False)

    def collect(self, hosts: Dict[str, object], draining=()) -> ClusterView:
        with self._collect_lock:
            return self._collect_locked(hosts, draining)

    def _collect_locked(self, hosts, draining) -> ClusterView:
        alive = sorted(k for k, nh in hosts.items() if self.host_alive(k, nh))
        # shard_id -> accumulated rows
        stats: Dict[int, list] = {}
        reporters = set()
        for key in alive:
            try:
                rows = hosts[key].balance_shard_stats()
            except Exception:  # noqa: BLE001 — host died mid-collect
                _log.warning("collect: host %s failed to report", key)
                continue
            reporters.add(key)
            for row in rows:
                stats.setdefault(row["shard_id"], []).append((key, row))
        # a round is COMPLETE for the rate baseline only if every host
        # that reported last round reported again: a host dropping out
        # (collect failure OR a liveness-predicate flap) shrinks the
        # cumulative sums, and advancing the baseline on that shrunken
        # total would fabricate a rate spike when the host returns
        complete = self._prev_reporters <= reporters
        self._prev_reporters = reporters
        shard_views = []
        for shard_id in sorted(stats):
            rows = stats[shard_id]
            # authoritative membership: the most-applied live replica's
            # (ties break on host key so the choice is deterministic)
            _, best = max(rows, key=lambda kr: (kr[1]["applied"], kr[0]))
            membership = best["membership"]
            members = tuple(sorted(
                (rid, addr) for rid, addr in membership.addresses.items()
            ))
            replicas = tuple(sorted(
                (
                    ReplicaView(
                        replica_id=row["replica_id"],
                        host=key,
                        applied=row["applied"],
                        is_leader=(row["leader_id"] == row["replica_id"]
                                   and row["leader_id"] != 0),
                        device=row.get("device", -1),
                    )
                    for key, row in rows
                ),
                key=lambda r: r.replica_id,
            ))
            # leader: a self-claim wins, and the HIGHEST-TERM self-claim
            # wins overall — during a handoff the old leader may not
            # have stepped down yet and still claims at a stale term
            # (otherwise: the majority view among live replicas)
            leader_id = 0
            claims = [
                (row["term"], row["replica_id"])
                for _, row in rows
                if row["leader_id"] and row["leader_id"] == row["replica_id"]
            ]
            if claims:
                leader_id = max(claims)[1]
            else:
                votes: Dict[int, int] = {}
                for _, row in rows:
                    if row["leader_id"]:
                        votes[row["leader_id"]] = votes.get(
                            row["leader_id"], 0) + 1
                if votes:
                    leader_id = max(sorted(votes), key=lambda k: votes[k])
            leader_host = ""
            for rid, h in members:
                if rid == leader_id:
                    leader_host = h
                    break
            ids = (
                [rid for rid, _ in members]
                + list(membership.non_votings)
                + list(membership.witnesses)
                + list(membership.removed)
                + [r.replica_id for r in replicas]
            )
            # rate baseline advances only on COMPLETE rounds: a host
            # failing to report mid-collect shrinks the cumulative sum,
            # and rewriting the baseline with that partial total would
            # fabricate a rate spike on the next full round
            total = sum(row["proposals"] for _, row in rows)
            prev = self._prev_proposals.get(shard_id, total)
            if complete:
                self._prev_proposals[shard_id] = total
            shard_views.append(
                ShardView(
                    shard_id=shard_id,
                    members=members,
                    replicas=replicas,
                    leader_replica_id=leader_id,
                    leader_host=leader_host,
                    next_replica_id=max(ids, default=0) + 1,
                    proposal_rate=max(0, total - prev),
                )
            )
        chips = []
        for key in alive:
            fn = getattr(hosts.get(key), "device_chip_count", None)
            if fn is None:
                continue
            try:
                n = int(fn())
            except Exception:  # noqa: BLE001 — host closing mid-collect
                n = 1
            if n > 1:
                chips.append((key, n))
        load_rows = []
        if self.load_source is not None:
            try:
                raw = self.load_source() or {}
            except Exception:  # noqa: BLE001 — gateway closing mid-collect
                raw = {}
            for sid in sorted(raw):
                row = raw[sid]
                sub = int(row.get("submitted", 0))
                shed = int(row.get("shed", 0))
                # first-sight baseline = current totals (delta 0), the
                # proposal_rate idiom; gateway counters are cumulative
                # and monotonic so the baseline always advances
                psub, pshed = self._prev_load.get(sid, (sub, shed))
                self._prev_load[sid] = (sub, shed)
                load_rows.append(ShardLoad(
                    shard_id=sid,
                    p99_ms=int(round(float(row.get("p99_s", 0.0)) * 1000)),
                    samples=int(row.get("samples", 0)),
                    submitted=max(0, sub - psub),
                    shed=max(0, shed - pshed),
                ))
        return ClusterView(
            hosts=tuple(alive),
            draining=tuple(sorted(set(draining))),
            shards=tuple(shard_views),
            chips=tuple(sorted(chips)),
            load=tuple(load_rows),
        )
