"""Move executor: realizes one planned move as the safe sequence

    add-replica -> wait-for-catchup -> transfer-leadership -> remove-replica

with a deadline and backoff per step, membership steps driven by GOAL
STATE rather than per-attempt acks (``client.propose_with_retry``-style
deadline discipline; see ``_member_goal``), rollback on failure (the
added replica is removed again, restoring the pre-move membership), and
every transition exported as labelled metrics and ``balance_move_*``
system events.  The nemesis hooks in via
``FaultController.on_balance_step`` (kind ``balance_abort`` /
``balance_stall``) so chaos schedules can kill a move mid-sequence.

Ordering is what makes the sequence safe: the new replica joins as a
voter FIRST and must catch up BEFORE the old one is removed, so the
shard never drops below its replication factor and never commits
through a quorum that contains a hollow member for longer than the
catch-up window; leadership is handed off explicitly so the removal
never triggers an election.
"""
from __future__ import annotations

import time
from typing import Callable, Dict, Optional

from ..logger import get_logger
from ..raftio import BalanceMoveInfo
from .planner import Move
from .view import ClusterView

_log = get_logger("balance")


def _live(nh) -> bool:
    """Host-handle liveness (the executor-side twin of
    Collector.host_alive): registered and not closed."""
    return nh is not None and not getattr(nh, "_closed", False)


class MoveFailed(Exception):
    """The move could not complete; rollback has been attempted."""


class BalanceAborted(MoveFailed):
    """A nemesis ``balance_abort`` fault killed the move mid-sequence."""


class MoveExecutor:
    """Executes moves against a live host map.

    ``hosts`` maps host key (raft address) -> NodeHost; ``sm_factory``
    and ``config_factory(shard_id, replica_id) -> Config`` tell the
    executor how to start the replacement replica on the destination
    host (the same factories the shards were originally started with).
    """

    def __init__(
        self,
        hosts: Dict[str, object],
        sm_factory: Callable,
        config_factory: Callable,
        *,
        metrics=None,
        events=None,
        fault_injector=None,
        step_timeout: float = 10.0,
        catchup_timeout: float = 30.0,
        catchup_gap: int = 0,
    ):
        self.hosts = hosts
        self.sm_factory = sm_factory
        self.config_factory = config_factory
        self.events = events
        self.fault_injector = fault_injector
        self.step_timeout = step_timeout
        self.catchup_timeout = catchup_timeout
        self.catchup_gap = catchup_gap
        if metrics is None:
            from ..metrics import MetricsRegistry

            metrics = MetricsRegistry(enabled=True)
        self.metrics = metrics
        # progress cadence for catchup_progress events (seconds)
        self.progress_interval = 0.5
        # per-move report: the catchup leg surfaces live
        # snapshot_stream_* progress here (bytes, resumes, ETA) instead
        # of a blind applied-index poll (ROADMAP 5b); rewritten at each
        # execute(), readable after it returns/raises
        self.last_move_report: Dict[str, object] = {}

    # -- plumbing --------------------------------------------------------
    def _info(self, move: Move, step: str, detail: str = "") -> BalanceMoveInfo:
        return BalanceMoveInfo(
            shard_id=move.shard_id, kind=move.kind, src=move.src_host,
            dst=move.dst_host, replica_id=move.new_replica_id, step=step,
            detail=detail,
        )

    def _event(self, name: str, move: Move, step: str,
               detail: str = "") -> None:
        if self.events is not None:
            getattr(self.events, name)(self._info(move, step, detail))

    @staticmethod
    def _stream_totals(hosts) -> Dict[str, int]:
        """Aggregate ``snapshot_stream_*`` counters across the fleet's
        transports (the SENDER side carries them — whichever member
        streams the joiner's snapshot).  Hosts without a transport
        (test doubles, closed hosts) contribute zeros."""
        out = {"bytes": 0, "resumes": 0, "active": 0}
        for nh in hosts.values():
            tr = getattr(nh, "transport", None)
            m = getattr(tr, "metrics", None)
            if not isinstance(m, dict):
                continue
            out["bytes"] += int(m.get("stream_bytes", 0))
            out["resumes"] += int(m.get("stream_resumes", 0))
            active_fn = getattr(tr, "active_stream_jobs", None)
            if callable(active_fn):
                out["active"] += int(active_fn())
        return out

    def _count(self, name: str, **labels) -> None:
        self.metrics.counter(f"balance_{name}", labels or None).add()

    def _checkpoint(self, move: Move, step: str) -> None:
        """Per-step fault point + progress event."""
        inj = self.fault_injector
        if inj is not None and inj.on_balance_step(move.shard_id, step):
            raise BalanceAborted(
                f"nemesis aborted {move.describe()} at step {step!r}"
            )
        self._event("balance_move_step", move, step)

    def _api_host(self, move: Move, view: ClusterView):
        """A live host holding the shard to issue requests through
        (prefer the leader's host, avoid the src being evicted; src is
        kept as the LAST resort — for a one-member shard it is the only
        door)."""
        s = view.shard(move.shard_id)
        order = []
        if s is not None:
            if s.leader_host and s.leader_host != move.src_host:
                order.append(s.leader_host)
            order.extend(h for _, h in s.members if h != move.src_host)
            order.extend(h for _, h in s.members)
        for key in order:
            nh = self.hosts.get(key)
            if _live(nh):
                return nh
        raise MoveFailed(
            f"no live host holds shard {move.shard_id} to drive the move"
        )

    @staticmethod
    def _applied(nh, shard_id: int, replica_id: Optional[int] = None) -> int:
        top = -1
        for row in nh.balance_shard_stats():
            if row["shard_id"] != shard_id:
                continue
            if replica_id is not None and row["replica_id"] != replica_id:
                continue
            top = max(top, row["applied"])
        return top

    def _member_goal(self, move: Move, api, replica_id: int, present: bool,
                     request) -> None:
        """Drive a membership change by GOAL STATE, not per-attempt acks
        (the de-flake discipline the membership tests use): an attempt's
        future can time out while its entry still commits, making the
        retry REJECTED even though the goal is reached — so success is
        the membership containing (or no longer containing) the replica,
        and rejections only matter while the goal state isn't seen."""
        from ..nodehost import RequestRejected

        deadline = time.monotonic() + self.step_timeout
        last = None
        while True:
            m = api.get_shard_membership(move.shard_id)
            if (replica_id in m.addresses) == present:
                return
            try:
                request()
            except RequestRejected as e:
                last = e  # may have raced a commit; the poll decides
            except Exception as e:  # noqa: BLE001 — transient; retry
                last = e
            if time.monotonic() >= deadline:
                raise MoveFailed(
                    f"membership goal (replica {replica_id} "
                    f"{'present' if present else 'absent'}) not reached "
                    f"for {move.describe()}: last error {last!r}"
                )
            time.sleep(0.05)

    # -- the move state machine -----------------------------------------
    def execute(self, move: Move, view: ClusterView) -> None:
        """Run one move to completion.  Raises :class:`MoveFailed` (after
        attempting rollback) on any step failure; a failed TRANSFER-only
        move needs no rollback (no membership was changed)."""
        self._event("balance_move_started", move, "plan")
        self._count("moves_started_total", kind=move.kind)
        self.last_move_report = {"move": move.describe(), "kind": move.kind}
        t0 = time.perf_counter()
        try:
            if move.kind == "transfer":
                self._checkpoint(move, "transfer")
                self._transfer(move, view, target=move.new_replica_id)
            elif move.kind == "remove":
                self._remove_only(move, view)
            else:
                self._membership_move(move, view)
        except Exception as e:  # noqa: BLE001 — a move failure must never
            # abort the whole pass: a host can stop its replica between
            # view collection and execution (ShardNotFound, closed host),
            # and those raw errors must get the same failed-move
            # accounting as a MoveFailed
            self._count("moves_failed_total", kind=move.kind)
            self._event("balance_move_failed", move, "failed")
            if isinstance(e, MoveFailed):
                raise
            raise MoveFailed(f"{move.describe()} failed: {e!r}") from e
        # move durations run seconds-to-minutes (catchup polls); the
        # default sub-second latency bounds would dump everything in +Inf
        self.metrics.histogram(
            "balance_move_seconds",
            bounds=(0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0),
        ).observe(time.perf_counter() - t0)
        self._count("moves_completed_total", kind=move.kind)
        self._event("balance_move_completed", move, "done")

    def _membership_move(self, move: Move, view: ClusterView) -> None:
        api = self._api_host(move, view)
        dst_nh = self.hosts.get(move.dst_host)
        if not _live(dst_nh):
            raise MoveFailed(f"destination host {move.dst_host} not alive")
        added = False
        removing = False
        try:
            # -- add ----------------------------------------------------
            self._checkpoint(move, "add")
            self._member_goal(
                move, api, move.new_replica_id, present=True,
                request=lambda: api.sync_request_add_replica(
                    move.shard_id, move.new_replica_id, move.dst_host,
                    timeout=2.0,
                ),
            )
            added = True
            # a stale LOCAL replica of this shard on dst can only be the
            # leftover of an earlier killed move (the planner never picks
            # a dst already holding a member) — clear it so the fresh
            # join can start
            if move.shard_id in getattr(dst_nh, "_nodes", {}):
                try:
                    dst_nh.stop_shard(move.shard_id)
                    _log.warning(
                        "dst %s had a stale replica of shard %d; stopped it",
                        move.dst_host, move.shard_id,
                    )
                except Exception:  # noqa: BLE001 — raced its removal
                    pass
            # join seeded with the CURRENT membership (it includes the
            # replica just added): a snapshot-less catch-up replays a
            # log that never mentions the bootstrap members, so an
            # unseeded joiner would know no voters but itself — the
            # leadership-transfer leg would then split-brain (see
            # Node.__init__)
            cfg = self.config_factory(move.shard_id, move.new_replica_id)
            seed = dict(api.get_shard_membership(move.shard_id).addresses)
            dst_nh.start_replica(seed, True, self.sm_factory, cfg)
            # -- catchup ------------------------------------------------
            self._checkpoint(move, "catchup")
            self._wait_catchup(move, api, dst_nh)
            if move.kind == "replace":
                # -- transfer (only if the evictee leads, by FRESH
                # leader info — the view can be a whole move stale) ----
                lid, ok = api.get_leader_id(move.shard_id)
                leads = ok and lid != 0 and lid == move.src_replica_id
                if leads:
                    self._checkpoint(move, "transfer")
                    self._transfer(move, view, target=move.new_replica_id,
                                   api=api)
                # -- remove ---------------------------------------------
                self._checkpoint(move, "remove")
                removing = True
                self._member_goal(
                    move, api, move.src_replica_id, present=False,
                    request=lambda: api.sync_request_delete_replica(
                        move.shard_id, move.src_replica_id, timeout=2.0
                    ),
                )
                src_nh = self.hosts.get(move.src_host)
                if _live(src_nh):
                    try:
                        src_nh.stop_shard(move.shard_id)
                    except Exception:  # noqa: BLE001 — already gone
                        pass
        except Exception as e:  # noqa: BLE001 — any step error fails the move
            # a failure DURING the final remove rolls FORWARD, not back:
            # the new replica is caught up (and may already lead), so
            # removing it now could leave the shard short if the
            # evictee's delete commits late — the next pass just sees a
            # surplus draining member and retries the remove
            if not removing:
                self._rollback(move, view, added)
            if isinstance(e, MoveFailed):
                raise
            raise MoveFailed(
                f"{move.describe()} failed: {e!r} "
                f"({'remove retries next pass' if removing else 'rolled back'})"
            ) from e

    def _remove_only(self, move: Move, view: ClusterView) -> None:
        """Trim a surplus member (planner invariant 0: ghosts left by a
        killed move's failed rollback, or an over-replicated shard).
        No replica is added, so there is nothing to roll back."""
        self._checkpoint(move, "remove")
        api = self._api_host(move, view)
        try:
            self._member_goal(
                move, api, move.src_replica_id, present=False,
                request=lambda: api.sync_request_delete_replica(
                    move.shard_id, move.src_replica_id, timeout=2.0
                ),
            )
        except Exception as e:  # noqa: BLE001
            if isinstance(e, MoveFailed):
                raise
            raise MoveFailed(f"{move.describe()} failed: {e!r}") from e
        src_nh = self.hosts.get(move.src_host)
        if _live(src_nh):
            try:
                src_nh.stop_shard(move.shard_id)
            except Exception:  # noqa: BLE001 — already gone
                pass

    def _wait_catchup(self, move: Move, api, dst_nh) -> None:
        """Wait until the new replica's applied index reaches the
        shard's applied frontier (captured per poll; ``catchup_gap``
        relaxes the threshold for write-heavy shards that never quite
        close the last few entries).

        While polling, the leg samples the fleet's ``snapshot_stream_*``
        counters and surfaces TRANSFER progress — bytes moved, resume
        count, active streams, and an applied-rate ETA — in
        ``last_move_report["catchup"]`` plus rate-limited
        ``balance_move_step``/``catchup_progress`` events (ROADMAP 5b:
        the old leg was a blind applied-index poll; an operator
        watching a big-state catch-up saw nothing until it finished or
        timed out)."""
        deadline = time.monotonic() + self.catchup_timeout
        t0 = time.monotonic()
        base = self._stream_totals(self.hosts)
        first_got: Optional[int] = None
        last_emit = 0.0
        while True:
            target = self._applied(api, move.shard_id)
            got = self._applied(dst_nh, move.shard_id, move.new_replica_id)
            now = time.monotonic()
            if first_got is None and got >= 0:
                first_got = got
            done = (
                got >= 0 and target >= 0
                and got >= target - self.catchup_gap
            )
            # sample the stream counters and (re)build the report only
            # at the emit cadence (and on the terminal states): the
            # poll loop runs every 20 ms for legs that can take
            # minutes, and sampling every host's transport 50x/s to
            # feed a 2 Hz progress event is pure waste (review
            # finding) — between windows the loop stays the cheap
            # applied-index comparison it always was
            timed_out = now >= deadline
            if done or timed_out or now - last_emit >= self.progress_interval:
                totals = self._stream_totals(self.hosts)
                eta = None
                if first_got is not None and target > got > first_got:
                    rate = (got - first_got) / max(now - t0, 1e-6)
                    if rate > 0:
                        eta = (target - got) / rate
                report = {
                    "snapshot_stream_bytes": (
                        totals["bytes"] - base["bytes"]
                    ),
                    "snapshot_stream_resumes": (
                        totals["resumes"] - base["resumes"]
                    ),
                    "snapshot_stream_active": totals["active"],
                    "applied": got,
                    "target": target,
                    "eta_seconds": eta,
                }
                self.last_move_report["catchup"] = report
                last_emit = now
                self._event(
                    "balance_move_step", move, "catchup_progress",
                    detail=(
                        f"stream_bytes={report['snapshot_stream_bytes']} "
                        f"resumes={report['snapshot_stream_resumes']} "
                        f"active={report['snapshot_stream_active']} "
                        f"applied={got}/{target}"
                        + (f" eta={eta:.1f}s" if eta is not None else "")
                    ),
                )
            if done:
                return
            if timed_out:
                report = self.last_move_report.get("catchup", {})
                raise MoveFailed(
                    f"catchup timed out for {move.describe()}: "
                    f"applied {got} < target {target} - {self.catchup_gap} "
                    "(stream: "
                    f"{report.get('snapshot_stream_bytes', 0)} bytes, "
                    f"{report.get('snapshot_stream_resumes', 0)} resumes)"
                )
            time.sleep(0.02)

    def _leader_nh(self, move: Move, api):
        """The host handle currently holding the shard's LEADER replica.
        A leadership transfer must be requested ON the leader (a
        follower ignores it) — and the leader may well sit on the very
        host being drained, which _api_host deliberately avoids."""
        try:
            lid, ok = api.get_leader_id(move.shard_id)
            if ok and lid:
                m = api.get_shard_membership(move.shard_id)
                nh = self.hosts.get(m.addresses.get(lid, ""))
                if _live(nh):
                    return nh
        except Exception:  # noqa: BLE001 — mid-election; fall back
            pass
        return api

    def _transfer(self, move: Move, view: ClusterView, target: int,
                  api=None) -> None:
        api = api or self._api_host(move, view)
        deadline = time.monotonic() + self.step_timeout
        last_issue = -1.0
        while True:
            lid, ok = api.get_leader_id(move.shard_id)
            if ok and lid == target:
                return
            now = time.monotonic()
            if now - last_issue >= 0.25:  # don't hammer a slow handoff
                try:
                    self._leader_nh(move, api).request_leader_transfer(
                        move.shard_id, target
                    )
                except Exception:  # noqa: BLE001 — mid-election; retry
                    pass
                last_issue = now
            if now >= deadline:
                raise MoveFailed(
                    f"leadership transfer to {target} timed out for "
                    f"{move.describe()}"
                )
            time.sleep(0.05)

    def _rollback(self, move: Move, view: ClusterView, added: bool) -> None:
        """Best-effort restore of the pre-move membership: remove the
        replica this move added (the original replica was never removed
        — the remove step is last — so the shard keeps its factor)."""
        self._count("rollbacks_total", kind=move.kind)
        if not added:
            return
        try:
            api = self._api_host(move, view)
            self._member_goal(
                move, api, move.new_replica_id, present=False,
                request=lambda: api.sync_request_delete_replica(
                    move.shard_id, move.new_replica_id, timeout=2.0
                ),
            )
        except Exception:  # noqa: BLE001 — quorum may be gone; log and move on
            _log.warning("rollback: could not remove replica %d of shard %d",
                         move.new_replica_id, move.shard_id)
        dst_nh = self.hosts.get(move.dst_host)
        if _live(dst_nh):
            try:
                dst_nh.stop_shard(move.shard_id)
            except Exception:  # noqa: BLE001 — never started / already gone
                pass
        self._event("balance_move_rolled_back", move, "rollback")
