"""Shard placement & rebalancing control plane.

The missing policy layer above the NodeHost mechanisms: a collector
aggregating per-shard stats into a :class:`ClusterView`, a
deterministic seeded :class:`Planner` computing moves toward the
placement invariants (zero shards on draining hosts, replication
factor restored after host loss, replica and leader counts within ±1),
and a :class:`MoveExecutor` realizing each move as the safe
add -> catchup -> transfer -> remove sequence with rollback.
:class:`Balancer` is the public handle.  See docs/BALANCE.md.
"""
from .balancer import Balancer, DrainTimeout, HotTracker, LoadPolicy
from .executor import BalanceAborted, MoveExecutor, MoveFailed
from .planner import Move, MovePlan, Planner
from .view import ClusterView, Collector, ReplicaView, ShardLoad, ShardView

__all__ = [
    "Balancer",
    "DrainTimeout",
    "HotTracker",
    "LoadPolicy",
    "BalanceAborted",
    "MoveExecutor",
    "MoveFailed",
    "Move",
    "MovePlan",
    "Planner",
    "ClusterView",
    "Collector",
    "ReplicaView",
    "ShardLoad",
    "ShardView",
]
