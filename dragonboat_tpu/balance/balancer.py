"""Balancer: the public handle of the placement & rebalancing control
plane.

Owns the host map and the collect -> plan -> execute loop::

    b = Balancer(sm_factory, config_factory, hosts={"nh-1": nh1, ...},
                 replication_factor=3, seed=7)
    b.rebalance_once()       # one pass, returns a report
    b.run(interval=0.5)      # background loop
    b.join("nh-5", nh5)      # new host starts absorbing load
    b.drain("nh-2")          # blocks until nh-2 holds zero replicas
    b.stop()

Moves execute strictly in plan order on the balancer thread — one move
in flight at a time, so a failure (or a nemesis kill) leaves at most
one shard with a superfluous replica, which the executor's rollback
removes again.  Re-planning from a FRESH view each pass is what makes
the loop self-healing: whatever a crashed/killed pass left behind is
just another observed state the next plan converges from.
"""
from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, Optional

from ..events import EventFanout
from ..logger import get_logger
from .executor import MoveExecutor, MoveFailed
from .planner import MovePlan, Planner
from .view import ClusterView, Collector, ShardLoad

_log = get_logger("balance")


class DrainTimeout(Exception):
    """drain() did not converge within its deadline."""


@dataclass(frozen=True)
class LoadPolicy:
    """Thresholds + thrash guards for the load-feedback mode
    (docs/BALANCE.md "Load-reactive rebalancing").

    A shard is HOT in one window when its observed commit p99 crosses
    ``hot_p99_s`` (with at least ``min_samples`` samples backing the
    estimate — a two-sample p99 is noise) OR the gateway shed at least
    ``hot_shed`` requests for it this window.  ``hot_submit`` adds an
    absolute submit-delta trigger, disabled by default (0).  A hot
    reading only FIRES a move after ``hysteresis`` consecutive hot
    windows, and a fired shard then cools for ``cooldown`` windows —
    counted in PASSES, not wall time, per the determinism rule (the
    planner and faults planes ban wall clocks; the Balancer's pass
    cadence is the one legitimate clock here).  ``max_moves`` clamps
    each firing pass."""

    hot_p99_s: float = 0.25
    hot_shed: int = 8
    hot_submit: int = 0
    min_samples: int = 12
    hysteresis: int = 3
    cooldown: int = 6
    max_moves: int = 1

    def is_hot(self, row: ShardLoad) -> bool:
        if row.samples >= self.min_samples and (
                row.p99_ms >= int(self.hot_p99_s * 1000)):
            return True
        if self.hot_shed and row.shed >= self.hot_shed:
            return True
        if self.hot_submit and row.submitted >= self.hot_submit:
            return True
        return False


class HotTracker:
    """Pure hysteresis/cooldown state machine over per-pass hot sets
    (unit-tested in isolation — tests/test_balance.py).  ``observe``
    takes the shards hot THIS pass and returns the sorted subset whose
    hot streak just reached the hysteresis bar and that are not
    cooling; ``fired`` starts their cooldown."""

    def __init__(self, hysteresis: int = 3, cooldown: int = 6):
        self.hysteresis = max(1, hysteresis)
        self.cooldown = max(0, cooldown)
        self._streak: Dict[int, int] = {}
        self._cooling: Dict[int, int] = {}

    def observe(self, hot_now) -> list:
        hot_now = set(hot_now)
        for sid in list(self._streak):
            if sid not in hot_now:
                del self._streak[sid]
        fire = []
        for sid in sorted(hot_now):
            self._streak[sid] = self._streak.get(sid, 0) + 1
            if sid in self._cooling:
                continue
            if self._streak[sid] >= self.hysteresis:
                fire.append(sid)
        # cooldown ages at the END of the pass so cooldown=N suppresses
        # exactly N subsequent passes
        for sid in list(self._cooling):
            self._cooling[sid] -= 1
            if self._cooling[sid] <= 0:
                del self._cooling[sid]
        return fire

    def fired(self, shard_ids) -> None:
        for sid in shard_ids:
            self._cooling[sid] = self.cooldown
            self._streak.pop(sid, None)


class Balancer:
    def __init__(
        self,
        sm_factory: Callable,
        config_factory: Callable,
        *,
        hosts: Optional[Dict[str, object]] = None,
        replication_factor: int = 3,
        seed: int = 0,
        balance_replicas: bool = True,
        metrics=None,
        event_listener=None,
        step_timeout: float = 10.0,
        catchup_timeout: float = 30.0,
        catchup_gap: int = 0,
        alive: Optional[Callable] = None,
        load_policy: Optional[LoadPolicy] = None,
    ):
        self.hosts: Dict[str, object] = dict(hosts or {})
        self.seed = seed
        self._draining: set = set()
        self._lock = threading.RLock()
        # serializes whole passes: drain() may overlap the run() loop,
        # and two executors moving concurrently would race membership
        self._pass_lock = threading.Lock()
        if metrics is None:
            from ..metrics import MetricsRegistry

            metrics = MetricsRegistry(enabled=True)
        self.metrics = metrics
        self.events = (
            EventFanout(None, event_listener)
            if event_listener is not None else None
        )
        self.collector = Collector(alive=alive)
        self.planner = Planner(
            seed=seed,
            replication_factor=replication_factor,
            balance_replicas=balance_replicas,
        )
        # nemesis plug point (FaultController.install_balancer)
        self.fault_injector = None
        # load-feedback mode (docs/BALANCE.md "Load-reactive
        # rebalancing"): hysteresis state + the most recent pass report
        self.load_policy = load_policy or LoadPolicy()
        self._hot = HotTracker(
            hysteresis=self.load_policy.hysteresis,
            cooldown=self.load_policy.cooldown,
        )
        self._load_moves = self.metrics.counter("balance_load_moves_total")
        self.last_load_report: dict = {}
        # the most recent pass's final collect (see _rebalance_locked)
        self._last_view: Optional[ClusterView] = None
        # shard -> consecutive passes its membership showed an all-live
        # surplus; at _TRIM_LIVE_PASSES the planner may trim a live
        # member (an interrupted replace's roll-forward leftover)
        self._surplus_streak: Dict[int, int] = {}
        self.executor = MoveExecutor(
            self.hosts,
            sm_factory,
            config_factory,
            metrics=self.metrics,
            events=self.events,
            step_timeout=step_timeout,
            catchup_timeout=catchup_timeout,
            catchup_gap=catchup_gap,
        )
        self._run_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.metrics.gauge(
            "balance_hosts", lambda: len(self.hosts)
        )
        self.metrics.gauge(
            "balance_draining_hosts", lambda: len(self._draining)
        )

    @property
    def last_move_report(self) -> dict:
        """The executor's most recent move report (incl. the
        ``"catchup"`` snapshot-stream progress block) — surfaced so
        drain/rebalance drivers (the scenario orchestrator's region
        drain foremost) can put stream totals in their ledgers without
        reaching into the executor."""
        return self.executor.last_move_report

    # -- membership of the host fleet -----------------------------------
    def join(self, key: str, nh) -> None:
        """Register a (new or returning) host; subsequent passes spread
        replicas and leaders onto it."""
        with self._lock:
            self.hosts[key] = nh
            self._draining.discard(key)

    def remove_host(self, key: str) -> None:
        """Forget a host (after drain, or after it died — the repair
        invariant then restores its replicas elsewhere)."""
        with self._lock:
            self.hosts.pop(key, None)
            self._draining.discard(key)

    def mark_draining(self, key: str) -> None:
        with self._lock:
            self._draining.add(key)

    # -- the control loop ------------------------------------------------
    def view(self) -> ClusterView:
        with self._lock:
            hosts = dict(self.hosts)
            draining = set(self._draining)
        return self.collector.collect(hosts, draining)

    def plan(self) -> MovePlan:
        return self.planner.plan(self.view())

    def rebalance_once(self, max_moves: Optional[int] = None) -> dict:
        """One collect -> plan -> execute pass.  Executes the plan's
        moves in order, re-collecting the view after each membership
        move (the next move must see the world the previous one made).
        Whole passes are serialized (``drain`` may overlap the ``run``
        loop; two executors moving concurrently would race membership).
        ``max_moves`` caps how many of the planned moves execute this
        pass (the churn nemesis races exactly ONE move against its
        schedule; later passes converge the rest).  Returns
        ``{"planned": n, "executed": n, "failed": n}``."""
        with self._pass_lock:
            return self._rebalance_locked(max_moves)

    _TRIM_LIVE_PASSES = 3

    def _update_surplus_streaks(self, view: ClusterView) -> set:
        """Track shards whose ALL-LIVE surplus persists across passes;
        a one-view surplus can be a stale snapshot (remove committed
        but not applied at the reporter), a persistent one is a
        rolled-forward replace's leftover voter."""
        rf = self.planner.replication_factor
        seen = set()
        for s in view.shards:
            live_hosts = {r.host for r in s.replicas}
            if (len(s.members) > rf
                    and all(h in live_hosts for _, h in s.members)):
                seen.add(s.shard_id)
                self._surplus_streak[s.shard_id] = (
                    self._surplus_streak.get(s.shard_id, 0) + 1
                )
        for sid in list(self._surplus_streak):
            if sid not in seen:
                del self._surplus_streak[sid]
        return {
            sid for sid, n in self._surplus_streak.items()
            if n >= self._TRIM_LIVE_PASSES
        }

    def _rebalance_locked(self, max_moves: Optional[int] = None) -> dict:
        view = self.view()
        plan = self.planner.plan(view, self._update_surplus_streaks(view))
        self.metrics.gauge("balance_last_plan_size").set(len(plan))
        executed = failed = 0
        # propagate the nemesis plug point installed after construction
        self.executor.fault_injector = self.fault_injector
        for move in plan:
            if self._stop.is_set():
                break
            if max_moves is not None and executed + failed >= max_moves:
                break
            try:
                self.executor.execute(move, view)
                executed += 1
            except MoveFailed as e:
                failed += 1
                _log.warning("move failed: %s", e)
            view = self.view()
        # the pass's final view is fresh (re-collected after the last
        # move): expose it so drain() doesn't pay a third full collect
        # per pass just to re-learn what this loop already knows
        self._last_view = view
        return {"planned": len(plan), "executed": executed, "failed": failed}

    # -- load-feedback mode ---------------------------------------------
    def set_load_policy(self, policy: LoadPolicy) -> None:
        """Swap the load policy AND reset the hysteresis tracker (a
        policy change mid-streak would make stale streaks fire under
        thresholds they never saw)."""
        self.load_policy = policy
        self._hot = HotTracker(
            hysteresis=policy.hysteresis, cooldown=policy.cooldown
        )

    def attach_load_source(self, fn: Callable[[], Dict[int, dict]]) -> None:
        """Wire the serving plane's evidence (``Gateway.shard_load``)
        into the collector; subsequent views carry per-shard load rows
        and ``load_rebalance_once`` can react to them."""
        self.collector.load_source = fn

    def load_rebalance_once(self) -> dict:
        """One load-feedback pass: collect (with load rows), classify
        hot shards against the policy, advance the hysteresis tracker,
        and — only for shards whose hot streak reached the bar — plan a
        seeded ``spread_hot`` pass and execute it with the normal move
        discipline (one move at a time, fresh view after each,
        rollback in the executor).  Fired shards start their cooldown
        whether their move succeeded or not: hammering a shard whose
        move just failed is exactly the thrash the guard exists for."""
        with self._pass_lock:
            return self._load_rebalance_locked()

    def _load_rebalance_locked(self) -> dict:
        pol = self.load_policy
        view = self.view()
        hot_now = [l.shard_id for l in view.load if pol.is_hot(l)]
        fire = self._hot.observe(hot_now)
        report = {
            "hot": sorted(hot_now), "fired": list(fire),
            "planned": 0, "executed": 0, "failed": 0, "moves": [],
        }
        if fire:
            plan = self.planner.plan_spread_hot(
                view, fire, max_moves=pol.max_moves
            )
            report["planned"] = len(plan)
            self.executor.fault_injector = self.fault_injector
            for move in plan:
                if self._stop.is_set():
                    break
                try:
                    self.executor.execute(move, view)
                    report["executed"] += 1
                    self._load_moves.add()
                    report["moves"].append(move.describe())
                except MoveFailed as e:
                    report["failed"] += 1
                    _log.warning("load move failed: %s", e)
                view = self.view()
            self._last_view = view
            self._hot.fired([m.shard_id for m in plan])
        self.last_load_report = report
        return report

    def drain(self, key: str, *, timeout: float = 120.0,
              settle_passes: int = 1) -> dict:
        """Drain a host: mark it, then rebalance until it holds zero
        member replicas AND the plan is empty (leader counts settled
        within ±1), or raise :class:`DrainTimeout`.  Returns the final
        pass report plus convergence stats."""
        self.mark_draining(key)
        deadline = time.monotonic() + timeout
        passes = 0
        settled = 0
        last = {"planned": 0, "executed": 0, "failed": 0}
        while True:
            if time.monotonic() >= deadline:
                raise DrainTimeout(
                    f"drain({key!r}) did not converge within {timeout}s: "
                    f"{self.view().replicas_on(key)} replicas left"
                )
            report = self.rebalance_once()
            passes += 1
            last = report
            view = self._last_view  # the pass's own final collect
            targets = set(view.target_hosts())
            # full leader coverage on survivors is part of the fixed
            # point: an empty plan over a view with a mid-election
            # (leaderless) shard is a lucky snapshot, not convergence —
            # that shard's leader may land anywhere and unbalance ±1
            covered = all(
                s.leader_host and s.leader_host in targets
                for s in view.shards
            )
            if not (report["planned"] == 0 and view.replicas_on(key) == 0
                    and covered):
                settled = 0
                # pace the loop: an unconverged pass (mid-election
                # shard, remove not yet applied) should not busy-spin
                # full cluster collects back-to-back for the whole
                # timeout
                time.sleep(0.05)
                continue
            settled += 1
            # one extra empty pass confirms a fixed point, not a
            # lucky snapshot between a remove-commit and its stats
            if settled >= settle_passes:
                break
        last["passes"] = passes
        return last

    def run(self, interval: float = 0.5, *,
            load_feedback: bool = False) -> None:
        """Start the continuous rebalancing loop on a daemon thread.
        With ``load_feedback=True`` each pass also runs the
        load-reactive pass (requires an attached load source; a pass
        without load rows is a no-op)."""
        with self._lock:
            if self._run_thread is not None:
                raise RuntimeError("balancer already running")
            self._stop.clear()
            self._run_thread = threading.Thread(
                target=self._run_main, args=(interval, load_feedback),
                daemon=True, name="tpu-raft-balancer",
            )
            self._run_thread.start()

    def _run_main(self, interval: float, load_feedback: bool = False) -> None:
        while not self._stop.wait(interval):
            try:
                self.rebalance_once()
                if load_feedback:
                    self.load_rebalance_once()
            except Exception:  # noqa: BLE001 — the loop must survive a bad pass
                _log.exception("rebalance pass raised")

    def stop(self) -> None:
        self._stop.set()
        t = self._run_thread
        if t is not None:
            t.join(timeout=5.0)
            if t.is_alive():
                # a pass can legitimately outlive the join (catchup
                # deadlines run tens of seconds): leave _stop SET and
                # the handle in place so the loop exits at its next
                # check and a later stop() can reap it — clearing the
                # event here would revive the loop as an unstoppable
                # zombie (review finding)
                _log.warning(
                    "balancer loop still finishing a move; it will "
                    "stop at the next pass boundary"
                )
                return
            self._run_thread = None
        self._stop.clear()
        if self.events is not None:
            self.events.close()
