"""Named package loggers with per-package levels and a pluggable factory.

reference: logger/ (ILogger, GetLogger, SetLoggerFactory) [U].
"""
from __future__ import annotations

import logging
import sys
from typing import Callable, Dict, Optional

CRITICAL = logging.CRITICAL
ERROR = logging.ERROR
WARNING = logging.WARNING
INFO = logging.INFO
DEBUG = logging.DEBUG

_factory: Optional[Callable[[str], logging.Logger]] = None
_loggers: Dict[str, logging.Logger] = {}
_handler: Optional[logging.Handler] = None


def _default_handler() -> logging.Handler:
    global _handler
    if _handler is None:
        _handler = logging.StreamHandler(sys.stderr)
        _handler.setFormatter(
            logging.Formatter(
                "%(asctime)s %(levelname).1s | %(name)s: %(message)s",
                datefmt="%H:%M:%S",
            )
        )
    return _handler


def set_logger_factory(factory: Callable[[str], logging.Logger]) -> None:
    """Install a custom logger factory (reference: logger.SetLoggerFactory [U])."""
    global _factory
    _factory = factory
    _loggers.clear()


def get_logger(pkg: str) -> logging.Logger:
    """Get the named package logger ("raft", "rsm", "transport", "logdb",
    "nodehost", ...)."""
    if pkg not in _loggers:
        if _factory is not None:
            _loggers[pkg] = _factory(pkg)
        else:
            lg = logging.getLogger(f"dragonboat_tpu.{pkg}")
            if not lg.handlers:
                lg.addHandler(_default_handler())
                lg.propagate = False
            lg.setLevel(logging.WARNING)
            _loggers[pkg] = lg
    return _loggers[pkg]


def set_package_log_level(pkg: str, level: int) -> None:
    get_logger(pkg).setLevel(level)
