"""Package-level tunables.

reference: internal/settings/{soft,hard}.go [U].  ``Soft`` values may change
freely; ``Hard`` values are format invariants that must never change across
restarts of the same data dir.
"""
from __future__ import annotations

from dataclasses import dataclass


@dataclass
class _Soft:
    # engine
    step_engine_worker_count: int = 16
    commit_worker_count: int = 16
    apply_worker_count: int = 16
    snapshot_worker_count: int = 48
    # queues
    incoming_proposal_queue_length: int = 2048
    incoming_read_index_queue_length: int = 4096
    received_message_queue_length: int = 1024
    # batching
    in_mem_entry_slice_size: int = 512
    max_entry_batch_size: int = 64 * 1024 * 1024
    max_message_batch_size: int = 64 * 1024 * 1024
    step_batch_max_updates: int = 1024
    # raft
    max_entries_per_replicate: int = 64
    max_replicate_bytes: int = 2 * 1024 * 1024
    in_memory_gc_cycle: int = 4
    quiesce_threshold_ticks_multiplier: int = 10
    # snapshots
    snapshot_chunk_size: int = 2 * 1024 * 1024
    max_concurrent_streaming_snapshots: int = 128
    # bounded re-stream before a stream job reports failure (each report
    # resets the remote to WAIT and costs a leader round trip)
    snapshot_stream_max_tries: int = 3
    # transport
    send_queue_length: int = 1024 * 2
    connection_retry_ticks: int = 5
    # tpu step engine
    device_msg_capacity_per_group: int = 8
    device_out_capacity_per_group: int = 8
    device_log_window: int = 256
    device_max_peers: int = 8


@dataclass
class _Hard:
    logdb_entry_batch_size: int = 48
    max_entry_size: int = 64 * 1024 * 1024
    lru_max_session_count: int = 4096
    logdb_shards: int = 16
    snapshot_header_size: int = 1024


Soft = _Soft()
Hard = _Hard()
