"""Leader-routing cache: shard -> leader host, without per-call discovery.

The table is a plain dict REPLACED WHOLESALE on every write
(copy-on-write under ``_lock``); the read path grabs the current dict
in one attribute load and never takes a lock — the same snapshot-read
discipline as ``metrics.export_text`` (raftlint's ``gateway-hot`` rule
pins it: a ``# gateway-hot`` function must not acquire anything).
Correctness does not depend on freshness: a stale entry routes a
proposal to a follower, which FORWARDS it to the leader
(raft._step_follower), and a lease read on a non-leader simply fails
the ``lease_held`` gate and falls back to ReadIndex — the cache is a
latency optimization, invalidation keeps it from staying slow.

Fed two ways (docs/GATEWAY.md "Routing"):

* events: each registered host's ``EventFanout`` tap pushes
  ``leader_updated`` (the leader's own self-observation learns the
  route; a leaderless observation invalidates) and ``balance_move_*``
  (a move in flight means membership/leadership is about to change —
  drop the entry and rediscover);
* bulk: ``refresh_from_view`` consumes the balance plane's
  ``ClusterView.leader_map()`` snapshot.

On a miss, ``resolve`` falls back to one O(hosts) discovery sweep.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

from ..logger import get_logger

_log = get_logger("gateway")


class RoutingCache:
    """shard_id -> leader-host-key cache (see module docstring)."""

    def __init__(self, hosts: Callable[[], Dict[str, object]], metrics=None):
        # hosts: zero-arg callable returning the live key -> NodeHost
        # map (the gateway owns membership; re-read per discovery so
        # host churn is observed)
        self._hosts = hosts
        self._lock = threading.Lock()
        # the snapshot table: NEVER mutated in place — writers build a
        # fresh dict under _lock and swap the reference; readers load
        # self._table once and use it lock-free
        self._table: Dict[int, str] = {}
        # shard_id -> tuple of host keys carrying a replica (the read
        # plane's fan-out set, docs/READPLANE.md).  Same copy-on-write
        # discipline as _table.  Staleness is safe the same way the
        # leader table's is: a host that no longer carries the shard
        # fails the read (ShardNotFound), the router penalizes it and
        # the next refresh drops it — never a wrong VALUE, only a
        # wasted attempt.
        self._replicas: Dict[int, tuple] = {}
        nop = _Nop()
        self.hits = metrics.counter("gateway_route_hits_total") if metrics else nop
        self.misses = metrics.counter("gateway_route_misses_total") if metrics else nop
        self.invalidations = (
            metrics.counter("gateway_route_invalidations_total") if metrics else nop
        )

    # -- read path (hot) --------------------------------------------------
    def lookup(self, shard_id: int) -> Optional[str]:  # gateway-hot
        """Current route, or None.  NO locking: one dict load, one get."""
        host = self._table.get(shard_id)
        if host is not None:
            self.hits.add()
        return host

    def replicas(self, shard_id: int) -> tuple:  # gateway-hot
        """Known replica-host set, or ().  NO locking (see lookup)."""
        return self._replicas.get(shard_id, ())

    # -- write paths (cold: event-driven, not per-request) ---------------
    def learn(self, shard_id: int, host: str) -> None:
        with self._lock:
            t = dict(self._table)
            t[shard_id] = host
            self._table = t

    def learn_replicas(self, shard_id: int, hosts) -> None:
        with self._lock:
            r = dict(self._replicas)
            r[shard_id] = tuple(hosts)
            self._replicas = r

    def invalidate(self, shard_id: int) -> None:
        # leader route only: the replica set stays — followers still
        # serve reads through a leadership change (that's the point)
        with self._lock:
            if shard_id not in self._table:
                return
            t = dict(self._table)
            del t[shard_id]
            self._table = t
        self.invalidations.add()

    def invalidate_replicas(self, shard_id: int) -> None:
        with self._lock:
            if shard_id not in self._replicas:
                return
            r = dict(self._replicas)
            del r[shard_id]
            self._replicas = r

    def invalidate_all(self) -> None:
        with self._lock:
            n = len(self._table)
            self._table = {}
            self._replicas = {}
        if n:
            self.invalidations.add(n)

    def refresh_from_view(self, view) -> None:
        """Bulk refresh from a balance ``ClusterView``: leader_map for
        the proposal route, per-shard member hosts (intersected with
        the view's ALIVE hosts) for the read plane's replica sets.
        View entries WIN over cached ones — the collector's snapshot is
        newer than any event we might have missed; a shard's replica
        set is REPLACED wholesale so removed members drop out."""
        lm = view.leader_map()
        live = set(view.hosts)
        reps = {
            s.shard_id: tuple(h for h in s.member_hosts() if h in live)
            for s in view.shards
        }
        with self._lock:
            t = dict(self._table)
            t.update(lm)
            self._table = t
            r = dict(self._replicas)
            for sid, hs in reps.items():
                if hs:
                    r[sid] = hs
                else:
                    r.pop(sid, None)
            self._replicas = r

    # -- event tap (one closure per registered host) ----------------------
    def host_tap(self, host_key: str) -> Callable:
        """The ``EventFanout`` tap invalidating/learning routes from one
        host's events.  Runs synchronously on that host's posting
        thread: dict swaps only, nothing blocking."""

        def tap(name: str, args) -> None:
            if name == "leader_updated":
                info = args[0]
                if info.leader_id == 0:
                    # shard went leaderless as seen from this host —
                    # drop the route; proposals re-discover or forward
                    self.invalidate(info.shard_id)
                elif info.leader_id == info.replica_id:
                    # this host's own replica became leader: the one
                    # observation that maps leader REPLICA to host
                    self.learn(info.shard_id, host_key)
                # a follower learning some other leader is ignored: it
                # cannot map replica->host, and the leader's own event
                # carries the authoritative route
            elif name.startswith("balance_move_"):
                info = args[0] if args else None
                sid = getattr(info, "shard_id", None)
                if sid is not None:
                    self.invalidate(sid)
                    # membership is about to change: rediscover the
                    # replica set rather than read from a leaver
                    self.invalidate_replicas(sid)

        return tap

    # -- discovery fallback ------------------------------------------------
    def resolve(self, shard_id: int) -> Optional[str]:
        """Route with one discovery sweep on miss: ask every live host
        for its leader view of the shard; the host whose OWN replica id
        equals the leader id is the leader host.  Learned routes stick
        until invalidated."""
        host = self.lookup(shard_id)
        if host is not None:
            return host
        self.misses.add()
        hosts = self._hosts()
        for key, nh in sorted(hosts.items()):
            if getattr(nh, "_closed", False):
                continue
            try:
                if nh.is_leader_of(shard_id):
                    self.learn(shard_id, key)
                    return key
            except Exception:  # noqa: BLE001 — host closing mid-sweep
                continue
        return None

    def resolve_replicas(self, shard_id: int) -> tuple:
        """Replica-host set with one discovery sweep on miss: every
        live host that carries the shard (``_get_node`` answers) is a
        serving replica.  Works for in-proc hosts and remote handles
        alike (the remote probes its cached STATS rows).  Learned sets
        stick until a balance move or a view refresh replaces them."""
        reps = self.replicas(shard_id)
        if reps:
            return reps
        self.misses.add()
        found = []
        for key, nh in sorted(self._hosts().items()):
            if getattr(nh, "_closed", False):
                continue
            try:
                nh._get_node(shard_id)
                found.append(key)
            except Exception:  # noqa: BLE001 — shard not on this host
                continue
        if found:
            self.learn_replicas(shard_id, found)
        return tuple(found)

    def table(self) -> Dict[int, str]:
        """Snapshot for observability/tests."""
        return dict(self._table)

    def replica_table(self) -> Dict[int, tuple]:
        """Snapshot for observability/tests."""
        return dict(self._replicas)


class _Nop:
    __slots__ = ()

    def add(self, n: int = 1) -> None: ...
