"""Admission control + deadline-aware shedding for the gateway.

Two gates run BEFORE a proposal is queued (docs/GATEWAY.md "Shedding
policy"):

* **bounded queue per shard** — ``depth[shard]`` counts ops admitted
  but not yet completed; at ``max_queue_per_shard`` new ops shed with
  reason ``queue_full``.  Rejecting at the door BOUNDS the in-gateway
  wait inside every admitted request's latency — the p99 the budget
  observes (admission to completion) stays within a queue-depth factor
  of the raft path's p99 instead of growing without bound, which is
  what keeps the deadline gate below meaningful under overload;
* **deadline feasibility** — ``LatencyBudget.can_meet``: an op whose
  remaining deadline is under the observed p99 commit latency (scaled
  by the queue ahead of it) cannot make it; shed with reason
  ``deadline`` now rather than time out after consuming a slot.

Every shed increments ``gateway_shed_total{reason=...}``.  Sustained
shedding — more than ``dump_threshold`` sheds inside a sliding
``dump_window``-second window — fires the ``dump_cb`` at most once per
``dump_cooldown`` (the gateway wires it to the flight-recorder merged
timeline, so the moment the front door starts refusing work there is a
cross-host record of why).

Depth accounting is a plain per-shard int mutated under ``_lock`` on
admit/complete (cold-ish: two short acquisitions per op, never held
across any wait).  The shed probe itself reads the depth once —
the hot READ path of the routing cache stays lock-free; admission is
where the one lock of the gateway front door lives, by design.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, Optional

from ..logger import get_logger

_log = get_logger("gateway")


class AdmissionController:
    def __init__(
        self,
        budget,
        *,
        max_queue_per_shard: int = 256,
        batch_hint: int = 64,
        dump_threshold: int = 50,
        dump_window: float = 5.0,
        dump_cooldown: float = 30.0,
        dump_cb: Optional[Callable[[str], None]] = None,
        metrics=None,
    ):
        self.budget = budget  # client.LatencyBudget (shared with gateway)
        self.max_queue_per_shard = max_queue_per_shard
        self.batch_hint = batch_hint
        self._lock = threading.Lock()
        self._depth: Dict[int, int] = {}  # guarded-by: _lock
        self._metrics = metrics
        self._shed_counters: Dict[str, object] = {}  # guarded-by: _lock
        self.shed_total = 0  # guarded-by: _lock
        # sustained-shed detection: ring of recent shed timestamps
        self._shed_times: deque = deque(maxlen=max(dump_threshold, 1))  # guarded-by: _lock
        self.dump_threshold = dump_threshold
        self.dump_window = dump_window
        self.dump_cooldown = dump_cooldown
        self.dump_cb = dump_cb
        self._last_dump = 0.0  # guarded-by: _lock
        self.dumps = 0  # guarded-by: _lock

    # -- depth accounting -------------------------------------------------
    def depth(self, shard_id: int) -> int:
        # raftlint: ignore[guarded-by] lock-free scrape-time snapshot
        return self._depth.get(shard_id, 0)

    def _shed(self, shard_id: int, reason: str) -> str:
        """Account one shed.  All shed-side state mutates under _lock
        (concurrent client threads shed simultaneously — unlocked
        read-modify-writes lost counts and double-fired dumps; review
        finding); the expensive dump callback runs OUTSIDE it."""
        now = time.monotonic()
        fire_dump = False
        with self._lock:
            self.shed_total += 1
            c = self._shed_counters.get(reason)
            if c is None and self._metrics is not None:
                c = self._metrics.counter(
                    "gateway_shed_total", {"reason": reason}
                )
                self._shed_counters[reason] = c
            if c is not None:
                c.add()
            self._shed_times.append(now)
            if (
                self.dump_cb is not None
                and len(self._shed_times) >= self.dump_threshold
                and now - self._shed_times[0] <= self.dump_window
                and now - self._last_dump >= self.dump_cooldown
            ):
                self._last_dump = now
                self.dumps += 1
                fire_dump = True
        if fire_dump:
            self._fire_dump(shard_id, reason)
        return reason

    def admit(self, shard_id: int, deadline: float) -> Optional[str]:
        """Admit or shed one proposal aimed at ``shard_id`` with an
        absolute ``time.monotonic()`` ``deadline``.  Returns None on
        admit (depth charged; caller MUST pair with :meth:`complete`)
        or the shed reason string."""
        now = time.monotonic()
        remaining = deadline - now
        if remaining <= 0:
            return self._shed(shard_id, "deadline")
        with self._lock:
            d = self._depth.get(shard_id, 0)
            if d >= self.max_queue_per_shard:
                queue_full = True
            else:
                queue_full = False
                if self.budget.can_meet(
                    remaining, queued_ahead=d, batch_hint=self.batch_hint
                ):
                    self._depth[shard_id] = d + 1
                    return None
        if queue_full:
            return self._shed(shard_id, "queue_full")
        return self._shed(shard_id, "deadline")

    def complete(self, shard_id: int) -> None:
        """Release one admitted op's depth charge (every completion
        path: applied, failed, timed out)."""
        with self._lock:
            d = self._depth.get(shard_id, 0)
            if d <= 1:
                self._depth.pop(shard_id, None)
            else:
                self._depth[shard_id] = d - 1

    # -- sustained-shed auto-dump -----------------------------------------
    def _fire_dump(self, shard_id: int, reason: str) -> None:
        try:
            self.dump_cb(
                f"sustained shedding: {self.dump_threshold}+ sheds "
                f"inside {self.dump_window:.1f}s (last: shard "
                f"{shard_id}, {reason})"
            )
        except Exception:  # noqa: BLE001 — the dump is evidence, not
            # a dependency; shedding must keep working without it
            _log.exception("shed dump callback raised")
