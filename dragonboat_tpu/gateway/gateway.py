"""The serving front plane: session multiplexing, batched submission,
leader routing, overload shedding, lease reads.

reference: dragonboat serves client traffic straight off NodeHost; the
missing production layer this module adds is the INGRESS story the
ROADMAP's item 4 describes — many lightweight client handles multiplexed
onto few raft-path submissions:

* :class:`ClientHandle` — a cheap per-client object wrapping one
  exactly-once ``client.Session`` (keyed into the replicated
  ``rsm/session.py`` SessionManager for dedupe).  Per-session ordering
  is STRUCTURAL: a handle has at most one proposal in flight; later
  proposals queue on the handle and are released by the completion of
  the previous one — exactly the series-id discipline the session
  registry requires.
* :class:`Gateway` — accepts handles' proposals, sheds at the door
  (``gateway/admission.py``), coalesces admitted ones into per-shard
  batches drained by a small worker pool, and submits each batch
  through the routed leader host's ``NodeHost.propose`` (one
  ``engine.notify`` wake per request, but the node-level proposal
  queue drains the whole batch into ONE raft append).  Reads take the
  CheckQuorum lease fast path (``NodeHost.try_lease_read``) and fall
  back to ReadIndex.

Retry discipline inside the worker: DROPPED (definitely not committed)
attempts are retried for every handle; timed-out (maybe committed)
attempts are retried ONLY on exactly-once handles, where the unchanged
series id makes the retry dedupe-safe (reference client semantics [U])
— noop handles surface the timeout instead, preserving at-most-once.
Once any attempt is maybe-committed, every terminal failure path burns
the series (``proposal_completed``) so the handle's NEXT op can never
be mistaken for a retry of the dead one.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, Optional

from ..client import LatencyBudget, Session
from ..logger import get_logger
from ..metrics import MetricsRegistry
from ..readplane import (
    BOUND_TICKS_DEFAULT,
    Consistency,
    PATH_BOUNDED,
    PATH_FOLLOWER,
    PATH_LEASE,
    PATH_READ_INDEX,
    READ_PATHS,
    ReadResult,
    ReadRouter,
    ReadUnsupported,
    STALENESS_TICK_BOUNDS,
    StaleBoundExceeded,
)
from ..request import RequestResultCode, ShardNotFound, SystemBusy
from .admission import AdmissionController
from .routing import RoutingCache

_log = get_logger("gateway")


class GatewayBusy(SystemBusy):
    """Shed at the gateway door (queue full / deadline infeasible).
    Subclasses SystemBusy so ``client.call_with_retry`` treats it as
    the transient it is."""


class GatewayClosed(RuntimeError):
    pass


class GatewayConfig:
    """Knobs for one Gateway (defaults suit the in-proc test fleets;
    see docs/GATEWAY.md for sizing guidance)."""

    def __init__(
        self,
        *,
        workers: int = 2,
        max_batch: int = 64,
        max_queue_per_shard: int = 256,
        default_timeout: float = 5.0,
        lease_margin_ticks: int = 2,
        shed_dump_threshold: int = 50,
        shed_dump_window: float = 5.0,
        shed_dump_cooldown: float = 30.0,
        budget: Optional[LatencyBudget] = None,
        cap_feedback: bool = True,
        cap_feedback_target_p99: float = 0.25,
        cap_feedback_interval: float = 1.0,
    ):
        if workers < 1:
            raise ValueError("workers must be >= 1")
        if max_batch < 1:
            raise ValueError("max_batch must be >= 1")
        self.workers = workers
        self.max_batch = max_batch
        self.max_queue_per_shard = max_queue_per_shard
        self.default_timeout = default_timeout
        self.lease_margin_ticks = lease_margin_ticks
        self.shed_dump_threshold = shed_dump_threshold
        self.shed_dump_window = shed_dump_window
        self.shed_dump_cooldown = shed_dump_cooldown
        self.budget = budget
        # snapshot-stream cap feedback (ROADMAP 5a): a NodeHost with a
        # gateway attached gets its `bigstate.pacing.CapFeedback` AIMD
        # loop fed from THIS gateway's LatencyBudget automatically —
        # the gateway observes every commit's latency anyway, which is
        # exactly the live signal the loop was missing.  cap_feedback=
        # False opts out (operators driving the cap by hand or from
        # their own control loop).
        self.cap_feedback = cap_feedback
        self.cap_feedback_target_p99 = cap_feedback_target_p99
        self.cap_feedback_interval = cap_feedback_interval


class _ShardLoadState:
    """Per-shard overload evidence (docs/BALANCE.md "Load-reactive
    rebalancing"): an observed-latency budget plus cumulative
    submit/shed counters, read by ``Gateway.shard_load`` and consumed
    by the balance Collector as window deltas.  The counters follow the
    read-path convention — lock-free-ish increments, nothing depends on
    them exactly — and the budget window is deliberately small (128)
    so a post-move latency picture flushes the storm's tail quickly."""

    __slots__ = ("budget", "submitted", "shed")

    def __init__(self):
        self.budget = LatencyBudget(bootstrap=0.25, floor=0.05, window=128)
        self.submitted = 0
        self.shed = 0


class GatewayFuture:
    """Completion future for one gateway proposal."""

    __slots__ = ("_event", "_result", "_exc")

    def __init__(self):
        self._event = threading.Event()
        self._result = None
        self._exc: Optional[BaseException] = None

    def _complete(self, result=None, exc: Optional[BaseException] = None):
        self._result = result
        self._exc = exc
        self._event.set()

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            from ..nodehost import TimeoutError_

            raise TimeoutError_("gateway future wait timed out")
        if self._exc is not None:
            raise self._exc
        return self._result


class _GwReq:
    __slots__ = ("handle", "cmd", "deadline", "future", "t_admit",
                 "ambiguous")

    def __init__(self, handle, cmd: bytes, deadline: float):
        self.handle = handle
        self.cmd = cmd
        self.deadline = deadline
        self.future = GatewayFuture()
        self.t_admit = time.monotonic()
        # True once ANY attempt of this op may have committed (a node-
        # side timeout, or termination with the outcome unobserved):
        # the series must then be burned on EVERY terminal path, not
        # just the final-code-TIMEOUT one — a later DROPPED attempt
        # does not un-commit the earlier ambiguous one (review
        # finding: reusing the series for the next op would let the
        # dedupe registry swallow it as a retry of this one)
        self.ambiguous = False


class ClientHandle:
    """One logical client: a Session plus its not-yet-released op FIFO.

    Cheap by design (a Session dataclass, a deque, one bool) — the
    multiplexing economics come from handles sharing the gateway's
    worker pool and per-shard lanes instead of each owning threads."""

    __slots__ = ("gateway", "session", "shard_id", "_lock", "_queue",
                 "_inflight", "closed")

    def __init__(self, gateway: "Gateway", session: Session):
        self.gateway = gateway
        self.session = session
        self.shard_id = session.shard_id
        self._lock = threading.Lock()
        self._queue: deque = deque()  # guarded-by: _lock
        self._inflight = False  # guarded-by: _lock
        self.closed = False

    def is_exactly_once(self) -> bool:
        return not self.session.is_noop()

    def propose(self, cmd: bytes, timeout: Optional[float] = None):
        """Queue one proposal; returns a :class:`GatewayFuture`.
        Sheds (GatewayBusy) at the door, never after queueing."""
        return self.gateway._submit(self, cmd, timeout)

    def sync_propose(self, cmd: bytes, timeout: Optional[float] = None):
        t = timeout if timeout is not None else self.gateway.config.default_timeout
        return self.propose(cmd, timeout=t).result(t + 1.0)

    def close(self, timeout: float = 2.0) -> None:
        self.gateway.close_handle(self, timeout=timeout)


class Gateway:
    """See module docstring.  ``hosts`` maps host key -> NodeHost (the
    same shape the balance Collector consumes); in-proc fleets pass the
    test harness's dict, a real deployment registers its single local
    host plus any co-located ones."""

    def __init__(
        self,
        hosts: Dict[str, object],
        config: Optional[GatewayConfig] = None,
        *,
        metrics: Optional[MetricsRegistry] = None,
    ):
        self.config = config or GatewayConfig()
        # copy-on-write (same discipline as RoutingCache._table and
        # EventFanout._taps): NEVER mutated in place — add/remove_host
        # build a fresh dict under _hosts_lock and swap the reference,
        # so the per-request paths (reads, proposal routing, shed
        # recording) read it in ONE attribute load with no lock and no
        # copy (review finding: a per-request locked dict copy
        # reintroduced exactly the per-request-mutex shape the
        # gateway-hot lint rule bans)
        self._hosts: Dict[str, object] = dict(hosts)
        self._hosts_lock = threading.Lock()
        self.metrics = metrics if metrics is not None else MetricsRegistry()
        self.budget = self.config.budget or LatencyBudget(
            bootstrap=0.25, floor=0.05
        )
        self.routes = RoutingCache(self._live_hosts, metrics=self.metrics)
        self.admission = AdmissionController(
            self.budget,
            max_queue_per_shard=self.config.max_queue_per_shard,
            batch_hint=self.config.max_batch,
            dump_threshold=self.config.shed_dump_threshold,
            dump_window=self.config.shed_dump_window,
            dump_cooldown=self.config.shed_dump_cooldown,
            dump_cb=self._shed_dump,
            metrics=self.metrics,
        )
        # completion counters mutate under _done_lock: tests and the
        # bench read them as exact deltas, and Counter.add is a GIL-
        # racy read-modify-write when several workers complete
        # concurrently (review finding).  The read-path counters
        # (lease/fallback/route) keep the project-wide lock-free-ish
        # metrics convention — nothing depends on them exactly.
        self._done_lock = threading.Lock()
        self._committed = self.metrics.counter("gateway_committed_total")  # guarded-by: _done_lock
        self._failed = self.metrics.counter("gateway_failed_total")  # guarded-by: _done_lock
        self._lease_reads = self.metrics.counter("gateway_lease_read_total")
        self._fallback_reads = self.metrics.counter(
            "gateway_read_fallback_total"
        )
        # read-plane counters (docs/READPLANE.md): one per served path
        # plus sheds; pre-resolved so the read path never takes the
        # registry lock (counter() locks on lookup)
        self._read_paths: Dict[str, int] = {p: 0 for p in READ_PATHS}
        self._read_paths["bounded_shed"] = 0
        self._read_counters = {
            p: self.metrics.counter("gateway_read_total", {"path": p})
            for p in self._read_paths
        }
        self.read_router = ReadRouter()
        # per-shard overload evidence for the elastic balance loop
        # (created lazily on first touch; the lock guards only dict
        # insertion — counter bumps are lock-free-ish by convention)
        self._shard_load: Dict[int, _ShardLoadState] = {}
        self._shard_load_lock = threading.Lock()
        self._staleness = self.metrics.histogram(
            "readplane_staleness_ticks", bounds=STALENESS_TICK_BOUNDS
        )
        self._latency = self.metrics.histogram("gateway_request_seconds")
        # per-shard submission lanes: shard -> deque of _GwReq released
        # by their handles; lanes are partitioned over workers by
        # shard_id so one shard's batch is always built by one worker
        self._lanes: Dict[int, deque] = {}
        self._lanes_lock = threading.Lock()
        self._closed = False
        self.last_shed_dump = ""
        # resolved once per host-set change, NOT per shed: the shed
        # path runs on client threads exactly when the gateway is
        # overloaded, so it must be one attribute load + one ring
        # append (review finding: a per-shed host-dict copy under
        # _hosts_lock concentrated contention on the overload path)
        self._shed_recorder = None
        self._taps = []  # (host, fn) pairs for detach on close
        # per-host snapshot-cap AIMD loops fed from self.budget (see
        # GatewayConfig.cap_feedback); guarded-by: _hosts_lock
        self._cap_loops: Dict[str, object] = {}
        self._cap_stop = threading.Event()
        self._cap_thread: Optional[threading.Thread] = None
        for key, nh in self._hosts.items():
            self._attach_host(key, nh)
        self._refresh_shed_recorder()
        if self.config.cap_feedback:
            self._cap_thread = threading.Thread(
                target=self._cap_feedback_main,
                daemon=True,
                name="tpu-gw-capfeedback",
            )
            self._cap_thread.start()
        self._wake_events = [
            threading.Event() for _ in range(self.config.workers)
        ]
        self._workers = [
            threading.Thread(
                target=self._worker_main,
                args=(i,),
                daemon=True,
                name=f"tpu-gw-worker-{i}",
            )
            for i in range(self.config.workers)
        ]
        for t in self._workers:
            t.start()

    # -- host membership ---------------------------------------------------
    def _live_hosts(self) -> Dict[str, object]:
        """Current host-map snapshot: one attribute load, lock-free
        (copy-on-write — treat as immutable, never mutate)."""
        return self._hosts

    def _attach_host(self, key: str, nh) -> None:
        tap = self.routes.host_tap(key)
        try:
            nh.add_event_tap(tap)
            self._taps.append((nh, tap))
        except Exception:  # noqa: BLE001 — a host without a fanout
            # (test double) still routes via discovery
            _log.exception("gateway: could not tap host %s", key)
        self._maybe_attach_cap_feedback(key, nh)

    def _maybe_attach_cap_feedback(self, key: str, nh) -> None:
        """Register the host for snapshot-cap feedback (ROADMAP 5a):
        the gateway's feedback thread wires any CONFIGURED stream cap
        (transport.snapshot_pacer) to a ``CapFeedback`` AIMD loop fed
        from ``self.budget``.  Binding is resolved PER TICK, not here:
        the operator's runtime knob (``set_snapshot_send_rate``) can
        create, retune or remove the bucket long after attach — a
        snapshot taken now would miss a late-configured cap, clamp a
        raised one back to a stale base, or keep ticking an orphaned
        bucket (review findings).  Hosts without a cap are left alone —
        the loop never INVENTS a cap the operator didn't configure."""
        if not self.config.cap_feedback:
            return
        if getattr(nh, "transport", None) is None:
            return
        with self._hosts_lock:
            self._cap_loops[key] = {"nh": nh, "fb": None}

    def _cap_feedback_main(self) -> None:
        from ..bigstate.pacing import CapFeedback  # stdlib-only module

        while not self._cap_stop.wait(self.config.cap_feedback_interval):
            samples_fn = getattr(self.budget, "samples", None)
            # no observed commits yet: p99() is returning the BOOTSTRAP
            # guess, not a measurement — keep binding/tracking loops
            # but make no rate adjustment.  An idle gateway must not
            # read a default 1s bootstrap as a degraded commit path and
            # shrink the operator's cap to the floor with zero load —
            # the exact big-state joiner-before-traffic window the cap
            # exists for (review finding).
            have_signal = not (callable(samples_fn) and samples_fn() == 0)
            with self._hosts_lock:
                loops = list(self._cap_loops.items())
            for key, ent in loops:
                try:
                    # each tick runs UNDER _hosts_lock with a membership
                    # re-check: remove_host/close pop the entry and then
                    # RESTORE the cap to base — a tick racing past that
                    # restore from a stale snapshot would re-shrink a
                    # cap nothing will ever grow back (review finding).
                    # The tick body is cheap (cached p99 + set_rate),
                    # and host add/remove is rare, so the lock hold is
                    # fine.
                    with self._hosts_lock:
                        if self._cap_loops.get(key) is not ent:
                            continue  # retired while we walked
                        tr = getattr(ent["nh"], "transport", None)
                        pacer = getattr(tr, "snapshot_pacer", None)
                        fb = ent["fb"]
                        if pacer is None:
                            # cap removed (set_snapshot_send_rate(0)):
                            # the loop retires, never ticks the orphan
                            ent["fb"] = None
                            continue
                        # the operator's configured base, re-read per
                        # tick so a runtime retune moves the ceiling too
                        base = float(
                            getattr(tr, "max_snapshot_send_rate", 0) or 0
                        )
                        if base <= 0:
                            ent["fb"] = None
                            continue
                        if fb is None or fb.bucket is not pacer:
                            fb = CapFeedback(
                                pacer,
                                base_rate=base,
                                target_p99=(
                                    self.config.cap_feedback_target_p99
                                ),
                                budget=self.budget,
                            )
                            ent["fb"] = fb
                        elif fb.base_rate != base:
                            fb.base_rate = base
                            fb.floor_rate = base / 16.0
                        if have_signal:
                            fb.tick()
                except Exception:  # noqa: BLE001 — one host's loop
                    # must not kill the others'
                    _log.exception("gateway: cap feedback tick failed")

    @staticmethod
    def _retire_cap_loop(ent) -> None:
        """Restore the host's cap to its configured base when the
        feedback stops owning it (remove_host / close): without this a
        cap shrunk by a transient latency spike would strand the host
        at the AIMD floor forever — nothing else would grow it back
        (review finding)."""
        fb = ent.get("fb")
        if fb is None:
            return
        tr = getattr(ent["nh"], "transport", None)
        if getattr(tr, "snapshot_pacer", None) is fb.bucket and (
            fb.bucket.rate != fb.base_rate
        ):
            try:
                fb.bucket.set_rate(fb.base_rate)
            except Exception:  # noqa: BLE001 — host mid-close
                pass

    def cap_feedback_stats(self) -> Dict[str, dict]:
        """Per-host cap-feedback observability: current rate vs base
        and the number of adjustments applied (hosts whose cap is
        unconfigured/removed have no live loop and are omitted)."""
        with self._hosts_lock:
            loops = dict(self._cap_loops)
        out = {}
        for key, ent in loops.items():
            fb = ent.get("fb")
            if fb is not None:
                out[key] = {
                    "rate": fb.bucket.rate,
                    "base_rate": fb.base_rate,
                    "adjustments": fb.adjustments,
                }
        return out

    def _refresh_shed_recorder(self) -> None:
        rec = None
        for _, nh in sorted(self._live_hosts().items()):
            r = getattr(nh, "recorder", None)
            if r is not None:
                rec = r
                break
        self._shed_recorder = rec

    def add_host(self, key: str, nh) -> None:
        with self._hosts_lock:
            t = dict(self._hosts)
            t[key] = nh
            self._hosts = t
        self._attach_host(key, nh)
        self._refresh_shed_recorder()

    def remove_host(self, key: str) -> None:
        with self._hosts_lock:
            t = dict(self._hosts)
            nh = t.pop(key, None)
            self._hosts = t
        if nh is None:
            return
        with self._hosts_lock:
            cap_ent = self._cap_loops.pop(key, None)
        if cap_ent is not None:
            self._retire_cap_loop(cap_ent)
        for pair in list(self._taps):
            if pair[0] is nh:
                try:
                    nh.remove_event_tap(pair[1])
                except Exception:  # noqa: BLE001 — host already closed
                    pass
                self._taps.remove(pair)
        self.routes.invalidate_all()
        self._refresh_shed_recorder()

    # -- session lifecycle -------------------------------------------------
    def connect(self, shard_id: int, timeout: float = 5.0) -> ClientHandle:
        """Register an exactly-once session through the routed leader
        host and wrap it in a handle (reference: SyncGetSession [U]).
        Retries the transient failures a still-electing shard emits
        until ``timeout`` (client.call_with_retry discipline)."""
        if self._closed:
            raise GatewayClosed("gateway closed")
        from ..client import call_with_retry

        deadline = time.monotonic() + timeout

        def register():
            nh = self._host_for(shard_id, any_ok=True)
            if nh is None:
                raise ShardNotFound(f"no live host for shard {shard_id}")
            per_try = max(0.2, min(2.0, deadline - time.monotonic()))
            return nh.sync_get_session(shard_id, timeout=per_try)

        session = call_with_retry(register, deadline=deadline)
        return ClientHandle(self, session)

    def noop_handle(self, shard_id: int) -> ClientHandle:
        """At-most-once handle (no dedupe; reference: NoOPSession [U])."""
        return ClientHandle(self, Session.noop(shard_id))

    def close_handle(self, handle: ClientHandle, timeout: float = 2.0) -> None:
        handle.closed = True
        if not handle.is_exactly_once():
            return
        nh = self._host_for(handle.shard_id, any_ok=True)
        if nh is None:
            return
        try:
            nh.sync_close_session(handle.session, timeout=timeout)
        except Exception:  # noqa: BLE001 — registry LRU will evict it
            pass

    # -- submission path -----------------------------------------------------
    def _submit(self, handle: ClientHandle, cmd: bytes,
                timeout: Optional[float]):
        if self._closed:
            raise GatewayClosed("gateway closed")
        if handle.closed:
            raise GatewayClosed("handle closed")
        t = timeout if timeout is not None else self.config.default_timeout
        deadline = time.monotonic() + t
        reason = self.admission.admit(handle.shard_id, deadline)
        if reason is not None:
            self._record_shed(handle.shard_id, reason)
            raise GatewayBusy(f"shed: {reason} (shard {handle.shard_id})")
        self._shard_load_state(handle.shard_id).submitted += 1
        req = _GwReq(handle, cmd, deadline)
        with handle._lock:
            if handle._inflight:
                handle._queue.append(req)
                return req.future
            handle._inflight = True
        self._enqueue(req)
        return req.future

    def _enqueue(self, req: _GwReq) -> None:
        sid = req.handle.shard_id
        with self._lanes_lock:
            # re-check closed UNDER the lanes lock: close() swaps the
            # lanes dict out under this lock and seals what it swapped —
            # a request landing in the fresh dict after the swap would
            # have no worker left to drain it and its caller would hang
            # (review finding)
            if not self._closed:
                lane = self._lanes.get(sid)
                if lane is None:
                    lane = self._lanes[sid] = deque()
                lane.append(req)
                sealed = False
            else:
                sealed = True
        if sealed:
            self._fail(req, GatewayClosed("gateway closed"))
            return
        self._wake_events[sid % self.config.workers].set()

    def _release_next(self, handle: ClientHandle) -> None:
        """Completion of a handle's in-flight op releases its next one
        (per-session ordering: the series id advanced only now).  After
        close, queued ops are sealed here in a loop — no worker will
        drain them and their callers must not hang."""
        while True:
            with handle._lock:
                if handle._queue:
                    nxt = handle._queue.popleft()
                else:
                    handle._inflight = False
                    return
            if not self._closed:
                self._enqueue(nxt)
                return
            with self._done_lock:
                self._failed.add()
            self.admission.complete(nxt.handle.shard_id)
            nxt.future._complete(exc=GatewayClosed("gateway closed"))

    # -- worker pool ---------------------------------------------------------
    def _my_lanes(self, idx: int):
        with self._lanes_lock:
            return [
                sid for sid in self._lanes
                if sid % self.config.workers == idx
            ]

    def _drain(self, sid: int, limit: int):
        out = []
        with self._lanes_lock:
            lane = self._lanes.get(sid)
            while lane and len(out) < limit:
                out.append(lane.popleft())
        return out

    def _worker_main(self, idx: int) -> None:
        """Drain-submit-poll loop.  Completions are POLLED, never
        blocked on: a shard that lost quorum must not head-of-line
        block the other shards mapped to this worker for its requests'
        whole deadlines (review finding) — its pending pairs just ride
        the ``pending`` list while every other lane keeps draining.
        The poll cadence (5ms with work in flight) bounds the added
        completion latency."""
        ev = self._wake_events[idx]
        pending = []  # (req, rs) submitted, awaiting completion
        while not self._closed:
            ev.wait(timeout=0.005 if pending else 0.05)
            ev.clear()
            for sid in self._my_lanes(idx):
                for req in self._drain(sid, self.config.max_batch):
                    rs = self._propose_once(req)
                    if rs is not None:
                        pending.append((req, rs))
            if pending:
                still = []
                for req, rs in pending:
                    nrs = self._poll_finish(req, rs)
                    if nrs is not None:
                        still.append((req, nrs))
                pending = still
        for req, _rs in pending:
            # submitted but unresolved at close: may still commit
            req.ambiguous = True
            self._fail(req, GatewayClosed("gateway closed"))

    def _host_for(self, shard_id: int, any_ok: bool = False):
        key = self.routes.resolve(shard_id)
        hosts = self._live_hosts()
        nh = hosts.get(key) if key is not None else None
        if nh is not None and not getattr(nh, "_closed", False):
            return nh
        if key is not None:
            self.routes.invalidate(shard_id)
        if not any_ok:
            return None
        # no known leader: any live host carrying the shard will do —
        # followers forward proposals, session ops and read_index alike
        for _, nh in sorted(hosts.items()):
            if getattr(nh, "_closed", False):
                continue
            try:
                nh._get_node(shard_id)
                return nh
            except Exception:  # noqa: BLE001 — shard not on this host
                continue
        return None

    def _propose_once(self, req: _GwReq):
        """One submission attempt; completes the future on terminal
        errors, returns the RequestState otherwise."""
        remaining = req.deadline - time.monotonic()
        if remaining <= 0:
            # expired while queued (e.g. behind a retrying predecessor
            # on its handle): fail BEFORE submission — a doomed submit
            # wastes a raft append and its inevitable timeout marks
            # the op ambiguous, burning a series for nothing (review
            # finding).  Nothing was proposed, so nothing is ambiguous.
            from ..nodehost import TimeoutError_

            self._fail(req, TimeoutError_("gateway deadline (pre-submit)"))
            return None
        nh = self._host_for(req.handle.shard_id, any_ok=True)
        if nh is None:
            self._fail(req, ShardNotFound(
                f"no live host for shard {req.handle.shard_id}"))
            return None
        try:
            return nh.propose(req.handle.session, req.cmd, remaining)
        except Exception as e:  # noqa: BLE001 — classified below
            self.routes.invalidate(req.handle.shard_id)
            self._fail(req, e)
            return None

    def _poll_finish(self, req: _GwReq, rs):
        """Non-blocking completion check for one submitted request.
        Returns None when the gateway future was completed (done,
        failed, or timed out), else the RequestState — possibly a NEW
        one after a dedupe-safe resubmission — to keep polling."""
        from ..nodehost import _CODE_ERRORS, TimeoutError_

        if not rs._event.is_set():
            # still pending node-side (the event is set LAST in
            # notify, after code/result — a set event is a complete,
            # readable outcome)
            if time.monotonic() < req.deadline:
                return rs
            # gateway deadline exhausted on an op that may still
            # commit: ambiguous (the _fail path burns the series —
            # audit-client discipline)
            req.ambiguous = True
            self._fail(req, TimeoutError_("gateway deadline"))
            return None
        code = rs.code
        if code == RequestResultCode.COMPLETED:
            lat = time.monotonic() - req.t_admit
            if req.handle.is_exactly_once():
                req.handle.session.proposal_completed()
            self.budget.observe(lat)
            self._shard_load_state(req.handle.shard_id).budget.observe(lat)
            with self._done_lock:
                self._latency.observe(lat)
                self._committed.add()
            self._done(req, result=rs.result)
            return None
        if code in (
            RequestResultCode.TIMEOUT,
            RequestResultCode.TERMINATED,
            RequestResultCode.ABORTED,
        ):
            # maybe-committed outcomes (the audit client's
            # _MAYBE_COMMITTED_ERRORS set): a timed-out entry may
            # commit later, and a TERMINATED one may already be
            # PERSISTED in the raft log — a shard restart replays and
            # applies it (review finding).  Ambiguity is forever for
            # this op — even if a LATER attempt ends DROPPED, an
            # earlier copy may still commit, so the terminal path must
            # burn the series.  DROPPED and REJECTED are definitive
            # no-effect outcomes and stay unambiguous.
            req.ambiguous = True
        # DROPPED (definitely not committed) retries for everyone.
        # TIMEOUT (maybe committed) retries ONLY for exactly-once
        # handles, whose unchanged series id lets the session registry
        # dedupe a double apply; resubmitting a maybe-committed noop
        # proposal would break noop_handle's at-most-once contract
        # (review finding).
        retryable = code == RequestResultCode.DROPPED or (
            code == RequestResultCode.TIMEOUT
            and req.handle.is_exactly_once()
        )
        if retryable and req.deadline - time.monotonic() > 0.01:
            # pacing comes from the node round trip + the poll cadence
            self.routes.invalidate(req.handle.shard_id)
            return self._propose_once(req)  # None => future completed
        err = _CODE_ERRORS.get(code, TimeoutError_)
        self._fail(req, err(code.name if code is not None else "unknown"))
        return None

    def _done(self, req: _GwReq, result) -> None:
        self.admission.complete(req.handle.shard_id)
        req.future._complete(result=result)
        self._release_next(req.handle)

    def _fail(self, req: _GwReq, exc: BaseException) -> None:
        if req.ambiguous and req.handle.is_exactly_once():
            # some attempt of this op may still commit: burn the
            # series exactly once so the handle's NEXT op can never be
            # taken for a retry of this one (review finding — a
            # terminal DROPPED after an ambiguous TIMEOUT previously
            # skipped the burn)
            req.ambiguous = False
            req.handle.session.proposal_completed()
        with self._done_lock:
            self._failed.add()
        self.admission.complete(req.handle.shard_id)
        req.future._complete(exc=exc)
        self._release_next(req.handle)

    # -- reads ---------------------------------------------------------------
    def read(self, shard_id: int, query, timeout: Optional[float] = None):
        """Linearizable read (value only; the pre-readplane surface).
        Fast path: the routed leader host serves it under its
        CheckQuorum lease, skipping the per-read ReadIndex quorum round
        trip; fallback: plain ``sync_read`` (ReadIndex) through any
        live host.  Safety: docs/GATEWAY.md."""
        return self.read_at(shard_id, query, timeout=timeout).value

    def read_at(
        self,
        shard_id: int,
        query,
        *,
        consistency: Consistency = Consistency.LINEARIZABLE,
        timeout: Optional[float] = None,
        bound_ticks: int = BOUND_TICKS_DEFAULT,
    ) -> ReadResult:
        """Consistency-routed read (docs/READPLANE.md).

        LINEARIZABLE goes to the routed leader (lease fast path,
        ReadIndex fallback); FOLLOWER_LINEARIZABLE and
        BOUNDED_STALENESS fan out over the shard's replica set, the
        serving replica picked by power-of-two-choices on observed
        per-replica p99 (``read_router``).  Returns the value with its
        provenance stamp; BOUNDED_STALENESS raises
        :class:`StaleBoundExceeded` when no replica can serve within
        ``bound_ticks``."""
        if self._closed:
            raise GatewayClosed("gateway closed")
        t = timeout if timeout is not None else self.config.default_timeout
        deadline = time.monotonic() + t
        if consistency == Consistency.FOLLOWER_LINEARIZABLE:
            return self._read_follower(shard_id, query, deadline)
        if consistency == Consistency.BOUNDED_STALENESS:
            return self._read_bounded(shard_id, query, deadline, bound_ticks)
        return self._read_linearizable(shard_id, query, deadline)

    def _count_read(self, path: str) -> None:
        # GIL-racy like the other read-path counters (nothing depends
        # on them exactly); the dict mirror feeds stats()/the ledger
        self._read_paths[path] += 1
        self._read_counters[path].add()

    def _read_event(self, shard_id: int, detail: str) -> None:
        """`read_path` flight-recorder lane: fallback transitions only
        (lease->read_index, follower->leader, bounded sheds) — the
        evidence trail for WHY a read took the path it took."""
        rec = self._shed_recorder  # one attribute load on the hot path
        if rec is not None:
            rec.record(shard_id, "read_path", detail)

    def _read_linearizable(self, shard_id: int, query,
                           deadline: float) -> ReadResult:
        key = self.routes.resolve(shard_id)
        if key is not None:
            nh = self._live_hosts().get(key)
            if nh is not None and not getattr(nh, "_closed", False):
                try:
                    ok, val = nh.try_lease_read(
                        shard_id, query,
                        margin_ticks=self.config.lease_margin_ticks,
                    )
                    if ok:
                        self._lease_reads.add()
                        self._count_read(PATH_LEASE)
                        return ReadResult(val, PATH_LEASE, host=key)
                except Exception:  # noqa: BLE001 — host/shard stopping:
                    # fall through to the quorum path
                    self.routes.invalidate(shard_id)
        # ReadIndex fallback, retried across hosts until the deadline
        self._fallback_reads.add()
        self._read_event(shard_id, "lease->read_index")
        last_exc: Optional[BaseException] = None
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                from ..nodehost import TimeoutError_

                raise last_exc or TimeoutError_("gateway read deadline")
            nh = self._host_for(shard_id, any_ok=True)
            if nh is None:
                time.sleep(0.02)
                continue
            try:
                val = nh.sync_read(shard_id, query, timeout=remaining)
                self._count_read(PATH_READ_INDEX)
                return ReadResult(val, PATH_READ_INDEX)
            except Exception as e:  # noqa: BLE001 — reads are
                # idempotent; retry through another route
                last_exc = e
                self.routes.invalidate(shard_id)
                time.sleep(0.02)

    def _pick_replica(self, shard_id: int, tried):
        """One p2c selection over the live, untried replica set.
        Returns (key, nh) or (None, None) when no candidate remains."""
        hosts = self._live_hosts()
        cands = [
            k for k in self.routes.resolve_replicas(shard_id)
            if k not in tried
            and not getattr(hosts.get(k), "_closed", True)
        ]
        key = self.read_router.pick(cands)
        if key is None:
            return None, None
        return key, hosts.get(key)

    def _read_follower(self, shard_id: int, query,
                       deadline: float) -> ReadResult:
        """FOLLOWER_LINEARIZABLE: any replica confirms via a ReadIndex
        round to the leader and serves from its local state machine.
        Failed replicas are penalized and excluded; an old server
        without the consistency byte degrades to a leader read."""
        last_exc: Optional[BaseException] = None
        tried: set = set()
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                from ..nodehost import TimeoutError_

                raise last_exc or TimeoutError_("gateway read deadline")
            key, nh = self._pick_replica(shard_id, tried)
            if nh is None:
                if not tried:
                    # no replica set known at all yet: rediscover
                    time.sleep(0.02)
                    self.routes.invalidate_replicas(shard_id)
                    continue
                tried.clear()  # every replica failed once: fresh round
                time.sleep(0.02)
                continue
            t0 = time.monotonic()
            try:
                val, applied = nh.follower_read(
                    shard_id, query, timeout=remaining
                )
                self.read_router.observe(key, time.monotonic() - t0)
                self._count_read(PATH_FOLLOWER)
                return ReadResult(val, PATH_FOLLOWER,
                                  applied_index=applied, host=key)
            except ReadUnsupported:
                # remote predates the consistency byte: leader read is
                # the compatible contract-preserving fallback
                self._read_event(shard_id,
                                 f"follower->leader: {key} unsupported")
                return self._read_linearizable(shard_id, query, deadline)
            except Exception as e:  # noqa: BLE001 — replica dark/
                # leaderless/mid-transfer: penalize and fan to the next
                self.read_router.penalize(key)
                tried.add(key)
                last_exc = e

    def _read_bounded(self, shard_id: int, query, deadline: float,
                      bound_ticks: int) -> ReadResult:
        """BOUNDED_STALENESS: a replica serves immediately from local
        state, stamped; replicas past the bound shed and the next is
        tried — when EVERY replica sheds, the caller gets
        StaleBoundExceeded (escalate the level or retry later)."""
        last_exc: Optional[BaseException] = None
        shed_exc: Optional[StaleBoundExceeded] = None
        tried: set = set()
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                from ..nodehost import TimeoutError_

                raise shed_exc or last_exc or TimeoutError_(
                    "gateway read deadline")
            key, nh = self._pick_replica(shard_id, tried)
            if nh is None:
                if shed_exc is not None:
                    # every live replica is past the bound: shed the
                    # read rather than spin the deadline down
                    raise shed_exc
                if not tried:
                    time.sleep(0.02)
                    self.routes.invalidate_replicas(shard_id)
                    continue
                tried.clear()
                time.sleep(0.02)
                continue
            t0 = time.monotonic()
            try:
                res = nh.bounded_read(shard_id, query,
                                      bound_ticks=bound_ticks)
                self.read_router.observe(key, time.monotonic() - t0)
                self._count_read(PATH_BOUNDED)
                self._staleness.observe(res.staleness_ticks)
                res.host = key
                return res
            except ReadUnsupported:
                self._read_event(shard_id,
                                 f"bounded->leader: {key} unsupported")
                return self._read_linearizable(shard_id, query, deadline)
            except StaleBoundExceeded as e:
                # not a latency fault — the replica is out of leader
                # contact; bias away AND record the shed evidence
                self._count_read("bounded_shed")
                self._read_event(
                    shard_id, f"bounded shed: {key}: {e}")
                self.read_router.penalize(key)
                tried.add(key)
                shed_exc = e
            except Exception as e:  # noqa: BLE001 — replica dark
                self.read_router.penalize(key)
                tried.add(key)
                last_exc = e

    # -- overload evidence -----------------------------------------------------
    def _shard_load_state(self, shard_id: int) -> _ShardLoadState:
        st = self._shard_load.get(shard_id)
        if st is None:
            with self._shard_load_lock:
                st = self._shard_load.setdefault(shard_id, _ShardLoadState())
        return st

    def shard_load(self) -> Dict[int, dict]:
        """Per-shard overload evidence for the elastic balance loop:
        observed commit p99 (seconds, this gateway's view), sample
        count, and CUMULATIVE submitted/shed counts — the Collector
        turns the cumulative counters into per-window deltas with the
        same first-sight baseline it uses for proposal rates."""
        out = {}
        for sid in sorted(self._shard_load):
            st = self._shard_load[sid]
            out[sid] = {
                "p99_s": st.budget.p99(),
                "samples": st.budget.samples(),
                "submitted": st.submitted,
                "shed": st.shed,
            }
        return out

    def _record_shed(self, shard_id: int, reason: str) -> None:
        self._shard_load_state(shard_id).shed += 1
        rec = self._shed_recorder  # one attribute load on the hot path
        if rec is not None:
            rec.record(shard_id, "gateway_shed", reason)

    def _shed_dump(self, why: str) -> None:
        """Sustained shedding: capture the merged cross-host timeline
        (the flight recorder's whole point — evidence at the moment the
        front door starts refusing work)."""
        from ..obs import format_timeline, merged_timeline

        hosts = list(self._live_hosts().values())
        recs = [h for h in (getattr(n, "recorder", None) for n in hosts)
                if h is not None]
        tracers = [t for t in (getattr(n, "tracer", None) for n in hosts)
                   if t is not None]
        dump = why
        if recs or tracers:
            try:
                dump = why + "\n" + format_timeline(
                    merged_timeline(recorders=recs, tracers=tracers)
                )
            except Exception:  # noqa: BLE001 — evidence best-effort
                pass
        self.last_shed_dump = dump
        _log.warning("gateway overload: %s", dump[:4000])

    # -- observability ----------------------------------------------------------
    def stats(self) -> dict:
        with self._done_lock:
            committed = self._committed.value
            failed = self._failed.value
        return {
            "committed": committed,
            "failed": failed,
            "shed": self.admission.shed_total,
            "shed_dumps": self.admission.dumps,
            "lease_reads": self._lease_reads.value,
            "read_fallbacks": self._fallback_reads.value,
            # per-consistency-path serve counts + the router's observed
            # per-replica p99 (the read plane's ledger row inputs)
            "read_paths": dict(self._read_paths),
            "read_p99_by_host": self.read_router.snapshot(),
            "route_table": self.routes.table(),
            "replica_table": self.routes.replica_table(),
            # the commit path's live latency picture, as the scenario
            # ledger samples it per phase (docs/SCENARIO.md): p99 is the
            # budget's sliding-window estimate (bootstrap until any
            # sample lands — see samples)
            "p99_s": self.budget.p99(),
            "budget_samples": self.budget.samples(),
        }

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._cap_stop.set()
        if self._cap_thread is not None:
            self._cap_thread.join(timeout=2.0)
        with self._hosts_lock:
            cap_loops, self._cap_loops = self._cap_loops, {}
        for ent in cap_loops.values():
            # hosts outlive the gateway: give them their configured
            # caps back (see _retire_cap_loop)
            self._retire_cap_loop(ent)
        for ev in self._wake_events:
            ev.set()
        for t in self._workers:
            t.join(timeout=2.0)
        for nh, tap in self._taps:
            try:
                nh.remove_event_tap(tap)
            except Exception:  # noqa: BLE001 — host already closed
                pass
        self._taps.clear()
        # seal everything still queued: no worker will drain it now
        with self._lanes_lock:
            lanes, self._lanes = self._lanes, {}
        for lane in lanes.values():
            for req in lane:
                self._fail(req, GatewayClosed("gateway closed"))
