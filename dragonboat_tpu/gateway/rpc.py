"""Networked NodeHost front door: RPC ingress over the TCP framing.

reference: the reference ships no RPC layer of its own — drummer's
nodehost-client talked to remote NodeHosts over a thin request/response
protocol beside the raft transport [U].  This module is that front
door for cross-PROCESS fleets (docs/GATEWAY.md "Networked ingress"):

* :class:`RpcServer` — a listener beside (not inside) a NodeHost's
  raft transport, speaking the same magic/kind/length/crc frames as
  ``transport/tcp.py`` with two new kinds (``KIND_RPC_REQ``/
  ``KIND_RPC_RESP``) and the same versioned-payload discipline.  It
  exposes propose / read (lease fast path, ReadIndex, stale) / session
  register+close / balance stats, bounded by a non-blocking admission
  semaphore — a full server sheds with ``RPC_ERR_BUSY`` instead of
  queueing.
* :class:`RemoteHostHandle` — the client side, duck-typing the
  in-process NodeHost surface the :class:`~.gateway.Gateway`
  multiplexes (``propose``/``try_lease_read``/``sync_read``/session
  ops/``balance_shard_stats``), so a Gateway routes over OS-process
  boundaries exactly like over in-proc hosts.  Degradation contract:
  a torn connection fails every pending op PROMPTLY — exactly-once
  proposals and reads as DROPPED (definitely-not-committed, the
  gateway's retryable outcome), already-sent noop proposals as TIMEOUT
  (maybe-committed; resubmitting would break at-most-once) — and a
  dark remote (breaker open) reports ``_closed`` so routing skips it
  and admission sheds before queueing.  No path blocks a gateway
  worker lane past its own deadline.
* :class:`RouteFeeder` — the gossip-backed routing loop: a
  ``balance.Collector`` over the gateway's (remote) hosts, liveness
  from ``GossipManager.alive_peers``, feeding
  ``RoutingCache.refresh_from_view`` and dropping routes to hosts the
  view no longer contains.  A multi-process fleet converges on leader
  changes with zero shared memory.
"""
from __future__ import annotations

import json
import socket
import struct
import threading
import time
from typing import Dict, Optional

from ..client import SERIES_ID_FIRST_PROPOSAL, Session
from ..logger import get_logger
from ..obs.fleetscope import ObsService, ObsUnsupported
from ..obs.trace import UNSAMPLED
from ..nodehost import (
    NodeHostClosed,
    RequestDropped,
    RequestRejected,
    RequestTerminated,
    TimeoutError_,
    _CODE_ERRORS,
)
from ..readplane import (
    BOUND_TICKS_DEFAULT,
    PATH_BOUNDED,
    ReadResult,
    ReadUnsupported,
    StaleBoundExceeded,
)
from ..request import (
    RequestError,
    RequestResultCode,
    ShardNotFound,
    SystemBusy,
)
from ..statemachine import Result
from ..transport.tcp import _read_frame, _write_frame, parse_address
from ..transport.transport import _OPEN, _Breaker
from ..transport.wire import (
    KIND_RPC_REQ,
    KIND_RPC_RESP,
    RPC_ERR,
    RPC_ERR_BUSY,
    RPC_ERR_DENIED,
    RPC_ERR_NO_LEASE,
    RPC_ERR_NOT_FOUND,
    RPC_ERR_STALE_BOUND,
    RPC_OBS_METRICS,
    RPC_OBS_RECORDER,
    RPC_OBS_SPANS,
    RPC_OP_FAULT,
    RPC_OP_OBS,
    RPC_OP_PROPOSE,
    RPC_OP_READ,
    RPC_OP_SESSION_CLOSE,
    RPC_OP_SESSION_OPEN,
    RPC_OP_STATS,
    RPC_READ_BOUNDED,
    RPC_READ_FOLLOWER,
    RPC_READ_INDEX,
    RPC_READ_LEASE,
    RPC_READ_STALE,
    RPC_STATS_READ_PATHS,
    RpcRequest,
    RpcResponse,
    WireError,
    decode_obs_query,
    decode_obs_reply,
    decode_rpc_request,
    decode_rpc_response,
    decode_rpc_stats,
    decode_rpc_value,
    encode_obs_query,
    encode_obs_reply,
    encode_rpc_request,
    encode_rpc_response,
    encode_rpc_stats,
    encode_rpc_value,
)

_log = get_logger("gateway")

_COMPLETED = int(RequestResultCode.COMPLETED)


class _WireCtx:
    """Trace context lifted off an RPC request frame — exactly the two
    fields ``NodeHost.propose``'s ``parent`` contract reads, so a
    gateway client's root span stitches into the server-side
    request→raft→apply spans."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: int, span_id: int):
        self.trace_id = trace_id
        self.span_id = span_id


class RpcLeaseNotHeld(RequestError):
    """Lease-only read on a host not holding the lease (fall back)."""


class RpcDenied(RequestError):
    """Operation disabled on this server (e.g. fault ops in prod)."""


def _err_name(code) -> str:
    try:
        return RequestResultCode(code).name
    except ValueError:
        return f"rpc-code-{code:#x}"


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------
class RpcServer:
    """One listening ingress for one NodeHost.

    Lifecycle mirrors TCPTransport: ``start()`` binds (port 0 rewrites
    ``listen_address``), one accept loop, one reader thread per client
    connection; request handling fans out to short-lived worker
    threads bounded by ``max_inflight`` — acquisition is NON-blocking,
    so overload answers ``RPC_ERR_BUSY`` immediately instead of
    building a queue the client's deadline can't see (the admission
    plane's shed-at-the-door policy, docs/GATEWAY.md).

    ``fault_controller``+``allow_fault_ops`` expose the nemesis plane
    to the multi-process scenario harness (``RPC_OP_FAULT`` activates /
    heals wire faults on THIS host's transport); production servers
    leave it off and the op answers ``RPC_ERR_DENIED``.
    """

    def __init__(
        self,
        nh,
        listen_address: str,
        *,
        fault_controller=None,
        allow_fault_ops: bool = False,
        enable_obs_ops: bool = True,
        max_inflight: int = 64,
        wait_grace: float = 0.25,
    ):
        self._nh = nh
        self.listen_address = listen_address
        self._fault = fault_controller
        self._allow_fault_ops = allow_fault_ops
        # enable_obs_ops=False simulates a pre-obs server binary:
        # RPC_OP_OBS falls through to "unknown op" and collectors mark
        # the process no-obs (the degrade matrix's testable hinge)
        self._enable_obs_ops = enable_obs_ops
        self._obs = ObsService(nh)
        self._sem = threading.Semaphore(max_inflight)
        # wait() a touch past the client's own deadline so the CLIENT
        # observes its timeout first and the reply (late TIMEOUT) is
        # dropped by its gone pending entry, not raced
        self._wait_grace = wait_grace
        self._listener: Optional[socket.socket] = None
        self._stop = threading.Event()
        self._threads = []
        self._conn_lock = threading.Lock()
        self._inbound = set()

    # -- lifecycle -------------------------------------------------------
    def start(self) -> None:
        host, port = parse_address(self.listen_address)
        ls = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        ls.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        ls.bind((host, port))
        ls.listen(128)
        ls.settimeout(0.2)
        self._listener = ls
        self.listen_address = f"{host}:{ls.getsockname()[1]}"
        t = threading.Thread(
            target=self._accept_main, daemon=True, name="tpu-rpc-accept"
        )
        t.start()
        self._threads.append(t)

    def close(self) -> None:
        self._stop.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        with self._conn_lock:
            socks = list(self._inbound)
            self._inbound.clear()
        for s in socks:
            try:
                s.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=1.0)

    # -- inbound ---------------------------------------------------------
    def _accept_main(self) -> None:
        while not self._stop.is_set():
            try:
                sock, _addr = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            with self._conn_lock:
                self._inbound.add(sock)
            t = threading.Thread(
                target=self._conn_main,
                args=(sock,),
                daemon=True,
                name="tpu-rpc-reader",
            )
            t.start()

    def _conn_main(self, sock) -> None:
        # one write lock per connection: replies from concurrent worker
        # threads interleave whole frames, never bytes
        wlock = threading.Lock()
        try:
            while not self._stop.is_set():
                frame = _read_frame(sock)
                if frame is None:
                    return
                kind, payload = frame
                if kind != KIND_RPC_REQ:
                    raise WireError(f"unexpected frame kind {kind}")
                q = decode_rpc_request(payload)
                if not self._sem.acquire(blocking=False):
                    # shed, don't queue: the client retries against its
                    # breaker/backoff, and a bounded server can't build
                    # an invisible latency queue
                    self._reply(sock, wlock, RpcResponse(
                        req_id=q.req_id, code=RPC_ERR_BUSY,
                        error="rpc server at max inflight",
                    ))
                    continue
                t = threading.Thread(
                    target=self._serve_one,
                    args=(sock, wlock, q),
                    daemon=True,
                    name="tpu-rpc-worker",
                )
                t.start()
        except (WireError, ValueError) as e:
            _log.warning("rpc: closing connection on bad frame: %s", e)
        except OSError:
            pass
        finally:
            with self._conn_lock:
                self._inbound.discard(sock)
            try:
                sock.close()
            except OSError:
                pass

    def _serve_one(self, sock, wlock, q: RpcRequest) -> None:
        try:
            p = self._handle(q)
        except Exception as e:  # noqa: BLE001 — reply, never kill the conn
            p = RpcResponse(req_id=q.req_id, code=RPC_ERR,
                            error=f"{type(e).__name__}: {e}")
        finally:
            self._sem.release()
        self._reply(sock, wlock, p)

    @staticmethod
    def _reply(sock, wlock, p: RpcResponse) -> None:
        buf = encode_rpc_response(p)
        try:
            with wlock:
                _write_frame(sock, KIND_RPC_RESP, buf)
        except OSError:
            # client gone; its side fails pending ops via teardown
            pass

    # -- dispatch --------------------------------------------------------
    def _handle(self, q: RpcRequest) -> RpcResponse:
        nh = self._nh
        timeout = max(0.05, q.timeout_ms / 1000.0)
        try:
            if q.op == RPC_OP_PROPOSE:
                s = Session(shard_id=q.shard_id, client_id=q.client_id,
                            series_id=q.series_id,
                            responded_to=q.responded_to)
                # trace context off the frame: the server-side propose
                # span continues the CLIENT's trace (cross-process
                # stitch); trace_id 0 = untraced request
                parent = (
                    _WireCtx(q.trace_id, q.span_id) if q.trace_id else None
                )
                rs = nh.propose(s, q.payload, timeout, parent=parent)
                # sliced wait: a NodeHost closed mid-flight leaves its
                # RequestStates permanently pending — detecting that
                # here turns a full client-timeout stall into a fast
                # NOT_FOUND (client maps it to retryable DROPPED)
                deadline = time.monotonic() + timeout + self._wait_grace
                while (not rs._event.is_set()
                       and time.monotonic() < deadline):
                    if getattr(nh, "_closed", False):
                        raise NodeHostClosed(
                            "nodehost closed while proposal pending")
                    rs._event.wait(0.05)
                code = rs.wait(0.001)
                resp = RpcResponse(req_id=q.req_id, code=int(code))
                if code == RequestResultCode.COMPLETED and rs.result is not None:
                    resp.value = int(getattr(rs.result, "value", 0) or 0)
                    resp.data = bytes(getattr(rs.result, "data", b"") or b"")
                return resp
            if q.op == RPC_OP_READ:
                return self._handle_read(q, timeout)
            if q.op == RPC_OP_SESSION_OPEN:
                s = nh.sync_get_session(q.shard_id, timeout=timeout)
                return RpcResponse(req_id=q.req_id, code=_COMPLETED,
                                   value=s.client_id)
            if q.op == RPC_OP_SESSION_CLOSE:
                s = Session(shard_id=q.shard_id, client_id=q.client_id,
                            series_id=q.series_id,
                            responded_to=q.responded_to)
                nh.sync_close_session(s, timeout=timeout)
                return RpcResponse(req_id=q.req_id, code=_COMPLETED)
            if q.op == RPC_OP_STATS:
                rp = None
                if q.flags & RPC_STATS_READ_PATHS:
                    fn = getattr(nh, "read_path_counts", None)
                    rp = fn() if callable(fn) else {}
                data = encode_rpc_stats(
                    getattr(nh, "nodehost_id", "") or "",
                    nh.raft_address(), nh.balance_shard_stats(),
                    read_paths=rp,
                )
                return RpcResponse(req_id=q.req_id, code=_COMPLETED,
                                   data=data)
            if q.op == RPC_OP_FAULT:
                if not self._allow_fault_ops or self._fault is None:
                    return RpcResponse(req_id=q.req_id, code=RPC_ERR_DENIED,
                                       error="fault ops disabled")
                return self._handle_fault(q)
            if q.op == RPC_OP_OBS and self._enable_obs_ops:
                return self._handle_obs(q)
            return RpcResponse(req_id=q.req_id, code=RPC_ERR,
                               error=f"unknown op {q.op}")
        except SystemBusy as e:
            return RpcResponse(req_id=q.req_id, code=RPC_ERR_BUSY,
                               error=str(e) or "busy")
        except (ShardNotFound, NodeHostClosed) as e:
            return RpcResponse(req_id=q.req_id, code=RPC_ERR_NOT_FOUND,
                               error=f"{type(e).__name__}: {e}")
        except TimeoutError_:
            return RpcResponse(req_id=q.req_id,
                               code=int(RequestResultCode.TIMEOUT))
        except RequestRejected:
            return RpcResponse(req_id=q.req_id,
                               code=int(RequestResultCode.REJECTED))
        except RequestDropped:
            return RpcResponse(req_id=q.req_id,
                               code=int(RequestResultCode.DROPPED))
        except RequestTerminated:
            return RpcResponse(req_id=q.req_id,
                               code=int(RequestResultCode.TERMINATED))

    def _handle_read(self, q: RpcRequest, timeout: float) -> RpcResponse:
        nh = self._nh
        query = decode_rpc_value(q.payload)
        if q.flags == RPC_READ_LEASE:
            ok, val = nh.try_lease_read(
                q.shard_id, query, margin_ticks=q.arg or 2
            )
            if not ok:
                return RpcResponse(req_id=q.req_id, code=RPC_ERR_NO_LEASE,
                                   error="lease not held")
        elif q.flags == RPC_READ_INDEX:
            val = nh.sync_read(q.shard_id, query, timeout=timeout)
        elif q.flags == RPC_READ_STALE:
            val = nh.stale_read(q.shard_id, query)
        elif q.flags == RPC_READ_FOLLOWER:
            # ReadIndex round via the leader, served from THIS host's
            # state machine; value = applied index (the stamp)
            val, applied = nh.follower_read(q.shard_id, query,
                                            timeout=timeout)
            return RpcResponse(req_id=q.req_id, code=_COMPLETED,
                               value=applied, data=encode_rpc_value(val))
        elif q.flags == RPC_READ_BOUNDED:
            try:
                res = nh.bounded_read(
                    q.shard_id, query,
                    bound_ticks=q.arg or BOUND_TICKS_DEFAULT,
                )
            except StaleBoundExceeded as e:
                return RpcResponse(req_id=q.req_id,
                                   code=RPC_ERR_STALE_BOUND,
                                   error=str(e) or "stale bound exceeded")
            # stamp rides value (applied) + a u32 staleness prefix on
            # data — binary, so bytes-typed SM values survive intact
            data = struct.pack("<I", res.staleness_ticks)
            data += encode_rpc_value(res.value)
            return RpcResponse(req_id=q.req_id, code=_COMPLETED,
                               value=res.applied_index, data=data)
        else:
            return RpcResponse(req_id=q.req_id, code=RPC_ERR,
                               error=f"unknown read mode {q.flags}")
        return RpcResponse(req_id=q.req_id, code=_COMPLETED,
                           data=encode_rpc_value(val))

    def _handle_obs(self, q: RpcRequest) -> RpcResponse:
        """Fleet-scope telemetry queries (``RPC_OP_OBS``, sub-kind in
        ``flags``).  The query's ``epoch`` is client-held bookkeeping
        (restart detection happens collector-side against the epoch in
        the reply) — the server only honors cursor+limit."""
        try:
            cursor, _epoch, limit = decode_obs_query(q.payload)
        except WireError as e:
            return RpcResponse(req_id=q.req_id, code=RPC_ERR,
                               error=f"bad obs query: {e}")
        if q.flags == RPC_OBS_METRICS:
            reply = self._obs.metrics_snapshot()
        elif q.flags == RPC_OBS_RECORDER:
            reply = self._obs.recorder_tail(cursor, limit=limit)
        elif q.flags == RPC_OBS_SPANS:
            reply = self._obs.trace_spans(cursor, limit=limit)
        else:
            return RpcResponse(req_id=q.req_id, code=RPC_ERR,
                               error=f"unknown obs kind {q.flags}")
        return RpcResponse(req_id=q.req_id, code=_COMPLETED,
                           data=encode_obs_reply(reply))

    def _handle_fault(self, q: RpcRequest) -> RpcResponse:
        from .. import faults as faults_mod

        try:
            spec = json.loads(q.payload.decode("utf-8"))
        except (ValueError, UnicodeDecodeError) as e:
            return RpcResponse(req_id=q.req_id, code=RPC_ERR,
                               error=f"bad fault spec: {e}")
        action = spec.get("action")
        if action == "heal_wire":
            self._fault.heal_wire()
        elif action == "heal_all":
            self._fault.heal_all()
        elif action == "activate":
            f = spec.get("fault") or {}
            try:
                fault = faults_mod.Fault(
                    kind=f["kind"],
                    at=0.0,
                    duration=float(f.get("duration", 0.0)),
                    targets=tuple(f.get("targets", ())),
                    p=float(f.get("p", 1.0)),
                    delay=float(f.get("delay", 0.05)),
                    both_ways=bool(f.get("both_ways", True)),
                )
            except (KeyError, TypeError, ValueError) as e:
                return RpcResponse(req_id=q.req_id, code=RPC_ERR,
                                   error=f"bad fault spec: {e}")
            self._fault.activate(fault)
        else:
            return RpcResponse(req_id=q.req_id, code=RPC_ERR,
                               error=f"unknown fault action {action!r}")
        return RpcResponse(req_id=q.req_id, code=_COMPLETED)


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------
class _RemoteCall:
    """RequestState-compatible completion for one in-flight RPC.

    Same discipline as request.RequestState: ``notify`` writes
    ``code``/``result`` BEFORE setting ``_event`` — a set event is a
    complete, readable outcome (the gateway's ``_poll_finish`` peeks
    ``_event.is_set()`` without any lock)."""

    __slots__ = ("req_id", "op", "noop", "sent", "expires", "code",
                 "result", "resp", "error", "span", "traced", "_event")

    def __init__(self, req_id: int, op: int, noop: bool, expires: float):
        self.req_id = req_id
        self.op = op
        self.noop = noop
        self.sent = False
        self.expires = expires
        self.code: Optional[RequestResultCode] = None
        self.result: Optional[Result] = None
        self.resp: Optional[RpcResponse] = None
        self.error = ""
        # client-side rpc span (ends in notify — the single completion
        # point); traced = this frame carried trace context on the wire
        self.span = None
        self.traced = False
        self._event = threading.Event()

    def notify(self, code: RequestResultCode, result=None, resp=None,
               error: str = "") -> None:
        self.code = code
        self.result = result
        self.resp = resp
        self.error = error
        self._event.set()
        sp = self.span
        if sp is not None:
            sp.end(
                "ok" if code == RequestResultCode.COMPLETED else code.name
            )

    def wait(self, timeout: float) -> RequestResultCode:
        if not self._event.wait(timeout):
            return RequestResultCode.TIMEOUT
        return self.code


class _RemoteConfig:
    """The one config field gateway/scenario helpers read off a host."""

    __slots__ = ("rtt_millisecond",)

    def __init__(self, rtt_millisecond: int):
        self.rtt_millisecond = rtt_millisecond


class RemoteHostHandle:
    """A NodeHost you can only reach over the wire.

    Duck-types the in-proc surface :class:`~.gateway.Gateway` and the
    balance Collector consume, over ONE long-lived RPC connection
    multiplexed by request id.  Shard placement / leadership questions
    (``_get_node``/``is_leader_of``/``get_leader_id``) answer from a
    briefly-cached STATS snapshot so routing sweeps don't issue one
    network round trip per shard per sweep.

    Failure semantics (docs/GATEWAY.md "Degradation matrix"):

    * breaker OPEN and still cooling → ``_closed`` is True (routing
      skips the host; ``propose`` raises SystemBusy = shed before
      queueing);
    * connect/send failure → breaker failure + every pending op fails
      NOW: DROPPED for reads, session ops and exactly-once proposals
      (definitely-not-committed → the gateway retries them), TIMEOUT
      for noop proposals already on the wire (maybe committed —
      at-most-once forbids resubmission);
    * a response that never comes → the caller's own bounded ``wait``
      returns TIMEOUT; an expiry sweep GCs the pending entry.
    """

    def __init__(
        self,
        address: str,
        *,
        connect_timeout: float = 1.0,
        rtt_millisecond: int = 20,
        stats_max_age: float = 0.25,
        stats_timeout: float = 1.0,
        lease_timeout: float = 0.5,
        propose_attempt_cap: float = 2.0,
        breaker: Optional[_Breaker] = None,
        tracer=None,
    ):
        self.address = address
        self.config = _RemoteConfig(rtt_millisecond)
        # attrs the gateway probes with getattr(): no recorder/
        # transport plane on a remote handle (cap feedback, shed dumps
        # and event taps stay host-side).  ``tracer`` is the CLIENT
        # process's tracer: propose starts an rpc:propose span whose
        # context rides the request frame — the server-side spans
        # continue it (the cross-process stitch).
        self.recorder = None
        self.tracer = tracer
        self.transport = None
        # trace degrade latch: old servers reject v1 frames by tearing
        # the connection; a teardown with traced frames in flight
        # before ANY traced exchange succeeded latches tracing off for
        # this address (retries go untraced = byte-identical v0)
        self._trace_confirmed = False
        self._trace_disabled = False
        self._connect_timeout = connect_timeout
        self._stats_max_age = stats_max_age
        self._stats_timeout = stats_timeout
        self._lease_timeout = lease_timeout
        self._propose_attempt_cap = propose_attempt_cap
        self._breaker = breaker if breaker is not None else _Breaker()
        self._lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._pending: Dict[int, _RemoteCall] = {}
        self._req_seq = 0
        self._closed_flag = False
        # stats snapshot (balance rows + remote identity + read paths)
        self._stats_rows = None
        self._stats_nhid = ""
        self._stats_raft = ""
        self._stats_read_paths: Dict[str, int] = {}
        self._stats_t = 0.0

    # -- liveness ---------------------------------------------------------
    @property
    def _closed(self) -> bool:  # gateway-hot
        """True when explicitly closed OR dark (breaker open, still
        cooling, no live connection).  Deliberately does NOT call
        ``_Breaker.ready()`` — that consumes the half-open probe; this
        is a pure state read so routing sweeps can poll it freely."""
        if self._closed_flag:
            return True
        b = self._breaker
        return (
            self._sock is None
            and b.state == _OPEN
            and (time.monotonic() - b.opened_at) < b._wait
        )

    @property
    def nodehost_id(self) -> str:
        """Remote NodeHostID (known after the first STATS exchange);
        the RouteFeeder's join key against gossip liveness."""
        return self._stats_nhid

    def close(self) -> None:
        with self._lock:
            self._closed_flag = True
            sock, self._sock = self._sock, None
            pending, self._pending = self._pending, {}
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
        for rc in pending.values():
            self._fail_rc(rc, "handle closed")

    # -- connection -------------------------------------------------------
    def _ensure_conn(self) -> socket.socket:
        with self._lock:
            if self._closed_flag:
                raise NodeHostClosed("remote handle closed")
            if self._sock is not None:
                return self._sock
            if not self._breaker.ready():
                raise SystemBusy(
                    f"remote {self.address} dark (breaker open)"
                )
        # connect OUTSIDE the lock: a slow remote must not block every
        # other caller of this handle for the connect timeout
        try:
            sock = socket.create_connection(
                parse_address(self.address), timeout=self._connect_timeout
            )
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            sock.settimeout(None)
        except OSError as e:
            self._breaker.failure()
            raise RequestDropped(f"connect {self.address}: {e}")
        with self._lock:
            if self._closed_flag:
                try:
                    sock.close()
                except OSError:
                    pass
                raise NodeHostClosed("remote handle closed")
            if self._sock is not None:
                # lost the race; ride the established connection
                try:
                    sock.close()
                except OSError:
                    pass
                return self._sock
            self._sock = sock
        self._breaker.success()
        t = threading.Thread(
            target=self._reader_main, args=(sock,),
            daemon=True, name="tpu-rpc-client-reader",
        )
        t.start()
        return sock

    def _teardown(self, sock, why: str) -> None:
        """Connection died: fail EVERY pending op now, per the
        degradation matrix — a worker lane polls completed state, it
        must never inherit a wedged socket's silence."""
        with self._lock:
            if self._sock is sock:
                self._sock = None
                pending, self._pending = self._pending, {}
            else:
                pending = {}
        try:
            sock.close()
        except OSError:
            pass
        if pending:
            _log.warning(
                "rpc %s: connection lost (%s); failing %d pending",
                self.address, why, len(pending),
            )
        if (
            not self._trace_confirmed
            and not self._trace_disabled
            and any(rc.traced for rc in pending.values())
        ):
            # an old server tears the connection on the first v1 frame
            # it sees — before any traced exchange has ever succeeded
            # that teardown is indistinguishable from "doesn't speak
            # v1", so degrade: this handle goes untraced from here on
            self._trace_disabled = True
            _log.warning(
                "rpc %s: tore connection on traced frame before any "
                "confirmation; disabling trace context (old server?)",
                self.address,
            )
        self._breaker.failure()
        for rc in pending.values():
            self._fail_rc(rc, why)

    def _fail_rc(self, rc: _RemoteCall, why: str) -> None:
        if rc.op == RPC_OP_PROPOSE and rc.noop and rc.sent:
            # a noop proposal already on the wire MAY have committed:
            # TIMEOUT keeps it ambiguous and non-retryable (at-most-once)
            rc.notify(RequestResultCode.TIMEOUT, error=why)
        else:
            rc.notify(RequestResultCode.DROPPED, error=why)

    # -- submit/complete plumbing ----------------------------------------
    def _submit(
        self,
        op: int,
        *,
        flags: int = 0,
        shard_id: int = 0,
        session: Optional[Session] = None,
        timeout: float = 1.0,
        arg: int = 0,
        payload: bytes = b"",
        span=None,
    ) -> _RemoteCall:
        timeout_ms = max(50, min(int(timeout * 1000.0), 0xFFFFFFFF))
        q = RpcRequest(
            op=op, flags=flags, shard_id=shard_id,
            client_id=session.client_id if session is not None else 0,
            series_id=session.series_id if session is not None else 0,
            responded_to=session.responded_to if session is not None else 0,
            timeout_ms=timeout_ms, arg=arg, payload=payload,
        )
        traced = span is not None and not self._trace_disabled
        if traced:
            q.trace_id = span.trace_id
            q.span_id = span.span_id
        buf_noop = session is None or session.is_noop()
        sock = self._ensure_conn()
        now = time.monotonic()
        with self._lock:
            if self._sock is not sock:
                raise RequestDropped("connection lost before send")
            self._req_seq += 1
            q.req_id = self._req_seq
            rc = _RemoteCall(q.req_id, op, buf_noop,
                             now + timeout_ms / 1000.0 + 5.0)
            rc.span = span
            rc.traced = traced
            self._pending[q.req_id] = rc
            expired = [
                p for p in self._pending.values()
                if p.expires < now and not p._event.is_set()
            ]
            for p in expired:
                del self._pending[p.req_id]
        for p in expired:
            # server never answered inside its grace: ambiguous
            p.notify(RequestResultCode.TIMEOUT, error="rpc expiry sweep")
        buf = encode_rpc_request(q)
        rc.sent = True
        try:
            with self._lock:
                if self._sock is not sock:
                    raise OSError("connection replaced")
                _write_frame(sock, KIND_RPC_REQ, buf)
        except OSError as e:
            self._teardown(sock, f"send: {e}")
            # rc was completed by the teardown sweep (matrix applied)
        return rc

    def _reader_main(self, sock) -> None:
        why = "eof"
        try:
            while True:
                frame = _read_frame(sock)
                if frame is None:
                    break
                kind, payload = frame
                if kind != KIND_RPC_RESP:
                    raise WireError(f"unexpected frame kind {kind}")
                p = decode_rpc_response(payload)
                with self._lock:
                    rc = self._pending.pop(p.req_id, None)
                if rc is not None:
                    self._complete(rc, p)
        except (WireError, ValueError) as e:
            why = f"bad frame: {e}"
        except OSError as e:
            why = f"recv: {e}"
        self._teardown(sock, why)

    def _complete(self, rc: _RemoteCall, p: RpcResponse) -> None:
        self._breaker.success()
        if rc.traced:
            # a traced frame got a reply: the server speaks v1, the
            # degrade latch can never fire for this handle again
            self._trace_confirmed = True
        if rc.op == RPC_OP_PROPOSE:
            if p.code <= int(RequestResultCode.COMMITTED):
                code = RequestResultCode(p.code)
                result = (
                    Result(p.value, p.data)
                    if code == RequestResultCode.COMPLETED else None
                )
                rc.notify(code, result=result, resp=p, error=p.error)
            else:
                # ingress-level outcomes (BUSY/NOT_FOUND/...) all mean
                # the proposal never reached a pending table: DROPPED
                # is the dedupe-safe, retryable mapping
                rc.notify(RequestResultCode.DROPPED, resp=p,
                          error=p.error or _err_name(p.code))
        else:
            code = (
                RequestResultCode(p.code)
                if p.code <= int(RequestResultCode.COMMITTED)
                else RequestResultCode.REJECTED
            )
            if code == RequestResultCode.COMPLETED:
                rc.notify(code, result=Result(p.value, p.data), resp=p)
            else:
                rc.notify(code, resp=p, error=p.error or _err_name(p.code))

    def _finish(self, rc: _RemoteCall, timeout: float):
        """Bounded wait + error mapping for the synchronous wrappers."""
        code = rc.wait(timeout)
        p = rc.resp
        if p is not None and p.code > int(RequestResultCode.COMMITTED):
            if p.code == RPC_ERR_BUSY:
                raise SystemBusy(p.error or "remote busy")
            if p.code == RPC_ERR_NOT_FOUND:
                raise ShardNotFound(p.error or "not on remote")
            if p.code == RPC_ERR_NO_LEASE:
                raise RpcLeaseNotHeld(p.error or "lease not held")
            if p.code == RPC_ERR_DENIED:
                raise RpcDenied(p.error or "denied")
            if p.code == RPC_ERR_STALE_BOUND:
                raise StaleBoundExceeded(p.error or "stale bound exceeded")
            if p.code == RPC_ERR and "unknown read mode" in p.error:
                # pre-readplane server: the caller degrades to a
                # leader read (docs/READPLANE.md "Version skew")
                raise ReadUnsupported(p.error)
            raise RequestError(p.error or _err_name(p.code))
        if code == RequestResultCode.COMPLETED:
            return rc.result
        raise _CODE_ERRORS.get(code, RequestError)(
            rc.error or _err_name(code)
        )

    # -- NodeHost surface (what the Gateway multiplexes) ------------------
    def propose(self, session: Session, cmd: bytes, timeout: float,
                parent=None) -> _RemoteCall:
        if not session.is_noop():
            # per-ATTEMPT bound, not per-op: an exactly-once proposal
            # that lands on a follower right as the leader dies is
            # forwarded into the void and its RequestState pends until
            # the server-side wait expires — letting one attempt carry
            # the caller's whole budget wedges the gateway lane for
            # exactly the window a kill needs retries.  TIMEOUT at the
            # cap is retryable for exactly-once sessions (the series
            # dedupes); noop proposals are never retried, so their one
            # attempt keeps the caller's full timeout.
            timeout = min(timeout, self._propose_attempt_cap)
        # root span for the wire hop: its context rides the request
        # frame, so the server-side request→raft→apply spans stitch
        # into the SAME trace.  parent=None roots a new trace here;
        # a caller-held parent is continued; UNSAMPLED propagates the
        # root's no (same contract as NodeHost.propose).
        span = None
        tracer = self.tracer
        if tracer is not None and not self._trace_disabled:
            if parent is None:
                span = tracer.start_trace("rpc:propose", session.shard_id)
            elif parent is not UNSAMPLED:
                span = tracer.start_span(
                    "rpc:propose", parent.trace_id, parent.span_id,
                    session.shard_id,
                )
        try:
            return self._submit(
                RPC_OP_PROPOSE, shard_id=session.shard_id, session=session,
                timeout=timeout, payload=cmd, span=span,
            )
        except (RequestDropped, SystemBusy, OSError) as e:
            # unreachable OR breaker-dark remote: complete as DROPPED
            # instead of raising — the gateway's _propose_once treats
            # raised errors as TERMINAL, but DROPPED is retryable
            # through other hosts
            rc = _RemoteCall(0, RPC_OP_PROPOSE, session.is_noop(), 0.0)
            rc.span = span
            rc.notify(RequestResultCode.DROPPED, error=str(e))
            return rc

    def sync_propose(self, session: Session, cmd: bytes,
                     timeout: float = 5.0, parent=None):
        # parent mirrors NodeHost.sync_propose: a tracer-holding handle
        # is a drop-in nodehost for propose_with_retry, whose root span
        # arrives here and parents the rpc:propose wire hop
        rc = self.propose(session, cmd, timeout, parent=parent)
        return self._finish(rc, timeout + 0.5)

    def try_lease_read(self, shard_id: int, query, margin_ticks: int = 2):
        if self._closed:
            return False, None
        try:
            rc = self._submit(
                RPC_OP_READ, flags=RPC_READ_LEASE, shard_id=shard_id,
                timeout=self._lease_timeout, arg=margin_ticks,
                payload=encode_rpc_value(query),
            )
        except (RequestError, OSError):
            return False, None
        if rc.wait(self._lease_timeout + 0.25) != RequestResultCode.COMPLETED:
            return False, None
        return True, decode_rpc_value(rc.result.data)

    def sync_read(self, shard_id: int, query, timeout: float = 5.0):
        rc = self._submit(
            RPC_OP_READ, flags=RPC_READ_INDEX, shard_id=shard_id,
            timeout=timeout, payload=encode_rpc_value(query),
        )
        result = self._finish(rc, timeout + 0.5)
        return decode_rpc_value(result.data)

    def stale_read(self, shard_id: int, query):
        rc = self._submit(
            RPC_OP_READ, flags=RPC_READ_STALE, shard_id=shard_id,
            timeout=self._stats_timeout, payload=encode_rpc_value(query),
        )
        result = self._finish(rc, self._stats_timeout + 0.5)
        return decode_rpc_value(result.data)

    def follower_read(self, shard_id: int, query, timeout: float = 5.0):
        """(value, applied_index) served from the REMOTE host's state
        machine after its ReadIndex round — the NodeHost.follower_read
        surface over the wire.  Raises ReadUnsupported against a
        pre-readplane server (caller degrades to a leader read)."""
        rc = self._submit(
            RPC_OP_READ, flags=RPC_READ_FOLLOWER, shard_id=shard_id,
            timeout=timeout, payload=encode_rpc_value(query),
        )
        result = self._finish(rc, timeout + 0.5)
        return decode_rpc_value(result.data), result.value

    def bounded_read(self, shard_id: int, query,
                     bound_ticks: int = BOUND_TICKS_DEFAULT) -> ReadResult:
        """Bounded-staleness read off the remote's local state; the
        stamp rides value (applied) + a u32 staleness prefix on data.
        Raises StaleBoundExceeded on a shed, ReadUnsupported against a
        pre-readplane server."""
        rc = self._submit(
            RPC_OP_READ, flags=RPC_READ_BOUNDED, shard_id=shard_id,
            timeout=self._stats_timeout, arg=bound_ticks,
            payload=encode_rpc_value(query),
        )
        result = self._finish(rc, self._stats_timeout + 0.5)
        if len(result.data) < 4:
            raise RequestError("bounded read: short stamp")
        (staleness,) = struct.unpack_from("<I", result.data, 0)
        return ReadResult(
            decode_rpc_value(result.data[4:]), PATH_BOUNDED,
            applied_index=result.value, staleness_ticks=staleness,
        )

    def get_noop_session(self, shard_id: int) -> Session:
        return Session.noop(shard_id)

    def sync_get_session(self, shard_id: int, timeout: float = 5.0) -> Session:
        rc = self._submit(RPC_OP_SESSION_OPEN, shard_id=shard_id,
                          timeout=timeout)
        result = self._finish(rc, timeout + 0.5)
        # the server already ran prepare_for_propose on its side; the
        # fresh client-side session starts at the first series id
        return Session(
            shard_id=shard_id, client_id=result.value,
            series_id=SERIES_ID_FIRST_PROPOSAL, responded_to=0,
        )

    def sync_close_session(self, session: Session,
                           timeout: float = 5.0) -> None:
        rc = self._submit(RPC_OP_SESSION_CLOSE,
                          shard_id=session.shard_id, session=session,
                          timeout=timeout)
        self._finish(rc, timeout + 0.5)

    # -- stats-backed placement probes ------------------------------------
    def _stats(self, *, max_age: Optional[float] = None):
        age = self._stats_max_age if max_age is None else max_age
        rows = self._stats_rows
        if rows is not None and time.monotonic() - self._stats_t < age:
            return rows
        rc = self._submit(RPC_OP_STATS, flags=RPC_STATS_READ_PATHS,
                          timeout=self._stats_timeout)
        result = self._finish(rc, self._stats_timeout + 0.5)
        nhid, raft, rows, read_paths = decode_rpc_stats(result.data)
        with self._lock:
            self._stats_nhid = nhid
            self._stats_raft = raft
            self._stats_rows = rows
            self._stats_read_paths = read_paths
            self._stats_t = time.monotonic()
        return rows

    def read_path_counts(self) -> Dict[str, int]:
        """The remote's per-path read serve counts (empty against a
        pre-readplane server — the section is flag-gated)."""
        try:
            self._stats()
        except (RequestError, OSError):
            pass
        return dict(self._stats_read_paths)

    def balance_shard_stats(self) -> list:
        # the Collector's feed: always a fresh snapshot (its own cadence
        # IS the staleness bound it wants)
        return self._stats(max_age=0.0)

    def _row(self, shard_id: int) -> dict:
        for row in self._stats():
            if row["shard_id"] == shard_id:
                return row
        raise ShardNotFound(f"shard {shard_id} not on {self.address}")

    def _get_node(self, shard_id: int):
        # placement probe only (gateway _host_for any_ok sweep): raises
        # ShardNotFound when the remote doesn't carry the shard
        return self._row(shard_id)

    def get_leader_id(self, shard_id: int):
        row = self._row(shard_id)
        lid = row["leader_id"]
        return lid, lid != 0

    def is_leader_of(self, shard_id: int) -> bool:
        try:
            row = self._row(shard_id)
        except (RequestError, OSError):
            return False
        return row["leader_id"] != 0 and row["leader_id"] == row["replica_id"]

    def raft_address(self) -> str:
        if not self._stats_raft:
            try:
                self._stats()
            except (RequestError, OSError):
                return ""
        return self._stats_raft

    # -- event taps (host-side planes; nothing to tap remotely) -----------
    def add_event_tap(self, tap) -> None:
        return None

    def remove_event_tap(self, tap) -> None:
        return None

    # -- fleet-scope telemetry (obs/fleetscope.py) -------------------------
    def obs_query(self, what: str, *, cursor: int = 0, epoch: int = 0,
                  limit: int = 256, timeout: float = 2.0) -> dict:
        """One fleet-scope query against the remote (``RPC_OP_OBS``).
        ``what``: metrics | recorder | spans.  Returns the decoded
        reply dict annotated with ``bytes`` (the reply payload size,
        the scope's overhead counter).  Raises :class:`ObsUnsupported`
        against a pre-obs server (the collector marks it no-obs)."""
        flags = {
            "metrics": RPC_OBS_METRICS,
            "recorder": RPC_OBS_RECORDER,
            "spans": RPC_OBS_SPANS,
        }[what]
        rc = self._submit(
            RPC_OP_OBS, flags=flags, timeout=timeout,
            payload=encode_obs_query(cursor=cursor, epoch=epoch,
                                     limit=limit),
        )
        try:
            result = self._finish(rc, timeout + 0.5)
        except RequestError as e:
            if "unknown op" in str(e):
                raise ObsUnsupported(str(e))
            raise
        reply = decode_obs_reply(result.data)
        reply["bytes"] = len(result.data)
        return reply

    # -- nemesis plane (scenario harness only) -----------------------------
    def send_fault(self, action: str, *, fault: Optional[dict] = None,
                   timeout: float = 2.0) -> None:
        """Drive the REMOTE host's FaultController (RPC_OP_FAULT must be
        enabled server-side).  ``action``: activate | heal_wire |
        heal_all; ``fault``: Fault fields for activate."""
        spec = {"action": action}
        if fault is not None:
            spec["fault"] = fault
        rc = self._submit(
            RPC_OP_FAULT, timeout=timeout,
            payload=json.dumps(spec).encode("utf-8"),
        )
        self._finish(rc, timeout + 0.5)


# ---------------------------------------------------------------------------
# gossip-fed routing
# ---------------------------------------------------------------------------
class RouteFeeder:
    """Periodic Collector sweep feeding the gateway's RoutingCache.

    In-proc gateways learn routes from host event taps; remote handles
    have no taps, so this loop is the multi-process fleet's routing
    plane: every ``interval`` it snapshots gossip liveness, collects
    ``balance_shard_stats`` over the live handles (one STATS RPC per
    host) and bulk-refreshes the routing table from the view's
    ``leader_map`` — then drops any cached route pointing at a host
    the view no longer contains (``refresh_from_view`` merges, it
    never removes; a dead leader's stale route would otherwise pin
    until a proposal bounced off it)."""

    def __init__(self, gateway, gossip=None, *, interval: float = 0.25):
        from ..balance.view import Collector

        self._gw = gateway
        self._gossip = gossip
        self._interval = interval
        self._alive_ids: set = set()
        self._collector = Collector(alive=self._host_alive)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.ticks = 0

    def _host_alive(self, key: str, nh) -> bool:
        if nh is None or getattr(nh, "_closed", False):
            return False
        if self._gossip is None:
            return True
        nhid = getattr(nh, "nodehost_id", "")
        # unknown identity (no STATS exchange yet): let the collect
        # attempt itself decide — its failure marks the host dead for
        # this round and the breaker darkens it for the next
        return not nhid or nhid in self._alive_ids

    def tick(self) -> None:
        """One sweep (the loop body; callable directly from tests)."""
        if self._gossip is not None:
            self._alive_ids = set(self._gossip.alive_peers())
        view = self._collector.collect(self._gw._live_hosts())
        routes = self._gw.routes
        routes.refresh_from_view(view)
        live = set(view.hosts)
        for sid, key in routes.table().items():
            if key not in live:
                routes.invalidate(sid)
        self.ticks += 1

    def start(self) -> None:
        t = threading.Thread(
            target=self._main, daemon=True, name="tpu-route-feeder"
        )
        self._thread = t
        t.start()

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2.0)

    def _main(self) -> None:
        while not self._stop.wait(self._interval):
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — feeder must outlive any
                # one flaky collect; routes just stay stale one round
                _log.exception("route feeder tick failed")
