"""Serving front plane: session gateway, leader routing, overload
shedding, lease reads (docs/GATEWAY.md; ROADMAP item 4).

The ingress layer between "a NodeHost per process" and "millions of
clients": :class:`Gateway` multiplexes many cheap :class:`ClientHandle`
sessions onto batched per-shard proposal submissions, routes via a
lock-free-read :class:`RoutingCache` invalidated by
``leader_updated``/``balance_move_*`` events, sheds at the door under
overload (:class:`AdmissionController`, ``gateway_shed_total``), and
serves read-heavy traffic from the CheckQuorum leader lease
(``NodeHost.try_lease_read``) with a ReadIndex fallback.
"""
from .admission import AdmissionController
from .gateway import (
    ClientHandle,
    Gateway,
    GatewayBusy,
    GatewayClosed,
    GatewayConfig,
    GatewayFuture,
)
from .routing import RoutingCache
from .rpc import RemoteHostHandle, RouteFeeder, RpcServer

__all__ = [
    "AdmissionController",
    "ClientHandle",
    "Gateway",
    "GatewayBusy",
    "GatewayClosed",
    "GatewayConfig",
    "GatewayFuture",
    "RemoteHostHandle",
    "RouteFeeder",
    "RoutingCache",
    "RpcServer",
]
