"""NodeHostID: a stable per-nodehost identity for the gossip registry.

reference: internal/id (uuid-based NodeHostID) [U].  Persisted in the
nodehost dir so a host keeps its identity across restarts even when its
raft address changes — that is the entire point of
``address_by_nodehost_id`` mode.
"""
from __future__ import annotations

import os
import uuid

_FILENAME = "NODEHOST.ID"
_PREFIX = "nhid-"


def new_nodehost_id() -> str:
    return _PREFIX + uuid.uuid4().hex


def is_nodehost_id(v: str) -> bool:
    return v.startswith(_PREFIX)


def get_nodehost_id(nodehost_dir: str) -> str:
    """Load-or-create the persistent NodeHostID for a nodehost dir."""
    os.makedirs(nodehost_dir, exist_ok=True)
    path = os.path.join(nodehost_dir, _FILENAME)
    try:
        with open(path, "r", encoding="utf-8") as f:
            v = f.read().strip()
        if is_nodehost_id(v):
            return v
    except FileNotFoundError:
        pass
    v = new_nodehost_id()
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        f.write(v)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return v
