"""Metrics: counters/gauges with Prometheus-text export.

reference: dragonboat's EnableMetrics wiring (VictoriaMetrics/metrics
counters in nodehost/transport/logdb/raft, exported via
NodeHost.WriteHealthMetrics [U]).  Lock-free-ish: counters use a plain
int guarded by the GIL for add(); export snapshots under a registry
lock.  Disabled registries short-circuit to no-ops so the hot paths pay
one attribute load when metrics are off.
"""
from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

from .logger import get_logger

_log = get_logger("metrics")


def _escape_label_value(v) -> str:
    """Prometheus text-format label-value escaping: backslash, double
    quote and newline must be escaped or the exposition line is
    malformed (the spec's only three escapes; backslash FIRST so the
    others aren't double-escaped)."""
    return (
        str(v)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _labeled(name: str, labels) -> str:
    """Prometheus-style labelled series name: name{k="v",...}."""
    if not labels:
        return name
    inner = ",".join(
        f'{k}="{_escape_label_value(labels[k])}"' for k in sorted(labels)
    )
    return f"{name}{{{inner}}}"


def _base_name(name: str) -> str:
    return name.split("{", 1)[0]


def _parse_labels(series: str) -> Dict[str, str]:
    """Inverse of :func:`_labeled`: the label dict out of a full series
    name, honoring the three text-format escapes.  Registry keys are
    produced by ``_labeled`` so the walk can assume well-formed
    ``k="v",...`` pairs; anything malformed yields what parsed so far
    (snapshot is observability, never a raise path)."""
    i = series.find("{")
    if i < 0:
        return {}
    out: Dict[str, str] = {}
    s = series[i + 1:series.rfind("}")]
    pos = 0
    while pos < len(s):
        eq = s.find('="', pos)
        if eq < 0:
            break
        key = s[pos:eq]
        val = []
        j = eq + 2
        while j < len(s):
            c = s[j]
            if c == "\\" and j + 1 < len(s):
                nxt = s[j + 1]
                val.append("\n" if nxt == "n" else nxt)
                j += 2
                continue
            if c == '"':
                break
            val.append(c)
            j += 1
        out[key] = "".join(val)
        pos = j + 1
        if pos < len(s) and s[pos] == ",":
            pos += 1
    return out


class Counter:
    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0

    def add(self, n: int = 1) -> None:
        self.value += n


class Gauge:
    __slots__ = ("name", "fn", "value", "_warned")

    def __init__(self, name: str, fn: Optional[Callable[[], float]] = None):
        self.name = name
        self.fn = fn
        self.value = 0.0
        self._warned = False

    def set(self, v: float) -> None:
        self.value = v

    def get(self) -> float:
        if self.fn is None:
            return self.value
        try:
            return float(self.fn())
        except Exception:  # noqa: BLE001 — a callback bug must not
            # poison the whole scrape: export NaN for THIS series and
            # log once per gauge (not once per scrape)
            if not self._warned:
                self._warned = True
                _log.exception("gauge %s callback raised; exporting NaN",
                               self.name)
            return float("nan")


class Histogram:
    """Fixed-bucket latency histogram (seconds).  The default bounds
    suit sub-second request latencies; pass ``bounds`` for series whose
    observations run longer (e.g. multi-second rebalance moves, which
    would otherwise all land in +Inf and carry no distribution)."""

    BOUNDS = (0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0)

    __slots__ = ("name", "bounds", "buckets", "count", "total")

    def __init__(self, name: str, bounds=None):
        self.name = name
        self.bounds = tuple(bounds) if bounds is not None else self.BOUNDS
        self.buckets = [0] * (len(self.bounds) + 1)
        self.count = 0
        self.total = 0.0

    def observe(self, v: float) -> None:
        self.count += 1
        self.total += v
        for i, b in enumerate(self.bounds):
            if v <= b:
                self.buckets[i] += 1
                return
        self.buckets[-1] += 1

    def percentile(self, q: float) -> float:
        """Estimated q-quantile (0 < q <= 1) from the bucket counts:
        the upper bound of the bucket containing the rank.  Overflow
        (+Inf) observations clamp to the last finite bound — callers
        deriving budgets from e.g. ``percentile(0.99)`` should size
        ``bounds`` to their latency regime (a recorded histogram's p99
        makes a ``client.LatencyBudget`` bootstrap when no raw samples
        are at hand)."""
        if self.count == 0:
            return 0.0
        rank = q * self.count
        acc = 0
        for i, b in enumerate(self.bounds):
            acc += self.buckets[i]
            if acc >= rank:
                return b
        return self.bounds[-1]


class _Noop:
    def add(self, n: int = 1) -> None: ...

    def set(self, v: float) -> None: ...

    def observe(self, v: float) -> None: ...


_NOOP = _Noop()


class MetricsRegistry:
    """Per-NodeHost metric registry (one per process is fine too)."""

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._hists: Dict[str, Histogram] = {}

    def counter(self, name: str, labels: Optional[Dict[str, str]] = None):
        if not self.enabled:
            return _NOOP
        name = _labeled(name, labels)
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter(name)
            return c

    def gauge(
        self,
        name: str,
        fn: Optional[Callable[[], float]] = None,
        labels: Optional[Dict[str, str]] = None,
    ):
        if not self.enabled:
            return _NOOP
        name = _labeled(name, labels)
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge(name, fn)
            elif fn is not None:
                g.fn = fn
            return g

    def histogram(self, name: str, labels: Optional[Dict[str, str]] = None,
                  bounds=None):
        if not self.enabled:
            return _NOOP
        name = _labeled(name, labels)
        with self._lock:
            h = self._hists.get(name)
            if h is None:
                h = self._hists[name] = Histogram(name, bounds=bounds)
            return h

    def timer(self, name: str):
        """Context manager recording elapsed seconds into a histogram."""
        hist = self.histogram(name)

        class _T:
            __slots__ = ("t0",)

            def __enter__(self):
                self.t0 = time.perf_counter()
                return self

            def __exit__(self, *exc):
                hist.observe(time.perf_counter() - self.t0)
                return False

        return _T()

    # -- export ----------------------------------------------------------
    def snapshot(self) -> dict:
        """Structured, delta-able dump: full series name -> entry with
        parsed base name/labels, the current value and a ``monotone``
        flag (counters and histogram count/sum only ever grow — the
        fleet-scope SLO evaluator deltas exactly those; gauges are
        levels and must be read, not differenced).  Same
        snapshot-under-the-lock / format-outside discipline as
        ``export_text`` (Gauge.get runs user callbacks)."""
        with self._lock:
            counters = list(self._counters.values())
            gauges = list(self._gauges.values())
            hists = list(self._hists.values())
        out: dict = {"counters": {}, "gauges": {}, "histograms": {}}
        for c in counters:
            out["counters"][c.name] = {
                "name": _base_name(c.name),
                "labels": _parse_labels(c.name),
                "value": c.value,
                "monotone": True,
            }
        for g in gauges:
            out["gauges"][g.name] = {
                "name": _base_name(g.name),
                "labels": _parse_labels(g.name),
                "value": g.get(),
                "monotone": False,
            }
        for h in hists:
            out["histograms"][h.name] = {
                "name": _base_name(h.name),
                "labels": _parse_labels(h.name),
                "bounds": list(h.bounds),
                "buckets": list(h.buckets),
                "count": h.count,
                "sum": h.total,
                "monotone": True,
            }
        return out

    def export_text(self) -> str:
        """Prometheus text exposition format."""
        out = []
        typed = set()  # one TYPE line per base name (labelled series share it)

        def type_line(name: str, kind: str) -> None:
            base = _base_name(name)
            if base not in typed:
                typed.add(base)
                out.append(f"# TYPE {base} {kind}")

        # snapshot the instrument lists under the lock, format OUTSIDE
        # it: Gauge.get() runs arbitrary user callbacks that routinely
        # take other locks (NodeHost gauges take _nodes_lock), and
        # calling out of a critical section is a lock-order edge away
        # from a deadlock (raftlint block-under-lock finding; the
        # lockcheck witness graphs exactly this edge).  Value reads are
        # the usual GIL-benign races.
        with self._lock:
            counters = sorted(self._counters.values(), key=lambda x: x.name)
            gauges = sorted(self._gauges.values(), key=lambda x: x.name)
            hists = sorted(self._hists.values(), key=lambda x: x.name)
        for c in counters:
            type_line(c.name, "counter")
            out.append(f"{c.name} {c.value}")
        for g in gauges:
            type_line(g.name, "gauge")
            out.append(f"{g.name} {g.get()}")
        for h in hists:
            type_line(h.name, "histogram")
            base = _base_name(h.name)
            # merge any labels into the bucket brace set: the le
            # label must join the series labels, not follow them
            inner = h.name[len(base):].strip("{}")
            pre = f"{inner}," if inner else ""
            acc = 0
            for i, b in enumerate(h.bounds):
                acc += h.buckets[i]
                out.append(f'{base}_bucket{{{pre}le="{b}"}} {acc}')
            out.append(f'{base}_bucket{{{pre}le="+Inf"}} {h.count}')
            suffix = f"{{{inner}}}" if inner else ""
            out.append(f"{base}_sum{suffix} {h.total}")
            out.append(f"{base}_count{suffix} {h.count}")
        return "\n".join(out) + "\n"


# module-level default used by components not owned by a NodeHost
global_registry = MetricsRegistry(enabled=True)
