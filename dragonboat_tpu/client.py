"""Client sessions for exactly-once proposal semantics.

reference: client/session.go [U].  A ``Session`` carries (client_id,
series_id, responded_to); the RSM's session manager caches the result of
each (client_id, series_id) so a retried proposal returns the cached result
instead of re-applying.  ``NoOPSession`` opts out (at-most-once).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from .obs.trace import UNSAMPLED

NOOP_SERIES_ID = 0
SERIES_ID_REGISTER = 0xFFFFFFFFFFFFFFFD
SERIES_ID_UNREGISTER = 0xFFFFFFFFFFFFFFFC
SERIES_ID_FIRST_PROPOSAL = 1

_client_id_counter = [0]


def _next_client_id() -> int:
    # Deterministic per-process id allocation; the uniqueness domain is the
    # shard (ids are registered through the raft log, so collisions across
    # processes are resolved by the session registry entry itself).
    import os
    import time

    _client_id_counter[0] += 1
    return (
        ((os.getpid() & 0xFFFF) << 48)
        | ((int(time.time()) & 0xFFFFFFFF) << 16)
        | (_client_id_counter[0] & 0xFFFF)
    )


@dataclass
class Session:
    shard_id: int = 0
    client_id: int = 0
    series_id: int = 0
    responded_to: int = 0

    @classmethod
    def new_session(cls, shard_id: int) -> "Session":
        return cls(
            shard_id=shard_id,
            client_id=_next_client_id(),
            series_id=SERIES_ID_REGISTER,
        )

    @classmethod
    def noop(cls, shard_id: int) -> "Session":
        return cls(shard_id=shard_id, client_id=0, series_id=NOOP_SERIES_ID)

    def is_noop(self) -> bool:
        return self.client_id == 0 and self.series_id == NOOP_SERIES_ID

    def prepare_for_register(self) -> None:
        self.series_id = SERIES_ID_REGISTER

    def prepare_for_propose(self) -> None:
        self.series_id = SERIES_ID_FIRST_PROPOSAL
        self.responded_to = 0

    def prepare_for_unregister(self) -> None:
        self.series_id = SERIES_ID_UNREGISTER

    def proposal_completed(self) -> None:
        """Call after a successful proposal so the server can GC the cached
        result for the completed series."""
        if self.series_id in (SERIES_ID_REGISTER, SERIES_ID_UNREGISTER):
            raise RuntimeError("proposal_completed on a register/unregister session")
        self.responded_to = self.series_id
        self.series_id += 1

    def valid_for_proposal(self, shard_id: int) -> bool:
        if self.shard_id != shard_id:
            return False
        if self.is_noop():
            return True
        return self.series_id not in (SERIES_ID_REGISTER, SERIES_ID_UNREGISTER) or True

    def valid_for_session_op(self, shard_id: int) -> bool:
        if self.shard_id != shard_id:
            return False
        if self.is_noop():
            return False
        return self.series_id in (SERIES_ID_REGISTER, SERIES_ID_UNREGISTER)


class LatencyBudget:
    """Latency-aware request budget (replaces hand-tuned per-scale
    deadlines — VERDICT weak #8: the proposal-deadline machinery was
    re-tuned by hand at every shard count).

    Tracks observed commit latencies in a sliding window and derives:

    * :meth:`per_try_timeout` — one attempt's timeout: enough for a
      p99 commit plus one election window (a mid-proposal leader loss
      needs a re-election before the retry can land);
    * :meth:`total_timeout` — a whole op's retry budget: several
      worst-case attempts.

    Both clamp to ``[floor, cap]``.  Before any sample exists the
    bootstrap latency (e.g. derived from an observed election phase —
    the first direct measurement of the cluster's latency scale)
    stands in for the p99.  Thread-safe; shared by concurrent clients
    so everyone learns from everyone's commits.
    """

    def __init__(
        self,
        *,
        election_window: float = 1.0,
        bootstrap: float = 1.0,
        floor: float = 0.5,
        cap: float = 600.0,
        window: int = 512,
        try_factor: float = 2.0,
        attempts: float = 4.0,
    ):
        import threading
        from collections import deque

        self.election_window = election_window
        self.bootstrap = bootstrap
        self.floor = floor
        self.cap = cap
        self.try_factor = try_factor
        self.attempts = attempts
        self._lat = deque(maxlen=window)  # guarded-by: _lock
        self._lock = threading.Lock()
        # p99 cache, refreshed every _P99_REFRESH observations: the
        # gateway admission gate reads p99 per PROPOSAL while holding
        # its own lock — a full 512-sample sort per admit would
        # serialize every submitting thread on the hottest path
        # (review finding).  Staleness is bounded at 16 samples of a
        # 512-sample window; deadline feasibility is an estimate
        # either way.
        self._p99_cache = None  # guarded-by: _lock
        self._since_refresh = 0  # guarded-by: _lock

    _P99_REFRESH = 16

    def observe(self, secs: float) -> None:
        with self._lock:
            self._lat.append(secs)
            self._since_refresh += 1
            if self._since_refresh >= self._P99_REFRESH:
                self._p99_cache = None

    def samples(self) -> int:
        """Observed-latency count in the sliding window.  0 means
        :meth:`p99` is returning the BOOTSTRAP guess, not a
        measurement — consumers acting on p99 (e.g. the gateway's
        snapshot-cap feedback) should treat that as "no signal", not
        as a degraded commit path."""
        with self._lock:
            return len(self._lat)

    def p99(self) -> float:
        with self._lock:
            if not self._lat:
                return self.bootstrap
            if self._p99_cache is None:
                s = sorted(self._lat)
                self._p99_cache = s[min(len(s) - 1, int(0.99 * len(s)))]
                self._since_refresh = 0
            return self._p99_cache

    def per_try_timeout(self) -> float:
        v = self.try_factor * self.p99() + self.election_window
        return max(self.floor, min(v, self.cap))

    def total_timeout(self) -> float:
        """Whole-op budget: ``attempts`` worst-case tries (already
        bounded by the per-try clamp, so no clamp of its own)."""
        return self.attempts * self.per_try_timeout()

    def can_meet(self, remaining: float, *, queued_ahead: int = 0,
                 batch_hint: int = 64) -> bool:
        """Deadline feasibility: can a request admitted NOW still meet
        a deadline ``remaining`` seconds away?  The gateway's
        reject-early gate (docs/GATEWAY.md "Shedding policy"): expected
        completion is one observed-p99 commit plus one more p99 per
        ``batch_hint`` requests already queued ahead on the same shard
        (each batch ahead of ours must commit first).  Conservative by
        design — shedding a request that WOULD have made it costs one
        retry somewhere less loaded; admitting one that can't poisons
        p99 for everyone behind it."""
        eta = self.p99() * (1.0 + queued_ahead / max(1, batch_hint))
        return remaining >= eta


def call_with_retry(
    fn,
    *,
    timeout: float = 10.0,
    deadline: Optional[float] = None,
    base_backoff: float = 0.02,
    max_backoff: float = 0.5,
    rng=None,
):
    """Deadline-aware retry of an arbitrary synchronous request call.

    The one retry discipline of the client path — retries ``fn()`` on
    the transient failures a healthy-but-shaken cluster emits —
    ShardNotReady, SystemBusy, ShardNotFound, RequestDropped and
    timeouts — with jittered exponential backoff, never exceeding the
    caller's deadline (``deadline`` as a ``time.monotonic()`` instant,
    or ``timeout`` seconds from now).  :func:`propose_with_retry` is
    the proposal-shaped wrapper.  Terminal errors propagate
    immediately.  Returns ``fn()``'s result.
    """
    import random as _random
    import time as _time

    # lazy: nodehost imports this module
    from .nodehost import RequestDropped, TimeoutError_
    from .request import ShardNotFound, ShardNotReady, SystemBusy

    retryable = (ShardNotReady, ShardNotFound, SystemBusy, RequestDropped,
                 TimeoutError_)
    rng = rng or _random.Random()
    if deadline is None:
        deadline = _time.monotonic() + timeout
    backoff = base_backoff
    attempt = 0
    while True:
        if deadline - _time.monotonic() <= 0:
            raise TimeoutError_(
                f"request deadline exhausted after {attempt} attempt(s)"
            )
        try:
            return fn()
        except retryable:
            attempt += 1
            remaining = deadline - _time.monotonic()
            if remaining <= 0:
                raise
            _time.sleep(min(backoff * (0.5 + rng.random()), remaining))
            backoff = min(backoff * 2.0, max_backoff)


def propose_with_retry(
    nodehost,
    session: Session,
    cmd: bytes,
    *,
    timeout: float = 10.0,
    deadline: Optional[float] = None,
    per_try_timeout: float = 1.0,
    base_backoff: float = 0.02,
    max_backoff: float = 0.5,
    rng=None,
    budget: Optional[LatencyBudget] = None,
):
    """Deadline-aware proposal retry (the self-healing client path).

    Retries ``nodehost.sync_propose`` on the TRANSIENT failures a
    healthy-but-shaken cluster emits — ShardNotReady (no leader yet),
    SystemBusy (queues full), ShardNotFound (replica restarting),
    RequestDropped and timeouts — with jittered exponential backoff,
    never exceeding the caller's deadline (``deadline`` as a
    ``time.monotonic()`` instant, or ``timeout`` seconds from now).

    Retrying is exactly-once-safe with a registered ``Session`` (the
    series id is unchanged across retries, so a retried proposal that
    already applied returns the cached result); with a ``NoOPSession``
    a retried timeout MAY apply twice — same contract as the reference
    client [U].  Terminal errors (InvalidTarget, rejected/terminated
    requests) propagate immediately.  Returns the proposal Result.

    The retry discipline itself lives in :func:`call_with_retry` — one
    loop to tune, not two.  A :class:`LatencyBudget` replaces the fixed
    ``timeout``/``per_try_timeout`` with latency-derived ones (explicit
    ``deadline`` still wins) and is fed each successful commit latency.
    """
    import time as _time

    if budget is not None:
        per_try_timeout = budget.per_try_timeout()
        if deadline is None:
            deadline = _time.monotonic() + budget.total_timeout()
    if deadline is None:
        deadline = _time.monotonic() + timeout

    # obs/: one CLIENT root span over the whole retry loop — each
    # attempt's nodehost "propose" span parents under it, so a trace of
    # a shaken-cluster proposal shows every failed try AND the one that
    # committed.  None when tracing is off/unsampled (one attribute
    # load + a falsy test per call).
    tracer = getattr(nodehost, "tracer", None)
    root = (
        tracer.start_trace("client:propose_with_retry",
                           shard_id=session.shard_id)
        if tracer is not None
        else None
    )

    last_try_at = [0.0]
    tries = [0]

    def attempt():
        remaining = max(deadline - _time.monotonic(), 0.001)
        last_try_at[0] = _time.monotonic()
        tries[0] += 1
        if tracer is None:
            # no parent kwarg on the untraced path: hosts only need to
            # accept it when they themselves handed out a tracer
            return nodehost.sync_propose(
                session, cmd, timeout=min(per_try_timeout, remaining)
            )
        if root is None:
            # the root's sampling draw said NO — tell the nodehost so
            # it doesn't make a second independent draw per attempt
            # (sampled once, at the root)
            return nodehost.sync_propose(
                session, cmd, timeout=min(per_try_timeout, remaining),
                parent=UNSAMPLED,
            )
        root.annotate(f"client:attempt={tries[0]}")
        return nodehost.sync_propose(
            session, cmd, timeout=min(per_try_timeout, remaining),
            parent=root,
        )

    try:
        result = call_with_retry(
            attempt,
            deadline=deadline,
            base_backoff=base_backoff,
            max_backoff=max_backoff,
            rng=rng,
        )
    except BaseException as e:
        if root is not None:
            root.end(status=type(e).__name__)
        raise
    if root is not None:
        root.end()
    if budget is not None:
        # feed the SUCCESSFUL attempt's latency, not the whole retry
        # loop's: backoff sleeps and failed tries in the sample would
        # ratchet per_try/total timeouts toward the cap under faults
        budget.observe(_time.monotonic() - last_try_at[0])
    return result
