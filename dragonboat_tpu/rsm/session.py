"""Server-side client sessions: the exactly-once dedupe registry.

reference: internal/rsm/session.go + sessionmanager.go [U].  An LRU of
``client_id -> Session{responded_to, history: series_id -> Result}``;
session create/close are raft entries themselves so the registry is
identical on every replica, and it is serialized into every snapshot.
"""
from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

from .. import settings
from ..statemachine import Result


@dataclass
class Session:
    client_id: int
    responded_to: int = 0
    history: Dict[int, Result] = field(default_factory=dict)

    def add_response(self, series_id: int, result: Result) -> None:
        if series_id in self.history:
            raise RuntimeError(f"series {series_id} already responded")
        self.history[series_id] = result

    def get_response(self, series_id: int) -> Tuple[Optional[Result], bool]:
        if series_id in self.history:
            return self.history[series_id], True
        return None, False

    def has_responded(self, series_id: int) -> bool:
        return series_id <= self.responded_to

    def clear_to(self, responded_to: int) -> None:
        if responded_to <= self.responded_to:
            return
        self.responded_to = responded_to
        for sid in [s for s in self.history if s <= responded_to]:
            del self.history[sid]


class SessionManager:
    def __init__(self, max_sessions: Optional[int] = None):
        self._lru: "OrderedDict[int, Session]" = OrderedDict()
        self._max = max_sessions or settings.Hard.lru_max_session_count
        # diagnostic counters (NOT serialized into snapshots — they are
        # per-replica evidence for the audit harness, not state):
        # dedupe_hits   = retried proposals answered from the cache
        #                 instead of re-applying (exactly-once at work)
        # responded_rejects = copies of an already-responded series
        #                 rejected without applying
        self.dedupe_hits = 0
        self.responded_rejects = 0

    def register(self, client_id: int) -> Result:
        if client_id in self._lru:
            self._lru.move_to_end(client_id)
        else:
            self._lru[client_id] = Session(client_id=client_id)
            while len(self._lru) > self._max:
                self._lru.popitem(last=False)
        return Result(value=client_id)

    def unregister(self, client_id: int) -> Result:
        if client_id in self._lru:
            del self._lru[client_id]
            return Result(value=client_id)
        return Result(value=0)

    def get(self, client_id: int) -> Optional[Session]:
        s = self._lru.get(client_id)
        if s is not None:
            self._lru.move_to_end(client_id)
        return s

    def count(self) -> int:
        return len(self._lru)

    # -- snapshot (de)serialization --------------------------------------
    # session tables ship inside snapshot payloads over the chunk lane,
    # i.e. they are decoded from untrusted network bytes — positional
    # binary via the wire codec, never pickle
    def serialize(self) -> bytes:
        from ..transport.wire import encode_session_table

        return encode_session_table(
            (s.client_id, s.responded_to, s.history)
            for s in self._lru.values()
        )

    @classmethod
    def deserialize(cls, data: bytes, max_sessions: Optional[int] = None):
        from ..transport.wire import decode_session_table

        sm = cls(max_sessions)
        for client_id, responded_to, history in decode_session_table(data):
            sm._lru[client_id] = Session(
                client_id=client_id,
                responded_to=responded_to,
                history=dict(history),
            )
        return sm
