"""Uniform internal wrapper over the three public SM types.

reference: internal/rsm/managed.go / nativesm.go [U].  Normalizes
everything to the batched interface and supplies the right locking:
regular SMs get an RW mutex (snapshot blocks writes), concurrent/on-disk
SMs run lock-free with PrepareSnapshot.
"""
from __future__ import annotations

import enum
import threading
from typing import BinaryIO, List, Optional

from ..statemachine import (
    IConcurrentStateMachine,
    IOnDiskStateMachine,
    IStateMachine,
    ISnapshotFileCollection,
    Result,
    SMEntry,
)


class SMType(enum.IntEnum):
    REGULAR = 0
    CONCURRENT = 1
    ON_DISK = 2


def wrap_state_machine(sm) -> "ManagedStateMachine":
    if isinstance(sm, IOnDiskStateMachine):
        return ManagedStateMachine(sm, SMType.ON_DISK)
    if isinstance(sm, IConcurrentStateMachine):
        return ManagedStateMachine(sm, SMType.CONCURRENT)
    if isinstance(sm, IStateMachine):
        return ManagedStateMachine(sm, SMType.REGULAR)
    raise TypeError(f"not a state machine: {type(sm)}")


class ManagedStateMachine:
    def __init__(self, sm, sm_type: SMType):
        self.sm = sm
        self.type = sm_type
        self._mu = threading.RLock()  # regular SM: excludes update vs snapshot

    @property
    def on_disk(self) -> bool:
        return self.type == SMType.ON_DISK

    @property
    def concurrent_snapshot(self) -> bool:
        return self.type in (SMType.CONCURRENT, SMType.ON_DISK)

    def open(self, stopc) -> int:
        if self.type != SMType.ON_DISK:
            return 0
        return self.sm.open(stopc)

    def batched_update(self, entries: List[SMEntry]) -> List[SMEntry]:
        if self.type == SMType.REGULAR:
            with self._mu:
                for e in entries:
                    e.result = self.sm.update(e)
                return entries
        return self.sm.update(entries)

    def lookup(self, query):
        if self.type == SMType.REGULAR:
            with self._mu:
                return self.sm.lookup(query)
        return self.sm.lookup(query)

    def sync(self) -> None:
        if self.type == SMType.ON_DISK:
            self.sm.sync()

    def prepare_snapshot(self):
        if self.type == SMType.REGULAR:
            return None
        return self.sm.prepare_snapshot()

    def save_snapshot(
        self,
        ctx,
        w: BinaryIO,
        files: Optional[ISnapshotFileCollection],
        done,
    ) -> None:
        if self.type == SMType.REGULAR:
            with self._mu:
                self.sm.save_snapshot(w, files, done)
        elif self.type == SMType.CONCURRENT:
            self.sm.save_snapshot(ctx, w, files, done)
        else:
            self.sm.save_snapshot(ctx, w, done)

    def recover_from_snapshot(self, r: BinaryIO, files, done) -> None:
        if self.type == SMType.ON_DISK:
            self.sm.recover_from_snapshot(r, done)
        else:
            self.sm.recover_from_snapshot(r, files, done)

    def close(self) -> None:
        self.sm.close()
