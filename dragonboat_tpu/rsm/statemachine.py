"""The ordered apply loop: rsm.StateMachine + TaskQueue.

reference: internal/rsm/statemachine.go [U].  Apply workers drain a
``TaskQueue`` of committed-entry batches (plus snapshot save/recover
tasks), route each entry by kind (application / config-change / session
ops / noop), dedupe through client sessions, and surface
``ApplyResult``s so the node can complete pending futures.
"""
from __future__ import annotations

import enum
import io
import threading
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from ..client import (
    NOOP_SERIES_ID,
    SERIES_ID_REGISTER,
    SERIES_ID_UNREGISTER,
)
from ..logger import get_logger
from ..pb import ConfigChange, Entry, EntryType, Membership, Snapshot
from ..statemachine import Result, SMEntry
from ..transport.wire import WireError, decode_config_change
from .managed import ManagedStateMachine
from .membership import MembershipManager
from .session import SessionManager

_log = get_logger("rsm")


class SnapshotFileCollection:
    """Concrete ISnapshotFileCollection: stages each added file via the
    storage-provided ``copy_fn`` (into the snapshot dir) at add time —
    the user contract is that the file exists until save returns
    (reference: statemachine.ISnapshotFileCollection [U])."""

    def __init__(self, copy_fn=None):
        self._copy = copy_fn
        self.files = []  # List[SnapshotFile]

    def add_file(self, file_id: int, path: str, metadata: bytes = b"") -> None:
        import os

        from ..pb import SnapshotFile

        if self._copy is not None:
            self.files.append(self._copy(file_id, path, metadata))
        else:
            self.files.append(
                SnapshotFile(
                    file_id=file_id,
                    filepath=path,
                    file_size=os.path.getsize(path),
                    metadata=metadata,
                )
            )


class TaskType(enum.IntEnum):
    ENTRIES = 0
    SNAPSHOT_SAVE = 1
    SNAPSHOT_RECOVER = 2
    SNAPSHOT_STREAM = 3
    SYNC = 4
    STOP = 5


@dataclass
class Task:
    type: TaskType = TaskType.ENTRIES
    entries: List[Entry] = field(default_factory=list)
    snapshot: Snapshot = None  # type: ignore[assignment]
    ctx: object = None  # snapshot request context (export path, sink, ...)


class TaskQueue:
    """MPSC committed-task queue (reference: rsm.TaskQueue [U]).

    A plain list with swap-drain: producers only append, the single
    consumer takes the whole list (an idle queue is one empty list, not
    a ~750 B deque — this object exists once per replica row)."""

    __slots__ = ("_q", "_lock")

    def __init__(self):
        self._q: List[Task] = []
        self._lock = threading.Lock()

    def add(self, t: Task) -> None:
        with self._lock:
            self._q.append(t)

    def get_all(self) -> List[Task]:
        if not self._q:
            return []
        with self._lock:
            out = self._q
            self._q = []
            return out

    def __len__(self) -> int:
        with self._lock:
            return len(self._q)


@dataclass
class ApplyResult:
    entry: Entry
    result: Result
    rejected: bool = False  # config change rejected / session op failed
    config_change: Optional[ConfigChange] = None


class StateMachine:
    """Per-replica managed SM + sessions + membership (reference:
    rsm.StateMachine [U])."""

    __slots__ = (
        "shard_id", "replica_id", "managed", "sessions", "members",
        "task_queue", "last_applied", "applied_term",
        "on_disk_init_index", "is_witness", "_mu",
    )

    def __init__(
        self,
        shard_id: int,
        replica_id: int,
        managed: ManagedStateMachine,
        ordered_config_change: bool = False,
        is_witness: bool = False,
    ):
        self.shard_id = shard_id
        self.replica_id = replica_id
        self.managed = managed
        self.sessions = SessionManager()
        self.members = MembershipManager(shard_id, ordered_config_change)
        self.task_queue = TaskQueue()
        self.last_applied = 0
        self.applied_term = 0
        self.on_disk_init_index = 0
        self.is_witness = is_witness
        self._mu = threading.RLock()

    # -- lifecycle --------------------------------------------------------
    def open(self, stopc) -> int:
        """On-disk SMs recover themselves and report their applied index."""
        idx = self.managed.open(stopc)
        self.on_disk_init_index = idx
        if idx > self.last_applied:
            self.last_applied = idx
        return idx

    def set_initial_membership(self, addresses, non_votings=None, witnesses=None):
        self.members.set_initial(addresses, non_votings, witnesses)

    def get_membership(self) -> Membership:
        with self._mu:
            return self.members.membership.copy()

    # -- apply ------------------------------------------------------------
    def handle(self, task: Task) -> List[ApplyResult]:
        """Apply one committed batch in order (reference: rsm.Handle [U])."""
        if task.type != TaskType.ENTRIES:
            raise ValueError("handle() only processes entry tasks")
        results: List[ApplyResult] = []
        batch: List[Tuple[Entry, SMEntry]] = []
        # session keys already queued in `batch` but not yet recorded in the
        # session store: a retried proposal can commit twice in one batch,
        # and dedupe must catch the second copy even before flush()
        batch_keys: set = set()

        def flush():
            if not batch:
                return
            sm_entries = [se for _, se in batch]
            self.managed.batched_update(sm_entries)
            for (entry, se) in batch:
                self._record_session_result(entry, se.result)
                results.append(ApplyResult(entry=entry, result=se.result))
            batch.clear()
            batch_keys.clear()

        with self._mu:
            for e in task.entries:
                # ONE dispatch ladder for live apply AND the on-disk
                # replay window (entries at or below the index an
                # IOnDiskStateMachine reported durably applied —
                # reference: statemachine.go's onDiskInitIndex
                # discipline [U]).  Membership and session state live
                # in rsm MEMORY, so config-change / register /
                # unregister entries run UNCONDITIONALLY and rebuild it
                # during replay (their `_advance` is a no-op below the
                # window); skipping them wholesale lost every
                # witness/non-voting added below the on-disk index on
                # the next restart without a snapshot — the restarted
                # replica, and any leader it became, forgot those
                # members existed and never replicated to them again
                # (found by the production-day soak's rolling-restart
                # phase, docs/SCENARIO.md).  Only USER code is gated on
                # the window, in the application branch below.
                if e.type == EntryType.CONFIG_CHANGE:
                    flush()
                    results.append(self._handle_config_change(e))
                elif e.type == EntryType.METADATA or e.is_noop():
                    flush()
                    self._advance(e)
                elif e.is_new_session_request():
                    flush()
                    results.append(self._handle_register(e))
                elif e.is_end_session_request():
                    flush()
                    results.append(self._handle_unregister(e))
                elif e.index <= self.last_applied:
                    # replay window, application entry: the effect is
                    # already inside the on-disk state — never re-run
                    # user code, but mark a session-managed series
                    # responded so a cross-restart retry dedupes
                    # instead of being rejected as an expired session.
                    # A series can appear TWICE below the window (a
                    # retry that committed both copies — the case
                    # _check_duplicate dedupes on the live path), so
                    # only the first replayed copy records; a second
                    # add_response would raise and wedge replay in a
                    # deterministic restart crash loop (review finding)
                    if e.is_session_managed():
                        s = self.sessions.get(e.client_id)
                        if s is not None:
                            s.clear_to(e.responded_to)
                            _, hit = s.get_response(e.series_id)
                            if not s.has_responded(e.series_id) and not hit:
                                s.add_response(e.series_id, Result())
                else:
                    if (
                        e.is_session_managed()
                        and (e.client_id, e.series_id) in batch_keys
                    ):
                        # duplicate of an entry queued in this same batch:
                        # apply the queued copy first so the session store
                        # has its result, then dedupe normally
                        flush()
                    dup = self._check_duplicate(e)
                    if dup is not None:
                        results.append(dup)
                    elif self.is_witness:
                        self._advance(e)  # witnesses never run user code
                    else:
                        batch.append((e, SMEntry(index=e.index, cmd=e.cmd)))
                        if e.is_session_managed():
                            batch_keys.add((e.client_id, e.series_id))
                        self._advance(e)
            flush()
        return results

    def _advance(self, e: Entry) -> None:
        from ..invariants import check

        check(
            e.index <= self.last_applied + 1,
            "apply gap: entry %d after applied %d",
            e.index,
            self.last_applied,
        )
        if e.index > self.last_applied:
            self.last_applied = e.index
            self.applied_term = e.term

    def _check_duplicate(self, e: Entry) -> Optional[ApplyResult]:
        if not e.is_session_managed():
            return None
        s = self.sessions.get(e.client_id)
        if s is None:
            # session expired from LRU (or never registered)
            self._advance(e)
            return ApplyResult(entry=e, result=Result(), rejected=True)
        s.clear_to(e.responded_to)
        if s.has_responded(e.series_id):
            self.sessions.responded_rejects += 1
            self._advance(e)
            return ApplyResult(entry=e, result=Result(), rejected=True)
        cached, hit = s.get_response(e.series_id)
        if hit:
            self.sessions.dedupe_hits += 1
            self._advance(e)
            return ApplyResult(entry=e, result=cached)
        return None

    def _record_session_result(self, e: Entry, result: Result) -> None:
        if not e.is_session_managed():
            return
        s = self.sessions.get(e.client_id)
        if s is not None:
            s.add_response(e.series_id, result)

    def _handle_config_change(self, e: Entry) -> ApplyResult:
        try:
            cc: ConfigChange = decode_config_change(e.cmd)
        except (WireError, ValueError):
            self._advance(e)
            return ApplyResult(entry=e, result=Result(), rejected=True)
        accepted = self.members.handle(cc, e.index)
        self._advance(e)
        return ApplyResult(
            entry=e,
            result=Result(value=1 if accepted else 0),
            rejected=not accepted,
            config_change=cc if accepted else None,
        )

    def _handle_register(self, e: Entry) -> ApplyResult:
        r = self.sessions.register(e.client_id)
        self._advance(e)
        return ApplyResult(entry=e, result=r, rejected=r.value == 0)

    def _handle_unregister(self, e: Entry) -> ApplyResult:
        r = self.sessions.unregister(e.client_id)
        self._advance(e)
        return ApplyResult(entry=e, result=r, rejected=r.value == 0)

    # -- reads ------------------------------------------------------------
    def lookup(self, query):
        return self.managed.lookup(query)

    def sync(self) -> None:
        self.managed.sync()

    # -- snapshot ---------------------------------------------------------
    def save_snapshot_stream(
        self,
        fileobj,
        collection=None,
        done=None,
        *,
        compression: int = 0,
        block_size: Optional[int] = None,
    ) -> Tuple[int, int, list]:
        """Stream a v2 container (storage/snapshotio.py) into ``fileobj``.

        The SM's data flows through the block writer with bounded
        memory — a 10GB on-disk SM never materializes its payload
        (reference: rsm streamed save for IOnDiskStateMachine [U]).
        Returns (index, term, external_files).
        """
        from ..storage.snapshotio import DEFAULT_BLOCK_SIZE, SnapshotWriter

        done = done or threading.Event()
        with self._mu:
            index, term = self.last_applied, self.applied_term
            membership = self.members.membership.copy()
            sessions_blob = self.sessions.serialize()
            # on-disk SMs: make everything applied so far durable in the
            # SM's OWN storage before the snapshot point is fixed
            # (reference: IOnDiskStateMachine.Sync before snapshotting
            # [U]) — the log may be compacted past `index` right after,
            # and the SM must never depend on replaying below it
            self.managed.sync()
            ctx = self.managed.prepare_snapshot()
            w = SnapshotWriter(
                fileobj,
                index=index,
                term=term,
                membership=membership,
                sessions=sessions_blob,
                on_disk=self.managed.on_disk,
                compression=compression,
                block_size=block_size or DEFAULT_BLOCK_SIZE,
            )
            if not self.managed.concurrent_snapshot:
                # regular SM: serialize inside the apply-exclusive section so
                # the payload cannot contain entries newer than `index`
                self.managed.save_snapshot(ctx, w, collection, done)
        if self.managed.concurrent_snapshot:
            # concurrent/on-disk SMs captured a consistent view in
            # prepare_snapshot; the slow serialization runs outside the lock
            self.managed.save_snapshot(ctx, w, collection, done)
        if collection is not None:
            for sf in collection.files:
                w.add_external_file(sf)
        w.close()
        return index, term, (collection.files if collection else [])

    def recover_from_snapshot_stream(self, reader, files, done=None) -> int:
        """Restore from a SnapshotReader; ``files`` are the resolved
        external SnapshotFile records (absolute paths)."""
        with self._mu:
            self.managed.recover_from_snapshot(
                reader.sm_stream(), files, done or threading.Event()
            )
            self.sessions = SessionManager.deserialize(reader.sessions)
            self.members.restore(reader.membership)
            self.last_applied = reader.index
            self.applied_term = reader.term
        return reader.index

    # bytes-level convenience (tests, in-mem flows) over the same container
    def save_snapshot_data(self, files=None, done=None) -> Tuple[bytes, int, int]:
        buf = io.BytesIO()
        index, term, _ = self.save_snapshot_stream(buf, files, done)
        return buf.getvalue(), index, term

    def recover_from_snapshot_data(self, payload: bytes, done=None) -> int:
        from ..storage.snapshotio import SnapshotReader

        return self.recover_from_snapshot_stream(
            SnapshotReader(io.BytesIO(payload)), [], done
        )
