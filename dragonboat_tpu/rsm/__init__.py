"""Replicated-state-machine execution layer (reference: internal/rsm/ [U])."""
from .session import Session as RSMSession, SessionManager
from .managed import ManagedStateMachine, wrap_state_machine, SMType
from .statemachine import StateMachine, Task, TaskQueue

__all__ = [
    "RSMSession",
    "SessionManager",
    "ManagedStateMachine",
    "wrap_state_machine",
    "SMType",
    "StateMachine",
    "Task",
    "TaskQueue",
]
