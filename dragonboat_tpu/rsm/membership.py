"""Deterministic membership tracking applied through the raft log.

reference: internal/rsm/membership.go [U].  Every replica applies the same
config-change entries in the same order; validation must therefore be a
pure function of (membership, change) so accept/reject is identical
everywhere.  ``config_change_id`` is the index of the last applied config
change (used by ordered_config_change mode).
"""
from __future__ import annotations

from typing import Optional, Tuple

from ..pb import ConfigChange, ConfigChangeType, Membership
from ..logger import get_logger

_log = get_logger("rsm")


class MembershipManager:
    def __init__(self, shard_id: int, ordered: bool = False):
        self.shard_id = shard_id
        self.ordered = ordered
        self.membership = Membership()

    def set_initial(self, addresses, non_votings=None, witnesses=None) -> None:
        self.membership = Membership(
            config_change_id=0,
            addresses=dict(addresses or {}),
            non_votings=dict(non_votings or {}),
            witnesses=dict(witnesses or {}),
        )

    def restore(self, membership: Membership) -> None:
        self.membership = membership.copy()

    def is_empty(self) -> bool:
        return not self.membership.addresses and not self.membership.witnesses

    def _validate(self, cc: ConfigChange) -> bool:
        m = self.membership
        pid = cc.replica_id
        if pid == 0:
            return False
        if self.ordered and cc.config_change_id != m.config_change_id:
            _log.info(
                "shard %d: rejected config change, ccid %d != %d",
                self.shard_id,
                cc.config_change_id,
                m.config_change_id,
            )
            return False
        if cc.type == ConfigChangeType.ADD_REPLICA:
            if pid in m.removed or pid in m.witnesses:
                return False
            if pid in m.addresses:
                # re-adding with same address is a no-op accept; different
                # address is rejected (the reference rejects addr reuse)
                return m.addresses[pid] == cc.address
            if cc.address in m.addresses.values():
                return False
        elif cc.type == ConfigChangeType.ADD_NON_VOTING:
            if pid in m.removed or pid in m.addresses or pid in m.witnesses:
                return False
        elif cc.type == ConfigChangeType.ADD_WITNESS:
            if pid in m.removed or pid in m.addresses or pid in m.non_votings:
                return False
        elif cc.type == ConfigChangeType.REMOVE_REPLICA:
            if pid in m.removed:
                return False
            if (
                pid not in m.addresses
                and pid not in m.non_votings
                and pid not in m.witnesses
            ):
                return False
        return True

    def handle(self, cc: ConfigChange, entry_index: int) -> bool:
        """Apply one committed config change; returns accepted."""
        if not self._validate(cc):
            return False
        m = self.membership
        addresses = dict(m.addresses)
        non_votings = dict(m.non_votings)
        witnesses = dict(m.witnesses)
        removed = dict(m.removed)
        pid = cc.replica_id
        if cc.type == ConfigChangeType.ADD_REPLICA:
            non_votings.pop(pid, None)  # promotion
            addresses[pid] = cc.address
        elif cc.type == ConfigChangeType.ADD_NON_VOTING:
            non_votings[pid] = cc.address
        elif cc.type == ConfigChangeType.ADD_WITNESS:
            witnesses[pid] = cc.address
        elif cc.type == ConfigChangeType.REMOVE_REPLICA:
            addresses.pop(pid, None)
            non_votings.pop(pid, None)
            witnesses.pop(pid, None)
            removed[pid] = True
        self.membership = Membership(
            config_change_id=entry_index,
            addresses=addresses,
            non_votings=non_votings,
            witnesses=witnesses,
            removed=removed,
        )
        return True
