"""CLI: ``python -m dragonboat_tpu.analysis [--baseline F] [paths...]``
(raftlint) or ``python -m dragonboat_tpu.analysis --jax [--baseline F]``
(the device-plane program auditor, docs/ANALYSIS.md)."""
import sys

argv = sys.argv[1:]
if "--jax" in argv:
    argv.remove("--jax")
    from .jaxcheck import main as _jax_main

    sys.exit(_jax_main(argv))

from .raftlint import main

sys.exit(main(argv))
