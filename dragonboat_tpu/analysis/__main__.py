"""CLI: ``python -m dragonboat_tpu.analysis [--baseline F] [paths...]``
(raftlint), ``python -m dragonboat_tpu.analysis --jax [--baseline F]``
(the device-plane program auditor) or ``--wire [--baseline F]
[--update-goldens]`` (the wire-compat auditor, docs/ANALYSIS.md)."""
import sys

argv = sys.argv[1:]
if "--jax" in argv:
    argv.remove("--jax")
    from .jaxcheck import main as _jax_main

    sys.exit(_jax_main(argv))

if "--wire" in argv:
    argv.remove("--wire")
    from .wirecheck import main as _wire_main

    sys.exit(_wire_main(argv))

from .raftlint import main

sys.exit(main(argv))
