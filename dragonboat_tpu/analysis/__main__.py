"""CLI: ``python -m dragonboat_tpu.analysis [--baseline F] [paths...]``."""
import sys

from .raftlint import main

sys.exit(main())
