"""raftlint: the project-native AST linter (stdlib ``ast``, no deps).

Rules (ids are stable — baseline entries and ignore comments key on them):

``guarded-by``
    A field whose defining assignment carries ``# guarded-by: <lock>``
    may only be accessed (read or write) via ``self.<field>`` inside a
    lexical ``with self.<lock>:`` block.  The function containing the
    defining assignment (normally ``__init__``) is exempt — state is
    unpublished there.  A ``def`` line carrying ``# guarded-by: <lock>``
    declares the whole function runs with the lock already held
    (callees of locked sections, e.g. ``_gc_extra``).

``block-under-lock``
    No potentially-unbounded blocking call lexically inside a ``with
    <lock>:`` body: ``.put(...)`` without a timeout/``block=False``
    (the exact shape of the PR 4 EventFanout close deadlock),
    zero-argument ``.get()`` (queue get; ``dict.get`` always takes a
    key), zero-argument ``.join()`` (thread join; ``str.join`` takes an
    iterable), ``time.sleep``, and socket ops (connect/accept/recv/
    send/sendall/recvfrom/sendto).  ``Condition.wait`` is fine — it
    releases the lock.

``determinism``
    The determinism plane (``faults.py``, ``balance/planner.py`` — the
    modules whose byte-deterministic event logs and seeded schedules
    the chaos/audit harnesses replay) must not read wall clocks or
    global rng: ``time.time()`` and module-level ``random.*`` calls are
    banned.  Allowed indirections: ``random.Random(seed)`` /
    ``random.SystemRandom`` construction, methods on rng instances,
    ``time.monotonic`` (deadlines, not identity) and ``time.sleep``.

``width-64``
    Codec modules (wire/tan/kvlogdb/snapshotio/gossip) pack protocol
    integers as uint64; every value feeding a ``Q`` slot of a
    ``struct`` pack must be masked ``& MASK64`` (docs/PARITY.md 64-bit
    policy) so encode wraps like the reference's uint64 instead of
    raising ``struct.error`` mid-persist.  Literals and ``len(...)``
    are exempt.

``host-sync``
    The device-plane modules (``ops/kernel.py``, ``ops/route.py`` —
    "pure int32 math, no host round-trips") must not force a
    device->host sync or a trace-time concretization: ``.item()``,
    ``int(...)``/``float(...)`` and ``np.asarray(...)``/``np.array(...)``
    applied to values are banned (each sync costs ~100-214 ms on a
    remote-device link, docs/BENCH_NOTES_r05.md).  Static facts are
    exempt: literals, ``len(...)`` and anything reading ``.shape`` /
    ``.ndim`` / ``.size`` / ``.dtype``.  A ``# raftlint:
    ignore[host-sync] <reason>`` on a ``def`` line exempts that whole
    function (the documented host-side helpers, e.g. the
    ``build_route_tables`` numpy precompute).

``gateway-hot``
    In ``gateway/`` modules, a function whose ``def`` line carries a
    ``# gateway-hot`` comment is a declared per-request READ path
    (RoutingCache.lookup and friends): it must not acquire anything —
    no ``with <lock>:`` and no ``.acquire()``.  The sanctioned shape is
    the snapshot read (grab a copy-on-write dict/tuple in one attribute
    load; writers swap a fresh object under their own lock), the same
    discipline as ``metrics.export_text`` — a per-request lock on the
    routing table would serialize every client of every shard through
    one mutex.

``host-loop``
    In the host-plane modules (``ops/colocated.py``, ``ops/engine.py``,
    ``ops/hostplane.py``), a function whose ``def`` line carries a
    ``# hostplane-hot`` comment is a declared array-at-once pass over
    ALL rows of a generation: ``for`` statements and comprehensions
    are banned inside it — per-row Python in the plan/merge stages is
    exactly what the r6 vectorization removed (t_plan 887 s +
    t_updates 538 s of a 2,731 s 50k-shard election at 250k rows,
    docs/BENCH_NOTES_r05.md) and must not rot back in; the r9
    update-lane assembly/sync functions (plan_update_sync and friends,
    ISSUE 13) carry the same marker.  A ``#
    raftlint: ignore[host-loop] <reason>`` on the ``def`` line (or on
    a pure-comment line directly above it) exempts a whole function —
    the documented scalar fallbacks and parity oracles (``*_scalar``
    twins in ops/hostplane.py).

``mesh-loop``
    The multi-chip launch path (functions marked ``# mesh-hot`` in the
    ops/ modules — the shard_map wrappers and their callers,
    docs/MULTICHIP.md) must stay free of per-device host work: the
    whole point of the sharded entry points is ONE dispatch for all
    chips, so a Python loop over ``jax.devices()``/``mesh.devices``
    or a ``jax.device_put``/``jax.device_get`` inside them re-opens
    the per-device host hop the collective lane exists to remove.
    Trace-time loops over static ranges (ring-shift unrolls) are fine.

``sync-budget``
    In the colocated launch path (``ops/colocated.py``,
    ``ops/engine.py``), a function whose ``def`` line carries a
    ``# sync-hot`` comment is a declared member of the launch
    pipeline's sync budget: every device->host round trip there costs
    ~100-214 ms of tunnel latency regardless of size and sequential
    syncs do not pipeline (docs/BENCH_NOTES_r05.md), so the budget is
    ONE commit-proving readback per generation (the split head/detail
    blob, requested at dispatch and collected at merge).  Bare
    ``np.asarray(<device value>)``, ``jax.device_get(...)`` and
    zero-arg ``.item()`` are banned inside such functions; the
    sanctioned readbacks (the blob collect, the documented fallback
    two-sync gather, debug-gated probes) carry a point
    ``# raftlint: ignore[sync-budget] <reason>``, as do host-built
    numpy conversions that never touch a device value.

``stream-read``
    The snapshot streaming path (``transport/chunk.py``,
    ``storage/snapshotter.py``, ``storage/snapshotio.py``,
    ``bigstate/``, ``tools.py``) exists so GB-scale state never
    materializes in memory: a zero-argument ``.read()`` buffers a whole
    stream and silently re-introduces the old whole-blob transfer.
    Every read must pass a size (bounded slice).  Deliberate whole-blob
    reads of small metadata carry a ``# raftlint: ignore[stream-read]
    <reason>``.

``obs-bound``
    The fleet-scope obs plane (``obs/fleetscope.py``,
    ``gateway/rpc.py``) answers ring-slice queries over the wire: a
    ``.tail(...)`` / ``.finished_tail(...)`` / ``.recorder_tail(...)``
    / ``.trace_spans(...)`` call without an explicit ``limit=`` keyword
    is an unbounded reply payload — one busy ring away from an
    8MB-frame teardown (docs/OBSERVABILITY.md "Fleet scope").

``import-hot``
    No function-level imports in the hot modules (``node.py``,
    ``request.py``, ``engine/``): a first call on the step/apply path
    must not pay an import-lock round trip.

``bare-except``
    No ``except:`` — it swallows KeyboardInterrupt/SystemExit.  The
    project idiom for intentional breadth is ``except Exception:`` with
    a ``# noqa: BLE001`` note.

``thread-discipline``
    Every ``threading.Thread(...)`` must pass ``name=`` (leak reports
    and timelines are useless full of ``Thread-12``) and an explicit
    ``daemon=`` (forcing the author to choose daemon-or-joined).

Point suppression: ``# raftlint: ignore[rule-id] <reason>`` on the
finding's line or on the first line of its enclosing statement.
Pre-existing accepted findings live in ``analysis/baseline.txt`` as
``<path> <rule> <count>`` lines; the gate fails only when a
(file, rule) count exceeds its baseline — zero new findings.
"""
from __future__ import annotations

import ast
import os
import re
import sys
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

GUARDED_RE = re.compile(r"#.*guarded-by:\s*([A-Za-z_][A-Za-z0-9_]*)")
IGNORE_RE = re.compile(r"#\s*raftlint:\s*ignore\[([a-z0-9-]+(?:\s*,\s*[a-z0-9-]+)*)\]")

MASK64 = 0xFFFFFFFFFFFFFFFF
MASK64_NAMES = {"MASK64", "_M64", "M64"}

# rule scoping (matched as posix-relpath suffixes/prefixes)
HOT_IMPORT_MODULES = (
    "dragonboat_tpu/node.py",
    "dragonboat_tpu/request.py",
    "dragonboat_tpu/engine/",
)
DETERMINISM_MODULES = (
    "dragonboat_tpu/faults.py",
    "dragonboat_tpu/balance/planner.py",
    # the production-day schedule builder: DayPlan.describe() is the
    # day's byte-determinism contract (docs/SCENARIO.md)
    "dragonboat_tpu/scenario/plan.py",
)
WIDTH_MODULES = (
    "dragonboat_tpu/transport/wire.py",
    "dragonboat_tpu/transport/gossip.py",
    "dragonboat_tpu/storage/tan.py",
    "dragonboat_tpu/storage/kvlogdb.py",
    "dragonboat_tpu/storage/snapshotio.py",
    # codec modules grown after the original rule list froze
    # (PR 20 wirecheck sweep): resume frames, rpc value/stats,
    # bigstate checkpoint/WAL records, journal framing, kvstore blocks
    "dragonboat_tpu/transport/tcp.py",
    "dragonboat_tpu/gateway/rpc.py",
    "dragonboat_tpu/bigstate/ondisk.py",
    "dragonboat_tpu/storage/journal.py",
    "dragonboat_tpu/storage/kvstore.py",
)
# the pure-device modules: host syncs are banned outright (engine.py /
# colocated.py legitimately sync — that is where launches read back)
HOST_SYNC_MODULES = (
    "dragonboat_tpu/ops/kernel.py",
    "dragonboat_tpu/ops/route.py",
)
# the snapshot streaming path: bounded reads only (docs/BIGSTATE.md)
STREAM_READ_MODULES = (
    "dragonboat_tpu/transport/chunk.py",
    "dragonboat_tpu/storage/snapshotter.py",
    "dragonboat_tpu/storage/snapshotio.py",
    "dragonboat_tpu/bigstate/",
    "dragonboat_tpu/tools.py",
)
# the serving front plane: `# gateway-hot` functions are lock-free
# snapshot-read paths (docs/GATEWAY.md "Routing")
GATEWAY_MODULES = ("dragonboat_tpu/gateway/",)
GATEWAY_HOT_RE = re.compile(r"#\s*gateway-hot\b")

# the colocated host plane: `# hostplane-hot` functions are
# array-at-once passes — no for-over-rows (docs/ANALYSIS.md).
# ops/engine.py joined for the ISSUE-13 update-lane assembly/sync
# functions (the base engine's merge tail shares the lane machinery).
HOSTPLANE_MODULES = (
    "dragonboat_tpu/ops/colocated.py",
    "dragonboat_tpu/ops/engine.py",
    "dragonboat_tpu/ops/hostplane.py",
)
HOSTPLANE_HOT_RE = re.compile(r"#\s*hostplane-hot\b")

# the colocated launch path: `# sync-hot` functions live inside the
# one-readback-per-generation sync budget (docs/BENCH_NOTES_r07.md)
SYNC_BUDGET_MODULES = (
    "dragonboat_tpu/ops/colocated.py",
    "dragonboat_tpu/ops/engine.py",
)
SYNC_HOT_RE = re.compile(r"#\s*sync-hot\b")

# the multi-chip launch path: `# mesh-hot` functions dispatch ONE
# program for every chip — no per-device Python (docs/MULTICHIP.md)
MESH_MODULES = (
    "dragonboat_tpu/ops/kernel.py",
    "dragonboat_tpu/ops/route.py",
    "dragonboat_tpu/ops/engine.py",
    "dragonboat_tpu/ops/colocated.py",
)
MESH_HOT_RE = re.compile(r"#\s*mesh-hot\b")

# the fleet-scope obs plane: every obs reply slices its ring with an
# EXPLICIT limit (docs/OBSERVABILITY.md "Fleet scope")
OBS_REPLY_MODULES = (
    "dragonboat_tpu/obs/fleetscope.py",
    "dragonboat_tpu/gateway/rpc.py",
)
_OBS_TAIL_METHODS = {"tail", "finished_tail", "recorder_tail",
                     "trace_spans"}

# attributes whose read is a static (trace-time, host-free) fact
_STATIC_FACT_ATTRS = {"shape", "ndim", "size", "dtype"}
_NUMPY_ALIASES = {"np", "numpy", "_np"}

BLOCKING_SOCKET_METHODS = {
    "connect", "accept", "recv", "send", "sendall", "recvfrom", "sendto",
}
# names that make a `with X:` item count as a lock for block-under-lock:
# the FINAL underscore-segment must itself be a lock word — an
# unanchored `lock$` would swallow clock/block/unlock (review finding)
LOCKISH_RE = re.compile(r"(?:^|_)(?:lock|qlock|glock|mu|mutex)$")


@dataclass(frozen=True)
class Finding:
    path: str
    line: int
    rule: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line} {self.rule} {self.message}"


def _module_matches(relpath: str, scopes) -> bool:
    p = relpath.replace(os.sep, "/")
    for s in scopes:
        if s.endswith("/"):
            if f"/{s}" in f"/{p}" or p.startswith(s):
                return True
        elif p == s or p.endswith("/" + s) or p.endswith(s):
            return True
    return False


def _parse_q_slots(fmt: str) -> Optional[List[int]]:
    """Indices of pack() args that land in 64-bit ('Q'/'q') slots.
    Returns None for formats raftlint cannot map (e.g. 's' with counts,
    which consumes one arg per run)."""
    slots: List[int] = []
    arg_i = 0
    count = ""
    for ch in fmt:
        if ch in "<>=!@ ":
            continue
        if ch.isdigit():
            count += ch
            continue
        n = int(count) if count else 1
        count = ""
        if ch in "sp":
            # one arg regardless of count
            arg_i += 1
            continue
        if ch == "x":
            continue
        for _ in range(n):
            if ch in "Qq":
                slots.append(arg_i)
            arg_i += 1
    return slots


def _is_masked64(node: ast.AST) -> bool:
    """True for expressions the width rule accepts in a Q slot."""
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, ast.Call):
        f = node.func
        if isinstance(f, ast.Name) and f.id == "len":
            return True
        if isinstance(f, ast.Attribute) and f.attr == "crc32":
            return True
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.BitAnd):
        for side in (node.left, node.right):
            if isinstance(side, ast.Name) and side.id in MASK64_NAMES:
                return True
            if isinstance(side, ast.Attribute) and side.attr in MASK64_NAMES:
                return True
            if isinstance(side, ast.Constant) and side.value == MASK64:
                return True
    return False


class _Linter(ast.NodeVisitor):
    def __init__(self, relpath: str, source: str, tree: ast.Module):
        self.relpath = relpath.replace(os.sep, "/")
        self.lines = source.splitlines()
        self.tree = tree
        self.findings: List[Finding] = []
        # rule scoping resolved once
        self.check_imports = _module_matches(self.relpath, HOT_IMPORT_MODULES)
        self.check_determinism = _module_matches(
            self.relpath, DETERMINISM_MODULES
        )
        self.check_width = _module_matches(self.relpath, WIDTH_MODULES)
        self.check_host_sync = _module_matches(
            self.relpath, HOST_SYNC_MODULES
        )
        self.check_stream_read = _module_matches(
            self.relpath, STREAM_READ_MODULES
        )
        self.check_gateway = _module_matches(self.relpath, GATEWAY_MODULES)
        self.check_hostplane = _module_matches(
            self.relpath, HOSTPLANE_MODULES
        )
        self.check_sync_budget = _module_matches(
            self.relpath, SYNC_BUDGET_MODULES
        )
        self.check_mesh = _module_matches(self.relpath, MESH_MODULES)
        self.check_obs_bound = _module_matches(
            self.relpath, OBS_REPLY_MODULES
        )
        # count of enclosing `# gateway-hot` / `# hostplane-hot` /
        # `# sync-hot` functions (nested defs inside a hot function
        # inherit the discipline)
        self._hot_depth = 0
        self._hp_depth = 0
        self._sync_depth = 0
        self._mesh_depth = 0
        # file-wide guarded fields: attr -> (lock attr, defining func node)
        self.guarded: Dict[str, Tuple[str, Optional[ast.AST]]] = {}
        # module-level struct.Struct assignments: name -> Q slot indices
        self.structs: Dict[str, List[int]] = {}
        # walk state
        self._held: List[str] = []  # lock names currently held (lexically)
        # locks held specifically via `with self.<lock>:` — the only form
        # that satisfies guarded-by (holding ANOTHER object's same-named
        # lock is exactly the bug class the rule exists to catch)
        self._held_self: List[str] = []
        self._func_stack: List[ast.AST] = []  # enclosing function defs
        self._stmt_stack: List[int] = []  # enclosing statement linenos

    # -- plumbing ---------------------------------------------------------

    def _line(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    def _guard_annot(self, node: ast.AST) -> Optional[str]:
        """The guarded-by lock name annotated on a node's line, or on a
        pure-comment line directly above it."""
        m = GUARDED_RE.search(self._line(node.lineno))
        if m is None and self._line(node.lineno - 1).strip().startswith("#"):
            m = GUARDED_RE.search(self._line(node.lineno - 1))
        return m.group(1) if m else None

    def _suppressed(self, rule: str, lineno: int) -> bool:
        candidates = {lineno}
        if self._stmt_stack:
            candidates.add(self._stmt_stack[-1])
        # a pure-comment line directly above the finding/statement also
        # counts (the ignore-next-line style keeps code lines readable)
        for ln in list(candidates):
            if self._line(ln - 1).strip().startswith("#"):
                candidates.add(ln - 1)
        for ln in candidates:
            m = IGNORE_RE.search(self._line(ln))
            if m and rule in {r.strip() for r in m.group(1).split(",")}:
                return True
        return False

    def _emit(self, rule: str, lineno: int, message: str) -> None:
        if not self._suppressed(rule, lineno):
            self.findings.append(Finding(self.relpath, lineno, rule, message))

    # -- pass 1: collect annotations and struct tables --------------------

    def collect(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                lock = self._guard_annot(node)
                if lock:
                    targets = (
                        node.targets
                        if isinstance(node, ast.Assign)
                        else [node.target]
                    )
                    for t in targets:
                        if (
                            isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"
                        ):
                            self.guarded[t.attr] = (lock, None)
                if (
                    isinstance(node, ast.Assign)
                    and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and isinstance(node.value, ast.Call)
                    and isinstance(node.value.func, ast.Attribute)
                    and node.value.func.attr == "Struct"
                    and node.value.args
                    and isinstance(node.value.args[0], ast.Constant)
                    and isinstance(node.value.args[0].value, str)
                ):
                    slots = _parse_q_slots(node.value.args[0].value)
                    if slots:
                        self.structs[node.targets[0].id] = slots
        # resolve each guarded field's defining function (the function
        # whose body contains the annotated assignment)
        for func in ast.walk(self.tree):
            if not isinstance(func, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for node in ast.walk(func):
                if isinstance(node, (ast.Assign, ast.AnnAssign)):
                    if self._guard_annot(node) is None:
                        continue
                    targets = (
                        node.targets
                        if isinstance(node, ast.Assign)
                        else [node.target]
                    )
                    for t in targets:
                        if (
                            isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"
                            and t.attr in self.guarded
                            and self.guarded[t.attr][1] is None
                        ):
                            self.guarded[t.attr] = (
                                self.guarded[t.attr][0],
                                func,
                            )

    # -- pass 2: the walk -------------------------------------------------

    def run(self) -> List[Finding]:
        self.collect()
        self.visit(self.tree)
        return self.findings

    def visit(self, node: ast.AST) -> None:
        pushed_stmt = False
        if isinstance(node, ast.stmt):
            self._stmt_stack.append(node.lineno)
            pushed_stmt = True
        try:
            super().visit(node)
        finally:
            if pushed_stmt:
                self._stmt_stack.pop()

    # ---- functions: reset lexical lock context, track nesting ----------

    def _visit_func(self, node) -> None:
        held, self._held = self._held, []
        held_self, self._held_self = self._held_self, []
        # a `# guarded-by: <lock>` on the def line declares the function
        # runs with the lock already held (the caller's self.<lock>)
        m = GUARDED_RE.search(self._line(node.lineno))
        if m:
            self._held.append(m.group(1))
            self._held_self.append(m.group(1))
        hot = self.check_gateway and bool(
            GATEWAY_HOT_RE.search(self._line(node.lineno))
        )
        if hot:
            self._hot_depth += 1
        hp = self.check_hostplane and bool(
            HOSTPLANE_HOT_RE.search(self._line(node.lineno))
        )
        if hp:
            self._hp_depth += 1
        sh = self.check_sync_budget and bool(
            SYNC_HOT_RE.search(self._line(node.lineno))
        )
        if sh:
            self._sync_depth += 1
        mh = self.check_mesh and bool(
            MESH_HOT_RE.search(self._line(node.lineno))
        )
        if mh:
            self._mesh_depth += 1
        self._func_stack.append(node)
        try:
            self.generic_visit(node)
        finally:
            self._func_stack.pop()
            self._held = held
            self._held_self = held_self
            if hot:
                self._hot_depth -= 1
            if hp:
                self._hp_depth -= 1
            if sh:
                self._sync_depth -= 1
            if mh:
                self._mesh_depth -= 1

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        self._visit_func(node)

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        self._visit_func(node)

    def visit_Lambda(self, node: ast.Lambda) -> None:
        held, self._held = self._held, []
        held_self, self._held_self = self._held_self, []
        try:
            self.generic_visit(node)
        finally:
            self._held = held
            self._held_self = held_self

    # ---- with: enter/exit lock scopes ----------------------------------

    @staticmethod
    def _lock_name(expr: ast.AST) -> Optional[str]:
        """The lock attr/name of a with-item, or None if not lock-like."""
        target = expr
        if isinstance(target, ast.Call):
            return None  # with open(...) etc.
        if isinstance(target, ast.Attribute):
            name = target.attr
        elif isinstance(target, ast.Name):
            name = target.id
        else:
            return None
        return name if LOCKISH_RE.search(name) else None

    def visit_With(self, node: ast.With) -> None:
        entered: List[str] = []
        entered_self: List[str] = []
        for item in node.items:
            expr = item.context_expr
            ln = self._lock_name(expr)
            if ln is not None:
                if self._hot_depth:
                    self._emit(
                        "gateway-hot",
                        node.lineno,
                        f"`with {ln}:` inside a # gateway-hot read path "
                        "(snapshot-read the copy-on-write table instead; "
                        "docs/GATEWAY.md)",
                    )
                entered.append(ln)
                if (
                    isinstance(expr, ast.Attribute)
                    and isinstance(expr.value, ast.Name)
                    and expr.value.id == "self"
                ):
                    entered_self.append(ln)
        self._held.extend(entered)
        self._held_self.extend(entered_self)
        try:
            self.generic_visit(node)
        finally:
            for _ in entered:
                self._held.pop()
            for _ in entered_self:
                self._held_self.pop()

    # ---- guarded-by -----------------------------------------------------

    def visit_Attribute(self, node: ast.Attribute) -> None:
        if (
            isinstance(node.value, ast.Name)
            and node.value.id == "self"
            and node.attr in self.guarded
        ):
            lock, def_func = self.guarded[node.attr]
            in_def_func = def_func is not None and any(
                f is def_func for f in self._func_stack
            )
            if not in_def_func and lock not in self._held_self:
                self._emit(
                    "guarded-by",
                    node.lineno,
                    f"self.{node.attr} accessed outside `with self.{lock}:`",
                )
        self.generic_visit(node)

    # ---- block-under-lock + determinism + width (all calls) ------------

    def visit_Call(self, node: ast.Call) -> None:
        if self._hot_depth and isinstance(node.func, ast.Attribute) and (
            node.func.attr == "acquire"
        ):
            self._emit(
                "gateway-hot",
                node.lineno,
                ".acquire() inside a # gateway-hot read path "
                "(snapshot-read discipline; docs/GATEWAY.md)",
            )
        if self._held:
            self._check_blocking(node)
        if self.check_determinism:
            self._check_determinism(node)
        if self.check_width:
            self._check_width(node)
        if self.check_host_sync:
            self._check_host_sync(node)
        if self.check_stream_read:
            self._check_stream_read(node)
        if self.check_obs_bound:
            self._check_obs_bound(node)
        if self._sync_depth:
            self._check_sync_budget(node)
        if self._mesh_depth:
            self._check_mesh_call(node)
        self._check_thread(node)
        self.generic_visit(node)

    def _kw(self, node: ast.Call, name: str) -> Optional[ast.AST]:
        for kw in node.keywords:
            if kw.arg == name:
                return kw.value
        return None

    def _check_blocking(self, node: ast.Call) -> None:
        f = node.func
        if not isinstance(f, ast.Attribute):
            return
        meth = f.attr
        lineno = node.lineno
        if meth == "put" and len(node.args) == 1:
            # one positional arg = the queue.put(item) shape; kv-store
            # put(key, value) is a dict write, not a blocking call
            blk = self._kw(node, "block")
            if (
                self._kw(node, "timeout") is None
                and not (isinstance(blk, ast.Constant) and blk.value is False)
            ):
                self._emit(
                    "block-under-lock",
                    lineno,
                    "blocking .put() under a held lock (use put_nowait or "
                    "a timeout; the EventFanout close deadlock shape)",
                )
        elif meth == "get" and not node.args and not node.keywords:
            self._emit(
                "block-under-lock",
                lineno,
                "blocking zero-arg .get() under a held lock",
            )
        elif meth == "join" and not node.args and self._kw(node, "timeout") is None:
            self._emit(
                "block-under-lock",
                lineno,
                "unbounded .join() under a held lock",
            )
        elif meth == "sleep" and isinstance(f.value, ast.Name) and (
            f.value.id in ("time", "_time")
        ):
            self._emit(
                "block-under-lock", lineno, "time.sleep under a held lock"
            )
        elif meth in BLOCKING_SOCKET_METHODS and isinstance(
            f.value, (ast.Name, ast.Attribute)
        ):
            recv = f.value.attr if isinstance(f.value, ast.Attribute) else f.value.id
            if "sock" in recv or recv == "s":
                self._emit(
                    "block-under-lock",
                    lineno,
                    f"socket .{meth}() under a held lock",
                )

    @staticmethod
    def _is_static_fact(node: ast.AST) -> bool:
        """Expressions that concretize without touching device data:
        literals, len(...), and anything whose value flows from a
        .shape/.ndim/.size/.dtype read (e.g. int(x.shape[0]))."""
        if all(
            isinstance(
                n,
                (ast.Constant, ast.BinOp, ast.UnaryOp, ast.operator,
                 ast.unaryop),
            )
            for n in ast.walk(node)
        ):
            return True  # constant arithmetic, e.g. int(2**31 - 1)
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Name)
            and node.func.id == "len"
        ):
            return True
        return any(
            isinstance(n, ast.Attribute) and n.attr in _STATIC_FACT_ATTRS
            for n in ast.walk(node)
        )

    def _func_exempt(self, rule: str) -> bool:
        """A `# raftlint: ignore[<rule>] <reason>` on an enclosing def
        line — or on a pure-comment line directly above it (the same
        ignore-next-line style `_suppressed` accepts) — exempts the
        whole function: the documented host-side helpers living inside
        a device module (host-sync) and the documented scalar
        fallbacks / parity oracles of the host plane (host-loop).
        Decorated defs are also covered via the decorator lines."""
        for func in self._func_stack:
            lines = {func.lineno}
            if self._line(func.lineno - 1).strip().startswith("#"):
                lines.add(func.lineno - 1)
            for ln in lines:
                m = IGNORE_RE.search(self._line(ln))
                if m and rule in {
                    r.strip() for r in m.group(1).split(",")
                }:
                    return True
        return False

    def _host_sync_func_exempt(self) -> bool:
        return self._func_exempt("host-sync")

    def _check_host_sync(self, node: ast.Call) -> None:
        f = node.func
        hit = None
        if isinstance(f, ast.Attribute) and f.attr == "item" and not node.args:
            hit = ".item() forces a device->host sync"
        elif (
            isinstance(f, ast.Name)
            and f.id in ("int", "float")
            and len(node.args) == 1
            and not self._is_static_fact(node.args[0])
        ):
            hit = (
                f"{f.id}(...) concretizes a (potential) device value — "
                "a host sync on the device plane"
            )
        elif (
            isinstance(f, ast.Attribute)
            and f.attr in ("asarray", "array")
            and isinstance(f.value, ast.Name)
            and f.value.id in _NUMPY_ALIASES
        ):
            hit = f"np.{f.attr}(...) materializes a device value on host"
        if hit is None or self._host_sync_func_exempt():
            return
        self._emit(
            "host-sync",
            node.lineno,
            hit + " (~100-214 ms per sync on a remote link; "
            "docs/BENCH_NOTES_r05.md)",
        )

    def _check_sync_budget(self, node: ast.Call) -> None:
        """Bare device->host syncs inside a `# sync-hot` function (the
        colocated launch pipeline's one-readback-per-generation
        budget).  Each stray sync is ~100-214 ms of tunnel latency that
        defeats the double-buffered overlap — docs/BENCH_NOTES_r07.md."""
        f = node.func
        hit = None
        if (
            isinstance(f, ast.Attribute)
            and f.attr in ("asarray", "array")
            and isinstance(f.value, ast.Name)
            and f.value.id in _NUMPY_ALIASES
        ):
            hit = (
                f"bare np.{f.attr}(...) in the launch pipeline — a"
                " potential device readback outside the blob sync"
            )
        elif (
            isinstance(f, ast.Attribute)
            and f.attr == "device_get"
        ):
            hit = "jax.device_get(...) outside the annotated blob readback"
        elif (
            isinstance(f, ast.Attribute)
            and f.attr == "item"
            and not node.args
        ):
            hit = ".item() forces an extra device->host round trip"
        if hit is None or self._func_exempt("sync-budget"):
            return
        self._emit(
            "sync-budget",
            node.lineno,
            hit + " (~100-214 ms per sync on the tunnel; the launch "
            "budget is ONE commit-proving readback per generation — "
            "docs/BENCH_NOTES_r05.md sync-latency model)",
        )

    def _check_stream_read(self, node: ast.Call) -> None:
        f = node.func
        if (
            isinstance(f, ast.Attribute)
            and f.attr == "read"
            and not node.args
            and not node.keywords
        ):
            self._emit(
                "stream-read",
                node.lineno,
                "zero-argument .read() buffers a whole stream in memory "
                "(pass a bounded size; the streaming path must handle "
                "state larger than RAM — docs/BIGSTATE.md)",
            )

    def _check_obs_bound(self, node: ast.Call) -> None:
        f = node.func
        if (
            isinstance(f, ast.Attribute)
            and f.attr in _OBS_TAIL_METHODS
            and self._kw(node, "limit") is None
        ):
            self._emit(
                "obs-bound",
                node.lineno,
                f".{f.attr}() without an explicit limit= is an unbounded "
                "obs reply payload (every ring slice must be bounded — "
                "docs/OBSERVABILITY.md \"Fleet scope\")",
            )

    def _check_determinism(self, node: ast.Call) -> None:
        f = node.func
        if not isinstance(f, ast.Attribute) or not isinstance(f.value, ast.Name):
            return
        mod = f.value.id
        if mod in ("time", "_time") and f.attr == "time":
            self._emit(
                "determinism",
                node.lineno,
                "naked wall clock time.time() in the determinism plane "
                "(use the seeded schedule / time.monotonic deadlines)",
            )
        elif mod in ("random", "_random") and f.attr not in (
            "Random",
            "SystemRandom",
        ):
            self._emit(
                "determinism",
                node.lineno,
                f"global rng random.{f.attr}() in the determinism plane "
                "(use a seeded random.Random instance)",
            )

    def _check_width(self, node: ast.Call) -> None:
        f = node.func
        if not isinstance(f, ast.Attribute) or f.attr != "pack":
            return
        slots: Optional[List[int]] = None
        if isinstance(f.value, ast.Name):
            if f.value.id == "struct":
                if node.args and isinstance(node.args[0], ast.Constant) and (
                    isinstance(node.args[0].value, str)
                ):
                    slots = [
                        i + 1
                        for i in _parse_q_slots(node.args[0].value) or []
                    ]
            elif f.value.id in self.structs:
                slots = self.structs[f.value.id]
        if not slots:
            return
        for i in slots:
            if i < len(node.args) and not _is_masked64(node.args[i]):
                self._emit(
                    "width-64",
                    node.lineno,
                    "u64 pack of unmasked value (append `& MASK64`; "
                    "docs/PARITY.md 64-bit policy)",
                )

    # ---- host-loop (for-over-rows in # hostplane-hot functions) ---------

    def _check_host_loop(self, node: ast.AST, what: str) -> None:
        if not self._hp_depth or self._func_exempt("host-loop"):
            return
        self._emit(
            "host-loop",
            node.lineno,
            f"{what} inside a # hostplane-hot array pass (use numpy "
            "array ops over all rows; per-row Python is the t_plan/"
            "t_updates cost the r6 vectorization removed — "
            "docs/ANALYSIS.md)",
        )

    def visit_For(self, node: ast.For) -> None:
        self._check_host_loop(node, "`for` loop")
        self._check_mesh_loop(node)
        self.generic_visit(node)

    def visit_AsyncFor(self, node: ast.AsyncFor) -> None:
        self._check_host_loop(node, "`async for` loop")
        self.generic_visit(node)

    def visit_ListComp(self, node: ast.ListComp) -> None:
        self._check_host_loop(node, "list comprehension")
        self.generic_visit(node)

    def visit_SetComp(self, node: ast.SetComp) -> None:
        self._check_host_loop(node, "set comprehension")
        self.generic_visit(node)

    def visit_DictComp(self, node: ast.DictComp) -> None:
        self._check_host_loop(node, "dict comprehension")
        self.generic_visit(node)

    def visit_GeneratorExp(self, node: ast.GeneratorExp) -> None:
        self._check_host_loop(node, "generator expression")
        self.generic_visit(node)

    # ---- mesh-loop (per-device host work in # mesh-hot functions) -------

    @staticmethod
    def _mentions_devices(expr: ast.AST) -> bool:
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Attribute) and sub.attr in (
                "devices", "local_devices", "device_set",
            ):
                return True
            if isinstance(sub, ast.Name) and sub.id in (
                "devices", "local_devices",
            ):
                return True
        return False

    def _check_mesh_loop(self, node) -> None:
        if not self._mesh_depth or self._func_exempt("mesh-loop"):
            return
        if self._mentions_devices(node.iter):
            self._emit(
                "mesh-loop",
                node.lineno,
                "Python iteration over devices inside a # mesh-hot "
                "function — the sharded launch path dispatches ONE "
                "program for every chip (docs/MULTICHIP.md); per-device "
                "host loops re-open the host hop the collective lane "
                "removes",
            )

    def _check_mesh_call(self, node: ast.Call) -> None:
        if not self._mesh_depth or self._func_exempt("mesh-loop"):
            return
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in (
            "device_put", "device_get",
        ):
            self._emit(
                "mesh-loop",
                node.lineno,
                f"`{f.attr}` inside a # mesh-hot function — host<->device "
                "transfers belong outside the sharded launch path "
                "(docs/MULTICHIP.md; the transfer-free gate is also "
                "machine-checked by jaxcheck over the mesh entries)",
            )

    # ---- hygiene --------------------------------------------------------

    def _check_import(self, node) -> None:
        if self.check_imports and self._func_stack:
            self._emit(
                "import-hot",
                node.lineno,
                "function-level import in a hot module (hoist to module "
                "level; the step/apply path must not pay the import lock)",
            )

    def visit_Import(self, node: ast.Import) -> None:
        self._check_import(node)
        self.generic_visit(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        self._check_import(node)
        self.generic_visit(node)

    def visit_ExceptHandler(self, node: ast.ExceptHandler) -> None:
        if node.type is None:
            self._emit(
                "bare-except",
                node.lineno,
                "bare `except:` (catches KeyboardInterrupt/SystemExit; "
                "use `except Exception:` at most)",
            )
        self.generic_visit(node)

    def _check_thread(self, value: ast.Call) -> None:
        f = value.func
        is_thread = (
            isinstance(f, ast.Attribute)
            and f.attr == "Thread"
            and isinstance(f.value, ast.Name)
            and f.value.id == "threading"
        ) or (isinstance(f, ast.Name) and f.id == "Thread")
        if not is_thread:
            return
        kwargs = {kw.arg for kw in value.keywords}
        if "name" not in kwargs:
            self._emit(
                "thread-discipline",
                value.lineno,
                "thread started without name= (leak reports and timelines "
                "need named threads)",
            )
        if "daemon" not in kwargs:
            self._emit(
                "thread-discipline",
                value.lineno,
                "thread without explicit daemon= (choose daemon-or-joined)",
            )


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------
def lint_source(source: str, relpath: str) -> List[Finding]:
    """Lint one source blob as if it lived at ``relpath`` (fixtures use
    fake paths to trigger module-scoped rules)."""
    tree = ast.parse(source, filename=relpath)
    return _Linter(relpath, source, tree).run()


def _iter_py_files(paths) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = [
                    d for d in dirs if d != "__pycache__" and not d.startswith(".")
                ]
                out.extend(
                    os.path.join(root, f) for f in files if f.endswith(".py")
                )
        elif p.endswith(".py"):
            out.append(p)
    return sorted(out)


def lint_paths(paths) -> List[Finding]:
    findings: List[Finding] = []
    for path in _iter_py_files(paths):
        rel = os.path.relpath(path).replace(os.sep, "/")
        try:
            with open(path, "r", encoding="utf-8") as f:
                src = f.read()
            findings.extend(lint_source(src, rel))
        except SyntaxError as e:
            findings.append(
                Finding(rel, e.lineno or 0, "parse-error", str(e.msg))
            )
    return findings


def _counts(findings) -> Dict[Tuple[str, str], int]:
    out: Dict[Tuple[str, str], int] = {}
    for f in findings:
        out[(f.path, f.rule)] = out.get((f.path, f.rule), 0) + 1
    return out


def load_baseline(path: str) -> Dict[Tuple[str, str], int]:
    """``<path> <rule> <count>`` lines; '#' comments and blanks ignored."""
    out: Dict[Tuple[str, str], int] = {}
    if not os.path.exists(path):
        return out
    with open(path, "r", encoding="utf-8") as f:
        for raw in f:
            line = raw.split("#", 1)[0].strip()
            if not line:
                continue
            parts = line.split()
            if len(parts) != 3:
                raise ValueError(f"bad baseline line: {raw.rstrip()}")
            out[(parts[0], parts[1])] = int(parts[2])
    return out


def write_baseline(path: str, findings) -> None:
    counts = _counts(findings)
    with open(path, "w", encoding="utf-8") as f:
        f.write(
            "# raftlint baseline: accepted pre-existing findings as\n"
            "# `<path> <rule> <count>` — the gate fails only on counts\n"
            "# ABOVE these.  Shrink it whenever you clean a finding up;\n"
            "# never grow it to sneak new debt in.\n"
        )
        for (p, rule), n in sorted(counts.items()):
            f.write(f"{p} {rule} {n}\n")


def gate(findings, baseline: Dict[Tuple[str, str], int]):
    """(new_findings, stale_entries): findings beyond baseline counts, and
    baseline entries whose debt shrank (candidates for ratcheting down)."""
    counts = _counts(findings)
    new: List[Finding] = []
    for (path, rule), n in sorted(counts.items()):
        allowed = baseline.get((path, rule), 0)
        if n > allowed:
            per = [f for f in findings if f.path == path and f.rule == rule]
            # report the whole group: line numbers drift, so naming
            # exactly the "new" ones is guesswork — show all candidates
            new.extend(per)
    stale = [
        (path, rule, allowed, counts.get((path, rule), 0))
        for (path, rule), allowed in sorted(baseline.items())
        if counts.get((path, rule), 0) < allowed
    ]
    return new, stale


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="raftlint", description=__doc__.splitlines()[0]
    )
    ap.add_argument("paths", nargs="*", default=["dragonboat_tpu"])
    ap.add_argument("--baseline", default=None, help="baseline file to gate against")
    ap.add_argument(
        "--update-baseline",
        action="store_true",
        help="rewrite the baseline from the current findings and exit 0",
    )
    args = ap.parse_args(argv)

    findings = lint_paths(args.paths or ["dragonboat_tpu"])
    if args.update_baseline:
        if not args.baseline:
            ap.error("--update-baseline requires --baseline")
        write_baseline(args.baseline, findings)
        print(f"raftlint: baseline written ({len(findings)} findings)")
        return 0

    baseline = load_baseline(args.baseline) if args.baseline else {}
    new, stale = gate(findings, baseline)
    for f in new:
        print(f.render())
    for path, rule, allowed, now in stale:
        print(
            f"raftlint: note: baseline for {path} {rule} is {allowed}, "
            f"tree has {now} — ratchet it down",
            file=sys.stderr,
        )
    if new:
        print(
            f"raftlint: {len(new)} unbaselined finding(s) "
            f"({len(findings)} total, baseline covers "
            f"{sum(baseline.values())})",
            file=sys.stderr,
        )
        return 1
    print(
        f"raftlint: clean ({len(findings)} finding(s), all baselined)"
        if findings
        else "raftlint: clean"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
