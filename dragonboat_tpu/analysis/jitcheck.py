"""Env-gated recompile sentry (the dynamic half of the device audit).

jaxcheck traces every ops/ entry point ONCE with canonical shapes —
it cannot see drift that only exists at runtime: a shape that varies
launch-to-launch, a weak-typed scalar leaking into an operand, an
uncommitted array keying a second executable (jax keys compiled
programs on shape/dtype/weak-type/sharding/committed-ness of every
argument).  Each such retrace stalls a launch pipeline for seconds on
a remote device (the r5 mid-run-compile finding: commits arrived ~25 s
late), so the engines go to great lengths to pre-compile every shape
they will ever use (``VectorStepEngine._warm`` and the colocated
ladder-tier warm).  This module turns that effort into a checked
invariant:

* every engine ``_warm()`` calls :func:`mark_warm` (gated on
  ``ENABLED`` — one attribute load when off), snapshotting each
  registered entry point's jit trace-cache size
  (``fn._cache_size()``);
* :func:`retraces` reports every entry point whose cache GREW since
  the snapshot — i.e. something traced a new program after warmup;
* conftest wraps the engine-driven test modules (test_vector_engine,
  test_colocated) and fails any test that retraced, exactly the
  lockcheck pattern.

The switch is ``DRAGONBOAT_TPU_JITCHECK`` (same env-gate family as
``DRAGONBOAT_TPU_INVARIANTS`` / ``_LOCKCHECK``): off by default, free
when off.  See docs/ANALYSIS.md "Device-plane audit".
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

ENABLED = os.environ.get("DRAGONBOAT_TPU_JITCHECK", "0") not in ("", "0")


def enable(on: bool = True) -> None:
    """Programmatic switch (tests)."""
    global ENABLED
    ENABLED = on


def _cache_size(fn) -> int:
    get = getattr(fn, "_cache_size", None)
    return int(get()) if callable(get) else 0


class Sentry:
    """Trace-cache watcher over a (name, jitted fn) list.

    The default instance watches the full ops runtime registry; tests
    construct their own over fixture functions."""

    def __init__(self, entries=None):
        self._entries = entries
        self._snap: Optional[Dict[str, int]] = None

    def entries(self):
        if self._entries is not None:
            return self._entries
        from ..ops import registry  # lazy: breaks the ops<->analysis cycle

        return registry.runtime_entry_points()

    def snapshot(self) -> Dict[str, int]:
        return {name: _cache_size(fn) for name, fn in self.entries()}

    def mark(self) -> None:
        """Declare 'warmup is complete as of now'."""
        self._snap = self.snapshot()

    def retraces(self) -> List[Tuple[str, int, int]]:
        """(name, at_mark, now) for entries whose cache grew since the
        last mark; empty when never marked (nothing to compare)."""
        if self._snap is None:
            return []
        now = self.snapshot()
        return [
            (name, before, now[name])
            for name, before in self._snap.items()
            if now.get(name, before) > before
        ]


_DEFAULT = Sentry()


def mark_warm() -> None:
    """Called by the engines at the end of ``_warm()`` (and by the
    conftest wrapper at test setup) — resets the post-warmup baseline."""
    _DEFAULT.mark()


def retraces() -> List[Tuple[str, int, int]]:
    return _DEFAULT.retraces()


def format_retraces(rows) -> str:
    return "\n".join(
        f"  {name}: trace cache {before} -> {now} (post-warmup retrace)"
        for name, before, now in rows
    )
