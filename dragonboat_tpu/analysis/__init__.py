"""Correctness tooling: static AST linting + dynamic lock-order witness.

reference: upstream dragonboat keeps its 40+-goroutine-per-host system
honest with the Go race detector, build-tag-gated ``internal/invariants``
checks and monkeytest CI [U].  Python has none of those out of the box;
this package is the port's equivalent discipline, grown after three
concurrency bugs in a row were found only by hand in review (the
EventFanout close deadlock, the ``drain_ticks_only`` missing ``_qlock``,
the ``Span.end`` double-fire race):

* :mod:`.raftlint` — a stdlib-``ast`` linter with project-specific rules
  (guarded-by field discipline, no blocking calls under a lock,
  determinism-plane clock/rng bans, the 64-bit pack-width policy,
  import/thread hygiene).  Gate: zero findings not recorded in
  ``analysis/baseline.txt`` (``scripts/lint.sh``, wired into tier-1).
* :mod:`.lockcheck` — an env-gated (``DRAGONBOAT_TPU_LOCKCHECK``)
  runtime witness wrapping the project's Lock/RLock constructors into a
  global lock-order graph: any cycle (potential deadlock) is reported
  with both witness stacks, and waits past a threshold while another
  lock is held are flagged.  conftest enables it for the chaos/fault
  test modules.
* :mod:`.jaxcheck` — the device-plane program auditor: traces every
  jitted entry point in ``ops/`` (``ops/registry.py``) and checks the
  jaxprs/lowerings against policy (int32 dtype discipline, no host-
  transfer primitives, real buffer donation, G-last internal layout,
  registry completeness).  Gate: zero findings not recorded in
  ``analysis/jax_baseline.txt`` (``python -m dragonboat_tpu.analysis
  --jax``, wired into scripts/lint.sh).
* :mod:`.jitcheck` — the dynamic half of the device audit: an
  env-gated (``DRAGONBOAT_TPU_JITCHECK``) recompile sentry that
  snapshots each entry point's jit trace-cache size at engine warmup
  and reports post-warmup retraces (the mid-run-compile pipeline
  stalls static tracing with fixed shapes cannot see).

See docs/ANALYSIS.md for the rule catalog and workflows.
"""
from .raftlint import Finding, lint_paths, lint_source, load_baseline  # noqa: F401
from . import jitcheck  # noqa: F401
from . import lockcheck  # noqa: F401
