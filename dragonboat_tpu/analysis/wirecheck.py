"""wirecheck — the wire-plane compat auditor (docs/ANALYSIS.md).

Four checks over the codec registry (``analysis/wire_registry.py``),
following the raftlint/jaxcheck discipline (per-(path,rule) findings,
baseline ratchet, ``python -m dragonboat_tpu.analysis --wire``):

``golden-drift`` / ``golden-missing``
    The checked-in byte corpus (``tests/wire_goldens/``) must equal the
    registry's canonical sample bytes for every codec x layout.  Any
    accidental layout change is a red gate NAMING the frame; deliberate
    changes regenerate the corpus via ``--update-goldens`` (and show up
    in the diff as golden-file churn, which review can then interrogate
    as a compat break).

``skew-matrix``
    The CURRENT decoder must read every stored golden (old bytes keep
    decoding), must REJECT a future-layout frame with the codec's own
    narrow error type (never a silent field shift), and flag-gated
    extensions (trace byte, stats read-path trailer, empty obs query)
    must decode as v0 when unstamped — the registry's ``checks``.

``fuzz-escape`` / ``fuzz-alloc``
    A seeded structure-aware mutator (truncation, bit flips,
    length-field inflation, 32-bit field corruption, version bumps,
    byte insert/delete) drives N mutations per registered decoder.
    Every escape must be the codec's narrow error type — no bare
    struct.error, KeyError or MemoryError surfacing to the transport
    loop — and per-decode allocation must stay bounded (tracemalloc
    peak <= a proportional allowance + the entry's declared slack),
    which is what catches decompression bombs.

``unregistered-codec`` / ``decode-bound``
    AST rot guards: any ``encode_*``/``decode_*`` def or
    ``KIND_*``/``K_*``/``*_BIN_VER``/``*_VERSION`` constant in a
    covered module that no registry entry claims is a finding (the
    jaxcheck unregistered-jit discipline), and every registered
    decoder's source must bound its length-prefixed reads — parse
    through the bounded ``_R`` reader, reference an explicit ``MAX_*``
    cap or a ``len()`` guard, and never call bare ``zlib.decompress``.
"""
from __future__ import annotations

import argparse
import ast
import os
import random
import re
import struct
import sys
import tracemalloc
import zlib
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from . import wire_registry
from .raftlint import Finding, gate, load_baseline, write_baseline
from .wire_registry import REGISTRY, SCAN_MODULES, CodecEntry

REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
GOLDENS_DIR = os.path.join(REPO_ROOT, "tests", "wire_goldens")
GOLDENS_REL = "tests/wire_goldens"

FUZZ_SEED = 0xD1A60  # deterministic: same corpus -> same verdict
DEFAULT_FUZZ_N = 500

# names the rot guard treats as codec surface when defined in a scanned
# module (assignment targets for constants, def names for functions)
_FN_PAT = re.compile(r"^(encode|decode)_[A-Za-z0-9_]+$")
_CONST_PAT = re.compile(
    r"(^KIND_[A-Z0-9_]+$|^K_[A-Z][A-Z0-9_]*$|_BIN_VER$|_VERSION$|^VERSION$)"
)

# decode-bound: calls that read attacker-sized data, and the bound
# references that license them
_RAW_READ_ATTRS = {"take", "read", "unpack", "unpack_from", "from_bytes",
                   "decompress", "ljust", "zfill"}
_BOUND_HINT = re.compile(r"MAX|_MAX|BOUND")


def golden_name(entry_name: str, label: str) -> str:
    return f"{entry_name}__{label}.bin"


# ---------------------------------------------------------------------------
# goldens
# ---------------------------------------------------------------------------
def check_goldens(
    entries: Sequence[CodecEntry],
    goldens_dir: str,
    update: bool = False,
) -> List[Finding]:
    findings: List[Finding] = []
    if update:
        os.makedirs(goldens_dir, exist_ok=True)
    for e in entries:
        for label, builder in e.samples.items():
            built = builder()
            fname = golden_name(e.name, label)
            path = os.path.join(goldens_dir, fname)
            rel = f"{GOLDENS_REL}/{fname}"
            if update:
                with open(path, "wb") as f:
                    f.write(built)
                continue
            if not os.path.exists(path):
                findings.append(Finding(
                    rel, 1, "golden-missing",
                    f"codec {e.name} layout {label} has no golden "
                    f"(regenerate via --update-goldens)",
                ))
                continue
            with open(path, "rb") as f:
                stored = f.read()
            if stored != built:
                findings.append(Finding(
                    rel, 1, "golden-drift",
                    f"codec {e.name} layout {label}: encoder output no "
                    f"longer matches the checked-in golden "
                    f"({len(built)}B built vs {len(stored)}B stored) — "
                    f"a wire-layout change; if deliberate, regenerate "
                    f"via --update-goldens and call it out as a compat "
                    f"break",
                ))
    return findings


def _golden_bytes(e: CodecEntry, label: str, goldens_dir: str) -> bytes:
    """The stored golden when present (the corpus is the source of
    truth), else the builder output (first run / --update-goldens)."""
    path = os.path.join(goldens_dir, golden_name(e.name, label))
    if os.path.exists(path):
        with open(path, "rb") as f:
            return f.read()
    return e.samples[label]()


# ---------------------------------------------------------------------------
# skew matrix
# ---------------------------------------------------------------------------
def check_skew(
    entries: Sequence[CodecEntry], goldens_dir: str
) -> List[Finding]:
    findings: List[Finding] = []
    for e in entries:
        # old-bytes-decode: every stored layout must still decode
        for label in e.samples:
            data = _golden_bytes(e, label, goldens_dir)
            try:
                out = e.decode(data)
            except Exception as ex:  # noqa: BLE001 - audit boundary
                findings.append(Finding(
                    e.module, 1, "skew-matrix",
                    f"codec {e.name}: current decoder failed on the "
                    f"{label} golden: {type(ex).__name__}: {ex}",
                ))
                continue
            if e.none_on_error and out is None:
                findings.append(Finding(
                    e.module, 1, "skew-matrix",
                    f"codec {e.name}: decoder returned None for the "
                    f"well-formed {label} golden",
                ))
        # future-version-reject: the narrow type, never a field shift
        if e.future is not None:
            data = e.future()
            try:
                out = e.decode(data)
            except Exception as ex:  # noqa: BLE001 - audit boundary
                if not isinstance(ex, e.errors):
                    findings.append(Finding(
                        e.module, 1, "skew-matrix",
                        f"codec {e.name}: future-layout frame raised "
                        f"{type(ex).__name__} instead of the codec's "
                        f"narrow error type",
                    ))
            else:
                if not (e.none_on_error and out is None):
                    findings.append(Finding(
                        e.module, 1, "skew-matrix",
                        f"codec {e.name}: future-layout frame DECODED "
                        f"instead of being rejected — silent field "
                        f"shift hazard",
                    ))
        # flag-gated extension invariants
        for check in e.checks:
            msg = check()
            if msg:
                findings.append(Finding(
                    e.module, 1, "skew-matrix", f"codec {e.name}: {msg}"
                ))
    return findings


# ---------------------------------------------------------------------------
# deterministic structure-aware fuzz
# ---------------------------------------------------------------------------
def _mutate(rng: random.Random, base: bytes) -> bytes:
    b = bytearray(base)
    op = rng.randrange(6)
    if op == 0 and b:  # truncation
        return bytes(b[: rng.randrange(len(b))])
    if op == 1 and b:  # bit flip
        i = rng.randrange(len(b))
        b[i] ^= 1 << rng.randrange(8)
        return bytes(b)
    if op == 2 and len(b) >= 4:  # length-field inflation
        i = rng.randrange(len(b) - 3)
        struct.pack_into(
            "<I", b, i, rng.choice((0xFFFFFFFF, 0x7FFFFFFF, 1 << 24))
        )
        return bytes(b)
    if op == 3 and len(b) >= 4:  # 32-bit field corruption (crc, counts)
        i = rng.randrange(len(b) - 3)
        struct.pack_into("<I", b, i, rng.getrandbits(32))
        return bytes(b)
    if op == 4 and len(b) >= 4:  # version bump at the frame head
        struct.pack_into("<I", b, 0, rng.randrange(2, 1 << 16))
        return bytes(b)
    # byte insert/delete (framing shift)
    i = rng.randrange(len(b) + 1)
    if rng.random() < 0.5 and b:
        del b[min(i, len(b) - 1)]
    else:
        b.insert(i, rng.getrandbits(8))
    return bytes(b)


def check_fuzz(
    entries: Sequence[CodecEntry],
    goldens_dir: str,
    n: int = DEFAULT_FUZZ_N,
) -> List[Finding]:
    """N seeded mutations per registered decoder.  Verdicts:

    * decode succeeds, raises one of ``entry.errors``, or (for
      none_on_error codecs) returns None — fine;
    * anything else escapes -> ``fuzz-escape`` naming the exception;
    * tracemalloc peak past the proportional allowance + declared
      slack -> ``fuzz-alloc`` (decompression-bomb class).
    """
    if n <= 0:
        return []
    findings: List[Finding] = []
    started_tracing = not tracemalloc.is_tracing()
    if started_tracing:
        tracemalloc.start()
    try:
        for e in entries:
            # crc32, not hash(): str hashing is process-salted and would
            # break run-to-run fuzz determinism
            rng = random.Random(FUZZ_SEED ^ zlib.crc32(e.name.encode()))
            bases = [
                _golden_bytes(e, label, goldens_dir) for label in e.samples
            ]
            bad_escape = bad_alloc = None
            for i in range(n):
                data = _mutate(rng, bases[i % len(bases)])
                allowed = e.alloc_slack + 64 * len(data) + (1 << 20)
                tracemalloc.reset_peak()
                try:
                    e.decode(data)
                except e.errors:
                    pass
                except Exception as ex:  # noqa: BLE001 - audit boundary
                    if bad_escape is None:
                        bad_escape = (i, ex)
                _, peak = tracemalloc.get_traced_memory()
                if peak > allowed and bad_alloc is None:
                    bad_alloc = (i, peak, allowed)
            if bad_escape is not None:
                i, ex = bad_escape
                t = type(ex)
                tname = t.__name__
                if t.__module__ not in ("builtins", "exceptions"):
                    tname = f"{t.__module__}.{tname}"  # e.g. struct.error
                findings.append(Finding(
                    e.module, 1, "fuzz-escape",
                    f"codec {e.name}: mutation #{i} escaped the narrow "
                    f"error contract with {tname}: {ex}",
                ))
            if bad_alloc is not None:
                i, peak, allowed = bad_alloc
                findings.append(Finding(
                    e.module, 1, "fuzz-alloc",
                    f"codec {e.name}: mutation #{i} allocated {peak}B "
                    f"(allowed {allowed}B) — unbounded decode "
                    f"allocation",
                ))
    finally:
        if started_tracing:
            tracemalloc.stop()
    return findings


# ---------------------------------------------------------------------------
# rot guards (AST)
# ---------------------------------------------------------------------------
def scan_module_source(
    source: str, relpath: str, claimed: Iterable[str]
) -> List[Finding]:
    """Flag codec-surface names in `source` the registry doesn't claim:
    top-level ``encode_*``/``decode_*`` defs and
    ``KIND_*``/``K_*``/``*_BIN_VER``/``*_VERSION`` constants."""
    claimed = set(claimed)
    findings: List[Finding] = []
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [Finding(relpath, e.lineno or 1, "unregistered-codec",
                        f"unparseable module: {e.msg}")]
    for node in tree.body:
        names: List[Tuple[str, int]] = []
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if _FN_PAT.match(node.name):
                names.append((node.name, node.lineno))
        elif isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and _CONST_PAT.search(t.id):
                    names.append((t.id, node.lineno))
        elif isinstance(node, ast.AnnAssign):
            t = node.target
            if isinstance(t, ast.Name) and _CONST_PAT.search(t.id):
                names.append((t.id, node.lineno))
        for name, lineno in names:
            if name not in claimed:
                findings.append(Finding(
                    relpath, lineno, "unregistered-codec",
                    f"codec surface `{name}` has no wire_registry entry "
                    f"(register it with samples + a narrow error "
                    f"contract, or claim it from an existing entry)",
                ))
    return findings


def check_registry_complete(root: str = REPO_ROOT) -> List[Finding]:
    findings: List[Finding] = []
    for rel in SCAN_MODULES:
        path = os.path.join(root, rel)
        if not os.path.exists(path):
            findings.append(Finding(
                rel, 1, "unregistered-codec",
                "scanned module vanished — update wire_registry."
                "SCAN_MODULES",
            ))
            continue
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
        findings.extend(
            scan_module_source(source, rel, wire_registry.claimed_names(rel))
        )
    return findings


def _find_function(tree: ast.Module, qualname: str) -> Optional[ast.AST]:
    parts = qualname.split(".")
    body = tree.body
    node: Optional[ast.AST] = None
    for part in parts:
        node = None
        for n in body:
            if isinstance(
                n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
            ) and n.name == part:
                node = n
                break
        if node is None:
            return None
        body = getattr(node, "body", [])
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return node
    return None


def check_decode_bounds_source(
    source: str, relpath: str, fn_names: Sequence[str]
) -> List[Finding]:
    """The decode-bound rule over one module's source (testable on
    fixture strings).  A registered decoder passes when its body either
    parses through the bounded ``_R`` reader, references an explicit
    ``MAX``/``BOUND`` cap, guards with ``len()``, or performs no raw
    length-prefixed reads at all.  Bare ``zlib.decompress`` always
    fails (use ``bounded_decompress`` / a capped decompressobj)."""
    findings: List[Finding] = []
    try:
        tree = ast.parse(source)
    except SyntaxError as e:
        return [Finding(relpath, e.lineno or 1, "decode-bound",
                        f"unparseable module: {e.msg}")]
    for qualname in fn_names:
        fn = _find_function(tree, qualname)
        if fn is None:
            findings.append(Finding(
                relpath, 1, "decode-bound",
                f"registered decoder `{qualname}` not found "
                f"(update wire_registry bound_fns)",
            ))
            continue
        has_bound = False
        raw_read_line = None
        bare_zlib_line = None
        for node in ast.walk(fn):
            if isinstance(node, ast.Name):
                if _BOUND_HINT.search(node.id) or node.id == "_R":
                    has_bound = True
            elif isinstance(node, ast.Attribute):
                if _BOUND_HINT.search(node.attr):
                    has_bound = True
            elif isinstance(node, ast.Call):
                f = node.func
                if isinstance(f, ast.Name):
                    if f.id in ("len", "bounded_decompress"):
                        has_bound = True
                elif isinstance(f, ast.Attribute):
                    if f.attr == "bounded_decompress":
                        has_bound = True
                    if (
                        f.attr == "decompress"
                        and isinstance(f.value, ast.Name)
                        and f.value.id == "zlib"
                    ):
                        bare_zlib_line = node.lineno
                    elif f.attr in _RAW_READ_ATTRS and node.args:
                        raw_read_line = raw_read_line or node.lineno
        if bare_zlib_line is not None:
            findings.append(Finding(
                relpath, bare_zlib_line, "decode-bound",
                f"decoder `{qualname}` calls bare zlib.decompress — "
                f"unbounded allocation on a crafted stream; use "
                f"bounded_decompress / a capped decompressobj",
            ))
        if raw_read_line is not None and not has_bound:
            findings.append(Finding(
                relpath, raw_read_line, "decode-bound",
                f"decoder `{qualname}` performs length-prefixed reads "
                f"with no explicit cap (no _R reader, MAX_* bound or "
                f"len() guard in scope)",
            ))
    return findings


def check_decode_bounds(
    entries: Sequence[CodecEntry], root: str = REPO_ROOT
) -> List[Finding]:
    by_module: Dict[str, List[str]] = {}
    for e in entries:
        if e.bound_fns:
            by_module.setdefault(e.module, []).extend(e.bound_fns)
    findings: List[Finding] = []
    for rel, fns in sorted(by_module.items()):
        path = os.path.join(root, rel)
        if not os.path.exists(path):
            findings.append(Finding(rel, 1, "decode-bound",
                                    "registered module vanished"))
            continue
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
        findings.extend(
            check_decode_bounds_source(source, rel, sorted(set(fns)))
        )
    return findings


# ---------------------------------------------------------------------------
# audit + CLI
# ---------------------------------------------------------------------------
def audit(
    names: Optional[Sequence[str]] = None,
    goldens_dir: Optional[str] = None,
    fuzz_n: int = DEFAULT_FUZZ_N,
    update_goldens: bool = False,
) -> List[Finding]:
    """Run the four checks; `names` narrows to specific codec entries
    (the whole-tree rot guards only run on a FULL audit, mirroring
    jaxcheck's registry-completeness rule)."""
    entries = [
        e for e in REGISTRY if names is None or e.name in names
    ]
    gdir = goldens_dir or GOLDENS_DIR
    findings = check_goldens(entries, gdir, update=update_goldens)
    findings += check_skew(entries, gdir)
    findings += check_fuzz(entries, gdir, fuzz_n)
    findings += check_decode_bounds(entries)
    if names is None:
        findings += check_registry_complete()
    return findings


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m dragonboat_tpu.analysis --wire",
        description="wire-compat auditor (golden corpus, skew matrix, "
                    "decoder fuzz, registry rot guards)",
    )
    p.add_argument("--baseline", help="baseline file (the ratchet)")
    p.add_argument(
        "--update-baseline", action="store_true",
        help="rewrite the baseline to the current findings",
    )
    p.add_argument(
        "--update-goldens", action="store_true",
        help="regenerate tests/wire_goldens/ from the registry's "
             "canonical samples (a deliberate wire-layout change)",
    )
    p.add_argument(
        "--fuzz", type=int, default=DEFAULT_FUZZ_N, metavar="N",
        help=f"mutations per registered decoder "
             f"(default {DEFAULT_FUZZ_N})",
    )
    args = p.parse_args(argv)
    if args.update_baseline and not args.baseline:
        p.error("--update-baseline requires --baseline")

    if args.update_goldens:
        check_goldens(list(REGISTRY), GOLDENS_DIR, update=True)
        count = sum(len(e.samples) for e in REGISTRY)
        print(f"wirecheck: regenerated {count} goldens in {GOLDENS_REL}/")

    findings = audit(fuzz_n=args.fuzz)
    findings.sort(key=lambda f: (f.path, f.rule, f.line))

    if args.update_baseline:
        write_baseline(args.baseline, findings)
        print(f"wirecheck: baseline updated with {len(findings)} findings")
        return 0

    baseline = load_baseline(args.baseline) if args.baseline else {}
    new, stale = gate(findings, baseline)
    for f in new:
        print(f.render())
    for path, rule, allowed, got in stale:
        print(
            f"note: baseline allows {allowed} {rule} findings for "
            f"{path} but only {got} remain — ratchet it down",
            file=sys.stderr,
        )
    if not new:
        goldens = sum(len(e.samples) for e in REGISTRY)
        print(
            f"wirecheck: clean over {len(REGISTRY)} codecs "
            f"({goldens} goldens, {args.fuzz} mutations/decoder)"
        )
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
