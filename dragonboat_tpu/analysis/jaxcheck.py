"""jaxcheck: static auditor for the compiled device-plane programs.

raftlint checks what the PYTHON says; jaxcheck checks what the DEVICE
will actually run.  It walks ``ops/registry.py`` (every jitted entry
point in ``ops/``), traces each with the canonical small geometry, and
checks the resulting jaxprs/lowerings against the device-plane policy
that ROADMAP items 1-3 keep piling more logic onto:

``dtype``
    Every intermediate of every program stays in the sanctioned set
    {int32, uint32, bool} (ops/types.py: "all protocol scalars are
    int32" — TPUs have no native int64, and a silent int64/float
    promotion doubles lane traffic or detours through the scalar
    unit).  Entry-point OUTPUTS additionally must not be weak-typed:
    a weak output fed back as the next launch's input re-traces the
    program (the drift the runtime sentry would catch late and this
    catches at lint time).

``transfer``
    No host-transfer primitives (``io_callback`` / ``pure_callback`` /
    ``debug_callback``, infeed/outfeed) inside a compiled hot program:
    every device->host sync costs ~100-214 ms of round-trip latency on
    a remote-device link regardless of size (docs/BENCH_NOTES_r05.md
    "sync-latency model") — one stray ``jax.debug.print`` in the step
    would erase the single-sync launch work.

``donation``
    Every ``donate_argnums`` declaration that CAN alias (a donated
    input whose shape+dtype matches an output) actually does alias in
    the lowering (``tf.aliasing_output``).  A donated-but-unaliased
    buffer where aliasing was possible is the fallback-copy regression
    class of ops/route.py's "aliased zeros break donate_argnums" —
    donation silently degrades to copy + free and the heap grows back
    (the r5 RESOURCE_EXHAUSTED mid-election class).  Declarations with
    NO shape-matched output (e.g. ``_assemble_and_step``'s inboxes,
    donated for early-free) are legitimate and not flagged.

``g-last``
    Internal-layout programs (``kernel.step_internal``) keep G as the
    trailing axis of every computed intermediate, so int32 operands
    pack the 128-wide TPU lane dimension instead of padding it 16-42x
    (ops/kernel.py module docstring).  The G axis is identified by its
    canonical size (registry.CANON — all sizes pairwise distinct);
    constant fills (all-literal inputs, e.g. the make_out constructors
    that fold under jit) are exempt.

``unregistered-jit``
    Every ``@jax.jit``-decorated function in ``ops/*.py`` must appear
    in the registry — the audit cannot cover what it cannot see.

Findings flow through the same baseline ratchet as raftlint
(``analysis/jax_baseline.txt``; gate = zero findings beyond baseline)
via ``python -m dragonboat_tpu.analysis --jax`` (scripts/lint.sh).
The dynamic half — post-warmup retrace detection — is
``analysis/jitcheck.py``.
"""
from __future__ import annotations

import ast
import os
import sys
import warnings
from collections import Counter
from typing import Dict, Iterable, List, Optional, Tuple

from .raftlint import Finding, gate, load_baseline, write_baseline

# dtypes a device-plane intermediate may legally carry (ops/types.py
# int32 policy; uint32 for the splitmix hash / bit-packed masks; bool
# for predication masks)
SANCTIONED_DTYPES = frozenset(("int32", "uint32", "bool"))

# primitive names that move data across the device/host boundary from
# INSIDE a compiled program
_TRANSFER_EXACT = frozenset(("infeed", "outfeed"))
_TRANSFER_SUBSTR = ("callback",)  # io_callback / pure_callback / debug_callback

_ALIAS_ATTR = "tf.aliasing_output"


# ---------------------------------------------------------------------------
# jaxpr plumbing
# ---------------------------------------------------------------------------
def _subjaxprs(param):
    import jax.core as jc

    if isinstance(param, jc.ClosedJaxpr):
        return [param.jaxpr]
    if isinstance(param, jc.Jaxpr):
        return [param]
    if isinstance(param, (tuple, list)):
        out = []
        for p in param:
            out.extend(_subjaxprs(p))
        return out
    return []


def _iter_eqns(jaxpr):
    """Depth-first over every equation, descending into sub-jaxprs
    (pjit bodies, cond branches, while carry/body, scans)."""
    for eqn in jaxpr.eqns:
        yield eqn
        for param in eqn.params.values():
            for sub in _subjaxprs(param):
                yield from _iter_eqns(sub)


def _trace(ep):
    """(args, Traced) of one entry point at the canonical geometry.

    Uses the jit object's AOT ``.trace()`` so ONE trace serves every
    rule — the Traced carries both the jaxpr (dtype/transfer/g-last)
    and the lowering (donation); a separate ``.lower()`` call would
    re-trace each donating entry from scratch (review finding)."""
    args, kwargs = ep.build()
    return args, ep.fn.trace(*args, **kwargs)


# ---------------------------------------------------------------------------
# rules
# ---------------------------------------------------------------------------
def _check_dtype(ep, closed, extra_ok: frozenset) -> List[Finding]:
    findings: List[Finding] = []
    seen: Dict[Tuple[str, str], int] = {}
    for eqn in _iter_eqns(closed.jaxpr):
        for v in eqn.outvars:
            aval = getattr(v, "aval", None)
            dt = getattr(aval, "dtype", None)
            if dt is None:
                continue
            name = str(dt)
            if name in SANCTIONED_DTYPES or name in extra_ok:
                continue
            key = (eqn.primitive.name, name)
            seen[key] = seen.get(key, 0) + 1
    for (prim, dtname), n in sorted(seen.items()):
        findings.append(
            Finding(
                ep.name, 0, "dtype",
                f"{prim} produces {dtname} (x{n}) outside the sanctioned "
                f"set {{int32, uint32, bool}} — ops/types.py int32 policy",
            )
        )
    # entry outputs must be strong-typed (weak outputs re-key the next
    # launch's trace — silent recompiles)
    weak = sum(
        1
        for v in closed.jaxpr.outvars
        if getattr(getattr(v, "aval", None), "weak_type", False)
    )
    if weak:
        findings.append(
            Finding(
                ep.name, 0, "dtype",
                f"{weak} weak-typed output(s): weak types drift across "
                "launches and force retraces",
            )
        )
    return findings


def _check_transfer(ep, closed) -> List[Finding]:
    hits = Counter()
    for eqn in _iter_eqns(closed.jaxpr):
        name = eqn.primitive.name
        if name in _TRANSFER_EXACT or any(
            s in name for s in _TRANSFER_SUBSTR
        ):
            hits[name] += 1
    return [
        Finding(
            ep.name, 0, "transfer",
            f"host-transfer primitive `{prim}` (x{n}) inside a compiled "
            "hot program — every sync costs ~100-214 ms on a remote link "
            "(docs/BENCH_NOTES_r05.md)",
        )
        for prim, n in sorted(hits.items())
    ]


def _leaf_keys(tree) -> Counter:
    import jax

    return Counter(
        (tuple(leaf.shape), str(leaf.dtype))
        for leaf in jax.tree_util.tree_leaves(tree)
    )


def _check_donation(ep, closed, args, traced) -> List[Finding]:
    """Expected aliases = maximal (shape, dtype) multiset matching of
    donated input leaves against output leaves; actual = aliasing
    attributes in the lowering.  actual < expected means XLA fell back
    to copy for a donation that could have aliased."""
    if not ep.donate:
        return []
    donated = Counter()
    for i in ep.donate:
        donated += _leaf_keys(args[i])
    outs = Counter(
        (tuple(v.aval.shape), str(v.aval.dtype))
        for v in closed.jaxpr.outvars
    )
    expected = sum(min(n, outs.get(k, 0)) for k, n in donated.items())
    with warnings.catch_warnings():
        # the "donated buffers were not usable" warning is exactly what
        # this rule quantifies; don't let it leak to callers
        warnings.simplefilter("ignore")
        text = traced.lower().as_text()
    actual = text.count(_ALIAS_ATTR)
    if actual < expected:
        return [
            Finding(
                ep.name, 0, "donation",
                f"only {actual}/{expected} shape-matched donated buffers "
                "alias in the lowering — donation fell back to copy "
                "(the ops/route.py aliased-zeros class)",
            )
        ]
    return []


def _check_g_last(ep, closed, G: int) -> List[Finding]:
    import jax.core as jc

    seen: Dict[Tuple[str, tuple], int] = {}
    for eqn in _iter_eqns(closed.jaxpr):
        # constant fills (all-literal inputs, e.g. jnp.zeros/full in
        # constructors) fold under jit and carry no lane traffic
        if all(isinstance(iv, jc.Literal) for iv in eqn.invars):
            continue
        for v in eqn.outvars:
            aval = getattr(v, "aval", None)
            shape = tuple(getattr(aval, "shape", ()))
            if len(shape) < 2 or G not in shape or shape[-1] == G:
                continue
            key = (eqn.primitive.name, shape)
            seen[key] = seen.get(key, 0) + 1
    return [
        Finding(
            ep.name, 0, "g-last",
            f"{prim} produces G-major {shape} (x{n}) in an internal-"
            "layout program — G must trail so int32 packs the 128-lane "
            "axis (ops/kernel.py layout contract)",
        )
        for (prim, shape), n in sorted(seen.items())
    ]


# ---------------------------------------------------------------------------
# registry completeness (AST over ops/*.py)
# ---------------------------------------------------------------------------
def _is_jit_decorator(dec: ast.expr) -> bool:
    """jax.jit / @functools.partial(jax.jit, ...) decorator shapes."""
    if isinstance(dec, ast.Attribute) and dec.attr == "jit":
        return True
    if isinstance(dec, ast.Name) and dec.id == "jit":
        return True
    if isinstance(dec, ast.Call):
        f = dec.func
        if isinstance(f, ast.Attribute) and f.attr == "partial" and dec.args:
            return _is_jit_decorator(dec.args[0])
        return _is_jit_decorator(f)
    return False


def _jit_defs(ops_dir: str):
    """(module_basename, name, lineno) of every jitted definition:
    decorator form (@jax.jit / @functools.partial(jax.jit, ...)) AND
    assignment form (``fast = jax.jit(impl)`` or
    ``fast = functools.partial(jax.jit, ...)(impl)``) — the audit
    cannot cover what it cannot see, in either spelling."""
    out = []
    for fname in sorted(os.listdir(ops_dir)):
        if not fname.endswith(".py"):
            continue
        path = os.path.join(ops_dir, fname)
        with open(path, "r", encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=path)
        mod = fname[:-3]
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if any(_is_jit_decorator(d) for d in node.decorator_list):
                    out.append((mod, node.name, node.lineno))
            elif (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
                and _is_jit_decorator(node.value)
            ):
                out.append((mod, node.targets[0].id, node.lineno))
    return out


def _check_registry_complete(entries) -> List[Finding]:
    from ..ops import registry as _reg

    ops_dir = os.path.dirname(os.path.abspath(_reg.__file__))
    registered = {ep.name for ep in entries}
    findings = []
    for mod, fname, lineno in _jit_defs(ops_dir):
        if mod == "registry":
            continue  # the audit wrapper itself
        if f"{mod}.{fname}" not in registered:
            findings.append(
                Finding(
                    f"ops/{mod}.py", lineno, "unregistered-jit",
                    f"jitted `{fname}` is not in ops/registry.py — the "
                    "device-plane audit cannot cover what it cannot see",
                )
            )
    return findings


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------
def audit(entries=None, extra_ok: Iterable[str] = ()) -> List[Finding]:
    """Trace + check every registered entry point; returns findings.

    ``entries`` defaults to the full ops registry (tests pass fixture
    registries).  Tracing is abstract — no kernels compile, no device
    memory is touched — so the whole audit runs in seconds on CPU.
    """
    from ..ops import registry as _reg

    if entries is None:
        entries = _reg.ENTRY_POINTS
        check_complete = True
    else:
        check_complete = False
    extra = frozenset(extra_ok)
    G = _reg.CANON["G"]
    findings: List[Finding] = []
    for ep in entries:
        args, traced = _trace(ep)
        closed = traced.jaxpr
        findings.extend(_check_dtype(ep, closed, extra))
        findings.extend(_check_transfer(ep, closed))
        findings.extend(_check_donation(ep, closed, args, traced))
        if ep.g_last:
            findings.extend(_check_g_last(ep, closed, G))
    if check_complete:
        findings.extend(_check_registry_complete(entries))
    return findings


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        prog="jaxcheck", description=__doc__.splitlines()[0]
    )
    ap.add_argument(
        "--baseline", default=None, help="baseline file to gate against"
    )
    ap.add_argument(
        "--update-baseline",
        action="store_true",
        help="rewrite the baseline from the current findings and exit 0",
    )
    args = ap.parse_args(argv)

    from ..ops import registry as _reg

    findings = audit()
    n_entries = len(_reg.ENTRY_POINTS)
    if args.update_baseline:
        if not args.baseline:
            ap.error("--update-baseline requires --baseline")
        write_baseline(args.baseline, findings)
        print(f"jaxcheck: baseline written ({len(findings)} findings)")
        return 0
    baseline = load_baseline(args.baseline) if args.baseline else {}
    new, stale = gate(findings, baseline)
    for f in new:
        print(f.render())
    for path, rule, allowed, now in stale:
        print(
            f"jaxcheck: note: baseline for {path} {rule} is {allowed}, "
            f"tree has {now} — ratchet it down",
            file=sys.stderr,
        )
    if new:
        print(
            f"jaxcheck: {len(new)} unbaselined finding(s) over {n_entries} "
            f"entry points ({len(findings)} total, baseline covers "
            f"{sum(baseline.values())})",
            file=sys.stderr,
        )
        return 1
    print(
        f"jaxcheck: clean over {n_entries} entry points"
        + (f" ({len(findings)} finding(s), all baselined)" if findings else "")
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
