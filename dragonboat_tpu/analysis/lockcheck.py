"""Env-gated runtime lock-order witness (the dynamic half of analysis/).

reference: upstream dragonboat runs its whole CI under the Go race
detector [U]; CPython has no race detector, but the deadlocks that
actually bit this port (EventFanout close, apply-vs-stop ordering) are
LOCK-ORDER bugs, which a cheap runtime witness can catch:

* ``install()`` wraps ``threading.Lock``/``threading.RLock`` so locks
  **created from project code** (caller file under ``dragonboat_tpu/``)
  are tracked; stdlib/jax internals keep real locks at zero overhead.
* Each tracked acquire records edges ``held-lock -> acquired-lock`` in
  a global lock-order graph, capturing the acquiring stack once per
  edge.  Any cycle — a potential deadlock, even if this run got lucky
  with timing — is reported with the witness stacks of every edge on
  the cycle.
* Waits longer than ``slow_wait_s`` while another lock is held are
  flagged (the "blocked inside a critical section" smell that raftlint
  can only approximate lexically).

The switch is ``DRAGONBOAT_TPU_LOCKCHECK`` (same pattern as
``invariants.py``): the test suite turns it on for the chaos/fault
modules in conftest.py, production defaults off and pays nothing — an
uninstalled witness leaves ``threading`` untouched.

Usage:
    from dragonboat_tpu.analysis import lockcheck
    w = lockcheck.install()
    try:
        ...  # run the workload
    finally:
        lockcheck.uninstall()
    w.assert_clean()          # raises LockOrderViolation on any cycle
"""
from __future__ import annotations

import os
import sys
import threading
import time
import traceback
from typing import Dict, List, Optional, Set, Tuple

ENABLED = os.environ.get("DRAGONBOAT_TPU_LOCKCHECK", "0") not in ("", "0")

# the REAL constructors, captured at import so uninstall always restores
# the genuine articles no matter how many installs happened
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock

_STACK_LIMIT = 16  # frames kept per witness stack


class LockOrderViolation(AssertionError):
    """A lock-order cycle (potential deadlock) was witnessed."""


def enable(on: bool = True) -> None:
    """Programmatic switch (tests)."""
    global ENABLED
    ENABLED = on


def _own_stack() -> List[str]:
    """Formatted acquiring stack, trimmed of lockcheck's own frames."""
    frames = traceback.extract_stack(limit=_STACK_LIMIT + 4)
    keep = [f for f in frames if os.path.basename(f.filename) != "lockcheck.py"]
    return traceback.format_list(keep[-_STACK_LIMIT:])


class _TrackedLock:
    """Wrapper around a real Lock/RLock feeding the witness graph.

    When the witness is inactive (uninstalled), every call is one
    attribute load + bool test away from the real lock."""

    __slots__ = ("_lk", "_w", "oid", "site", "reentrant")

    def __init__(self, real, witness: "Witness", site: str, reentrant: bool):
        self._lk = real
        self._w = witness
        self.site = site
        self.reentrant = reentrant
        self.oid = witness._register(self)

    # -- lock protocol ---------------------------------------------------
    def acquire(self, blocking: bool = True, timeout: float = -1):
        w = self._w
        if not w.active:
            return self._lk.acquire(blocking, timeout)
        return w._acquire(self, blocking, timeout)

    def release(self) -> None:
        w = self._w
        if w.active:
            w._note_release(self)
        self._lk.release()

    def __enter__(self):
        return self.acquire()

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._lk.locked()

    def __repr__(self) -> str:
        return f"<tracked {'RLock' if self.reentrant else 'Lock'} {self.site}>"

    # -- Condition integration -------------------------------------------
    # Condition binds these off the lock it is given; the underlying
    # real RLock provides them, a plain Lock does not — fall back to
    # CPython Condition's own plain-lock defaults in that case.
    def _is_owned(self):
        fn = getattr(self._lk, "_is_owned", None)
        if fn is not None:
            return fn()
        if self._lk.acquire(False):
            self._lk.release()
            return False
        return True

    def _release_save(self):
        # Condition.wait: the lock is FULLY released regardless of
        # recursion depth — drop every held-stack entry for it
        w = self._w
        if w.active:
            w._note_release(self, all_depths=True)
        fn = getattr(self._lk, "_release_save", None)
        if fn is not None:
            return fn()
        self._lk.release()
        return None

    def _acquire_restore(self, state) -> None:
        fn = getattr(self._lk, "_acquire_restore", None)
        if fn is not None:
            fn(state)
        else:
            self._lk.acquire()
        w = self._w
        if w.active:
            w._note_reacquired(self)

    def _at_fork_reinit(self) -> None:
        fn = getattr(self._lk, "_at_fork_reinit", None)
        if fn is not None:
            fn()


class Witness:
    """The global lock-order graph + per-thread held stacks."""

    def __init__(self, root: str, slow_wait_s: float):
        self.root = root
        self.slow_wait_s = slow_wait_s
        self.active = False
        self._glock = _REAL_LOCK()  # guards the graph (always a REAL lock)
        self._next_oid = 0
        self.sites: Dict[int, str] = {}  # oid -> creation site
        # edge (a, b): thread held a while acquiring b; stack captured once
        self.edges: Dict[Tuple[int, int], List[str]] = {}
        self.adj: Dict[int, Set[int]] = {}
        self.cycles: List[dict] = []
        self.slow_waits: List[dict] = []
        self.acquires = 0  # tracked-acquire count (overhead accounting)
        self._tls = threading.local()

    # -- bookkeeping -----------------------------------------------------
    def _register(self, tl: _TrackedLock) -> int:
        with self._glock:
            self._next_oid += 1
            oid = self._next_oid
            self.sites[oid] = tl.site
            return oid

    def _stack(self) -> List[_TrackedLock]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def _acquire(self, tl: _TrackedLock, blocking: bool, timeout: float):
        held = self._stack()
        already = any(h is tl for h in held)
        got = tl._lk.acquire(False)
        waited = 0.0
        if not got:
            if not blocking:
                return False
            t0 = time.monotonic()
            got = tl._lk.acquire(True, timeout)
            waited = time.monotonic() - t0
        if not got:
            return False
        self.acquires += 1
        if held and not already:
            seen: Set[int] = set()
            for h in held:
                if h.oid != tl.oid and h.oid not in seen:
                    seen.add(h.oid)
                    self._edge(h, tl)
        if waited > self.slow_wait_s and any(h is not tl for h in held):
            with self._glock:
                self.slow_waits.append(
                    {
                        "lock": tl.site,
                        "held": [h.site for h in held if h is not tl],
                        "waited_s": round(waited, 4),
                        "thread": threading.current_thread().name,
                        "stack": _own_stack(),
                    }
                )
        held.append(tl)
        return True

    def _note_release(self, tl: _TrackedLock, all_depths: bool = False) -> None:
        held = self._stack()
        for i in range(len(held) - 1, -1, -1):
            if held[i] is tl:
                del held[i]
                if not all_depths:
                    return

    def _note_reacquired(self, tl: _TrackedLock) -> None:
        # Condition.wait re-acquire: no edge recording — the wait's
        # whole point is that the lock was NOT held in between
        self._stack().append(tl)

    # -- the graph --------------------------------------------------------
    def _edge(self, a: _TrackedLock, b: _TrackedLock) -> None:
        key = (a.oid, b.oid)
        with self._glock:
            if key in self.edges:
                return
            self.edges[key] = _own_stack()
            self.adj.setdefault(a.oid, set()).add(b.oid)
            path = self._find_path(b.oid, a.oid)
        if path:
            # cycle: a -> b (new) plus path b -> ... -> a (existing)
            edge_list = [key] + list(zip(path, path[1:]))
            with self._glock:
                self.cycles.append(
                    {
                        "locks": [self.sites[o] for o in [a.oid, b.oid]]
                        + [self.sites[o] for o in path[1:]],
                        "edges": [
                            {
                                "from": self.sites[x],
                                "to": self.sites[y],
                                "stack": self.edges.get((x, y), []),
                            }
                            for x, y in edge_list
                        ],
                        "thread": threading.current_thread().name,
                    }
                )

    def _find_path(self, src: int, dst: int) -> Optional[List[int]]:
        """DFS path src -> dst in the order graph (called under _glock)."""
        stack = [(src, [src])]
        visited = {src}
        while stack:
            node, path = stack.pop()
            if node == dst:
                return path
            for nxt in self.adj.get(node, ()):
                if nxt not in visited:
                    visited.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    # -- reporting --------------------------------------------------------
    def make_lock(self, site: str = "explicit", reentrant: bool = False):
        """Explicitly-tracked lock (tests; code outside the root filter)."""
        real = _REAL_RLOCK() if reentrant else _REAL_LOCK()
        return _TrackedLock(real, self, site, reentrant)

    def report(self) -> dict:
        with self._glock:
            return {
                "tracked_locks": self._next_oid,
                "acquires": self.acquires,
                "edges": len(self.edges),
                "cycles": list(self.cycles),
                "slow_waits": list(self.slow_waits),
            }

    def format_cycles(self) -> str:
        out = []
        for c in self.cycles:
            out.append(
                "lock-order cycle (potential deadlock) witnessed by "
                f"thread {c['thread']}:\n  " + " -> ".join(c["locks"])
            )
            for e in c["edges"]:
                out.append(f"  edge {e['from']} -> {e['to']} acquired at:")
                out.extend("    " + ln.rstrip() for ln in e["stack"])
        return "\n".join(out)

    def assert_clean(self) -> None:
        if self.cycles:
            raise LockOrderViolation(self.format_cycles())


_witness: Optional[Witness] = None
_DEFAULT_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def install(
    slow_wait_s: Optional[float] = None, root: Optional[str] = None
) -> Witness:
    """Patch threading.Lock/RLock so project-created locks are tracked.
    Returns the active Witness (idempotent while installed)."""
    global _witness
    if _witness is not None and _witness.active:
        return _witness
    if slow_wait_s is None:
        slow_wait_s = float(
            os.environ.get("DRAGONBOAT_TPU_LOCKCHECK_SLOW", "0.25")
        )
    w = Witness(root or _DEFAULT_ROOT, slow_wait_s)
    w.active = True

    def _site(depth: int = 2) -> Optional[str]:
        f = sys._getframe(depth)
        fn = f.f_code.co_filename
        if fn.startswith(w.root):
            return f"{os.path.relpath(fn, os.path.dirname(w.root))}:{f.f_lineno}"
        return None

    def lock_factory():
        site = _site()
        if w.active and site is not None:
            return _TrackedLock(_REAL_LOCK(), w, site, reentrant=False)
        return _REAL_LOCK()

    def rlock_factory():
        site = _site()
        if w.active and site is not None:
            return _TrackedLock(_REAL_RLOCK(), w, site, reentrant=True)
        return _REAL_RLOCK()

    threading.Lock = lock_factory
    threading.RLock = rlock_factory
    _witness = w
    return w


def uninstall() -> Optional[Witness]:
    """Restore the real constructors; returns the (now inactive) witness
    so callers can inspect/assert its report."""
    global _witness
    threading.Lock = _REAL_LOCK
    threading.RLock = _REAL_RLOCK
    w = _witness
    if w is not None:
        w.active = False
    _witness = None
    return w


def current() -> Optional[Witness]:
    return _witness
