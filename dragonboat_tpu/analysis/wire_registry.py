"""The wire-plane codec registry (wirecheck's ground truth).

Every versioned encode/decode pair in the repo — the TCP frame payloads
(`transport/wire.py`), the gossip packet, the tan WAL records, the
kvlogdb value codecs, the snapshot container (`storage/snapshotio`),
the on-disk SM command codec (`bigstate/ondisk.py`) and the DR manifest
(`bigstate/dr.py`) — is registered here with:

* canonical sample builders per supported layout (``samples``): pure
  functions of constants, so the bytes are reproducible and pin the
  golden corpus under ``tests/wire_goldens/``;
* the decoder and its NARROW error contract (``errors`` — the only
  exception types allowed to escape on hostile bytes; gossip's contract
  is a ``None`` return instead, ``none_on_error``);
* a future-layout builder (``future``) the decoder must REJECT with
  that same narrow type (rolling-upgrade discipline: never a silent
  field shift);
* extra skew invariants (``checks``) for flag-gated extensions — the
  untraced RPC frame staying byte-identical to v0, the stats read-path
  trailer staying absent unless requested, the empty obs query
  defaulting;
* the ``encode_*``/``decode_*`` names and ``KIND_*``/``K_*``/
  ``*_BIN_VER``/``*_VERSION`` constants each entry covers (``claims``)
  so wirecheck's rot guard can flag codec surface that grows WITHOUT a
  registry entry (the jaxcheck ``unregistered-jit`` discipline);
* the decoder functions whose source the ``decode-bound`` rule audits
  (``bound_fns``) and an allocation allowance for the fuzz harness
  (``alloc_slack`` — 0 means "proportional to input only").

Samples intentionally reuse the repo's PRIVATE writer helpers
(``wire._ws`` et al.) to hand-build OLD layouts (e.g. MessageBatch v0 =
the current per-message bytes minus the trailing trace-flag byte) —
the same technique the version-skew tests used before this registry
consolidated them.
"""
from __future__ import annotations

import json
import struct
from dataclasses import dataclass, field
from io import BytesIO
from typing import Callable, Dict, Mapping, Optional, Tuple

from ..pb import (
    Bootstrap,
    Chunk,
    ConfigChange,
    ConfigChangeType,
    Entry,
    EntryType,
    ManifestFile,
    Membership,
    Message,
    MessageBatch,
    MessageType,
    Snapshot,
    SnapshotFile,
    SnapshotManifest,
    State,
    Update,
)
from ..transport import wire
from ..transport.wire import WireError

# repo-relative module paths the rot guard scans.  A module may appear
# here with no claims at all (tcp/chunk/journal only CONSUME the wire
# constants); listing it still guards against a future codec landing
# there unregistered.
SCAN_MODULES = (
    "dragonboat_tpu/pb.py",
    "dragonboat_tpu/transport/wire.py",
    "dragonboat_tpu/transport/tcp.py",
    "dragonboat_tpu/transport/chunk.py",
    "dragonboat_tpu/transport/gossip.py",
    "dragonboat_tpu/storage/tan.py",
    "dragonboat_tpu/storage/kvlogdb.py",
    "dragonboat_tpu/storage/journal.py",
    "dragonboat_tpu/storage/snapshotio.py",
    "dragonboat_tpu/bigstate/ondisk.py",
    "dragonboat_tpu/bigstate/dr.py",
    "dragonboat_tpu/gateway/rpc.py",
    "dragonboat_tpu/obs/fleetscope.py",
    "dragonboat_tpu/readplane/consistency.py",
    "dragonboat_tpu/readplane/router.py",
)

# claims that belong to a module rather than any one codec entry
# (framing flags, the pb-side version constant)
EXTRA_CLAIMS: Mapping[str, Tuple[str, ...]] = {
    "dragonboat_tpu/pb.py": ("MESSAGE_BATCH_BIN_VER",),
    # KIND_RESUME_RESP lives in wire.py but its codec entry is scoped to
    # tcp.py (the only encoder/decoder of the resume frame body)
    "dragonboat_tpu/transport/wire.py": ("KIND_COMPRESSED",
                                         "KIND_RESUME_RESP"),
    "dragonboat_tpu/storage/tan.py": ("K_COMPRESSED",),
}


@dataclass(frozen=True)
class CodecEntry:
    """One registered encode/decode pair (see module docstring)."""

    name: str
    module: str
    samples: Mapping[str, Callable[[], bytes]]
    decode: Callable[[bytes], object]
    errors: Tuple[type, ...]
    encode: Optional[Callable[[], bytes]] = None  # current-layout encoder
    none_on_error: bool = False
    future: Optional[Callable[[], bytes]] = None
    checks: Tuple[Callable[[], Optional[str]], ...] = ()
    claims: Tuple[str, ...] = ()
    bound_fns: Tuple[str, ...] = ()  # qualnames in `module` for decode-bound
    alloc_slack: int = 0  # extra decode-side allocation allowance (bytes)


# ---------------------------------------------------------------------------
# canonical sample values (constants only — golden bytes must be
# reproducible from a clean checkout)
# ---------------------------------------------------------------------------
def _membership() -> Membership:
    return Membership(
        config_change_id=3,
        addresses={1: "n1:7100", 2: "n2:7100"},
        non_votings={3: "n3:7100"},
        witnesses={4: "n4:7100"},
        removed={9: True},
    )


def _entries() -> Tuple[Entry, ...]:
    return (
        Entry(term=2, index=10, type=EntryType.APPLICATION, key=11,
              client_id=7, series_id=1, responded_to=0, cmd=b"put k v"),
        Entry(term=2, index=11, cmd=b""),
    )


def _snapshot() -> Snapshot:
    return Snapshot(
        filepath="snapshot-0000000000000064.dbss",
        file_size=4096,
        index=100,
        term=2,
        membership=_membership(),
        files=(SnapshotFile(file_id=1, filepath="ext/sst-1", file_size=512,
                            metadata=b"meta"),),
        checksum=b"\x01\x02\x03\x04",
        shard_id=1,
        replica_id=2,
        on_disk_index=90,
        type=1,
    )


def _message(traced: bool = False) -> Message:
    return Message(
        type=MessageType.REPLICATE,
        to=2,
        from_=1,
        shard_id=1,
        term=2,
        log_term=2,
        log_index=9,
        commit=8,
        entries=_entries(),
        snapshot=_snapshot(),
        trace_id=0xABCDEF if traced else 0,
        span_id=0x123456 if traced else 0,
    )


def _batch_bytes(bin_ver: int, traced: bool, strip_flag: bool) -> bytes:
    """Hand-built MessageBatch frame: v0 is the current per-message
    layout minus the trailing trace-flag byte (the layout that predates
    the trace extension)."""
    b = BytesIO()
    wire._ws(b, "n1:7100")
    wire._wu64(b, 7)  # deployment_id
    wire._wu32(b, bin_ver)
    wire._wu32(b, 1)
    mb = BytesIO()
    wire._w_message(mb, _message(traced))
    raw = mb.getvalue()
    b.write(raw[:-1] if strip_flag else raw)
    return b.getvalue()


def _chunk(file_info: bool) -> Chunk:
    return Chunk(
        shard_id=1, replica_id=2, from_=3, chunk_id=4, chunk_size=1024,
        chunk_count=8, index=100, term=2, message_term=2, file_size=8192,
        on_disk_index=90, witness=False, dummy=False,
        has_file_info=file_info, filepath="snapshot.dbss",
        data=b"chunk-data" * 8, membership=_membership(),
        file_info=SnapshotFile(file_id=1, filepath="ext/sst-1",
                               file_size=512, metadata=b"meta")
        if file_info else SnapshotFile(),
        file_chunk_id=2 if file_info else 0,
        file_chunk_count=4 if file_info else 0,
    )


def _rpc_request(traced: bool) -> "wire.RpcRequest":
    return wire.RpcRequest(
        req_id=42, op=wire.RPC_OP_PROPOSE, flags=0, shard_id=1,
        client_id=7, series_id=3, responded_to=2, timeout_ms=1000,
        arg=0, payload=b"put k v",
        trace_id=0xABCDEF if traced else 0,
        span_id=0x123456 if traced else 0,
    )


def _stats_rows():
    return [{
        "shard_id": 1, "replica_id": 2, "leader_id": 1, "term": 2,
        "applied": 100, "proposals": 5, "device": -1,
        "membership": _membership(),
    }]


def _u32_patched(data: bytes, offset: int, value: int) -> bytes:
    out = bytearray(data)
    struct.pack_into("<I", out, offset, value)
    return bytes(out)


def _rsm_snapshot_bytes() -> bytes:
    return wire.encode_rsm_snapshot(
        index=100, term=2, membership=_membership(),
        sessions=b"sess", sm_data=b"smdata", on_disk=False,
    )


# -- gossip -----------------------------------------------------------------
def _gossip_packet() -> bytes:
    from ..transport import gossip

    table = {
        "nhid-aaaa": ("n1:7100", 3),
        "nhid-bbbb": ("n2:7100", 5),
    }
    return gossip._encode_packets(table, "n1:7946", "nhid-aaaa")[0]


def _gossip_decode(data: bytes):
    from ..transport import gossip

    return gossip._decode_table(data)


# -- tan WAL records --------------------------------------------------------
# golden layout: kind byte + record body (the framing CRC/length live in
# storage/journal.py and are covered by its own crash tests)
def _tan_update() -> Update:
    u = Update(shard_id=1, replica_id=2)
    u.state = State(term=2, vote=1, commit=8)
    u.entries_to_save = list(_entries())
    u.snapshot = _snapshot()
    return u


def _tan_decode(data: bytes):
    """Replays one record through the REAL decoder
    (``TanLogDB._apply_record``) against a scratch in-memory mirror —
    no filesystem, no segment framing."""
    from ..storage import tan
    from ..storage.logdb import InMemLogDB

    if not data:
        raise WireError("empty tan record")
    db = tan.TanLogDB.__new__(tan.TanLogDB)
    db._mirror = InMemLogDB()
    db._apply_record(data[0], bytes(data[1:]))
    return db._mirror


def _tan_record(kind_name: str, body_builder: Callable[[], bytes]):
    def build() -> bytes:
        from ..storage import tan

        return bytes([getattr(tan, kind_name)]) + body_builder()

    return build


def _tan_body(fn_name: str, *args_builders):
    def build() -> bytes:
        from ..storage import tan

        return getattr(tan, fn_name)(*[a() for a in args_builders])

    return build


# -- kvlogdb value codecs ---------------------------------------------------
def _kv(fn_name: str, *args):
    def build() -> bytes:
        from ..storage import kvlogdb

        return getattr(kvlogdb, fn_name)(*args)

    return build


def _kv_decode(fn_name: str):
    def decode(data: bytes):
        from ..storage import kvlogdb

        return getattr(kvlogdb, fn_name)(data)

    return decode


# -- snapshot container -----------------------------------------------------
def _snapio_container() -> bytes:
    from ..pb import CompressionType
    from ..storage import snapshotio

    buf = BytesIO()
    w = snapshotio.SnapshotWriter(
        buf, index=100, term=2, membership=_membership(),
        sessions=b"sess", on_disk=False,
        compression=int(CompressionType.ZLIB), block_size=256,
    )
    w.write(b"the-sm-payload " * 64)  # > 1 block, compressible
    w.add_external_file(SnapshotFile(file_id=1, filepath="ext/sst-1",
                                     file_size=512, metadata=b"meta"))
    w.close()
    return buf.getvalue()


def _snapio_decode(data: bytes):
    from ..storage import snapshotio

    r = snapshotio.SnapshotReader(BytesIO(data))
    r.validate()
    return r


def _snapio_future() -> bytes:
    out = bytearray(_snapio_container())
    out[4] = 3  # container version byte
    return bytes(out)


def _snapio_errors() -> Tuple[type, ...]:
    from ..storage import snapshotio

    return (snapshotio.SnapshotCorruptError,)


# -- ondisk SM commands -----------------------------------------------------
def _ondisk_cmd(op: str) -> Callable[[], bytes]:
    def build() -> bytes:
        from ..bigstate import ondisk

        if op == "put":
            return ondisk.put_cmd(b"key-1", b"value-1")
        return ondisk.del_cmd(b"key-1")

    return build


def _ondisk_decode(data: bytes):
    from ..bigstate import ondisk

    return ondisk.decode_cmd(data)


def _ondisk_future() -> bytes:
    from ..bigstate import ondisk

    out = bytearray(ondisk.put_cmd(b"key-1", b"value-1"))
    out[0] = 9  # unknown op
    return bytes(out)


# -- DR manifest ------------------------------------------------------------
def _manifest(format_version: int = 1) -> bytes:
    from ..bigstate import dr
    from ..pb import CompressionType

    m = SnapshotManifest(
        format_version=1,
        shard_id=1,
        replica_id=2,
        index=100,
        term=2,
        on_disk=True,
        chunk_size=1 << 20,
        compression=CompressionType.NO_COMPRESSION,
        membership=_membership(),
        files=(ManifestFile(name="snapshot.dbss", size=4096,
                            sha256="ab" * 32, chunk_crcs=(1, 2, 3)),),
    )
    text = dr.manifest_to_json(m)
    if format_version != 1:
        obj = json.loads(text)
        obj["format_version"] = format_version
        text = json.dumps(obj, indent=2, sort_keys=True)
    return text.encode("utf-8")


def _manifest_decode(data: bytes):
    from ..bigstate import dr

    # the archive reader opens the manifest as text; undecodable bytes
    # reach manifest_from_json as replacement chars and fail its
    # structural checks — the shim mirrors that path
    return dr.manifest_from_json(data.decode("utf-8", "replace"))


def _manifest_errors() -> Tuple[type, ...]:
    from ..bigstate import dr

    return (dr.ArchiveError,)


# -- resume-response frame (transport/tcp.py) -------------------------------
def _resume_resp_decode(data: bytes) -> int:
    """The KIND_RESUME_RESP payload: exactly one little-endian u64 (the
    receiver's next-chunk cursor).  tcp.query_resume degrades any
    malformed response to cursor 0; the shim raises the narrow type so
    the fuzz harness can tell 'rejected' from 'misparsed'."""
    if len(data) != 8:
        raise WireError(f"resume response must be 8 bytes, got {len(data)}")
    return struct.unpack("<Q", data)[0]


# ---------------------------------------------------------------------------
# skew invariants for flag-gated extensions
# ---------------------------------------------------------------------------
def _check_untraced_rpc_is_v0() -> Optional[str]:
    v0 = wire.encode_rpc_request(_rpc_request(traced=False))
    if struct.unpack_from("<I", v0, 0)[0] != 0:
        return "untraced rpc request stamped a non-zero bin_ver"
    v1 = wire.encode_rpc_request(_rpc_request(traced=True))
    if struct.unpack_from("<I", v1, 0)[0] != wire.RPC_BIN_VER:
        return "traced rpc request did not stamp RPC_BIN_VER"
    if v1[:len(v0)] == v0:
        return "traced frame must differ from v0 before the trailer"
    return None


def _check_batch_v0_decodes_unstamped() -> Optional[str]:
    d = wire.decode_batch(_batch_bytes(0, traced=False, strip_flag=True))
    if d.bin_ver != 0:
        return f"v0 batch decoded with bin_ver {d.bin_ver}"
    if d.messages[0].trace_id != 0:
        return "v0 batch grew a trace id from nowhere"
    # re-encode always stamps the CURRENT layout
    from ..pb import MESSAGE_BATCH_BIN_VER

    re = wire.encode_batch(d)
    if wire.decode_batch(re).bin_ver != MESSAGE_BATCH_BIN_VER:
        return "re-encode of a v0 batch did not stamp the current bin_ver"
    return None


def _check_stats_trailer_flag_gated() -> Optional[str]:
    plain = wire.encode_rpc_stats("nhid-aaaa", "n1:7100", _stats_rows())
    _, _, _, read_paths = wire.decode_rpc_stats(plain)
    if read_paths != {}:
        return "stats decode invented a read-path trailer"
    with_rp = wire.encode_rpc_stats(
        "nhid-aaaa", "n1:7100", _stats_rows(),
        read_paths={"follower": 3, "lease": 9},
    )
    _, _, _, read_paths = wire.decode_rpc_stats(with_rp)
    if read_paths != {"follower": 3, "lease": 9}:
        return "stats read-path trailer did not round-trip"
    return None


def _check_obs_query_empty_defaults() -> Optional[str]:
    if wire.decode_obs_query(b"") != (0, 0, 256):
        return "empty obs query did not decode as the v0 defaults"
    return None


# ---------------------------------------------------------------------------
# the registry
# ---------------------------------------------------------------------------
_WIRE = "dragonboat_tpu/transport/wire.py"
_TCP = "dragonboat_tpu/transport/tcp.py"
_GOSSIP = "dragonboat_tpu/transport/gossip.py"
_TAN = "dragonboat_tpu/storage/tan.py"
_KVLOG = "dragonboat_tpu/storage/kvlogdb.py"
_SNAPIO = "dragonboat_tpu/storage/snapshotio.py"
_ONDISK = "dragonboat_tpu/bigstate/ondisk.py"
_DR = "dragonboat_tpu/bigstate/dr.py"

REGISTRY: Tuple[CodecEntry, ...] = (
    CodecEntry(
        name="batch",
        module=_WIRE,
        samples={
            "v0": lambda: _batch_bytes(0, traced=False, strip_flag=True),
            "v1": lambda: _batch_bytes(1, traced=True, strip_flag=False),
        },
        encode=lambda: wire.encode_batch(MessageBatch(
            messages=(_message(traced=True),), source_address="n1:7100",
            deployment_id=7)),
        decode=wire.decode_batch,
        errors=(WireError,),
        future=lambda: _batch_bytes(2, traced=True, strip_flag=False),
        checks=(_check_batch_v0_decodes_unstamped,),
        claims=("encode_batch", "decode_batch", "KIND_BATCH"),
        bound_fns=("decode_batch",),
    ),
    CodecEntry(
        name="snapshot_meta",
        module=_WIRE,
        samples={"plain": lambda: wire.encode_snapshot_meta(_snapshot())},
        encode=lambda: wire.encode_snapshot_meta(_snapshot()),
        decode=wire.decode_snapshot_meta,
        errors=(WireError,),
        claims=("encode_snapshot_meta", "decode_snapshot_meta"),
        bound_fns=("decode_snapshot_meta",),
    ),
    CodecEntry(
        name="chunk",
        module=_WIRE,
        samples={
            "plain": lambda: wire.encode_chunk(_chunk(file_info=False)),
            "file_info": lambda: wire.encode_chunk(_chunk(file_info=True)),
        },
        encode=lambda: wire.encode_chunk(_chunk(file_info=True)),
        decode=wire.decode_chunk,
        errors=(WireError,),
        claims=("encode_chunk", "decode_chunk", "KIND_CHUNK",
                "KIND_RESUME_QUERY"),
        bound_fns=("decode_chunk",),
    ),
    CodecEntry(
        name="resume_resp",
        module=_TCP,
        samples={"v0": lambda: struct.pack("<Q", 5)},
        encode=lambda: struct.pack("<Q", 5),
        decode=_resume_resp_decode,
        errors=(WireError,),
        claims=("KIND_RESUME_RESP",),
    ),
    CodecEntry(
        name="config_change",
        module=_WIRE,
        samples={"v0": lambda: wire.encode_config_change(ConfigChange(
            config_change_id=7, type=ConfigChangeType.ADD_NON_VOTING,
            replica_id=42, address="n9:7100", initialize=True))},
        encode=lambda: wire.encode_config_change(ConfigChange(
            config_change_id=7, type=ConfigChangeType.ADD_NON_VOTING,
            replica_id=42, address="n9:7100", initialize=True)),
        decode=wire.decode_config_change,
        errors=(WireError,),
        claims=("encode_config_change", "decode_config_change"),
        bound_fns=("decode_config_change",),
    ),
    CodecEntry(
        name="session_table",
        module=_WIRE,
        samples={"v0": lambda: wire.encode_session_table(
            _session_rows())},
        encode=lambda: wire.encode_session_table(_session_rows()),
        decode=wire.decode_session_table,
        errors=(WireError,),
        claims=("encode_session_table", "decode_session_table"),
        bound_fns=("decode_session_table",),
    ),
    CodecEntry(
        name="rsm_snapshot",
        module=_WIRE,
        samples={"v2": _rsm_snapshot_bytes},
        encode=_rsm_snapshot_bytes,
        decode=wire.decode_rsm_snapshot,
        errors=(WireError,),
        future=lambda: bytes([3]) + _rsm_snapshot_bytes()[1:],
        claims=("encode_rsm_snapshot", "decode_rsm_snapshot",
                "RSM_SNAPSHOT_VERSION"),
        bound_fns=("decode_rsm_snapshot",),
    ),
    CodecEntry(
        name="rpc_request",
        module=_WIRE,
        samples={
            "v0": lambda: wire.encode_rpc_request(_rpc_request(False)),
            "v1": lambda: wire.encode_rpc_request(_rpc_request(True)),
        },
        encode=lambda: wire.encode_rpc_request(_rpc_request(True)),
        decode=wire.decode_rpc_request,
        errors=(WireError,),
        future=lambda: _u32_patched(
            wire.encode_rpc_request(_rpc_request(True)), 0,
            wire.RPC_BIN_VER + 1),
        checks=(_check_untraced_rpc_is_v0,),
        claims=("encode_rpc_request", "decode_rpc_request",
                "KIND_RPC_REQ", "RPC_BIN_VER"),
        bound_fns=("decode_rpc_request",),
    ),
    CodecEntry(
        name="rpc_response",
        module=_WIRE,
        samples={"v1": lambda: wire.encode_rpc_response(wire.RpcResponse(
            req_id=42, code=0, value=1, data=b"result", error=""))},
        encode=lambda: wire.encode_rpc_response(wire.RpcResponse(
            req_id=42, code=0, value=1, data=b"result", error="")),
        decode=wire.decode_rpc_response,
        errors=(WireError,),
        future=lambda: _u32_patched(
            wire.encode_rpc_response(wire.RpcResponse(req_id=42)), 0,
            wire.RPC_BIN_VER + 1),
        claims=("encode_rpc_response", "decode_rpc_response",
                "KIND_RPC_RESP"),
        bound_fns=("decode_rpc_response",),
    ),
    CodecEntry(
        name="rpc_value",
        module=_WIRE,
        samples={
            "none": lambda: wire.encode_rpc_value(None),
            "bytes": lambda: wire.encode_rpc_value(b"\x00\x01value"),
            "str": lambda: wire.encode_rpc_value("value"),
            "int": lambda: wire.encode_rpc_value(12345),
            "json": lambda: wire.encode_rpc_value(
                {"applied": 100, "keys": [1, 2, 3]}),
        },
        encode=lambda: wire.encode_rpc_value({"applied": 100}),
        decode=wire.decode_rpc_value,
        errors=(WireError,),
        # tag bytes above RPC_VAL_JSON are the future lane
        future=lambda: bytes([9]) + wire.encode_rpc_value(None)[1:],
        claims=("encode_rpc_value", "decode_rpc_value"),
        bound_fns=("decode_rpc_value",),
    ),
    CodecEntry(
        name="rpc_stats",
        module=_WIRE,
        samples={
            "v0": lambda: wire.encode_rpc_stats(
                "nhid-aaaa", "n1:7100", _stats_rows()),
            "readpaths": lambda: wire.encode_rpc_stats(
                "nhid-aaaa", "n1:7100", _stats_rows(),
                read_paths={"follower": 3, "lease": 9}),
        },
        encode=lambda: wire.encode_rpc_stats(
            "nhid-aaaa", "n1:7100", _stats_rows()),
        decode=wire.decode_rpc_stats,
        errors=(WireError,),
        checks=(_check_stats_trailer_flag_gated,),
        claims=("encode_rpc_stats", "decode_rpc_stats"),
        bound_fns=("decode_rpc_stats",),
    ),
    CodecEntry(
        name="obs_query",
        module=_WIRE,
        samples={
            "v1": lambda: wire.encode_obs_query(cursor=17, epoch=2,
                                                limit=128),
            "empty": lambda: b"",
        },
        encode=lambda: wire.encode_obs_query(cursor=17, epoch=2, limit=128),
        decode=wire.decode_obs_query,
        errors=(WireError,),
        future=lambda: _u32_patched(
            wire.encode_obs_query(), 0, wire.OBS_BIN_VER + 1),
        checks=(_check_obs_query_empty_defaults,),
        claims=("encode_obs_query", "decode_obs_query", "OBS_BIN_VER"),
        bound_fns=("decode_obs_query",),
    ),
    CodecEntry(
        name="obs_reply",
        module=_WIRE,
        samples={"v1": lambda: wire.encode_obs_reply(
            {"metrics": {"counters": {"proposals": 5}}, "epoch": 2})},
        encode=lambda: wire.encode_obs_reply({"epoch": 2}),
        decode=wire.decode_obs_reply,
        errors=(WireError,),
        future=lambda: json.dumps(
            {"v": wire.OBS_BIN_VER + 1, "epoch": 2},
            separators=(",", ":")).encode("utf-8"),
        claims=("encode_obs_reply", "decode_obs_reply"),
        bound_fns=("decode_obs_reply",),
    ),
    CodecEntry(
        name="gossip_packet",
        module=_GOSSIP,
        samples={"v0": _gossip_packet},
        encode=_gossip_packet,
        decode=_gossip_decode,
        errors=(),
        none_on_error=True,
        # no version field: an unknown-magic packet must read as None
        future=lambda: b"\xff\xff\xff\xff" + _gossip_packet()[4:],
        bound_fns=("_decode_table",),
    ),
    CodecEntry(
        name="tan_state_entries",
        module=_TAN,
        samples={"v0": _tan_record(
            "K_STATE_ENTRIES",
            _tan_body("_encode_state_entries", _tan_update))},
        decode=_tan_decode,
        errors=(WireError,),
        # an unknown kind byte is tan's future lane: refused, then the
        # journal-level replay surfaces it as mid-log corruption
        future=lambda: bytes([0x3F]) + b"\x00" * 16,
        claims=("K_STATE_ENTRIES",),
        bound_fns=("TanLogDB._apply_record",),
    ),
    CodecEntry(
        name="tan_snapshot",
        module=_TAN,
        samples={"v0": _tan_record(
            "K_SNAPSHOT",
            _tan_body("_encode_snapshot", lambda: 1, lambda: 2, _snapshot))},
        decode=_tan_decode,
        errors=(WireError,),
        claims=("K_SNAPSHOT",),
    ),
    CodecEntry(
        name="tan_bootstrap",
        module=_TAN,
        samples={"v0": _tan_record(
            "K_BOOTSTRAP",
            _tan_body("_encode_bootstrap", lambda: 1, lambda: 2,
                      lambda: Bootstrap(addresses={1: "n1:7100",
                                                   2: "n2:7100"},
                                        join=False)))},
        decode=_tan_decode,
        errors=(WireError,),
        claims=("K_BOOTSTRAP",),
    ),
    CodecEntry(
        name="tan_remove_to",
        module=_TAN,
        samples={"v0": _tan_record(
            "K_REMOVE_TO",
            _tan_body("_encode_pair_index", lambda: 1, lambda: 2,
                      lambda: 50))},
        decode=_tan_decode,
        errors=(WireError,),
        claims=("K_REMOVE_TO",),
    ),
    CodecEntry(
        name="tan_remove_node",
        module=_TAN,
        samples={"v0": _tan_record(
            "K_REMOVE_NODE",
            _tan_body("_encode_pair", lambda: 1, lambda: 2))},
        decode=_tan_decode,
        errors=(WireError,),
        claims=("K_REMOVE_NODE",),
    ),
    CodecEntry(
        name="kv_entries",
        module=_KVLOG,
        samples={"v0": _kv("_enc_entries", list(_entries()))},
        decode=_kv_decode("_dec_entries"),
        errors=(WireError,),
        claims=("K_ENTRY",),
        bound_fns=("_dec_entries",),
    ),
    CodecEntry(
        name="kv_state",
        module=_KVLOG,
        samples={"v0": _kv("_enc_state", State(term=2, vote=1, commit=8))},
        decode=_kv_decode("_dec_state"),
        errors=(WireError,),
        claims=("K_STATE",),
        bound_fns=("_dec_state",),
    ),
    CodecEntry(
        name="kv_bootstrap",
        module=_KVLOG,
        samples={"v0": _kv("_enc_bootstrap", Bootstrap(
            addresses={1: "n1:7100", 2: "n2:7100"}, join=True))},
        decode=_kv_decode("_dec_bootstrap"),
        errors=(WireError,),
        claims=("K_BOOTSTRAP", "K_SNAPSHOT", "K_MININDEX"),
        bound_fns=("_dec_bootstrap",),
    ),
    CodecEntry(
        name="snapshotio_container",
        module=_SNAPIO,
        samples={"v2": _snapio_container},
        decode=_snapio_decode,
        errors=_snapio_errors(),
        future=_snapio_future,
        claims=("VERSION",),
        bound_fns=("_SMStream._next_block", "SnapshotReader.__init__"),
        # a corrupt-but-valid-CRC compressed block may legally inflate
        # up to the container's block bound before the size check fires
        alloc_slack=64 * 1024 * 1024,
    ),
    CodecEntry(
        name="ondisk_cmd",
        module=_ONDISK,
        samples={"put": _ondisk_cmd("put"), "del": _ondisk_cmd("del")},
        decode=_ondisk_decode,
        errors=(ValueError,),
        future=_ondisk_future,
        claims=("decode_cmd", "_BASE_VERSION"),
        bound_fns=("decode_cmd",),
    ),
    CodecEntry(
        name="dr_manifest",
        module=_DR,
        samples={"v1": _manifest},
        decode=_manifest_decode,
        errors=_manifest_errors(),
        future=lambda: _manifest(format_version=2),
        bound_fns=("manifest_from_json",),
    ),
)


def _session_rows():
    from ..statemachine import Result

    return [
        (11, 3, {1: Result(value=9, data=b"x"), 2: Result(value=8)}),
        (5, 0, {}),
        (99, 7, {7: Result(data=b"\x00" * 64)}),
    ]


def entry(name: str) -> CodecEntry:
    for e in REGISTRY:
        if e.name == name:
            return e
    raise KeyError(name)


def claimed_names(module: str) -> frozenset:
    """Every codec name/constant the registry claims for `module`."""
    names = set(EXTRA_CLAIMS.get(module, ()))
    for e in REGISTRY:
        if e.module == module:
            names.update(e.claims)
    return frozenset(names)
