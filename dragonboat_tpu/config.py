"""Configuration objects (reference: config/config.go [U]).

``Config`` is per-replica, ``NodeHostConfig`` per-process, ``ExpertConfig``
holds the sanctioned plug points — including ``step_engine_factory``, the
TPU-native addition that swaps the serial host step loop for the vectorized
device engine (the north-star plug point beside ``logdb_factory`` /
``transport_factory``).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional


class ConfigError(ValueError):
    pass


@dataclass
class Config:
    """Per-replica raft configuration (reference: config.Config [U]).

    Time is logical: ``election_rtt`` / ``heartbeat_rtt`` are in units of
    ``NodeHostConfig.rtt_millisecond`` ticks — never wall clock.  This is
    what makes the protocol core a pure, reproducible function and lets it
    run on device.
    """

    replica_id: int = 0
    shard_id: int = 0
    check_quorum: bool = False
    pre_vote: bool = False
    election_rtt: int = 10
    heartbeat_rtt: int = 1
    snapshot_entries: int = 0          # 0 disables periodic snapshots
    compaction_overhead: int = 5
    ordered_config_change: bool = False
    max_in_mem_log_size: int = 0       # 0 = unlimited (bytes)
    snapshot_compression: int = 0
    entry_compression: int = 0
    disable_auto_compactions: bool = False
    is_non_voting: bool = False
    is_witness: bool = False
    quiesce: bool = False

    def validate(self) -> None:
        if self.replica_id == 0:
            raise ConfigError("invalid replica_id 0")
        if self.heartbeat_rtt <= 0:
            raise ConfigError("heartbeat_rtt must be > 0")
        if self.election_rtt <= 2 * self.heartbeat_rtt:
            raise ConfigError("election_rtt must be > 2 * heartbeat_rtt")
        if self.election_rtt < 10 * self.heartbeat_rtt:
            import warnings

            warnings.warn(
                "election_rtt < 10 * heartbeat_rtt; recommended ratio is 10x"
            )
        if self.max_in_mem_log_size != 0 and self.max_in_mem_log_size < 65536:
            raise ConfigError("max_in_mem_log_size must be >= 64KiB or 0")
        from .pb import CompressionType

        try:
            CompressionType(self.snapshot_compression)
        except ValueError:
            raise ConfigError(
                f"invalid snapshot_compression {self.snapshot_compression}"
            )
        if self.is_witness and self.snapshot_entries > 0:
            raise ConfigError("witness can not take snapshots")
        if self.is_witness and self.is_non_voting:
            raise ConfigError("witness can not be a non-voting replica")


@dataclass
class GossipConfig:
    """Gossip-registry config (reference: config.GossipConfig [U])."""

    bind_address: str = ""
    advertise_address: str = ""
    seed: list = field(default_factory=list)

    def is_empty(self) -> bool:
        return not self.bind_address


@dataclass
class ExpertConfig:
    """Advanced tuning + plug points (reference: config.ExpertConfig [U]).

    ``step_engine_factory`` is the TPU-native addition described in the
    north star: a callable ``(nodehost) -> IStepEngine`` that replaces the
    default host step loop with the vectorized device engine.
    """

    engine: "EngineConfig" = None  # type: ignore[assignment]
    logdb_factory: Optional[Callable] = None
    transport_factory: Optional[Callable] = None
    step_engine_factory: Optional[Callable] = None
    snapshot_storage_factory: Optional[Callable] = None
    fs: Optional[object] = None              # vfs injection for tests
    test_node_host_id: int = 0
    test_gossip_probe_interval_ms: int = 0

    def __post_init__(self):
        if self.engine is None:
            self.engine = EngineConfig()


@dataclass
class EngineConfig:
    """Worker-pool sizing (reference: config.EngineConfig / settings.Soft [U])."""

    exec_shards: int = 16
    commit_shards: int = 16
    apply_shards: int = 16
    snapshot_shards: int = 48
    close_shards: int = 32


@dataclass
class NodeHostConfig:
    """Per-process configuration (reference: config.NodeHostConfig [U]).

    ``tick_sweep_batch`` coarsens the host ticker: the per-node sweep
    runs only every Nth ``rtt_millisecond`` period, crediting N logical
    ticks at once — the same logical tick RATE at 1/N the per-node host
    cost (the mass-start tooling knob, formerly the undocumented
    ``TICK_SWEEP_BATCH`` env var, which remains honoured when this field
    is 0).  Timing-granularity implication: election/heartbeat/quiesce
    deadlines are still crossed at the right tick COUNT, but the
    crossing is only observed at sweep boundaries, so any raft timer can
    fire up to ``(N-1) * rtt_millisecond`` wall-clock late and N ticks
    land in one step with no wall time between them for responses.
    Keep ``N * heartbeat_rtt`` well under ``election_rtt`` or healthy
    leaders will flap; intended for experiments and mass-start tooling,
    not steady-state deployments.  0 = use the env var, else 1.
    """

    deployment_id: int = 0
    nodehost_dir: str = ""
    wal_dir: str = ""
    rtt_millisecond: int = 200
    raft_address: str = ""
    address_by_nodehost_id: bool = False
    listen_address: str = ""
    mutual_tls: bool = False
    ca_file: str = ""
    cert_file: str = ""
    key_file: str = ""
    max_send_queue_size: int = 0
    max_receive_queue_size: int = 0
    max_snapshot_send_bytes_per_second: int = 0
    max_snapshot_recv_bytes_per_second: int = 0
    notify_commit: bool = False
    enable_metrics: bool = False
    # observability (dragonboat_tpu.obs, docs/OBSERVABILITY.md): both
    # off by default; the disabled hot paths cost one attribute load.
    # ``trace_sample_rate`` bounds per-request tracing cost at high
    # rates (the sampling decision is made once, at the root span).
    enable_tracing: bool = False
    trace_sample_rate: float = 1.0
    enable_flight_recorder: bool = False
    tick_sweep_batch: int = 0  # 0 = TICK_SWEEP_BATCH env var, else 1
    gossip: GossipConfig = field(default_factory=GossipConfig)
    expert: ExpertConfig = field(default_factory=ExpertConfig)
    raft_event_listener: Optional[object] = None
    system_event_listener: Optional[object] = None

    def validate(self) -> None:
        if not self.nodehost_dir:
            raise ConfigError("nodehost_dir not set")
        if self.rtt_millisecond <= 0:
            raise ConfigError("rtt_millisecond must be > 0")
        if self.tick_sweep_batch < 0:
            raise ConfigError("tick_sweep_batch must be >= 0")
        if not 0.0 <= self.trace_sample_rate <= 1.0:
            raise ConfigError("trace_sample_rate must be in [0, 1]")
        if not self.raft_address:
            raise ConfigError("raft_address not set")
        if self.address_by_nodehost_id and self.gossip.is_empty():
            raise ConfigError("gossip config required for address_by_nodehost_id")

    def get_listen_address(self) -> str:
        return self.listen_address or self.raft_address
