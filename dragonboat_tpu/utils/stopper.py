"""Stopper: owned-thread lifecycle management.

reference: internal/utils/syncutil -> Stopper [U] — every goroutine the
reference spawns registers with a Stopper; Close() signals ShouldStop
and joins them all, so shutdown is deterministic and leak-checkable.
The same contract here for Python threads: components create a Stopper,
spawn workers through ``run_worker``, poll ``should_stop`` (or wait on
it) in their loops, and ``stop()`` joins everything with a deadline.
"""
from __future__ import annotations

import threading
from typing import Callable, List, Optional


class Stopper:
    def __init__(self, name: str = "stopper"):
        self.name = name
        self._should_stop = threading.Event()
        self._threads: List[threading.Thread] = []
        self._lock = threading.Lock()

    @property
    def should_stop(self) -> threading.Event:
        return self._should_stop

    def stopping(self) -> bool:
        return self._should_stop.is_set()

    def run_worker(
        self, fn: Callable[[], None], name: Optional[str] = None
    ) -> threading.Thread:
        """Spawn a managed worker.  ``fn`` must return promptly once
        ``should_stop`` is set."""
        if self._should_stop.is_set():
            raise RuntimeError(f"{self.name}: already stopped")
        t = threading.Thread(
            target=fn, name=name or f"{self.name}-worker", daemon=True
        )
        with self._lock:
            self._threads.append(t)
        t.start()
        return t

    def stop(self, timeout: float = 5.0) -> List[str]:
        """Signal + join all workers; returns the names of any that did
        not exit within the deadline (callers may assert it is empty —
        the leaktest contract)."""
        self._should_stop.set()
        with self._lock:
            threads = list(self._threads)
            self._threads.clear()
        leaked = []
        for t in threads:
            t.join(timeout=timeout)
            if t.is_alive():
                leaked.append(t.name)
        return leaked
