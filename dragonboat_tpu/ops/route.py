"""Device-side message routing: outbox -> co-located peer inboxes.

The reference's step workers hand every outbound message to the
transport, even when the destination replica lives in the same process
(reference: engine.go stepWorkerMain -> transport.Send [U]; the in-proc
loopback only short-circuits the socket).  On TPU that host detour is
the scaling bottleneck: at 100k groups x 3 replicas every row's traffic
would round-trip device->host->device each step.

``route`` keeps intra-device traffic ON the device: messages in a
``DeviceOut`` buffer whose destination replica is resident on the same
chip are scattered straight into the next step's ``Inbox``.  Combined
with ``ops/kernel.step`` this closes the loop — elections, replication
and commit advance run entirely device-side, which is what the
consensus benchmark (bench.py) measures.

Routing is **best-effort**: anything the router cannot deliver (peer
off-device, per-sender slot budget exhausted, REPLICATE entries no
longer reconstructible from the sender's ring) is DROPPED and counted.
Raft tolerates arbitrary message loss — drops cost retries, never
safety — so the fast path needs no overflow side-channel.

Slot assignment is direct-mapped, not sorted: the inbox is laid out as

    [0, base)                      host/injected slots (ticks, proposals)
    [base + r*budget, +budget)     messages from the sender holding slot
                                   r in the DESTINATION row's peer table

so a message's target slot is a pure per-message computation (one
cumulative count per sender), with no cross-row sort.  Per-sender
in-order delivery is preserved; ``base + P*budget <= M`` must hold.

Static tables (host-precomputed, see ``build_route_tables``):
  dest_row[g, p]      device row hosting (shard_id[g], peer_id[g, p]),
                      -1 when that replica is not on this device/shard
  rank_in_dest[g, p]  the slot index row g's replica occupies in THAT
                      row's peer table (the region selector above)
"""
from __future__ import annotations

from typing import Dict, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .types import (
    DeviceOut,
    DeviceState,
    F_COMMIT,
    F_HINT,
    F_HINT_HIGH,
    F_LOG_INDEX,
    F_LOG_TERM,
    F_MTYPE,
    F_N_ENTRIES,
    F_REJECT,
    F_TERM,
    F_TO,
    I32,
    Inbox,
    MT_PROPOSE,
    MT_REPLICATE,
    MT_TICK,
    ROLE_LEADER,
)


class RouteStats(NamedTuple):
    """Per-call routing outcome counters (all scalars)."""

    delivered: jnp.ndarray
    dropped_off_device: jnp.ndarray   # destination replica not resident
    dropped_budget: jnp.ndarray       # per-sender region full
    dropped_ring: jnp.ndarray         # REPLICATE entries aged out of ring
    suppressed: jnp.ndarray           # messages of escalated source rows

    def __add__(self, other: "RouteStats") -> "RouteStats":
        return RouteStats(*(a + b for a, b in zip(self, other)))


def build_route_tables(
    shard_ids: np.ndarray,
    replica_ids: np.ndarray,
    peer_ids: np.ndarray,
) -> Tuple[np.ndarray, np.ndarray]:
    """Host-side precompute of (dest_row, rank_in_dest) for a row layout.

    Rows are identified by (shard, replica); a peer slot whose replica is
    not hosted in this layout routes to -1 (off-device -> transport).
    """
    G, P = peer_ids.shape
    row_of: Dict[Tuple[int, int], int] = {
        (int(s), int(r)): g
        for g, (s, r) in enumerate(zip(shard_ids, replica_ids))
    }
    # per-row {pid: slot} so rank lookup is O(1), not a nonzero scan
    slot_of = [
        {int(pid): p for p, pid in enumerate(row) if pid}
        for row in peer_ids
    ]
    dest_row = np.full((G, P), -1, np.int32)
    rank_in_dest = np.zeros((G, P), np.int32)
    for g in range(G):
        shard = int(shard_ids[g])
        me = int(replica_ids[g])
        for p in range(P):
            pid = int(peer_ids[g, p])
            if pid == 0:
                continue
            d = row_of.get((shard, pid))
            if d is None:
                continue
            mine = slot_of[d].get(me)
            if mine is None:
                # destination doesn't know us (mid-membership-change):
                # no slot region is ours, and borrowing rank 0 would
                # silently collide with the real rank-0 sender — leave
                # it off-device so the drop is counted (or the host
                # transport carries it)
                continue
            dest_row[g, p] = d
            rank_in_dest[g, p] = mine
    return dest_row, rank_in_dest


def route(
    state: DeviceState,
    out: DeviceOut,
    dest_row: jnp.ndarray,
    rank_in_dest: jnp.ndarray,
    *,
    M: int,
    E: int,
    budget: int,
    base: int,
    base_inbox: Optional[Inbox] = None,
    suppress: Optional[jnp.ndarray] = None,
) -> Tuple[Inbox, RouteStats]:
    """Scatter ``out``'s messages into a fresh (or prefilled) Inbox.

    ``state`` must be the POST-step state of the sending rows: REPLICATE
    payloads are reconstructed from the sender's log-term ring, which
    holds the entries appended in the step that emitted the message.
    ``suppress`` masks source rows whose device effects were discarded
    (escalations): their messages must not be delivered.
    """
    G, O, _ = out.buf.shape
    P = state.P
    W = state.W
    if base + P * budget > M:
        raise ValueError(
            f"inbox too small: base={base} + P={P} * budget={budget} > M={M}"
        )

    buf = out.buf
    mtype = buf[:, :, F_MTYPE]
    to = buf[:, :, F_TO]
    n_ent = buf[:, :, F_N_ENTRIES]
    log_index = buf[:, :, F_LOG_INDEX]

    valid = jnp.arange(O)[None, :] < out.count[:, None]
    n_suppressed = jnp.zeros((), I32)
    if suppress is not None:
        n_suppressed = jnp.sum(
            valid & suppress[:, None], dtype=I32
        )
        valid = valid & ~suppress[:, None]

    # destination peer slot in the SENDER's table
    hits = (
        (state.peer_id[:, None, :] == to[:, :, None])
        & (to[:, :, None] != 0)
        & (state.peer_id[:, None, :] != 0)
    )  # [G, O, P]
    found = jnp.any(hits, axis=2)
    p_star = jnp.argmax(hits, axis=2).astype(I32)  # [G, O]

    dest = jnp.take_along_axis(dest_row, p_star, axis=1)      # [G, O]
    rank = jnp.take_along_axis(rank_in_dest, p_star, axis=1)  # [G, O]

    routable = valid & found
    on_device = routable & (dest >= 0)

    # per-sender emission index toward each peer slot (exclusive count)
    oh = (hits & valid[:, :, None]).astype(I32)               # [G, O, P]
    k_excl = jnp.cumsum(oh, axis=1) - oh
    k = jnp.take_along_axis(k_excl, p_star[:, :, None], axis=2)[:, :, 0]
    in_budget = k < budget

    # REPLICATE entry reconstruction from the sender's ring
    is_repl = mtype == MT_REPLICATE
    carries = is_repl & (n_ent > 0)
    win_lo = jnp.maximum(state.first_index, state.last_index - (W - 1))
    ring_ok = ~carries | (
        (log_index + 1 >= win_lo[:, None])
        & (log_index + n_ent <= state.last_index[:, None])
    )

    keep = on_device & in_budget & ring_ok
    slot_final = base + rank * budget + k                     # [G, O]
    didx = jnp.where(keep, dest, G)  # G = out-of-bounds -> mode='drop'

    if base_inbox is None:
        zm = jnp.zeros((G, M), I32)
        base_inbox = Inbox(
            mtype=zm, from_id=zm, term=zm, log_term=zm, log_index=zm,
            commit=zm, reject=zm, hint=zm, hint_high=zm, n_entries=zm,
            ent_term=jnp.zeros((G, M, E), I32),
            ent_cc=jnp.zeros((G, M, E), I32),
        )

    def put(dst, val):
        return dst.at[didx, slot_final].set(val, mode="drop")

    # gather the sender's ring terms/cc for carried entries
    idxs = log_index[:, :, None] + 1 + jnp.arange(E)[None, None, :]
    pos = (jnp.clip(idxs, 0, None) & (W - 1)).reshape(G, O * E)
    ent_term = jnp.take_along_axis(state.ring_term, pos, axis=1).reshape(
        G, O, E
    )
    ent_cc = jnp.take_along_axis(state.ring_cc, pos, axis=1).reshape(G, O, E)
    ent_mask = carries[:, :, None] & (
        jnp.arange(E)[None, None, :] < n_ent[:, :, None]
    )
    ent_term = jnp.where(ent_mask, ent_term, 0)
    ent_cc = jnp.where(ent_mask, ent_cc, 0)

    inbox = Inbox(
        mtype=put(base_inbox.mtype, mtype),
        from_id=put(
            base_inbox.from_id,
            jnp.broadcast_to(state.replica_id[:, None], (G, O)),
        ),
        term=put(base_inbox.term, buf[:, :, F_TERM]),
        log_term=put(base_inbox.log_term, buf[:, :, F_LOG_TERM]),
        log_index=put(base_inbox.log_index, log_index),
        commit=put(base_inbox.commit, buf[:, :, F_COMMIT]),
        reject=put(base_inbox.reject, buf[:, :, F_REJECT]),
        hint=put(base_inbox.hint, buf[:, :, F_HINT]),
        hint_high=put(base_inbox.hint_high, buf[:, :, F_HINT_HIGH]),
        n_entries=put(base_inbox.n_entries, n_ent),
        ent_term=base_inbox.ent_term.at[didx, slot_final].set(
            ent_term, mode="drop"
        ),
        ent_cc=base_inbox.ent_cc.at[didx, slot_final].set(
            ent_cc, mode="drop"
        ),
    )
    stats = RouteStats(
        delivered=jnp.sum(keep, dtype=I32),
        dropped_off_device=jnp.sum(routable & (dest < 0), dtype=I32),
        dropped_budget=jnp.sum(on_device & ~in_budget, dtype=I32),
        dropped_ring=jnp.sum(
            on_device & in_budget & ~ring_ok, dtype=I32
        ),
        suppressed=n_suppressed,
    )
    return inbox, stats


def make_prefill(
    state: DeviceState,
    M: int,
    E: int,
    *,
    tick: bool = True,
    propose_leaders: bool = False,
    propose_n: int = 1,
) -> Inbox:
    """Injected inbox prefix: slot 0 = LOCAL_TICK for every row, slot 1 =
    a ``propose_n``-entry PROPOSE on rows currently leading (the bench's
    load generator; empty slots stay NO_OP and cost nothing)."""
    G = state.G

    def zm():
        # distinct buffers per field: aliased zeros break donate_argnums
        # (XLA rejects donating the same buffer twice)
        return jnp.zeros((G, M), I32)

    mtype = zm()
    n_entries = zm()
    if tick:
        mtype = mtype.at[:, 0].set(MT_TICK)
    if propose_leaders:
        lead = state.role == ROLE_LEADER
        mtype = mtype.at[:, 1].set(jnp.where(lead, MT_PROPOSE, 0))
        n_entries = n_entries.at[:, 1].set(jnp.where(lead, propose_n, 0))
    return Inbox(
        mtype=mtype, from_id=zm(), term=zm(), log_term=zm(),
        log_index=zm(), commit=zm(), reject=zm(), hint=zm(),
        hint_high=zm(), n_entries=n_entries,
        ent_term=jnp.zeros((G, M, E), I32),
        ent_cc=jnp.zeros((G, M, E), I32),
    )


def routed_round(
    state: DeviceState,
    inbox: Inbox,
    dest_row: jnp.ndarray,
    rank_in_dest: jnp.ndarray,
    *,
    out_capacity: int,
    budget: int,
    base: int,
    propose_leaders: bool = False,
    propose_n: int = 1,
) -> Tuple[DeviceState, Inbox, RouteStats, jnp.ndarray]:
    """One full consensus round: step every row through ``inbox``, undo
    escalated rows (their device effects are discarded, exactly the
    host-replay contract minus the replay — dropping the inputs is
    raft-safe message loss), then route the outboxes into the next
    round's inbox on top of a fresh tick/proposal prefill.

    Returns (state', inbox', stats, escalated_row_count).
    """
    from . import kernel as K

    M, E = inbox.M, inbox.E
    new_state, out = K.step(state, inbox, out_capacity=out_capacity)
    esc = out.escalate != 0
    n_esc = jnp.sum(esc, dtype=I32)
    keep = ~esc

    def sel(a, b):
        m = keep.reshape((-1,) + (1,) * (a.ndim - 1))
        return jnp.where(m, b, a)

    state = jax.tree.map(sel, state, new_state)
    prefill = make_prefill(
        state, M, E,
        propose_leaders=propose_leaders, propose_n=propose_n,
    )
    inbox, stats = route(
        state, out, dest_row, rank_in_dest,
        M=M, E=E, budget=budget, base=base,
        base_inbox=prefill, suppress=esc,
    )
    return state, inbox, stats, n_esc
