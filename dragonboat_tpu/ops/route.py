"""Device-side message routing: outbox -> co-located peer inboxes.

The reference's step workers hand every outbound message to the
transport, even when the destination replica lives in the same process
(reference: engine.go stepWorkerMain -> transport.Send [U]; the in-proc
loopback only short-circuits the socket).  On TPU that host detour is
the scaling bottleneck: at 100k groups x 3 replicas every row's traffic
would round-trip device->host->device each step.

``route`` keeps intra-device traffic ON the device: messages in a
``DeviceOut`` buffer whose destination replica is resident on the same
chip are scattered straight into the next step's ``Inbox``.  Combined
with ``ops/kernel.step`` this closes the loop — elections, replication
and commit advance run entirely device-side, which is what the
consensus benchmark (bench.py) measures.

Routing is **best-effort**: anything the router cannot deliver (peer
off-device, per-sender slot budget exhausted, REPLICATE entries no
longer reconstructible from the sender's ring) is DROPPED and counted.
Raft tolerates arbitrary message loss — drops cost retries, never
safety — so the fast path needs no overflow side-channel.

Slot assignment is direct-mapped, not sorted: the inbox is laid out as

    [0, base)                      host/injected slots (ticks, proposals)
    [base + r*budget, +budget)     messages from the sender holding slot
                                   r in the DESTINATION row's peer table

so a message's target slot is a pure per-message computation (one
cumulative count per sender), with no cross-row sort.  Per-sender
in-order delivery is preserved.  ``base + P*budget == M`` must hold
exactly: the inbox IS the concatenation of the prefill columns and the
per-sender regions (route() assembles it by reshape, not scatter).

Static tables (host-precomputed, see ``build_route_tables``):
  dest_row[g, p]      device row hosting (shard_id[g], peer_id[g, p]),
                      -1 when that replica is not on this device/shard
  rank_in_dest[g, p]  the slot index row g's replica occupies in THAT
                      row's peer table (the region selector above)
"""
from __future__ import annotations

from typing import Dict, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .types import (
    DeviceOut,
    DeviceState,
    F_COMMIT,
    F_HINT,
    F_HINT_HIGH,
    F_LOG_INDEX,
    F_LOG_TERM,
    F_MTYPE,
    F_N_ENTRIES,
    F_REJECT,
    F_TERM,
    F_TO,
    I32,
    Inbox,
    MT_PROPOSE,
    MT_REPLICATE,
    MT_TICK,
    ROLE_LEADER,
)


class RouteStats(NamedTuple):
    """Per-call routing outcome counters (all scalars)."""

    delivered: jnp.ndarray
    dropped_off_device: jnp.ndarray   # destination replica not resident
    dropped_budget: jnp.ndarray       # per-sender region full
    dropped_ring: jnp.ndarray         # REPLICATE entries aged out of ring
    suppressed: jnp.ndarray           # messages of escalated source rows
    host_carried: jnp.ndarray         # deliberately left to the host path
    #                                   (forwarded PROPOSE, dest row dirty)

    def __add__(self, other: "RouteStats") -> "RouteStats":
        return RouteStats(*(a + b for a, b in zip(self, other)))


def build_route_tables(  # raftlint: ignore[host-sync] host-side numpy precompute of static tables
    shard_ids: np.ndarray,
    replica_ids: np.ndarray,
    peer_ids: np.ndarray,
) -> Tuple[np.ndarray, np.ndarray]:
    """Host-side precompute of (dest_row, rank_in_dest) for a row layout.

    Rows are identified by (shard, replica); a peer slot whose replica is
    not hosted in this layout routes to -1 (off-device -> transport).
    """
    G, P = peer_ids.shape
    row_of: Dict[Tuple[int, int], int] = {
        (int(s), int(r)): g
        for g, (s, r) in enumerate(zip(shard_ids, replica_ids))
    }
    # per-row {pid: slot} so rank lookup is O(1), not a nonzero scan
    slot_of = [
        {int(pid): p for p, pid in enumerate(row) if pid}
        for row in peer_ids
    ]
    dest_row = np.full((G, P), -1, np.int32)
    rank_in_dest = np.zeros((G, P), np.int32)
    for g in range(G):
        shard = int(shard_ids[g])
        me = int(replica_ids[g])
        for p in range(P):
            pid = int(peer_ids[g, p])
            if pid == 0:
                continue
            d = row_of.get((shard, pid))
            if d is None:
                continue
            mine = slot_of[d].get(me)
            if mine is None:
                # destination doesn't know us (mid-membership-change):
                # no slot region is ours, and borrowing rank 0 would
                # silently collide with the real rank-0 sender — leave
                # it off-device so the drop is counted (or the host
                # transport carries it)
                continue
            dest_row[g, p] = d
            rank_in_dest[g, p] = mine
    return dest_row, rank_in_dest


def route(
    state: DeviceState,
    out: DeviceOut,
    dest_row: jnp.ndarray,
    rank_in_dest: jnp.ndarray,
    *,
    M: int,
    E: int,
    budget: int,
    base: int,
    base_inbox: Optional[Inbox] = None,
    suppress: Optional[jnp.ndarray] = None,
    dest_alive: Optional[jnp.ndarray] = None,
) -> Tuple[Inbox, RouteStats, jnp.ndarray]:
    """Scatter ``out``'s messages into a fresh (or prefilled) Inbox.

    ``state`` must be the POST-step state of the sending rows: REPLICATE
    payloads are reconstructed from the sender's log-term ring, which
    holds the entries appended in the step that emitted the message.
    ``suppress`` masks source rows whose device effects were discarded
    (escalations): their messages must not be delivered.
    ``dest_alive`` ([G] bool) masks DESTINATION rows that must not be fed
    (engine rows on the host/scalar path): messages to them are left
    undelivered so the host transport can carry them instead.

    Returns ``(inbox, stats, delivered)`` where ``delivered`` is a
    [G, O] bool — True where outbox message o of row g was scattered
    into a peer row (the engine skips host decode for those).  Two
    message classes are never device-delivered even when the peer is
    resident: forwarded PROPOSE (its cmd payload exists only on the
    sending host) and anything addressed to the sender itself (the
    kernel's host-coordination READ_INDEX_RESP).
    """
    G, O, _ = out.buf.shape
    P = state.P
    W = state.W
    B = budget
    if base + P * B != M:
        raise ValueError(
            f"inbox layout mismatch: base={base} + P={P} * budget={B} "
            f"must equal M={M} (the inbox IS the region layout)"
        )

    # NOTE on lowering: NO arbitrary-index scatter anywhere (TPU lowers
    # data-dependent scatters to a serial loop — measured ~20x) and
    # per-ELEMENT gathers are avoided too (~18 ns/element serialized,
    # measured r5 — a dozen [G,P,B] field gathers dominated the round).
    # The only gather left is ONE cross-row gather of packed per-sender
    # rows (row gathers amortize to ~1 ns/element); everything else is
    # one-hot select / reduce over a small axis.  The direct-mapped slot
    # layout makes the inbox exactly
    # ``concat([prefill, region(r=0), ..., region(r=P-1)], axis=1)``.

    buf = out.buf
    mtype = buf[:, :, F_MTYPE]
    to = buf[:, :, F_TO]
    n_ent = buf[:, :, F_N_ENTRIES]
    log_index = buf[:, :, F_LOG_INDEX]
    log_term = buf[:, :, F_LOG_TERM]

    valid = jnp.arange(O)[None, :] < out.count[:, None]
    n_suppressed = jnp.zeros((), I32)
    if suppress is not None:
        n_suppressed = jnp.sum(valid & suppress[:, None], dtype=I32)
        valid = valid & ~suppress[:, None]

    # destination peer slot in the SENDER's table
    hits = (
        (state.peer_id[:, None, :] == to[:, :, None])
        & (to[:, :, None] != 0)
        & (state.peer_id[:, None, :] != 0)
    )  # [G, O, P]
    found = jnp.any(hits, axis=2)
    routable = valid & found

    # per-peer destination facts, [G, P] (static tables — elementwise)
    dest_ge0 = dest_row >= 0
    dest_not_self = dest_row != jnp.arange(G)[:, None]
    if dest_alive is not None:
        # [G, P] per-element gather over the static table: tiny next to
        # the per-message alternative (dest_alive[dest] was [G, O])
        alive_tab = dest_alive[jnp.clip(dest_row, 0, G - 1)] & dest_ge0
    else:
        alive_tab = dest_ge0

    def at_pstar(tab):  # tab [G, P] -> per-message [G, O] via the one-hot
        return jnp.any(hits & tab[:, None, :], axis=2)

    on_device = routable & at_pstar(dest_ge0)

    # deliverability per MESSAGE (sender side; used for selection + stats)
    is_repl = mtype == MT_REPLICATE
    carries = is_repl & (n_ent > 0)
    win_lo = jnp.maximum(state.first_index, state.last_index - (W - 1))
    # a log_term=0 marker on a nonzero prev is the kernel's below-ring
    # HOST-FIXUP request (_send_replicate): the true prev term must be
    # stamped by the sender's host before delivery.  The entries-only
    # window check passes at prev == win_lo - 1 (entries start at
    # prev+1), so without this the one-below-window REPLICATE would be
    # device-delivered with a fake prev term (review finding).
    marker = is_repl & (log_index > 0) & (log_term == 0)
    ring_ok = ~carries | (
        (log_index + 1 >= win_lo[:, None])
        & (log_index + n_ent <= state.last_index[:, None])
        & ~marker
    )

    # host-only classes: forwarded PROPOSE (cmd bytes never reach the
    # device) and self-addressed coordination messages; plus messages
    # whose destination row is currently host-authoritative (dirty)
    not_propose = mtype != MT_PROPOSE
    msg_ok = not_propose & at_pstar(dest_not_self) & at_pstar(alive_tab)

    # per-sender emission index toward each peer slot, counted over
    # DELIVERABLE messages only — host-carried/ring-stale messages must
    # not consume budget ranks they will never occupy (their slot would
    # sit empty while a later deliverable message got pushed past B)
    deliverable = valid & ring_ok & msg_ok  # [G, O]
    oh = (hits & deliverable[:, :, None]).astype(I32)  # [G, O, P]
    k_excl = jnp.cumsum(oh, axis=1) - oh
    k = jnp.sum(jnp.where(hits, k_excl, 0), axis=2)  # k_excl at p_star

    # SENDER-side selection + packing.  m_b (at most one outbox slot per
    # (g, p, b)) doubles as the one-hot selector for every field — no
    # o_sel index materialization, no per-element field gathers.
    sendable = hits & deliverable[:, :, None]  # [G, O, P]
    sel_b = []
    for b in range(B):
        sel_b.append(sendable & (k_excl == b))
    send_sel = jnp.stack(sel_b, axis=3)  # [G, O, P, B]
    pick_found = jnp.any(send_sel, axis=1)  # [G, P, B]

    def pick(col):  # [G, P, B]: buf[g, o_sel[g,p,b], col] via one-hot
        return jnp.sum(
            jnp.where(send_sel, buf[:, :, col][:, :, None, None], 0),
            axis=1,
        )

    wire_cols = (
        F_MTYPE, F_TERM, F_LOG_TERM, F_LOG_INDEX, F_COMMIT,
        F_REJECT, F_HINT, F_HINT_HIGH, F_N_ENTRIES,
    )
    picked = {c: pick(c) for c in wire_cols}

    # REPLICATE payload, sender-side: ring terms/cc at [li+1, li+n] via
    # one-hot over the W ring positions (per-element ring gathers were
    # the single most expensive op of the old route)
    li_pb = picked[F_LOG_INDEX]
    n_pb = picked[F_N_ENTRIES]
    repl_pb = pick_found & (picked[F_MTYPE] == MT_REPLICATE)
    wm = W - 1
    went = []
    for e in range(E):
        pos = (jnp.clip(li_pb + 1 + e, 0, None) & wm)  # [G, P, B]
        selw = (
            pos[:, :, :, None] == jnp.arange(W)[None, None, None, :]
        )  # [G, P, B, W]
        has_e = repl_pb & (e < n_pb)
        et = jnp.sum(
            jnp.where(selw, state.ring_term[:, None, None, :], 0), axis=3
        )
        ec = jnp.sum(
            jnp.where(selw, state.ring_cc[:, None, None, :], 0), axis=3
        )
        went.append((
            jnp.where(has_e, et, 0), jnp.where(has_e, ec, 0),
        ))
    ent_term_s = jnp.stack([t for t, _ in went], axis=3)  # [G, P, B, E]
    ent_cc_s = jnp.stack([c for _, c in went], axis=3)

    # pack everything a receiver needs into one row per (sender, slot):
    # 9 wire fields + found + from_id + E terms + E cc bits
    from_pb = jnp.broadcast_to(
        state.replica_id[:, None, None], (G, P, B)
    )
    pack = jnp.stack(
        [picked[c] for c in wire_cols]
        + [pick_found.astype(I32), from_pb],
        axis=3,
    )  # [G, P, B, 11]
    # packed-row layout (single source of truth for the unpack below)
    IDX_FOUND = len(wire_cols)      # found flag
    IDX_FROM = len(wire_cols) + 1   # sender replica id
    KF = len(wire_cols) + 2         # ent_term starts here
    pack = jnp.concatenate([pack, ent_term_s, ent_cc_s], axis=3)
    KT = KF + 2 * E
    packr = pack.reshape(G * P, B * KT)

    # dest-side assembly: for dest d, region r is fed by the replica in
    # d's peer slot r; in THAT sender's table, d occupies slot
    # rank_in_dest[d, r] (the mapping is symmetric by construction).
    # ONE cross-row row-gather moves the packed rows.
    src = dest_row                                   # [G, P] (as dest view)
    src_ok = src >= 0
    src_c = jnp.clip(src, 0, G - 1)
    flat = (src_c * P + rank_in_dest).reshape(-1)    # [G*P]
    region = packr[flat].reshape(G, P, B, KT)
    # region r of row d must not be fed by d itself (its own slot)
    not_self_d = src_c != jnp.arange(G)[:, None]
    sel_found = (
        (region[:, :, :, IDX_FOUND] != 0)
        & src_ok[:, :, None]
        & not_self_d[:, :, None]
    )  # [G, P, B]

    def field(i):  # unpack + mask + flatten one received field
        return jnp.where(sel_found, region[:, :, :, i], 0).reshape(G, P * B)

    if base_inbox is None:
        base_inbox = make_prefill(state, M, E, tick=False)
    pre = {k_: getattr(base_inbox, k_)[:, :base] for k_ in (
        "mtype", "from_id", "term", "log_term", "log_index", "commit",
        "reject", "hint", "hint_high", "n_entries",
    )}

    col_at = {c: i for i, c in enumerate(wire_cols)}

    def asm(name, col):
        return jnp.concatenate([pre[name], field(col_at[col])], axis=1)

    ent_term = jnp.where(
        sel_found[:, :, :, None], region[:, :, :, KF:KF + E], 0
    ).reshape(G, P * B, E)
    ent_cc = jnp.where(
        sel_found[:, :, :, None], region[:, :, :, KF + E:KT], 0
    ).reshape(G, P * B, E)

    inbox = Inbox(
        mtype=asm("mtype", F_MTYPE),
        from_id=jnp.concatenate(
            [pre["from_id"], field(IDX_FROM)], axis=1
        ),
        term=asm("term", F_TERM),
        log_term=asm("log_term", F_LOG_TERM),
        log_index=asm("log_index", F_LOG_INDEX),
        commit=asm("commit", F_COMMIT),
        reject=asm("reject", F_REJECT),
        hint=asm("hint", F_HINT),
        hint_high=asm("hint_high", F_HINT_HIGH),
        n_entries=asm("n_entries", F_N_ENTRIES),
        ent_term=jnp.concatenate(
            [base_inbox.ent_term[:, :base], ent_term], axis=1
        ),
        ent_cc=jnp.concatenate(
            [base_inbox.ent_cc[:, :base], ent_cc], axis=1
        ),
    )
    in_budget = k < B
    delivered = valid & found & ring_ok & msg_ok & in_budget  # [G, O]
    stats = RouteStats(
        delivered=jnp.sum(sel_found, dtype=I32),
        dropped_off_device=jnp.sum(
            routable & ~at_pstar(dest_ge0), dtype=I32
        ),
        dropped_budget=jnp.sum(
            on_device & msg_ok & ring_ok & ~in_budget, dtype=I32
        ),
        dropped_ring=jnp.sum(on_device & msg_ok & ~ring_ok, dtype=I32),
        suppressed=n_suppressed,
        host_carried=jnp.sum(on_device & ~msg_ok, dtype=I32),
    )
    return inbox, stats, delivered


def make_prefill(
    state: DeviceState,
    M: int,
    E: int,
    *,
    tick: bool = True,
    propose_leaders: bool = False,
    propose_n: int = 1,
) -> Inbox:
    """Injected inbox prefix: slot 0 = LOCAL_TICK for every row, slot 1 =
    a ``propose_n``-entry PROPOSE on rows currently leading (the bench's
    load generator; empty slots stay NO_OP and cost nothing)."""
    G = state.G

    def zm():
        # distinct buffers per field: aliased zeros break donate_argnums
        # (XLA rejects donating the same buffer twice)
        return jnp.zeros((G, M), I32)

    mtype = zm()
    n_entries = zm()
    if tick:
        mtype = mtype.at[:, 0].set(MT_TICK)
    if propose_leaders:
        lead = state.role == ROLE_LEADER
        mtype = mtype.at[:, 1].set(jnp.where(lead, MT_PROPOSE, 0))
        n_entries = n_entries.at[:, 1].set(jnp.where(lead, propose_n, 0))
    return Inbox(
        mtype=mtype, from_id=zm(), term=zm(), log_term=zm(),
        log_index=zm(), commit=zm(), reject=zm(), hint=zm(),
        hint_high=zm(), n_entries=n_entries,
        ent_term=jnp.zeros((G, M, E), I32),
        ent_cc=jnp.zeros((G, M, E), I32),
    )


def merge_and_route(
    old_state: DeviceState,
    new_state: DeviceState,
    out,
    dest_row: jnp.ndarray,
    rank_in_dest: jnp.ndarray,
    *,
    M: int,
    E: int,
    budget: int,
    base: int,
    propose_leaders: bool = False,
    propose_n: int = 1,
) -> Tuple[DeviceState, Inbox, RouteStats, jnp.ndarray]:
    """The post-step tail of a consensus round: undo escalated rows
    (their device effects are discarded — the host-replay contract minus
    the replay; dropping the inputs is raft-safe message loss), then
    route the outboxes into the next round's inbox on top of a fresh
    tick/proposal prefill.  Shared by ``routed_round`` and callers that
    jit step/route as SEPARATE programs for compile time (bench.py).

    Returns (state', inbox', stats, escalated_row_count).
    """
    esc = out.escalate != 0
    n_esc = jnp.sum(esc, dtype=I32)
    keep = ~esc

    def sel(a, b):
        m = keep.reshape((-1,) + (1,) * (a.ndim - 1))
        return jnp.where(m, b, a)

    state = jax.tree.map(sel, old_state, new_state)
    prefill = make_prefill(
        state, M, E,
        propose_leaders=propose_leaders, propose_n=propose_n,
    )
    inbox, stats, _delivered = route(
        state, out, dest_row, rank_in_dest,
        M=M, E=E, budget=budget, base=base,
        base_inbox=prefill, suppress=esc,
    )
    return state, inbox, stats, n_esc


def routed_round(
    state: DeviceState,
    inbox: Inbox,
    dest_row: jnp.ndarray,
    rank_in_dest: jnp.ndarray,
    *,
    out_capacity: int,
    budget: int,
    base: int,
    propose_leaders: bool = False,
    propose_n: int = 1,
) -> Tuple[DeviceState, Inbox, RouteStats, jnp.ndarray]:
    """One full consensus round: step every row through ``inbox``, then
    ``merge_and_route`` the outboxes into the next round's inbox."""
    from . import kernel as K

    M, E = inbox.M, inbox.E
    new_state, out = K.step(state, inbox, out_capacity=out_capacity)
    return merge_and_route(
        state, new_state, out, dest_row, rank_in_dest,
        M=M, E=E, budget=budget, base=base,
        propose_leaders=propose_leaders, propose_n=propose_n,
    )


def fused_rounds(
    state: DeviceState,
    inbox: Inbox,
    dest_row: jnp.ndarray,
    rank_in_dest: jnp.ndarray,
    *,
    rounds: int,
    out_capacity: int,
    budget: int,
    base: int,
    propose_leaders: bool = False,
    propose_n: int = 1,
) -> Tuple[DeviceState, Inbox, jnp.ndarray, jnp.ndarray]:
    """``rounds`` consecutive consensus rounds chained INSIDE one
    program — the fused commit wave (ISSUE 15 / ROADMAP item 2).

    Each round is exactly :func:`routed_round`: step, discard escalated
    rows, route the outboxes into the next round's inbox.  Chaining
    them device-side means a quiet-path propose -> replicate/ack ->
    commit/deliver sequence (``rounds=3``, the default wave) completes
    in ONE launch with no host round trip between rounds — on the
    remote-device tunnel each round trip is ~100-214 ms of latency
    (docs/BENCH_NOTES_r05.md), so a 3-round commit collapses from three
    floors to one.

    UNROLLED, not ``lax.scan``: ``rounds`` is static and small (2-4),
    per-round stats fall out of the unrolled loop for free, and the
    compile cost is ``rounds`` copies of one round's program — NOT the
    pathological step+route mega-fusion the r5 compile-time finding
    rules out (bench.py keeps step and route as separate jit units at
    scale geometry for exactly that reason; a K-chain of the SAME
    round program reuses its fusion decisions and stays linear).

    Bit-exactness contract: ``fused_rounds(..., rounds=K)`` must equal
    K sequential ``routed_round`` calls, state and inbox, bit for bit
    — the serial-K parity oracle (tests/test_hostplane.py, armed live
    under ``DRAGONBOAT_TPU_HOSTPLANE_PARITY`` in the bench's fused
    split).

    Returns ``(state', inbox', stats [rounds, 6], n_esc [rounds])`` —
    per-round RouteStats rows and escalation counts (an escalated
    row's effects are discarded in ITS round and the row re-steps in
    later rounds, the same restore-and-continue contract the launch
    pipeline applies across generations)."""
    if rounds < 1:
        raise ValueError(f"rounds must be >= 1, got {rounds}")
    stats_l = []
    esc_l = []
    for _ in range(rounds):
        state, inbox, stats, n_esc = routed_round(
            state, inbox, dest_row, rank_in_dest,
            out_capacity=out_capacity, budget=budget, base=base,
            propose_leaders=propose_leaders, propose_n=propose_n,
        )
        stats_l.append(jnp.stack(list(stats)))
        esc_l.append(n_esc)
    return state, inbox, jnp.stack(stats_l), jnp.stack(esc_l)


# ---------------------------------------------------------------------------
# multi-chip device plane: sharded tables + the collective exchange lane
# (ROADMAP item 3 / docs/MULTICHIP.md)
# ---------------------------------------------------------------------------
class MeshTables(NamedTuple):
    """Static route tables for a G-sharded mesh (row-block placement:
    device ``d`` owns global rows [d*Gl, (d+1)*Gl) — ops/placement.py).

    All three are [G, P] (sharded over G like the state), describing the
    peer in each slot of each row:

      dest_dev[g, p]    device hosting that replica (-1: not placed)
      dest_local[g, p]  its LOCAL row index on that device
      rank_in_dest[g, p] the slot index row g's replica occupies in THAT
                        row's peer table (identical to the single-device
                        table — region selection is device-agnostic)
    """

    dest_local: np.ndarray
    dest_dev: np.ndarray
    rank_in_dest: np.ndarray


class CrossStats(NamedTuple):
    """Per-call collective-lane counters (all scalars, per shard)."""

    sent: jnp.ndarray            # messages packed onto the lane
    delivered: jnp.ndarray       # received messages scattered into slots
    dropped_budget: jnp.ndarray  # per-sender region rank >= budget
    dropped_xlane: jnp.ndarray   # per-edge lane slots exhausted (>= XB)
    dropped_ring: jnp.ndarray    # REPLICATE no longer ring-resident


def build_route_tables_mesh(  # raftlint: ignore[host-sync] host-side numpy precompute of static tables
    shard_ids: np.ndarray,
    replica_ids: np.ndarray,
    peer_ids: np.ndarray,
    n_devices: int,
) -> MeshTables:
    """Device-boundary classification of the route tables: the global
    ``build_route_tables`` output split by the row-block placement into
    (device, local-row) coordinates.  A peer on the SAME device routes
    through the ordinary intra-device ``route``; a peer on another
    device rides the collective exchange lane (``cross_exchange``)."""
    G = peer_ids.shape[0]
    if n_devices <= 0 or G % n_devices:
        raise ValueError(f"G={G} must divide over {n_devices} devices")
    gl = G // n_devices
    dest, rank = build_route_tables(shard_ids, replica_ids, peer_ids)
    placed = dest >= 0
    dest_dev = np.where(placed, dest // gl, -1).astype(np.int32)
    dest_local = np.where(placed, dest % gl, -1).astype(np.int32)
    return MeshTables(dest_local, dest_dev, rank)


def xbudget_for(  # raftlint: ignore[host-sync] host-side numpy sizing of a static lane budget
    tables: MeshTables, budget: int, n_devices: int
) -> int:
    """Worst-case per-edge lane volume for ``tables``: for each
    (src device, dst device) edge, every local row can emit up to
    ``budget`` messages toward each of its peer slots on that edge.
    Sizing ``xbudget`` here makes ``dropped_xlane`` structurally zero —
    the precondition for the bit-exact sharded/single-device parity
    gate (a lane drop has no single-device analogue).  Topologies that
    accept lossy cross traffic (raft-safe) may pass less."""
    G = tables.dest_dev.shape[0]
    gl = G // n_devices
    worst = 1
    blocks = tables.dest_dev.reshape(n_devices, gl, -1)
    for s in range(n_devices):
        for d in range(n_devices):
            if d == s:
                continue
            worst = max(worst, int((blocks[s] == d).sum()) * budget)
    return worst


# packed cross-lane row layout (single source of truth for pack/unpack):
# the 9 wire columns, then sender replica id, destination local row,
# destination region rank, region slot b, found flag, then E entry
# terms and E entry cc bits.
_X_WIRE = (
    F_MTYPE, F_TERM, F_LOG_TERM, F_LOG_INDEX, F_COMMIT,
    F_REJECT, F_HINT, F_HINT_HIGH, F_N_ENTRIES,
)
_XI_FROM = len(_X_WIRE)
_XI_LOC = _XI_FROM + 1
_XI_RANK = _XI_FROM + 2
_XI_B = _XI_FROM + 3
_XI_FOUND = _XI_FROM + 4
_X_KF = _XI_FROM + 5  # ent_term starts here; width = _X_KF + 2*E


def cross_exchange(
    state: DeviceState,
    out: DeviceOut,
    inbox: Inbox,
    dest_local: jnp.ndarray,
    dest_dev: jnp.ndarray,
    rank_in_dest: jnp.ndarray,
    *,
    axis: str,
    n_dev: int,
    budget: int,
    xbudget: int,
    base: int,
    suppress: Optional[jnp.ndarray] = None,
) -> Tuple[Inbox, CrossStats]:
    """The device-to-device collective lane (runs INSIDE shard_map).

    Messages whose destination replica lives on another device are
    packed into a fixed per-edge buffer ([n_dev, xbudget, KT] int32 —
    the same fixed-budget discipline as the routed regions), exchanged
    with ``lax.ppermute`` (one hop per ring shift; n_dev-1 permutes of a
    tiny buffer), and scattered into the SAME inbox region slots the
    intra-device router would have used — ``base + rank*budget + b`` —
    so a sharded round's assembled inbox is bit-identical to the
    single-device router's (the parity contract of
    tests/test_multichip.py).  Region-slot identity is safe because a
    (dest row, rank) region has exactly ONE sender, and that sender is
    on exactly one device: a region is local-fed XOR lane-fed.

    Overflow (per-sender rank >= budget, per-edge slot >= xbudget) is
    DROPPED and counted — raft tolerates arbitrary message loss, same
    contract as the intra-device router.  Zero host transfers: pure
    int32 device math + ppermute.
    """
    G, O, _ = out.buf.shape
    P, W, B, E = state.P, state.W, budget, inbox.E
    M = inbox.M
    D, XB = n_dev, xbudget
    if D <= 1:
        zero = jnp.zeros((), I32)
        return inbox, CrossStats(zero, zero, zero, zero, zero)
    me = jax.lax.axis_index(axis)

    buf = out.buf
    mtype = buf[:, :, F_MTYPE]
    to = buf[:, :, F_TO]
    n_ent = buf[:, :, F_N_ENTRIES]
    log_index = buf[:, :, F_LOG_INDEX]
    log_term = buf[:, :, F_LOG_TERM]
    valid = jnp.arange(O)[None, :] < out.count[:, None]
    if suppress is not None:
        valid = valid & ~suppress[:, None]
    hits = (
        (state.peer_id[:, None, :] == to[:, :, None])
        & (to[:, :, None] != 0)
        & (state.peer_id[:, None, :] != 0)
    )  # [G, O, P]
    found = jnp.any(hits, axis=2)

    def at_pstar(tab):  # [G, P] table value at the hit slot, [G, O]
        return jnp.sum(jnp.where(hits, tab[:, None, :], 0), axis=2)

    xdev = at_pstar(dest_dev)
    xloc = at_pstar(dest_local)
    xrank = at_pstar(rank_in_dest)
    # deliverability mirrors route(): REPLICATE payload must be ring-
    # resident on the sender (below-ring HOST-FIXUP markers excluded),
    # forwarded PROPOSE never rides the device (payload is host-only)
    is_repl = mtype == MT_REPLICATE
    carries = is_repl & (n_ent > 0)
    win_lo = jnp.maximum(state.first_index, state.last_index - (W - 1))
    marker = is_repl & (log_index > 0) & (log_term == 0)
    ring_ok = ~carries | (
        (log_index + 1 >= win_lo[:, None])
        & (log_index + n_ent <= state.last_index[:, None])
        & ~marker
    )
    remote = found & (xdev >= 0) & (xdev != me)
    routable = valid & remote & (mtype != MT_PROPOSE)
    deliverable = routable & ring_ok
    # per-(sender, peer-slot) region rank b — the SAME counting the
    # single-device router applies (all of a (g, p) pair's messages go
    # to one destination device, so the two counts can never interleave)
    oh = (hits & deliverable[:, :, None]).astype(I32)
    k_excl = jnp.cumsum(oh, axis=1) - oh
    b_of = jnp.sum(jnp.where(hits, k_excl, 0), axis=2)  # [G, O]
    in_b = b_of < B
    sendable = deliverable & in_b
    # per-edge lane slot q (fixed budget XB per destination device)
    N = G * O
    edge = (
        (xdev[:, :, None] == jnp.arange(D)[None, None, :])
        & sendable[:, :, None]
    ).reshape(N, D)
    q_excl = jnp.cumsum(edge.astype(I32), axis=0) - edge
    in_q = edge & (q_excl < XB)
    # pack one [KT] row per message: wire fields + lane metadata + the
    # REPLICATE payload (terms/cc) reconstructed from the sender's ring
    wm = W - 1
    ents_t = []
    ents_c = []
    for e in range(E):
        pos = jnp.clip(log_index + 1 + e, 0, None) & wm  # [G, O]
        selw = pos[:, :, None] == jnp.arange(W)[None, None, :]
        has_e = carries & (e < n_ent)
        et = jnp.sum(
            jnp.where(selw, state.ring_term[:, None, :], 0), axis=2
        )
        ec = jnp.sum(jnp.where(selw, state.ring_cc[:, None, :], 0), axis=2)
        ents_t.append(jnp.where(has_e, et, 0))
        ents_c.append(jnp.where(has_e, ec, 0))
    from_g = jnp.broadcast_to(state.replica_id[:, None], (G, O))
    fields = jnp.stack(
        [buf[:, :, c] for c in _X_WIRE]
        + [from_g, xloc, xrank, b_of, sendable.astype(I32)]
        + ents_t + ents_c,
        axis=2,
    ).reshape(N, -1)  # [N, KT]
    KT = fields.shape[1]
    # xbuf[d, xb] = the message holding lane slot xb of edge me->d
    sel = (
        in_q[:, :, None] & (q_excl[:, :, None] == jnp.arange(XB))
    )  # [N, D, XB]
    xbuf = jnp.matmul(
        sel.astype(I32).transpose(1, 2, 0).reshape(D * XB, N), fields
    ).reshape(D, XB, KT)
    # ring exchange: shift s hands each device the buffer its neighbor
    # s hops back packed for it — D-1 ppermutes of [XB, KT] int32
    recv_parts = []
    for shift in range(1, D):
        dst_slice = jax.lax.dynamic_index_in_dim(
            xbuf, (me + shift) % D, axis=0, keepdims=False
        )
        perm = [(i, (i + shift) % D) for i in range(D)]
        recv_parts.append(jax.lax.ppermute(dst_slice, axis, perm=perm))
    recv = jnp.concatenate(recv_parts, axis=0)  # [(D-1)*XB, KT]
    R = recv.shape[0]
    ok = recv[:, _XI_FOUND] != 0
    row = recv[:, _XI_LOC]
    slot = base + recv[:, _XI_RANK] * B + recv[:, _XI_B]
    # one-hot scatter into the (guaranteed-empty) region slots: no two
    # received messages share (row, slot) — single sender per region,
    # distinct b per sender — so the adds never collide, and the local
    # router left lane-fed regions zero (their dest_row is -1 locally)
    selr = (
        ok[:, None, None]
        & (row[:, None, None] == jnp.arange(G)[None, :, None])
        & (slot[:, None, None] == jnp.arange(M)[None, None, :])
    )  # [R, G, M]

    def put(col):
        return jnp.sum(
            jnp.where(selr, recv[:, col][:, None, None], 0), axis=0
        ).astype(I32)

    wire_at = {c: i for i, c in enumerate(_X_WIRE)}
    ent_t = jnp.sum(
        jnp.where(
            selr[:, :, :, None],
            recv[:, None, None, _X_KF:_X_KF + E],
            0,
        ),
        axis=0,
    ).astype(I32)
    ent_c = jnp.sum(
        jnp.where(
            selr[:, :, :, None],
            recv[:, None, None, _X_KF + E:_X_KF + 2 * E],
            0,
        ),
        axis=0,
    ).astype(I32)
    inbox = Inbox(
        mtype=inbox.mtype + put(wire_at[F_MTYPE]),
        from_id=inbox.from_id + put(_XI_FROM),
        term=inbox.term + put(wire_at[F_TERM]),
        log_term=inbox.log_term + put(wire_at[F_LOG_TERM]),
        log_index=inbox.log_index + put(wire_at[F_LOG_INDEX]),
        commit=inbox.commit + put(wire_at[F_COMMIT]),
        reject=inbox.reject + put(wire_at[F_REJECT]),
        hint=inbox.hint + put(wire_at[F_HINT]),
        hint_high=inbox.hint_high + put(wire_at[F_HINT_HIGH]),
        n_entries=inbox.n_entries + put(wire_at[F_N_ENTRIES]),
        ent_term=inbox.ent_term + ent_t,
        ent_cc=inbox.ent_cc + ent_c,
    )
    stats = CrossStats(
        sent=jnp.sum(in_q, dtype=I32),
        delivered=jnp.sum(ok, dtype=I32),
        dropped_budget=jnp.sum(deliverable & ~in_b, dtype=I32),
        dropped_xlane=jnp.sum(
            sendable & ~jnp.any(in_q.reshape(G, O, D), axis=2), dtype=I32
        ),
        dropped_ring=jnp.sum(routable & ~ring_ok, dtype=I32),
    )
    return inbox, stats


def make_sharded_round(  # mesh-hot
    mesh,
    *,
    M: int,
    E: int,
    out_capacity: int,
    budget: int,
    xbudget: int,
    base: int,
    propose_leaders: bool = False,
    propose_n: int = 1,
    rounds: int = 1,
):
    """Build the jitted shard_map'd consensus round for a 1-D groups
    mesh: per-device step over the local G-slice, intra-device routing
    EXACTLY as the single-device router (``route`` over the mesh
    tables' local view), and cross-device raft traffic on the
    ``cross_exchange`` collective lane — zero host transfers in the
    steady loop (pinned by the jaxcheck transfer audit over
    ``registry.mesh_entry_points``).

    ``rounds > 1`` fuses consecutive rounds INSIDE the shard-mapped
    program (the mesh form of :func:`fused_rounds`): the ppermute
    collective lane fires BETWEEN fused rounds — cross-chip raft
    traffic sent in round k is scattered into round k+1's inbox
    regions before that round steps, never deferred to the end of the
    wave — so a sharded fused wave is bit-exact with ``rounds``
    sequential sharded rounds AND with the single-device
    ``fused_rounds`` over the same global topology
    (tests/test_pipeline.py mesh parity).

    Returns ``round_fn(state, inbox, dest_local, dest_dev, rank) ->
    (state', inbox', route_stats [D*rounds, 6], lane_stats
    [D*rounds, 7])`` where all row-axis operands are sharded over the
    mesh (jit re-shards uncommitted inputs automatically) and the
    per-device stats lanes are: RouteStats order for the local router,
    then [sent, delivered, dropped_budget, dropped_xlane, dropped_ring,
    escalated, rows_live] for the lane/step, one row per (device,
    round) — the per-device split ``bench.py phase_multichip``
    balances and records (``rounds=1``, the default, keeps the
    historical [D, 6]/[D, 7] shape).
    """
    import jax as _jax

    try:
        from jax import shard_map as _shard_map
    except ImportError:  # pragma: no cover - older jax spelling
        from jax.experimental.shard_map import shard_map as _shard_map
    from jax.sharding import PartitionSpec as _PS

    if len(mesh.axis_names) != 1:
        raise ValueError("groups mesh must be one-dimensional")
    axis = mesh.axis_names[0]
    D = mesh.size
    from . import kernel as K

    if rounds < 1:
        raise ValueError(f"rounds must be >= 1, got {rounds}")

    def _local_round(state, inbox, dest_local, dest_dev, rank):
        me = jax.lax.axis_index(axis)
        local_dest = jnp.where(
            dest_dev == me, dest_local, jnp.int32(-1)
        )
        stats_l = []
        lane_l = []
        # unrolled fused rounds: the collective lane runs INSIDE the
        # per-round tail, so cross-chip traffic from round k feeds
        # round k+1's step — never batched to the end of the wave
        for _ in range(rounds):
            new_state, out = K.step(
                state, inbox, out_capacity=out_capacity
            )
            esc = out.escalate != 0
            n_esc = jnp.sum(esc, dtype=I32)
            keep = ~esc

            def sel(a, b, keep=keep):
                m = keep.reshape((-1,) + (1,) * (a.ndim - 1))
                return jnp.where(m, b, a)

            state2 = jax.tree.map(sel, state, new_state)
            prefill = make_prefill(
                state2, M, E,
                propose_leaders=propose_leaders, propose_n=propose_n,
            )
            next_inbox, stats, _delivered = route(
                state2, out, local_dest, rank,
                M=M, E=E, budget=budget, base=base,
                base_inbox=prefill, suppress=esc,
            )
            next_inbox, xstats = cross_exchange(
                state2, out, next_inbox, dest_local, dest_dev, rank,
                axis=axis, n_dev=D, budget=budget, xbudget=xbudget,
                base=base, suppress=esc,
            )
            rows_live = jnp.sum(keep, dtype=I32)
            stats_l.append(jnp.stack(list(stats)))
            lane_l.append(jnp.stack(list(xstats) + [n_esc, rows_live]))
            state, inbox = state2, next_inbox
        # [rounds, 6]/[rounds, 7] per shard -> [D*rounds, *] global
        return state, inbox, jnp.stack(stats_l), jnp.stack(lane_l)

    return _jax.jit(
        _shard_map(
            _local_round,
            mesh=mesh,
            in_specs=(
                _PS(axis), _PS(axis), _PS(axis), _PS(axis), _PS(axis),
            ),
            out_specs=(_PS(axis), _PS(axis), _PS(axis), _PS(axis)),
            # see make_step_sharded: while_loop has no replication rule
            check_rep=False,
        )
    )
