"""VectorStepEngine: the device-backed step engine (the north star).

Replaces the per-shard scalar ``node.step()`` loop of ``HostStepEngine``
with ONE kernel launch over a `[G]`-row device-resident state tensor
(reference: engine.go stepWorkerMain becomes a vectorized kernel, per
BASELINE.json north_star).  The division of labor:

  * **device** — protocol state (term/vote/role/ticks/remotes/log-term
    ring) and the hot step function (`ops/kernel.py`).
  * **host (scalar ``Raft``)** — the authoritative payload log
    (``EntryLog`` over the LogDB reader), sessions, ReadIndex
    bookkeeping, snapshots, and every cold input.  For device-resident
    rows the scalar's protocol fields are stale EXCEPT term / vote /
    leader_id / role / log.committed, which are re-synced from the
    device after every step so the standard ``Peer.get_update()`` /
    ``node.process_update()`` plumbing keeps working unchanged.

Row routing per step (see `_plan_device`):

  * hot inputs (ticks, hot wire messages, application proposals) →
    encoded into the device inbox;
  * cold inputs (config change, read index, snapshot request, leader
    transfer, cold message types, oversized batches) → the row is
    **materialized** (device → scalar copy) and stepped by the scalar
    path; the row is re-uploaded when it goes hot again;
  * kernel escalation (ESC_* bits) → the row's device effects are
    discarded (pre-step state restored) and the drained inputs are
    replayed on the materialized scalar — the escalation contract from
    ops/kernel.py's module docstring.

Log reconstruction: the kernel reports ``append_lo`` (lowest ring-
written index).  The host stamps payload entries for
[append_lo, last_index] from its staging map (proposal entries by
slot_base; REPLICATE payloads by wire position), picking the last
slot-order candidate whose term matches the ring term; gaps are
become-leader noop barriers.  The merged entries flow out through
``Update.entries_to_save`` exactly as in the scalar engine.
"""
from __future__ import annotations

import dataclasses
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis import jitcheck
from ..engine.execengine import IStepEngine
from ..logger import get_logger
from ..pb import Entry, EntryType, Message, MessageType, Snapshot
from ..raft.raft import Raft, RaftRole
from ..raft.remote import RemoteState
from ..request import gc_tables
from ..rsm.statemachine import Task, TaskType
from . import hostplane
from . import kernel as K
from . import sync as S
from .types import (
    APPEND_LO_NONE,
    ROLE_LEADER as ROLE_LEADER_I,
    N_FIELDS as N_FIELDS_BUF,
    F_LOG_INDEX,
    F_MTYPE,
    F_N_ENTRIES,
    F_QUORUM_ACTIVE,
    F_SRC_SLOT,
    F_TO,
    HOT_TYPES,
    I32,
    KIND_VOTER,
    KIND_WITNESS,
    RS_SNAPSHOT,
    SLOT_DROPPED,
    DeviceState,
    make_state,
)

_log = get_logger("engine")

_HOT_SET = frozenset(HOT_TYPES)

# readback row indices of the per-row VALUES block (_gather_detail's
# idx_sum part); 0-5 double as the [6, G] host mirror's row indices
# AND the update-lane word layout (hostplane.UpdateLanes).  The values
# live in types.py (one definition across the device gather program,
# both merge tails and the lane store); the `_R_*` aliases keep this
# module's historical spelling.
from .types import (  # noqa: E402 — alias block, not a new dependency
    N_VALS,
    R_TERM as _R_TERM,
    R_VOTE as _R_VOTE,
    R_COMMIT as _R_COMMIT,
    R_LEADER as _R_LEADER,
    R_ROLE as _R_ROLE,
    R_LAST as _R_LAST,
    R_COUNT as _R_COUNT,
    R_APPEND_LO as _R_APPEND_LO,
    R_BARRIER_IDX as _R_BARRIER_IDX,
    R_BARRIER_TERM as _R_BARRIER_TERM,
    U_COMMIT,
    U_LEADER,
    U_LOST_LEAD,
    U_ROLE,
    U_STATE,
)

# int role -> RaftRole member: the merge tails' enum lookup.  The
# `RaftRole(role)` enum call costs ~0.5 µs per row (EnumMeta.__call__)
# — a real share of the per-affected-row residual at 250k rows.
_ROLE_OF = {int(x): x for x in RaftRole}

# per-row flag bits of the _summarize_flags readback — the ONLY
# full-width [G] readback a launch performs.  Everything row-valued
# (terms, counts, outboxes, rings) is gathered afterwards for flagged
# rows only: at 65k rows the old [12, G] summary + [G, O] delivered
# readbacks were ~5 MB per launch, which on a remote-device link (the
# TPU tunnel) costs tens of seconds — the flags word is 256 KB and the
# steady-state gather is a few rows.  The bit values live in types.py
# (shared with the vectorized host-plane machinery in ops/hostplane.py);
# the `_F_*` aliases keep this module's historical spelling.
from .types import (  # noqa: E402 — alias block, not a new dependency
    F_CHANGED as _F_CHANGED,
    F_COUNT as _F_COUNT,
    F_APPEND as _F_APPEND,
    F_NEED_SS as _F_NEED_SS,
    F_ESC as _F_ESC,
    F_PEERS_BEHIND as _F_PEERS_BEHIND,
    F_ANY_LIVE as _F_ANY_LIVE,
)


def _bucket(n: int) -> int:
    """Next power of two ≥ n (bounds jit recompiles for dynamic row sets)."""
    b = 1
    while b < n:
        b <<= 1
    return b


def _pad_idx(idx: Sequence[int], pad: Optional[int] = None) -> np.ndarray:
    if pad is None:
        pad = _bucket(len(idx))
    out = np.empty((pad,), np.int32)
    out[: len(idx)] = idx
    out[len(idx):] = idx[-1]  # duplicate scatter/gather of one row is benign
    return out


def _place_rows(a, b, pos):
    """a's row g := b[pos[g]] where pos[g] >= 0, else unchanged — the
    pos-map gather-select shared by every row placement (NOT
    a.at[idx].set(): a scatter with data-dependent row indices lowers
    to a serial per-row loop on TPU, the same pathology as
    kernel._set_col; row uploads were ~seconds per launch)."""
    take = jnp.clip(pos, 0, b.shape[0] - 1)
    picked = b[take]
    m = (pos >= 0).reshape((-1,) + (1,) * (a.ndim - 1))
    return jnp.where(m, picked, a)


@jax.jit
def _scatter_rows(state: DeviceState, pos, sub: DeviceState) -> DeviceState:
    """Place sub's rows into state at the rows marked by ``pos`` — a
    [G] int32 position map (pos[g] = row of ``sub`` to take, -1 = keep
    state's row)."""
    return jax.tree.map(lambda a, b: _place_rows(a, b, pos), state, sub)


def _pos_map(G: int, gs) -> np.ndarray:
    """Host-built [G] position map for _scatter_rows/_scatter_inbox_rows:
    pos[g] = index into the sub batch, -1 elsewhere.  ONE definition —
    delegates to hostplane.pos_of, the same map the merge tail's
    index-array machinery uses (review finding: two byte-equivalent
    copies would drift)."""
    return hostplane.pos_of(G, gs)


@jax.jit
def _select_rows(keep_new, old: DeviceState, new: DeviceState) -> DeviceState:
    def sel(a, b):
        m = keep_new.reshape((-1,) + (1,) * (a.ndim - 1))
        return jnp.where(m, b, a)

    return jax.tree.map(sel, old, new)


@jax.jit
def _gather_rows(state: DeviceState, idx) -> DeviceState:
    return jax.tree.map(lambda a: a[idx], state)


@jax.jit
def _summarize_flags(old: DeviceState, new: DeviceState, out) -> jnp.ndarray:
    """Per-row flag word (see _F_*) — the one full-width readback."""
    changed = (
        (new.term != old.term)
        | (new.vote != old.vote)
        | (new.committed != old.committed)
        | (new.leader_id != old.leader_id)
        | (new.role != old.role)
        | (new.last_index != old.last_index)
    )
    f = jnp.where(changed, _F_CHANGED, 0)
    f = f | jnp.where(out.count > 0, _F_COUNT, 0)
    f = f | jnp.where(out.append_lo != APPEND_LO_NONE, _F_APPEND, 0)
    f = f | jnp.where(jnp.any(out.need_snapshot == 1, axis=1), _F_NEED_SS, 0)
    f = f | jnp.where(out.escalate != 0, _F_ESC, 0)
    peer_lane = (new.peer_id != 0) & (
        jnp.arange(new.peer_id.shape[1])[None, :] != new.self_slot[:, None]
    )
    behind = (new.role == ROLE_LEADER_I) & jnp.any(
        peer_lane & (new.match < new.last_index[:, None]), axis=1
    )
    f = f | jnp.where(behind, _F_PEERS_BEHIND, 0)
    # device-plane lease evidence (ROADMAP 4b): a CheckQuorum leader
    # whose current activity window already holds a quorum of active
    # voter lanes.  Mirrors kernel._check_quorum's count (self implicit
    # + active non-self voters vs voting-member quorum); self must
    # currently be a VOTER slot — witness/removed leaders serve no
    # reads, matching Raft.quorum_responded_tick's membership gate.
    voters = (new.peer_id != 0) & (
        (new.peer_kind == KIND_VOTER) | (new.peer_kind == KIND_WITNESS)
    )
    n_voters = jnp.sum(voters, axis=1).astype(I32)
    quorum = n_voters // 2 + 1
    self_lane = (
        jnp.arange(new.peer_id.shape[1])[None, :] == new.self_slot[:, None]
    )
    self_is_voter = jnp.any(
        self_lane & (new.peer_id != 0) & (new.peer_kind == KIND_VOTER),
        axis=1,
    )
    n_active = 1 + jnp.sum(
        voters & ~self_lane & (new.active == 1), axis=1
    ).astype(I32)
    q_active = (
        (new.role == ROLE_LEADER_I)
        & (new.check_quorum == 1)
        & self_is_voter
        & (n_active >= quorum)
    )
    f = f | jnp.where(q_active, F_QUORUM_ACTIVE, 0)
    return f.astype(I32)


@jax.jit
def _gather_vals(state, out, idx):
    """Per-row VALUES block (_R_* order) for flagged rows — replaces the
    old full-width summary readback.  Split from _gather_detail because
    their cardinalities differ wildly: during an election storm most
    rows change state (values needed) while few carry host-relevant
    outbox bytes; one fused gather padded the huge buf part to the
    values cardinality (~44 MB readbacks at 65k rows)."""
    return jnp.stack(
        [
            state.term[idx],
            state.vote[idx],
            state.committed[idx],
            state.leader_id[idx],
            state.role[idx],
            state.last_index[idx],
            out.count[idx],
            out.append_lo[idx],
            out.barrier_idx[idx],
            out.barrier_term[idx],
        ],
        axis=1,
    )


@jax.jit
def _gather_detail(state, out, idx4):
    """All heavy post-step detail reads in ONE dispatch and ONE [b, K]
    readback array: the four equal-length index sets travel as a stacked
    [4, b] transfer, and the flattened results concatenate on axis 1 so
    the host issues a single D2H copy (latency floor is round-trips, not
    bytes)."""
    idx_buf, idx_slot, idx_need, idx_ring = idx4
    b = idx_buf.shape[0]
    parts = (
        out.buf[idx_buf],
        out.slot_base[idx_slot],
        out.slot_term[idx_slot],
        out.ent_drop[idx_slot],
        out.need_snapshot[idx_need],
        state.ring_term[idx_ring],
        state.ring_cc[idx_ring],
    )
    return jnp.concatenate([p.reshape(b, -1) for p in parts], axis=1)


def _detail_width(O: int, M: int, E: int, P: int, W: int) -> int:
    """Per-row int32 width of _gather_detail's packing — the ONE
    definition shared by _split_detail, _fetch_detail_vals and the
    colocated single-sync blob parse (review finding: the formula was
    hand-duplicated and a packing change would silently misalign)."""
    return O * N_FIELDS_BUF + M + M + M * E + P + W + W


def _split_detail(flat: np.ndarray, O: int, M: int, E: int, P: int, W: int):
    """Host-side inverse of _gather_detail's packing."""
    b = flat.shape[0]
    sizes = (O * N_FIELDS_BUF, M, M, M * E, P, W, W)
    shapes = ((b, O, N_FIELDS_BUF), (b, M), (b, M), (b, M, E), (b, P), (b, W), (b, W))
    outs = []
    pos = 0
    for size, shape in zip(sizes, shapes):
        outs.append(flat[:, pos : pos + size].reshape(shape))
        pos += size
    return tuple(outs)


@jax.jit
def _gather_detail_vals(state, out, idx4, idx_sum):
    """_gather_detail + _gather_vals in ONE dispatch and ONE flat 1-D
    readback.  A device->host sync on a remote-device link costs ~100 ms
    of round-trip latency regardless of size (measured r5); issuing the
    detail and values gathers as two programs with two np.asarray calls
    was two of the launch's ~5 round trips."""
    detail = _gather_detail(state, out, idx4)
    vals = _gather_vals(state, out, idx_sum)
    return jnp.concatenate([detail.reshape(-1), vals.reshape(-1)])


def _build_idx4(buf_rows, slot_rows, need_rows, append_rows):
    """[4, b] padded index sets for _gather_detail, or None when all
    four are empty.  All sets pad to ONE bucket so the fused gather
    compiles per bucket size, not per size combination; the pad repeats
    the last real row (duplicate gathers of one row are benign)."""
    if not (buf_rows or append_rows or slot_rows or need_rows):
        return None
    b = _bucket(
        max(len(buf_rows), len(append_rows), len(slot_rows), len(need_rows))
    )
    idx4 = np.zeros((4, b), np.int32)
    for row_i, rows in enumerate(
        (buf_rows, slot_rows, need_rows, append_rows)
    ):
        if rows:
            idx4[row_i, : len(rows)] = rows
            idx4[row_i, len(rows):] = rows[-1]
    return idx4


def _fetch_detail_vals(state, out, idx4, sum_rows, put, O, M, E, P, W,
                       allow_fused: bool = True):
    """Gather post-step detail and/or per-row values with the MINIMUM
    number of sync round trips: one fused dispatch+readback when both
    are needed, one when only one is.  Returns (detail_tuple_or_None,
    vals_np_or_None) where detail_tuple is _split_detail's output.

    The fused program is compiled per (detail-bucket, sum-bucket) shape
    pair but the warm loops only warm EQUAL pairs (review finding), so
    the buckets are equalized whenever padding is cheap: sum rows up is
    always cheap (N_VALS ints/row); detail rows up only until ~1 MB of
    padded transfer.  A mismatched pair beyond that uses the two
    separate per-bucket-warmed gathers instead of an unwarmed compile.
    ``allow_fused=False`` forces the separate gathers — the colocated
    fallback path uses it because only the separate per-bucket programs
    are in its warm set (a fused compile mid-run stalls the tunnel).
    """
    detail = vals_np = None
    if allow_fused and idx4 is not None and sum_rows:
        b = idx4.shape[1]
        bs = _bucket(len(sum_rows))
        K = _detail_width(O, M, E, P, W)
        if bs < b:
            bs = b  # pad sum rows up: N_VALS ints per padded row
        elif bs > b and (bs - b) * K * 4 <= 1_000_000:
            idx4 = np.concatenate(
                [idx4, np.repeat(idx4[:, -1:], bs - b, axis=1)], axis=1
            )
            b = bs
        if b == bs:
            flat = np.asarray(
                _gather_detail_vals(
                    state, out, put(jnp.asarray(idx4)),
                    put(jnp.asarray(_pad_idx(sum_rows, bs))),
                )
            )
            detail = _split_detail(
                flat[: b * K].reshape(b, K), O, M, E, P, W
            )
            vals_np = flat[b * K:].reshape(-1, N_VALS)
            return detail, vals_np
    if idx4 is not None:
        detail = _split_detail(
            np.asarray(_gather_detail(state, out, put(jnp.asarray(idx4)))),
            O, M, E, P, W,
        )
    if sum_rows:
        vals_np = np.asarray(
            _gather_vals(state, out, put(jnp.asarray(_pad_idx(sum_rows))))
        )
    return detail, vals_np


@jax.jit
def _set_remote_snapshot(state: DeviceState, g_idx, p_idx, snap_idx):
    return state._replace(
        rstate=state.rstate.at[g_idx, p_idx].set(RS_SNAPSHOT),
        snap_index=state.snap_index.at[g_idx, p_idx].set(snap_idx),
    )


def _shift_msg_indexes(msg: Message, delta: int) -> Message:
    """Shift a wire message's INDEX fields by ``delta`` (the rebase
    boundary conversion): log_index and commit always; hint only when it
    is an index (a REPLICATE_RESP reject hint), never when it is a ctx
    key.  Used with -base entering the device and +base leaving it —
    one definition so encode and decode can never disagree.

    READ_INDEX_RESP is special-cased: the kernel's synthetic to-self
    resp overloads log_index as a VOTER REPLICA ID (or 0 = "request
    recorded"), not a log index — shifting it would turn the recorded
    marker into ``base`` and voter ids into garbage, stalling every
    device-path read once a row's base is nonzero.  Its ``commit`` IS a
    real index (the recorded read index) and still shifts.  Wire
    READ_INDEX_RESP (whose log_index is a real index) never crosses
    this boundary: the type is not in HOT_TYPES, so it cannot enter a
    device inbox, and the kernel only emits the self-addressed form."""
    if delta == 0:
        return msg
    if msg.type == MessageType.READ_INDEX_RESP:
        return dataclasses.replace(msg, commit=msg.commit + delta)
    h = (
        msg.hint + delta
        if msg.type == MessageType.REPLICATE_RESP and msg.reject
        else msg.hint
    )
    return dataclasses.replace(
        msg,
        log_index=msg.log_index + delta,
        commit=msg.commit + delta,
        hint=h,
    )


def _tick_bookkeeping(node, ticks: int) -> None:
    """Advance the node's logical clock and GC timed-out futures — the
    device path's mirror of the tick tail of ``Node.step_with_inputs``.

    The GC is ONE hint-gated sweep over the node's five pending tables
    per call (request.gc_tables) instead of the old five per-table
    ``gc()`` calls — at 250k rows the five probes (and, with any table
    non-empty, five lock acquisitions) per affected row per generation
    were a top-3 share of the merge tail's residual (ISSUE 13).  The
    monotone-deadline argument, kept honest: deadlines are fixed at
    allocation and the clock is monotone, so sweeping exactly when the
    clock first reaches the earliest pending deadline (the hint cell)
    delivers every timeout at the same tick value the old per-table
    sweep did — fused multi-tick counts land on the SAME final count
    either way, and ticks below the hint can expire nothing."""
    if not ticks:
        return
    tc = node.tick_count + ticks
    node.tick_count = tc
    # the SCALAR raft's logical clock advances too: device-resident
    # rows never call Raft.tick(), and a frozen r.tick_count poisons
    # every wall-clock comparison made while resident — the CheckQuorum
    # grace rate limit, the boot-lease grace, and (ROADMAP 4b) the
    # lease math, where a device-window anchor stamped on the live node
    # clock against a frozen raft clock OVERSTATES the lease by the
    # whole residency.  The scalar path keeps the two clocks in
    # lockstep (step_with_inputs ticks the raft, then advances the node
    # clock by the same count); this is the device path's mirror.
    node.peer.raft.tick_count += ticks
    if tc >= node.pending_deadline_hint[0]:
        gc_tables(node.pending_tables, node.pending_deadline_hint, tc)


def _plan_lane_words(  # hostplane-hot
    ulanes, bases, gs_live, sum_rows, vals, capacity, mirror=None,
):
    """Assemble one generation's array-side update words (ISSUE 13).

    Gathers the live rows' last-synced lanes, diffs the generation's
    merged values against them (``hostplane.plan_update_sync``) and
    writes the new words back for exactly those rows — the whole
    assembly is numpy gathers over ``[G]`` lanes; rows the caller's
    merge loop then skips (none on this engine: the batch is
    re-validated under the lock) would be re-seeded at their next
    upload, so the bulk write-back is always safe.  When ``mirror`` is
    given, the device-frame ``[6, G]`` host mirror is bulk-synced for
    every values-carrying row too (replacing the per-row
    ``mirror[:6, g] = vals[k, :6]`` writes of the old merge loop).
    Returns the ``UpdateSyncPlan`` whose ``ubits`` drive the
    LANE/heavy row split.
    """
    sum_k = hostplane.pos_of(
        capacity, np.asarray(sum_rows, np.int64)
    )[gs_live]
    old_w = ulanes.words[:, gs_live]
    uplan = hostplane.plan_update_sync(old_w, sum_k, vals, bases[gs_live])
    if hostplane.PARITY:
        hostplane.check_update_plan_parity(
            old_w, sum_k, vals, bases[gs_live], uplan
        )
    ulanes.words[:, gs_live] = uplan.words
    if mirror is not None:
        in_sum = sum_k >= 0
        if in_sum.any():
            mirror[:6, gs_live[in_sum]] = vals[sum_k[in_sum], :6].T
    return uplan


def _apply_lane_commit(node, ce, notify: bool = True) -> None:
    """The lane rows' post-save apply handoff — one definition for the
    slot-batched and list-fallback persist paths (both MUST run it
    only after the row's save landed: persist-before-apply,
    peer.commit's order).  Hands the committed entries to the apply
    queue, advances the processed cursor, and runs the AMORTIZED
    in-mem GC: ``applied_log_to`` slices the entry list (O(live
    entries)) every call, so sweep once per ~32 applied entries
    instead of per commit — bounded residency (<=32 applied entries
    linger), 32x fewer slices on the commit-wave path.

    ``notify=False`` defers the apply-worker wakeup to the caller —
    the batched per-SM-worker handoff (:func:`_apply_lane_commits`)."""
    if node._trace_spans:
        node._trace_committed(ce)
    node.sm.task_queue.add(Task(type=TaskType.ENTRIES, entries=ce))
    log = node.peer.raft.log
    log.processed = ce[-1].index
    im = log.inmem
    if log.processed - im.marker >= 32:
        im.applied_log_to(log.processed)
    if notify and node.engine_apply_ready is not None:
        node.engine_apply_ready(node.shard_id)


def _apply_lane_commits(handoffs) -> None:
    """BATCHED apply handoff per SM worker per generation (ROADMAP
    item 1's named next cut for the commit-wave split): enqueue every
    commit row's Task/cursor-advance, then wake each apply-worker
    partition ONCE via ``WorkReady.notify_all`` instead of per row.

    The per-row ``engine_apply_ready`` closure takes its partition's
    condition lock on every call — at a commit wave touching thousands
    of rows that is thousands of interleaved lock acquisitions against
    the very apply workers the wakeups target.  ``notify_all`` groups
    the shard ids by partition host-side and takes each partition's
    lock exactly once per generation.  Nodes registered before the
    batched hook existed (``apply_work_ready`` is None — bespoke
    engines, tests driving nodes directly) keep the per-row path.

    ``handoffs`` is ``[(node, committed-entries)]`` for rows whose
    batched save ALREADY landed — the persist-before-apply order is
    the caller's contract, unchanged."""
    by_wr: Dict[int, Tuple] = {}
    for node, ce in handoffs:
        _apply_lane_commit(node, ce, notify=False)
        # getattr: bespoke node doubles (bench twins, direct-drive
        # tests) predate the hook and keep the per-row path
        wr = getattr(node, "apply_work_ready", None)
        if wr is not None:
            by_wr.setdefault(id(wr), (wr, []))[1].append(node.shard_id)
        elif node.engine_apply_ready is not None:
            node.engine_apply_ready(node.shard_id)
    for wr, shard_ids in by_wr.values():
        wr.notify_all(shard_ids)


class _RowMeta:
    """Per-row metadata view.  The TRUTH lives in the engine's
    ``hostplane.RowLanes`` SoA arrays so the vectorized plan classifier
    and merge stage read whole lanes at once; these properties keep the
    scalar paths' field syntax (``meta.dirty = True`` etc.) working
    unchanged.  Field semantics:

    * dirty — the scalar Raft is authoritative and the device row is
      stale (fresh rows, cold-stepped rows, escalated rows).
    * plan_ok — the last FULL _plan_device pass for this row passed
      every static eligibility check; while it holds (and the cheap
      per-launch conditions — empty queues, clean row, no snapshot/
      read state — are re-verified inline), the colocated fast tick
      lane may skip the full classifier.  Invalidated by the events
      that can change a static check: merge-loop snapshot sends,
      int32-limit proximity, membership traffic (which arrives via
      the queues and forces the full path anyway).
    * esc_hold — steps to HOLD the row on the scalar path after an
      escalation (set via set_escalation_hold so both engines share
      the formula).  An escalation triggered by ROUTED-ONLY inputs
      discards those inputs (raft-safe for SAFETY, not for liveness):
      re-uploading immediately starves the scalar of the wire round
      trip it needs to act — observed as an infinite probe->reject->
      escalate loop when a resident leader's next_idx walked below its
      ring window (r4 colocated chaos: a healed follower never caught
      up; ~3k ESC_WINDOW escalations doing nothing).  A few held steps
      let real wire traffic reach the scalar, which then probes from
      the full authoritative log.
    """

    __slots__ = ("node", "_lanes", "_g")

    def __init__(self, node, lanes, g: int):
        self.node = node
        self._lanes = lanes
        self._g = g
        lanes.reset_row(g, attached=True)

    @property
    def dirty(self) -> bool:
        return bool(self._lanes.dirty[self._g])

    @dirty.setter
    def dirty(self, v: bool) -> None:
        self._lanes.dirty[self._g] = v

    @property
    def plan_ok(self) -> bool:
        return bool(self._lanes.plan_ok[self._g])

    @plan_ok.setter
    def plan_ok(self, v: bool) -> None:
        self._lanes.plan_ok[self._g] = v

    @property
    def esc_hold(self) -> int:
        return int(self._lanes.esc_hold[self._g])

    @esc_hold.setter
    def esc_hold(self, v: int) -> None:
        self._lanes.esc_hold[self._g] = v

    def set_escalation_hold(self, config) -> None:
        self.esc_hold = max(4, 2 * config.heartbeat_rtt + 2)


class VectorStepEngine(IStepEngine):
    """Device-backed IStepEngine (plug in via ExpertConfig
    .step_engine_factory = vector_step_engine_factory(...))."""

    def __init__(
        self,
        logdb,
        *,
        capacity: int = 1024,
        P: int = 5,
        W: int = 32,
        M: int = 8,
        E: int = 4,
        O: int = 32,
        device=None,
        mesh=None,
    ):
        if capacity & (capacity - 1):
            raise ValueError("capacity must be a power of two")
        self.logdb = logdb
        self.capacity, self.P, self.W, self.M, self.E, self.O = (
            capacity,
            P,
            W,
            M,
            E,
            O,
        )
        if mesh is not None:
            # SPMD mode: every row-axis tensor is sharded over the mesh
            # on the groups axis (SURVEY §2: the only parallel axis).
            # The kernel is row-local so the step compiles with zero
            # collectives; upload/readback gathers and (in the colocated
            # subclass) cross-shard routing legitimately induce XLA
            # collective permutes — correctness first, the bench path
            # stays single-device.
            from jax.sharding import NamedSharding, PartitionSpec

            if capacity % mesh.size:
                raise ValueError(
                    f"capacity {capacity} must divide over {mesh.size} devices"
                )
            if len(mesh.axis_names) != 1:
                raise ValueError("engine mesh must be one-dimensional")
            self._mesh = mesh
            self._row_sharding = NamedSharding(
                mesh, PartitionSpec(mesh.axis_names[0])
            )
            self._rep_sharding = NamedSharding(mesh, PartitionSpec())
            self._device = None
        else:
            self._mesh = None
            # mesh-aware selection helper (env-overridable; defaults to
            # device 0 — the old hardcoded jax.devices()[0])
            from . import placement

            self._device = (
                device if device is not None
                else placement.default_device(jax)
            )
        # inert rows: no peers, empty inbox -> the kernel never touches them
        self._state = self._put_rows(
            make_state(capacity, P, W, replica_ids=np.zeros(capacity))
        )
        self._row_of: Dict[int, int] = {}  # shard_id -> g
        self._meta: Dict[int, _RowMeta] = {}  # g -> meta
        # SoA truth store behind every _RowMeta (ops/hostplane.py): the
        # vectorized plan classifier and merge stage read these lanes
        # array-at-once instead of probing per-row attributes
        self._lanes = hostplane.RowLanes(capacity)
        # device-plane lease evidence lanes (ROADMAP 4b): the host's
        # model of each resident leader's CheckQuorum activity window,
        # anchored from the F_QUORUM_ACTIVE flag bit — see
        # hostplane.LeaseLanes and _lease_row_step
        self._lease = hostplane.LeaseLanes(capacity)
        # array-side pb.Update lanes (ISSUE 13): the last SYNCED
        # absolute scalar words per row.  A generation's effects diff
        # against these in one vectorized pass (plan_update_sync), and
        # effect-free/commit-only rows skip the per-row get_update
        # object walk entirely — see hostplane.UpdateLanes.
        self._ulanes = hostplane.UpdateLanes(capacity)
        # lane rows classified by the last _device_step, drained by
        # step_shards AFTER the core lock releases (_persist_lane_rows)
        self._lane_pending: List[Tuple] = []
        # array-batched STATE-ONLY persists (no per-row tuples at all):
        # (db, slots, terms, votes, commits, live, js) per LogDB — see
        # _persist_lane_batches.  Rows map to their store through the
        # per-row slot/db-index lanes below, resolved at upload via the
        # ILogDB optional slot protocol (-1 = store has no slot path;
        # such rows ride the tuple form + save_state_lanes instead).
        self._lane_pending_arr: List[Tuple] = []
        self._lane_slot = np.full((capacity,), -1, np.int64)
        self._lane_dbi = np.full((capacity,), -1, np.int64)
        self._lane_dbs: List = []
        if self._mesh is not None:
            # STRIPED free order: consecutive attaches land on distinct
            # device blocks, so resident rows (and their group-tick
            # load) balance across the mesh instead of filling chip 0
            # first (ISSUE 12: per-device counters within 10%).  Pops
            # come from the END of the list, so build the stripe
            # reversed.  The row-block contract is ops/placement.py's.
            blocks = self._mesh.size
            per = capacity // blocks
            order = [
                b * per + i for i in range(per) for b in range(blocks)
            ]
            self._free: List[int] = list(reversed(order))
        else:
            self._free = list(range(capacity - 1, -1, -1))
        # per-row index base (the 64-bit story): the host log is 64-bit
        # throughout; device rows hold indexes REBASED by a per-row
        # multiple of W so the int32 lanes never overflow.  Recomputed at
        # every upload; all host<->device index conversions go through it.
        self._base = np.zeros((capacity,), np.int64)
        self._lock = threading.Lock()
        self._warned_full = False
        # host mirrors of the summary scalars (term/vote/commit/...)
        self._mirror = np.zeros((6, capacity), np.int64)
        # updates whose batched WAL save failed: their nodes re-emit on a
        # later step (peer.commit never ran, so get_update regenerates
        # the same entries/commits) — but device rows only construct
        # updates when FLAGGED, so a failed save must force re-emission
        # explicitly or the batch is silently lost (r4 colocated chaos
        # finding: WAL-fault injection skipped apply batches and
        # diverged a replica's SM)
        self._update_retry: "set" = set()
        self._retry_lock = threading.Lock()
        # nodes whose last save FAILED: their rows are held on the
        # scalar path (save-before-send) until a save succeeds — on the
        # colocated engine a resident row's acks are device-routed in
        # the same launch as the append, so letting it keep stepping on
        # the device while its WAL is faulty would repeatedly expose
        # acked-but-unpersisted entries (review finding)
        self._save_quarantine: "set" = set()
        # device-synced "leader has a lagging peer" bit per row (the
        # scalar remotes of resident rows are stale) — quiesce gate
        self._behind = np.zeros((capacity,), bool)
        # the unified fault plane (faults.FaultController): an active
        # `escalate` fault forces rows through the kernel-escalation
        # recovery machinery.  The base engine consumes it post-launch
        # (discard device effects + scalar replay — the true escalation
        # contract); the colocated engine consumes it at plan time (its
        # routed regions suppress escalated rows ON device, so a
        # post-hoc flag flip there would desync merged state).
        self.fault_injector = None
        self._consume_engine_fault_at_plan = False
        self.stats = {
            "device_steps": 0,
            "device_rows_stepped": 0,
            "host_rows_stepped": 0,
            "escalations": 0,
            "divergence_halts": 0,
            "save_failures": 0,
            "device_reads": 0,
        }
        self._warm()

    def _put(self, x):
        """Commit a SMALL array/pytree (indexes, gathered sub-states) to
        the engine device — replicated in mesh mode.

        EVERY array entering a jitted helper goes through this: jax keys
        executables on argument committed-ness/sharding, so mixing
        committed and uncommitted calls silently doubles every compile
        (~60s each for the step kernel)."""
        if self._mesh is not None:
            return jax.device_put(x, self._rep_sharding)
        return jax.device_put(x, self._device)

    def _put_rows(self, x):
        """Commit a full-capacity row pytree (state, inboxes, [G] masks)
        — sharded over the groups axis in mesh mode."""
        if self._mesh is not None:
            return jax.device_put(x, self._row_sharding)
        return jax.device_put(x, self._device)

    @staticmethod
    def _cq_grace(r) -> None:
        """CheckQuorum grace across a device<->host residency boundary:
        the peer-activity window is sheared by the transition (the other
        side may have just cleared the flags), and an immediate quorum
        check against an empty window steps a healthy leader down.

        The grace DELAYS the next check by restarting the activity
        window (election_tick = 0) instead of fabricating activity: the
        old mark-all-remotes-active form satisfied every check for a
        leader crossing the boundary about once per window — the same
        cadence as the check itself — so a minority-partitioned leader
        could evade stepdown indefinitely (advisor finding).  With the
        reset, passing the delayed check still requires GENUINE
        responses during the fresh window.

        Rate-limited to once per election window (tracked on the raft's
        logical clock) so an oscillating leader cannot push the check
        out forever; worst case a partitioned leader steps down within
        ~2-3 windows instead of the reference's ~1 (`raft.go
        checkQuorumActive [U]`)."""
        now = r.tick_count
        last = getattr(r, "_cq_grace_at", None)
        if last is not None and now - last < r.election_timeout:
            return
        r._cq_grace_at = now
        r.election_tick = 0

    def _warm(self) -> None:
        """Pre-compile the kernel and every per-bucket helper shape so the
        first real step doesn't stall the step worker for seconds (the
        persistent compilation cache makes this nearly free after the
        first process on a machine)."""
        from .types import make_inbox

        st = self._state
        inbox = self._put_rows(make_inbox(self.capacity, self.M, self.E))
        _, out = K.step(st, inbox, out_capacity=self.O)
        _summarize_flags(st, st, out)
        _select_rows(self._put_rows(jnp.ones((self.capacity,), bool)), st, st)
        pos0 = self._put_rows(
            jnp.full((self.capacity,), -1, jnp.int32)
        )
        b = 1
        while b <= self.capacity:
            idx = self._put(jnp.zeros((b,), jnp.int32))
            sub = _gather_rows(st, idx)
            _scatter_rows(st, pos0, sub)
            _gather_detail(st, out, self._put(jnp.zeros((4, b), jnp.int32)))
            _gather_vals(st, out, self._put(jnp.zeros((b,), jnp.int32)))
            _gather_detail_vals(
                st, out, self._put(jnp.zeros((4, b), jnp.int32)),
                self._put(jnp.zeros((b,), jnp.int32)),
            )
            b <<= 1
        one = self._put(jnp.zeros((1,), jnp.int32))
        _set_remote_snapshot(st, one, one, one)
        jax.block_until_ready(self._state)
        if jitcheck.ENABLED:
            # recompile sentry: everything after this point must hit
            # the warmed caches (analysis/jitcheck, docs/ANALYSIS.md)
            jitcheck.mark_warm()

    # ------------------------------------------------------------------
    # row lifecycle
    # ------------------------------------------------------------------
    def _row_key(self, node):
        """Row-table key.  One NodeHost hosts one replica per shard, so
        the base engine keys by shard id; the colocated engine (multiple
        NodeHosts sharing one device) overrides with (shard, replica)."""
        return node.shard_id

    def detach(self, shard_id: int) -> None:
        with self._lock:
            g = self._row_of.pop(shard_id, None)
            if g is not None:
                self._meta.pop(g, None)
                self._lanes.reset_row(g, attached=False)
                self._free.append(g)

    def _halt_replica(self, g: int) -> None:
        """Fail-stop a diverged replica (caller holds the engine lock).

        ``node.stop()`` drops every pending future and closes the SM —
        without it, enqueued traffic and registered futures would leak
        forever on a node nothing will ever step again.  The row slot is
        freed so other shards can use it.  Safe under the engine lock:
        apply workers never call back into the step engine."""
        node = self._meta[g].node
        self.stats["divergence_halts"] += 1
        self._row_of.pop(self._row_key(node), None)
        self._meta.pop(g, None)
        self._lanes.reset_row(g, attached=False)
        self._free.append(g)
        node.stop()

    def _compute_base(self, r) -> int:
        """Largest W-multiple not exceeding any live index quantity of
        the row — subtracting it keeps every device lane positive (0
        stays the sentinel for match/next/snap) and, being a multiple of
        W, leaves ring slot assignment invariant.  The colocated engine
        overrides this to 0: routed messages carry raw index lanes
        between rows, which is only sound under one shared base."""
        # committed bounds the base, NOT first_index: the device only
        # holds the [last-W+1, last] ring, so a shifted first_index lane
        # may legitimately go negative (uniform shift keeps every
        # comparison exact); an uncompacted log whose retained span
        # itself exceeds int32 is rejected by the planner's spread guard
        qs = [r.log.committed]
        if r.role == RaftRole.LEADER:
            # per-peer progress lanes are live state only on a leader;
            # followers carry stale values (e.g. next=1 from boot) that
            # get reset at the next election — including those would pin
            # the base at 0 forever.  Stale non-leader lanes clamp to the
            # 0 sentinel at upload instead (state_from_rafts).
            for group in (r.remotes, r.non_votings, r.witnesses):
                for rm in group.values():
                    if rm.match > 0:
                        qs.append(rm.match - 1)
                    if rm.next > 0:
                        qs.append(rm.next - 1)
                    if rm.snapshot_index > 0:
                        qs.append(rm.snapshot_index - 1)
        base = max(0, min(qs))
        return base - (base % self.W)

    def _static_host_only(self, node) -> bool:
        """Shards that can never (currently) be device-resident — checked
        BEFORE attaching a row or consuming quiesce state."""
        r = node.peer.raft
        if len(r.addresses) > self.P:
            return True
        if r.is_self_removed():
            # mid-join (empty membership) or removed: the kernel derives
            # the replica's tier from its own peer slot, which doesn't
            # exist yet/anymore — scalar path until membership settles
            return True
        return False

    def _attach(self, node) -> Optional[int]:
        g = self._row_of.get(self._row_key(node))
        if g is not None:
            return g
        if not self._free:
            if not self._warned_full:
                self._warned_full = True
                _log.warning(
                    "vector engine at capacity %d; overflow shards stay on "
                    "the host path",
                    self.capacity,
                )
            return None
        g = self._pick_row(node)
        self._row_of[self._row_key(node)] = g
        self._meta[g] = _RowMeta(node, self._lanes, g)
        return g

    def _pick_row(self, node) -> int:
        """Pop a free row slot.  The base policy is the free-list order
        (striped across device blocks in mesh mode); the colocated
        engine overrides with shard affinity — see its _pick_row."""
        return self._free.pop()

    def device_coordinate(self, shard_id: int):
        """Device block hosting this shard's row under the placement
        contract (ops/placement.py), or None when unknown / no mesh —
        the balance plane's new chip-placement dimension (ROADMAP 3)."""
        if self._mesh is None:
            return None
        g = self._row_of.get(shard_id)
        if g is None:
            return None
        return g // (self.capacity // self._mesh.size)

    def device_chip_count(self) -> int:
        """Chips this engine spreads rows over (1 = single device)."""
        return self._mesh.size if self._mesh is not None else 1

    # ------------------------------------------------------------------
    # classification
    # ------------------------------------------------------------------
    def _plan_device(
        self, node, si, mirror_leader: bool, g: int
    ) -> Optional[List[Tuple]]:
        """Return the ordered inbox slot plan, or None for the host path.

        Slot order mirrors the scalar replay order in
        ``Node.step_with_inputs``: received messages, proposals,
        read-indexes, ticks.  Reads stay on the device only when the
        row's mirror says LEADER (the kernel's ReadIndex hot path); a
        stale mirror is safe — the kernel reject-resps and the client
        retries.

        Quiesce (reference: quiesceManager [U]) runs host-side even for
        device rows: quiesced ticks simply produce no TICK slots, so an
        idle shard's device row is never touched — the TPU equivalent of
        "millions of idle groups cost nothing".  Exiting quiesce needs
        the scalar poke path (LEADER_HEARTBEAT), so that step goes host.
        """
        if (
            si.config_changes
            or si.cc_results
            or si.snapshot_reqs
            or si.transfers
        ):
            return None
        inj = self.fault_injector
        if (
            inj is not None
            and self._consume_engine_fault_at_plan
            and getattr(inj, "has_active", lambda k: True)("escalate")
            and inj.on_engine_step(node.shard_id, node.replica_id)
        ):
            return None  # nemesis: forced scalar excursion for this row
        if si.read_indexes and not mirror_leader:
            return None
        if node in self._save_quarantine:
            return None  # WAL faulting: scalar path is save-before-send
        meta = self._meta.get(g)
        if meta is not None and meta.esc_hold > 0:
            meta.esc_hold -= 1
            return None  # post-escalation scalar hold (see _RowMeta)
        if node.quiesce.enabled:
            # QUIESCE enter-hints never touch raft state (node.py applies
            # them via quiesce_hint() only) — consume them HERE instead
            # of bouncing the row to the scalar path: at 10k shards the
            # post-election quiesce wave otherwise broadcasts a cold
            # wire type to every peer of every quiescing shard (~P x
            # shards host excursions + re-uploads, measured as ~96k host
            # steps during the r4 scale run's propose phase).  Safe
            # against the host-fallback double-processing rule: hints
            # are removed from si.received, and the scalar step's only
            # handling of them is the same quiesce_hint() call.
            kept = []
            for m in si.received:
                if int(m.type) == int(MessageType.QUIESCE):
                    # no-leader gate (see QuiesceManager.tick block=):
                    # joining a peer's quiesce while this node knows no
                    # leader can park a shard mid-election
                    leader = (
                        node.peer.raft.leader_id
                        if self._meta[g].dirty
                        else int(self._mirror[_R_LEADER, g])
                    )
                    if leader:
                        node.quiesce.quiesce_hint()
                else:
                    kept.append(m)
            si.received = kept
        if node.quiesce.enabled and node.quiesce.is_quiesced() and (
            si.received or si.proposals
        ):
            # activity exits quiesce; peers must be poked — scalar path
            # (quiesce state deliberately untouched: step_with_inputs
            # re-processes these inputs and performs the exit + poke)
            return None
        r = node.peer.raft
        if r.read_index.pending or r.read_index.queue:
            return None
        if r.snapshotting:
            return None
        lim = 2**31 - 1
        # index lanes are REBASED per row (see _compute_base), so log
        # growth never ages a row off the device; the remaining int32
        # ceilings are terms (2^31 elections is out of scope — the row
        # falls back loudly below) and a pathological >2^31 spread
        # between a row's lowest live index quantity and its last index
        if self._meta[g].dirty:
            base = self._compute_base(r)
            self._base[g] = base
        else:
            base = int(self._base[g])
        if r.term >= lim:
            if not getattr(r, "_term_lim_warned", False):
                r._term_lim_warned = True
                _log.warning(
                    "[%d:%d] term %d exceeds the device int32 lane; "
                    "scalar path permanently",
                    r.shard_id, r.replica_id, r.term,
                )
            return None
        if r.log.last_index() - base + self.M * self.E >= lim:
            return None
        if base - r.log.first_index() >= lim:
            return None  # >2^31 retained-but-uncompacted span
        for group in (r.remotes, r.non_votings, r.witnesses):
            for rm in group.values():
                if (
                    rm.state == RemoteState.SNAPSHOT
                    and 0 < rm.snapshot_index <= base
                ):
                    # a below-base snapshot install is in flight: the
                    # device lane can't represent it (see
                    # _send_snapshots), so the row stays scalar until
                    # SnapshotStatus/Received resolves the transfer —
                    # otherwise re-uploads would re-fire need_snapshot
                    # and stream duplicate full snapshots every cycle
                    return None
        slots: List[Tuple] = []
        for m in si.received:
            if int(m.type) not in _HOT_SET:
                return None
            if int(m.type) == int(MessageType.READ_INDEX):
                # a follower-FORWARDED read: the kernel's hot path only
                # answers to self, so the wire response to the origin
                # must come from the scalar leader (host path) — device
                # handling would silently swallow the follower's read
                return None
            if len(m.entries) > self.E:
                return None
            # index fields enter the device rebased; ctx keys (hint on
            # heartbeat/read slots) are 64-bit-split and checked raw, but
            # a reject hint IS an index and shifts with the base
            if int(m.type) == int(MessageType.REPLICATE_RESP) and m.reject:
                h = m.hint - base
                if base and h <= 0:
                    # the follower's last index sits BELOW this row's
                    # base: the kernel's decrease floor (max(..., 1) in
                    # rebased space) cannot walk next under the base, so
                    # the scalar path must handle this rejection — it
                    # decreases in absolute space and the next upload
                    # recomputes a base low enough for the lagging peer
                    return None
            else:
                h = m.hint
            if (
                m.term > lim
                or m.log_term > lim
                or not -lim < m.log_index - base < lim
                or not -lim < m.commit - base < lim
                or not -lim < h < lim
                or m.hint_high > lim
            ):
                return None
            slots.append(("msg", m))
        E = self.E
        props = si.proposals
        for i in range(0, len(props), E):
            slots.append(("prop", props[i : i + E]))
        for ctx in si.read_indexes:
            slots.append(("read", ctx))
        # conservative capacity check BEFORE consuming quiesce state so a
        # host fallback never double-processes ticks/activity
        if len(slots) > self.M:
            return None
        # multi-tick fusion: ALL of a row's drained ticks ride one
        # count-carrying LOCAL_TICK slot (kernel._tick advances timers
        # by n).  The count cap mirrors the scalar step's half-election-
        # window gulp limit — at most one timer threshold crossing per
        # launch, so a stalled row can't replay several CheckQuorum/
        # election windows back-to-back with no wall time for responses.
        # Overflow ticks are DEFERRED (the logical clock briefly lags;
        # reference: dragonboat coalesces LocalTick bursts [U]).
        cap = max(1, r.election_timeout // 2)
        if si.ticks > cap:
            node.defer_ticks(si.ticks - cap)
            si.ticks = cap
        if si.ticks and len(slots) >= self.M:
            # every slot taken by messages/proposals: defer the ticks
            # rather than bouncing the row off the device
            node.defer_ticks(si.ticks)
            si.ticks = 0
        ticks = si.ticks
        if node.quiesce.enabled:
            # committed to the device path now: record (non-exiting)
            # activity and swallow quiesced ticks — a quiesced row gets
            # no TICK slots, so its device state is never touched.
            # (QUIESCE enter-hints are a cold type and never reach here.)
            for m in si.received:
                node.quiesce.record_activity(m.type)
            if si.proposals:
                node.quiesce.record_activity(MessageType.PROPOSE)
            ticks = 0
            if self._meta[g].dirty:
                busy = node.peer.raft.catching_up_peers()
                no_leader = node.peer.raft.leader_id == 0
            else:
                busy = bool(self._behind[g])
                no_leader = int(self._mirror[_R_LEADER, g]) == 0
            was_quiesced = node.quiesce.quiesced
            ticks += node.quiesce.tick_n(
                si.ticks, busy=busy, block=no_leader
            )
            if node.quiesce.quiesced and not was_quiesced:
                node.broadcast_quiesce_enter()
        if ticks:
            slots.append(("tick", ticks))
        return slots

    # ------------------------------------------------------------------
    # device <-> scalar state movement
    # ------------------------------------------------------------------
    def _upload_rows(self, rows: List[Tuple[int, "Raft"]]) -> None:
        """Scalar -> device for dirty rows (batched scatter)."""
        if not rows:
            return
        import time as _time

        _t0 = _time.perf_counter()
        for _, r in rows:
            if r.role == RaftRole.LEADER and r.check_quorum:
                self._cq_grace(r)
        bases = [int(self._base[g]) for g, _ in rows]
        # padding happens in numpy INSIDE state_from_rafts: the old
        # eager jnp slice/repeat/concat per field compiled ~93 tiny
        # programs per new bucket shape on the remote TPU link
        sub = S.state_from_rafts(
            [r for _, r in rows], self.P, self.W, bases=bases,
            pad_to=_bucket(len(rows)),
        )
        self.stats["uploaded_rows"] = (
            self.stats.get("uploaded_rows", 0) + len(rows)
        )
        # float ms: mass start streams thousands of sub-ms batches and
        # int truncation would hide exactly the cost this counter exists
        # to expose (review finding)
        self.stats["t_up_pack_ms"] = self.stats.get(
            "t_up_pack_ms", 0
        ) + (_time.perf_counter() - _t0) * 1000.0
        _t0 = _time.perf_counter()
        pos = self._put_rows(jnp.asarray(
            _pos_map(self.capacity, [g for g, _ in rows])
        ))
        self._state = _scatter_rows(self._state, pos, self._put(sub))
        self.stats["t_up_scatter_ms"] = self.stats.get(
            "t_up_scatter_ms", 0
        ) + (_time.perf_counter() - _t0) * 1000.0
        for k, (g, r) in enumerate(rows):
            # the mirror holds what the DEVICE holds: index rows shifted
            self._mirror[_R_TERM, g] = r.term
            self._mirror[_R_VOTE, g] = r.vote
            self._mirror[_R_COMMIT, g] = r.log.committed - self._base[g]
            self._mirror[_R_LEADER, g] = r.leader_id
            self._mirror[_R_ROLE, g] = int(r.role)
            self._mirror[_R_LAST, g] = r.log.last_index() - self._base[g]
            # update lanes hold the ABSOLUTE frame (rebases never
            # perturb them); the scalar raft is authoritative at upload
            self._ulanes.seed_row(
                g, r.term, r.vote, r.log.committed, r.leader_id,
                int(r.role), r.log.last_index(),
            )
            # lane-diff leader notifications (U_LEADER) assume the node
            # view is in sync with the raft at seed time; the scalar
            # path's own _check_leader_change keeps it so, but a join/
            # restore can upload before the first scalar step ran
            node = self._meta[g].node
            if node.leader_id != r.leader_id:
                node._check_leader_change()
            # hard-state lane slot + db index (the ILogDB optional slot
            # protocol): resolved once per upload so the merge tail's
            # state-only persist is a pure array scatter per LogDB
            db = node.logdb
            get_slot = getattr(db, "state_lane_slot", None)
            if get_slot is not None:
                s = node.hs_lane_slot
                if s < 0:
                    s = get_slot(node.shard_id, node.replica_id)
                    node.hs_lane_slot = s
                self._lane_slot[g] = s
                for di, d in enumerate(self._lane_dbs):
                    if d is db:
                        break
                else:
                    self._lane_dbs.append(db)
                    di = len(self._lane_dbs) - 1
                self._lane_dbi[g] = di
            else:
                self._lane_slot[g] = -1
                self._lane_dbi[g] = -1
            # lease evidence lanes follow device residency (ROADMAP 4b)
            if r.role == RaftRole.LEADER and r.check_quorum:
                self._lease.arm(g, r.election_timeout, r.election_tick)
            else:
                self._lease.disarm(g)
            self._meta[g].dirty = False
            # the scalar excursion may have changed the static plan
            # facts (term, log span, remotes); require a fresh full
            # plan before the fast tick lane re-engages
            self._meta[g].plan_ok = False

    def _materialize_rows(
        self, gs: List[int], state: Optional[DeviceState] = None
    ) -> None:
        """Device -> scalar for rows leaving the device (batched gather).

        Copies the protocol fields the device owns; scalar-only state
        (ReadIndex table, sessions, is_leader_transfer_target) was never
        touched by the device path and stays as-is.
        """
        if not gs:
            return
        st = state if state is not None else self._state
        idx = self._put(jnp.asarray(_pad_idx(gs)))
        sub = jax.tree.map(np.asarray, _gather_rows(st, idx))
        for k, g in enumerate(gs):
            self._lease.disarm(g)  # scalar path re-arms at next upload
            node = self._meta[g].node
            base = int(self._base[g])
            if node.device_reads.has_pending():
                # the scalar path takes over: device-read confirmations
                # ride device steps and would never arrive — fail fast
                # so clients retry on the host path
                node.drop_device_reads()
            r = node.peer.raft
            r.term = int(sub.term[k])
            r.vote = int(sub.vote[k])
            r.leader_id = int(sub.leader_id[k])
            r.role = RaftRole(int(sub.role[k]))
            r.log.committed = int(sub.committed[k]) + base
            r.election_tick = int(sub.election_tick[k])
            r.heartbeat_tick = int(sub.heartbeat_tick[k])
            r.randomized_election_timeout = int(sub.rand_timeout[k])
            r._timeout_seq = int(sub.timeout_seq[k])
            r.pending_config_change = bool(sub.pending_cc[k])
            r.leader_transfer_target = int(sub.transfer_target[k])
            votes = {}
            for p in range(self.P):
                pid = int(sub.peer_id[k, p])
                if pid == 0:
                    continue
                rm = r.get_remote(pid)
                if rm is None:
                    continue
                m_ = int(sub.match[k, p])
                n_ = int(sub.next_idx[k, p])
                s_ = int(sub.snap_index[k, p])
                rm.match = m_ + base if m_ > 0 else m_
                rm.next = n_ + base if n_ > 0 else n_
                rm.state = RemoteState(int(sub.rstate[k, p]))
                rm.snapshot_index = s_ + base if s_ > 0 else s_
                rm.active = bool(sub.active[k, p])
                granted = int(sub.granted[k, p])
                if granted:
                    votes[pid] = granted == 1
            r.votes = votes
            if r.role == RaftRole.LEADER and r.check_quorum:
                self._cq_grace(r)  # sheared window — see _cq_grace
            dev_last = int(sub.last_index[k]) + base
            host_last = r.log.last_index()
            if dev_last != host_last:
                # the reconstruction invariant broke: the host log no
                # longer mirrors the rows the device stepped, so any
                # further ack could be for an entry the WAL never saw.
                # Halt the replica loudly, like the snapshot-recovery
                # failure path in node.py (reference: dragonboat panics
                # on unrecoverable state [U]).
                _log.critical(
                    "[%d:%d] FATAL: device/host log divergence: device "
                    "last=%d host last=%d; halting replica",
                    r.shard_id,
                    r.replica_id,
                    dev_last,
                    host_last,
                )
                self._halt_replica(g)

    # ------------------------------------------------------------------
    # the step
    # ------------------------------------------------------------------
    def step_shards(self, nodes, worker_id: int) -> None:
        """Per-node structures are safe without the engine lock — the
        ExecEngine partitions shards over workers, so each node is only
        ever stepped by its owning worker.  The lock guards the shared
        device state (self._state, row tables, mirrors); host-path scalar
        stepping and save/process run outside it so a slow cold shard
        cannot stall the other workers' partitions."""
        updates: List[Tuple] = []  # (node, Update)
        host_rows: List[Tuple] = []  # (node, si)
        batch: List[Tuple] = []  # (node, g, si, plan)
        with self._lock:
            for node in nodes:
                if node.stopped:
                    continue
                si = node.drain_step_inputs()
                # row attachment must precede planning: _plan_device
                # consumes quiesce ticks once committed to the device
                # path, and a post-plan capacity fallback would make the
                # host path re-process them
                if self._static_host_only(node):
                    host_rows.append((node, si))
                    continue
                g = self._attach(node)
                if g is None:
                    host_rows.append((node, si))
                    continue
                mirror_leader = (
                    not self._meta[g].dirty
                    and self._mirror[_R_ROLE, g] == int(RaftRole.LEADER)
                )
                plan = self._plan_device(node, si, mirror_leader, g)
                if plan is None:
                    host_rows.append((node, si))
                    continue
                if not plan and not self._meta[g].dirty:
                    # nothing for the device, but the logical clock still
                    # advanced: a quiesced row's swallowed ticks must GC
                    # pending futures exactly like the scalar loop does
                    _tick_bookkeeping(node, si.ticks + si.gc_ticks)
                    continue
                batch.append((node, g, si, plan))

            # cold rows leave the device before their scalar step
            to_mat = []
            for node, si in host_rows:
                g = self._row_of.get(self._row_key(node))
                if g is not None and not self._meta[g].dirty:
                    to_mat.append(g)
                    self._meta[g].dirty = True
            self._materialize_rows(to_mat)  # one batched gather for all

        # ---- host path (cold rows; engine lock released) -------------
        for node, si in host_rows:
            if node.stopped:  # e.g. halted by a divergence fail-stop
                continue
            u = node.step_with_inputs(si)
            self.stats["host_rows_stepped"] += 1
            if u is not None:
                updates.append((node, u))

        # ---- device path ---------------------------------------------
        lane_rows: List[Tuple] = []
        lane_batches: List[Tuple] = []
        if batch:
            with self._lock:
                # re-validate: a concurrent detach() (stop_replica) may
                # have freed — or freed and re-assigned — a row between
                # the lock sections
                batch = [
                    (node, g, si, plan)
                    for node, g, si, plan in batch
                    if self._row_of.get(self._row_key(node)) == g
                    and self._meta.get(g) is not None
                    and self._meta[g].node is node
                    and not node.stopped
                ]
                self._upload_rows(
                    [
                        (g, node.peer.raft)
                        for node, g, si, plan in batch
                        if self._meta[g].dirty
                    ]
                )
                if batch:
                    updates.extend(self._device_step(batch))
                    # this worker's lane rows, swapped out under the
                    # same lock hold (each worker persists only its own)
                    lane_rows, self._lane_pending = (
                        self._lane_pending, []
                    )
                    lane_batches, self._lane_pending_arr = (
                        self._lane_pending_arr, []
                    )

        # lane persist FIRST: it advances the processed cursors, so a
        # retrying node's get_update below re-emits only the remainder
        self._persist_lane_batches(lane_batches, worker_id)
        self._persist_lane_rows(lane_rows, worker_id)
        self._drain_update_retries(updates, owned={id(n) for n in nodes})
        if updates:
            self._persist_and_process(updates, worker_id)

    def _drain_update_retries(self, updates, owned=None) -> None:
        """Re-emit updates for nodes whose last batched save failed.
        ``owned`` restricts the drain to nodes this worker may touch
        (the ExecEngine partitions shards over workers); unrestricted
        callers (the colocated engine, which owns everything under its
        core lock) pass None."""
        with self._retry_lock:
            # prune stopped nodes from both sets: a killed member's dead
            # Node object must not be leaked (or consulted) forever
            self._save_quarantine = {
                n for n in self._save_quarantine if not n.stopped
            }
            self._update_retry = {
                n for n in self._update_retry if not n.stopped
            }
            if not self._update_retry:
                return
            if owned is None:
                retry, self._update_retry = self._update_retry, set()
            else:
                retry = {n for n in self._update_retry if id(n) in owned}
                self._update_retry -= retry
        have = {id(n) for n, _ in updates}
        for node in retry:
            if node.stopped or id(node) in have:
                continue
            u = node.peer.get_update(last_applied=node.sm.last_applied)
            if u is not None:
                node.dispatch_dropped(u)
                updates.append((node, u))

    def _demote_row_to_host(self, node) -> None:
        """Pull a resident row back to scalar authority with a short
        hold — used when the device path hits something only the full
        host log can resolve (e.g. a below-ring send whose prev index
        the host has compacted)."""
        g = self._row_of.get(self._row_key(node))
        if g is None:
            return
        meta = self._meta.get(g)
        if meta is None or meta.dirty:
            return
        self._materialize_rows([g])
        meta.dirty = True
        meta.set_escalation_hold(node.config)

    def _persist_and_process(self, updates, worker_id: int) -> None:
        """save -> send/apply with per-LogDB fault isolation.  A failed
        batched save loses nothing: peer.commit(u) never ran for those
        nodes, so their entries/commits re-emit via _drain_update_retries
        on a later step; other LogDBs' batches still save and process
        (one member's disk fault must not stall the cluster)."""
        by_db: Dict[int, Tuple] = {}
        for node, u in updates:
            by_db.setdefault(id(node.logdb), (node.logdb, []))[1].append(
                (node, u)
            )
        for db, pairs in by_db.values():
            try:
                db.save_raft_state([u for _, u in pairs], worker_id)
            except Exception:  # noqa: BLE001
                self.stats["save_failures"] += 1
                _log.exception(
                    "batched save failed for %d update(s); will re-emit",
                    len(pairs),
                )
                self._on_save_failure(pairs)
                continue
            self._on_save_ok(pairs)
            for node, u in pairs:
                if node.process_update(u):
                    node.engine_apply_ready(node.shard_id)

    def _persist_lane_batches(self, batches, worker_id: int) -> None:
        """Array-batched persist for slot-backed lane rows: one
        ``save_state_slots`` scatter per LogDB, zero per-row Python on
        the state-only success path.  ``batches`` entries are ``(db,
        slots, terms, votes, commits, live, js, applies)`` — the node
        list is materialized from ``live[j]`` ONLY on a save failure
        (re-emit + quarantine, the _persist_and_process contract) or
        while a quarantine is active.  ``applies`` carries the batch's
        commit rows' ``(node, committed-entries)`` handoffs; they run
        strictly AFTER the batch's save lands (peer.commit's
        persist-before-apply order) and not at all on failure — the
        failed rows re-emit classic updates with cursors untouched.
        Same ordering contract as _persist_lane_rows: runs before this
        step's _drain_update_retries."""
        if not batches:
            return
        n = 0
        n_commit = 0
        handoffs: List[Tuple] = []
        for db, slots, terms, votes, commits, live, js, applies \
                in batches:
            n += len(slots)
            try:
                db.save_state_slots(slots, terms, votes, commits,
                                    worker_id)
            except Exception:  # noqa: BLE001
                self.stats["save_failures"] += 1
                _log.exception(
                    "batched slot save failed for %d row(s); will "
                    "re-emit",
                    len(slots),
                )
                self._on_save_failure(
                    [(live[j][0], None) for j in js.tolist()]
                )
                continue
            if self._save_quarantine:
                self._on_save_ok(
                    [(live[j][0], None) for j in js.tolist()]
                )
            # collected, not applied inline: the whole generation's
            # commit rows hand off in ONE batched per-SM-worker pass
            # below (each row still strictly after ITS batch's save
            # landed — failed batches never reach this list)
            handoffs.extend(applies)
            n_commit += len(applies)
        _apply_lane_commits(handoffs)
        if n:
            self.stats["lane_rows"] = (
                self.stats.get("lane_rows", 0) + n
            )
        if n_commit:
            self.stats["lane_commit_rows"] = (
                self.stats.get("lane_commit_rows", 0) + n_commit
            )

    def _persist_lane_rows(self, rows, worker_id: int) -> None:
        """Persist + apply-handoff for LANE rows — the batched
        replacement for per-row save_raft_state/process_update/
        peer.commit on rows whose whole effect is a hard-state move
        and/or a commit advance (ISSUE 13).

        ``rows`` is a list of ``(node, term, vote, commit, ce)`` where
        ``ce`` is the row's committed-entries list (None when only the
        hard state moved).  One ``save_state_lanes`` call per LogDB
        persists every row's (term, vote, commit) triple; only then do
        commit rows hand their entries to the apply queue and advance
        the processed cursor — peer.commit's job, inlined: ``ce`` came
        from ``entries_to_apply(processed+1 .. committed+1)``, so the
        new processed is in (processed, committed] by construction
        (the commit_update guard, pre-verified).  A failed batched
        save advances NOTHING: the nodes re-emit classic full updates
        (state + the same committed entries, cursors untouched) via
        _drain_update_retries — exactly the _persist_and_process
        contract.  MUST run before this step's _drain_update_retries,
        or a retrying node's fresh get_update would collect entries a
        pending lane handoff is about to deliver too."""
        if not rows:
            return
        self.stats["lane_rows"] = (
            self.stats.get("lane_rows", 0) + len(rows)
        )
        by_db: Dict[int, Tuple] = {}
        for t in rows:
            db = t[0].logdb
            by_db.setdefault(id(db), (db, []))[1].append(t)
        n_commit = 0
        handoffs: List[Tuple] = []
        for db, rs in by_db.values():
            try:
                save_slots = getattr(db, "save_state_slots", None)
                if save_slots is not None:
                    # vectorized scatter by cached slot (the ILogDB
                    # optional slot protocol): slot resolution is a
                    # once-per-node event, the steady save is three
                    # numpy scatters under one lock hold
                    get_slot = db.state_lane_slot
                    slots = []
                    for t in rs:
                        node = t[0]
                        s = node.hs_lane_slot
                        if s < 0:
                            s = get_slot(node.shard_id, node.replica_id)
                            node.hs_lane_slot = s
                        slots.append(s)
                    save_slots(
                        slots,
                        [t[1] for t in rs],
                        [t[2] for t in rs],
                        [t[3] for t in rs],
                        worker_id,
                    )
                else:
                    db.save_state_lanes(
                        [t[0].shard_id for t in rs],
                        [t[0].replica_id for t in rs],
                        [t[1] for t in rs],
                        [t[2] for t in rs],
                        [t[3] for t in rs],
                        worker_id,
                    )
            except Exception:  # noqa: BLE001
                self.stats["save_failures"] += 1
                _log.exception(
                    "batched lane save failed for %d row(s); will "
                    "re-emit",
                    len(rs),
                )
                self._on_save_failure([(t[0], None) for t in rs])
                continue
            self._on_save_ok([(t[0], None) for t in rs])
            for node, _term, _vote, _commit, ce in rs:
                if not ce:
                    continue
                n_commit += 1
                handoffs.append((node, ce))
        _apply_lane_commits(handoffs)
        if n_commit:
            self.stats["lane_commit_rows"] = (
                self.stats.get("lane_commit_rows", 0) + n_commit
            )

    def _on_save_failure(self, pairs) -> None:
        """Queue re-emission and quarantine the nodes to the scalar
        path until a save succeeds (see _save_quarantine)."""
        with self._retry_lock:
            for node, _u in pairs:
                self._update_retry.add(node)
                self._save_quarantine.add(node)
        for node, _u in pairs:
            if node.notify_work is not None:
                node.notify_work()

    def _on_save_ok(self, pairs) -> None:
        if not self._save_quarantine:
            return
        with self._retry_lock:
            for node, _u in pairs:
                self._save_quarantine.discard(node)

    def _encode_batch(self, batch, slot_offset: int = 0):
        """Plans -> (per-row Message lists, staging, proposal rows).

        Shared by the base and colocated device steps: slot order mirrors
        the scalar replay order; staged payload entries are keyed by slot
        for the post-step append reconstruction; ``prop_rows`` marks rows
        whose slot_base detail must be gathered (local 'prop' slots AND
        wire PROPOSE messages — a forwarded proposal arriving at the
        leader carries staged entries too).

        ``slot_offset`` shifts staging keys to ASSEMBLED slot indices:
        the colocated engine prepends its routed regions (width P*B)
        before the host slots, and the kernel reports slot_base/
        ent_drop/src_slot in assembled coordinates.

        ``tick_fed`` (4th return, row -> fused tick count) is the
        device-window mirror input for the lease evidence lanes
        (hostplane.LeaseLanes.row_step)."""
        msg_rows: List[List[Message]] = [[] for _ in range(self.capacity)]
        staging: Dict[int, Dict[int, List[Entry]]] = {}
        prop_rows: List[int] = []
        tick_fed: Dict[int, int] = {}
        for node, g, si, plan in batch:
            row_msgs = msg_rows[g]
            stage: Dict[int, List[Entry]] = {}
            base = int(self._base[g])
            for plan_slot, (kind, payload) in enumerate(plan):
                slot = slot_offset + plan_slot
                if kind == "msg":
                    if payload.entries:
                        stage[slot] = list(payload.entries)
                    row_msgs.append(_shift_msg_indexes(payload, -base))
                elif kind == "prop":
                    row_msgs.append(
                        Message(
                            type=MessageType.PROPOSE,
                            entries=tuple(payload),
                        )
                    )
                    stage[slot] = list(payload)
                elif kind == "read":
                    self.stats["device_reads"] += 1
                    row_msgs.append(
                        Message(
                            type=MessageType.READ_INDEX,
                            hint=payload.low,
                            hint_high=payload.high,
                        )
                    )
                else:  # tick — log_index carries the fused count; hint
                    # lanes carry the latest pending read ctx so lost
                    # confirmations retry on the heartbeat cadence
                    tick_fed[g] = payload
                    pc = node.device_reads.peek_ctx()
                    row_msgs.append(
                        Message(
                            type=MessageType.LOCAL_TICK,
                            log_index=payload,
                            hint=pc.low if pc else 0,
                            hint_high=pc.high if pc else 0,
                        )
                    )
            if stage:
                staging[g] = stage
            if any(k == "prop" for k, _ in plan) or any(
                k == "msg" and int(p.type) == int(MessageType.PROPOSE)
                for k, p in plan
            ):
                prop_rows.append(g)
        return msg_rows, staging, prop_rows, tick_fed

    def _device_step(self, batch) -> List[Tuple]:
        G, M, E = self.capacity, self.M, self.E
        msg_rows, staging, prop_rows, tick_fed = self._encode_batch(batch)
        inbox, overflow = S.encode_inbox(msg_rows, M, E)
        assert not overflow, f"planner let oversized rows through: {overflow}"
        inbox = self._put_rows(inbox)

        old_state = self._state
        from ..profiling import annotate

        with annotate("raft-device-step"):
            new_state, out = K.step(old_state, inbox, out_capacity=self.O)
            flags = np.asarray(_summarize_flags(old_state, new_state, out))
        inj = self.fault_injector
        if (
            inj is not None
            and not self._consume_engine_fault_at_plan
            and getattr(inj, "has_active", lambda k: True)("escalate")
        ):
            # nemesis: force the kernel-escalation recovery path for the
            # selected rows — their device effects are discarded below
            # exactly as for a real ESC_* escalation.  The jax-backed
            # asarray view is read-only; take a writable copy to flip
            # bits in (only on the injected path — never in production)
            flags = np.array(flags)
            for node, g, si, plan in batch:
                if not flags[g] & _F_ESC and inj.on_engine_step(
                    node.shard_id, node.replica_id
                ):
                    flags[g] |= _F_ESC
        self._behind = (flags & _F_PEERS_BEHIND) != 0
        self.stats["device_steps"] += 1
        self.stats["device_rows_stepped"] += len(batch)

        # ---- escalations: restore + scalar replay --------------------
        esc_rows = [
            (node, g, si)
            for node, g, si, plan in batch
            if flags[g] & _F_ESC
        ]
        updates: List[Tuple] = []
        if esc_rows:
            self.stats["escalations"] += len(esc_rows)
            keep_new = np.ones((G,), bool)
            for _, g, _ in esc_rows:
                keep_new[g] = False
            new_state = _select_rows(
                self._put_rows(jnp.asarray(keep_new)), old_state, new_state
            )
            self._materialize_rows([g for _, g, _ in esc_rows], old_state)
            for node, g, si in esc_rows:
                meta = self._meta.get(g)
                if meta is None:  # halted + detached during materialize
                    continue
                meta.dirty = True
                meta.set_escalation_hold(node.config)
                # quiesce note: _plan_device already consumed this step's
                # quiesce ticks; the replay re-ticks the manager, which can
                # only make the shard quiesce EARLIER — benign for a perf
                # heuristic that exits on any activity
                u = node.step_with_inputs(si)
                if u is not None:
                    updates.append((node, u))
        self._state = new_state
        esc_set = {g for _, g, _ in esc_rows}

        # ---- gather detail for affected rows (ONE fused dispatch: the
        # per-step latency floor is dispatch round-trips, which on remote
        # device links cost far more than the extra padded bytes) -------
        live = [(node, g, si) for node, g, si, plan in batch if g not in esc_set]
        buf_rows = [g for _, g, _ in live if flags[g] & _F_COUNT]
        append_rows = [g for _, g, _ in live if flags[g] & _F_APPEND]
        slot_rows = [g for g in prop_rows if g not in esc_set]
        need_rows = [g for _, g, _ in live if flags[g] & _F_NEED_SS]
        # rows whose VALUES the merge loop reads: anything flagged or
        # carrying proposal slots (the rest only tick)
        slot_set = set(slot_rows)
        sum_rows = [
            g for _, g, _ in live
            if (flags[g] & _F_ANY_LIVE) or g in slot_set
        ]
        idx4 = _build_idx4(buf_rows, slot_rows, need_rows, append_rows)
        detail, vals_np = _fetch_detail_vals(
            new_state, out, idx4, sum_rows, self._put,
            self.O, self.M, self.E, self.P, self.W,
        )
        if detail is not None:
            (buf_np, slot_base, slot_term, ent_drop, need_np, ring_t,
             ring_c) = detail
        else:
            buf_np = slot_base = slot_term = ent_drop = need_np = None
            ring_t = ring_c = None
        buf_at = {g: k for k, g in enumerate(buf_rows)}
        ring_at = {g: k for k, g in enumerate(append_rows)}
        slot_at = {g: k for k, g in enumerate(slot_rows)}
        need_at = {g: k for k, g in enumerate(need_rows)}
        sum_at = {g: k for k, g in enumerate(sum_rows)}

        # ---- per-row update construction -----------------------------
        # A generation's effects classify ARRAY-SIDE first: one
        # plan_update_sync pass over the update lanes yields per-row
        # U_* effect bits, and rows with no heavy sections (append /
        # outbox / slot / snapshot-need) sync from the plan's words and
        # hand a (node, term, vote, commit, entries) LANE tuple to the
        # batched _persist_lane_rows — no per-row get_update object
        # walk, no per-row Update/State/UpdateCommit construction
        # (ISSUE 13; hostplane.UpdateLanes).  Heavy rows keep the
        # classic full-body merge.
        gs_live = np.asarray([g for _, g, _ in live], np.int64)
        vals_for_plan = (
            vals_np if vals_np is not None
            else np.zeros((1, N_VALS), np.int64)
        )
        ub_l = w_term = w_vote = w_com = w_lead = w_role = None
        so_mask = None
        if len(gs_live):
            uplan = _plan_lane_words(
                self._ulanes, self._base, gs_live, sum_rows,
                vals_for_plan, self.capacity, mirror=self._mirror,
            )
            ub_l = uplan.ubits.tolist()
            w_term = uplan.words[_R_TERM].tolist()
            w_vote = uplan.words[_R_VOTE].tolist()
            w_com = uplan.words[_R_COMMIT].tolist()
            w_lead = uplan.words[_R_LEADER].tolist()
            w_role = uplan.words[_R_ROLE].tolist()
            # rows eligible for the array-batched persist (hard-state
            # effect, no heavy sections, slot-backed store) classify
            # vectorized; the loop only CLEARS exceptions (residue
            # fallbacks).  Their persist is three scatters per LogDB
            # (_persist_lane_batches); commit rows additionally hand
            # (node, entries) to the post-save apply leg.
            so_mask = (uplan.ubits & (U_STATE | U_COMMIT)) != 0
            if so_mask.any():
                hv = np.zeros((self.capacity,), bool)
                if buf_rows:
                    hv[buf_rows] = True
                if slot_rows:
                    hv[slot_rows] = True
                if need_rows:
                    hv[need_rows] = True
                so_mask &= ~hv[gs_live]
                so_mask &= (flags[gs_live] & _F_APPEND) == 0
                so_mask &= self._lane_dbi[gs_live] >= 0
            so_l = so_mask.tolist()
        lane_rows = self._lane_pending
        lane_apply: List[Tuple] = []
        sum_get = sum_at.get
        # (g, p, lane-or-None, pid, ss_index) — see _send_snapshots
        snapshot_sends: List[Tuple[int, int, Optional[int], int, int]] = []
        for j, (node, g, si) in enumerate(live):
            r = node.peer.raft
            # PRE-launch clock for lease window starts: stamping after
            # bookkeeping would date a window up to half an election
            # window late (the fused tick count) and overstate the
            # lease by the same amount — the colocated _lease_pass
            # follows the same pre-bookkeeping contract
            now0 = node.tick_count
            # tick bookkeeping, inlined (mirrors Node.step_with_inputs
            # / _tick_bookkeeping: clock lockstep + hint-gated GC)
            t = si.ticks + si.gc_ticks
            if t:
                tc = now0 + t
                node.tick_count = tc
                r.tick_count += t
                if tc >= node.pending_deadline_hint[0]:
                    gc_tables(
                        node.pending_tables, node.pending_deadline_hint,
                        tc,
                    )
            k = sum_get(g, -1)
            if k < 0:
                # no flags, no slots: the row only ticked — but an
                # armed leader's window mirror still advances, and the
                # quorum-active flag may anchor the lease (ROADMAP 4b)
                a = self._lease.row_step(
                    g, tick_fed.get(g, 0), now0, int(flags[g])
                )
                if a >= 0:
                    r.anchor_quorum_evidence(a)
                continue
            ub = ub_l[j]
            term = w_term[j]
            vote = w_vote[j]
            committed = w_com[j]
            leader = w_lead[j]
            role = w_role[j]
            # lease lanes track role transitions observed at merge: an
            # on-device election win arms a FRESH window model
            # (election_tick reset to 0 by the kernel's _reset), any
            # other transition disarms.  U_ROLE is exactly the old
            # `role != mirror role` probe: lanes and mirror both seed
            # at upload and sync at every merge.
            if ub & U_ROLE:
                if role == ROLE_LEADER_I and r.check_quorum:
                    self._lease.arm(g, r.election_timeout, 0)
                else:
                    self._lease.disarm(g)
            a = self._lease.row_step(
                g, tick_fed.get(g, 0), now0, int(flags[g])
            )
            log = r.log
            appended = bool(flags[g] & _F_APPEND)
            if not (
                appended or g in buf_at or g in slot_at or g in need_at
            ):
                # ---- LANE row: no heavy sections ---------------------
                # NOTE: this residue-probe + U_*-application block is
                # intentionally OPEN-CODED in three places — here,
                # colocated._lane_commit_pass and the bench's
                # _lane_stage twin — because a shared per-row helper
                # (call/closure per row) costs exactly the altitude
                # this loop exists to remove.  Any semantic change
                # MUST land in all three; the bench's twin-population
                # raft-word + persisted-state equality is the
                # application-level drift detector.
                im = log.inmem
                if (
                    r.msgs or r.ready_to_reads or r.dropped_entries
                    or r.dropped_read_indexes or im.snapshot.index
                    or im.saved_to + 1 - im.marker < len(im.entries)
                ):
                    # scalar-side residue (a resident-clean row should
                    # never accumulate any — defense in depth): only
                    # the classic get_update walk drains it
                    r.term, r.vote, r.leader_id = term, vote, leader
                    r.role = _ROLE_OF[role]
                    if a >= 0:
                        r.anchor_quorum_evidence(a)
                    if committed > log.committed:
                        log.commit_to(committed)
                    if (
                        role != ROLE_LEADER_I
                        and node.device_reads.has_pending()
                    ):
                        node.drop_device_reads()
                    u = node.peer.get_update(
                        last_applied=node.sm.last_applied
                    )
                    node.dispatch_dropped(u)
                    updates.append((node, u))
                    node._check_leader_change()
                    so_mask[j] = False  # residue rows left the array path
                    continue
                if ub & U_STATE:
                    r.term = term
                    r.vote = vote
                if ub & U_LEADER:
                    r.leader_id = leader
                if ub & U_ROLE:
                    r.role = _ROLE_OF[role]
                if a >= 0:
                    r.anchor_quorum_evidence(a)  # post-sync role
                if ub & U_LOST_LEAD and node.device_reads.has_pending():
                    # leadership lost: confirmations will never arrive.
                    # U_LOST_LEAD is exact for lane rows: device reads
                    # only register off merged outbox messages (a heavy
                    # row by definition), so any pending read predates
                    # this sync — if the row is no longer leader, the
                    # losing transition is THIS generation's lane diff
                    # (docs/PARITY.md "Update-lane contract").
                    node.drop_device_reads()
                if ub & U_COMMIT:
                    log.commit_to(committed)
                    ce = log.entries_to_apply()
                    if so_l[j]:
                        # persist rides the array batch; entries hand
                        # off after that batch's save proves durable
                        lane_apply.append((j, node, ce))
                    else:
                        lane_rows.append(
                            (node, term, vote, committed, ce)
                        )
                elif ub & U_STATE and not so_l[j]:
                    # hard-state move without a slot-backed store:
                    # tuple form through save_state_lanes
                    lane_rows.append((node, term, vote, committed, None))
                if ub & U_LEADER:
                    node._check_leader_change()
                continue
            # ---- heavy row: the classic full-body merge --------------
            sv = vals_np[k]
            base = int(self._base[g])
            last = int(sv[_R_LAST]) + base
            # 1. append reconstruction
            if appended:
                self._merge_appends(
                    r,
                    g,
                    int(sv[_R_APPEND_LO]) + base,
                    last,
                    staging.get(g, {}),
                    slot_at.get(g, -1),
                    slot_base,
                    slot_term,
                    ent_drop,
                    ring_t[ring_at[g]],
                    ring_c[ring_at[g]],
                    base=base,
                )
            # 2. protocol scalar sync
            r.term, r.vote, r.leader_id = term, vote, leader
            r.role = _ROLE_OF[role]
            if a >= 0:
                r.anchor_quorum_evidence(a)  # post-sync: role is fresh
            if committed > r.log.committed:
                r.log.commit_to(committed)
            if (
                role != ROLE_LEADER_I
                and node.device_reads.has_pending()
            ):
                # leadership lost: confirmations will never arrive
                node.drop_device_reads()
            # 3. outbox -> messages with payload attachment
            if g in buf_at:
                self._attach_messages(
                    r,
                    node,
                    buf_np[buf_at[g]],
                    int(sv[_R_COUNT]),
                    staging.get(g, {}),
                    base=base,
                )
            # 4. dropped proposal slots / cc-gated entries -> futures
            if g in slot_at:
                sb = slot_base[slot_at[g]]
                drop = ent_drop[slot_at[g]]
                for slot, ents in staging.get(g, {}).items():
                    if sb[slot] == SLOT_DROPPED:
                        r.dropped_entries.extend(ents)
                    elif sb[slot] >= 0:
                        r.dropped_entries.extend(
                            e
                            for j2, e in enumerate(ents)
                            if drop[slot, j2]
                        )
            # 5. peers needing a snapshot stream
            if g in need_at:
                self._send_snapshots(
                    r, g, need_np[need_at[g]], snapshot_sends
                )
            u = node.peer.get_update(last_applied=node.sm.last_applied)
            node.dispatch_dropped(u)
            updates.append((node, u))
            node._check_leader_change()

        if so_mask is not None and so_mask.any():
            # array-batched persist: group the survivors by LogDB
            # through the db-index lane; node lists materialize lazily
            # (only on save failure / active quarantine); commit rows'
            # apply handoffs ride with their db's batch so entries
            # never reach the apply queue before their save lands
            js = np.nonzero(so_mask)[0]
            gs_so = gs_live[js]
            dbi = self._lane_dbi[gs_so]
            slots = self._lane_slot[gs_so]
            w = uplan.words
            app_by_db: Dict[int, List] = {}
            if lane_apply:
                dbi_all = self._lane_dbi
                for j, node, ce in lane_apply:
                    app_by_db.setdefault(
                        int(dbi_all[gs_live[j]]), []
                    ).append((node, ce))
            for d in np.unique(dbi).tolist():
                m = dbi == d
                jd = js[m]
                self._lane_pending_arr.append((
                    self._lane_dbs[d], slots[m], w[_R_TERM][jd],
                    w[_R_VOTE][jd], w[_R_COMMIT][jd], live, jd,
                    app_by_db.get(d, ()),
                ))

        lanes = [t for t in snapshot_sends if t[2] is not None]
        if lanes:
            self._state = _set_remote_snapshot(
                self._state,
                self._put(jnp.asarray(_pad_idx([t[0] for t in lanes]))),
                self._put(jnp.asarray(_pad_idx([t[1] for t in lanes]))),
                self._put(jnp.asarray(_pad_idx([t[2] for t in lanes]))),
            )
        below = [t for t in snapshot_sends if t[2] is None]
        if below:
            # see _send_snapshots: these rows continue on the scalar path
            gs = sorted(
                {t[0] for t in below if self._meta.get(t[0]) is not None}
            )
            for g in gs:
                self._meta[g].dirty = True
            self._materialize_rows(gs)
            # mark the scalar remotes AFTER materialize (which overwrote
            # them from the device): the SNAPSHOT state both suppresses
            # probe spam and keeps the planner off the device path
            for g, p, _, pid, ss_index in below:
                meta = self._meta.get(g)
                if meta is None or meta.node.stopped:
                    continue
                rm = meta.node.peer.raft.get_remote(pid)
                if rm is not None:
                    rm.become_snapshot(ss_index)
        return updates

    # -- append reconstruction -----------------------------------------
    def _merge_appends(
        self,
        r: Raft,
        g: int,
        lo: int,
        last: int,
        stage: Dict[int, List[Entry]],
        slot_idx: int,
        slot_base,
        slot_term,
        ent_drop,
        ring_term_row,
        ring_cc_row,
        fallback=None,
        barrier: Optional[Tuple[int, int]] = None,
        base: int = 0,
    ) -> List[Entry]:
        # ``slot_idx`` is the row's position in the gathered slot
        # sections (-1 = the row carried no proposal slots) — an
        # index-array lookup the callers batch-compute, replacing the
        # old per-row `g in slot_at` dict probes (hostplane refactor)
        W = self.W
        # candidates[idx] = (slot_order, Entry, term); later slots win
        cand: Dict[int, List[Tuple[int, Entry, int]]] = {}
        sb = slot_base[slot_idx] if slot_idx >= 0 else None
        stm = slot_term[slot_idx] if slot_idx >= 0 else None
        drop = ent_drop[slot_idx] if slot_idx >= 0 else None
        for slot in sorted(stage):
            ents = stage[slot]
            if sb is not None and sb[slot] >= 0:
                # a PROPOSE slot accepted at pre-append index sb[slot]
                # (device-shifted; sentinels < 0 never shift)
                pos = int(sb[slot]) + base
                for j, e in enumerate(ents):
                    if drop is not None and drop[slot, j]:
                        continue
                    pos += 1
                    cand.setdefault(pos, []).append(
                        (slot, e, int(stm[slot]))
                    )
            elif ents and ents[0].index > 0:
                # REPLICATE payload: wire entries carry index+term
                for e in ents:
                    cand.setdefault(e.index, []).append((slot, e, e.term))
        stamped: List[Entry] = []
        for idx in range(lo, last + 1):
            rt = int(ring_term_row[idx & (W - 1)])
            pick: Optional[Tuple[int, Entry, int]] = None
            for c in cand.get(idx, ()):
                if c[2] == rt and (pick is None or c[0] >= pick[0]):
                    pick = c
            if pick is None and fallback is not None:
                # device-routed append: the payload never crossed this
                # host's wire — reconstruct from the colocated cache
                fe = fallback(r, idx, rt)
                if fe is not None:
                    pick = (-1, fe, rt)
            if pick is None:
                # become-leader noop barrier (the only unstaged append)
                if int(ring_cc_row[idx & (W - 1)]) != 0:
                    raise RuntimeError(
                        f"[{r.shard_id}:{r.replica_id}] unstaged config "
                        f"change at index {idx}"
                    )
                if fallback is not None and (
                    barrier is None
                    or idx != barrier[0]
                    or rt != barrier[1]
                ):
                    # routed-append mode: the ONLY legitimately unstaged
                    # append is the barrier this row self-appended this
                    # step (kernel-reported, valid even if the row then
                    # stepped down in the same step).  Anything else came
                    # over the device route and its payload is gone —
                    # stamping an empty noop would silently diverge the
                    # SM, so fail-stop (same policy as the last_index
                    # divergence halt).
                    raise RuntimeError(
                        f"[{r.shard_id}:{r.replica_id}] unreconstructible "
                        f"routed append at index {idx} (term {rt})"
                    )
                stamped.append(
                    Entry(term=rt, index=idx, type=EntryType.APPLICATION)
                )
            else:
                e = pick[1]
                stamped.append(
                    Entry(
                        term=rt,
                        index=idx,
                        type=e.type,
                        key=e.key,
                        client_id=e.client_id,
                        series_id=e.series_id,
                        responded_to=e.responded_to,
                        cmd=e.cmd,
                    )
                )
        r.log.inmem.merge(stamped)
        return stamped

    # -- outbox decode + payload attachment ----------------------------
    def _attach_messages(
        self,
        r: Raft,
        node,
        buf_row: np.ndarray,
        count: int,
        stage: Dict[int, List[Entry]],
        delivered_row: Optional[np.ndarray] = None,
        base: int = 0,
    ) -> None:
        shim = {"count": np.array([count]), "buf": buf_row[None]}
        for k, (msg, n_ent, src_slot) in enumerate(
            S.decode_out_row(shim, 0, r.shard_id, r.replica_id)
        ):
            if delivered_row is not None and delivered_row[k]:
                continue  # already scattered into a peer row on device
            msg = _shift_msg_indexes(msg, base)
            if (
                msg.type == MessageType.READ_INDEX_RESP
                and msg.to == r.replica_id
            ):
                # synthetic host-coordination message from the kernel's
                # ReadIndex hot path — never hits the wire
                node.handle_device_read_resp(msg)
                continue
            if msg.type == MessageType.REPLICATE and n_ent > 0:
                if msg.log_term == 0 and msg.log_index > 0:
                    # below-ring send (see kernel._send_replicate): the
                    # device couldn't resolve the prev term; stamp it
                    # from the authoritative log
                    try:
                        msg = dataclasses.replace(
                            msg, log_term=r.log.term(msg.log_index)
                        )
                    except Exception:  # noqa: BLE001
                        # prev compacted on the host: nothing below the
                        # ring is sendable and the device's next_idx
                        # already advanced — demote the row so the
                        # SCALAR path (full log + its own snapshot
                        # machinery) drives this follower; silently
                        # dropping starves it (review finding)
                        self._demote_row_to_host(node)
                        continue
                ents = self._replicate_payload(r, msg, n_ent)
                if ents is None:
                    continue  # stale vs final log; dropping is raft-safe
                msg = dataclasses.replace(msg, entries=tuple(ents))
            elif msg.type == MessageType.PROPOSE and src_slot >= 0:
                msg = dataclasses.replace(
                    msg, entries=tuple(stage.get(src_slot, ()))
                )
            r.msgs.append(msg)

    def _replicate_payload(
        self, r: Raft, msg: Message, n_ent: int
    ) -> Optional[List[Entry]]:
        from ..raft.log import LogCompactedError, LogUnavailableError

        try:
            if msg.log_index > 0 and r.log.term(msg.log_index) != msg.log_term:
                return None
            ents = r.log._get_entries(
                msg.log_index + 1, msg.log_index + 1 + n_ent, 2**62
            )
        except (LogCompactedError, LogUnavailableError):
            return None
        if len(ents) != n_ent:
            return None
        if msg.to in r.witnesses:
            ents = [r._to_witness_entry(e) for e in ents]
        return ents

    # -- snapshot streaming kick-off -----------------------------------
    def _send_snapshots(
        self,
        r: Raft,
        g: int,
        need_row: np.ndarray,
        snapshot_sends: List[Tuple[int, int, Optional[int], int, int]],
    ) -> None:
        # snapshot_sends entries are (g, p, lane, pid, ss_index); lane is
        # None when the durable snapshot sits below the row's base (the
        # host-excursion path)
        peer_ids = np.asarray(self._state.peer_id[g])  # small row fetch
        ss = r.log.logdb.snapshot()
        for p in range(self.P):
            if not need_row[p]:
                continue
            pid = int(peer_ids[p])
            if pid == 0 or ss.is_empty():
                continue  # remote stays WAIT; retried via heartbeat resp
            send = ss
            if pid in r.witnesses:
                send = Snapshot(
                    index=ss.index,
                    term=ss.term,
                    membership=ss.membership,
                    dummy=True,
                    witness=True,
                    shard_id=r.shard_id,
                )
            r.msgs.append(
                Message(
                    type=MessageType.INSTALL_SNAPSHOT,
                    to=pid,
                    from_=r.replica_id,
                    shard_id=r.shard_id,
                    term=r.term,
                    snapshot=send,
                )
            )
            lane = ss.index - int(self._base[g])
            if lane <= 0:
                # the durable snapshot sits below this row's base (a
                # compacted leader whose retained window outruns the
                # snapshot): the int32 lane can't represent it, and a
                # zero/negative lane would corrupt the remote's snapshot
                # tracking.  The INSTALL message above still goes out
                # (absolute, host wire); the ROW takes a host excursion
                # and the scalar remote is marked SNAPSHOT after the
                # materialize (below) so the planner keeps the row off
                # the device until the install resolves — otherwise
                # every re-upload would re-fire need_snapshot and
                # stream a duplicate full snapshot.
                snapshot_sends.append((g, p, None, pid, ss.index))
                continue
            # the device's snap_index lane is rebased like every index
            snapshot_sends.append((g, p, lane, pid, ss.index))


def vector_step_engine_factory(**kw):
    """ExpertConfig.step_engine_factory hook:

        expert.step_engine_factory = vector_step_engine_factory(capacity=2048)
    """

    def factory(nodehost):
        return VectorStepEngine(nodehost.logdb, **kw)

    return factory
