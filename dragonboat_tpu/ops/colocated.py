"""Colocated-cluster mode: one device state shared by several NodeHosts.

The reference's step workers hand every outbound message to the
transport even when the peer replica lives in the same process
(reference: engine.go stepWorkerMain -> transport.Send [U]).  The
``VectorStepEngine`` inherits that shape: each message round-trips
device -> host decode -> transport -> host encode -> device.  When a
whole cluster is colocated on one chip (multiple NodeHosts in one
process — the standard test/bench topology, and the production topology
for BASELINE configs 2-4), that detour is the scaling bottleneck.

``ColocatedEngineGroup`` is the product configuration that removes it:

    group = ColocatedEngineGroup(capacity=64, P=5, budget=2)
    for each NodeHost config:
        cfg.expert.step_engine_factory = group.factory

Every member NodeHost's step engine becomes a facade over ONE shared
``ColocatedVectorEngine``: all replicas live in one device state, and
``ops/route.py`` scatters each step's outbox straight into co-located
peers' inbox regions — elections, replication and commit advance run
device-side, exactly like the consensus benchmark, while off-device
peers (and host-only message classes) fall back to the per-host
transport unchanged (route's ``delivered`` mask tells the host which
messages it still owns).

Payload reconstruction across replicas: device-routed REPLICATE carries
only (term, is-config-change) per entry — the cmd bytes never leave the
sending host.  Colocation makes the fix cheap: every stamped append is
published to a shared per-shard entry cache (bounded by the ring
lifetime), and a receiving replica's merge pulls payloads from the
cache by (index, term).  A miss on a non-leader row fail-stops the
replica (see ``VectorStepEngine._merge_appends``) — silent empty
entries would diverge the SM.

Concurrency: the colocated step holds the core lock end-to-end.  Member
NodeHosts keep their own ExecEngines, apply workers, LogDBs and
transports; only the step stage is fused.  A launch triggered by any
member steps EVERY resident row (routed traffic may target any of
them), and updates are persisted to each node's own LogDB before its
messages are dispatched (the reference's save -> send -> apply order).
"""
from __future__ import annotations

import dataclasses
import functools
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..analysis import jitcheck
from ..engine.execengine import IStepEngine
from . import hostplane
from ..logger import get_logger
from ..node import StepInputs
from ..pb import Entry
from ..raft.raft import RaftRole
from ..request import gc_tables
from . import kernel as K
from . import sync as S
from .engine import (
    VectorStepEngine,
    _shift_msg_indexes,
    _F_ANY_LIVE,
    _F_APPEND,
    _F_COUNT,
    _F_ESC,
    _F_NEED_SS,
    _F_PEERS_BEHIND,
    _R_APPEND_LO,
    _R_BARRIER_IDX,
    _R_BARRIER_TERM,
    _R_COMMIT,
    _R_COUNT,
    _R_LEADER,
    _R_ROLE,
    _R_TERM,
    _R_VOTE,
    _R_LAST,
    _ROLE_OF,
    _bucket,
    _place_rows,
    _pos_map,
    _build_idx4,
    _detail_width,
    _fetch_detail_vals,
    _gather_detail,
    _gather_detail_vals,
    _gather_vals,
    _split_detail,
    N_FIELDS_BUF,
    N_VALS,
    _summarize_flags,
    _tick_bookkeeping,
    _pad_idx,
    _set_remote_snapshot,
)
from .types import (
    ROLE_LEADER as _ROLE_LEADER_I,
    U_COMMIT,
    U_LEADER,
    U_LOST_LEAD,
    U_ROLE,
    U_STATE,
)
from .route import build_route_tables, route
from .types import (
    APPEND_LO_NONE,
    I32,
    MT_TICK,
    SLOT_UNUSED as SLOT_UNUSED_I,
    Inbox,
    make_inbox,
    make_state,
)
from ..metrics import global_registry as _metrics

_log = get_logger("engine")

import os as _os

_DEBUG_LAUNCH = _os.environ.get("COLOC_DEBUG_LAUNCH", "") == "1"

# -- double-buffered generations (the launch pipeline) -----------------
# DRAGONBOAT_TPU_PIPELINE_DEPTH: how many generations may be in flight
# at once.  2 (the default) double-buffers: while generation N's blob
# readback is in flight, generation N+1 assembles, uploads and
# dispatches — the donated-buffer program chain permits it, and on the
# remote-device tunnel (every sync ~100-214 ms of round-trip latency,
# docs/BENCH_NOTES_r05.md) the readback overlaps the next launch's
# host work so sync count stops being the unit of product-path
# latency.  1 = the serial r5/r6 loop (dispatch, sync, merge, repeat).
_PIPE_DEPTH_DEFAULT = int(
    _os.environ.get("DRAGONBOAT_TPU_PIPELINE_DEPTH", "2") or 2
)
# DRAGONBOAT_TPU_SYNC_FLOOR_MS: simulated-tunnel sync latency shim — a
# readback's data is not considered landed until <floor> ms after the
# D2H copy was REQUESTED (copy_to_host_async).  Models the r5 tunnel
# finding on CPU: the floor is round-trip latency, paid from request to
# data regardless of size, and requests issued early (at dispatch)
# collect late for free — which is exactly what the pipeline exploits
# and what `bench.py phase_pipeline` measures without hardware.
_SYNC_FLOOR_MS_DEFAULT = float(
    _os.environ.get("DRAGONBOAT_TPU_SYNC_FLOOR_MS", "0") or 0
)
# DRAGONBOAT_TPU_FUSED_ROUNDS: how many consecutive consensus rounds a
# routable generation chains device-side before its ONE readback (the
# fused commit wave, ISSUE 15).  3 (the default) is one full
# propose -> replicate/ack -> commit/deliver sequence: a quiet-path
# proposal commits in one launch + one readback instead of three of
# each, breaking the ~0.52x 3-round probe asymptote the double-buffered
# pipeline alone is bounded by (docs/BENCH_NOTES_r07.md).  1 disables
# fusing (the PR 11 single-round launch loop, bit for bit).
_FUSED_ROUNDS_DEFAULT = int(
    _os.environ.get("DRAGONBOAT_TPU_FUSED_ROUNDS", "3") or 3
)

# fast-lane invalidation margin: re-validate a row's int32 headroom via
# the full plan well before the hard 2^31 ceiling (margin >> M*E and
# any per-launch term burst)
_LIM_SOFT = 2**31 - 2**24


# per-launch [G, 4] host-upload lane assignments: every per-launch [G]
# host input rides ONE device_put (each H2D put costs ~10-20 ms of
# link latency; four separate puts were a fifth of the launch budget)
_C_ALIVE, _C_BATCH, _C_PROP, _C_TICKS = range(4)


@jax.jit
def _assemble_inbox(host: Inbox, pending: Inbox, alive: jnp.ndarray) -> Inbox:
    """Concatenate the ROUTED regions first, then the host-encoded
    slots, zeroing rows that are not device-authoritative (dirty /
    detached — a stale device row receiving traffic could double-vote).

    Routed-first is the scalar replay order (received messages before
    proposals/reads/ticks): routed traffic IS received messages, and
    the host region ends with the fused tick slot.  With the old
    host-first order a candidate's tick slot could re-fire its election
    BEFORE counting the vote responses already sitting in its routed
    region — with multi-tick fusion (+timeout//2 per launch) that
    re-campaign loop stalled whole-cluster elections."""

    def cat(h, p):
        x = jnp.concatenate([p, h], axis=1)  # pending | host
        m = alive.reshape((-1,) + (1,) * (x.ndim - 1))
        return jnp.where(m, x, 0)

    return Inbox(*(cat(getattr(host, f), getattr(pending, f))
                   for f in Inbox._fields))


@functools.partial(jax.jit, static_argnames=("out_capacity",),
                   donate_argnums=(1, 2))
def _assemble_and_step(state, host: Inbox, pending: Inbox, combo,
                       *, out_capacity: int):
    """Fused inbox assembly + kernel step in ONE program, with the host
    and pending inboxes DONATED: the remote TPU service frees device
    garbage lazily and a fast launch cadence at 65k-row geometry
    out-allocated it (r5 finding — RESOURCE_EXHAUSTED mid-election);
    fusing avoids materializing the assembled inbox as a host-held
    buffer and donation lets the runtime reuse the inbox allocations
    instead of growing the heap every generation.  ``combo`` is the
    [G, 4] fused host-upload (see _C_*); the alive lane masks rows."""
    full = _assemble_inbox(host, pending, combo[:, _C_ALIVE] != 0)
    return K.step(state, full, out_capacity=out_capacity)


@functools.partial(jax.jit, static_argnames=("PB", "E", "budget"),
                   donate_argnums=(1,))
def _route_step(old_state, new_state, out, dest, rank, combo,
                *, PB: int, E: int, budget: int):
    """Post-launch tail: discard escalated rows' effects, route the
    outboxes into the next launch's pending regions (width P*budget,
    base=0 — host slots are prepended at the next assemble), and compute
    the per-row flag word + bit-packed delivered mask so the host reads
    back O(1)-width arrays instead of the full summary/delivered
    matrices (multi-MB per launch — tens of seconds on the TPU tunnel)."""
    esc = out.escalate != 0

    def sel(a, b):
        m = (~esc).reshape((-1,) + (1,) * (a.ndim - 1))
        return jnp.where(m, b, a)

    merged = jax.tree.map(sel, old_state, new_state)
    regions, stats, delivered = route(
        merged, out, dest, rank,
        M=PB, E=E, budget=budget, base=0,
        suppress=esc, dest_alive=combo[:, _C_ALIVE] != 0,
    )
    flags = _summarize_flags(old_state, merged, out)
    # colocated override of _F_COUNT: only rows with UNdelivered outbox
    # messages need host decode — a leader whose heartbeats/votes all
    # scattered into peer rows has nothing host-visible, and during an
    # election storm that is nearly every row (the buf gather would
    # otherwise be a ~44 MB readback at 65k rows)
    G, O = delivered.shape
    valid = jnp.arange(O)[None, :] < out.count[:, None]
    undeliv = jnp.any(valid & ~delivered, axis=1)
    flags = (flags & ~jnp.int32(_F_COUNT)) | jnp.where(
        undeliv, _F_COUNT, 0
    ).astype(I32)
    nwords = (O + 31) // 32
    shift = jnp.arange(O, dtype=jnp.uint32) % 32
    word = jnp.arange(O) // 32
    bits = jnp.where(delivered, jnp.uint32(1) << shift, jnp.uint32(0))
    packed = jnp.zeros((G, nwords), jnp.uint32)
    for w in range(nwords):  # nwords is static and tiny (O<=64 -> <=2)
        packed = packed.at[:, w].set(
            jnp.sum(jnp.where(word[None, :] == w, bits, 0), axis=1,
                    dtype=jnp.uint32)
        )
    return merged, regions, jnp.stack(list(stats)), packed, flags


# deterministic select-capacity ladder (clamped to G at use): free-form
# adaptive capacities keyed a fresh XLA program per distinct tuple and
# the mid-run compiles froze the launch pipeline for tens of seconds on
# the remote link (r5 finding: phase C commits arrived ~25 s late).
# Three fixed tiers are warmed at startup, live in the persistent
# cache, and any count beyond the big tier falls back to the exact
# host-side gather for that launch.
_SEL_TIERS = (
    {"b": 16, "sl": 64, "n": 8, "a": 64, "s": 1024},
    {"b": 64, "sl": 1024, "n": 32, "a": 1024, "s": 16384},
    {"b": 256, "sl": 4096, "n": 64, "a": 4096, "s": 65536},
    # storm tier for scale geometries (mass-start elections append the
    # become-leader barrier on tens of thousands of rows per launch);
    # ring rows are 2W ints and vals rows 10, so even 32k/256k rows
    # transfer in ~100s of ms — the exact bytes the r5 two-sync path
    # moved for the same storms, minus its extra round trips
    {"b": 1024, "sl": 8192, "n": 256, "a": 32768, "s": 1 << 18},
)


@functools.partial(
    jax.jit,
    static_argnames=("CAP_B", "CAP_SL", "CAP_N", "CAP_A", "CAP_S",
                     "HOST_OFF"),
)
def _select_and_blob(merged, out, stats, packed, flags, combo,
                     *, CAP_B: int, CAP_SL: int, CAP_N: int, CAP_A: int,
                     CAP_S: int, HOST_OFF: int):
    """Device-side row selection + detail/vals gather + split-blob
    packing — the launch's one commit-proving readback, as a (head,
    detail) pair of int32 vectors whose D2H copies ride in parallel.

    Every sync round trip on a remote-device link costs ~100 ms of
    latency regardless of size (measured r5); the r5 launch paid ~5
    (flags, stats, delivered, detail, vals).  This program mirrors the
    host's row-set computation (live/buf/append/need/slot/sum) from the
    flag word, compacts each set with a stable argsort (selected rows
    first, ascending), gathers each section for its own capacity, and
    packs everything the host reads per launch into TWO int32 vectors:

    * the HEAD carries the flags/delivered prefix, route stats, section
      counts, the selected row ids and the per-row VALUES block — i.e.
      everything that PROVES a proposal's commit (committed/term/role
      per row).  The pipeline completes futures from this, the
      earliest commit-proving sync, without waiting for the detail
      payload to land and merge.
    * the DETAIL carries the heavy sections (outbox bytes, slot
      bookkeeping, need rows, ring windows) the append/message merge
      needs.  Both copies are requested together at dispatch, so on a
      latency-floor link they arrive for one round trip — the head is
      simply parsed (and acted on) first, and a generation whose
      sections are all empty never reads the detail at all.

    Counts above the static capacities are reported so the host can
    fall back to an exact multi-sync gather (rare; it then raises its
    capacity floors).

    Capacities are PER SECTION because their per-row widths differ
    wildly: one buf row is O*N_FIELDS ints (352 at O=32) while a slot
    row is M*(2+E) and a vals row is 10 — a shared capacity padded the
    heavy buf section to the proposal-row cardinality (~4 MB/launch at
    1k shards, the whole launch budget after the sync collapse).

    The slot sections ship only the HOST-region columns (HOST_OFF =
    P*budget onward): proposals ride host slots exclusively — forwarded
    PROPOSE is never device-routed — so the routed-region columns are
    always SLOT_UNUSED/0 and the host re-pads them for free.

    Head layout (all int32):
      [0:G]               flags
      [G:G+G*nw]          delivered bits (bitcast u32)
      [+6]                route stats
      [+5]                counts: n_buf, n_slot, n_need, n_append, n_sum
      [+CAP_B]            row ids: buf
      [+CAP_SL]           row ids: slot
      [+CAP_N]            row ids: need
      [+CAP_A]            row ids: append
      [+CAP_S]            row ids: sum
      [+CAP_S*N_VALS]     values
    Detail layout (all int32):
      [0:CAP_B*O*NF]      out.buf rows
      [+CAP_SL*M]         slot_base (host cols) | [+CAP_SL*M] slot_term
      [+CAP_SL*M*E]       ent_drop (host cols)
      [+CAP_N*P]          need rows
      [+CAP_A*W]          ring_term | [+CAP_A*W] ring_cc
    """
    G = flags.shape[0]
    alive = combo[:, _C_ALIVE] != 0
    batch_mask = combo[:, _C_BATCH] != 0
    prop_mask = combo[:, _C_PROP] != 0
    esc = (flags & _F_ESC) != 0
    anylive = (flags & _F_ANY_LIVE) != 0
    # the host's live set: batch rows + resident alive rows with
    # any-live flags, minus escalations
    live = (batch_mask | (alive & anylive)) & ~esc
    buf_sel = live & ((flags & _F_COUNT) != 0)
    append_sel = live & ((flags & _F_APPEND) != 0)
    need_sel = live & ((flags & _F_NEED_SS) != 0)
    slot_sel = prop_mask & ~esc
    sum_sel = live & (anylive | slot_sel)

    def pick(sel, cap):
        order = jnp.argsort(jnp.where(sel, 0, 1), stable=True)
        return (
            jax.lax.slice_in_dim(order, 0, cap).astype(I32),
            jnp.sum(sel, dtype=I32),
        )

    rows_buf, n_buf = pick(buf_sel, CAP_B)
    rows_slot, n_slot = pick(slot_sel, CAP_SL)
    rows_need, n_need = pick(need_sel, CAP_N)
    rows_append, n_append = pick(append_sel, CAP_A)
    rows_sum, n_sum = pick(sum_sel, CAP_S)
    vals = _gather_vals(merged, out, rows_sum)      # [CAP_S, N_VALS]
    head = jnp.concatenate([
        flags,
        jax.lax.bitcast_convert_type(packed, jnp.int32).reshape(-1),
        stats.astype(I32),
        jnp.stack([n_buf, n_slot, n_need, n_append, n_sum]),
        rows_buf,
        rows_slot,
        rows_need,
        rows_append,
        rows_sum,
        vals.reshape(-1),
    ])
    detail = jnp.concatenate([
        out.buf[rows_buf].reshape(-1),
        out.slot_base[rows_slot][:, HOST_OFF:].reshape(-1),
        out.slot_term[rows_slot][:, HOST_OFF:].reshape(-1),
        out.ent_drop[rows_slot][:, HOST_OFF:].reshape(-1),
        out.need_snapshot[rows_need].reshape(-1),
        merged.ring_term[rows_append].reshape(-1),
        merged.ring_cc[rows_append].reshape(-1),
    ])
    return head, detail


@jax.jit
def _zero_inbox_rows(inbox: Inbox, mask) -> Inbox:
    """Zero the inbox rows where ``mask`` ([G] bool) — mask-select, not
    a data-dependent scatter (serial on TPU; see _scatter_rows)."""

    def z(a):
        m = mask.reshape((-1,) + (1,) * (a.ndim - 1))
        return jnp.where(m, 0, a)

    return Inbox(*(z(getattr(inbox, f)) for f in Inbox._fields))


@functools.partial(jax.jit, static_argnames=("M", "E"))
def _host_inbox_from_ticks(combo, *, M: int, E: int) -> Inbox:
    """Build the host inbox region ON DEVICE from a [G] fused-tick-count
    vector.  At scale, nearly every row's host region is exactly one
    count-carrying LOCAL_TICK slot — uploading the dense [G, M(, E)]
    inbox arrays cost ~28 MB per launch through the TPU tunnel (~100 s,
    the whole launch budget); the tick vector is 256 KB.  Rows with real
    host slots (wire messages, proposals, reads, tick-with-read-hint)
    are scattered over this base by _scatter_inbox_rows."""
    tick_counts = combo[:, _C_TICKS]
    G = tick_counts.shape[0]
    z = jnp.zeros((G, M), I32)
    ze = jnp.zeros((G, M, E), I32)
    has = tick_counts > 0
    return Inbox(
        mtype=z.at[:, 0].set(jnp.where(has, MT_TICK, 0)),
        from_id=z,
        term=z,
        log_term=z,
        log_index=z.at[:, 0].set(tick_counts),
        commit=z,
        reject=z,
        hint=z,
        hint_high=z,
        n_entries=z,
        ent_term=ze,
        ent_cc=ze,
    )


@jax.jit
def _scatter_inbox_rows(host: Inbox, pos, sub: Inbox) -> Inbox:
    """Place sub's rows at pos (a [G] position map, -1 = keep) — the
    shared pos-map gather-select (see engine._place_rows)."""
    return Inbox(*(
        _place_rows(getattr(host, f), getattr(sub, f), pos)
        for f in Inbox._fields
    ))


class _InFlightGen:
    """One dispatched-but-unmerged generation of the launch pipeline.

    Holds every host-side fact the deferred merge tail needs (the
    generation's OWN inputs — the parity oracle must run against these,
    not the interleaved stream) plus the device handles the exact
    two-sync fallback gather reads.  ``merged``/``out`` pin the
    generation's buffers alive until its merge runs; with depth 2 that
    is the ISSUE's "two in-flight state handles".

    A FUSED generation (``rounds > 1``, ISSUE 15) carries one entry
    per round in ``merged``/``out``/``head_dev``/``detail_dev``: the
    wave dispatched K rounds back-to-back with every round's (head,
    detail) D2H copy requested at dispatch, so the whole wave's blobs
    ride the tunnel in ONE latency-floor window and the merge tail
    unpacks them round by round."""

    __slots__ = (
        "batch", "staging", "alive_np", "batch_gs", "prop_gs", "caps",
        "merged", "out", "head_dev", "detail_dev", "t_req", "tick_fed",
        "rounds",
    )

    def __init__(self, *, batch, staging, alive_np, batch_gs, prop_gs,
                 caps, merged, out, head_dev, detail_dev, t_req,
                 tick_fed=None, rounds=1):
        self.batch = batch
        self.staging = staging
        self.alive_np = alive_np
        self.batch_gs = batch_gs
        self.prop_gs = prop_gs
        self.caps = caps
        self.merged = merged          # per-round list of state handles
        self.out = out                # per-round list of DeviceOut
        self.head_dev = head_dev      # per-round list of head blobs
        self.detail_dev = detail_dev  # per-round list of detail blobs
        self.t_req = t_req
        self.tick_fed = tick_fed or {}
        self.rounds = rounds


class ColocatedVectorEngine(VectorStepEngine):
    """Shared device engine for several NodeHosts in one process.

    Do not construct directly — use ``ColocatedEngineGroup``.
    """

    def __init__(self, *, budget: int = 2, capacity: int = 64, P: int = 5,
                 W: int = 32, M: int = 8, E: int = 4, O: int = 32,
                 rebase_chunk: int = 1 << 30, device=None, mesh=None,
                 pipeline_depth: Optional[int] = None,
                 sync_floor_ms: Optional[float] = None,
                 fused_rounds: Optional[int] = None):
        self.budget = budget
        self._pending: Optional[Inbox] = None
        self._pending_live = False  # last route delivered > 0 messages
        self._host_shard = np.zeros((capacity,), np.int64)
        self._host_replica = np.zeros((capacity,), np.int64)
        self._host_peers = np.zeros((capacity, P), np.int64)
        self._tables_dirty = True
        self._dest_dev = None
        self._rank_dev = None
        # shard -> OrderedDict[(index, term) -> Entry]; bounded FIFO per
        # shard.  Depth must cover BOTH lifetimes an entry is needed
        # for: the device ring window (8*W) and the stamp-to-consumption
        # gap of a routed append — the receiver merges one launch after
        # the sender stamped, and a proposal storm can stamp up to ~M*E
        # entries per launch in between, evicting the referenced entry
        # from a W-sized budget (chaos finding: rare fail-stops at
        # W=8 under full-rate clients).  8*M*E covers several launches
        # of worst-case append volume.
        self._entry_cache: Dict[int, "OrderedDict[Tuple[int, int], Entry]"] = {}
        self._cache_depth = max(8 * W, 8 * M * E)
        # per-SHARD shared index base (the colocated 64-bit story):
        # routed messages carry raw int32 index lanes between rows, so a
        # per-row base would desynchronize them — instead every resident
        # row of a shard shares one W-aligned base, advanced by whole-
        # shard rebases (see _maybe_rebase_shards).  rebase_chunk is how
        # far committed may outrun the base before a rebase (tests
        # shrink it to exercise multi-rebase traffic at ordinary scale).
        self._shard_base: Dict[int, int] = {}
        self._rebase_chunk = rebase_chunk
        # shard -> committed level below which rebase attempts are
        # suppressed (set when an attempt finds no representable
        # progress, e.g. a lagging peer lane pins the candidate min)
        self._rebase_block: Dict[int, int] = {}
        # chaos/fault plug point: (shard_id, replica_id) -> partition
        # group.  Rows in different groups lose their device route (the
        # link falls back to the host transport — counted in
        # routed_dropped as dest<0 — where the usual drop hooks apply);
        # both sides keep ticking and campaigning, exactly a network
        # partition.  None = fully connected.
        self._part_fn = None
        # rate limit for the O(resident rows) coalesce scan (see
        # _coalesce); 0 = never scanned yet
        self._last_coalesce_scan = 0.0
        self._scan_cost = 0.0
        # adaptive device-select capacities for the single-sync launch
        # blob (see _select_and_blob): detail rows are ~2 KB each so
        # CAP_D tracks actual peaks tightly; vals rows are 40 B so
        # CAP_S can ride elections up to G cheaply
        # deterministic select-capacity tier (see _SEL_TIERS): index into
        # the warmed ladder + the consecutive-fits-lower-tier streak
        self._sel_tier = 0
        self._sel_fit_streak = 0
        # ---- launch pipeline (double-buffered generations) ----------
        # FIFO of dispatched-but-unmerged generations; the merge tail
        # runs one generation behind the device at depth 2.  The fence
        # contract (docs/PARITY.md "Pipeline safety argument"): rows
        # being evicted/escalated/detached drain this to depth 0 before
        # membership mutates — mirroring the ≤1-launch detach-race
        # argument at any depth.
        from collections import deque as _deque

        self._inflight: "_deque[_InFlightGen]" = _deque()
        self._pipeline_depth = max(
            1,
            pipeline_depth
            if pipeline_depth is not None
            else _PIPE_DEPTH_DEFAULT,
        )
        self._sync_floor_s = (
            sync_floor_ms
            if sync_floor_ms is not None
            else _SYNC_FLOOR_MS_DEFAULT
        ) / 1000.0
        # fused commit waves (ISSUE 15): K consecutive routed rounds
        # chained device-side per routable generation — propose ->
        # commit in one launch + one readback.  Non-routable
        # generations (membership mutation in sight, escalation holds,
        # save quarantine, stopping rows) fence to the single-round
        # path, extending the PR 11 pipeline fence argument unchanged.
        self._fuse_rounds = max(
            1,
            fused_rounds
            if fused_rounds is not None
            else _FUSED_ROUNDS_DEFAULT,
        )
        # deferred membership actions discovered mid-completion
        # (escalation replays, snapshot-below / save-failure evictions,
        # demotes): they mutate membership, so they run only once the
        # pipeline is drained to depth 0 — never from inside a merge.
        self._deferred: List[Tuple] = []
        self._running_deferred = False
        # True while a generation's merge tail is executing: membership
        # mutators called from inside it (demote, save-failure evict)
        # must defer instead of fencing — a fence mid-merge would
        # complete LATER generations before this one finishes.
        self._completing = False
        # row slots freed while generations are in flight: an in-flight
        # merge still references them by id, so they re-enter _free only
        # at depth 0 (a re-attach reusing the slot mid-flight would let
        # one generation's effects merge into another replica's row)
        self._free_pending: List[int] = []
        self._last_worker_id = 0
        super().__init__(None, capacity=capacity, P=P, W=W, M=M, E=E, O=O,
                         device=device, mesh=mesh)
        # nemesis escalations are consumed at plan time here: routed
        # regions suppress escalated rows ON device, so the base
        # engine's post-launch flag flip would desync the merged state
        self._consume_engine_fault_at_plan = True
        # loop-invariant delivered-bit unpack tables (word index and
        # in-word shift per outbox slot) — hoisted out of the merge loop
        self._dw_word = np.arange(self.O) // 32
        self._dw_shift = (np.arange(self.O) % 32).astype(np.uint32)
        self.stats.update(
            launches=0, routed_delivered=0, routed_host_carried=0,
            routed_dropped=0, coalesced_rows=0, shard_rebases=0,
            # cumulative wall-time breakdown (ms) of the launch path —
            # the single-core CPU backend hides where a 65k-row launch
            # goes without it
            t_coalesce_ms=0, t_plan_ms=0, t_upload_ms=0, t_device_ms=0,
            t_detail_ms=0, t_updates_ms=0, t_persist_ms=0,
            # pipeline observability: host work overlapped with an
            # in-flight readback request (the double-buffering win),
            # fences (drains to depth 0 forced by membership mutation),
            # futures completed from the head-only early pass, and the
            # floor-shim wait actually paid at collect time
            pipeline_overlap_s=0.0, pipeline_fences=0,
            early_completions=0, t_sync_wait_ms=0.0,
            # fused commit waves (ISSUE 15): waves dispatched, rounds
            # stepped inside them, single-round fences (a routable-work
            # generation that could NOT fuse), and readback windows —
            # ONE per completed generation regardless of its round
            # count (plus one per exact-gather fallback round), the
            # counter proving one readback per fused wave
            fused_waves=0, fused_rounds_stepped=0, fused_fences=0,
            readback_windows=0,
        )

    def _compute_base(self, r) -> int:
        # the SHARD's shared base, not a per-row quantity — see __init__
        return self._shard_base.get(r.shard_id, 0)

    def _lease_pass(self, live, flags, vals_np, pos_sum,
                    tick_fed) -> None:
        """Per-generation device-lease evidence pass (ROADMAP 4b): see
        hostplane.LeaseLanes.  Runs before the bulk mirror write (role
        transitions read the OLD mirror) and before per-row tick
        bookkeeping (window starts stamp the pre-launch clock — the
        conservative side)."""
        for node, g, si in live:
            if node.stopped or self._meta.get(g) is None:
                continue
            r = node.peer.raft
            if vals_np is not None:
                k = int(pos_sum[g])
                if k >= 0:
                    role = int(vals_np[k, _R_ROLE])
                    if role != int(self._mirror[_R_ROLE, g]):
                        if (
                            role == int(RaftRole.LEADER)
                            and r.check_quorum
                        ):
                            self._lease.arm(g, r.election_timeout, 0)
                        else:
                            self._lease.disarm(g)
            a = self._lease.row_step(
                g, tick_fed.get(g, 0), node.tick_count, int(flags[g])
            )
            if a >= 0:
                r.anchor_quorum_evidence(a)

    def device_coordinate(self, shard_id: int, replica_id=None):
        if self._mesh is None:
            return None
        if replica_id is None:
            gs = [
                g for (s, _r), g in self._row_of.items() if s == shard_id
            ]
            g = min(gs) if gs else None
        else:
            g = self._row_of.get((shard_id, replica_id))
        if g is None:
            return None
        return g // (self.capacity // self._mesh.size)

    def _pick_row(self, node) -> int:
        """Mesh-mode shard affinity: place a shard's replicas on the
        device block already hosting the shard, so a shard's commit
        rounds route intra-device and only cross-SHARD load spreads
        over the mesh (docs/MULTICHIP.md "Placement").  The scan is
        bounded to the free-list tail — with the striped base order the
        tail alternates blocks, so the preferred block is almost always
        within a few slots; after heavy churn it degrades gracefully to
        the plain pop."""
        if self._mesh is None:
            return self._free.pop()
        per = self.capacity // self._mesh.size
        want = None
        for (s, _r), g0 in self._row_of.items():
            if s == node.shard_id:
                want = g0 // per
                break
        if want is None:
            return self._free.pop()
        lo = max(0, len(self._free) - 4 * self._mesh.size)
        for i in range(len(self._free) - 1, lo - 1, -1):
            if self._free[i] // per == want:
                return self._free.pop(i)
        return self._free.pop()

    def _tier_caps(self, t: int) -> Dict[str, int]:
        return {k: min(self.capacity, v) for k, v in _SEL_TIERS[t].items()}

    # -- row identity ---------------------------------------------------
    def _row_key(self, node):
        # several NodeHosts share this engine: replicas of one shard are
        # distinct rows
        return (node.shard_id, node.replica_id)

    def _free_slot(self, g: int) -> None:
        """Return a row slot to the free pool — quarantined in
        ``_free_pending`` while generations are in flight (an in-flight
        merge still references the slot by id; re-attaching it before
        depth 0 would merge one replica's device effects into
        another's scalar state).  Flushed back at every drain."""
        (self._free_pending if self._inflight else self._free).append(g)

    def _flush_free_pending(self) -> None:
        if self._free_pending and not self._inflight:
            self._free.extend(self._free_pending)
            self._free_pending.clear()

    def _attach(self, node) -> Optional[int]:
        key = self._row_key(node)
        g = self._row_of.get(key)
        if g is not None and self._meta[g].node is not node:
            # replica restarted without a detach (stop raced the step):
            # drop the stale binding and re-key freshly.  PIPELINE
            # FENCE first — this is a membership mutation like any
            # detach, and in-flight merges still reference row g (the
            # old node's device acks must persist before the row is
            # released); the call site is the plan loop, never a
            # merge, so fencing is legal here (review finding)
            self._fence()
            self._row_of.pop(key)
            self._meta.pop(g, None)
            self._free_slot(g)
            self._release_row(g, node.shard_id)
            g = None
        is_new = key not in self._row_of
        g = super()._attach(node)
        if g is not None and is_new:
            self._host_shard[g] = node.shard_id
            self._host_replica[g] = node.replica_id
            self._host_peers[g, :] = 0
            self._tables_dirty = True
        return g

    def _release_row(self, g: int, shard_id: int) -> None:
        """Clear the route-table claim of a freed row (caller holds the
        lock and has already popped _row_of/_meta).  Also drops the
        shard's entry cache when its last resident replica is gone —
        without this a process cycling many shards leaks one payload
        cache per shard id ever hosted."""
        self._host_shard[g] = 0
        self._host_replica[g] = 0
        self._host_peers[g, :] = 0
        self._lanes.reset_row(g, attached=False)
        self._tables_dirty = True
        if not any(
            s == shard_id for s, _ in self._row_of
        ):
            self._entry_cache.pop(shard_id, None)
            # base resets with the last replica; a returning shard with
            # a large log re-establishes it via _maybe_rebase_shards
            # before any row can pass the planner's lane bounds
            self._shard_base.pop(shard_id, None)
            self._rebase_block.pop(shard_id, None)

    def _halt_replica(self, g: int) -> None:
        node = self._meta[g].node
        super()._halt_replica(g)  # appends g to _free
        if self._inflight and g in self._free:
            # fail-stops happen mid-merge with later generations in
            # flight: quarantine the slot until depth 0 (see _free_slot)
            self._free.remove(g)
            self._free_pending.append(g)
        self._release_row(g, node.shard_id)

    def detach_replica(self, shard_id: int, replica_id: int) -> None:
        self.detach_replicas([(shard_id, replica_id)])

    def detach_replicas(self, pairs) -> None:
        """Batch detach under ONE core-lock acquisition (NodeHost.close
        releases every row of a member at once; per-row locking would
        interleave thousands of acquisitions with live launches).

        PIPELINE FENCE: membership must not mutate under an in-flight
        generation — the pending merges still reference these rows, and
        a stopping node's device acks were already routed, so its
        appends must persist before the row goes away (the ≤1-launch
        detach-race argument, now enforced at any depth by draining
        first: the drained merges run while the node is still live,
        then the row is released)."""
        with self._lock:
            self._fence()
            for shard_id, replica_id in pairs:
                g = self._row_of.pop((shard_id, replica_id), None)
                if g is not None:
                    self._meta.pop(g, None)
                    self._free_slot(g)
                    self._release_row(g, shard_id)

    def _upload_rows(self, rows) -> None:
        super()._upload_rows(rows)
        for g, r in rows:
            lay = np.zeros((self.P,), np.int64)
            for s, (pid, _) in enumerate(S.peer_layout(r)):
                lay[s] = pid
            if (self._host_peers[g] != lay).any():
                self._host_peers[g] = lay
                self._tables_dirty = True
            self._publish_ring_window(r)

    def _publish_ring_window(self, r) -> None:
        """Publish an uploading row's ring window to the shard cache:
        entries appended on the HOST path (scalar excursions, WAL
        replay) can later be device-route-replicated straight from this
        row's ring, and the receiving replica reconstructs payloads
        from the cache.  Witness rows must NOT publish — their own log
        holds stripped metadata entries (no cmd) under the same
        (index, term) keys; publishing them would overwrite real
        payloads in the shared cache and silently diverge any replica
        that reconstructs from it (witness RECEIVERS get the stripped
        form applied at _cache_lookup instead)."""
        if r.replica_id in r.witnesses:
            return
        last = r.log.last_index()
        lo = max(r.log.first_index(), last - self.W + 1)
        if last >= lo:
            try:
                ents = r.log._get_entries(lo, last + 1, 2**62)
            except Exception:  # noqa: BLE001 — compacted tails are fine
                ents = []
            self._cache_put(r.shard_id, ents)

    def _demote_row_to_host(self, node) -> None:
        g = self._row_of.get(self._row_key(node))
        if g is None:
            return
        meta = self._meta.get(g)
        if meta is None or meta.dirty:
            return
        self._evict_rows_to_host([g], "demote")  # drains pending routed traffic
        meta.set_escalation_hold(node.config)

    def _on_save_failure(self, pairs) -> None:
        super()._on_save_failure(pairs)
        # evict the failing nodes' rows (we hold the core lock:
        # colocated persist runs inside _step_colocated) so no further
        # device launch routes acks for appends their WAL cannot hold;
        # the scalar path only sends after a successful save.  With the
        # pipeline live this defers to the next depth-0 point (before
        # the next dispatch): the base class's save quarantine already
        # keeps the rows out of every new plan, and the ≤depth launches
        # already in flight were dispatched before the failure was
        # knowable — the same exposure window as the detach race.
        self._evict_rows_to_host([
            g
            for node, _u in pairs
            if (g := self._row_of.get(self._row_key(node))) is not None
        ], "save_failure")

    def _rebuild_tables(self) -> None:
        dest, rank = build_route_tables(
            self._host_shard, self._host_replica, self._host_peers
        )
        if self._part_fn is not None:
            # cut cross-partition links by severing the device route:
            # the message is left undelivered (dest<0, counted in
            # routed_dropped) and the sending host re-sends it via its
            # transport, where the partition's drop hook loses it — the
            # destination row still ticks, campaigns and answers its
            # own side, which is what a real network partition does
            part = np.array([
                self._part_fn(int(s), int(r)) if s else 0
                for s, r in zip(self._host_shard, self._host_replica)
            ])
            cut = (dest >= 0) & (
                part[np.clip(dest, 0, len(part) - 1)] != part[:, None]
            )
            dest = np.where(cut, -1, dest)
        self._dest_dev = self._put_rows(jnp.asarray(dest))
        self._rank_dev = self._put_rows(jnp.asarray(rank))
        self._tables_dirty = False

    def set_partition(self, fn) -> None:
        """Install (or clear, with ``None``) a partition-group function
        ``fn(shard_id, replica_id) -> int``: device routes between rows
        in different groups are severed until cleared — cross-group
        messages fall back to each sender's host transport (chaos
        testing — see _rebuild_tables).  Takes effect from the next
        launch."""
        with self._lock:
            self._part_fn = fn
            self._tables_dirty = True

    # -- entry cache ----------------------------------------------------
    def _cache_put(self, shard_id: int, entries: List[Entry]) -> None:
        od = self._entry_cache.setdefault(shard_id, OrderedDict())
        for e in entries:
            od[(e.index, e.term)] = e
            od.move_to_end((e.index, e.term))
        while len(od) > self._cache_depth:
            # evict the LOWEST index, not the FIFO-oldest: a follower
            # catch-up re-inserts evicted low keys one batch at a time,
            # and FIFO eviction then rolls a wave through the insert
            # order that eventually eats the NEWEST entries — the very
            # ones the leader's ring can still device-route, fail-
            # stopping the follower at the last ring-window hop (r4
            # chaos finding: wedged at last-W+2 after a 300-entry lag)
            od.pop(min(od))

    def _cache_lookup(self, r, idx: int, term: int) -> Optional[Entry]:
        od = self._entry_cache.get(r.shard_id)
        e = od.get((idx, term)) if od else None
        if e is not None and r.replica_id in r.witnesses:
            e = r._to_witness_entry(e)
        return e

    # -- warm -----------------------------------------------------------
    def _warm(self) -> None:
        G, P, B, E, O = self.capacity, self.P, self.budget, self.E, self.O
        self._pending = self._put_rows(make_inbox(G, P * B, E))
        st = self._state
        host = self._put_rows(make_inbox(G, self.M, E))
        combo = self._put_rows(jnp.zeros((G, 4), jnp.int32))
        # persistent all-zero combo: rounds >= 2 of a fused wave build
        # their (empty) host inbox region from it ON DEVICE — ticks and
        # host slots are fed exactly once, in round 1 (never donated,
        # so one handle serves every wave)
        self._zero_combo = combo
        dest = self._put_rows(jnp.full((G, P), -1, I32))
        rank = self._put_rows(jnp.zeros((G, P), I32))
        # warm the REAL launch signature: host inbox built on device
        # from the (row-sharded) fused combo upload — warming with a
        # host-side make_inbox would key different executables
        # (committed-ness / sharding) and the first production launch
        # would recompile
        host2 = _host_inbox_from_ticks(combo, M=self.M, E=E)
        # warm the PRODUCTION fused executable; it donates host2 and
        # _pending, so rebuild _pending afterwards
        new_st, out = _assemble_and_step(
            st, host2, self._pending, combo, out_capacity=O
        )
        self._pending = self._put_rows(make_inbox(G, P * B, E))
        merged_w, _regions_w, stats_w, packed_w, flags_w = _route_step(
            st, new_st, out, dest, rank, combo, PB=P * B, E=E, budget=B
        )
        # warm EVERY ladder tier: tier changes mid-run must hit the
        # (persistent) cache, never a fresh tunnel compile — a mid-run
        # compile froze the launch pipeline for tens of seconds (r5)
        for t in range(len(_SEL_TIERS)):
            caps = self._tier_caps(t)
            _select_and_blob(
                merged_w, out, stats_w, packed_w, flags_w, combo,
                CAP_B=caps["b"], CAP_SL=caps["sl"], CAP_N=caps["n"],
                CAP_A=caps["a"], CAP_S=caps["s"], HOST_OFF=P * B,
            )
        from .engine import _gather_rows, _scatter_rows, _select_rows

        _select_rows(self._put(jnp.ones((G,), bool)), st, st)
        pos0 = self._put_rows(jnp.full((G,), -1, jnp.int32))
        mask0 = self._put_rows(jnp.zeros((G,), bool))
        _zero_inbox_rows(self._pending, mask0)
        # host2 was DONATED into _assemble_and_step above; warm the
        # scatter against a fresh host inbox of the same signature
        host3 = _host_inbox_from_ticks(
            self._put_rows(jnp.zeros((G, 4), jnp.int32)), M=self.M, E=E
        )
        b = 1
        while b <= G:
            idx = self._put(jnp.zeros((b,), jnp.int32))
            sub = _gather_rows(st, idx)
            _scatter_rows(st, pos0, sub)
            _gather_detail(st, out, self._put(jnp.zeros((4, b), jnp.int32)))
            _gather_vals(st, out, idx)
            # the eviction drain gathers rows of the PENDING INBOX
            # (_drain_pending_to_host) — a distinct _gather_rows
            # signature the state-gather warms above don't cover; the
            # first post-warm eviction paid a fresh compile mid-run
            # (found by the analysis/jitcheck recompile sentry)
            _gather_rows(self._pending, idx)
            _scatter_inbox_rows(
                host3, pos0,
                self._put(Inbox(*(jnp.zeros((b,) + f.shape[1:], I32)
                                  for f in host3))),
            )
            b <<= 1
        one = self._put(jnp.zeros((1,), jnp.int32))
        _set_remote_snapshot(st, one, one, one)
        jax.block_until_ready(self._state)
        if jitcheck.ENABLED:
            # recompile sentry baseline (analysis/jitcheck): the warm
            # set above is the COMPLETE post-warm compile surface
            jitcheck.mark_warm()

    def _evict_rows_to_host(self, gs, cause: str = "other") -> None:
        """Move resident rows to the host path losing nothing.  Order is
        a correctness invariant encoded ONCE here: drain each row's
        routed-but-unconsumed inbox traffic into its node's receive
        queue FIRST (the next launch's alive mask would destroy it —
        losing a heartbeat stream turns a brief host excursion into an
        election storm), then materialize device state into the scalar
        mirrors, then mark the rows host-authoritative.  Already-dirty
        rows are skipped wholesale: their scalar side is authoritative
        and materializing stale device lanes over it would corrupt it.
        Caller holds the core lock.

        PIPELINE FENCE: eviction mutates membership (rows leave the
        device), so in-flight generations drain to depth 0 first —
        their merges still reference these rows, and materializing a
        row whose unmerged device appends are in flight would trip a
        false divergence halt.  A caller running INSIDE a generation's
        merge (demote on a compacted below-ring send, a save-failure
        mid-persist) must not fence — completing later generations
        before the current one finishes would break the FIFO scalar
        sync — so the eviction defers to the next depth-0 point
        instead (before the next dispatch, see _run_deferred)."""
        if self._completing:
            self._deferred.append(("evict", [int(g) for g in gs], cause))
            return
        if self._inflight and any(
            (m := self._meta.get(g)) is not None and not m.dirty
            for g in gs
        ):
            self._fence()
        pairs = []
        for g in gs:
            meta = self._meta.get(g)
            if meta is not None and not meta.dirty:
                pairs.append((meta.node, g))
        if not pairs:
            return
        self.stats[f"evict_{cause}"] = (
            self.stats.get(f"evict_{cause}", 0) + len(pairs)
        )
        self._drain_pending_to_host(pairs)
        self._materialize_rows([g for _, g in pairs])
        for _, g in pairs:
            meta = self._meta.get(g)
            if meta is not None:
                meta.dirty = True

    def _drain_pending_to_host(self, pairs) -> None:
        """Decode rows' pending routed-inbox regions into wire Messages
        and enqueue them on the owning nodes (rows transitioning device
        -> host).  REPLICATE payloads reconstruct from the entry cache;
        an unreconstructible message is dropped (raft retries it)."""
        from ..pb import Message, MessageType
        from .engine import _gather_rows
        from .types import MT_REPLICATE

        if self._pending is None or not pairs:
            return
        idx = self._put(jnp.asarray(_pad_idx([g for _, g in pairs])))
        sub = jax.tree.map(np.asarray, _gather_rows(self._pending, idx))
        for k, (node, g) in enumerate(pairs):
            r = node.peer.raft
            base = int(self._base[g])  # routed lanes are shard-rebased
            for s in range(sub.mtype.shape[1]):
                mt = int(sub.mtype[k, s])
                if mt == 0:
                    continue
                n = int(sub.n_entries[k, s])
                msg = _shift_msg_indexes(
                    Message(
                        type=MessageType(mt),
                        to=node.replica_id,
                        from_=int(sub.from_id[k, s]),
                        shard_id=node.shard_id,
                        term=int(sub.term[k, s]),
                        log_term=int(sub.log_term[k, s]),
                        log_index=int(sub.log_index[k, s]),
                        commit=int(sub.commit[k, s]),
                        reject=bool(sub.reject[k, s]),
                        hint=int(sub.hint[k, s]),
                        hint_high=int(sub.hint_high[k, s]),
                    ),
                    base,
                )
                ents = []
                ok = True
                if mt == MT_REPLICATE and n > 0:
                    for j in range(n):
                        e = self._cache_lookup(
                            r,
                            msg.log_index + 1 + j,
                            int(sub.ent_term[k, s, j]),
                        )
                        if e is None:
                            ok = False
                            break
                        ents.append(e)
                if not ok:
                    continue
                if ents:
                    msg = dataclasses.replace(msg, entries=tuple(ents))
                node.enqueue_received(msg)
        # drained => CLEARED: the pending copies are dead the moment
        # they re-enter the host queues.  Without this, a shard rebase
        # that re-uploads its rows in the SAME step re-delivers the
        # stale copies with index lanes encoded against the OLD base
        # (review finding: healthy replicas fail-stopped on the shifted
        # replicates); the host-excursion path only survived it because
        # drained rows stayed dirty through the next launch's alive mask.
        mask = np.zeros((self.capacity,), bool)
        mask[[g for _, g in pairs]] = True
        self._pending = _zero_inbox_rows(
            self._pending, self._put_rows(jnp.asarray(mask))
        )

    # -- the launch pipeline -------------------------------------------
    def _fence(self) -> None:
        """Drain the pipeline to depth 0, run the deferred membership
        actions and persist every drained update — invoked before any
        membership mutation (evict/detach/rebase/stale re-attach).
        No-op when nothing is in flight or deferred.  Caller holds the
        core lock; must NOT be called from inside a generation's merge
        (those paths defer instead — see _evict_rows_to_host)."""
        if not self._inflight and not self._deferred:
            self._flush_free_pending()
            return
        if self._inflight:
            self.stats["pipeline_fences"] += 1
        updates = self._drain_pipeline()
        if updates:
            self._drain_update_retries(updates)
            self._persist_and_process(updates, self._last_worker_id)

    def _drain_pipeline(self) -> List[Tuple]:
        """Complete every in-flight generation in dispatch order, then
        run the deferred actions; returns the updates to persist."""
        updates: List[Tuple] = []
        while self._inflight:
            updates.extend(self._complete_oldest())
        updates.extend(self._run_deferred())
        self._flush_free_pending()
        return updates

    def _complete_oldest(self) -> List[Tuple]:
        rec = self._inflight.popleft()
        self._completing = True
        try:
            return self._complete_generation(rec)
        except BaseException:
            # the generation chain is poisoned (its outputs feed every
            # later in-flight handle): roll the resident set back to
            # the last merged generation
            self._reset_after_pipeline_failure()
            raise
        finally:
            self._completing = False

    def _run_deferred(self) -> List[Tuple]:
        """Execute deferred membership actions (escalation replays,
        snapshot-below/save-failure evictions, demotes) in the order
        they were recorded — only at depth 0, so every generation that
        stepped the affected rows has merged first.  Returns updates to
        persist.  Reentrancy guard: an action's own eviction fences,
        which calls back here — the inner call no-ops and the outer
        loop keeps draining."""
        if self._running_deferred or self._inflight:
            return []
        updates: List[Tuple] = []
        self._running_deferred = True
        try:
            while self._deferred and not self._inflight:
                action = self._deferred.pop(0)
                kind = action[0]
                if kind == "esc":
                    updates.extend(
                        self._apply_escalation(action[1], action[2],
                                               action[3])
                    )
                elif kind == "evict":
                    # covers mid-merge demotes and save-failure
                    # quarantine evictions too — both defer through
                    # _evict_rows_to_host's completing check
                    self._evict_rows_to_host(action[1], action[2])
                elif kind == "below":
                    self._apply_snapshot_below(action[1])
        finally:
            self._running_deferred = False
        return updates

    def _apply_escalation(self, node, g: int, si) -> List[Tuple]:
        """Deferred kernel-escalation recovery — the pipeline-safe form
        of the serial restore-and-replay.  The device already restored
        the row's pre-step state (_route_step's suppress mask), and any
        LATER in-flight generation re-stepped it from there: a valid
        raft evolution whose routed acks were delivered, so its effects
        merged normally before this runs (FIFO drain).  Recovery is
        therefore a plain eviction of the row's CURRENT device state
        (drains pending routed traffic, materializes, marks dirty)
        followed by a scalar replay of the escalated generation's
        drained inputs — late replay of messages/proposals/ticks is
        raft-safe, and at depth 1 the current state IS the restored
        pre-step state, so this degenerates to the old serial shape."""
        meta = self._meta.get(g)
        if meta is None or meta.node is not node or node.stopped:
            return []
        self._evict_rows_to_host([g], "escalation")
        meta = self._meta.get(g)
        if meta is None:  # halted during the eviction's materialize
            return []
        meta.set_escalation_hold(node.config)
        if si is None:
            return []  # routed-only inputs: raft-safe to lose
        u = node.step_with_inputs(si)
        return [(node, u)] if u is not None else []

    def _apply_snapshot_below(self, below) -> None:
        """Deferred snapshot-below host excursion: evict the rows (the
        int32 lane can't represent the durable snapshot index), then
        mark the scalar remotes SNAPSHOT — after the materialize, which
        would otherwise overwrite them and re-fire duplicate full
        snapshot streams on every re-upload."""
        self._evict_rows_to_host(
            sorted({t[0] for t in below}), "snapshot_below"
        )
        for g, p, _, pid, ss_index in below:
            meta = self._meta.get(g)
            if meta is None or meta.node.stopped:
                continue
            rm = meta.node.peer.raft.get_remote(pid)
            if rm is not None:
                rm.become_snapshot(ss_index)

    def _floor_wait(self, t_req: float) -> None:
        """Simulated-tunnel sync latency: data counts as landed no
        earlier than the floor after the D2H request was issued.  A
        request issued at dispatch and collected after host work pays
        only the remainder — the overlap the pipeline exists for."""
        if self._sync_floor_s <= 0:
            return
        import time as _time

        rem = self._sync_floor_s - (_time.monotonic() - t_req)
        if rem > 0:
            _time.sleep(rem)
            self.stats["t_sync_wait_ms"] += rem * 1000.0

    def _collect_blob(self, dev, t_req: float) -> np.ndarray:
        """THE launch readback: blocking collect of a blob whose D2H
        copy was requested at dispatch, honoring the sync-floor shim."""
        # raftlint: ignore[sync-budget] the single sanctioned blob readback of the launch path
        arr = np.asarray(dev)
        self._floor_wait(t_req)
        return arr

    def _reset_after_pipeline_failure(self) -> None:
        """A launch program failed after later generations chained onto
        its outputs: every in-flight handle (state, pending regions,
        blobs) is transitively poisoned.  Roll the WHOLE resident set
        back to the last merged generation: scalar state is
        authoritative through it, and the unmerged generations' effects
        existed only device-side — appends and the acks they earned
        vanish TOGETHER for every colocated row (one shared device
        state), which is raft-safe message loss.  Rows re-upload from
        scratch on their next step."""
        # keep the one-readback identity (readback_windows + in-flight
        # == launches + sel_fallbacks, the fused-round smoke's gate) an
        # invariant across resets: the discarded generations' windows
        # will never be collected, so account them here
        self.stats["readback_windows"] += len(self._inflight)
        self._inflight.clear()
        self._pending_live = False
        self._flush_free_pending()
        for g, meta in list(self._meta.items()):
            if not meta.dirty:
                meta.dirty = True
                meta.plan_ok = False
                if meta.node.device_reads.has_pending():
                    meta.node.drop_device_reads()
        try:
            self._state = self._put_rows(
                make_state(self.capacity, self.P, self.W,
                           replica_ids=np.zeros(self.capacity))
            )
            self._pending = self._put_rows(
                make_inbox(self.capacity, self.P * self.budget, self.E)
            )
        except Exception:  # noqa: BLE001 — rebuilt lazily next launch
            self._pending = None

    # -- the colocated step --------------------------------------------
    def step_shards(self, nodes, worker_id: int) -> None:
        if all(n.stopped or n.stopping for n in nodes):
            # teardown fast path: don't contend for the core lock (the
            # owning worker may be asked to stop while we'd be queued
            # behind another member's multi-second launch)
            return
        # floor pre-wait: never hold the core lock just to wait out a
        # readback's latency floor.  Two shapes paid the floor IN the
        # lock and stalled every other worker's fresh proposal behind
        # ~a full floor (measured: the unloaded probe sat at ~2
        # floors): (a) the poke-driven idle drain (no node has work —
        # the call exists only to merge the tail generation) blocking
        # on the oldest collect, and (b) the dispatch room check with
        # the pipe FULL, blocking on the oldest collect before a new
        # generation may launch.  Both waits are for the SAME event —
        # the oldest in-flight readback reaching its floor — so sleep
        # it out here in small slices with the lock free: an idle call
        # aborts the moment any of its nodes gains real work (it can
        # then dispatch), a full-pipe call waits regardless (it needs
        # the room anyway).  Racy peeks of the in-flight deque are
        # benign — the in-lock paths re-check everything.
        if self._sync_floor_s > 0 and self._inflight:
            import time as _time

            # bounded at ONE floor from entry: under multi-worker
            # contention the oldest in-flight keeps getting fresher
            # (another worker merges + redispatches), and an unbounded
            # re-wait could starve this worker's nodes — past the
            # bound it falls into the lock and blocks there exactly as
            # before (correctness never depended on the pre-wait)
            _cap = _time.monotonic() + self._sync_floor_s
            while _time.monotonic() < _cap:
                if not self._inflight:
                    break
                try:
                    t_req = self._inflight[0].t_req  # racy peek
                except IndexError:
                    break
                rem = t_req + self._sync_floor_s - _time.monotonic()
                if rem <= 0:
                    break
                if (
                    len(self._inflight) < self._pipeline_depth
                    and any(n.has_work() for n in nodes)
                ):
                    break
                _time.sleep(min(rem, 0.002))
        with self._lock:
            self._step_colocated(nodes, worker_id)

    def _coalesce(self, nodes) -> List:
        """Pull every other attached node with queued work into this
        launch: a full-width kernel step costs the same whether it
        carries one member NodeHost's inputs or all of them, so one
        launch serves the whole cluster's tick generation instead of
        one launch per member (at 10k shards x 5 members that is the
        difference between 1 and 5 multi-second launches per
        generation).  Safe under the core lock: ALL colocated node
        stepping happens inside it, so no other worker can be draining
        these queues concurrently."""
        # throttle: the scan is O(resident rows) of pure Python and ran
        # once per generation — ~1000 small preload generations during a
        # 50k-row mass start made it the single largest cost of the r5
        # scale run (294 s).  Skipping it is always SAFE: a node with
        # work was notified, so its own exec worker delivers it in
        # `nodes` on an upcoming generation; coalescing is a batching
        # optimization, not a delivery guarantee.
        import time as _time

        now = _time.monotonic()
        # interval scales with the measured scan cost (>=10x) so the
        # scan can never consume more than ~10% of wall time: at 250k
        # resident rows one scan is 1-2 s of Python and a fixed 200 ms
        # interval let it dominate the 50k-shard election
        if now - self._last_coalesce_scan < max(0.2, 10 * self._scan_cost):
            return list(nodes)
        self._last_coalesce_scan = now
        seen = {id(n) for n in nodes}
        out = list(nodes)
        for meta in self._meta.values():
            n = meta.node
            if (
                id(n) not in seen
                and not n.stopped
                and not n.stopping
                and n.has_work()
            ):
                seen.add(id(n))
                out.append(n)
        self._scan_cost = _time.monotonic() - now
        coalesced = len(out) - len(nodes)
        if coalesced:
            self.stats["coalesced_rows"] += coalesced
        return out

    def _maybe_rebase_shards(self, nodes) -> None:
        """Whole-shard group rebasing (the colocated 64-bit story).

        When any row's committed outruns its shard's shared base by
        ``rebase_chunk``, every RESIDENT row of that shard leaves the
        device together — in-flight routed traffic drains to the host
        queues first, so no rebased int32 lane survives the base change
        — and the shard's base advances to the largest W-multiple safe
        for ALL its rows (min across rows; leader rows bound it by
        their laggiest peer lane).  Rows re-upload with the new base on
        their next step.  Reference: uint64 log indexes throughout
        raftpb [U]; this keeps the colocated device path unbounded
        instead of aging shards off at 2^31 (r03 verdict #4)."""
        need = set()
        for node in nodes:
            if node.stopped or node.stopping:
                continue
            r = node.peer.raft
            shard = node.shard_id
            if (
                r.log.committed - self._shard_base.get(shard, 0)
                >= self._rebase_chunk
                and r.log.committed >= self._rebase_block.get(shard, 0)
            ):
                need.add(shard)
        if not need:
            return
        # the trigger uses committed (device-synced every step); the
        # CANDIDATE base needs fresh peer lanes, which only materialize
        # refreshes — so pull the shard's rows off the device first,
        # then decide.  If the candidate cannot advance (a lagging peer
        # lane or a freshly joined replica pins the min), the base must
        # neither regress nor be retried every step (review finding:
        # drain/materialize thrash): back off until committed grows by
        # another chunk.
        self._evict_rows_to_host(
            [g for (shard, _), g in self._row_of.items() if shard in need],
            "rebase",
        )
        for shard in need:
            rafts = [
                self._meta[g].node.peer.raft
                for (s, _), g in self._row_of.items()
                if s == shard and self._meta.get(g) is not None
            ]
            if not rafts:
                continue
            candidate = min(
                VectorStepEngine._compute_base(self, r) for r in rafts
            )
            if candidate > self._shard_base.get(shard, 0):
                self._shard_base[shard] = candidate
                self._rebase_block.pop(shard, None)
                self.stats["shard_rebases"] += 1
            else:
                # back off by a FRACTION of the chunk, not a whole one:
                # a full-chunk block scheduled the retry at ~2x chunk,
                # which under the default chunk (2^30) lands at/past the
                # int32 planner ceiling — a transiently lagging peer
                # then doomed the shard to a whole-shard scalar eviction
                # even though a valid rebase opened up long before.
                # chunk//8 keeps the thrash amortized (one materialize
                # per chunk//8 commit growth) while leaving ~8 retries
                # of headroom before the ceiling.
                self._rebase_block[shard] = (
                    max(r.log.committed for r in rafts)
                    + max(self.W, self._rebase_chunk // 8)
                )

    def _plan_device(self, node, si, mirror_leader: bool, g):
        # a replica rejoining a shard whose base already advanced past
        # its committed position cannot upload (its lanes would go
        # negative): scalar path until host-wire catch-up reaches the
        # base.  Rows known at rebase time can never be in this state —
        # the candidate min() is bounded by them.
        if node.peer.raft.log.committed < self._shard_base.get(
            node.shard_id, 0
        ):
            return None
        return super()._plan_device(node, si, mirror_leader, g)

    def _step_colocated(self, nodes, worker_id: int) -> None:
        import time as _time

        self._last_worker_id = worker_id
        # ---- opportunistic completion: the earliest ripe sync -------
        # Merge any in-flight generation whose readback has LANDED
        # (floor elapsed, value ready) without blocking: proposals
        # complete from the earliest sync that proves their commit, not
        # from the pipe-full room check several generations later.
        # Runs before planning, so the plan also sees the freshest
        # merged scalars the link can provide.
        ripe: List[Tuple] = []
        while self._inflight:
            rec = self._inflight[0]
            if self._sync_floor_s > 0:
                import time as _t

                if _t.monotonic() - rec.t_req < self._sync_floor_s:
                    break
            # EVERY round's blobs must have landed: the merge may read
            # any round's detail payload too, and blocking the core
            # lock on a still-in-flight transfer is exactly the stall
            # this non-blocking pass exists to avoid (review finding)
            if any(
                (ir := getattr(dev, "is_ready", None)) is not None
                and not ir()
                for dev in (*rec.head_dev, *rec.detail_dev)
            ):
                break
            ripe.extend(self._complete_oldest())
        if ripe:
            self._drain_update_retries(ripe)
            self._persist_and_process(ripe, worker_id)
        if self._deferred:
            # deferred membership actions (recorded mid-merge, e.g. a
            # save-failure eviction during the driver's persist or an
            # escalation a ripe completion just surfaced) run before
            # anything new dispatches
            self._fence()
        updates: List[Tuple] = []
        host_rows: List[Tuple] = []
        batch: List[Tuple] = []
        _t0 = _time.perf_counter()
        nodes = self._coalesce(nodes)
        self._maybe_rebase_shards(nodes)
        self.stats["t_coalesce_ms"] += int(
            (_time.perf_counter() - _t0) * 1000
        )
        _t0 = _time.perf_counter()
        n_fast = 0
        # ---- batched plan classifier --------------------------------
        # ONE vectorized pass over the SoA lanes (ops/hostplane.py)
        # decides static eligibility for the whole generation —
        # plan_ok/dirty/esc_hold as bool lanes instead of per-row
        # _RowMeta attribute probes.  Rows that pass still re-verify
        # the cheap per-launch dynamic conditions (empty queues, clean
        # binding, no snapshot/read state) inline; rows that fail take
        # the scalar _plan_device classifier below — the escalation/
        # slow-path oracle, exactly the contract the plan_ok fast tick
        # lane (57 µs -> 5 µs per row) proved.
        row_of = self._row_of
        gs_list = [
            row_of.get((n.shard_id, n.replica_id), -1) for n in nodes
        ]
        static_arr = hostplane.classify_static(
            self._lanes, np.asarray(gs_list, np.int64)
        )
        if hostplane.PARITY:
            hostplane.check_classify_parity(
                self._lanes, gs_list, static_arr
            )
        static_ok = static_arr.tolist()
        # rows of nodes seen stopping THIS generation: cleared from the
        # launch's alive mask (their detach may still be queued behind
        # the core lock)
        self._gen_stopping = []
        for i, node in enumerate(nodes):
            if node.stopped or node.stopping:
                if gs_list[i] >= 0:
                    self._gen_stopping.append(gs_list[i])
                continue
            # ---- fast tick lane -------------------------------------
            # A clean resident row whose ONLY input is the lock-free
            # tick lane skips the drain lock and the full classifier:
            # the static checks were proven by the last full plan
            # (the plan_ok lane, batch-checked above) and everything
            # that can change them either arrives through the queues
            # (checked empty right here, GIL-atomic truthiness) or
            # invalidates plan_ok at its source.  At 50k rows the full
            # per-row plan was ~57 us and t_plan was 152 s of a 269 s
            # election (10k-shard TPU run); the fast lane is ~5 us.
            g = gs_list[i]
            meta = self._meta.get(g) if static_ok[i] else None
            if (
                meta is not None
                and meta.node is node  # not a stale pre-restart binding
                and node not in self._save_quarantine
                and not (
                    node._received
                    or node._proposals
                    or node._read_indexes
                    or node._config_changes
                    or node._cc_to_apply
                    or node._snapshot_reqs
                    or node._leader_transfers
                )
            ):
                r = node.peer.raft
                if not (
                    r.snapshotting
                    or r.read_index.pending
                    or r.read_index.queue
                ):
                    # ONE shared definition of the tick drain/cap/defer
                    # arithmetic (node.drain_ticks_only) — see its
                    # locking contract: this worker holds the core lock
                    ticks, gc_t = node.drain_ticks_only(
                        r.election_timeout // 2
                    )
                    q = node.quiesce
                    if q.enabled and ticks:
                        busy = bool(self._behind[g])
                        no_leader = int(self._mirror[_R_LEADER, g]) == 0
                        was = q.quiesced
                        ticks_dev = q.tick_n(ticks, busy=busy,
                                             block=no_leader)
                        if q.quiesced and not was:
                            node.broadcast_quiesce_enter()
                    else:
                        ticks_dev = ticks
                    n_fast += 1
                    if ticks_dev:
                        si = StepInputs(ticks=ticks, gc_ticks=gc_t)
                        batch.append(
                            (node, g, si, [("tick", ticks_dev)])
                        )
                    else:
                        _tick_bookkeeping(node, ticks + gc_t)
                    continue
            # ---- full path ------------------------------------------
            si = node.drain_step_inputs()
            if self._static_host_only(node):
                host_rows.append((node, si))
                continue
            g = self._attach(node)
            if g is None:
                host_rows.append((node, si))
                continue
            mirror_leader = (
                not self._meta[g].dirty
                and self._mirror[_R_ROLE, g] == int(RaftRole.LEADER)
            )
            plan = self._plan_device(node, si, mirror_leader, g)
            if plan is None:
                host_rows.append((node, si))
                continue
            # every static eligibility check passed: arm the fast lane
            self._meta[g].plan_ok = True
            if not plan and not self._meta[g].dirty:
                _tick_bookkeeping(node, si.ticks + si.gc_ticks)
                continue
            batch.append((node, g, si, plan))

        self._evict_rows_to_host([
            g
            for node, _si in host_rows
            if (g := self._row_of.get(self._row_key(node))) is not None
        ], "host_plan")

        # host path runs under the core lock in colocated mode: update
        # construction for OTHER hosts' rows happens inside launches, so
        # one lock must order both (the per-host parallelism the base
        # engine preserves is deliberately traded away here)
        for node, si in host_rows:
            if node.stopped:
                continue
            u = node.step_with_inputs(si)
            self.stats["host_rows_stepped"] += 1
            if u is not None:
                updates.append((node, u))

        if n_fast:
            self.stats["fast_lane_rows"] = self.stats.get(
                "fast_lane_rows", 0
            ) + n_fast
        self.stats["t_plan_ms"] += int((_time.perf_counter() - _t0) * 1000)
        launched = False
        if batch or self._pending_live:
            if self._pending_live or any(plan for _, _, _, plan in batch):
                _t0 = _time.perf_counter()
                dirty_lane = self._lanes.dirty  # one load; np bool [G]
                self._upload_rows(
                    [
                        (g, node.peer.raft)
                        for node, g, si, plan in batch
                        if dirty_lane[g]
                    ]
                )
                # float ms: lazy upload streams many sub-ms batches and
                # int truncation under-reports the aggregate (same fix
                # as t_up_pack_ms/t_up_scatter_ms)
                self.stats["t_upload_ms"] += (
                    (_time.perf_counter() - _t0) * 1000.0
                )
                self._launch_generation(batch)
                launched = True
            else:
                # pure preload: nothing to step and no routed traffic in
                # flight — skip the launch AND the upload (mass start
                # streams thousands of such registrations; r5 profiling
                # showed the incremental small-batch preload uploads
                # alone cost ~7 ms/replica of the start loop).  Rows
                # stay dirty/host-authoritative and upload lazily in
                # the first generation that actually steps them.
                # Clock bookkeeping matches what the launch path's live
                # loop would have done for these rows: si.ticks still
                # counts quiesce-swallowed ticks, gc_ticks the dropped.
                for node, g, si, plan in batch:
                    _tick_bookkeeping(node, si.ticks + si.gc_ticks)

        # ---- pipeline completion ------------------------------------
        # Depth 1 completes its own generation in-call (the serial
        # loop).  At depth >= 2 a dispatched generation stays in flight
        # until the pipe is FULL at the next dispatch (the room check
        # inside _launch_generation): its readback — requested at
        # dispatch — then rode the tunnel for a full pipeline's worth
        # of host work (plan/upload/dispatch of the following
        # generations), which is what turns the sync floor from a
        # per-generation cost into a hidden one.  An idle call (nothing
        # to launch) drains fully so no generation waits on work that
        # never comes, and a completion that recorded deferred
        # membership actions forces a full drain — they must run
        # before the next dispatch.
        if (not launched) or self._pipeline_depth == 1 or self._deferred:
            while self._inflight:
                updates.extend(self._complete_oldest())
        if self._deferred and not self._inflight:
            updates.extend(self._run_deferred())
        self._flush_free_pending()

        self._drain_update_retries(updates)
        if updates:
            _t0 = _time.perf_counter()
            self._persist_and_process(updates, worker_id)
            self.stats["t_persist_ms"] += int(
                (_time.perf_counter() - _t0) * 1000
            )
        if self._inflight:
            # completion guarantee: a dispatched generation must be
            # merged even if no member ever has work again — poke ONE
            # live node so some worker calls back in (that call,
            # finding nothing to launch, drains the pipeline).  One
            # notify suffices and per-generation fan-out to the whole
            # batch measurably serialized the 1-core bench.  A
            # pending-live-only launch has an EMPTY batch (review
            # finding), so fall back to any alive resident node.
            poked = False
            for node, _g, _si, _plan in batch:
                if not node.stopped and node.notify_work is not None:
                    node.notify_work()
                    poked = True
                    break
            if not poked:
                for g in np.nonzero(self._lanes.alive_mask())[0].tolist():
                    meta = self._meta.get(g)
                    if (
                        meta is not None
                        and not meta.node.stopped
                        and meta.node.notify_work is not None
                    ):
                        meta.node.notify_work()
                        break

    def _sel_cover(self, G, caps, counts, sel_rows, sets):  # hostplane-hot
        """Index-array coverage of the device's single-sync row
        selection: when every host-side merge set is contained in the
        device-selected sections (and the counts fit the warmed
        capacity tier), return the five row->gather-position maps plus
        the vals source rows; ``None`` sends the launch down the exact
        two-sync fallback.  Replaces the old per-row ``*_at`` dict
        builds and ``all(g in …)`` membership scans (O(rows) Python per
        launch — pinned array-at-once by raftlint's host-loop rule)."""
        n_buf, n_slot, n_need, n_append, n_sum = counts
        if not (
            n_buf <= caps["b"] and n_slot <= caps["sl"]
            and n_need <= caps["n"] and n_append <= caps["a"]
            and n_sum <= caps["s"]
        ):
            return None
        rows_buf, rows_slot, rows_need, rows_append, rows_sum = sel_rows
        pos_buf = hostplane.pos_of(G, rows_buf[:n_buf])
        pos_slot = hostplane.pos_of(G, rows_slot[:n_slot])
        pos_need = hostplane.pos_of(G, rows_need[:n_need])
        pos_ring = hostplane.pos_of(G, rows_append[:n_append])
        pos_sum = hostplane.pos_of(G, rows_sum[:n_sum])
        if not (
            hostplane.covered(pos_buf, sets.buf_rows)
            and hostplane.covered(pos_slot, sets.slot_rows)
            and hostplane.covered(pos_need, sets.need_rows)
            and hostplane.covered(pos_ring, sets.append_rows)
            and hostplane.covered(pos_sum, sets.sum_rows)
        ):
            return None
        return (pos_buf, pos_slot, pos_need, pos_ring, pos_sum,
                rows_sum[:n_sum])

    def _bookkeeping_pass(self, live) -> None:
        """Batched tick bookkeeping for one generation's live rows —
        hoisted out of the merge loops so every row pays it exactly
        once, BEFORE any effects merge (and AFTER _lease_pass: lease
        window starts stamp the PRE-launch clock).  Zero-tick rows (a
        launch-rate above the wall-tick cadence makes them the
        majority) skip with two attribute loads; ticked rows advance
        both clocks and take the hint-gated single-lock pending-table
        sweep inside _tick_bookkeeping."""
        meta_get = self._meta.get
        for node, g, si in live:
            if si is None:
                continue
            t = si.ticks + si.gc_ticks
            if t and not node.stopped and meta_get(g) is not None:
                # _tick_bookkeeping, inlined (clock lockstep +
                # hint-gated single-lock pending-table sweep)
                tc = node.tick_count + t
                node.tick_count = tc
                node.peer.raft.tick_count += t
                if tc >= node.pending_deadline_hint[0]:
                    gc_tables(
                        node.pending_tables,
                        node.pending_deadline_hint, tc,
                    )

    def _lane_commit_pass(self, live, flags, pos_sum, pos_buf, pos_slot,
                          pos_need, vals_np, early_done) -> None:
        """Array-side update assembly for commit-only rows — the
        update-lane contract (ISSUE 13; docs/PARITY.md).

        Eligible: live rows with a values entry but no append, no
        host-visible outbox bytes, no proposal slots and no
        snapshot-needing peer — their whole merge is the scalar sync +
        commit advance + update emission, none of which touches the
        detail payload.  One ``plan_update_sync`` pass over the update
        lanes classifies their effects (``U_*`` bits vs the last
        synced words); the residual loop then only writes the scalar
        words that moved and collects ``(node, term, vote, commit,
        entries)`` LANE tuples for ONE batched ``_persist_lane_rows``
        call — no per-row ``get_update`` walk, no per-row Update/
        State/UpdateCommit objects.  On the pipelined path this still
        runs straight off the HEAD blob, so a proposal whose commit
        this generation proves completes without waiting for the
        detail payload (PR 11's early-completion win, kept).

        Rows with scalar-side residue (pending raft msgs / reads /
        drops / unsaved entries / snapshot — a resident-clean row
        should never accumulate any; defense in depth) fall back to
        the classic get_update emission.  Marks completed positions in
        ``early_done`` so the heavy loop skips them."""
        if not live:
            return
        # raftlint: ignore[sync-budget] host-built index array, not a device readback
        gs_all = np.asarray([g for _, g, _ in live], np.int64)
        sum_k = pos_sum[gs_all]
        eligible = (
            (sum_k >= 0)
            & ((flags[gs_all] & _F_APPEND) == 0)
            & (pos_buf[gs_all] < 0)
            & (pos_slot[gs_all] < 0)
            & (pos_need[gs_all] < 0)
        )
        if not eligible.any():
            return
        idx = np.nonzero(eligible)[0]
        gs = gs_all[idx]
        k_sel = sum_k[idx]
        old_w = self._ulanes.words[:, gs]
        uplan = hostplane.plan_update_sync(
            old_w, k_sel, vals_np, self._base[gs]
        )
        if hostplane.PARITY:
            hostplane.check_update_plan_parity(
                old_w, k_sel, vals_np, self._base[gs], uplan
            )
        # rows the loop below skips (stopped/halted mid-flight) are
        # freed and re-seeded at their next upload — bulk write is moot
        # for them, exactly the mirror-table argument
        self._ulanes.words[:, gs] = uplan.words
        ub_l = uplan.ubits.tolist()
        w_term = uplan.words[_R_TERM].tolist()
        w_vote = uplan.words[_R_VOTE].tolist()
        w_com = uplan.words[_R_COMMIT].tolist()
        w_lead = uplan.words[_R_LEADER].tolist()
        w_role = uplan.words[_R_ROLE].tolist()
        # rows eligible for the array-batched persist (hard-state
        # effect, slot-backed store; `eligible` already proved no heavy
        # sections) — the loop only records exceptions; commit rows
        # hand (node, entries) to the post-save apply leg
        so_mask = (
            ((uplan.ubits & (U_STATE | U_COMMIT)) != 0)
            & (self._lane_dbi[gs] >= 0)
        )
        so_drop: List[int] = []
        meta_get = self._meta.get
        lane_rows: List[Tuple] = []
        lane_append = lane_rows.append
        lane_apply: List[Tuple] = []
        fulls: List[Tuple] = []
        for j, ub, term, vote, committed, leader, role, so in zip(
            idx.tolist(), ub_l, w_term, w_vote, w_com, w_lead, w_role,
            so_mask.tolist(),
        ):
            node, g, si = live[j]
            early_done[j] = True
            if node.stopped or meta_get(g) is None:
                if so:
                    so_drop.append(j)
                continue
            r = node.peer.raft
            log = r.log
            im = log.inmem
            # NOTE: open-coded in lockstep with the engine lane branch
            # and the bench twin — see the note in engine._device_step
            if (
                r.msgs or r.ready_to_reads or r.dropped_entries
                or r.dropped_read_indexes or im.snapshot.index
                or im.saved_to + 1 - im.marker < len(im.entries)
            ):
                # residue: the classic path drains it
                if so:
                    so_drop.append(j)
                r.term, r.vote, r.leader_id = term, vote, leader
                r.role = _ROLE_OF[role]
                if committed > log.committed:
                    log.commit_to(committed)
                if (
                    role != _ROLE_LEADER_I
                    and node.device_reads.has_pending()
                ):
                    node.drop_device_reads()
                u = node.peer.get_update(
                    last_applied=node.sm.last_applied
                )
                node.dispatch_dropped(u)
                fulls.append((node, u))
                node._check_leader_change()
                continue
            if ub & U_STATE:
                r.term = term
                r.vote = vote
            if ub & U_LEADER:
                r.leader_id = leader
            if ub & U_ROLE:
                r.role = _ROLE_OF[role]
            if ub & U_LOST_LEAD and node.device_reads.has_pending():
                # leadership lost: confirmations will never arrive.
                # Exact for lane rows — device reads only register off
                # merged outbox messages (a heavy row by definition),
                # so any pending read predates this sync and the
                # losing transition is THIS generation's lane diff
                # (docs/PARITY.md "Update-lane contract").
                node.drop_device_reads()
            if ub & U_COMMIT:
                log.commit_to(committed)
                ce = log.entries_to_apply()
                if so:
                    lane_apply.append((g, node, ce))
                else:
                    lane_append((node, term, vote, committed, ce))
            elif ub & U_STATE and not so:
                # hard-state move without a slot-backed store
                lane_append((node, term, vote, committed, None))
            if ub & U_LEADER:
                node._check_leader_change()
        n_so = 0
        if so_mask.any():
            if so_drop:
                so_mask &= ~np.isin(idx, np.asarray(so_drop))
            ii = np.nonzero(so_mask)[0]
            n_so = len(ii)
            if n_so:
                gs_so = gs[ii]
                dbi = self._lane_dbi[gs_so]
                slots = self._lane_slot[gs_so]
                w = uplan.words
                app_by_db: Dict[int, List] = {}
                if lane_apply:
                    dbi_all = self._lane_dbi
                    for g2, node, ce in lane_apply:
                        app_by_db.setdefault(
                            int(dbi_all[g2]), []
                        ).append((node, ce))
                batches = []
                for d in np.unique(dbi).tolist():
                    m = dbi == d
                    im_ = ii[m]
                    batches.append((
                        self._lane_dbs[d], slots[m], w[_R_TERM][im_],
                        w[_R_VOTE][im_], w[_R_COMMIT][im_], live,
                        idx[im_], app_by_db.get(d, ()),
                    ))
                self._persist_lane_batches(
                    batches, self._last_worker_id
                )
        n = len(lane_rows) + len(fulls) + n_so
        if n:
            self.stats["early_completions"] += n
        if lane_rows:
            self._persist_lane_rows(lane_rows, self._last_worker_id)
        if fulls:
            self._persist_and_process(fulls, self._last_worker_id)

    def _launch_generation(self, batch) -> None:  # sync-hot
        """Assemble, upload and dispatch one generation, request its
        (head, detail) readback, and push the in-flight record — the
        merge tail runs later in _complete_generation (behind the
        device by up to pipeline_depth generations).  Caller holds the
        core lock."""
        # room check: the pipe holds up to depth dispatched-unmerged
        # generations; complete the oldest BEFORE adding a new one so
        # each readback stays in flight across a full pipeline's worth
        # of host work — completing right after dispatch (the naive
        # order) gave every readback only ONE cycle of overlap and
        # left half the floor exposed on the 1-core bench.  (An
        # "express" +1 slot for proposal-carrying waves was tried and
        # REVERTED: exceeding the depth makes the next dispatch drain
        # TWO generations, the second still mid-floor — a systematic
        # in-lock stall that measured worse than the wait it removed.)
        while len(self._inflight) >= self._pipeline_depth:
            room_updates = self._complete_oldest()
            if room_updates:
                self._drain_update_retries(room_updates)
                self._persist_and_process(
                    room_updates, self._last_worker_id
                )
        G, M, E, P, B = self.capacity, self.M, self.E, self.P, self.budget
        # staging keys in ASSEMBLED coordinates: the routed regions
        # (width P*B) come first, host slots after (see _assemble_inbox)
        msg_rows, staging, prop_rows, tick_fed = self._encode_batch(
            batch, slot_offset=P * B
        )
        # compact host-inbox upload: tick-only rows (the overwhelming
        # majority at scale) ride a [G] count vector built into an inbox
        # ON DEVICE; only rows with real host slots upload dense rows
        tick_counts = np.zeros((G,), np.int32)
        sparse: List[Tuple[int, List]] = []
        for node, g, si, plan in batch:
            msgs = msg_rows[g]
            if not msgs:
                continue
            m0 = msgs[0]
            if (
                len(msgs) == 1
                and int(m0.type) == MT_TICK
                and m0.hint == 0
                and m0.hint_high == 0
            ):
                tick_counts[g] = m0.log_index
            else:
                sparse.append((g, msgs))
        if self._tables_dirty:
            self._rebuild_tables()
        # ONE fused [G, 4] host upload for every per-launch [G] input
        # (alive, batch membership, proposal rows, fused tick counts):
        # each separate device_put pays ~10-20 ms of link latency
        combo_np = np.zeros((G, 4), np.int32)
        combo_np[:, _C_TICKS] = tick_counts
        # alive straight off the SoA lanes (attached & clean) — the old
        # per-launch Python scan over the whole meta table cost
        # ~0.5 µs/row (~125 ms/launch at 250k rows).  Stopping rows
        # must neither consume routed traffic nor be routable targets
        # (a stopped-but-undetached leader would keep winning device
        # elections while its host no longer publishes payloads to the
        # entry cache — healthy peers then fail-stop on
        # unreconstructible appends): STOPPED rows can never be
        # lane-alive because every stop path detaches first
        # (stop_shard/unregister, close/unregister_many, _halt_replica
        # all clear the lane before node.stop() runs); a STOPPING
        # not-yet-detached row is cleared here from this generation's
        # plan-loop observations, and for the at-most-one launch that
        # can race the detach's core-lock acquisition a stopping node
        # still merges and publishes payloads (see the stopping-row
        # merge contract below), so routed appends stay
        # reconstructible.
        alive_np = self._lanes.alive_mask()
        gen_stopping = getattr(self, "_gen_stopping", None)
        if gen_stopping:
            alive_np[gen_stopping] = False
        # raftlint: ignore[sync-budget] host-built index arrays, not device readbacks
        batch_gs = np.asarray(
            [g for _, g, _, _ in batch], np.int64
        )
        # raftlint: ignore[sync-budget] host-built index array, not a device readback
        prop_gs = np.asarray(prop_rows, np.int64)
        # ---- fused commit wave decision (ISSUE 15) ------------------
        # Chain K rounds device-side only when the generation's pending
        # work is ROUTABLE: there is multi-round work to do (proposals
        # riding this launch, or routed traffic already in flight whose
        # delivery spawns responses) and nothing in sight mutates
        # membership — stopping rows, deferred actions, quarantined
        # saves, quarantined row slots and escalation holds all fence
        # to the single-round path, which keeps the PR 11 detach-race
        # argument at its proven <=1-launch exposure (a K-round wave
        # would widen it to K).  Tick-only generations with an idle
        # route stay single-round: rounds 2..K would step an empty
        # inbox for every row.
        rounds = 1
        if self._fuse_rounds > 1 and (len(prop_gs) or self._pending_live):
            # multi-round work exists; fuse unless a fence condition
            # holds.  fused_fences counts ONLY this shape — routable
            # work forced single-round — so the stat carries fence
            # signal instead of drowning in idle tick generations
            # (review finding)
            if (
                not gen_stopping
                and not self._deferred
                and not self._free_pending
                and not self._save_quarantine
                and not self._lanes.esc_hold.any()
            ):
                rounds = self._fuse_rounds
                self.stats["fused_waves"] += 1
                self.stats["fused_rounds_stepped"] += rounds
                _metrics.counter("fused_waves_total").add(1)
            else:
                self.stats["fused_fences"] += 1
        combo_np[:, _C_ALIVE] = alive_np
        combo_np[batch_gs, _C_BATCH] = 1
        combo_np[prop_gs, _C_PROP] = 1
        combo = self._put_rows(jnp.asarray(combo_np))
        host_inbox = _host_inbox_from_ticks(combo, M=M, E=E)
        if sparse:
            nsb = _bucket(len(sparse))
            # pad with COPIES of the last real row: _pad_idx repeats its
            # g, and duplicate .at[idx].set() is only benign when every
            # duplicate writes identical data (an empty pad row would
            # race the real one and could zero its messages)
            batches = (
                [m for _, m in sparse]
                + [sparse[-1][1]] * (nsb - len(sparse))
            )
            sub, overflow = S.encode_inbox(batches, M, E)
            assert not overflow, (
                "planner let oversized rows through: "
                f"{[sparse[i][0] for i in overflow if i < len(sparse)]}"
            )
            host_inbox = _scatter_inbox_rows(
                host_inbox,
                self._put_rows(jnp.asarray(
                    _pos_map(G, [g for g, _ in sparse])
                )),
                self._put(sub),
            )

        old_state = self._state
        import time as _time

        from ..profiling import annotate

        if self._pending is None:
            # a prior launch failure consumed the donated pending inbox
            # and could not rebuild it (see the handler below)
            self._pending = self._put_rows(make_inbox(G, P * B, E))
        if _DEBUG_LAUNCH:
            # debug-only sync, FUSED into one device_get (each stray
            # sync is ~100 ms of tunnel time — three separate gets were
            # three round trips even on the debug path): how much PRIOR
            # device work (uploads, materialize, scatters) is in flight?
            import sys as _sys
            _td = _time.perf_counter()
            # raftlint: ignore[sync-budget] debug-gated pre-launch probe, one fused get
            _t1g, _occ_h, _occ_p = jax.device_get((
                old_state.term[:1],
                (host_inbox.mtype != 0).sum(axis=1),
                (self._pending.mtype != 0).sum(axis=1),
            ))
            print(
                f"[pre ] prior-work wait "
                f"{(_time.perf_counter() - _td) * 1000:.0f} ms "
                f"n_occ_max={int((_occ_h + _occ_p).max())} "
                f"occ_mean={float((_occ_h + _occ_p).mean()):.2f} "
                f"ticks_max={int(tick_counts.max())}",
                file=_sys.stderr, flush=True,
            )
        _t0 = _time.perf_counter()
        try:
            with annotate("raft-colocated-step"):
                # fused assemble+step with host/pending donated, and
                # new_state donated into route (dead after the merge):
                # minimizes per-generation device allocations — the
                # remote TPU service frees lazily and allocation-heavy
                # cadences exhausted it (see _assemble_and_step)
                new_state, out = _assemble_and_step(
                    old_state, host_inbox, self._pending, combo,
                    out_capacity=self.O,
                )
                self.stats["t_dev_step_ms"] = self.stats.get(
                    "t_dev_step_ms", 0
                ) + int((_time.perf_counter() - _t0) * 1000)
                _t1 = _time.perf_counter()
                merged, regions, stats_dev, packed_dev, flags_dev = (
                    _route_step(
                        old_state, new_state, out, self._dest_dev,
                        self._rank_dev, combo, PB=P * B, E=E, budget=B,
                    )
                )
                self.stats["t_dev_route_ms"] = self.stats.get(
                    "t_dev_route_ms", 0
                ) + int((_time.perf_counter() - _t1) * 1000)
        except BaseException:
            # self._pending was DONATED above; leaving the deleted
            # buffer in place would poison every later generation with
            # "Array has been deleted" after one transient launch
            # failure (review finding).  Clear FIRST, then try to
            # rebuild — the rebuild itself allocates and can fail under
            # the same RESOURCE_EXHAUSTED this guards against, so a
            # None sentinel (rebuilt lazily at the next launch) must
            # never be skipped over.  Dropping the in-flight routed
            # traffic is raft-safe message loss.
            self._pending = None
            self._pending_live = False
            try:
                self._pending = self._put_rows(make_inbox(G, P * B, E))
            except Exception:  # noqa: BLE001 — next launch rebuilds
                pass
            raise
        # from here the generation is the new device truth: the next
        # launch (possibly dispatched before this one merges) chains on
        # merged/regions.  A failure past this point poisons the chain
        # and takes the pipeline-reset recovery instead.
        self._pending = regions
        self._state = merged
        try:
            with annotate("raft-colocated-select"):
                _t1 = _time.perf_counter()
                # the wave's one commit-proving readback, requested NOW
                # and collected at merge time: flags + delivered +
                # counts + row ids + vals in each round's head, heavy
                # sections in its detail (see _select_and_blob).  Every
                # round's pair is requested at dispatch, so the whole
                # wave's blobs ride the tunnel in ONE latency-floor
                # window while the host assembles and dispatches the
                # NEXT generation.
                caps = self._tier_caps(self._sel_tier)
                merged_l, out_l = [merged], [out]
                head_l, detail_l = [], []

                def _sel(merged_k, out_k, stats_k, packed_k, flags_k):
                    head_dev, detail_dev = _select_and_blob(
                        merged_k, out_k, stats_k, packed_k, flags_k,
                        combo, CAP_B=caps["b"], CAP_SL=caps["sl"],
                        CAP_N=caps["n"], CAP_A=caps["a"],
                        CAP_S=caps["s"], HOST_OFF=P * B,
                    )
                    for dev in (head_dev, detail_dev):
                        fn = getattr(dev, "copy_to_host_async", None)
                        if fn is not None:
                            fn()
                    head_l.append(head_dev)
                    detail_l.append(detail_dev)

                _sel(merged, out, stats_dev, packed_dev, flags_dev)
                # ---- fused wave: rounds 2..K, dispatched back-to-back
                # with NO host sync between rounds.  Each round is the
                # exact single-round program chain (assemble over the
                # previous round's routed regions with an EMPTY host
                # inbox — ticks and proposals fed once, in round 1 —
                # then step, route, select), so a K-round wave is
                # bit-exact with K serial launches by construction and
                # reuses the warmed executables: no new XLA programs,
                # no tier recompiles (the r5 compile-time finding rules
                # out a monolithic K-round mega-program here).
                for _k in range(1, rounds):
                    host_k = _host_inbox_from_ticks(
                        self._zero_combo, M=M, E=E
                    )
                    new_k, out_k = _assemble_and_step(
                        self._state, host_k, self._pending, combo,
                        out_capacity=self.O,
                    )
                    merged_k, regions_k, stats_k, packed_k, flags_k = (
                        _route_step(
                            self._state, new_k, out_k, self._dest_dev,
                            self._rank_dev, combo, PB=P * B, E=E,
                            budget=B,
                        )
                    )
                    self._pending = regions_k
                    self._state = merged_k
                    merged_l.append(merged_k)
                    out_l.append(out_k)
                    _sel(merged_k, out_k, stats_k, packed_k, flags_k)
                self.stats["t_dev_sel_ms"] = self.stats.get(
                    "t_dev_sel_ms", 0
                ) + int((_time.perf_counter() - _t1) * 1000)
        except BaseException:
            self._reset_after_pipeline_failure()
            raise
        self.stats["t_device_ms"] += int((_time.perf_counter() - _t0) * 1000)
        self.stats["launches"] += 1
        self.stats["device_steps"] += rounds
        self.stats["device_rows_stepped"] += len(batch)
        if _DEBUG_LAUNCH:
            import sys as _sys

            print(
                f"[launch {self.stats['launches']}] tier="
                f"{self._sel_tier} batch={len(batch)} rounds={rounds} "
                f"inflight={len(self._inflight) + 1}",
                file=_sys.stderr, flush=True,
            )
        self._inflight.append(_InFlightGen(
            batch=batch, staging=staging, alive_np=alive_np,
            batch_gs=batch_gs, prop_gs=prop_gs, caps=caps,
            merged=merged_l, out=out_l, head_dev=head_l,
            detail_dev=detail_l, t_req=_time.monotonic(),
            tick_fed=tick_fed, rounds=rounds,
        ))

    def _parse_head(self, head, caps, G: int, nw: int):  # sync-hot
        """Host-side parse of one round's head blob (_select_and_blob's
        head layout): flags, packed delivered bits, route stats, the
        five section counts, the five selected-row-id sections and the
        values block."""
        flags = head[:G]
        delivered_bits = (
            head[G:G + G * nw].view(np.uint32).reshape(G, nw)
        )  # [G, ceil(O/32)] u32
        _parse = [G + G * nw]

        def take(n, shape=None):
            part = head[_parse[0]:_parse[0] + n]
            _parse[0] += n
            return part.reshape(shape) if shape is not None else part

        rstats = take(6)
        sel_counts = take(5)
        sel_rows = (
            take(caps["b"]), take(caps["sl"]), take(caps["n"]),
            take(caps["a"]), take(caps["s"]),
        )
        sel_vals = take(caps["s"] * N_VALS, (caps["s"], N_VALS))
        return flags, delivered_bits, rstats, sel_counts, sel_rows, sel_vals

    def _parse_detail(self, det, caps):  # sync-hot
        """Host-side parse of one round's detail blob, re-padding the
        routed-region slot columns the device omitted (always unused
        for slot bookkeeping — forwarded PROPOSE never rides the
        routed regions)."""
        O, W, M, E = self.O, self.W, self.M, self.E
        PB = self.P * self.budget
        _dp = [0]

        def dtake(n, shape):
            part = det[_dp[0]:_dp[0] + n]
            _dp[0] += n
            return part.reshape(shape)

        buf_np = dtake(
            caps["b"] * O * N_FIELDS_BUF, (caps["b"], O, N_FIELDS_BUF)
        )
        sel_slot_base = dtake(caps["sl"] * M, (caps["sl"], M))
        sel_slot_term = dtake(caps["sl"] * M, (caps["sl"], M))
        sel_ent_drop = dtake(caps["sl"] * M * E, (caps["sl"], M, E))
        need_np = dtake(caps["n"] * self.P, (caps["n"], self.P))
        ring_t = dtake(caps["a"] * W, (caps["a"], W))
        ring_c = dtake(caps["a"] * W, (caps["a"], W))
        slot_base = np.concatenate([
            np.full((caps["sl"], PB), SLOT_UNUSED_I, np.int32),
            sel_slot_base,
        ], axis=1)
        slot_term = np.concatenate([
            np.zeros((caps["sl"], PB), np.int32), sel_slot_term
        ], axis=1)
        ent_drop = np.concatenate([
            np.zeros((caps["sl"], PB, E), np.int32), sel_ent_drop
        ], axis=1)
        return (buf_np, slot_base, slot_term, ent_drop, need_np,
                ring_t, ring_c)

    def _merge_intermediate_round(  # sync-hot
        self, rec, rnd, caps, sets, flags, delivered_bits, sel_counts,
        sel_rows, sel_vals, needs_max, touched, esc_seen,
    ) -> None:
        """Merge ONE intermediate round of a fused wave, in two legs:

        * HEAVY rows (appends, host-visible outbox bytes, round-1
          proposal slots, snapshot-needing rows) take the per-row
          merge: scalar sync from THIS round's values, append
          reconstruction against THIS round's ring (entries published
          to the shard cache round-by-round so a receiver's round k+1
          reconstructs exactly as across k+1 serial launches), message
          attachment against THIS round's delivered bits.  Their ONE
          get_update rides the final round (``touched``).  The
          snapshot-need SECTION itself is final-round-only — the need
          flag re-fires while the condition persists (benign refire) —
          but need-flagged rows still sync state here.
        * every other row of the round's values block takes the LANE
          pass — the same ``_lane_commit_pass`` a single-round
          generation runs.  This is load-bearing, not an optimization:
          the flags word's F_CHANGED is a per-ROUND delta, so a commit
          advance or granted vote landing in an intermediate round is
          INVISIBLE to the final round's flags — only the lane diff
          (new words vs last HOST sync) sees it.  Skipping this leg
          stranded mid-wave commits' futures forever (found by the
          one-readback test's first soak)."""
        import time as _time

        G = self.capacity
        n_buf_d, n_slot_d, n_need_d, n_append_d, n_sum_d = (
            int(x) for x in sel_counts
        )
        for key, need in (
            ("b", max(n_buf_d, len(sets.buf_rows))),
            ("sl", max(n_slot_d, len(sets.slot_rows))),
            ("n", max(n_need_d, len(sets.need_rows))),
            ("a", max(n_append_d, len(sets.append_rows))),
            ("s", max(n_sum_d, len(sets.sum_rows))),
        ):
            needs_max[key] = max(needs_max[key], need)
        slot_live = len(sets.slot_rows) if rnd == 0 else 0
        has_heavy = bool(
            len(sets.buf_rows) or len(sets.append_rows) or slot_live
        )
        if not has_heavy and not len(sets.sum_rows):
            # nothing host-visible happened this round: its detail
            # payload is never read (same contract as a pure
            # commit/tick generation)
            self.stats["detail_skipped"] = self.stats.get(
                "detail_skipped", 0
            ) + 1
            return
        _t0 = _time.perf_counter()
        cover = self._sel_cover(
            G, caps,
            (n_buf_d, n_slot_d, n_need_d, n_append_d, n_sum_d),
            sel_rows, sets,
        )
        if cover is not None:
            pos_buf, pos_slot, pos_need, pos_ring, pos_sum, _src = cover
            vals_np = sel_vals[:n_sum_d]
            if has_heavy:
                det = self._collect_blob(rec.detail_dev[rnd], rec.t_req)
                (buf_np, slot_base, slot_term, ent_drop, _need_np,
                 ring_t, ring_c) = self._parse_detail(det, caps)
            else:
                buf_np = slot_base = slot_term = ent_drop = None
                ring_t = ring_c = None
                self.stats["detail_skipped"] = self.stats.get(
                    "detail_skipped", 0
                ) + 1
        else:
            # exact host-side selection for this round (capacity
            # overflow): one extra sync round trip, charged one fresh
            # floor — identical to the single-round fallback
            self.stats["sel_fallbacks"] = (
                self.stats.get("sel_fallbacks", 0) + 1
            )
            self.stats["readback_windows"] += 1
            idx4 = _build_idx4(
                sets.buf_rows.tolist(), sets.slot_rows.tolist(),
                sets.need_rows.tolist(), sets.append_rows.tolist(),
            )
            _tq = _time.monotonic()
            detail, vals_np = _fetch_detail_vals(
                rec.merged[rnd], rec.out[rnd], idx4,
                sets.sum_rows.tolist(), self._put, self.O,
                self.M + self.P * self.budget, self.E, self.P, self.W,
                allow_fused=False,
            )
            self._floor_wait(_tq)
            if detail is not None:
                (buf_np, slot_base, slot_term, ent_drop, _need_np,
                 ring_t, ring_c) = detail
            else:
                buf_np = slot_base = slot_term = ent_drop = None
                ring_t = ring_c = None
            pos_buf = hostplane.pos_of(G, sets.buf_rows)
            pos_ring = hostplane.pos_of(G, sets.append_rows)
            pos_slot = hostplane.pos_of(G, sets.slot_rows)
            pos_need = hostplane.pos_of(G, sets.need_rows)
            pos_sum = hostplane.pos_of(G, sets.sum_rows)
        from .engine import SLOT_DROPPED

        stage_map = rec.staging if rnd == 0 else {}
        vals_l = vals_np.tolist() if vals_np is not None else None
        heavy_gs = set(sets.buf_rows.tolist())
        heavy_gs.update(sets.append_rows.tolist())
        # need-flagged rows sync state here (their SECTION waits for
        # the final round — benign refire); without this a
        # need-only row's mid-wave state change would strand like any
        # other non-final F_CHANGED
        heavy_gs.update(sets.need_rows.tolist())
        if rnd == 0:
            heavy_gs.update(sets.slot_rows.tolist())
        for g in sorted(heavy_gs):
            meta = self._meta.get(g)
            if meta is None or meta.node.stopped or vals_l is None:
                continue
            node = meta.node
            r = node.peer.raft
            base = int(self._base[g])
            k = int(pos_sum[g])
            if k < 0:
                continue  # heavy rows always carry values; defense
            sv = vals_l[k]
            term, vote, committed, leader, role, last = sv[:6]
            committed += base
            last += base
            # scalar sync BEFORE the merge — same order as the final
            # round's loop (see the noop-barrier note there)
            r.term, r.vote, r.leader_id = term, vote, leader
            r.role = _ROLE_OF[role]
            if (flags[g] & _F_APPEND) and int(pos_ring[g]) >= 0:
                try:
                    stamped = self._merge_appends(
                        r, g, int(sv[_R_APPEND_LO]) + base, last,
                        stage_map.get(g, {}),
                        int(pos_slot[g]) if rnd == 0 else -1,
                        slot_base, slot_term, ent_drop,
                        ring_t[int(pos_ring[g])],
                        ring_c[int(pos_ring[g])],
                        fallback=self._cache_lookup,
                        barrier=(
                            int(sv[_R_BARRIER_IDX]) + base,
                            int(sv[_R_BARRIER_TERM]),
                        ),
                        base=base,
                    )
                except RuntimeError:
                    od = self._entry_cache.get(r.shard_id)
                    _log.critical(
                        "[%d:%d] routed append reconstruction failed "
                        "in fused round %d; halting replica (cache "
                        "keys tail: %s)",
                        r.shard_id, r.replica_id, rnd,
                        list(od.keys())[-12:] if od else [],
                        exc_info=True,
                    )
                    self._halt_replica(g)
                    continue
                self._cache_put(r.shard_id, stamped)
            if committed > r.log.committed:
                r.log.commit_to(committed)
            if (
                role != int(RaftRole.LEADER)
                and node.device_reads.has_pending()
            ):
                node.drop_device_reads()
            if int(pos_buf[g]) >= 0 and buf_np is not None:
                bits = delivered_bits[g]
                dr = (
                    (bits[self._dw_word] >> self._dw_shift) & 1
                ).astype(bool)
                self._attach_messages(
                    r, node, buf_np[int(pos_buf[g])], int(sv[_R_COUNT]),
                    stage_map.get(g, {}), delivered_row=dr, base=base,
                )
            sk = int(pos_slot[g]) if rnd == 0 else -1
            if sk >= 0 and slot_base is not None:
                sb = slot_base[sk]
                drop = ent_drop[sk]
                for slot, ents in stage_map.get(g, {}).items():
                    if sb[slot] == SLOT_DROPPED:
                        r.dropped_entries.extend(ents)
                    elif sb[slot] >= 0:
                        r.dropped_entries.extend(
                            e for i_e, e in enumerate(ents)
                            if drop[slot, i_e]
                        )
            touched[g] = node
        # ---- lane leg: every OTHER row with values this round --------
        # The same lane commit pass a single-round generation runs —
        # heavy rows fall out of its eligibility mask by construction
        # (append flag / buf / slot / need positions), rows already
        # deferred to escalation recovery are excluded, and rows it
        # syncs update the lanes so the NEXT round's diff composes.
        if vals_np is not None and len(sets.sum_rows):
            live_k: List[Tuple] = [
                (node, g, si)
                for node, g, si, _plan in rec.batch
                if g not in esc_seen
            ]
            live_set = {g for _, g, _ in live_k}
            meta_get = self._meta.get
            for g in sets.live_other.tolist():
                if g in esc_seen or g in live_set:
                    continue
                meta = meta_get(g)
                if meta is not None:
                    live_k.append((meta.node, g, None))
            pos_slot_k = (
                pos_slot if rnd == 0
                else hostplane.pos_of(G, sets.slot_rows)
            )
            self._lane_commit_pass(
                live_k, flags, pos_sum, pos_buf, pos_slot_k, pos_need,
                vals_np, np.zeros((len(live_k),), bool),
            )
            # bulk mirror + update-lane write for the round's sum rows
            # — the final round's bulk write only covers rows flagged
            # in the FINAL round, and F_CHANGED is a per-round delta:
            # without this, a leader elected mid-wave left a
            # permanently stale leader=0 mirror, which blocked quiesce
            # parking on the whole shard (found by test_scale's
            # cold-kill gate).  Lane-pass rows were already written —
            # identical values, idempotent; heavy rows sync here.
            gs_sum = sets.sum_rows
            sum_pos = pos_sum[gs_sum]
            ok = sum_pos >= 0
            if ok.any():
                gs_ok = gs_sum[ok].astype(np.int64)
                w = vals_np[sum_pos[ok], :6].T
                # lease arm/disarm on role transitions observed THIS
                # round, probed against the PRE-write mirror — the
                # final _lease_pass compares against the mirror too,
                # and this write is about to refresh it, so a mid-wave
                # election win would otherwise never arm its
                # CheckQuorum lease (found by
                # test_device_lease_reads_colocated: a resident leader
                # whose win landed inside a wave held lease 0 forever)
                chg = np.nonzero(
                    w[_R_ROLE] != self._mirror[_R_ROLE, gs_ok]
                )[0]
                for i in chg.tolist():
                    g2 = int(gs_ok[i])
                    meta2 = self._meta.get(g2)
                    if meta2 is None or meta2.node.stopped:
                        continue
                    r2 = meta2.node.peer.raft
                    if (
                        int(w[_R_ROLE, i]) == _ROLE_LEADER_I
                        and r2.check_quorum
                    ):
                        self._lease.arm(g2, r2.election_timeout, 0)
                    else:
                        self._lease.disarm(g2)
                self._mirror[:6, gs_ok] = w
                w_abs = w.astype(np.int64)
                b_abs = self._base[gs_ok]
                w_abs[_R_COMMIT] += b_abs
                w_abs[_R_LAST] += b_abs
                self._ulanes.words[:, gs_ok] = w_abs
        self.stats["t_updates_ms"] += int(
            (_time.perf_counter() - _t0) * 1000
        )

    def _complete_generation(self, rec: _InFlightGen) -> List[Tuple]:  # sync-hot
        """Merge one in-flight generation: collect each round's head
        (the earliest commit-proving sync), complete commit-only rows
        straight off the FINAL round's head, and read detail payloads
        (all in flight since dispatch) only for rounds with heavy
        sections.  A fused wave (rec.rounds > 1, ISSUE 15) unpacks its
        per-round delivered bits and heavy sections round by round —
        intermediate rounds merge appends/outboxes/round-1 slots into
        the scalar rafts, the final round runs the full single-round
        tail (lease, bookkeeping, lane commit pass, get_update) over
        the wave's end state, so every row emits at most ONE update
        per wave.  Caller holds the core lock; generations complete in
        dispatch order (_complete_oldest)."""
        import time as _time

        G, M, E, P, B = self.capacity, self.M, self.E, self.P, self.budget
        batch, staging, caps = rec.batch, rec.staging, rec.caps
        alive_np, batch_gs, prop_gs = (
            rec.alive_np, rec.batch_gs, rec.prop_gs
        )
        K = rec.rounds
        nw = (self.O + 31) // 32
        updates: List[Tuple] = []
        esc_seen: set = set()
        # rows whose scalar state an intermediate round already
        # mutated: they owe ONE get_update at the end of the wave even
        # if the final round left them quiet
        touched: Dict[int, object] = {}
        needs_max = {"b": 0, "sl": 0, "n": 0, "a": 0, "s": 0}
        empty_gs = np.zeros((0,), np.int64)
        # ONE readback window per generation: every round's blobs were
        # requested together at dispatch and share rec.t_req, so the
        # first collect pays the floor remainder and the rest land in
        # the same round trip — the one-readback-per-wave budget the
        # fused-round smoke asserts
        self.stats["readback_windows"] += 1
        for rnd in range(K):
            final = rnd == K - 1
            round_props = prop_gs if rnd == 0 else empty_gs
            _t0 = _time.perf_counter()
            _tc = _time.monotonic()
            head = self._collect_blob(rec.head_dev[rnd], rec.t_req)
            if rnd == 0 and self._pipeline_depth > 1:
                # host-side work done between the D2H request
                # (dispatch) and this collect ran concurrently with
                # the readback — the double-buffering win, visible
                # without hardware
                overlap = max(0.0, _tc - rec.t_req)
                if self._sync_floor_s > 0:
                    overlap = min(overlap, self._sync_floor_s)
                self.stats["pipeline_overlap_s"] += overlap
                _metrics.counter(
                    "pipeline_overlap_seconds_total"
                ).add(overlap)
            self.stats["t_dev_blob_ms"] = self.stats.get(
                "t_dev_blob_ms", 0
            ) + int((_time.perf_counter() - _t0) * 1000)
            self.stats["t_device_ms"] += int(
                (_time.perf_counter() - _t0) * 1000
            )
            (flags, delivered_bits, rstats, sel_counts, sel_rows,
             sel_vals) = self._parse_head(head, caps, G, nw)
            (sel_rows_buf, sel_rows_slot, sel_rows_need,
             sel_rows_append, sel_rows_sum) = sel_rows
            if final:
                self._behind = (flags & _F_PEERS_BEHIND) != 0
                self._pending_live = int(rstats[0]) > 0
            self.stats["routed_delivered"] += int(rstats[0])
            self.stats["routed_host_carried"] += int(rstats[5])
            self.stats["routed_dropped"] += int(
                rstats[1] + rstats[2] + rstats[3]
            )
            # per-cause breakdown (RouteStats order; r4 verdict weak
            # #5: the aggregate hid which drop class dominates)
            self.stats["routed_dropped_off_device"] = self.stats.get(
                "routed_dropped_off_device", 0
            ) + int(rstats[1])
            self.stats["routed_dropped_budget"] = self.stats.get(
                "routed_dropped_budget", 0
            ) + int(rstats[2])
            self.stats["routed_dropped_ring"] = self.stats.get(
                "routed_dropped_ring", 0
            ) + int(rstats[3])

            # ---- merge row sets (array-at-once) ----------------------
            # ONE vectorized pass over the [G] flags word classifies
            # every row of the round (ops/hostplane.py).  The scalar
            # twins remain the parity oracle
            # (DRAGONBOAT_TPU_HOSTPLANE_PARITY runs both every round).
            sets = hostplane.build_merge_sets(
                flags, alive_np, batch_gs, round_props, G=G
            )
            hostplane.record_generation(
                flags, alive_np, batch_gs, round_props, G
            )
            if hostplane.PARITY:
                hostplane.check_merge_parity(
                    flags, alive_np, batch_gs, round_props, sets, G=G
                )

            # ---- escalations: DEFERRED to the pipeline drain ---------
            # The device already restored escalated rows (suppress mask
            # in _route_step) and suppressed their outboxes; later
            # rounds/generations re-stepped them from the restored
            # state, so the recovery (evict + scalar replay) runs only
            # at depth 0 (see _apply_escalation).  A wave records each
            # escalated row ONCE: the batch inputs are replayed only
            # when round 1 suppressed them — a row escalating first in
            # a LATER round consumed its inputs in round 1, so only
            # the routed-only (input-less) recovery applies, exactly
            # the cross-generation contract.
            n_esc = len(sets.esc_batch_pos) + len(sets.esc_other)
            if n_esc:
                self.stats["escalations"] += n_esc
                for i in sets.esc_batch_pos.tolist():
                    node, g, si, _plan = batch[i]
                    if g in esc_seen:
                        continue
                    esc_seen.add(g)
                    self._deferred.append(
                        ("esc", node, g, si if rnd == 0 else None)
                    )
                for g in sets.esc_other.tolist():
                    if g in esc_seen:
                        continue
                    meta = self._meta.get(g)
                    if meta is not None:
                        esc_seen.add(g)
                        # routed-only inputs: discarded (raft-safe)
                        self._deferred.append(("esc", meta.node, g, None))

            if not final:
                self._merge_intermediate_round(
                    rec, rnd, caps, sets, flags, delivered_bits,
                    sel_counts, sel_rows, sel_vals, needs_max, touched,
                    esc_seen,
                )
                continue

            # ================= FINAL round ===========================
            break  # fall through to the final-round tail below

        stage_map = staging if K == 1 else {}
        rnd = K - 1
        # ---- live rows: batch rows + any resident row with effects ----
        esc_keep = np.ones((len(batch),), bool)
        # every batch row whose device row escalated in ANY round of
        # the wave is excluded from the final merge (its recovery is
        # the deferred evict+replay above)
        esc_keep[[
            i for i, (_n, g, _s, _p) in enumerate(batch)
            if g in esc_seen
        ]] = False
        live: List[Tuple] = [
            (node, g, si)
            for (node, g, si, plan), k in zip(batch, esc_keep.tolist())
            if k
        ]
        live_gs = {g for _, g, _ in live}
        for g in sets.live_other.tolist():
            meta = self._meta.get(g)
            if meta is not None:
                live.append((meta.node, g, None))
                live_gs.add(g)
        # rows an intermediate round touched that the final round left
        # quiet still owe their get_update (merged appends/messages
        # must persist and dispatch)
        for g, node in touched.items():
            if g not in live_gs and g not in esc_seen:
                live.append((node, g, None))
                live_gs.add(g)

        buf_rows = sets.buf_rows
        append_rows = sets.append_rows
        slot_rows = sets.slot_rows
        need_rows = sets.need_rows
        sum_rows = sets.sum_rows
        n_buf_d, n_slot_d, n_need_d, n_append_d, n_sum_d = (
            int(x) for x in sel_counts
        )
        _t0 = _time.perf_counter()
        # device-selected detail (the split-blob fast path): the head
        # already carries counts/row-ids/vals for the rows the DEVICE
        # selected with the same flag logic; verify the host's sets are
        # covered and fall back to an exact two-sync gather when not
        # (capacity overflow, or a row the device's live approximation
        # missed).  Coverage and row->gather-position maps are index
        # arrays (hostplane.pos_of/covered) — the old per-row dict
        # builds and `all(g in …)` membership scans were O(rows) Python
        cover = self._sel_cover(
            G, caps,
            (n_buf_d, n_slot_d, n_need_d, n_append_d, n_sum_d),
            (sel_rows_buf, sel_rows_slot, sel_rows_need,
             sel_rows_append, sel_rows_sum),
            sets,
        )
        dev_ok = cover is not None
        early_done = np.zeros((len(live),), bool)
        lease_done = False
        if dev_ok:
            pos_buf, pos_slot, pos_need, pos_ring, pos_sum, sum_src = cover
            if K > 1:
                # the DEVICE's slot selection keys off the wave-wide
                # prop mask (combo rides every round), but host slot
                # bookkeeping is round-1-only and round 1's
                # intermediate merge already consumed it — the final
                # round's host semantics (empty slot set) rule, or the
                # loop would index slot sections it never collected
                pos_slot = hostplane.pos_of(G, slot_rows)
            # live rows only: the padded capacity tail is garbage the
            # merge loop never indexes, and converting it cost tens of
            # ms/launch at storm-tier capacities (review finding)
            sel_vals = sel_vals[:n_sum_d]
            vals_np = sel_vals
            # lease pass BEFORE bookkeeping: lease window starts must
            # stamp the PRE-launch clock (see _lease_pass); then ONE
            # batched bookkeeping pass for the whole generation
            self._lease_pass(live, flags, vals_np, pos_sum, rec.tick_fed)
            lease_done = True
            self._bookkeeping_pass(live)
            # ---- EARLY completion: the commit-proving prefix --------
            # A live row with values but NO append/outbox/slot/need
            # sections (the common shape: a leader whose routed acks
            # just advanced commit, a follower applying) needs nothing
            # from the detail payload — the LANE pass diffs its words
            # against the update lanes, syncs only what moved and
            # persists the whole set in one batched lane save NOW, so
            # proposals complete from the earliest sync that proves
            # their commit instead of waiting for the detail to land
            # and the heavy merge tail to run.
            self._lane_commit_pass(
                live, flags, pos_sum, pos_buf, pos_slot, pos_need,
                vals_np, early_done,
            )
            need_detail = bool(
                len(buf_rows) or len(append_rows)
                or len(slot_rows) or len(need_rows)
            )
            if need_detail:
                det = self._collect_blob(rec.detail_dev[rnd], rec.t_req)
                (buf_np, slot_base, slot_term, ent_drop, need_np,
                 ring_t, ring_c) = self._parse_detail(det, caps)
            else:
                # pure commit/tick generation: the detail payload is
                # never read — on hardware its bytes still rode the
                # same round trip, and nothing here waits for them
                self.stats["detail_skipped"] = self.stats.get(
                    "detail_skipped", 0
                ) + 1
                buf_np = slot_base = slot_term = ent_drop = None
                need_np = ring_t = ring_c = None
        else:
            # exact host-side selection (the r5 two-sync path) — an
            # extra sync round trip; the floor shim charges it one
            # fresh floor from request time
            self.stats["sel_fallbacks"] = (
                self.stats.get("sel_fallbacks", 0) + 1
            )
            self.stats["readback_windows"] += 1
            idx4 = _build_idx4(
                buf_rows.tolist(), slot_rows.tolist(),
                need_rows.tolist(), append_rows.tolist(),
            )
            _tq = _time.monotonic()
            # the kernel ran on the ASSEMBLED inbox (host slots + routed
            # regions), so the out slot arrays are M + P*B wide
            detail, vals_np = _fetch_detail_vals(
                rec.merged[rnd], rec.out[rnd], idx4, sum_rows.tolist(),
                self._put,
                self.O, M + P * B, E, P, self.W, allow_fused=False,
            )
            self._floor_wait(_tq)
            if detail is not None:
                (buf_np, slot_base, slot_term, ent_drop, need_np, ring_t,
                 ring_c) = detail
            else:
                buf_np = slot_base = slot_term = ent_drop = need_np = None
                ring_t = ring_c = None
            # position maps over the HOST-ordered gather sections (the
            # same order _build_idx4 packed them in)
            pos_buf = hostplane.pos_of(G, buf_rows)
            pos_ring = hostplane.pos_of(G, append_rows)
            pos_slot = hostplane.pos_of(G, slot_rows)
            pos_need = hostplane.pos_of(G, need_rows)
            pos_sum = hostplane.pos_of(G, sum_rows)
            sum_src = sum_rows
        # tier selection: promote immediately to the smallest warmed
        # tier that fits this generation's needs — the max over EVERY
        # round of the wave (overflow used the exact fallback above,
        # once per overflowing round); demote only after 64
        # consecutive launches that would have fit the lower tier
        needs = {
            "b": max(needs_max["b"], n_buf_d, len(buf_rows)),
            "sl": max(needs_max["sl"], n_slot_d, len(slot_rows)),
            "n": max(needs_max["n"], n_need_d, len(need_rows)),
            "a": max(needs_max["a"], n_append_d, len(append_rows)),
            "s": max(needs_max["s"], n_sum_d, len(sum_rows)),
        }
        need_tier = len(_SEL_TIERS) - 1
        for t in range(len(_SEL_TIERS)):
            c = self._tier_caps(t)
            if all(needs[k] <= c[k] for k in c):
                need_tier = t
                break
        if need_tier > self._sel_tier:
            self._sel_tier = need_tier
            self._sel_fit_streak = 0
        elif need_tier < self._sel_tier:
            self._sel_fit_streak += 1
            if self._sel_fit_streak >= 64:
                self._sel_tier = need_tier
                self._sel_fit_streak = 0
        else:
            self._sel_fit_streak = 0
        self.stats["t_detail_ms"] += int(
            (_time.perf_counter() - _t0) * 1000
        )
        # device-plane lease evidence (ROADMAP 4b): advance each batch
        # row's CheckQuorum window mirror and anchor the scalar voting
        # remotes when the quorum-active flag holds — BEFORE the bulk
        # mirror write below so role transitions are still observable.
        # The dev_ok path already ran this pass (pre-early-commit, so
        # window starts stamp the pre-launch clock); running it again
        # would feed tick_fed twice and halve the modeled window period.
        # On the exact-fallback path the bookkeeping + lane passes run
        # here instead (detail and position maps only just landed) —
        # same order as dev_ok: lease, bookkeeping, lane commit.
        if not lease_done:
            self._lease_pass(live, flags, vals_np, pos_sum, rec.tick_fed)
            self._bookkeeping_pass(live)
            if vals_np is not None:
                self._lane_commit_pass(
                    live, flags, pos_sum, pos_buf, pos_slot, pos_need,
                    vals_np, early_done,
                )
        # one C-level conversion for the merge loop's 10-ints-per-row
        # reads (numpy scalar -> int costs ~100 ns each)
        vals_l = vals_np.tolist() if vals_np is not None else None

        from .engine import SLOT_DROPPED

        _t0 = _time.perf_counter()
        # ---- per-row effect merge, batch-indexed ---------------------
        # Everything the loop used to look up per row (gather positions
        # via the *_at dicts, flag probes, bases, delivered-bit unpack,
        # limit checks, mirror writes) is gathered ONCE here over the
        # [*, G] arrays; the residual per-row body below only mutates
        # the Python raft objects it must (scalar sync, append merge,
        # update construction) — see ops/hostplane.py.
        # raftlint: ignore[sync-budget] host-built index array, not a device readback
        gs_m = np.asarray([g for _, g, _ in live], np.int64)
        n_live = len(gs_m)
        if n_live:
            sum_k = pos_sum[gs_m]
            buf_k = pos_buf[gs_m]
            slot_k = pos_slot[gs_m]
            need_k = pos_need[gs_m]
            ring_k = pos_ring[gs_m]
            app_l = ((flags[gs_m] & _F_APPEND) != 0).tolist()
            bases_l = self._base[gs_m].tolist()
            sum_k_l = sum_k.tolist()
            buf_k_l = buf_k.tolist()
            slot_k_l = slot_k.tolist()
            need_k_l = need_k.tolist()
            ring_k_l = ring_k.tolist()
            # delivered bits unpacked for ALL buf rows in one shot (the
            # per-row word/shift unpack cost ~1-2 µs a row)
            has_buf = buf_k >= 0
            nb = int(has_buf.sum())
            if nb:
                bits = delivered_bits[gs_m[has_buf]]
                dr_pack = (
                    (bits[:, self._dw_word] >> self._dw_shift) & 1
                ).astype(bool)
                dr_at = np.full((n_live,), -1, np.int32)
                dr_at[has_buf] = np.arange(nb, dtype=np.int32)
                dr_at_l = dr_at.tolist()
            # bulk mirror write for every row the loop will merge
            # (rows it then skips — stopped/halted — are freed and
            # re-seeded at their next upload, so the write is moot)
            in_sum = sum_k >= 0
            if vals_np is not None and in_sum.any():
                self._mirror[:6, gs_m[in_sum]] = (
                    vals_np[sum_k[in_sum], :6].T
                )
                # update lanes follow for the HEAVY rows the loop below
                # syncs per-row (lane-pass rows were already written —
                # identical values, idempotent), absolute frame: the
                # next generation's lane diff must see what was synced
                w_abs = vals_np[sum_k[in_sum], :6].T.astype(np.int64)
                b_abs = self._base[gs_m[in_sum]]
                w_abs[_R_COMMIT] += b_abs
                w_abs[_R_LAST] += b_abs
                self._ulanes.words[:, gs_m[in_sum]] = w_abs
        if vals_np is not None and len(sum_src):
            # fast-lane invalidation, batch-wide: rows approaching an
            # int32 lane limit or streaming a snapshot re-run the full
            # plan (the only plan facts a DEVICE step can change;
            # everything else arrives via the host queues, which the
            # fast lane checks each launch).  Safe-side: clearing
            # plan_ok for a row the loop later skips only forces one
            # extra full plan.  (The fallback gather pads vals to a
            # bucket; only the first len(sum_src) rows are real.)
            v = vals_np[: len(sum_src)]
            over = (
                (v[:, _R_TERM] > _LIM_SOFT) | (v[:, _R_LAST] > _LIM_SOFT)
            )
            if over.any():
                # raftlint: ignore[sync-budget] host numpy row ids, not a device readback
                self._lanes.plan_ok[np.asarray(sum_src)[over]] = False
        if len(need_rows):
            self._lanes.plan_ok[need_rows] = False
        # (g, p, lane-or-None, pid, ss_index) — see _send_snapshots
        snapshot_sends: List[Tuple[int, int, Optional[int], int, int]] = []
        for j, (node, g, si) in enumerate(live):
            if early_done[j]:
                continue  # fully handled by the early commit pass
            # a STOPPING node still merges and persists this launch's
            # results: its device acks were already routed to peers in
            # this very launch, and dropping the corresponding append
            # persist would let an acked entry vanish on restart — the
            # follower then wedges forever on the by-design
            # reject<=match floor (r4 chaos finding: kill racing a
            # launch left a replica acked-at-23 with a WAL at 22).
            # Only truly STOPPED nodes (logdb closing) are skipped; the
            # alive mask already keeps stopping rows out of the NEXT
            # launch.
            if node.stopped or self._meta.get(g) is None:
                continue
            r = node.peer.raft
            base = bases_l[j]  # the shard's shared base
            # (tick bookkeeping already ran in _bookkeeping_pass)
            k = sum_k_l[j]
            if k < 0:
                # no final-round flags, no slots — but a row an
                # intermediate round of the wave touched (merged
                # appends, attached messages, dropped slots) still
                # owes its ONE wave-end update: the scalar sync ran in
                # its last heavy round, so only the emission remains
                if g in touched:
                    u = node.peer.get_update(
                        last_applied=node.sm.last_applied
                    )
                    node.dispatch_dropped(u)
                    updates.append((node, u))
                    node._check_leader_change()
                # else: the row only ticked
                continue
            sv = vals_l[k]
            term, vote, committed, leader, role, last = sv[:6]
            committed += base
            last += base
            # scalar sync BEFORE the merge: the noop-barrier-vs-lost-
            # payload distinction in _merge_appends needs the POST-step
            # role (a row that just won its election self-appends the
            # barrier; its host mirror still says candidate)
            r.term, r.vote, r.leader_id = term, vote, leader
            r.role = RaftRole(role)
            if app_l[j]:
                try:
                    stamped = self._merge_appends(
                        r, g, int(sv[_R_APPEND_LO]) + base, last,
                        stage_map.get(g, {}), slot_k_l[j], slot_base,
                        slot_term, ent_drop, ring_t[ring_k_l[j]],
                        ring_c[ring_k_l[j]],
                        fallback=self._cache_lookup,
                        barrier=(
                            int(sv[_R_BARRIER_IDX]) + base,
                            int(sv[_R_BARRIER_TERM]),
                        ),
                        base=base,
                    )
                except RuntimeError:
                    # fail-stop THIS replica only (divergence policy);
                    # aborting the loop would strand every other row's
                    # merge and spread the inconsistency
                    od = self._entry_cache.get(r.shard_id)
                    _log.critical(
                        "[%d:%d] routed append reconstruction failed; "
                        "halting replica (cache keys tail: %s)",
                        r.shard_id, r.replica_id,
                        list(od.keys())[-12:] if od else [],
                        exc_info=True,
                    )
                    self._halt_replica(g)
                    continue
                self._cache_put(r.shard_id, stamped)
            if committed > r.log.committed:
                r.log.commit_to(committed)
            if (
                role != int(RaftRole.LEADER)
                and node.device_reads.has_pending()
            ):
                node.drop_device_reads()
            if buf_k_l[j] >= 0:
                self._attach_messages(
                    r, node, buf_np[buf_k_l[j]], int(sv[_R_COUNT]),
                    stage_map.get(g, {}), delivered_row=dr_pack[dr_at_l[j]],
                    base=base,
                )
            sk = slot_k_l[j]
            if sk >= 0:
                sb = slot_base[sk]
                drop = ent_drop[sk]
                for slot, ents in stage_map.get(g, {}).items():
                    if sb[slot] == SLOT_DROPPED:
                        r.dropped_entries.extend(ents)
                    elif sb[slot] >= 0:
                        r.dropped_entries.extend(
                            e for i_e, e in enumerate(ents)
                            if drop[slot, i_e]
                        )
            if need_k_l[j] >= 0:
                self._send_snapshots(r, g, need_np[need_k_l[j]],
                                     snapshot_sends)
            u = node.peer.get_update(last_applied=node.sm.last_applied)
            node.dispatch_dropped(u)
            updates.append((node, u))
            node._check_leader_change()
        self.stats["t_updates_ms"] += int((_time.perf_counter() - _t0) * 1000)

        lanes = [t for t in snapshot_sends if t[2] is not None]
        if lanes:
            # applied to the CURRENT state handle — possibly one
            # generation past the one that flagged the need.  Benign:
            # the need flag re-fires while the condition persists, the
            # lane write is idempotent, and at most one extra probe
            # volley reaches a peer already being streamed to
            self._state = _set_remote_snapshot(
                self._state,
                self._put(jnp.asarray(_pad_idx([t[0] for t in lanes]))),
                self._put(jnp.asarray(_pad_idx([t[1] for t in lanes]))),
                self._put(jnp.asarray(_pad_idx([t[2] for t in lanes]))),
            )
        below = [t for t in snapshot_sends if t[2] is None]
        if below:
            # the durable snapshot sits below the shard base (see
            # VectorStepEngine._send_snapshots): these rows take a host
            # excursion — a membership mutation, so it runs at the next
            # depth-0 point (_apply_snapshot_below), never mid-merge
            self._deferred.append(("below", below))

        if self._pending_live:
            # in-flight routed traffic: wake every ALIVE resident
            # node's engine so some worker launches again and the
            # messages are consumed (lane scan — the notify itself is
            # per-node, but dirty rows no longer pay a Python probe)
            for g in np.nonzero(self._lanes.alive_mask())[0].tolist():
                meta = self._meta.get(g)
                if meta is not None and meta.node.notify_work is not None:
                    meta.node.notify_work()
        return updates


class _ColocatedFacade(IStepEngine):
    """Per-NodeHost view of the shared core (the IStepEngine each
    ExecEngine drives).  Tracks shard -> replica so ``detach(shard_id)``
    — the IStepEngine contract — releases only THIS host's replica."""

    def __init__(self, core: ColocatedVectorEngine):
        self.core = core
        self._replica_of: Dict[int, int] = {}

    @property
    def stats(self):
        return self.core.stats

    def step_shards(self, nodes, worker_id: int) -> None:
        for n in nodes:
            self._replica_of[n.shard_id] = n.replica_id
        self.core.step_shards(nodes, worker_id)

    def device_coordinate(self, shard_id: int):
        return self.core.device_coordinate(
            shard_id, self._replica_of.get(shard_id)
        )

    def device_chip_count(self) -> int:
        return self.core.device_chip_count()

    def detach(self, shard_id: int) -> None:
        rid = self._replica_of.pop(shard_id, None)
        if rid is not None:
            self.core.detach_replica(shard_id, rid)

    def detach_many(self, shard_ids) -> None:
        pairs = []
        for s in shard_ids:
            rid = self._replica_of.pop(s, None)
            if rid is not None:
                pairs.append((s, rid))
        if pairs:
            self.core.detach_replicas(pairs)


class ColocatedEngineGroup:
    """Product plug point: one group per colocated cluster.

        group = ColocatedEngineGroup(capacity=64, P=5, budget=2)
        cfg.expert.step_engine_factory = group.factory   # every member
    """

    def __init__(self, **kw):
        self._kw = kw
        self._core: Optional[ColocatedVectorEngine] = None
        self._lock = threading.Lock()

    @property
    def core(self) -> Optional[ColocatedVectorEngine]:
        return self._core

    def factory(self, nodehost) -> _ColocatedFacade:
        with self._lock:
            if self._core is None:
                self._core = ColocatedVectorEngine(**self._kw)
            return _ColocatedFacade(self._core)
