"""Registry of every jitted device-plane entry point in ``ops/``.

The device plane's load-bearing contracts — pure int32 math (kernel.py),
no host round-trips inside compiled programs, G-last internal layout,
real buffer donation — existed only as docstrings until this registry:
``analysis/jaxcheck.py`` walks it, traces each entry point with the
canonical small geometry below, and machine-checks the jaxprs and
lowerings against policy (docs/ANALYSIS.md "Device-plane audit").  The
runtime half (``analysis/jitcheck.py``) snapshots each entry's jit
trace-cache size after engine warmup and reports post-warmup retraces.

Keeping the registry IN ops/ (next to the entry points) is deliberate:
adding a ``@jax.jit`` here without registering it fails the auditor's
``unregistered-jit`` rule, so the list cannot silently rot.

Canonical geometry: every dimension is given a DISTINCT size so the
auditor can identify axes by size alone (the G-last rule finds the G
axis as "the axis of size CANON['G']"); G is the only size that may
appear in a batched array, so keep the others unique and small.

Scope note (r6): ``ops/hostplane.py`` — the array-at-once host-plane
machinery — is deliberately numpy-only and carries NO jitted entry
points, so it registers nothing here; the auditor's
``unregistered-jit`` AST scan covers it like every other ops/ module,
and any future ``@jax.jit`` added there must be registered or the
scan fails.  Its per-row discipline is enforced separately by
raftlint's ``host-loop`` rule (docs/ANALYSIS.md).

Scope note (r9): the update-lane plane (``hostplane.UpdateLanes`` /
``plan_update_sync``, ``ops/engine._plan_lane_words``, the batched
persist paths in both merge tails — ISSUE 13) is host-side numpy over
the ALREADY-read-back values blob: no new device programs, no new
jitted entry points, nothing to register.  The same ``unregistered-
jit`` scan and the ``host-loop`` rule (now spanning ``ops/engine.py``)
gate it.
"""
from __future__ import annotations

import functools
from typing import Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from . import colocated as C
from . import engine as E
from . import kernel as K
from . import route as R
from .types import I32, make_inbox, make_out, make_state

# canonical audit geometry — sizes chosen pairwise-distinct (see module
# docstring); PB = P*budget is the colocated routed-region width and
# M_ASM = M + PB the assembled inbox width
CANON = dict(G=64, P=3, W=8, M=5, E=2, O=8, budget=2)
CANON["PB"] = CANON["P"] * CANON["budget"]
CANON["M_ASM"] = CANON["M"] + CANON["PB"]


class EntryPoint(NamedTuple):
    """One audited jitted callable.

    ``build`` returns ``(args, static_kwargs)`` at the canonical
    geometry; ``donate`` mirrors the jit declaration's donate_argnums
    (the donation audit recomputes the expected alias count from the
    built args); ``g_last`` opts into the internal-layout rule (only
    sound for programs whose WHOLE body runs G-trailing); ``runtime``
    marks entries the recompile sentry watches (audit-only wrappers,
    which production never calls, are excluded so their cold caches
    don't read as permanent warmup)."""

    name: str
    fn: Callable
    build: Callable[[], Tuple[tuple, dict]]
    donate: Tuple[int, ...] = ()
    g_last: bool = False
    runtime: bool = True


def _g():
    return CANON["G"]


def _state(rows: Optional[int] = None):
    return make_state(rows or _g(), CANON["P"], CANON["W"])


def _inbox(M: int, rows: Optional[int] = None):
    return make_inbox(rows or _g(), M, CANON["E"])


def _out(M: int):
    return make_out(_g(), CANON["P"], M, CANON["E"], CANON["O"])


def _combo():
    return jnp.zeros((_g(), 4), I32)


def _idx(n: int):
    return jnp.zeros((n,), I32)


def _idx4(b: int):
    return jnp.zeros((4, b), I32)


# -- per-entry builders ------------------------------------------------
def _b_step():
    return (_state(), _inbox(CANON["M"])), dict(out_capacity=CANON["O"])


def _b_step_internal():
    st = K.state_to_internal(_state())
    ib = K._inbox_to_internal(_inbox(CANON["M"]))
    return (st, ib), dict(out_capacity=CANON["O"])


def _b_scatter_rows():
    pos = jnp.full((_g(),), -1, I32)
    return (_state(), pos, _state(4)), {}


def _b_select_rows():
    return (jnp.zeros((_g(),), bool), _state(), _state()), {}


def _b_gather_rows():
    return (_state(), _idx(4)), {}


def _b_summarize_flags():
    return (_state(), _state(), _out(CANON["M"])), {}


def _b_gather_vals():
    return (_state(), _out(CANON["M"]), _idx(4)), {}


def _b_gather_detail():
    return (_state(), _out(CANON["M"]), _idx4(4)), {}


def _b_gather_detail_vals():
    return (_state(), _out(CANON["M"]), _idx4(4), _idx(4)), {}


def _b_set_remote_snapshot():
    return (_state(), _idx(1), _idx(1), _idx(1)), {}


def _b_assemble_inbox():
    return (
        _inbox(CANON["M"]),
        _inbox(CANON["PB"]),
        jnp.ones((_g(),), bool),
    ), {}


def _b_assemble_and_step():
    return (
        _state(), _inbox(CANON["M"]), _inbox(CANON["PB"]), _combo(),
    ), dict(out_capacity=CANON["O"])


def _b_route_step():
    dest = jnp.full((_g(), CANON["P"]), -1, I32)
    rank = jnp.zeros((_g(), CANON["P"]), I32)
    return (
        _state(), _state(), _out(CANON["M_ASM"]), dest, rank, _combo(),
    ), dict(PB=CANON["PB"], E=CANON["E"], budget=CANON["budget"])


def _b_select_and_blob():
    G = _g()
    nwords = (CANON["O"] + 31) // 32
    return (
        _state(),
        _out(CANON["M_ASM"]),
        jnp.zeros((6,), I32),
        jnp.zeros((G, nwords), jnp.uint32),
        jnp.zeros((G,), I32),
        _combo(),
    ), dict(
        CAP_B=16, CAP_SL=G, CAP_N=8, CAP_A=G, CAP_S=G,
        HOST_OFF=CANON["PB"],
    )


def _b_zero_inbox_rows():
    return (_inbox(CANON["M_ASM"]), jnp.zeros((_g(),), bool)), {}


def _b_host_inbox_from_ticks():
    return (_combo(),), dict(M=CANON["M"], E=CANON["E"])


def _b_scatter_inbox_rows():
    pos = jnp.full((_g(),), -1, I32)
    return (_inbox(CANON["M"]), pos, _inbox(CANON["M"], 4)), {}


# audit-only jit of the bench/consensus round: route() itself is a pure
# function callers jit (bench.py compiles its own); this wrapper puts
# its program under the same dtype/transfer audit as everything else
_routed_round_audit = functools.partial(
    jax.jit,
    static_argnames=(
        "out_capacity", "budget", "base", "propose_leaders", "propose_n",
    ),
)(R.routed_round)

# audit-only jit of the fused commit wave (ISSUE 15): K routed rounds
# chained inside one program.  rounds=2 at the canonical geometry keeps
# the trace cheap while exercising the round-to-round chaining (the
# dtype/transfer findings of any K>1 are identical — the body is K
# copies of the same round program).
_fused_rounds_audit = functools.partial(
    jax.jit,
    static_argnames=(
        "rounds", "out_capacity", "budget", "base", "propose_leaders",
        "propose_n",
    ),
)(R.fused_rounds)

# routed_round inbox width must satisfy base + P*budget == M
_M_ROUTE = CANON["M_ASM"]
_BASE_ROUTE = _M_ROUTE - CANON["PB"]


def _b_routed_round():
    dest = jnp.full((_g(), CANON["P"]), -1, I32)
    rank = jnp.zeros((_g(), CANON["P"]), I32)
    return (
        _state(), _inbox(_M_ROUTE), dest, rank,
    ), dict(
        out_capacity=CANON["O"], budget=CANON["budget"],
        base=_BASE_ROUTE, propose_leaders=True,
    )


def _b_fused_rounds():
    dest = jnp.full((_g(), CANON["P"]), -1, I32)
    rank = jnp.zeros((_g(), CANON["P"]), I32)
    return (
        _state(), _inbox(_M_ROUTE), dest, rank,
    ), dict(
        rounds=2, out_capacity=CANON["O"], budget=CANON["budget"],
        base=_BASE_ROUTE, propose_leaders=True,
    )


ENTRY_POINTS: Tuple[EntryPoint, ...] = (
    # kernel
    EntryPoint("kernel.step", K.step, _b_step),
    EntryPoint(
        "kernel.step_internal", K.step_internal, _b_step_internal,
        g_last=True,
    ),
    # engine helpers (the per-launch gather/scatter plumbing)
    EntryPoint("engine._scatter_rows", E._scatter_rows, _b_scatter_rows),
    EntryPoint("engine._select_rows", E._select_rows, _b_select_rows),
    EntryPoint("engine._gather_rows", E._gather_rows, _b_gather_rows),
    EntryPoint(
        "engine._summarize_flags", E._summarize_flags, _b_summarize_flags
    ),
    EntryPoint("engine._gather_vals", E._gather_vals, _b_gather_vals),
    EntryPoint("engine._gather_detail", E._gather_detail, _b_gather_detail),
    EntryPoint(
        "engine._gather_detail_vals",
        E._gather_detail_vals,
        _b_gather_detail_vals,
    ),
    EntryPoint(
        "engine._set_remote_snapshot",
        E._set_remote_snapshot,
        _b_set_remote_snapshot,
    ),
    # colocated launch pipeline
    EntryPoint(
        "colocated._assemble_inbox", C._assemble_inbox, _b_assemble_inbox
    ),
    EntryPoint(
        "colocated._assemble_and_step",
        C._assemble_and_step,
        _b_assemble_and_step,
        donate=(1, 2),
    ),
    EntryPoint(
        "colocated._route_step", C._route_step, _b_route_step, donate=(1,)
    ),
    EntryPoint(
        "colocated._select_and_blob", C._select_and_blob, _b_select_and_blob
    ),
    EntryPoint(
        "colocated._zero_inbox_rows", C._zero_inbox_rows, _b_zero_inbox_rows
    ),
    EntryPoint(
        "colocated._host_inbox_from_ticks",
        C._host_inbox_from_ticks,
        _b_host_inbox_from_ticks,
    ),
    EntryPoint(
        "colocated._scatter_inbox_rows",
        C._scatter_inbox_rows,
        _b_scatter_inbox_rows,
    ),
    # route (audit-only jit wrappers; bench jits its own copies)
    EntryPoint(
        "route.routed_round", _routed_round_audit, _b_routed_round,
        runtime=False,
    ),
    EntryPoint(
        "route.fused_rounds", _fused_rounds_audit, _b_fused_rounds,
        runtime=False,
    ),
)


def runtime_entry_points():
    """(name, jitted fn) pairs the recompile sentry watches."""
    return [(ep.name, ep.fn) for ep in ENTRY_POINTS if ep.runtime]


def mesh_entry_points(mesh) -> Tuple[EntryPoint, ...]:
    """Audit entries for the SHARDED launch path over ``mesh`` — the
    jaxcheck transfer/dtype rules extended to the multi-chip programs
    (docs/MULTICHIP.md; the ISSUE-12 "zero cross-device host hops"
    gate).  Not part of the static ENTRY_POINTS tuple because a mesh
    needs visible devices: bench.phase_multichip, the multichip smoke
    and tests/test_multichip.py audit these explicitly under forced
    host devices.  CANON['G'] must divide the mesh (64 covers 1-8)."""
    import numpy as np

    G = CANON["G"]
    if G % mesh.size:
        raise ValueError(f"CANON G={G} must divide mesh size {mesh.size}")

    step_sharded = K.make_step_sharded(
        mesh, _state(), _inbox(CANON["M"]), out_capacity=CANON["O"]
    )
    round_sharded = R.make_sharded_round(
        mesh, M=_M_ROUTE, E=CANON["E"], out_capacity=CANON["O"],
        budget=CANON["budget"], xbudget=4, base=_BASE_ROUTE,
        propose_leaders=True,
    )

    def _b_step_sharded():
        return (_state(), _inbox(CANON["M"])), {}

    def _b_round_sharded():
        # strided tables so every device has genuine cross-device edges
        # in the traced program (an all-local trace would never reach
        # the collective lane)
        dl = jnp.asarray(
            np.zeros((G, CANON["P"]), np.int32)
        )
        dd = jnp.asarray(
            (np.arange(G)[:, None] % mesh.size * np.ones(
                (1, CANON["P"]), np.int64
            )).astype(np.int32)
        )
        rank = jnp.zeros((G, CANON["P"]), I32)
        return (_state(), _inbox(_M_ROUTE), dl, dd, rank), {}

    return (
        EntryPoint(
            "kernel.step_sharded", step_sharded, _b_step_sharded,
            runtime=False,
        ),
        EntryPoint(
            "route.sharded_round", round_sharded, _b_round_sharded,
            runtime=False,
        ),
    )
