"""Vectorized device ops: the TPU raft step kernel and its host glue.

Layout (SURVEY.md §7 build step 4):
  types.py  — SoA DeviceState / Inbox / DeviceOut tensor layouts
  kernel.py — the jit/vmap step function (the "raft.Step as MXU work" core)
  sync.py   — oracle<->row conversion, message staging, parity helpers
"""
from .types import DeviceOut, DeviceState, Inbox, make_inbox, make_out, make_state
from .kernel import step

__all__ = [
    "DeviceOut",
    "DeviceState",
    "Inbox",
    "make_inbox",
    "make_out",
    "make_state",
    "step",
]
