"""Vectorized device ops: the TPU raft step kernel and its host glue.

Layout (SURVEY.md §7 build step 4):
  types.py  — SoA DeviceState / Inbox / DeviceOut tensor layouts
  kernel.py — the jit/vmap step function (the "raft.Step as MXU work" core)
  sync.py   — oracle<->row conversion, message staging, parity helpers
  engine.py — VectorStepEngine: the device-backed IStepEngine
"""
from .types import DeviceOut, DeviceState, Inbox, make_inbox, make_out, make_state
from .kernel import step
from .engine import VectorStepEngine, vector_step_engine_factory

__all__ = [
    "DeviceOut",
    "DeviceState",
    "Inbox",
    "make_inbox",
    "make_out",
    "make_state",
    "step",
    "VectorStepEngine",
    "vector_step_engine_factory",
]
