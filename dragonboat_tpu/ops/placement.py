"""Device placement for the ops plane: one mesh-aware selection helper.

Before this module every launch site hardcoded ``jax.devices()[0]``
(``ops/engine.py``, both bench phases), which is exactly the
single-chip assumption ROADMAP item 3 calls the missing multiplier.
All device/mesh selection now routes through here:

* :func:`default_device` — the single-device engine's home chip.
  Env-overridable (``DRAGONBOAT_TPU_DEVICE=<index>``); defaults to
  device 0, i.e. exactly the old behavior.
* :func:`groups_mesh` — a 1-D ``jax.sharding.Mesh`` over the first N
  devices with the canonical ``"groups"`` axis name (SURVEY §2: the
  groups axis is the ONLY parallel axis).  ``DRAGONBOAT_TPU_MESH_DEVICES``
  selects N; unset/0/1 returns None (single-device mode).
* :func:`device_of_row` / :func:`rows_per_device` — the row-block
  placement contract shared by the sharded route tables
  (``route.build_route_tables_mesh``), the engine's striped row
  allocator and the balance plane's device coordinates: device ``d``
  owns the contiguous row block ``[d*Gl, (d+1)*Gl)``.

Keeping the block contract in ONE module matters: the shard_map'd
launch slices state by block, the route tables classify device
boundaries by block, and the engine reports ``device_coordinate`` by
block — three layers that silently corrupt cross-chip traffic if they
ever disagree.
"""
from __future__ import annotations

import os
from typing import Optional


def default_device(jax_module=None):
    """The engine/bench home device.  ``DRAGONBOAT_TPU_DEVICE=<i>``
    overrides the index; the default (0) is byte-for-byte the old
    hardcoded ``jax.devices()[0]`` behavior."""
    if jax_module is None:
        import jax as jax_module
    devs = jax_module.devices()
    idx = int(os.environ.get("DRAGONBOAT_TPU_DEVICE", "0") or 0)
    if not 0 <= idx < len(devs):
        raise ValueError(
            f"DRAGONBOAT_TPU_DEVICE={idx} out of range: "
            f"{len(devs)} device(s) visible"
        )
    return devs[idx]


def groups_mesh(n_devices: Optional[int] = None, jax_module=None):
    """A 1-D mesh over the groups axis, or None for single-device mode.

    ``n_devices`` defaults to ``DRAGONBOAT_TPU_MESH_DEVICES`` (unset,
    0 or 1 → None, preserving current single-device behavior).
    """
    if jax_module is None:
        import jax as jax_module
    if n_devices is None:
        n_devices = int(
            os.environ.get("DRAGONBOAT_TPU_MESH_DEVICES", "0") or 0
        )
    if n_devices <= 1:
        return None
    from jax.sharding import Mesh
    import numpy as np

    devs = jax_module.devices()
    if len(devs) < n_devices:
        raise ValueError(
            f"mesh wants {n_devices} devices, only {len(devs)} visible"
        )
    return Mesh(np.asarray(devs[:n_devices]), ("groups",))


def rows_per_device(capacity: int, n_devices: int) -> int:
    """Block size of the row-block placement; capacity must divide."""
    if n_devices <= 0 or capacity % n_devices:
        raise ValueError(
            f"capacity {capacity} must divide over {n_devices} devices"
        )
    return capacity // n_devices


def device_of_row(g: int, capacity: int, n_devices: int) -> int:
    """Device coordinate hosting row ``g`` under the block contract."""
    return g // rows_per_device(capacity, n_devices)
