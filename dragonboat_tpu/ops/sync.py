"""Oracle <-> device-row conversion and message staging.

Three jobs:

  1. ``state_from_rafts`` — pack scalar ``Raft`` oracles into a
     ``DeviceState`` (parity tests, engine bootstrap, escalation return).
  2. ``raft_to_row`` / ``assert_row_matches`` — read a row back out for
     differential comparison or host-side replay.
  3. ``encode_inbox`` / ``decode_out`` — Message lists <-> tensor batches.

The slot layout contract: peer slots hold the union of voters,
non-votings and witnesses sorted by replica id; empty slots are 0.  The
same ordering governs the oracle's sorted broadcast loops, so device and
host iterate peers identically.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..pb import Message, MessageType
from ..raft.raft import Raft, RaftRole
from .types import (
    DeviceOut,
    DeviceState,
    F_COMMIT,
    F_HINT,
    F_HINT_HIGH,
    F_LOG_INDEX,
    F_LOG_TERM,
    F_MTYPE,
    F_N_ENTRIES,
    F_REJECT,
    F_SRC_SLOT,
    F_TERM,
    F_TO,
    KIND_NON_VOTING,
    KIND_VOTER,
    KIND_WITNESS,
    Inbox,
    make_state_np,
)

import jax.numpy as jnp


def peer_layout(raft: Raft) -> List[Tuple[int, int]]:
    """[(replica_id, kind)] sorted by id — the canonical slot order."""
    out = []
    for pid in raft.remotes:
        out.append((pid, KIND_VOTER))
    for pid in raft.non_votings:
        out.append((pid, KIND_NON_VOTING))
    for pid in raft.witnesses:
        out.append((pid, KIND_WITNESS))
    return sorted(out)


def state_from_rafts(
    rafts: Sequence[Raft], P: int, W: int,
    bases: Optional[Sequence[int]] = None,
    pad_to: int = 0,
) -> DeviceState:
    """Pack oracles into a DeviceState, copying the full volatile state
    (not just a fresh boot) so escalated rows can return to the device.

    ``bases``: optional per-row int64 index base subtracted from every
    log-index field (committed/last/first/match/next/snap) so rows whose
    absolute indexes exceed int32 stay device-steppable — the engine's
    64-bit story (the host WAL is 64-bit throughout; the device works in
    a rebased window).  Each base MUST be a multiple of W so the ring
    slot of an index is invariant under the shift ((abs-base) % W ==
    abs % W), and must not exceed any live index quantity of its row.

    ``pad_to``: pad the row axis to this length by repeating the last
    row, IN NUMPY — callers used to pad with eager jnp slice/repeat/
    concat per field, and on a remote TPU link every first-per-shape
    eager op is a fresh tiny compile (~31 fields x 3 ops x ~0.4 s ate
    46% of the r4 10k-shard election as "upload" time).
    """
    G = len(rafts)
    # pure-NUMPY staging end to end: make_state_np never touches the
    # device, so packing costs no device->host readbacks (31 per batch
    # before — the dominant upload cost on a remote TPU link, r4 SCALE)
    base_cols = make_state_np(
        G,
        P,
        W,
        shard_ids=[r.shard_id for r in rafts],
        replica_ids=[r.replica_id for r in rafts],
        peer_ids=_peer_ids(rafts, P),
        peer_kinds=_peer_kinds(rafts, P),
    )
    # int64 staging: absolute indexes may exceed int32 before the shift
    cols: Dict[str, np.ndarray] = {
        k: v.astype(np.int64) for k, v in base_cols.items()
    }
    for g, r in enumerate(rafts):
        _fill_row(cols, g, r, P, W)
        if bases is not None and bases[g]:
            b = int(bases[g])
            assert b % W == 0, f"row {g}: base {b} not a multiple of W"
            for k in ("committed", "last_index", "first_index"):
                cols[k][g] -= b
            for k in ("match", "next_idx", "snap_index"):
                row = cols[k][g]
                row[row > 0] -= b
                # stale lanes below the base (a non-leader's boot-time
                # next=1 etc.) clamp to the 0 sentinel: they are dead
                # state that the next election resets anyway, and
                # negative lanes would wrap int32
                row[row < 0] = 0
    out: Dict[str, np.ndarray] = {}
    for k, v in cols.items():
        if (v > 2**31 - 1).any() or (v < -(2**31)).any():
            raise OverflowError(
                f"state field {k} exceeds int32 after rebase"
            )
        v = v.astype(np.int32)
        if pad_to > v.shape[0]:
            v = np.concatenate(
                [v, np.repeat(v[-1:], pad_to - v.shape[0], axis=0)]
            )
        out[k] = v
    return DeviceState(**{k: jnp.asarray(v) for k, v in out.items()})


def _peer_ids(rafts, P):
    G = len(rafts)
    out = np.zeros((G, P), np.int32)
    for g, r in enumerate(rafts):
        lay = peer_layout(r)
        if len(lay) > P:
            raise ValueError(f"row {g}: {len(lay)} peers > P={P}")
        for s, (pid, _) in enumerate(lay):
            out[g, s] = pid
    return out


def _peer_kinds(rafts, P):
    G = len(rafts)
    out = np.zeros((G, P), np.int32)
    for g, r in enumerate(rafts):
        for s, (_, kind) in enumerate(peer_layout(r)):
            out[g, s] = kind
    return out


def _fill_row(cols, g, r: Raft, P, W):
    cols["election_timeout"][g] = r.election_timeout
    cols["heartbeat_timeout"][g] = r.heartbeat_timeout
    cols["check_quorum"][g] = int(r.check_quorum)
    cols["pre_vote"][g] = int(r.pre_vote)
    cols["term"][g] = r.term
    cols["vote"][g] = r.vote
    cols["leader_id"][g] = r.leader_id
    cols["role"][g] = int(r.role)
    cols["committed"][g] = r.log.committed
    last = r.log.last_index()
    first = r.log.first_index()
    cols["last_index"][g] = last
    cols["first_index"][g] = first
    try:
        cols["base_term"][g] = r.log.term(first - 1) if first > 1 else 0
    except Exception:
        cols["base_term"][g] = 0
    cols["election_tick"][g] = r.election_tick
    cols["heartbeat_tick"][g] = r.heartbeat_tick
    cols["rand_timeout"][g] = r.randomized_election_timeout
    cols["timeout_seq"][g] = r._timeout_seq
    cols["pending_cc"][g] = int(r.pending_config_change)
    cols["transfer_target"][g] = r.leader_transfer_target
    for s, (pid, _) in enumerate(peer_layout(r)):
        rm = r.get_remote(pid)
        cols["match"][g, s] = rm.match
        cols["next_idx"][g, s] = rm.next
        cols["rstate"][g, s] = int(rm.state)
        cols["snap_index"][g, s] = rm.snapshot_index
        cols["active"][g, s] = int(rm.active)
        if pid in r.votes:
            cols["granted"][g, s] = 1 if r.votes[pid] else 2
    win_lo = max(first, last - W + 1)
    for idx in range(win_lo, last + 1):
        t = r.log.term(idx)
        cols["ring_term"][g, idx % W] = t
        ents = r.log._get_entries(idx, idx + 1, 2**62)
        cols["ring_cc"][g, idx % W] = int(bool(ents and ents[0].is_config_change()))


ROW_SCALARS = (
    "term",
    "vote",
    "leader_id",
    "role",
    "committed",
    "last_index",
    "election_tick",
    "heartbeat_tick",
    "rand_timeout",
    "timeout_seq",
    "pending_cc",
    "transfer_target",
)
ROW_PEER = ("match", "next_idx", "rstate", "snap_index", "active", "granted")


def raft_to_row(r: Raft, P: int, W: int) -> dict:
    """The oracle's state in row form (for comparisons)."""
    cols = {
        k: np.zeros((1,), np.int32)
        for k in ROW_SCALARS
        + ("election_timeout", "heartbeat_timeout", "check_quorum", "pre_vote",
           "base_term", "first_index")
    }
    for k in ROW_PEER:
        cols[k] = np.zeros((1, P), np.int32)
    cols["ring_term"] = np.zeros((1, W), np.int32)
    cols["ring_cc"] = np.zeros((1, W), np.int32)
    _fill_row(cols, 0, r, P, W)
    return {k: v[0] for k, v in cols.items()}


def row_diff(state: DeviceState, g: int, r: Raft) -> List[str]:
    """Human-readable field mismatches between device row g and oracle."""
    want = raft_to_row(r, state.P, state.W)
    errs = []
    for k in ROW_SCALARS:
        got = int(np.asarray(getattr(state, k))[g])
        if got != int(want[k]):
            errs.append(f"{k}: device={got} oracle={int(want[k])}")
    for k in ROW_PEER:
        got = np.asarray(getattr(state, k))[g]
        if not np.array_equal(got, want[k]):
            errs.append(f"{k}: device={got.tolist()} oracle={want[k].tolist()}")
    # ring: compare only the in-window slice
    last = r.log.last_index()
    first = r.log.first_index()
    win_lo = max(first, last - state.W + 1)
    ring_d = np.asarray(state.ring_term)[g]
    ring_cc_d = np.asarray(state.ring_cc)[g]
    for idx in range(win_lo, last + 1):
        if ring_d[idx % state.W] != r.log.term(idx):
            errs.append(
                f"ring_term[{idx}]: device={ring_d[idx % state.W]} "
                f"oracle={r.log.term(idx)}"
            )
        if ring_cc_d[idx % state.W] != want["ring_cc"][idx % state.W]:
            errs.append(f"ring_cc[{idx}] mismatch")
    return errs


# ---------------------------------------------------------------------------
# inbox / outbox staging
# ---------------------------------------------------------------------------
INBOX_FIELDS = (
    "mtype",
    "from_id",
    "term",
    "log_term",
    "log_index",
    "commit",
    "reject",
    "hint",
    "hint_high",
    "n_entries",
)


def encode_inbox(
    batches: Sequence[Sequence[Message]], M: int, E: int
) -> Tuple[Inbox, List[int]]:
    """Pack per-row ordered Message lists into an Inbox.

    Returns (inbox, overflow_rows): rows whose batch exceeds M slots or
    whose REPLICATE carries more than E entries must be host-stepped.
    """
    G = len(batches)
    cols = {k: np.zeros((G, M), np.int32) for k in INBOX_FIELDS}
    ent_term = np.zeros((G, M, E), np.int32)
    ent_cc = np.zeros((G, M, E), np.int32)
    overflow: List[int] = []
    for g, msgs in enumerate(batches):
        if len(msgs) > M:
            overflow.append(g)
            continue
        for i, m in enumerate(msgs):
            if len(m.entries) > E:
                overflow.append(g)
                break
            cols["mtype"][g, i] = int(m.type)
            cols["from_id"][g, i] = m.from_
            cols["term"][g, i] = m.term
            cols["log_term"][g, i] = m.log_term
            cols["log_index"][g, i] = m.log_index
            cols["commit"][g, i] = m.commit
            cols["reject"][g, i] = int(m.reject)
            cols["hint"][g, i] = m.hint
            cols["hint_high"][g, i] = m.hint_high
            cols["n_entries"][g, i] = len(m.entries)
            for j, e in enumerate(m.entries):
                ent_term[g, i, j] = e.term
                ent_cc[g, i, j] = int(e.is_config_change())
    return (
        Inbox(
            **{k: jnp.asarray(v) for k, v in cols.items()},
            ent_term=jnp.asarray(ent_term),
            ent_cc=jnp.asarray(ent_cc),
        ),
        overflow,
    )


def decode_out_row(
    out_np: dict, g: int, shard_id: int, replica_id: int
) -> List[Tuple[Message, int, int]]:
    """Outbox row -> [(message, n_entries, src_slot)].

    Entry payloads are attached by the host from its payload log
    (REPLICATE: indexes [log_index+1, log_index+n]; forwarded PROPOSE:
    the staged entries of inbox slot ``src_slot``)."""
    n = int(out_np["count"][g])
    buf = out_np["buf"][g]
    msgs = []
    for k in range(n):
        rec = buf[k]
        msgs.append(
            (
                Message(
                    type=MessageType(int(rec[F_MTYPE])),
                    to=int(rec[F_TO]),
                    from_=replica_id,
                    shard_id=shard_id,
                    term=int(rec[F_TERM]),
                    log_term=int(rec[F_LOG_TERM]),
                    log_index=int(rec[F_LOG_INDEX]),
                    commit=int(rec[F_COMMIT]),
                    reject=bool(rec[F_REJECT]),
                    hint=int(rec[F_HINT]),
                    hint_high=int(rec[F_HINT_HIGH]),
                ),
                int(rec[F_N_ENTRIES]),
                int(rec[F_SRC_SLOT]),
            )
        )
    return msgs


def out_to_numpy(out: DeviceOut) -> dict:
    return {k: np.asarray(getattr(out, k)) for k in out._fields}
