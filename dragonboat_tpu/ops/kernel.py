"""The vectorized raft step kernel.

``step(state, inbox) -> (state', DeviceOut)`` advances **every row at
once** through an ordered inbox of M message slots.  Slot i is processed
for all G rows in parallel (one masked pass over the whole batch), and
slots are processed sequentially — exactly the order the scalar oracle
(`dragonboat_tpu.raft.raft.Raft.handle`) would process the same messages,
which is what makes bit-exact differential testing possible.

The semantics mirror the oracle function-for-function (which itself
mirrors reference internal/raft/raft.go [U]); each helper cites its
oracle counterpart.  Everything here is pure int32 math — no host
callbacks, no dynamic shapes, no data-dependent Python control flow —
so XLA compiles it to a single fused program that scales to 100k+ rows
(BASELINE north star).

Lane packing: the public layout keeps G (rows) on the MAJOR axis —
``[G, P]`` peer slots, ``[G, W]`` ring, ``[G, M]`` inboxes — because
that is the natural host-side indexing.  On TPU the MINOR axis maps to
the 128-wide lane dimension, so a [G, P] int32 operand with P=3..8 pads
the lanes 16-42x and every pass over the state moved that much dead
HBM traffic (the r4 ledger's residual ~1 us/row/slot).  The kernel
therefore runs **G-last internally**: ``step`` transposes the state,
inbox and outbox to ``[P, G]`` / ``[W, G]`` / ``[M, G]`` /
``[O, N_FIELDS, G]`` at the boundary (two cheap contiguous copies,
~100 MB/launch at 300k rows) and every per-slot op streams fully packed
lanes.  All helpers in this file expect the INTERNAL layout; the
``step`` contract (external layout in/out) is unchanged.

Escalation contract: if a row needs anything the device cannot resolve
(log term outside the W-ring, outbox overflow, a cold message type) its
ESC bit is set in ``out.escalate``; the host replays that row's inbox on
the scalar oracle from the pre-step snapshot and discards every
device-side effect for the row (state column, outbox rows, aux outputs).
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .types import (
    APPEND_LO_NONE,
    DeviceOut,
    DeviceState,
    ESC_COLD,
    ESC_INVARIANT,
    ESC_OVERFLOW,
    ESC_WINDOW,
    F_SRC_SLOT,
    HOT_TYPES,
    I32,
    Inbox,
    KIND_NON_VOTING,
    KIND_VOTER,
    KIND_WITNESS,
    MT_CHECK_QUORUM,
    MT_ELECTION,
    MT_HEARTBEAT,
    MT_HEARTBEAT_RESP,
    MT_INSTALL_SNAPSHOT,
    MT_PROPOSE,
    MT_READ_INDEX,
    MT_READ_INDEX_RESP,
    MT_REPLICATE,
    MT_REPLICATE_RESP,
    MT_REQUEST_PREVOTE,
    MT_REQUEST_PREVOTE_RESP,
    MT_REQUEST_VOTE,
    MT_REQUEST_VOTE_RESP,
    MT_SNAPSHOT_RECEIVED,
    MT_SNAPSHOT_STATUS,
    MT_TICK,
    MT_TIMEOUT_NOW,
    MT_UNREACHABLE,
    N_FIELDS,
    ROLE_CANDIDATE,
    ROLE_FOLLOWER,
    ROLE_LEADER,
    ROLE_NON_VOTING,
    ROLE_PRE_CANDIDATE,
    ROLE_WITNESS,
    RS_REPLICATE,
    RS_RETRY,
    RS_SNAPSHOT,
    RS_WAIT,
    SLOT_DROPPED,
    SLOT_FORWARDED,
    make_out,
)

# test hook (tests/test_kernel_parity.py): True forces every lax.cond
# handler gate in _process_slot open, so each handler also runs under
# an all-false mask — pinning the handler no-op invariant documented at
# the campaign section header.  Read at trace time; never set this in
# production code.
_FORCE_GATES = False

# ---------------------------------------------------------------------------
# internal (G-last) layout plumbing
# ---------------------------------------------------------------------------
# state fields that carry a per-peer or per-ring axis; everything else is [G]
_PEER_FIELDS = (
    "peer_id",
    "peer_kind",
    "match",
    "next_idx",
    "rstate",
    "snap_index",
    "active",
    "granted",
)
_RING_FIELDS = ("ring_term", "ring_cc")


def _state_to_internal(st: DeviceState) -> DeviceState:
    """[G, P] -> [P, G], [G, W] -> [W, G]; [G] fields untouched."""
    return st._replace(
        **{f: getattr(st, f).T for f in _PEER_FIELDS + _RING_FIELDS}
    )


# the transpose is its own inverse
_state_from_internal = _state_to_internal


def _inbox_to_internal(ib: Inbox) -> Inbox:
    """[G, M] -> [M, G]; [G, M, E] -> [M, E, G]."""
    return Inbox(
        **{
            f: (
                getattr(ib, f).transpose(1, 2, 0)
                if getattr(ib, f).ndim == 3
                else getattr(ib, f).T
            )
            for f in Inbox._fields
        }
    )


def _make_out_internal(G: int, P: int, M: int, E: int, O: int) -> DeviceOut:
    # derived from the canonical external constructor so sentinel values
    # (SLOT_UNUSED, APPEND_LO_NONE, barrier -1) have one source of truth;
    # under jit the transposes of fresh constants fold away
    return _out_to_internal(make_out(G, P, M, E, O))


def _out_to_internal(out: DeviceOut) -> DeviceOut:
    return out._replace(
        buf=out.buf.transpose(1, 2, 0),
        need_snapshot=out.need_snapshot.T,
        slot_base=out.slot_base.T,
        slot_term=out.slot_term.T,
        ent_drop=out.ent_drop.transpose(1, 2, 0),
    )


def _out_from_internal(out: DeviceOut) -> DeviceOut:
    return out._replace(
        buf=out.buf.transpose(2, 0, 1),
        need_snapshot=out.need_snapshot.T,
        slot_base=out.slot_base.T,
        slot_term=out.slot_term.T,
        ent_drop=out.ent_drop.transpose(2, 0, 1),
    )


def _P(st: DeviceState) -> int:
    """Peer-slot count in the internal [P, G] layout (st.P reads shape[1],
    which is G here)."""
    return st.peer_id.shape[0]


def _W(st: DeviceState) -> int:
    return st.ring_term.shape[0]


def _w(mask, new, old):
    """Masked field update; mask is [G], fields are [G] or [..., G] — the
    trailing-G layout makes mask broadcasting automatic."""
    return jnp.where(mask, new, old)


def _wp(mask_pg, new, old):
    """Masked per-(peer, row) update; mask is [P, G]."""
    return jnp.where(mask_pg, new, old)


# ---------------------------------------------------------------------------
# deterministic election jitter (mirrors raft.splitmix32 / election_jitter)
# ---------------------------------------------------------------------------
def _splitmix32(x):
    x = (x.astype(jnp.uint32) + jnp.uint32(0x9E3779B9))
    z = x
    z = z ^ (z >> 16)
    z = z * jnp.uint32(0x85EBCA6B)
    z = z ^ (z >> 13)
    z = z * jnp.uint32(0xC2B2AE35)
    z = z ^ (z >> 16)
    return z


def _jitter(shard_id, replica_id, seq, span):
    h = _splitmix32(
        (shard_id.astype(jnp.uint32) << 24)
        ^ (replica_id.astype(jnp.uint32) << 8)
        ^ seq.astype(jnp.uint32)
    )
    return (h % span.astype(jnp.uint32)).astype(I32)


def reset_timeout(st: DeviceState, mask) -> DeviceState:
    """oracle: Raft._reset_randomized_timeout.  Touches only [G] fields,
    so it works on both the external and internal layouts."""
    seq = st.timeout_seq + 1
    rt = st.election_timeout + _jitter(
        st.shard_id, st.replica_id, seq, st.election_timeout
    )
    return st._replace(
        timeout_seq=_w(mask, seq, st.timeout_seq),
        rand_timeout=_w(mask, rt, st.rand_timeout),
    )


# ---------------------------------------------------------------------------
# peer-slot helpers (internal layout: peer arrays are [P, G])
# ---------------------------------------------------------------------------
def _valid(st):
    return st.peer_id != 0


def _voters(st):
    """Voting members = voters + witnesses (oracle: voting_members)."""
    return _valid(st) & (
        (st.peer_kind == KIND_VOTER) | (st.peer_kind == KIND_WITNESS)
    )


def _num_voters(st):
    return jnp.sum(_voters(st), axis=0).astype(I32)


def _quorum(st):
    return _num_voters(st) // 2 + 1


def _self_kind(st):
    return _col(st.peer_kind, st.self_slot)


def _self_is_voter(st):
    """True when this replica currently appears as a voter slot."""
    return (_col(st.peer_id, st.self_slot) == st.replica_id) & (
        _self_kind(st) == KIND_VOTER
    )


def _slot_of(st, pid):
    """Peer-axis slot holding replica ``pid`` [G] -> (slot [G], found [G])."""
    hit = (st.peer_id == pid) & _valid(st) & (pid != 0)
    found = jnp.any(hit, axis=0)
    slot = jnp.argmax(hit, axis=0).astype(I32)
    return slot, found


def _col(arr, slot):
    """arr[slot[g], g] for [P, G] arr.

    One-hot select, NOT take_along_axis: a gather with per-lane
    data-dependent indices costs ~3.3 ms per call at 300k lanes on TPU
    (measured r5 — it dominates the whole slot pass), while the one-hot
    multiply-reduce over the small leading axis is fused elementwise
    work and effectively free."""
    onehot = jnp.arange(arr.shape[0])[:, None] == slot[None, :]
    return jnp.sum(jnp.where(onehot, arr, 0), axis=0)


def _permute0(a, order):
    """a[order[j, g], ..., g] — per-lane permutation along axis 0 via
    one-hot select (see _col: per-lane gathers serialize on TPU).
    ``a`` is [M, G] or [M, E, G]; ``order`` is [M, G]."""
    M = order.shape[0]
    # sel[i, j, g] = (order[j, g] == i)
    sel = order[None, :, :] == jnp.arange(M, dtype=order.dtype)[:, None, None]
    if a.ndim == 2:
        return jnp.sum(jnp.where(sel, a[:, None, :], 0), axis=0)
    # [M, E, G]: broadcast sel over E
    return jnp.sum(
        jnp.where(sel[:, :, None, :], a[:, None, :, :], 0), axis=0
    )


def _set_col(arr, slot, mask, val):
    # one-hot select, NOT arr.at[slot, arange(G)].set(...): a scatter
    # with per-row data-dependent indices lowers to a serial per-row
    # loop on TPU (measured ~100 us/row — it serialized the whole
    # kernel); a [P, G] where() vectorizes
    onehot = jnp.arange(arr.shape[0])[:, None] == slot[None, :]
    val = jnp.broadcast_to(jnp.asarray(val, arr.dtype), slot.shape)
    return jnp.where(onehot & mask, val, arr)


# ---------------------------------------------------------------------------
# log-term ring (internal layout: ring arrays are [W, G])
# ---------------------------------------------------------------------------
def _win_lo(st):
    return jnp.maximum(st.first_index, st.last_index - (_W(st) - 1))


def _ring_at(st, idx):
    wm = _W(st) - 1
    safe = jnp.clip(idx, 0, None) & wm
    return _col(st.ring_term, safe), _col(st.ring_cc, safe)


def _log_term(st, idx):
    """term(idx) -> (term, known, needs_escalation).

    oracle: EntryLog.term.  known=False + esc=False means "definitely
    unavailable" (idx beyond last, a legitimate mismatch); esc=True means
    the ring cannot answer (compacted / outside the W window).
    """
    rt, _ = _ring_at(st, idx)
    zero = idx == 0
    boundary = idx == st.first_index - 1
    in_win = (idx >= _win_lo(st)) & (idx <= st.last_index)
    beyond = idx > st.last_index
    term = jnp.where(zero, 0, jnp.where(boundary, st.base_term, rt))
    known = zero | boundary | in_win
    esc = ~known & ~beyond
    return term, known, esc


def _match_term(st, idx, term):
    """oracle: EntryLog.match_term (False on compacted/unavailable)."""
    t, known, esc = _log_term(st, idx)
    return known & (t == term), esc


def _last_term(st):
    t, _, esc = _log_term(st, st.last_index)
    return t, esc


def _ring_append_one(st, mask, idx, term, cc):
    """Write (term, cc) for log position idx where mask.  One-hot
    select over W (see _set_col: data-dependent scatter serializes)."""
    wm = _W(st) - 1
    pos = jnp.clip(idx, 0, None) & wm
    sel = (jnp.arange(_W(st))[:, None] == pos[None, :]) & mask
    term = jnp.broadcast_to(jnp.asarray(term, st.ring_term.dtype), pos.shape)
    cc = jnp.broadcast_to(jnp.asarray(cc, st.ring_cc.dtype), pos.shape)
    rt = jnp.where(sel, term, st.ring_term)
    rc = jnp.where(sel, cc, st.ring_cc)
    return st._replace(ring_term=rt, ring_cc=rc)


def _pending_cc_scan(st, mask):
    """Any config-change bit in (committed, last_index]?  Used by
    become_leader (oracle: _compute_pending_config_change).  Escalates if
    the uncommitted tail extends below the ring window."""
    W = _W(st)
    idxs = jnp.arange(W)[:, None]  # ring positions, [W, 1]
    # log index currently stored at ring position j:
    # the ring holds indexes in [win_lo, last]; position j holds the unique
    # index in that range congruent to j mod W.
    lo = _win_lo(st)[None, :]
    last = st.last_index[None, :]
    cand = lo + ((idxs - lo) & (W - 1))
    in_tail = (cand > st.committed[None, :]) & (cand <= last)
    any_cc = jnp.any(in_tail & (st.ring_cc == 1), axis=0)
    esc = mask & (st.committed + 1 < _win_lo(st)) & (st.committed < st.last_index)
    return any_cc, esc


# ---------------------------------------------------------------------------
# outbox emission (internal layout: buf is [O, N_FIELDS, G])
# ---------------------------------------------------------------------------
def _emit(
    out: DeviceOut,
    mask,
    *,
    mtype,
    to,
    term,
    log_term=0,
    log_index=0,
    commit=0,
    reject=0,
    hint=0,
    hint_high=0,
    n_entries=0,
    src_slot=-1,
) -> DeviceOut:
    """Append one message per masked row (oracle: Raft._send)."""
    O, G = out.buf.shape[0], out.buf.shape[2]

    def bc(v):
        return jnp.broadcast_to(jnp.asarray(v, I32), (G,))

    row = jnp.stack(
        [
            bc(mtype),
            bc(to),
            bc(term),
            bc(log_term),
            bc(log_index),
            bc(commit),
            bc(reject),
            bc(hint),
            bc(hint_high),
            bc(n_entries),
            bc(src_slot),
        ],
        axis=0,
    )  # [N_FIELDS, G]
    idx = out.count
    can = mask & (idx < O)
    overflow = mask & (idx >= O)
    pos = jnp.clip(idx, 0, O - 1)
    # one-hot select over O (see _set_col: scatter serializes)
    sel = (jnp.arange(O)[:, None] == pos[None, :]) & can  # [O, G]
    buf = jnp.where(sel[:, None, :], row[None, :, :], out.buf)
    return out._replace(
        buf=buf,
        count=out.count + can.astype(I32),
        escalate=out.escalate | jnp.where(overflow, ESC_OVERFLOW, 0),
    )


# ---------------------------------------------------------------------------
# role transitions (oracle: Raft._reset / become_*)
# ---------------------------------------------------------------------------
def _reset(st: DeviceState, mask, new_term) -> DeviceState:
    term_changed = mask & (st.term != new_term)
    st = st._replace(
        term=_w(mask, new_term, st.term),
        vote=_w(term_changed, 0, st.vote),
        leader_id=_w(mask, 0, st.leader_id),
        election_tick=_w(mask, 0, st.election_tick),
        heartbeat_tick=_w(mask, 0, st.heartbeat_tick),
        granted=_w(mask, 0, st.granted),
        transfer_target=_w(mask, 0, st.transfer_target),
        pending_cc=_w(mask, 0, st.pending_cc),
    )
    st = reset_timeout(st, mask)
    # remotes: rm.reset(last+1); self slot keeps match=last
    mgp = mask & _valid(st)
    is_self = (
        jnp.arange(_P(st))[:, None] == st.self_slot[None, :]
    ) & mgp
    last = st.last_index[None, :]
    return st._replace(
        match=_wp(mgp, jnp.where(is_self, last, 0), st.match),
        next_idx=_wp(mgp, last + 1, st.next_idx),
        rstate=_wp(mgp, RS_RETRY, st.rstate),
        snap_index=_wp(mgp, 0, st.snap_index),
    )


def _become_follower(st, mask, new_term, leader) -> DeviceState:
    sk = _self_kind(st)
    role = jnp.where(
        sk == KIND_NON_VOTING,
        ROLE_NON_VOTING,
        jnp.where(sk == KIND_WITNESS, ROLE_WITNESS, ROLE_FOLLOWER),
    )
    st = st._replace(role=_w(mask, role, st.role))
    st = _reset(st, mask, jnp.broadcast_to(jnp.asarray(new_term, I32), (st.G,)))
    return st._replace(leader_id=_w(mask, leader, st.leader_id))


def _become_pre_candidate(st, mask) -> DeviceState:
    """oracle: become_pre_candidate — does NOT touch term/vote/remotes."""
    st = st._replace(
        role=_w(mask, ROLE_PRE_CANDIDATE, st.role),
        granted=_w(mask, 0, st.granted),
        leader_id=_w(mask, 0, st.leader_id),
        election_tick=_w(mask, 0, st.election_tick),
    )
    return reset_timeout(st, mask)


def _become_candidate(st, mask) -> DeviceState:
    st = st._replace(role=_w(mask, ROLE_CANDIDATE, st.role))
    st = _reset(st, mask, st.term + 1)
    st = st._replace(vote=_w(mask, st.replica_id, st.vote))
    return st._replace(granted=_grant_self(st, mask))


def _grant_self(st, mask):
    sel = (
        jnp.arange(st.granted.shape[0])[:, None] == st.self_slot[None, :]
    ) & mask
    return jnp.where(sel, 1, st.granted)


def _vote_quorum(st):
    n = jnp.sum(_voters(st) & (st.granted == 1), axis=0).astype(I32)
    return n >= _quorum(st)


def _vote_rejected(st):
    n = jnp.sum(_voters(st) & (st.granted == 2), axis=0).astype(I32)
    return n >= _quorum(st)


def _append_one(st, out, mask, cc) -> Tuple[DeviceState, DeviceOut]:
    """Leader-side append of one entry at the current term
    (oracle: _append_entries for a single entry, incl. self try_update)."""
    new_last = st.last_index + 1
    out = out._replace(
        append_lo=jnp.where(
            mask, jnp.minimum(out.append_lo, new_last), out.append_lo
        )
    )
    st = _ring_append_one(st, mask, new_last, st.term, cc)
    st = st._replace(last_index=_w(mask, new_last, st.last_index))
    self_match = _col(st.match, st.self_slot)
    self_next = _col(st.next_idx, st.self_slot)
    st = st._replace(
        match=_set_col(
            st.match, st.self_slot, mask, jnp.maximum(self_match, new_last)
        ),
        next_idx=_set_col(
            st.next_idx, st.self_slot, mask, jnp.maximum(self_next, new_last + 1)
        ),
    )
    return st, out


def _try_commit(st, out, mask) -> Tuple[DeviceState, DeviceOut, jnp.ndarray]:
    """oracle: try_commit — sorted-match quorum + current-term-only gate."""
    voters = _voters(st)
    eff = jnp.where(voters, st.match, -1)
    s = jnp.sort(eff, axis=0)  # ascending; non-voters sink to the top
    q = _quorum(st)
    qidx = _col(s, _P(st) - q)
    higher = mask & (qidx > st.committed)
    ok, esc = _match_term(st, qidx, st.term)
    out = out._replace(
        escalate=out.escalate | jnp.where(higher & esc, ESC_WINDOW, 0)
    )
    adv = higher & ok
    st = st._replace(committed=_w(adv, qidx, st.committed))
    return st, out, adv


# ---------------------------------------------------------------------------
# sending replicate / heartbeats
# ---------------------------------------------------------------------------
def _send_replicate(st, out, mask, slot, E) -> Tuple[DeviceState, DeviceOut]:
    """oracle: send_replicate(to) with the device entry cap E.

    ``slot`` is a per-row peer-slot index [G].
    """
    rs = _col(st.rstate, slot)
    nxt = _col(st.next_idx, slot)
    to = _col(st.peer_id, slot)
    paused = (rs == RS_WAIT) | (rs == RS_SNAPSHOT)
    m = mask & ~paused & (to != 0)
    prev = nxt - 1
    # compacted below the resolvable boundary -> snapshot path
    need_ss = m & (prev < st.first_index - 1)
    sel = (
        jnp.arange(out.need_snapshot.shape[0])[:, None] == slot[None, :]
    ) & need_ss
    out = out._replace(
        need_snapshot=jnp.where(sel, 1, out.need_snapshot)
    )
    # hold the remote paused until the host starts the snapshot stream
    st = st._replace(rstate=_set_col(st.rstate, slot, need_ss, RS_WAIT))
    prev_term, known, _esc = _log_term(st, prev)  # esc unused: see below
    m2 = m & ~need_ss
    # below-ring prev (known=False): emit anyway with log_term=0 as a
    # HOST-FIXUP marker — the route host-carries any REPLICATE whose
    # entries predate the ring, and _attach_messages stamps the true
    # prev term + payload from the authoritative scalar log (terms
    # start at 1, so 0 is unambiguous; n>0 is guaranteed here since
    # prev == last is always ring-resident).  Escalating instead
    # livelocked: the reject that walked next below the ring arrived
    # via the ROUTED region, and escalation discards routed inputs —
    # probe -> reject -> escalate forever while a healed follower
    # starved (r4 colocated chaos finding).  The oracle always sends
    # from the full log; this matches it.
    n = jnp.clip(st.last_index - prev, 0, E)
    out = _emit(
        out,
        m2,
        mtype=MT_REPLICATE,
        to=to,
        term=st.term,
        log_index=prev,
        log_term=jnp.where(known, prev_term, 0),
        commit=st.committed,
        n_entries=n,
    )
    # oracle: rm.progress(last sent) only when entries were carried
    prog = m2 & (n > 0)
    last_sent = prev + n
    st = st._replace(
        next_idx=_set_col(
            st.next_idx, slot, prog & (rs == RS_REPLICATE), last_sent + 1
        ),
        rstate=_set_col(st.rstate, slot, prog & (rs == RS_RETRY), RS_WAIT),
    )
    return st, out


def _broadcast_replicate(st, out, mask, E) -> Tuple[DeviceState, DeviceOut]:
    for p in range(_P(st)):
        slot = jnp.full((st.G,), p, I32)
        pm = mask & _valid(st)[p] & (st.self_slot != p)
        st, out = _send_replicate(st, out, pm, slot, E)
    return st, out


def _broadcast_heartbeat(st, out, mask, hint=0, hint_high=0) -> DeviceOut:
    """oracle: broadcast_heartbeat.  ``hint``/``hint_high`` carry a
    pending read-index ctx ([G] or scalar): tick slots get the host's
    latest pending ctx, READ_INDEX slots their own (the device
    ReadIndex hot path — see engine)."""
    for p in range(_P(st)):
        pm = mask & _valid(st)[p] & (st.self_slot != p)
        out = _emit(
            out,
            pm,
            mtype=MT_HEARTBEAT,
            to=st.peer_id[p],
            term=st.term,
            commit=jnp.minimum(st.match[p], st.committed),
            # uncapped commit advisory for the follower's
            # leader_commit_hint (oracle: broadcast_heartbeat's
            # log_index; unused by HEARTBEAT handling proper)
            log_index=st.committed,
            hint=hint,
            hint_high=hint_high,
        )
    return out


def _become_leader(st, out, mask, E) -> Tuple[DeviceState, DeviceOut]:
    """oracle: become_leader (+ the single-voter fast commit)."""
    st = st._replace(role=_w(mask, ROLE_LEADER, st.role))
    st = _reset(st, mask, st.term)
    st = st._replace(leader_id=_w(mask, st.replica_id, st.leader_id))
    # full activity window for a fresh leader (oracle + etcd-raft's
    # RecentActive=true at becomeLeader): with fused ticks an election
    # window can elapse in two launches — one ack round-trip — and the
    # first CheckQuorum against empty lanes deposed every winner
    st = st._replace(active=_wp(mask & _valid(st), 1, st.active))
    any_cc, esc = _pending_cc_scan(st, mask)
    out = out._replace(escalate=out.escalate | jnp.where(esc, ESC_WINDOW, 0))
    st = st._replace(
        pending_cc=_w(mask, any_cc.astype(I32), st.pending_cc)
    )
    # commit barrier: empty entry at the new term
    st, out = _append_one(st, out, mask, jnp.zeros((st.G,), I32))
    # record the barrier so the host can stamp it empty during append
    # reconstruction even if this row steps down LATER IN THE SAME STEP
    # (a higher-term message after the win) — the barrier is the only
    # append that never has a staged or wire payload
    out = out._replace(
        barrier_idx=jnp.where(mask, st.last_index, out.barrier_idx),
        barrier_term=jnp.where(mask, st.term, out.barrier_term),
    )
    single = _num_voters(st) == 1
    st, out, _ = _try_commit(st, out, mask & single & _self_is_voter(st))
    return st, out


# ---------------------------------------------------------------------------
# campaign (oracle: campaign / _handle_election)
#
# HANDLER INVARIANT (load-bearing for the _process_slot lax.cond gating):
# every handler below — and every handler added later — must be a PURE
# NO-OP under an all-false mask: all writes to ``st``/``out`` must be
# mask-selected (jnp.where/_emit with the handler's mask), with NO
# unmasked state normalization, clamping or counter maintenance outside
# the mask.  _process_slot skips whole handler blocks via lax.cond when
# a slot batch contains none of their message types; a handler that
# mutated anything under an all-false mask would make gated and ungated
# execution diverge, surfacing only as rare batch-composition-dependent
# corruption.  tests/test_kernel_parity.py pins the equivalence by
# running _process_slot with every gate forced open (_FORCE_GATES)
# against the normally-gated path.
# ---------------------------------------------------------------------------
def _campaign(st, out, mask, pre, transfer, E) -> Tuple[DeviceState, DeviceOut]:
    pre_m = mask & pre
    real_m = mask & ~pre
    # --- prevote leg ---------------------------------------------------
    st = _become_pre_candidate(st, pre_m)
    st = st._replace(granted=_grant_self(st, pre_m))
    promote = pre_m & _vote_quorum(st)  # single-voter shortcut
    bcast_pre = pre_m & ~promote
    lt, lt_esc = _last_term(st)
    out = out._replace(
        escalate=out.escalate | jnp.where(bcast_pre & lt_esc, ESC_WINDOW, 0)
    )
    for p in range(_P(st)):
        pm = (
            bcast_pre
            & _voters(st)[p]
            & (st.self_slot != p)
        )
        out = _emit(
            out,
            pm,
            mtype=MT_REQUEST_PREVOTE,
            to=st.peer_id[p],
            term=st.term + 1,
            log_index=st.last_index,
            log_term=lt,
        )
    real_m = real_m | promote
    # --- real leg ------------------------------------------------------
    st = _become_candidate(st, real_m)
    lead = real_m & _vote_quorum(st)  # single voter
    st, out = _become_leader(st, out, lead, E)
    bcast = real_m & ~lead
    lt2, lt2_esc = _last_term(st)
    out = out._replace(
        escalate=out.escalate | jnp.where(bcast & lt2_esc, ESC_WINDOW, 0)
    )
    hint = jnp.where(transfer, st.replica_id, 0)
    for p in range(_P(st)):
        pm = bcast & _voters(st)[p] & (st.self_slot != p)
        out = _emit(
            out,
            pm,
            mtype=MT_REQUEST_VOTE,
            to=st.peer_id[p],
            term=st.term,
            log_index=st.last_index,
            log_term=lt2,
            hint=hint,
        )
    return st, out


def _handle_election(st, out, mask, hint, E):
    """oracle: _handle_election."""
    m = (
        mask
        & (st.role != ROLE_LEADER)
        & (st.role != ROLE_NON_VOTING)
        & (st.role != ROLE_WITNESS)
        & _self_is_voter(st)
    )
    transfer = hint == st.replica_id
    pre = (st.pre_vote == 1) & ~transfer
    return _campaign(st, out, m, pre, transfer, E)


# ---------------------------------------------------------------------------
# check quorum (oracle: _handle_check_quorum)
# ---------------------------------------------------------------------------
def _check_quorum(st, mask) -> DeviceState:
    voters = _voters(st)
    is_self = jnp.arange(_P(st))[:, None] == st.self_slot[None, :]
    cnt = 1 + jnp.sum(voters & ~is_self & (st.active == 1), axis=0).astype(I32)
    st = st._replace(
        active=_wp(mask & voters, 0, st.active)
    )
    down = mask & (cnt < _quorum(st))
    return _become_follower(st, down, st.term, 0)


# ---------------------------------------------------------------------------
# tick (oracle: Raft.tick)
# ---------------------------------------------------------------------------
def _tick(
    st, out, mask, E, hint=0, hint_high=0, n=None
) -> Tuple[DeviceState, DeviceOut]:
    """Advance the tick timers by ``n`` logical ticks in one slot
    (multi-tick fusion).

    ``n=1`` is bit-identical to the reference's per-tick stepping; the
    fused form exists because one launch over all rows costs the same
    whether a slot carries 1 tick or 10, and election timeouts are tens
    of ticks.  Encoders cap ``n`` at election_timeout//2 (the same cap
    the scalar step applies to drained tick batches), so at most ONE
    timer threshold crossing happens per slot.  Heartbeats coalesce: k
    firings within the fused span emit one broadcast — the reference
    coalesces heartbeat bursts the same way [U], and a follower only
    needs >=1 heartbeat per election window to hold its timer."""
    if n is None:
        n = jnp.ones((st.G,), I32)
    lead = mask & (st.role == ROLE_LEADER)
    non = mask & (st.role != ROLE_LEADER)
    # --- leader tick ---------------------------------------------------
    el = st.election_tick + n
    hb = st.heartbeat_tick + n
    fired = el >= st.election_timeout
    st = st._replace(
        election_tick=_w(lead, jnp.where(fired, 0, el), st.election_tick),
        heartbeat_tick=_w(lead, hb, st.heartbeat_tick),
    )
    cq = lead & fired & (st.check_quorum == 1)
    st = _check_quorum(st, cq)
    still = lead & (st.role == ROLE_LEADER)
    st = st._replace(
        transfer_target=_w(still & fired, 0, st.transfer_target)
    )
    hb_fire = still & (st.heartbeat_tick >= st.heartbeat_timeout)
    st = st._replace(heartbeat_tick=_w(hb_fire, 0, st.heartbeat_tick))
    out = _broadcast_heartbeat(st, out, hb_fire, hint, hint_high)
    # --- non-leader tick ----------------------------------------------
    el2 = st.election_tick + n
    time_up = el2 >= st.rand_timeout
    nvw = (st.role == ROLE_NON_VOTING) | (st.role == ROLE_WITNESS)
    probe = non & nvw & (st.check_quorum == 1) & time_up
    st = st._replace(election_tick=_w(non, el2, st.election_tick))
    st = st._replace(election_tick=_w(probe, 0, st.election_tick))
    st = reset_timeout(st, probe)
    elect = non & ~nvw & time_up
    st = st._replace(election_tick=_w(elect, 0, st.election_tick))
    st, out = _handle_election(st, out, elect, jnp.zeros((st.G,), I32), E)
    return st, out


# ---------------------------------------------------------------------------
# message-term gate (oracle: _on_message_term)
# ---------------------------------------------------------------------------
def _on_message_term(st, out, msg, mask):
    mt = msg["mtype"]
    mterm = msg["term"]
    local = mterm == 0
    higher = mask & ~local & (mterm > st.term)
    lower = mask & ~local & (mterm < st.term)
    vote_like = (mt == MT_REQUEST_VOTE) | (mt == MT_REQUEST_PREVOTE)
    in_lease = (
        (st.check_quorum == 1)
        & (st.leader_id != 0)
        & (st.election_tick < st.election_timeout)
    )
    drop_lease = higher & vote_like & in_lease & (msg["hint"] == 0)
    leader_msg = (
        (mt == MT_REPLICATE)
        | (mt == MT_INSTALL_SNAPSHOT)
        | (mt == MT_HEARTBEAT)
        | (mt == MT_TIMEOUT_NOW)
        | (mt == MT_READ_INDEX_RESP)
    )
    keep_term = (mt == MT_REQUEST_PREVOTE) | (
        (mt == MT_REQUEST_PREVOTE_RESP) & (msg["reject"] == 0)
    )
    become = higher & ~drop_lease & ~keep_term
    st = _become_follower(
        st, become, mterm, jnp.where(leader_msg, msg["from_id"], 0)
    )
    # deposed-leader poke: a lower-term leader must step down
    poke = (
        lower
        & ((mt == MT_REPLICATE) | (mt == MT_HEARTBEAT) | (mt == MT_INSTALL_SNAPSHOT))
        & ((st.check_quorum == 1) | (st.pre_vote == 1))
    )
    out = _emit(
        out, poke, mtype=MT_REPLICATE_RESP, to=msg["from_id"], term=st.term
    )
    pv_rej = lower & (mt == MT_REQUEST_PREVOTE)
    out = _emit(
        out,
        pv_rej,
        mtype=MT_REQUEST_PREVOTE_RESP,
        to=msg["from_id"],
        term=st.term,
        reject=1,
    )
    passed = mask & (local | (mterm == st.term) | (higher & ~drop_lease))
    return st, out, passed


# ---------------------------------------------------------------------------
# vote handling
# ---------------------------------------------------------------------------
def _can_grant_vote(st, msg, prevote):
    return (
        (st.vote == 0)
        | (st.vote == msg["from_id"])
        | (prevote & (msg["term"] > st.term))
    )


def _up_to_date(st, out, mask, msg):
    lt, esc = _last_term(st)
    out = out._replace(
        escalate=out.escalate | jnp.where(mask & esc, ESC_WINDOW, 0)
    )
    utd = (msg["log_term"] > lt) | (
        (msg["log_term"] == lt) & (msg["log_index"] >= st.last_index)
    )
    return out, utd


def _handle_request_vote(st, out, msg, mask):
    m = mask & (st.role != ROLE_NON_VOTING)
    out, utd = _up_to_date(st, out, m, msg)
    grant = m & _can_grant_vote(st, msg, jnp.asarray(False)) & utd
    st = st._replace(
        election_tick=_w(grant, 0, st.election_tick),
        vote=_w(grant, msg["from_id"], st.vote),
    )
    out = _emit(
        out,
        m,
        mtype=MT_REQUEST_VOTE_RESP,
        to=msg["from_id"],
        term=st.term,
        reject=jnp.where(grant, 0, 1),
    )
    return st, out


def _handle_request_prevote(st, out, msg, mask):
    m = mask & (st.role != ROLE_NON_VOTING)
    out, utd = _up_to_date(st, out, m, msg)
    grant = m & utd & (
        (msg["term"] > st.term) | _can_grant_vote(st, msg, jnp.asarray(True))
    )
    out = _emit(
        out,
        m,
        mtype=MT_REQUEST_PREVOTE_RESP,
        to=msg["from_id"],
        term=jnp.where(grant, msg["term"], st.term),
        reject=jnp.where(grant, 0, 1),
    )
    return st, out


# ---------------------------------------------------------------------------
# replicate / heartbeat handling (follower side)
# ---------------------------------------------------------------------------
def _handle_replicate(st, out, msg, mask, slot_i):
    """oracle: _handle_replicate (follower log append + log matching)."""
    E = int(msg["ent_term"].shape[0])
    stale = mask & (msg["log_index"] < st.committed)
    out = _emit(
        out,
        stale,
        mtype=MT_REPLICATE_RESP,
        to=msg["from_id"],
        term=st.term,
        log_index=st.committed,
    )
    m = mask & ~stale
    prev_ok, esc = _match_term(st, msg["log_index"], msg["log_term"])
    out = out._replace(
        escalate=out.escalate | jnp.where(m & esc, ESC_WINDOW, 0)
    )
    ok = m & prev_ok
    n = msg["n_entries"]
    last_new = msg["log_index"] + n
    # conflict scan: first carried entry whose (index, term) mismatches
    conflict_off = jnp.full((st.G,), E + 1, I32)
    conflict_esc = jnp.zeros((st.G,), bool)
    for i in reversed(range(E)):
        idx = msg["log_index"] + 1 + i
        et = msg["ent_term"][i]
        mt_ok, e_esc = _match_term(st, idx, et)
        has = ok & (i < n)
        conflict_off = jnp.where(has & ~mt_ok, i, conflict_off)
        conflict_esc = jnp.where(has & ~mt_ok, e_esc, conflict_esc)
    # a conflict beyond last_index is an append, not an escalation
    idx_at_conf = msg["log_index"] + 1 + conflict_off
    conflict_esc = conflict_esc & (idx_at_conf <= st.last_index)
    out = out._replace(
        escalate=out.escalate | jnp.where(ok & conflict_esc, ESC_WINDOW, 0)
    )
    has_conflict = ok & (conflict_off <= E)
    # invariant: conflict must be above commit (oracle raises otherwise)
    bad = has_conflict & (idx_at_conf <= st.committed)
    out = out._replace(
        escalate=out.escalate | jnp.where(bad, ESC_INVARIANT, 0)
    )
    # append entries[conflict_off:] — ring writes + truncation to last_new
    first_written = msg["log_index"] + 1 + conflict_off
    out = out._replace(
        append_lo=jnp.where(
            has_conflict,
            jnp.minimum(out.append_lo, first_written),
            out.append_lo,
        )
    )
    for i in range(E):
        idx = msg["log_index"] + 1 + i
        wmask = has_conflict & (i >= conflict_off) & (i < n)
        st = _ring_append_one(
            st, wmask, idx, msg["ent_term"][i], msg["ent_cc"][i]
        )
    st = st._replace(
        last_index=_w(has_conflict, last_new, st.last_index)
    )
    # commit_to(min(m.commit, last_new))
    new_commit = jnp.minimum(msg["commit"], last_new)
    st = st._replace(
        committed=_w(ok, jnp.maximum(st.committed, new_commit), st.committed)
    )
    out = _emit(
        out,
        ok,
        mtype=MT_REPLICATE_RESP,
        to=msg["from_id"],
        term=st.term,
        log_index=last_new,
    )
    rej = m & ~prev_ok
    out = _emit(
        out,
        rej,
        mtype=MT_REPLICATE_RESP,
        to=msg["from_id"],
        term=st.term,
        reject=1,
        log_index=msg["log_index"],
        hint=st.last_index,
    )
    return st, out


def _handle_heartbeat(st, out, msg, mask):
    new_commit = jnp.minimum(msg["commit"], st.last_index)
    st = st._replace(
        committed=_w(mask, jnp.maximum(st.committed, new_commit), st.committed)
    )
    out = _emit(
        out,
        mask,
        mtype=MT_HEARTBEAT_RESP,
        to=msg["from_id"],
        term=st.term,
        hint=msg["hint"],
        hint_high=msg["hint_high"],
    )
    return st, out


# ---------------------------------------------------------------------------
# leader-side response handling
# ---------------------------------------------------------------------------
def _handle_replicate_resp(st, out, msg, mask, E):
    slot, found = _slot_of(st, msg["from_id"])
    m = mask & found
    st = st._replace(active=_set_col(st.active, slot, m, 1))
    rs = _col(st.rstate, slot)
    match = _col(st.match, slot)
    nxt = _col(st.next_idx, slot)
    snap = _col(st.snap_index, slot)
    rej = m & (msg["reject"] == 1)
    # -- decrease (oracle: remote.decrease) -----------------------------
    repl = rs == RS_REPLICATE
    do_r = rej & repl & (msg["log_index"] > match)
    # become_retry from REPLICATE: next = match + 1
    st = st._replace(
        next_idx=_set_col(st.next_idx, slot, do_r, match + 1),
        snap_index=_set_col(st.snap_index, slot, do_r, 0),
        rstate=_set_col(st.rstate, slot, do_r, RS_RETRY),
    )
    do_nr = rej & ~repl & (nxt - 1 == msg["log_index"])
    dec_next = jnp.maximum(
        jnp.maximum(jnp.minimum(msg["log_index"], msg["hint"] + 1), match + 1),
        1,
    )
    st = st._replace(
        next_idx=_set_col(st.next_idx, slot, do_nr, dec_next),
        rstate=_set_col(
            st.rstate,
            slot,
            do_nr & (rs == RS_WAIT),
            RS_RETRY,
        ),
    )
    st, out = _send_replicate(st, out, do_r | do_nr, slot, E)
    # -- ack (oracle: _handle_replicate_resp accept path) ---------------
    ack = m & (msg["reject"] == 0)
    paused = (rs == RS_WAIT) | (rs == RS_SNAPSHOT)
    advanced = ack & (match < msg["log_index"])
    new_match = jnp.maximum(match, msg["log_index"])
    new_next = jnp.maximum(nxt, msg["log_index"] + 1)
    st = st._replace(
        match=_set_col(st.match, slot, advanced, new_match),
        next_idx=_set_col(st.next_idx, slot, ack, new_next),
        rstate=_set_col(
            st.rstate, slot, advanced & (rs == RS_WAIT), RS_RETRY
        ),
    )
    # snapshot -> retry -> replicate promotions
    rs2 = _col(st.rstate, slot)
    promote_ss = advanced & (rs2 == RS_SNAPSHOT) & (new_match >= snap)
    st = st._replace(
        next_idx=_set_col(
            st.next_idx,
            slot,
            promote_ss,
            jnp.maximum(new_match + 1, snap + 1),
        ),
        snap_index=_set_col(st.snap_index, slot, promote_ss, 0),
        rstate=_set_col(st.rstate, slot, promote_ss, RS_RETRY),
    )
    rs3 = _col(st.rstate, slot)
    promote_r = advanced & (rs3 == RS_RETRY)
    st = st._replace(
        next_idx=_set_col(st.next_idx, slot, promote_r, new_match + 1),
        snap_index=_set_col(st.snap_index, slot, promote_r, 0),
        rstate=_set_col(st.rstate, slot, promote_r, RS_REPLICATE),
    )
    st, out, committed_adv = _try_commit(st, out, advanced)
    st, out = _broadcast_replicate(st, out, committed_adv, E)
    st, out = _send_replicate(
        st, out, advanced & ~committed_adv & paused, slot, E
    )
    # leader transfer: target caught up -> TIMEOUT_NOW
    ready = (
        advanced
        & (st.transfer_target == msg["from_id"])
        & (st.last_index == new_match)
    )
    out = _emit(
        out, ready, mtype=MT_TIMEOUT_NOW, to=msg["from_id"], term=st.term
    )
    # stale ack while streaming a snapshot that has completed
    rs4 = _col(st.rstate, slot)
    m4 = _col(st.match, slot)
    s4 = _col(st.snap_index, slot)
    stale_ss = ack & ~advanced & (rs4 == RS_SNAPSHOT) & (m4 >= s4)
    st = st._replace(
        next_idx=_set_col(
            st.next_idx, slot, stale_ss, jnp.maximum(m4 + 1, s4 + 1)
        ),
        snap_index=_set_col(st.snap_index, slot, stale_ss, 0),
        rstate=_set_col(st.rstate, slot, stale_ss, RS_RETRY),
    )
    return st, out


def _handle_heartbeat_resp(st, out, msg, mask, E):
    slot, found = _slot_of(st, msg["from_id"])
    m = mask & found
    st = st._replace(active=_set_col(st.active, slot, m, 1))
    rs = _col(st.rstate, slot)
    st = st._replace(
        rstate=_set_col(st.rstate, slot, m & (rs == RS_WAIT), RS_RETRY)
    )
    lag = m & (_col(st.match, slot) < st.last_index)
    st, out = _send_replicate(st, out, lag, slot, E)
    # read-index ctx echo: surface the confirmation to the HOST as a
    # synthetic READ_INDEX_RESP-to-self (log_index = confirming voter;
    # the engine routes self-addressed resps to node.device_reads).
    # Only VOTING members count — matching the oracle's quorum gate.
    kind = _col(st.peer_kind, slot)
    voter = (kind == KIND_VOTER) | (kind == KIND_WITNESS)
    has_ctx = m & voter & ((msg["hint"] != 0) | (msg["hint_high"] != 0))
    out = _emit(
        out,
        has_ctx,
        mtype=MT_READ_INDEX_RESP,
        to=st.replica_id,
        term=st.term,
        log_index=msg["from_id"],
        hint=msg["hint"],
        hint_high=msg["hint_high"],
    )
    return st, out


def _handle_read_index(st, out, msg, mask) -> DeviceOut:
    """Device ReadIndex hot path (oracle: _handle_leader_read_index).

    The ctx -> (index, acks) table lives on the HOST (node.device_reads);
    the kernel only emits synthetic READ_INDEX_RESP-to-self messages the
    engine intercepts:

        reject=1                     -> drop the pending read (not
                                        leader / current-term gate)
        reject=0, log_index=0        -> request recorded at index=commit
        reject=0, log_index=K>0      -> confirmation from voter K
                                        (emitted by heartbeat-resp)

    and broadcasts the quorum-confirming heartbeats with the ctx riding
    the hint fields — so a read-heavy workload stays device-resident.
    """
    lead = mask & (st.role == ROLE_LEADER) & (_self_kind(st) != KIND_WITNESS)
    non_lead = mask & ~lead
    out = _emit(
        out,
        non_lead,
        mtype=MT_READ_INDEX_RESP,
        to=st.replica_id,
        term=st.term,
        reject=1,
        hint=msg["hint"],
        hint_high=msg["hint_high"],
    )
    # oracle: committed_entry_in_current_term — unsafe to serve before
    # the leader's no-op barrier commits
    ok, esc = _match_term(st, st.committed, st.term)
    out = out._replace(
        escalate=out.escalate | jnp.where(lead & esc, ESC_WINDOW, 0)
    )
    gate_fail = lead & ~ok & ~esc
    out = _emit(
        out,
        gate_fail,
        mtype=MT_READ_INDEX_RESP,
        to=st.replica_id,
        term=st.term,
        reject=1,
        hint=msg["hint"],
        hint_high=msg["hint_high"],
    )
    serve = lead & ok
    out = _emit(
        out,
        serve,
        mtype=MT_READ_INDEX_RESP,
        to=st.replica_id,
        term=st.term,
        commit=st.committed,
        hint=msg["hint"],
        hint_high=msg["hint_high"],
    )
    # single-voter groups confirm instantly host-side (quorum == 1)
    multi = serve & (_num_voters(st) > 1)
    return _broadcast_heartbeat(st, out, multi, msg["hint"], msg["hint_high"])


def _handle_unreachable(st, msg, mask):
    slot, found = _slot_of(st, msg["from_id"])
    m = mask & found & (_col(st.rstate, slot) == RS_REPLICATE)
    match = _col(st.match, slot)
    st = st._replace(
        next_idx=_set_col(st.next_idx, slot, m, match + 1),
        snap_index=_set_col(st.snap_index, slot, m, 0),
        rstate=_set_col(st.rstate, slot, m, RS_RETRY),
    )
    return st


def _handle_snapshot_status(st, msg, mask):
    """oracle: _handle_snapshot_status / _handle_snapshot_received — the
    remote leaves SNAPSHOT into WAIT (become_wait)."""
    slot, found = _slot_of(st, msg["from_id"])
    m = mask & found & (_col(st.rstate, slot) == RS_SNAPSHOT)
    snap = _col(st.snap_index, slot)
    # reject=1 clears the pending snapshot index first (SNAPSHOT_STATUS)
    snap = jnp.where(m & (msg["reject"] == 1), 0, snap)
    match = _col(st.match, slot)
    new_next = jnp.maximum(match + 1, snap + 1)
    st = st._replace(
        next_idx=_set_col(st.next_idx, slot, m, new_next),
        snap_index=_set_col(st.snap_index, slot, m, 0),
        rstate=_set_col(st.rstate, slot, m, RS_WAIT),
    )
    return st


# ---------------------------------------------------------------------------
# propose (oracle: _handle_propose)
# ---------------------------------------------------------------------------
def _handle_propose(st, out, msg, mask, slot_i, E):
    lead = mask & (st.role == ROLE_LEADER)
    n = msg["n_entries"]
    transferring = st.transfer_target != 0
    drop_all = lead & transferring
    accept = lead & ~transferring
    base = st.last_index
    # per-entry config-change gate, sequential within the message
    appended_any = jnp.zeros((st.G,), bool)
    ent_drop = out.ent_drop
    for i in range(E):
        has = accept & (i < n)
        is_cc = msg["ent_cc"][i] == 1
        dropped = has & is_cc & (st.pending_cc == 1)
        ent_drop = ent_drop.at[slot_i, i].set(
            jnp.where(dropped, 1, ent_drop[slot_i, i])
        )
        put = has & ~dropped
        st = st._replace(
            pending_cc=_w(put & is_cc, 1, st.pending_cc)
        )
        st, out = _append_one(st, out, put, jnp.where(is_cc, 1, 0))
        appended_any = appended_any | put
    out = out._replace(ent_drop=ent_drop)
    # single-voter commit advance happens inside _append_entries via
    # try_commit; mirror it once after the batch (equivalent because the
    # commit quorum for a single voter is just its own last_index)
    single = (_num_voters(st) == 1) & _self_is_voter(st)
    st, out, _ = _try_commit(st, out, appended_any & single)
    st, out = _broadcast_replicate(st, out, appended_any, E)
    # host bookkeeping: where did this slot's entries land?
    sb = jnp.where(
        accept,
        base,
        jnp.where(drop_all, SLOT_DROPPED, out.slot_base[slot_i]),
    )
    stm = jnp.where(accept, st.term, out.slot_term[slot_i])
    # follower: forward to the leader; candidate/no-leader: drop
    foll = mask & (
        (st.role == ROLE_FOLLOWER)
        | (st.role == ROLE_NON_VOTING)
        | (st.role == ROLE_WITNESS)
    )
    fwd = foll & (st.leader_id != 0)
    out = _emit(
        out,
        fwd,
        mtype=MT_PROPOSE,
        to=st.leader_id,
        term=st.term,
        n_entries=n,
        src_slot=slot_i,
    )
    sb = jnp.where(fwd, SLOT_FORWARDED, sb)
    dropped_f = (foll & (st.leader_id == 0)) | (
        mask
        & ((st.role == ROLE_CANDIDATE) | (st.role == ROLE_PRE_CANDIDATE))
    )
    sb = jnp.where(dropped_f, SLOT_DROPPED, sb)
    out = out._replace(
        slot_base=out.slot_base.at[slot_i].set(sb),
        slot_term=out.slot_term.at[slot_i].set(stm),
    )
    return st, out


# ---------------------------------------------------------------------------
# the per-slot dispatcher (oracle: Raft.handle + _step)
# ---------------------------------------------------------------------------
def _is_hot(mt):
    acc = jnp.zeros_like(mt, dtype=bool)
    for t in HOT_TYPES:
        acc = acc | (mt == t)
    return acc


def _process_slot(st, out, msg, slot_i, E):
    """One inbox slot for every row.  INTERNAL layout: state peer/ring
    arrays [P, G]/[W, G], out.buf [O, N_FIELDS, G], msg fields [G]
    (``ent_term``/``ent_cc`` are [E, G]).

    Handler blocks are gated behind ``lax.cond`` on batch-wide presence
    of their message types: a slot pass only pays for the handlers its
    messages actually need (measured r5: a tick-only slot dropped from
    ~12 ms to ~2.3 ms at 300k rows — the untaken branches are real
    runtime skips on TPU, not just masked no-ops).  Reordering handler
    blocks is semantics-preserving because per-row handler masks are
    disjoint by message type; the one real cross-block ordering
    constraint — candidates demoted by a leader's REPLICATE/HEARTBEAT
    must then be processed by the follower block in the same slot — is
    kept (cand block runs before foll block).
    """
    mask = (msg["mtype"] != 0) & (out.escalate == 0)
    mt = msg["mtype"]
    # cold types escalate the whole row
    out = out._replace(
        escalate=out.escalate | jnp.where(mask & ~_is_hot(mt), ESC_COLD, 0)
    )
    mask = mask & _is_hot(mt)

    def _has(*types):
        acc = jnp.zeros((), bool)
        for t in types:
            acc = acc | jnp.any(mask & (mt == t))
        return acc

    def _gate(pred, fn, st, out):
        # _FORCE_GATES (test hook): run every handler regardless of
        # batch presence, exercising them under all-false masks — the
        # parity test's lever for pinning the handler no-op invariant
        # (see the campaign section header)
        if _FORCE_GATES:
            return fn(st, out)
        return lax.cond(pred, fn, lambda s, o: (s, o), st, out)

    # LOCAL_TICK short-circuits the gate (oracle: handle); log_index
    # carries the fused tick count (0 on legacy single-tick slots)
    st, out = _gate(
        _has(MT_TICK),
        lambda s, o: _tick(
            s, o, mask & (mt == MT_TICK), E, msg["hint"], msg["hint_high"],
            n=jnp.maximum(msg["log_index"], 1),
        ),
        st, out,
    )
    rest = mask & (mt != MT_TICK)

    def _non_tick(st, out):
        st, out, passed = _on_message_term(st, out, msg, rest)

        def _votes(st, out):
            st, out = _handle_election(
                st, out, passed & (mt == MT_ELECTION), msg["hint"], E
            )
            st, out = _handle_request_vote(
                st, out, msg, passed & (mt == MT_REQUEST_VOTE)
            )
            st, out = _handle_request_prevote(
                st, out, msg, passed & (mt == MT_REQUEST_PREVOTE)
            )
            return st, out

        st, out = _gate(
            _has(MT_ELECTION, MT_REQUEST_VOTE, MT_REQUEST_PREVOTE),
            _votes, st, out,
        )
        role_routed = passed & ~(
            (mt == MT_ELECTION)
            | (mt == MT_REQUEST_VOTE)
            | (mt == MT_REQUEST_PREVOTE)
        )

        def _prop_read(st, out):
            st, out = _handle_propose(
                st, out, msg, role_routed & (mt == MT_PROPOSE), slot_i, E
            )
            out = _handle_read_index(
                st, out, msg, role_routed & (mt == MT_READ_INDEX)
            )
            return st, out

        st, out = _gate(
            _has(MT_PROPOSE, MT_READ_INDEX), _prop_read, st, out
        )

        def _rare(st, out):
            lead = role_routed & (st.role == ROLE_LEADER)
            st = _check_quorum(st, lead & (mt == MT_CHECK_QUORUM))
            st = _handle_unreachable(st, msg, lead & (mt == MT_UNREACHABLE))
            st = _handle_snapshot_status(
                st,
                msg,
                lead
                & ((mt == MT_SNAPSHOT_STATUS) | (mt == MT_SNAPSHOT_RECEIVED)),
            )
            return st, out

        st, out = _gate(
            _has(MT_CHECK_QUORUM, MT_UNREACHABLE, MT_SNAPSHOT_STATUS,
                 MT_SNAPSHOT_RECEIVED),
            _rare, st, out,
        )

        def _lead_resps(st, out):
            lead = role_routed & (st.role == ROLE_LEADER)
            st, out = _handle_replicate_resp(
                st, out, msg, lead & (mt == MT_REPLICATE_RESP), E
            )
            st, out = _handle_heartbeat_resp(
                st, out, msg, lead & (mt == MT_HEARTBEAT_RESP), E
            )
            return st, out

        st, out = _gate(
            _has(MT_REPLICATE_RESP, MT_HEARTBEAT_RESP), _lead_resps, st, out
        )

        def _cand(st, out):
            cand = role_routed & (
                (st.role == ROLE_CANDIDATE) | (st.role == ROLE_PRE_CANDIDATE)
            )
            # REPLICATE / HEARTBEAT at our term from a legitimate leader
            from_leader = cand & ((mt == MT_REPLICATE) | (mt == MT_HEARTBEAT))
            st = _become_follower(st, from_leader, st.term, msg["from_id"])
            # vote responses
            vr = cand & (mt == MT_REQUEST_VOTE_RESP) & (
                st.role == ROLE_CANDIDATE
            )
            slot, found = _slot_of(st, msg["from_id"])
            rec = vr & found
            st = st._replace(
                granted=_set_col(
                    st.granted, slot, rec, jnp.where(msg["reject"] == 1, 2, 1)
                )
            )
            win = vr & _vote_quorum(st)
            st, out = _become_leader(st, out, win, E)
            st, out = _broadcast_replicate(st, out, win, E)
            lose = vr & ~win & _vote_rejected(st)
            st = _become_follower(st, lose, st.term, 0)
            pv = cand & (mt == MT_REQUEST_PREVOTE_RESP) & (
                st.role == ROLE_PRE_CANDIDATE
            )
            slot2, found2 = _slot_of(st, msg["from_id"])
            rec2 = pv & found2
            st = st._replace(
                granted=_set_col(
                    st.granted, slot2, rec2, jnp.where(msg["reject"] == 1, 2, 1)
                )
            )
            pv_win = pv & _vote_quorum(st)
            st, out = _campaign(
                st,
                out,
                pv_win,
                jnp.zeros((st.G,), bool),
                jnp.zeros((st.G,), bool),
                E,
            )
            pv_lose = pv & ~pv_win & _vote_rejected(st)
            st = _become_follower(st, pv_lose, st.term, 0)
            return st, out

        st, out = _gate(
            _has(MT_REQUEST_VOTE_RESP, MT_REQUEST_PREVOTE_RESP,
                 MT_REPLICATE, MT_HEARTBEAT),
            _cand, st, out,
        )

        def _foll(st, out):
            # follower-ish roles (+ the just-demoted candidates)
            foll = role_routed & (
                (st.role == ROLE_FOLLOWER)
                | (st.role == ROLE_NON_VOTING)
                | (st.role == ROLE_WITNESS)
            )
            lmsg = foll & ((mt == MT_REPLICATE) | (mt == MT_HEARTBEAT))
            st = st._replace(
                election_tick=_w(lmsg, 0, st.election_tick),
                leader_id=_w(lmsg, msg["from_id"], st.leader_id),
            )
            st, out = _handle_replicate(
                st, out, msg, lmsg & (mt == MT_REPLICATE), slot_i
            )
            st, out = _handle_heartbeat(
                st, out, msg, lmsg & (mt == MT_HEARTBEAT)
            )
            tn = (
                foll
                & (mt == MT_TIMEOUT_NOW)
                & (st.role == ROLE_FOLLOWER)
                & _self_is_voter(st)
            )
            st, out = _campaign(
                st, out, tn, jnp.zeros((st.G,), bool), jnp.ones((st.G,), bool),
                E,
            )
            return st, out

        st, out = _gate(
            _has(MT_REPLICATE, MT_HEARTBEAT, MT_TIMEOUT_NOW), _foll, st, out
        )
        return st, out

    return _gate(jnp.any(rest), _non_tick, st, out)


def _slot_view(inbox: Inbox, i):
    """Slot i of every row ([G] / [E, G] views) from an INTERNAL-layout
    inbox ([M, G] / [M, E, G]); i may be traced."""

    def ix(a):
        return lax.dynamic_index_in_dim(a, i, axis=0, keepdims=False)

    return {
        "mtype": ix(inbox.mtype),
        "from_id": ix(inbox.from_id),
        "term": ix(inbox.term),
        "log_term": ix(inbox.log_term),
        "log_index": ix(inbox.log_index),
        "commit": ix(inbox.commit),
        "reject": ix(inbox.reject),
        "hint": ix(inbox.hint),
        "hint_high": ix(inbox.hint_high),
        "n_entries": ix(inbox.n_entries),
        "ent_term": ix(inbox.ent_term),
        "ent_cc": ix(inbox.ent_cc),
    }


def _step_impl(
    state: DeviceState, cin: Inbox, out_capacity: int
) -> Tuple[DeviceState, DeviceOut]:
    """The step body over INTERNAL-layout operands: state peer/ring
    arrays [P, G]/[W, G], inbox [M, G]/[M, E, G].  Returns internal
    layout.  ``step`` wraps this with the boundary transposes;
    ``step_internal`` exposes it directly so device-resident loops
    (bench phase A, future engine paths) never pay the padded-layout
    boundary traffic (~12 ms/launch at 300k rows, measured r5)."""
    G = state.G
    P = _P(state)
    M = cin.mtype.shape[0]
    E = cin.ent_term.shape[1]
    out = _make_out_internal(G, P, M, E, out_capacity)
    # inherit the state's varying-ness (shard_map vma) so the loop carry
    # types match when the step runs sharded over the groups axis; every
    # out array is G-trailing, so a bare [G] zero broadcasts onto all
    zero = state.term * 0  # [G]
    out = jax.tree.map(lambda a: a + zero, out)

    # slot compaction: a slot pass costs the same whether the slot is
    # empty or not, and the assembled colocated inbox is mostly-empty
    # routed lanes (P*budget + M slots, typically 2-6 occupied).
    # Stable-sort each row's occupied slots to the front (empty slots
    # are exact no-ops in _process_slot, and the stable key preserves
    # the replay order of the occupied ones), then run only as many
    # passes as the BUSIEST row needs.  The while_loop's data-dependent
    # trip count replaces M static iterations.
    occ = cin.mtype != 0  # [M, G]
    order = jnp.argsort(jnp.where(occ, 0, 1), axis=0, stable=True)

    def compact(a):
        # one-hot permutation, not take_along_axis (per-lane gathers
        # serialize on TPU — see _col)
        return _permute0(a, order)

    cin = Inbox(*(compact(getattr(cin, f)) for f in Inbox._fields))
    # IMPORTANT: out's slot arrays (slot_base/slot_term/ent_drop and
    # src_slot lanes) are reported in COMPACTED coordinates; map them
    # back to the original slot indices afterwards so the host staging
    # keys still match.
    n_occ = jnp.max(jnp.sum(occ.astype(jnp.int32), axis=0))

    def cond(carry):
        i, _st, _o = carry
        return i < n_occ

    def body(carry):
        i, st, o = carry
        st, o = _process_slot(st, o, _slot_view(cin, i), i, E)
        return (i + 1, st, o)

    _, state, out = lax.while_loop(cond, body, (jnp.int32(0), state, out))
    # un-compact the per-slot output arrays back to caller coordinates:
    # compacted slot j of row g corresponds to original slot order[j, g]
    inv = jnp.argsort(order, axis=0, stable=True)

    def uncompact(a):
        return _permute0(a, inv)

    # src_slot values inside the outbox buffer index COMPACTED slots;
    # translate through order so the host sees original coordinates
    src = out.buf[:, F_SRC_SLOT, :]  # [O, G]
    src_ok = src >= 0
    srcc = jnp.clip(src, 0, M - 1)
    # src_orig[o, g] = order[srcc[o, g], g] — one-hot select over M
    sel = srcc[None, :, :] == jnp.arange(M, dtype=srcc.dtype)[:, None, None]
    src_orig = jnp.sum(jnp.where(sel, order[:, None, :], 0), axis=0)
    buf = out.buf.at[:, F_SRC_SLOT, :].set(jnp.where(src_ok, src_orig, src))
    out = out._replace(
        buf=buf,
        slot_base=uncompact(out.slot_base),
        slot_term=uncompact(out.slot_term),
        ent_drop=uncompact(out.ent_drop),
    )
    return state, out


@functools.partial(jax.jit, static_argnames=("out_capacity",))
def step(
    state: DeviceState, inbox: Inbox, out_capacity: int = 32
) -> Tuple[DeviceState, DeviceOut]:
    """Advance every row through its inbox.  Pure and jit-compiled; the
    host wrapper (ops/engine.py) owns staging, payload logs and the
    escalation replay.

    External layout in and out (``[G, ...]`` everywhere); internally the
    whole loop runs G-last so int32 operands pack the 128-lane axis
    instead of padding it 16-42x (see the module docstring).

    Slots run under ``lax.while_loop`` so the compiled program contains
    ONE slot body regardless of M — compile time stays flat and XLA
    still fuses the whole body into a few kernels per slot iteration.
    """
    state = _state_to_internal(state)
    cin = _inbox_to_internal(inbox)
    state, out = _step_impl(state, cin, out_capacity)
    return _state_from_internal(state), _out_from_internal(out)


@functools.partial(jax.jit, static_argnames=("out_capacity",))
def step_internal(
    state: DeviceState, inbox: Inbox, out_capacity: int = 32
) -> Tuple[DeviceState, DeviceOut]:
    """``step`` without the boundary transposes: all operands and
    results in the INTERNAL (G-last) layout — state peer/ring arrays
    [P, G]/[W, G], inbox [M, G]/[M, E, G], out.buf [O, N_FIELDS, G].

    The padded-layout boundary traffic of ``step`` costs ~12 ms/launch
    at 300k rows (measured r5, real barrier) — more than the slot pass
    itself.  Device-resident loops that keep state in the internal
    layout across launches (bench phase A) skip it entirely; hosts can
    build internal-layout operands directly in numpy (a host-side
    transpose is a cheap packed copy) via ``state_to_internal``.
    """
    return _step_impl(state, inbox, out_capacity)


def state_to_internal(st: DeviceState) -> DeviceState:
    """Public [G, ...] -> internal (G-last) state layout.  Works on jnp
    or numpy fields (transpose is a view host-side).  The transpose is
    its own inverse; internal-layout Inbox/DeviceOut construction stays
    module-private until a second consumer exists."""
    return _state_to_internal(st)


def inbox_to_internal(ib: Inbox) -> Inbox:
    """Public [G, M]/[G, M, E] -> internal (G-last) inbox layout — the
    companion of :func:`state_to_internal` for callers (bench phase A
    sharded, tests) that build internal-layout launches host-side."""
    return _inbox_to_internal(ib)


def make_step_sharded(  # mesh-hot
    mesh, state: DeviceState, inbox: Inbox, *, out_capacity: int,
    internal: bool = False,
):
    """Build the shard_map'd step over a 1-D groups mesh (ROADMAP 3).

    Returns a jitted ``(state, inbox) -> (state', out)`` whose program
    runs PER DEVICE on that device's G-slice: the step body is
    row-local (every reduction is over the P/W/M/O axes, never G), so
    the compiled per-shard program contains ZERO collectives and is
    bit-identical to the single-device ``step`` on the concatenation of
    the slices (pinned by tests/test_multichip.py).  The only
    shard-local quantity is the slot-compaction trip count ``n_occ``
    (a per-shard max): a shard with emptier inboxes runs fewer slot
    passes, which is exactly the empty-slot no-op contract.

    ``state``/``inbox`` are EXAMPLE operands (shape/ndim only) used to
    derive per-leaf partition specs; ``internal=True`` expects the
    G-last layout (``state_to_internal``/``inbox_to_internal``) and
    shards the TRAILING axis of every leaf, so phase-A-style loops keep
    the packed-lane layout across launches with no boundary transposes.
    """
    import jax as _jax

    try:
        from jax import shard_map as _shard_map
    except ImportError:  # pragma: no cover - older jax spelling
        from jax.experimental.shard_map import shard_map as _shard_map
    from jax.sharding import PartitionSpec as _PS

    if len(mesh.axis_names) != 1:
        raise ValueError("groups mesh must be one-dimensional")
    axis = mesh.axis_names[0]
    fn = step_internal if internal else step

    def _local(st, ib):
        return fn(st, ib, out_capacity=out_capacity)

    if internal:
        # G trails every leaf: build per-leaf specs by ndim
        def spec_of(a):
            return _PS(*([None] * (a.ndim - 1) + [axis]))

        out_shapes = _jax.eval_shape(_local, state, inbox)
        in_specs = (
            _jax.tree.map(spec_of, state),
            _jax.tree.map(spec_of, inbox),
        )
        out_specs = _jax.tree.map(spec_of, out_shapes)
    else:
        # G leads every leaf: a single prefix spec covers each pytree
        in_specs = (_PS(axis), _PS(axis))
        out_specs = (_PS(axis), _PS(axis))
    return _jax.jit(
        _shard_map(
            _local, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            # the step body carries a lax.while_loop (slot
            # compaction); jax 0.4.x has no replication rule for
            # while under shard_map's rep checker — the specs
            # here are all-sharded, so the check is vacuous
            check_rep=False,
        )
    )
