"""Array-at-once host-plane machinery for the colocated launch path.

The r5 ledger (docs/BENCH_NOTES_r05.md, Config 4) showed that at 250k
replica rows the DEVICE plane costs ~4 s of a 2,731 s 50k-shard
election while ``t_plan`` (887 s) and ``t_updates`` (538 s) — per-row
Python in the colocated engine's plan and merge stages — dominate.
This module is the fix: the per-row work that is pure *metadata math*
(eligibility classification, merge row-set construction, coverage
checks, index maps) runs as numpy array ops over ALL rows per
generation instead of per-row attribute probes and dict builds.

Three layers:

* ``RowLanes`` — the SoA truth store for per-row engine metadata
  (``attached``/``dirty``/``plan_ok``/``esc_hold``).  The per-row
  ``_RowMeta`` objects in ``ops/engine.py`` are thin property views
  over these lanes, so every existing scalar path keeps its field
  syntax while the vectorized passes read whole lanes at once.

* vectorized passes — ``classify_static`` (the batched plan
  classifier's static-eligibility prefilter), ``build_merge_sets``
  (the post-launch row sets: escalations, live rows, buf/append/
  need/slot/sum), ``pos_of``/``covered`` (index-array replacements
  for the old per-row ``*_at`` dict builds and ``all(g in …)``
  membership scans).  These carry the ``# hostplane-hot`` marker:
  raftlint's ``host-loop`` rule bans ``for``-over-rows inside them so
  the vectorization cannot rot back into per-row Python.

* scalar twins — ``classify_static_scalar`` / ``build_merge_sets_scalar``
  replicate the pre-vectorization per-row logic verbatim.  They are
  the PARITY ORACLE: with ``PARITY`` enabled (env
  ``DRAGONBOAT_TPU_HOSTPLANE_PARITY=1``, or set directly by tests) the
  colocated engine runs both implementations on every generation and
  fail-stops on any divergence.  They also let ``bench.py
  phase_hostplane`` measure the stage cost the vectorization removed.

The scalar ``_plan_device`` classifier in ops/engine.py remains the
slow-path fallback for rows that fail the static prefilter — exactly
the contract the ``plan_ok`` fast tick lane (57 µs -> 5 µs) proved.
Deliberately numpy-only: nothing here may touch jax — the host plane
must never inject device syncs into the launch tail (that is the
device plane's job, audited separately by analysis/jaxcheck).
"""
from __future__ import annotations

import os
from typing import List, NamedTuple, Sequence

import numpy as np

from .types import (
    F_ANY_LIVE,
    F_APPEND,
    F_COUNT,
    F_ESC,
    F_NEED_SS,
    F_QUORUM_ACTIVE,
    R_COMMIT,
    R_LAST,
    R_LEADER,
    R_ROLE,
    R_TERM,
    R_VOTE,
    ROLE_LEADER,
    U_COMMIT,
    U_LEADER,
    U_LOST_LEAD,
    U_ROLE,
    U_STATE,
    UL_N,
)

# parity mode: run the scalar twins beside every vectorized pass and
# assert identical outputs (tests flip the module attribute directly;
# the env var serves soak/CI runs).  Off by default — the twins are
# O(rows) Python, the very cost this module exists to remove.
PARITY = os.environ.get("DRAGONBOAT_TPU_HOSTPLANE_PARITY", "") == "1"


class HostPlaneParityError(AssertionError):
    """Vectorized and scalar host-plane passes disagreed (a bug in one
    of them); the engine fail-stops the launch loudly rather than
    letting the two decode paths diverge the cluster."""


class RowLanes:
    """SoA metadata lanes for device rows — the ``_RowMeta`` truth store.

    One lane per static plan fact the classifier needs:

    * ``attached`` — a ``_RowMeta`` exists for this row (set at attach,
      cleared at detach/halt/release; ``attached & ~dirty`` is the
      device-authoritative "alive" set the launch masks ride on).
    * ``dirty`` — the scalar Raft is authoritative and the device row
      is stale (fresh rows, cold-stepped rows, escalated rows).
    * ``plan_ok`` — the last FULL ``_plan_device`` pass passed every
      static eligibility check (the fast tick lane's proof).
    * ``esc_hold`` — steps left to hold the row on the scalar path
      after an escalation.

    All writes happen under the engine's core lock (the same contract
    the ``_RowMeta`` fields always had); the vectorized readers run
    under that lock too.
    """

    __slots__ = ("attached", "dirty", "plan_ok", "esc_hold")

    def __init__(self, capacity: int):
        self.attached = np.zeros((capacity,), bool)
        # rows start dirty: scalar-authoritative until the first upload
        self.dirty = np.ones((capacity,), bool)
        self.plan_ok = np.zeros((capacity,), bool)
        self.esc_hold = np.zeros((capacity,), np.int64)

    def reset_row(self, g: int, attached: bool) -> None:
        """Fresh-row state (attach) or freed-row state (detach/halt)."""
        self.attached[g] = attached
        self.dirty[g] = True
        self.plan_ok[g] = False
        self.esc_hold[g] = 0

    def alive_mask(self) -> np.ndarray:  # hostplane-hot
        """The device-authoritative row set: attached and clean.  A
        fresh [G] bool array (callers mutate it for per-generation
        stopping corrections).  Replaces the old per-launch Python scan
        over the whole ``_meta`` table (~0.5 µs/row — ~125 ms/launch at
        250k rows)."""
        return self.attached & ~self.dirty


class LeaseLanes:
    """Host model of resident CheckQuorum leaders' activity windows —
    the device-plane lease evidence plumbing (ROADMAP 4b).

    The device SoA tracks ``check_quorum``/``active`` per row but never
    drove the scalar remotes' ``last_resp_tick``, so lease reads on
    device-hosted shards always fell back to ReadIndex.  The wiring:

    * the kernel's flags word gains ``F_QUORUM_ACTIVE`` — a CheckQuorum
      leader whose CURRENT activity window already holds a quorum of
      active voter lanes (engine._summarize_flags; rides the existing
      per-launch readback for free);
    * the host mirrors each armed row's device ``election_tick`` from
      the ticks it feeds (``row_step``), so it knows when the device's
      CheckQuorum sweep cleared the lanes — the WINDOW START, recorded
      on the row's own node clock;
    * when the flag is up mid-window, the scalar voting remotes are
      anchored at that window start (``Raft.anchor_quorum_evidence``),
      and ``quorum_responded_tick``/``lease_remaining_ticks`` work
      unchanged — the ~0.006 ms lease read stays on the engines that
      host the most shards.

    SAFETY SHAPE: an ``active`` lane proves its peer responded AFTER
    the sweep observed it cleared, so the quorum's election clocks
    reset no earlier than (window start - one in-flight probe delay).
    Window-start anchoring is therefore the classic clock-based
    CheckQuorum lease (etcd's leader lease), one notch weaker than the
    scalar path's probe-send FIFO anchoring; the margin lease callers
    already keep (NodeHost.try_lease_read) absorbs the in-flight skew.
    The leader's own FIRST window is never anchored (window_start
    starts at -1): become_leader fabricates a full activity window
    (kernel._become_leader), and only a window that began with a real
    on-device sweep counts as evidence.

    All writes run under the engine's core lock, like RowLanes.
    """

    __slots__ = ("window_start", "dev_el", "et")

    def __init__(self, capacity: int):
        self.window_start = np.full((capacity,), -1, np.int64)
        self.dev_el = np.zeros((capacity,), np.int64)
        self.et = np.zeros((capacity,), np.int64)  # 0 = disarmed

    def disarm(self, g: int) -> None:
        self.et[g] = 0
        self.dev_el[g] = 0
        self.window_start[g] = -1

    def arm(self, g: int, election_timeout: int, election_tick: int) -> None:
        """Arm a row entering device residency (or winning an election
        on-device) as a CheckQuorum leader.  ``election_tick`` seeds
        the device-window mirror (uploads carry the scalar's tick; an
        on-device win resets it to 0)."""
        self.et[g] = election_timeout
        self.dev_el[g] = election_tick
        self.window_start[g] = -1  # first window: fabricated actives

    def row_step(self, g: int, fed_ticks: int, now: int,
                 flags_word: int) -> int:
        """Advance one armed row by the ticks its launch fed and return
        the anchor tick (>= 0) when the quorum-active flag holds inside
        an observed window, else -1.  Crossings mirror kernel._tick's
        leader leg exactly: el += n, fired at el >= et, reset to 0 (the
        planner's half-window tick cap guarantees at most one crossing
        per launch)."""
        et = self.et[g]
        if et <= 0:
            return -1
        el = self.dev_el[g] + fed_ticks
        if el >= et:
            # the device's CheckQuorum sweep ran this launch: actives
            # cleared, a fresh window starts on this row's clock NOW
            self.dev_el[g] = 0
            self.window_start[g] = now
            return -1
        self.dev_el[g] = el
        ws = self.window_start[g]
        if ws >= 0 and (flags_word & F_QUORUM_ACTIVE):
            return int(ws)
        return -1


class UpdateLanes:
    """SoA mirror of the scalar words the merge tail syncs into each
    resident row's ``Raft`` — the array-side ``pb.Update`` truth store
    (ISSUE 13 / ROADMAP item 1's "Raft-less host rows").

    One ``[UL_N, G]`` int64 block, rows indexed by the values-block
    layout (``types.R_TERM`` … ``types.R_LAST``), holding the LAST
    SYNCED absolute-frame words per device row: term / vote / commit /
    leader / role / last-log-index (commit and last carry the shard
    base added back, so rebases never perturb them).  Beside the lanes
    the device plane already tracks per row — delivered outbox bits
    (the head blob), lease evidence (:class:`LeaseLanes`) and the
    plan/alive flags (:class:`RowLanes`) — this completes the set: a
    generation's *effects* now diff as ``new words != lane words``
    over whole ``[G]`` gathers (:func:`plan_update_sync`) instead of
    one Python object walk per affected row.

    Chip-sharded by construction: the block's G axis is the engine row
    axis, so under the ``ops/placement.py`` row-block contract a
    device's G-slice is the contiguous column slice
    :meth:`device_slice` returns — per-device lane views compose with
    zero copies (docs/MULTICHIP.md), ready for the mesh plane.

    Lifecycle mirrors the ``_mirror`` table: seeded at upload
    (``_upload_rows``) from the scalar raft, bulk-written at every
    merge for the rows the generation synced; rows skipped by a merge
    (stopped / halted mid-flight) are freed and re-seeded at their
    next upload, so their stale words are moot.  All access runs under
    the engine's core lock, like RowLanes.
    """

    __slots__ = ("words",)

    def __init__(self, capacity: int):
        self.words = np.zeros((UL_N, capacity), np.int64)

    def seed_row(self, g: int, term: int, vote: int, commit: int,
                 leader: int, role: int, last: int) -> None:
        """Scalar -> lanes at upload: the raft is authoritative."""
        w = self.words
        w[R_TERM, g] = term
        w[R_VOTE, g] = vote
        w[R_COMMIT, g] = commit
        w[R_LEADER, g] = leader
        w[R_ROLE, g] = role
        w[R_LAST, g] = last

    def device_slice(self, device_index: int, n_devices: int) -> np.ndarray:
        """The contiguous per-device lane view under the row-block
        contract (placement.device_of_row): device ``d`` owns columns
        ``[d*Gl, (d+1)*Gl)``.  A VIEW, never a copy — the mesh test
        asserts the slices tile the block exactly."""
        from .placement import rows_per_device

        per = rows_per_device(self.words.shape[1], n_devices)
        return self.words[:, device_index * per:(device_index + 1) * per]


class UpdateSyncPlan(NamedTuple):
    """One generation's vectorized effect classification: the new
    absolute words ``[UL_N, n]`` for the planned rows and the per-row
    ``U_*`` effect bits ``[n]`` (0 = the row's merged values are
    byte-identical to the last sync — nothing to write, persist or
    notify)."""

    words: np.ndarray
    ubits: np.ndarray


def plan_update_sync(  # hostplane-hot
    old_words: np.ndarray,
    sum_k: np.ndarray,
    vals: np.ndarray,
    bases: np.ndarray,
) -> UpdateSyncPlan:
    """Vectorized update-sync classification for one generation.

    ``old_words`` is the ``[UL_N, n]`` gather of the rows' current
    lanes, ``sum_k`` the per-row position into the ``[m, N_VALS]``
    values block (-1 = the row carried no values this generation —
    its words are kept and its ubits are 0), ``bases`` the per-row
    shard bases converting the device frame to the absolute frame.

    The ``U_*`` bits come from lane diffs, NOT from the device's
    F_CHANGED flag: F_CHANGED compares one step's old/new device
    state, while the lanes compare against the last HOST sync — the
    quantity the merge tail actually owes an action for.  The caller
    writes ``plan.words`` back into the lanes for exactly the rows it
    then merges (skipped rows re-seed at their next upload).
    """
    in_sum = sum_k >= 0
    if not len(vals):
        # no row carried values this generation: every sum_k is -1 and
        # the gather below must still be indexable
        vals = np.zeros((1, UL_N), np.int64)
    safe_k = np.where(in_sum, sum_k, 0)
    new = vals[safe_k, :UL_N].T.astype(np.int64)
    new[R_COMMIT] += bases
    new[R_LAST] += bases
    new = np.where(in_sum[None, :], new, old_words)
    state_chg = (
        (new[R_TERM] != old_words[R_TERM])
        | (new[R_VOTE] != old_words[R_VOTE])
        | (new[R_COMMIT] != old_words[R_COMMIT])
    )
    ubits = (
        np.where(state_chg, U_STATE, 0)
        | np.where(new[R_COMMIT] > old_words[R_COMMIT], U_COMMIT, 0)
        | np.where(new[R_ROLE] != old_words[R_ROLE], U_ROLE, 0)
        | np.where(new[R_LEADER] != old_words[R_LEADER], U_LEADER, 0)
        | np.where(
            (old_words[R_ROLE] == ROLE_LEADER)
            & (new[R_ROLE] != ROLE_LEADER),
            U_LOST_LEAD,
            0,
        )
    )
    return UpdateSyncPlan(words=new, ubits=ubits)


# raftlint: ignore[host-loop] parity oracle — the per-row decision shape the lanes replaced, kept for the harness
def plan_update_sync_scalar(  # hostplane-hot
    old_words: np.ndarray,
    sum_k: Sequence[int],
    vals: np.ndarray,
    bases: Sequence[int],
) -> UpdateSyncPlan:
    """Per-row twin of :func:`plan_update_sync` — the old merge loop's
    implicit per-row comparisons (scalar sync always wrote, commit
    advance probed ``committed > r.log.committed``, role/leader
    transitions probed per row), made explicit row by row."""
    n = len(sum_k)
    words = np.array(old_words, np.int64, copy=True)
    ubits = np.zeros((n,), np.int64)
    for i in range(n):
        k = int(sum_k[i])
        if k < 0:
            continue
        term, vote, commit, leader, role, last = (
            int(vals[k, c]) for c in range(UL_N)
        )
        commit += int(bases[i])
        last += int(bases[i])
        ub = 0
        if (
            term != int(old_words[R_TERM, i])
            or vote != int(old_words[R_VOTE, i])
            or commit != int(old_words[R_COMMIT, i])
        ):
            ub |= U_STATE
        if commit > int(old_words[R_COMMIT, i]):
            ub |= U_COMMIT
        if role != int(old_words[R_ROLE, i]):
            ub |= U_ROLE
        if leader != int(old_words[R_LEADER, i]):
            ub |= U_LEADER
        if (
            int(old_words[R_ROLE, i]) == ROLE_LEADER
            and role != ROLE_LEADER
        ):
            ub |= U_LOST_LEAD
        words[:, i] = (term, vote, commit, leader, role, last)
        ubits[i] = ub
    return UpdateSyncPlan(words=words, ubits=ubits)


def assert_update_plan_parity(
    old_words: np.ndarray,
    sum_k: np.ndarray,
    vals: np.ndarray,
    bases: np.ndarray,
    plan: UpdateSyncPlan,
) -> None:
    ref = plan_update_sync_scalar(
        old_words, np.asarray(sum_k).tolist(), vals,
        np.asarray(bases).tolist(),
    )
    if not np.array_equal(np.asarray(plan.ubits), ref.ubits):
        raise HostPlaneParityError(_diff("update_ubits", plan.ubits,
                                         ref.ubits))
    if not np.array_equal(np.asarray(plan.words), ref.words):
        raise HostPlaneParityError(_diff("update_words", plan.words,
                                         ref.words))


def check_update_plan_parity(old_words, sum_k, vals, bases, plan) -> None:
    try:
        assert_update_plan_parity(old_words, sum_k, vals, bases, plan)
    except HostPlaneParityError as e:  # pragma: no cover - bug path
        _record_failure(e)


# ---------------------------------------------------------------------------
# the batched plan classifier (static-eligibility prefilter)
# ---------------------------------------------------------------------------


def classify_static(lanes: RowLanes, gs: np.ndarray) -> np.ndarray:  # hostplane-hot
    """[n] bool: rows whose last full-plan proof still stands.

    ``gs`` is the per-node row-id array (-1 for unattached).  A True
    lane means the row may take the fast tick lane PROVIDED the cheap
    per-launch dynamic conditions (empty queues, no snapshot/read
    state, save quarantine, stale binding) also hold — those live on
    Python objects and are re-verified per row by the caller, exactly
    as the fast lane always did.  A False lane routes the node to the
    scalar ``_plan_device`` classifier (the slow-path oracle)."""
    ok = gs >= 0
    safe = np.where(ok, gs, 0)
    return (
        ok
        & lanes.plan_ok[safe]
        & ~lanes.dirty[safe]
        & (lanes.esc_hold[safe] == 0)
    )


# raftlint: ignore[host-loop] parity oracle — the pre-vectorization per-row shape, kept for the harness
def classify_static_scalar(lanes: RowLanes, gs: Sequence[int]) -> np.ndarray:
    """Per-row twin of :func:`classify_static` (the r5 probe shape)."""
    out = np.zeros((len(gs),), bool)
    for i, g in enumerate(gs):
        if g < 0:
            continue
        out[i] = (
            bool(lanes.plan_ok[g])
            and not bool(lanes.dirty[g])
            and int(lanes.esc_hold[g]) == 0
        )
    return out


# ---------------------------------------------------------------------------
# merge row sets (the post-launch tail's classification)
# ---------------------------------------------------------------------------
class MergeSets(NamedTuple):
    """Row sets the merge stage consumes, as sorted int32 row-id arrays
    (``esc_batch_pos`` is positions into the BATCH list, everything
    else is device row ids).  Replaces the old per-row list/dict
    comprehensions over the whole meta table."""

    esc_batch_pos: np.ndarray  # batch positions whose row escalated
    esc_other: np.ndarray      # alive non-batch rows that escalated
    live_other: np.ndarray     # alive non-batch rows with any-live flags
    buf_rows: np.ndarray       # live rows with host-visible outbox bytes
    append_rows: np.ndarray    # live rows that ring-appended
    slot_rows: np.ndarray      # non-escalated proposal-slot rows
    need_rows: np.ndarray      # live rows with a peer needing a snapshot
    sum_rows: np.ndarray       # live rows whose VALUES the merge reads


def _mask_of(G: int, rows) -> np.ndarray:  # hostplane-hot
    m = np.zeros((G,), bool)
    if len(rows):
        m[np.asarray(rows, np.int64)] = True
    return m


def build_merge_sets(  # hostplane-hot
    flags: np.ndarray,
    alive: np.ndarray,
    batch_gs: np.ndarray,
    prop_gs: np.ndarray,
    *,
    G: int,
) -> MergeSets:
    """Vectorized merge-row classification for one launch.

    Inputs: the [G] int32 flags word (types.F_*), the [G] bool alive
    mask (attached & clean, with this generation's stopping rows
    cleared), the batch row ids in batch order, and the proposal-slot
    row ids.  Mirrors the scalar semantics bit for bit (the parity
    harness holds both to it):

    * escalated batch rows replay on the scalar path; escalated ALIVE
      non-batch rows (stepped only by routed traffic) just discard
      their device effects;
    * live = batch rows + alive resident rows with any-live flags,
      minus escalations;
    * buf/append/need sets are flag-gated subsets of live; slot rows
      are the non-escalated proposal rows; sum rows are live rows with
      any-live flags or proposal slots (the rest only ticked).
    """
    batch_mask = _mask_of(G, batch_gs)
    prop_mask = _mask_of(G, prop_gs)
    esc = (flags & F_ESC) != 0
    anylive = (flags & F_ANY_LIVE) != 0
    esc_batch_pos = np.nonzero(esc[batch_gs])[0].astype(np.int32) if len(
        batch_gs
    ) else np.zeros((0,), np.int32)
    esc_other = np.nonzero(alive & ~batch_mask & esc)[0].astype(np.int32)
    live_mask = ~esc & (batch_mask | (alive & ~batch_mask & anylive))
    slot_mask = prop_mask & ~esc  # prop rows ride the batch; esc drops them
    i32 = np.int32
    return MergeSets(
        esc_batch_pos=esc_batch_pos,
        esc_other=esc_other,
        live_other=np.nonzero(live_mask & ~batch_mask)[0].astype(i32),
        buf_rows=np.nonzero(live_mask & ((flags & F_COUNT) != 0))[0].astype(i32),
        append_rows=np.nonzero(live_mask & ((flags & F_APPEND) != 0))[0].astype(i32),
        slot_rows=np.nonzero(slot_mask)[0].astype(i32),
        need_rows=np.nonzero(live_mask & ((flags & F_NEED_SS) != 0))[0].astype(i32),
        sum_rows=np.nonzero(live_mask & (anylive | slot_mask))[0].astype(i32),
    )


# raftlint: ignore[host-loop] parity oracle — replicates the r5 per-row loops verbatim for the harness
def build_merge_sets_scalar(
    flags: Sequence[int],
    alive: Sequence[bool],
    batch_gs: Sequence[int],
    prop_gs: Sequence[int],
    *,
    G: int,
) -> MergeSets:
    """Per-row twin of :func:`build_merge_sets` — the exact loop shapes
    the colocated merge tail ran before vectorization (flag probes per
    row, membership via Python sets), with outputs sorted into the
    canonical MergeSets form for comparison."""
    flags = list(flags)
    batch_set = set(int(g) for g in batch_gs)
    esc_batch_pos = [
        i for i, g in enumerate(batch_gs) if flags[int(g)] & F_ESC
    ]
    esc_other = [
        g for g in range(G)
        if alive[g] and g not in batch_set and flags[g] & F_ESC
    ]
    esc_set = {int(batch_gs[i]) for i in esc_batch_pos} | set(esc_other)
    live = [int(g) for g in batch_gs if int(g) not in esc_set]
    for g in range(G):
        if (
            alive[g]
            and g not in batch_set
            and g not in esc_set
            and flags[g] & F_ANY_LIVE
        ):
            live.append(g)
    slot_rows = [int(g) for g in prop_gs if int(g) not in esc_set]
    slot_set = set(slot_rows)
    buf_rows = [g for g in live if flags[g] & F_COUNT]
    append_rows = [g for g in live if flags[g] & F_APPEND]
    need_rows = [g for g in live if flags[g] & F_NEED_SS]
    sum_rows = [
        g for g in live if (flags[g] & F_ANY_LIVE) or g in slot_set
    ]
    live_other = [g for g in live if g not in batch_set]
    srt = lambda xs: np.asarray(sorted(xs), np.int32)  # noqa: E731
    return MergeSets(
        esc_batch_pos=np.asarray(sorted(esc_batch_pos), np.int32),
        esc_other=srt(esc_other),
        live_other=srt(live_other),
        buf_rows=srt(buf_rows),
        append_rows=srt(append_rows),
        slot_rows=srt(slot_rows),
        need_rows=srt(need_rows),
        sum_rows=srt(sum_rows),
    )


# ---------------------------------------------------------------------------
# index maps (the *_at dict replacements)
# ---------------------------------------------------------------------------
def pos_of(G: int, rows: np.ndarray) -> np.ndarray:  # hostplane-hot
    """[G] int32 position map: pos[g] = index of g in ``rows``, -1
    elsewhere — the index-array replacement for the per-row
    ``{g: k for k, g in enumerate(rows)}`` dict builds."""
    pos = np.full((G,), -1, np.int32)
    n = len(rows)
    if n:
        pos[np.asarray(rows, np.int64)] = np.arange(n, dtype=np.int32)
    return pos


def covered(pos: np.ndarray, rows: np.ndarray) -> bool:  # hostplane-hot
    """Every row of ``rows`` has a position in ``pos`` — the
    index-array replacement for ``all(g in at for g in rows)``."""
    if not len(rows):
        return True
    return bool((pos[np.asarray(rows, np.int64)] >= 0).all())


# ---------------------------------------------------------------------------
# parity harness
# ---------------------------------------------------------------------------
def _diff(name: str, a: np.ndarray, b: np.ndarray) -> str:
    return (
        f"{name}: vectorized {np.asarray(a).tolist()[:32]} != "
        f"scalar {np.asarray(b).tolist()[:32]}"
    )


def assert_classify_parity(lanes: RowLanes, gs: Sequence[int],
                           vec: np.ndarray) -> None:
    ref = classify_static_scalar(lanes, list(gs))
    if not np.array_equal(np.asarray(vec, bool), ref):
        raise HostPlaneParityError(_diff("classify_static", vec, ref))


def assert_merge_parity(
    flags: np.ndarray,
    alive: np.ndarray,
    batch_gs: np.ndarray,
    prop_gs: np.ndarray,
    vec: MergeSets,
    *,
    G: int,
) -> None:
    """Run the scalar oracle on the same launch inputs and compare
    every set (vectorized outputs sorted first — the oracle's canonical
    form).  Raises :class:`HostPlaneParityError` naming the first
    diverging set."""
    ref = build_merge_sets_scalar(
        np.asarray(flags).tolist(),
        np.asarray(alive, bool).tolist(),
        list(np.asarray(batch_gs).tolist()),
        list(np.asarray(prop_gs).tolist()),
        G=G,
    )
    for name in MergeSets._fields:
        got = np.sort(np.asarray(getattr(vec, name)))
        want = np.asarray(getattr(ref, name))
        if not np.array_equal(got, want):
            raise HostPlaneParityError(_diff(name, got, want))


# parity failures observed by the in-engine checker (check_* wrappers):
# the engine must not crash a live launch mid-merge over a checker
# finding, so the wrappers record + log instead of raising — tests and
# soaks gate on PARITY_FAILURE_COUNT == 0 / the list being empty.  The
# list keeps only the first _FAILURE_CAP diffs (a multi-day soak with
# a persistent divergence appends per launch — an unbounded list would
# OOM the soak long before anyone reads it); the counter is exact.
PARITY_FAILURES: List[str] = []
PARITY_FAILURE_COUNT = 0
_FAILURE_CAP = 256


def _record_failure(e: Exception) -> None:  # pragma: no cover - bug path
    global PARITY_FAILURE_COUNT
    PARITY_FAILURE_COUNT += 1
    if len(PARITY_FAILURES) < _FAILURE_CAP:
        PARITY_FAILURES.append(str(e))


def check_classify_parity(lanes: RowLanes, gs, vec) -> None:
    try:
        assert_classify_parity(lanes, gs, vec)
    except HostPlaneParityError as e:  # pragma: no cover - bug path
        _record_failure(e)


def check_merge_parity(flags, alive, batch_gs, prop_gs, vec, *, G) -> None:
    try:
        assert_merge_parity(flags, alive, batch_gs, prop_gs, vec, G=G)
    except HostPlaneParityError as e:  # pragma: no cover - bug path
        _record_failure(e)


# recorded generation traces (parity satellite): with ``RECORD`` on,
# the colocated engine appends one entry per launch so tests can replay
# scalar-vs-vectorized over REAL generation inputs (elections,
# escalations, membership churn) rather than only fabricated ones.
RECORD = False
TRACE: List[dict] = []
_TRACE_CAP = 512


def record_generation(flags, alive, batch_gs, prop_gs, G: int) -> None:
    if not RECORD:
        return
    TRACE.append(
        dict(
            flags=np.array(flags, np.int64, copy=True),
            alive=np.array(alive, bool, copy=True),
            batch_gs=np.array(batch_gs, np.int64, copy=True),
            prop_gs=np.array(prop_gs, np.int64, copy=True),
            G=G,
        )
    )
    if len(TRACE) > _TRACE_CAP:
        del TRACE[: len(TRACE) - _TRACE_CAP]
