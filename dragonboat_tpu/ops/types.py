"""Device-side tensor layouts for the vectorized raft step kernel.

The reference steps each raft group with a scalar state machine
(reference: internal/raft/raft.go [U]); here the same state is a
struct-of-arrays pytree over ``G`` replica-rows so one ``jit``-compiled
step advances every row at once (SURVEY.md §7 "Architecture stance").

A **row** is one (shard, replica) pair — exactly what one scalar ``Raft``
object models.  All protocol scalars are ``int32`` (TPUs have no native
int64; indexes/terms stay < 2^31 which is ample for any deployment the
bench exercises — the host WAL uses 64-bit indexes and escalates rows on
overflow long before that).

Shape legend:
  G — rows (replicas hosted on this chip)
  P — peer slots (max membership size; ragged 3/5/7 memberships are
      masked, BASELINE config 4)
  W — in-window log-term ring size (power of two)
  M — inbox message slots per row per step
  E — max entries carried per REPLICATE / PROPOSE on the device path
  O — outbox message capacity per row per step
"""
from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp
import numpy as np

from ..pb import MessageType
from ..raft.raft import RaftRole
from ..raft.remote import RemoteState

I32 = jnp.int32

# ---------------------------------------------------------------------------
# role / remote-state / message-type constants (device-side mirrors)
# ---------------------------------------------------------------------------
ROLE_FOLLOWER = int(RaftRole.FOLLOWER)
ROLE_PRE_CANDIDATE = int(RaftRole.PRE_CANDIDATE)
ROLE_CANDIDATE = int(RaftRole.CANDIDATE)
ROLE_LEADER = int(RaftRole.LEADER)
ROLE_NON_VOTING = int(RaftRole.NON_VOTING)
ROLE_WITNESS = int(RaftRole.WITNESS)

RS_RETRY = int(RemoteState.RETRY)
RS_WAIT = int(RemoteState.WAIT)
RS_REPLICATE = int(RemoteState.REPLICATE)
RS_SNAPSHOT = int(RemoteState.SNAPSHOT)

# peer slot kinds
KIND_VOTER = 0
KIND_NON_VOTING = 1
KIND_WITNESS = 2

MT_NOOP = int(MessageType.NO_OP)
MT_TICK = int(MessageType.LOCAL_TICK)
MT_ELECTION = int(MessageType.ELECTION)
MT_PROPOSE = int(MessageType.PROPOSE)
MT_REPLICATE = int(MessageType.REPLICATE)
MT_REPLICATE_RESP = int(MessageType.REPLICATE_RESP)
MT_REQUEST_VOTE = int(MessageType.REQUEST_VOTE)
MT_REQUEST_VOTE_RESP = int(MessageType.REQUEST_VOTE_RESP)
MT_REQUEST_PREVOTE = int(MessageType.REQUEST_PREVOTE)
MT_REQUEST_PREVOTE_RESP = int(MessageType.REQUEST_PREVOTE_RESP)
MT_HEARTBEAT = int(MessageType.HEARTBEAT)
MT_HEARTBEAT_RESP = int(MessageType.HEARTBEAT_RESP)
MT_READ_INDEX = int(MessageType.READ_INDEX)
MT_READ_INDEX_RESP = int(MessageType.READ_INDEX_RESP)
MT_INSTALL_SNAPSHOT = int(MessageType.INSTALL_SNAPSHOT)
MT_SNAPSHOT_STATUS = int(MessageType.SNAPSHOT_STATUS)
MT_SNAPSHOT_RECEIVED = int(MessageType.SNAPSHOT_RECEIVED)
MT_UNREACHABLE = int(MessageType.UNREACHABLE)
MT_LEADER_TRANSFER = int(MessageType.LEADER_TRANSFER)
MT_TIMEOUT_NOW = int(MessageType.TIMEOUT_NOW)
MT_CHECK_QUORUM = int(MessageType.CHECK_QUORUM)

# the kernel's hot set; anything else in an inbox escalates the row
HOT_TYPES = (
    MT_TICK,
    MT_ELECTION,
    MT_PROPOSE,
    MT_READ_INDEX,
    MT_REPLICATE,
    MT_REPLICATE_RESP,
    MT_REQUEST_VOTE,
    MT_REQUEST_VOTE_RESP,
    MT_REQUEST_PREVOTE,
    MT_REQUEST_PREVOTE_RESP,
    MT_HEARTBEAT,
    MT_HEARTBEAT_RESP,
    MT_TIMEOUT_NOW,
    MT_CHECK_QUORUM,
    MT_UNREACHABLE,
    MT_SNAPSHOT_STATUS,
    MT_SNAPSHOT_RECEIVED,
)

# escalation reason bits (DeviceOut.escalate)
ESC_WINDOW = 1        # needed a log term outside the W-entry ring
ESC_OVERFLOW = 2      # outbox capacity exhausted mid-step
ESC_COLD = 4          # a cold message type reached the device inbox
ESC_INVARIANT = 8     # conflict below commit / malformed input

# slot_base sentinel values (per inbox PROPOSE slot)
SLOT_UNUSED = -3      # slot was not a PROPOSE / row escalated
SLOT_FORWARDED = -2   # follower forwarded the proposal to the leader
SLOT_DROPPED = -1     # proposal dropped (no leader / transfer in flight)

# per-row flag bits of the post-step flags-word readback (the ONLY
# full-width [G] readback a launch performs — see engine._summarize_flags).
# Defined HERE (not in engine.py) because three layers consume them:
# the device-side summarize program, the host merge stage, and the
# array-at-once host-plane machinery in ops/hostplane.py — one
# definition keeps the device readback and the vectorized host decode
# from ever disagreeing on a bit.
F_CHANGED, F_COUNT, F_APPEND, F_NEED_SS, F_ESC = 1, 2, 4, 8, 16
# leader row with a peer lane still behind its log: quiesce entry is
# blocked while set (the scalar remotes of a resident row are stale)
F_PEERS_BEHIND = 32
# CheckQuorum leader row (self a voter) whose CURRENT activity window
# already holds a quorum of active voter lanes: the device-plane lease
# evidence bit (ROADMAP 4b) — the host anchors the scalar remotes'
# last_resp_tick at the window start so gateway lease reads stay on
# device-hosted shards (ops/hostplane.LeaseLanes; docs/GATEWAY.md).
# Deliberately NOT in F_ANY_LIVE: it must ride the flags word for free
# without promoting a quiet leader into the values-readback set.
F_QUORUM_ACTIVE = 64
F_ANY_LIVE = F_CHANGED | F_COUNT | F_APPEND | F_NEED_SS

# per-row VALUES block layout (engine._gather_vals order) — the columns
# of the post-step values readback.  Defined HERE (like the F_* bits)
# because three layers consume them: the device-side gather program,
# both engines' merge tails, and the array-at-once update lanes in
# ops/hostplane.py (UpdateLanes stores the first UL_N columns per row,
# absolute frame) — one definition keeps the device readback, the host
# decode and the lane store from ever disagreeing on a column.
R_TERM, R_VOTE, R_COMMIT, R_LEADER, R_ROLE, R_LAST = range(6)
R_COUNT, R_APPEND_LO = 6, 7
R_BARRIER_IDX, R_BARRIER_TERM = 8, 9
N_VALS = 10
UL_N = 6  # update-lane words = the first 6 values columns

# per-row update effect bits (hostplane.plan_update_sync): what a
# generation's merged values changed RELATIVE TO THE LAST SYNC for one
# row — the vectorized replacement for the per-row "did anything I
# must act on happen" probes of the old merge loop.  U_STATE means the
# hard-state triple (term/vote/commit) moved and must persist;
# U_COMMIT that commit advanced (committed entries to hand to apply);
# U_ROLE / U_LEADER that the role / leader word moved (role resync,
# leader-change notification); U_LOST_LEAD that the row held LEADER at
# the last sync and no longer does (pending device reads must drop).
U_STATE, U_COMMIT, U_ROLE, U_LEADER, U_LOST_LEAD = 1, 2, 4, 8, 16


class DeviceState(NamedTuple):
    """SoA mirror of one scalar ``Raft`` per row.

    The host keeps the authoritative payload log (entries with commands);
    the device ring holds only (term, is-config-change) per in-window
    index — everything ``raft.Step`` needs for log matching, vote
    up-to-date checks and the current-term commit gate.
    """

    # -- static identity / config, [G] ---------------------------------
    shard_id: jnp.ndarray
    replica_id: jnp.ndarray
    self_slot: jnp.ndarray          # index into peer axis for this replica
    election_timeout: jnp.ndarray
    heartbeat_timeout: jnp.ndarray
    check_quorum: jnp.ndarray       # 0/1
    pre_vote: jnp.ndarray           # 0/1
    # -- volatile protocol state, [G] -----------------------------------
    term: jnp.ndarray
    vote: jnp.ndarray
    leader_id: jnp.ndarray
    role: jnp.ndarray
    committed: jnp.ndarray
    last_index: jnp.ndarray
    first_index: jnp.ndarray        # lowest index whose term is resolvable
    base_term: jnp.ndarray          # term(first_index - 1)
    election_tick: jnp.ndarray
    heartbeat_tick: jnp.ndarray
    rand_timeout: jnp.ndarray
    timeout_seq: jnp.ndarray
    pending_cc: jnp.ndarray         # 0/1: uncommitted config change in log
    transfer_target: jnp.ndarray    # 0 = none
    # -- per-peer slots, [G, P] -----------------------------------------
    peer_id: jnp.ndarray            # 0 = empty slot
    peer_kind: jnp.ndarray          # KIND_*
    match: jnp.ndarray
    next_idx: jnp.ndarray
    rstate: jnp.ndarray             # RS_*
    snap_index: jnp.ndarray
    active: jnp.ndarray             # 0/1, CheckQuorum liveness
    granted: jnp.ndarray            # votes: 0 unknown / 1 granted / 2 rejected
    # -- in-window log ring, [G, W] -------------------------------------
    ring_term: jnp.ndarray
    ring_cc: jnp.ndarray            # 0/1 config-change bit per entry

    @property
    def G(self) -> int:
        return self.term.shape[0]

    @property
    def P(self) -> int:
        return self.peer_id.shape[1]

    @property
    def W(self) -> int:
        return self.ring_term.shape[1]


class Inbox(NamedTuple):
    """One step's ordered per-row message batch.

    Slot order is the processing order (the scalar oracle processes the
    same messages in the same order — that is the parity contract).
    ``ent_term``/``ent_cc`` carry per-entry metadata for REPLICATE
    (terms) and PROPOSE (config-change bits) slots.
    """

    mtype: jnp.ndarray       # [G, M]
    from_id: jnp.ndarray
    term: jnp.ndarray
    log_term: jnp.ndarray
    log_index: jnp.ndarray
    commit: jnp.ndarray
    reject: jnp.ndarray      # 0/1
    hint: jnp.ndarray
    hint_high: jnp.ndarray
    n_entries: jnp.ndarray
    ent_term: jnp.ndarray    # [G, M, E]
    ent_cc: jnp.ndarray      # [G, M, E]

    @property
    def M(self) -> int:
        return self.mtype.shape[1]

    @property
    def E(self) -> int:
        return self.ent_term.shape[2]


# outbox buffer field order (DeviceOut.buf[..., F_*])
F_MTYPE = 0
F_TO = 1
F_TERM = 2
F_LOG_TERM = 3
F_LOG_INDEX = 4
F_COMMIT = 5
F_REJECT = 6
F_HINT = 7
F_HINT_HIGH = 8
F_N_ENTRIES = 9
F_SRC_SLOT = 10
N_FIELDS = 11


APPEND_LO_NONE = 2**31 - 1  # DeviceOut.append_lo sentinel: no append


class DeviceOut(NamedTuple):
    """Step outputs: emitted messages + host-coordination side channels."""

    buf: jnp.ndarray            # [G, O, N_FIELDS]
    count: jnp.ndarray          # [G] messages emitted
    escalate: jnp.ndarray       # [G] ESC_* bitmask; host replays the row
    need_snapshot: jnp.ndarray  # [G, P] 0/1: peer slot needs InstallSnapshot
    slot_base: jnp.ndarray      # [G, M] PROPOSE: pre-append last_index or SLOT_*
    slot_term: jnp.ndarray      # [G, M] PROPOSE: term entries were stamped with
    ent_drop: jnp.ndarray       # [G, M, E] 0/1: proposal entry dropped (cc gate)
    append_lo: jnp.ndarray      # [G] lowest log index ring-written this step
                                # (APPEND_LO_NONE if nothing appended); with
                                # state'.last_index this bounds the host's
                                # entries_to_save reconstruction
    barrier_idx: jnp.ndarray    # [G] index of the become-leader noop barrier
                                # self-appended THIS step (-1 if none): the
                                # only append with no staged/wire payload, so
                                # hosts reconstructing routed appends can
                                # stamp it empty even if the row stepped down
                                # later in the same step
    barrier_term: jnp.ndarray   # [G] term that barrier was appended at

    @property
    def O(self) -> int:
        return self.buf.shape[1]


# ---------------------------------------------------------------------------
# constructors
# ---------------------------------------------------------------------------
def _splitmix32_np(x: np.ndarray) -> np.ndarray:
    """Numpy mirror of kernel._splitmix32 — bit-identical uint32 math."""
    with np.errstate(over="ignore"):
        z = x.astype(np.uint32) + np.uint32(0x9E3779B9)
        z = z ^ (z >> np.uint32(16))
        z = z * np.uint32(0x85EBCA6B)
        z = z ^ (z >> np.uint32(13))
        z = z * np.uint32(0xC2B2AE35)
        z = z ^ (z >> np.uint32(16))
    return z


def make_state_np(
    G: int,
    P: int,
    W: int,
    *,
    shard_ids=None,
    replica_ids=None,
    peer_ids=None,
    peer_kinds=None,
    election_timeout: int = 10,
    heartbeat_timeout: int = 1,
    check_quorum: bool = False,
    pre_vote: bool = False,
) -> dict:
    """``make_state`` as a pure-NUMPY field dict (same values bit for
    bit, including the constructor timeout jitter).

    This exists for the host staging path: ``state_from_rafts`` packs
    scalar oracles on the host and must never round-trip through the
    device — building jnp arrays here and reading them back cost ~31
    device->host readbacks per upload batch, which on a remote TPU link
    was the single largest launch cost at scale (r4 SCALE:
    t_upload_ms = 46% of a 10k-shard election).
    """
    if W & (W - 1):
        raise ValueError(f"W must be a power of two, got {W}")
    zg = np.zeros((G,), np.int32)
    zgp = np.zeros((G, P), np.int32)
    shard_ids = np.asarray(
        shard_ids if shard_ids is not None else np.arange(G), np.int32
    )
    replica_ids = np.asarray(
        replica_ids if replica_ids is not None else np.ones(G), np.int32
    )
    if peer_ids is None:
        peer_ids = np.zeros((G, P), np.int32)
        peer_ids[:, 0] = replica_ids
    peer_ids = np.asarray(peer_ids, np.int32)
    peer_kinds = np.asarray(
        peer_kinds if peer_kinds is not None else zgp, np.int32
    )
    self_slot = np.argmax(peer_ids == replica_ids[:, None], axis=1).astype(
        np.int32
    )
    valid = peer_ids != 0
    et = np.full((G,), election_timeout, np.int32)
    # match Raft.__init__: the constructor resets the randomized timeout
    # once (kernel.reset_timeout with seq 0 -> 1), in numpy
    seq = np.ones((G,), np.int32)
    h = _splitmix32_np(
        (shard_ids.astype(np.uint32) << np.uint32(24))
        ^ (replica_ids.astype(np.uint32) << np.uint32(8))
        ^ seq.astype(np.uint32)
    )
    rand_timeout = (et + (h % et.astype(np.uint32)).astype(np.int32)).astype(
        np.int32
    )
    return dict(
        shard_id=shard_ids,
        replica_id=replica_ids,
        self_slot=self_slot,
        election_timeout=et,
        heartbeat_timeout=np.full((G,), heartbeat_timeout, np.int32),
        check_quorum=np.full((G,), int(check_quorum), np.int32),
        pre_vote=np.full((G,), int(pre_vote), np.int32),
        term=zg.copy(),
        vote=zg.copy(),
        leader_id=zg.copy(),
        role=_initial_roles(replica_ids, peer_ids, peer_kinds),
        committed=zg.copy(),
        last_index=zg.copy(),
        first_index=np.ones((G,), np.int32),
        base_term=zg.copy(),
        election_tick=zg.copy(),
        heartbeat_tick=zg.copy(),
        rand_timeout=rand_timeout,
        timeout_seq=seq,
        pending_cc=zg.copy(),
        transfer_target=zg.copy(),
        peer_id=peer_ids,
        peer_kind=np.where(valid, peer_kinds, 0).astype(np.int32),
        match=zgp.copy(),
        next_idx=np.where(valid, 1, 0).astype(np.int32),
        rstate=zgp.copy(),
        snap_index=zgp.copy(),
        active=zgp.copy(),
        granted=zgp.copy(),
        ring_term=np.zeros((G, W), np.int32),
        ring_cc=np.zeros((G, W), np.int32),
    )


def make_state(
    G: int,
    P: int,
    W: int,
    **kw,
) -> DeviceState:
    """Fresh state for G rows.

    ``peer_ids`` is [G, P] with 0 marking empty slots; ``replica_ids`` must
    appear in their own row's slots.  Fresh rows start as followers at
    term 0 with an empty log, exactly like ``Raft.__init__``.
    """
    cols = make_state_np(G, P, W, **kw)
    return DeviceState(**{k: jnp.asarray(v) for k, v in cols.items()})


def _initial_roles(replica_ids, peer_ids, peer_kinds):
    G = replica_ids.shape[0]
    roles = np.full((G,), ROLE_FOLLOWER, np.int32)
    self_mask = peer_ids == replica_ids[:, None]
    kind = np.where(self_mask, peer_kinds, -1).max(axis=1)
    roles[kind == KIND_NON_VOTING] = ROLE_NON_VOTING
    roles[kind == KIND_WITNESS] = ROLE_WITNESS
    return roles


def make_inbox(G: int, M: int, E: int) -> Inbox:
    zm = jnp.zeros((G, M), I32)
    return Inbox(
        mtype=zm,
        from_id=zm,
        term=zm,
        log_term=zm,
        log_index=zm,
        commit=zm,
        reject=zm,
        hint=zm,
        hint_high=zm,
        n_entries=zm,
        ent_term=jnp.zeros((G, M, E), I32),
        ent_cc=jnp.zeros((G, M, E), I32),
    )


def make_out(G: int, P: int, M: int, E: int, O: int) -> DeviceOut:
    return DeviceOut(
        buf=jnp.zeros((G, O, N_FIELDS), I32),
        count=jnp.zeros((G,), I32),
        escalate=jnp.zeros((G,), I32),
        need_snapshot=jnp.zeros((G, P), I32),
        slot_base=jnp.full((G, M), SLOT_UNUSED, I32),
        slot_term=jnp.zeros((G, M), I32),
        ent_drop=jnp.zeros((G, M, E), I32),
        append_lo=jnp.full((G,), APPEND_LO_NONE, I32),
        barrier_idx=jnp.full((G,), -1, I32),
        barrier_term=jnp.zeros((G,), I32),
    )
